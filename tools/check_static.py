"""Static analysis gate: JAX hazard linter + plan-IR verifier.

Runs both passes of pinot_tpu/analysis and exits non-zero on anything
new (tier-1 runs this through tests/test_static_analysis.py, alongside
tools/check_ledger.py):

1. **Linter** (analysis/jaxlint.py) over the whole pinot_tpu tree.
   Findings are ratcheted against tools/jaxlint_baseline.json: new
   findings above a ``file::scope::rule`` count fail; counts that DROP
   also fail until the baseline is ratcheted down (run with
   ``--update-baseline`` after fixing sites).
2. **Verifier** (analysis/plan_verify.py) over every plan the planner
   produces for the full SSB query set (bench.QUERIES), the NYC-taxi
   set (bench_taxi.QUERIES), and ``--fuzz N`` seeded fuzzer-generated
   queries (pinot_tpu/tools/fuzzer.py) — all at CI scale, plan-only
   (no kernels execute). Any diagnostic fails.

    python tools/check_static.py [--lint-only|--verify-only]
                                 [--update-baseline] [--fuzz N]

Prints one summary JSON line last, check_ledger-style.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# match the test environment: CPU backend before jax initializes (the
# sitecustomize may force a TPU platform otherwise)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

BASELINE = os.path.join(REPO, "tools", "jaxlint_baseline.json")
FUZZ_SEED = 20260804


def run_lint(update_baseline: bool = False) -> dict:
    from pinot_tpu.analysis import jaxlint

    findings = jaxlint.lint_tree(REPO)
    if update_baseline:
        jaxlint.write_baseline(findings, BASELINE)
        # re-compare against the freshly written baseline: parse-error
        # findings are never written into it, so an unparseable module
        # keeps the gate red even on the re-ratchet run itself
        baseline = jaxlint.load_baseline(BASELINE)
        new, stale = jaxlint.compare_baseline(findings, baseline)
        for f in new:
            print(f"NEW {f}")
        return {"findings": len(findings), "new": len(new),
                "stale": len(stale), "updated": True}
    baseline = jaxlint.load_baseline(BASELINE)
    new, stale = jaxlint.compare_baseline(findings, baseline)
    for f in new:
        print(f"NEW {f}")
    for key, allowed, actual in stale:
        print(f"STALE {key}: baseline {allowed}, found {actual} — "
              "ratchet down with --update-baseline")
    return {"findings": len(findings), "new": len(new),
            "stale": len(stale)}


def _verify_corpus(label: str, segment, sqls, counts: dict,
                   diags: list) -> None:
    from pinot_tpu.analysis.plan_verify import verify_compiled_plan
    from pinot_tpu.query.context import build_query_context
    from pinot_tpu.query.planner import PlanError, SegmentPlanner
    from pinot_tpu.query.sql import SqlError, parse_sql

    for sql in sqls:
        counts["queries"] += 1
        try:
            ctx = build_query_context(parse_sql(sql))
            plan = SegmentPlanner(ctx, segment).plan()
        except (PlanError, SqlError) as e:
            # multi-table / window shapes that never reach the segment
            # planner — not this gate's surface, but printed so a
            # planner regression demoting whole corpora is visible
            counts["skipped"] += 1
            print(f"SKIP [{label}] {type(e).__name__}: {e}\n"
                  f"  query: {sql}")
            continue
        counts["plans"] += 1
        if plan.kind in ("kernel", "kselect"):
            counts["device_plans"] = counts.get("device_plans", 0) + 1
            counts[plan.kind] = counts.get(plan.kind, 0) + 1
        for d in verify_compiled_plan(plan):
            diags.append((label, sql, d))


def run_verify(fuzz_n: int) -> dict:
    # collect diagnostics instead of letting the planner raise; restore
    # whatever the caller had set (an embedding host may deliberately
    # run with verification off)
    prior = os.environ.get("PINOT_PLAN_VERIFY")
    os.environ["PINOT_PLAN_VERIFY"] = "0"
    try:
        return _run_verify(fuzz_n)
    finally:
        if prior is None:
            os.environ.pop("PINOT_PLAN_VERIFY", None)
        else:
            os.environ["PINOT_PLAN_VERIFY"] = prior


def _run_verify(fuzz_n: int) -> dict:
    import bench
    import bench_taxi
    from pinot_tpu.tools.fuzzer import (QueryGenerator,
                                        build_fuzz_segment, render_sql)

    corpora: dict = {}
    diags: list = []
    with tempfile.TemporaryDirectory() as tmp:
        seg = bench.build_segment(1 << 12, os.path.join(tmp, "ssb"))
        corpora["ssb"] = {"queries": 0, "plans": 0, "skipped": 0}
        _verify_corpus(
            "ssb", seg,
            [bench.spec_to_sql(p, v, g) + bench.OPTION
             for _q, p, v, g in bench.QUERIES],
            corpora["ssb"], diags)

        seg_t = bench_taxi.build_segment(1 << 12, os.path.join(tmp, "taxi"))
        corpora["taxi"] = {"queries": 0, "plans": 0, "skipped": 0}
        _verify_corpus(
            "taxi", seg_t,
            [bench_taxi._sql(k, w) + bench_taxi.OPTION
             for _q, k, w in bench_taxi.QUERIES],
            corpora["taxi"], diags)

        seg_f = build_fuzz_segment(2000, tmp)
        gen = QueryGenerator(FUZZ_SEED, with_exists=False)
        corpora["fuzz"] = {"queries": 0, "plans": 0, "skipped": 0}
        _verify_corpus(
            "fuzz", seg_f,
            [render_sql(gen.generate()) for _ in range(fuzz_n)],
            corpora["fuzz"], diags)

    warns = [(lb, s, d) for lb, s, d in diags if d.severity != "error"]
    diags = [(lb, s, d) for lb, s, d in diags if d.severity == "error"]
    for label, sql, d in diags:
        print(f"DIAG [{label}] {d}\n  query: {sql}")
    for label, sql, d in warns:
        print(f"WARN [{label}] {d}\n  query: {sql}")

    # anti-vacuous-pass floors: zero diagnostics only counts if the
    # verifier actually saw the plans it claims to cover. Every SSB and
    # taxi query must reach a device (kernel/kselect) plan — exactly
    # the bar tests/test_ssb.py and test_taxi.py hold the planner to —
    # and the fuzzer corpus must surface a healthy device-plan share.
    coverage: list = []
    for label in ("ssb", "taxi"):
        c = corpora[label]
        if c["skipped"] or c.get("device_plans", 0) != c["queries"]:
            coverage.append(
                f"{label}: {c.get('device_plans', 0)}/{c['queries']} "
                f"device plans ({c['skipped']} skipped) — the corpus "
                "regressed off the kernel path, verifier coverage lost")
    if corpora["fuzz"]["queries"] and \
            corpora["fuzz"].get("device_plans", 0) < max(
                corpora["fuzz"]["queries"] // 10, 1):
        coverage.append(
            f"fuzz: only {corpora['fuzz'].get('device_plans', 0)} of "
            f"{corpora['fuzz']['queries']} queries reached a device "
            "plan — generator or planner drift gutted coverage")
    for msg in coverage:
        print(f"COVERAGE {msg}")

    out = {"queries": 0, "plans": 0, "skipped": 0, "device_plans": 0}
    for c in corpora.values():
        for k, v in c.items():
            out[k] = out.get(k, 0) + v
    out["diagnostics"] = len(diags)
    out["warnings"] = len(warns)
    out["coverage_failures"] = len(coverage)
    return out


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    update = "--update-baseline" in args
    lint_only = "--lint-only" in args
    verify_only = "--verify-only" in args
    fuzz_n = 150
    if "--fuzz" in args:
        fuzz_n = int(args[args.index("--fuzz") + 1])

    summary: dict = {}
    rc = 0
    if not verify_only:
        summary["lint"] = run_lint(update)
        if summary["lint"].get("new") or summary["lint"].get("stale"):
            rc = 1
    if not lint_only:
        summary["verify"] = run_verify(fuzz_n)
        if summary["verify"]["diagnostics"] or \
                summary["verify"]["coverage_failures"]:
            rc = 1
    summary["ok"] = rc == 0
    print(json.dumps(summary))
    return rc


if __name__ == "__main__":
    sys.exit(main())
