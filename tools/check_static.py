"""Static analysis gate: JAX hazard linter + concurrency verifier +
determinism verifier + plan-IR verifier.

Runs the four passes of pinot_tpu/analysis and exits non-zero on
anything new (tier-1 runs this through tests/test_static_analysis.py,
alongside tools/check_ledger.py):

1. **Linter** (analysis/jaxlint.py) over the whole pinot_tpu tree.
   Findings are ratcheted against tools/jaxlint_baseline.json: new
   findings above a ``file::scope::rule`` count fail; counts that DROP
   also fail until the baseline is ratcheted down (run with
   ``--update-baseline`` after fixing sites).
2. **Concurrency verifier** (analysis/concur.py, rules CC201-CC205:
   mixed-guard, blocking-under-lock, lock-order cycles, thread-local
   escape, check-then-act) over the whole tree, ratcheted the same way
   against tools/concur_baseline.json.
3. **Determinism verifier** (analysis/detlint.py, rules DT301-DT305:
   wall-clock, ambient RNG, unordered serialization, query-time
   environ, completion-order float accumulation) — whole-program:
   taint propagates from the deterministic-plane entry registry
   through the corpus call graph (the tree plus
   tools/traffic_replay.py), ratcheted against
   tools/detlint_baseline.json.
4. **Plan verifier** (analysis/plan_verify.py) over every plan the
   planner produces for the full SSB query set (bench.QUERIES), the
   NYC-taxi set (bench_taxi.QUERIES), and ``--fuzz N`` seeded
   fuzzer-generated queries (pinot_tpu/tools/fuzzer.py) — all at CI
   scale, plan-only (no kernels execute). Any diagnostic fails.

``--changed`` is the fast pre-commit mode: the three lint passes still
analyze the whole program (detlint's reachability needs the full call
graph) but findings and baselines are restricted to git-changed .py
files, and the plan verifier is skipped.

Prints one summary JSON line last, check_ledger-style; ``--json``
instead prints exactly one machine-readable JSON document (per-rule
finding counts, file/line per finding, suppressed/baselined split per
pass) so CI and the builder can diff findings across PRs.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# match the test environment: CPU backend before jax initializes (the
# sitecustomize may force a TPU platform otherwise)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

BASELINE = os.path.join(REPO, "tools", "jaxlint_baseline.json")
CONCUR_BASELINE = os.path.join(REPO, "tools", "concur_baseline.json")
DETLINT_BASELINE = os.path.join(REPO, "tools", "detlint_baseline.json")
FUZZ_SEED = 20260804

EXIT_CODES = """\
exit codes:
  0  clean: no findings beyond the committed ratchet baselines, no
     stale baseline counts, no plan diagnostics or coverage failures
  1  gate failure: new lint/concur/detlint findings above a baseline
     count, a baseline count that no longer matches (ratchet it down),
     a plan verifier diagnostic, or lost corpus coverage
  2  usage error (bad arguments)

The three ratchet baselines (tools/jaxlint_baseline.json,
tools/concur_baseline.json, tools/detlint_baseline.json) grandfather
true-but-benign findings per file::scope::rule; regenerate with
--update-baseline (combine with --lint-only / --concur-only /
--detlint-only to re-ratchet one of them)."""


def _changed_files() -> list:
    """Repo-relative .py files changed vs HEAD (staged + unstaged +
    untracked) — the --changed reporting scope."""
    import subprocess
    paths: list = []
    for cmd in (["git", "-C", REPO, "diff", "--name-only", "HEAD"],
                ["git", "-C", REPO, "ls-files", "--others",
                 "--exclude-standard"]):
        try:
            out = subprocess.run(cmd, capture_output=True, text=True,
                                 check=True).stdout
        except Exception:
            continue
        paths.extend(p.strip() for p in out.splitlines() if p.strip())
    return sorted({p for p in paths if p.endswith(".py")})


def _ratchet_pass(findings, suppressed, baseline_path, update, label,
                  write_baseline, paths=None):
    """Shared jaxlint/concur/detlint ratchet flow -> summary dict (+
    the machine-readable details for --json). ``paths`` (the --changed
    scope) restricts findings AND baseline keys to those files."""
    from pinot_tpu.analysis import jaxlint

    if update:
        write_baseline(findings, baseline_path)
    baseline = jaxlint.load_baseline(baseline_path)
    if paths is not None:
        scope = set(paths)
        findings = [f for f in findings if f.path in scope]
        suppressed = [f for f in suppressed if f.path in scope]
        baseline = {k: v for k, v in baseline.items()
                    if k.split("::", 1)[0] in scope}
    new, stale = jaxlint.compare_baseline(findings, baseline)
    for f in new:
        print(f"NEW [{label}] {f}")
    for key, allowed, actual in stale:
        print(f"STALE [{label}] {key}: baseline {allowed}, found "
              f"{actual} — ratchet down with --update-baseline")
    rules: dict = {}
    for f in findings:
        rules[f.rule] = rules.get(f.rule, 0) + 1
    out = {"findings": len(findings), "new": len(new),
           "stale": len(stale), "suppressed": len(suppressed),
           "baselined": len(findings) - len(new), "rules": rules}
    if update:
        out["updated"] = True
    out["_details"] = {
        "findings": [{"rule": f.rule, "file": f.path, "line": f.line,
                      "scope": f.scope, "message": f.message,
                      "baselined": f not in new}
                     for f in findings],
        "suppressed": [{"rule": f.rule, "file": f.path, "line": f.line,
                        "scope": f.scope} for f in suppressed],
        "stale": [{"key": k, "baseline": a, "found": n}
                  for k, a, n in stale],
    }
    return out


def run_lint(update_baseline: bool = False, paths=None) -> dict:
    from pinot_tpu.analysis import jaxlint

    findings, suppressed = jaxlint.lint_tree_ex(REPO)
    return _ratchet_pass(findings, suppressed, BASELINE,
                         update_baseline, "jaxlint",
                         jaxlint.write_baseline, paths)


def run_concur(update_baseline: bool = False, paths=None) -> dict:
    from pinot_tpu.analysis import concur

    findings, suppressed = concur.analyze_tree(REPO)
    return _ratchet_pass(findings, suppressed, CONCUR_BASELINE,
                         update_baseline, "concur",
                         concur.write_baseline, paths)


def run_detlint(update_baseline: bool = False, paths=None) -> dict:
    from pinot_tpu.analysis import detlint

    findings, suppressed = detlint.analyze_tree(REPO)
    return _ratchet_pass(findings, suppressed, DETLINT_BASELINE,
                         update_baseline, "detlint",
                         detlint.write_baseline, paths)


def _verify_corpus(label: str, segment, sqls, counts: dict,
                   diags: list) -> None:
    from pinot_tpu.analysis.plan_verify import verify_compiled_plan
    from pinot_tpu.query.context import build_query_context
    from pinot_tpu.query.planner import PlanError, SegmentPlanner
    from pinot_tpu.query.sql import SqlError, parse_sql

    for sql in sqls:
        counts["queries"] += 1
        try:
            ctx = build_query_context(parse_sql(sql))
            plan = SegmentPlanner(ctx, segment).plan()
        except (PlanError, SqlError) as e:
            # multi-table / window shapes that never reach the segment
            # planner — not this gate's surface, but printed so a
            # planner regression demoting whole corpora is visible
            counts["skipped"] += 1
            print(f"SKIP [{label}] {type(e).__name__}: {e}\n"
                  f"  query: {sql}")
            continue
        counts["plans"] += 1
        if plan.kind in ("kernel", "kselect"):
            counts["device_plans"] = counts.get("device_plans", 0) + 1
            counts[plan.kind] = counts.get(plan.kind, 0) + 1
        for d in verify_compiled_plan(plan):
            diags.append((label, sql, d))


def run_verify(fuzz_n: int) -> dict:
    # collect diagnostics instead of letting the planner raise; restore
    # whatever the caller had set (an embedding host may deliberately
    # run with verification off)
    prior = os.environ.get("PINOT_PLAN_VERIFY")
    os.environ["PINOT_PLAN_VERIFY"] = "0"
    try:
        return _run_verify(fuzz_n)
    finally:
        if prior is None:
            os.environ.pop("PINOT_PLAN_VERIFY", None)
        else:
            os.environ["PINOT_PLAN_VERIFY"] = prior


def _run_verify(fuzz_n: int) -> dict:
    import bench
    import bench_taxi
    from pinot_tpu.tools.fuzzer import (QueryGenerator,
                                        build_fuzz_segment, render_sql)

    corpora: dict = {}
    diags: list = []
    with tempfile.TemporaryDirectory() as tmp:
        seg = bench.build_segment(1 << 12, os.path.join(tmp, "ssb"))
        corpora["ssb"] = {"queries": 0, "plans": 0, "skipped": 0}
        _verify_corpus(
            "ssb", seg,
            [bench.spec_to_sql(p, v, g) + bench.OPTION
             for _q, p, v, g in bench.QUERIES],
            corpora["ssb"], diags)

        seg_t = bench_taxi.build_segment(1 << 12, os.path.join(tmp, "taxi"))
        corpora["taxi"] = {"queries": 0, "plans": 0, "skipped": 0}
        _verify_corpus(
            "taxi", seg_t,
            [bench_taxi._sql(k, w) + bench_taxi.OPTION
             for _q, k, w in bench_taxi.QUERIES],
            corpora["taxi"], diags)

        seg_f = build_fuzz_segment(2000, tmp)
        gen = QueryGenerator(FUZZ_SEED, with_exists=False)
        corpora["fuzz"] = {"queries": 0, "plans": 0, "skipped": 0}
        _verify_corpus(
            "fuzz", seg_f,
            [render_sql(gen.generate()) for _ in range(fuzz_n)],
            corpora["fuzz"], diags)

    warns = [(lb, s, d) for lb, s, d in diags if d.severity != "error"]
    diags = [(lb, s, d) for lb, s, d in diags if d.severity == "error"]
    for label, sql, d in diags:
        print(f"DIAG [{label}] {d}\n  query: {sql}")
    for label, sql, d in warns:
        print(f"WARN [{label}] {d}\n  query: {sql}")
    detail = {
        "diagnostics": [{"corpus": lb, "rule": d.rule, "path": d.path,
                         "message": d.message, "query": s}
                        for lb, s, d in diags],
        "warnings": [{"corpus": lb, "rule": d.rule, "path": d.path,
                      "message": d.message, "query": s}
                     for lb, s, d in warns],
    }

    # anti-vacuous-pass floors: zero diagnostics only counts if the
    # verifier actually saw the plans it claims to cover. Every SSB and
    # taxi query must reach a device (kernel/kselect) plan — exactly
    # the bar tests/test_ssb.py and test_taxi.py hold the planner to —
    # and the fuzzer corpus must surface a healthy device-plan share.
    coverage: list = []
    for label in ("ssb", "taxi"):
        c = corpora[label]
        if c["skipped"] or c.get("device_plans", 0) != c["queries"]:
            coverage.append(
                f"{label}: {c.get('device_plans', 0)}/{c['queries']} "
                f"device plans ({c['skipped']} skipped) — the corpus "
                "regressed off the kernel path, verifier coverage lost")
    if corpora["fuzz"]["queries"] and \
            corpora["fuzz"].get("device_plans", 0) < max(
                corpora["fuzz"]["queries"] // 10, 1):
        coverage.append(
            f"fuzz: only {corpora['fuzz'].get('device_plans', 0)} of "
            f"{corpora['fuzz']['queries']} queries reached a device "
            "plan — generator or planner drift gutted coverage")
    for msg in coverage:
        print(f"COVERAGE {msg}")
    detail["coverage"] = coverage

    out = {"queries": 0, "plans": 0, "skipped": 0, "device_plans": 0}
    for c in corpora.values():
        for k, v in c.items():
            out[k] = out.get(k, 0) + v
    out["diagnostics"] = len(diags)
    out["warnings"] = len(warns)
    out["coverage_failures"] = len(coverage)
    out["_details"] = detail
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="check_static.py",
        description=__doc__,
        epilog=EXIT_CODES,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    only = ap.add_mutually_exclusive_group()
    only.add_argument("--lint-only", action="store_true",
                      help="run only the jaxlint pass")
    only.add_argument("--concur-only", action="store_true",
                      help="run only the concurrency verifier pass")
    only.add_argument("--detlint-only", action="store_true",
                      help="run only the determinism verifier pass")
    only.add_argument("--verify-only", action="store_true",
                      help="run only the plan-IR verifier pass")
    ap.add_argument("--changed", action="store_true",
                    help="fast pre-commit mode: restrict lint/concur/"
                         "detlint findings and baselines to git-"
                         "changed .py files (analysis still covers "
                         "the whole program) and skip the plan "
                         "verifier")
    ap.add_argument("--update-baseline", action="store_true",
                    help="re-ratchet the baseline(s) of the passes "
                         "being run (jaxlint/concur/detlint), then "
                         "re-compare; parse errors stay red")
    ap.add_argument("--fuzz", type=int, default=150, metavar="N",
                    help="fuzzer queries for the plan verifier "
                         "(default 150)")
    ap.add_argument("--json", action="store_true",
                    help="print exactly one machine-readable JSON "
                         "document (per-rule counts, file/line per "
                         "finding, suppressed/baselined split) "
                         "instead of the line-oriented report")
    args = ap.parse_args(argv)
    if args.changed and args.verify_only:
        ap.error("--changed skips the plan verifier; it cannot be "
                 "combined with --verify-only")
    if args.changed and args.update_baseline:
        ap.error("--update-baseline needs the full-corpus view; it "
                 "cannot be combined with --changed")

    changed = _changed_files() if args.changed else None

    # --json buffers the human chatter so stdout is ONE JSON document
    out_buf = None
    real_stdout = sys.stdout
    if args.json:
        import io
        out_buf = io.StringIO()
        sys.stdout = out_buf

    lint_passes = (
        ("lint", args.lint_only, run_lint),
        ("concur", args.concur_only, run_concur),
        ("detlint", args.detlint_only, run_detlint),
    )
    any_only = any(flag for _s, flag, _r in lint_passes) or \
        args.verify_only
    summary: dict = {}
    rc = 0
    try:
        if changed is not None:
            summary["changed"] = changed
        for sec, only_flag, runner in lint_passes:
            if (any_only and not only_flag) or \
                    (changed is not None and not changed):
                continue
            summary[sec] = runner(args.update_baseline, changed)
            if summary[sec]["new"] or summary[sec]["stale"]:
                rc = 1
        if (not any_only or args.verify_only) and changed is None:
            summary["verify"] = run_verify(args.fuzz)
            if summary["verify"]["diagnostics"] or \
                    summary["verify"]["coverage_failures"]:
                rc = 1
    finally:
        if out_buf is not None:
            sys.stdout = real_stdout
    summary["ok"] = rc == 0
    if args.json:
        # scalar counts stay as-is; the per-finding records (file/line/
        # rule/scope, suppressed/stale splits, plan diagnostics and
        # coverage messages) land under "detail" — a failing run must
        # be actionable from the JSON alone, since the line report was
        # swallowed by the buffer
        for sec in ("lint", "concur", "detlint", "verify"):
            if sec in summary and "_details" in summary[sec]:
                summary[sec]["detail"] = summary[sec].pop("_details")
        print(json.dumps(summary, indent=1))
    else:
        for sec in ("lint", "concur", "detlint", "verify"):
            summary.get(sec, {}).pop("_details", None)
        print(json.dumps(summary))
    return rc


if __name__ == "__main__":
    sys.exit(main())
