"""Closed-loop traffic replay: the overload-resilience gate (ISSUE 12).

ROADMAP direction 3 named the missing half of the millions-of-users
story: replay real query mixes "at replayable multiples against a
scaling cluster, with per-tenant accountant budgets enforcing QoS — the
millions-of-users benchmark bench.py can't express". This harness is
that loop, closed end to end:

1. **Record** — a seeded three-tenant query mix (``protected`` /
   ``standard`` / ``besteffort`` tables) runs at 1x through the real
   broker path, landing ``query_stats`` ledger records that carry SQL,
   per-query ``arrival_ms`` offsets, tenant and qid — the replay input
   AND the pre-spike latency baseline.
2. **Plan** — the recorded records compress to ``--multiple N`` x their
   inter-arrival spacing. The offered-rate curve (a pure function of
   ledger + multiple + capacity) maps through the SAME watermark ladder
   live signals drive (``OverloadGovernor.rung_for_pressure``) into a
   per-qid rung schedule, and the pure shed ladder
   (``workload.shed_decision``) precomputes the full shed stream —
   retries included (a shed query retries once after its deterministic
   ``retryAfterMs``). The plan is computed TWICE and must match itself;
   this is the round-16 stream-keying discipline applied to load
   shedding.
3. **Spike** — the rung schedule pins onto the broker's governor
   (``pin_rungs`` — decisions stay in the broker: tier ladder, hash
   draws, 429 shaping, counters, ledger rows all execute there), a
   chaos plan arms (recoverable faults: straggler delay + one dropped
   dispatch per server, so failover runs under load), and the replay
   client dispatches on schedule, honoring each shed response's
   ``retryAfterMs`` before its single retry. Every shed response must
   be a structured 429 (errorCode + retryAfterMs) — a 500 anywhere
   fails the gate.
4. **Verify** — the broker's OBSERVED shed stream must equal the
   precomputed one byte-for-byte; ``protected`` must see ZERO sheds and
   zero errors with spike p99 inside its self-calibrated bar while
   ``besteffort`` absorbs the excess; and after the spike the governor
   unpins and a fresh 1x pass must land back inside the pre-spike noise
   floor — no metastable retry-storm state.

The summary lands as one validated ``replay_bench`` ledger record
(utils/ledger.py). Consumers: ``tools/chaos_smoke.py --overload``
(tier-1, cluster mode) and ``bench_common.finish()``'s overload gate
(local mode).

    python tools/traffic_replay.py gate [--multiple 4] [--seed N]
        [--queries 48] [--mode cluster|local] [--no-chaos]
        [--ledger OUT.jsonl]
    python tools/traffic_replay.py plan STATS.jsonl --multiple 4
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import threading
import time
import urllib.error
from typing import Any, Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# -- gate scenario ----------------------------------------------------------

TENANT_TABLES = (
    # (table, tenant, tier, mix weight)
    ("rp_orders", "ten_protected", "protected", 3),
    ("rp_events", "ten_standard", "standard", 3),
    ("rp_logs", "ten_besteffort", "besteffort", 4),
)

QUERY_SHAPES = (
    "SELECT k, SUM(v), COUNT(*) FROM {t} WHERE v < {p} GROUP BY k "
    "ORDER BY k LIMIT 16",
    "SELECT COUNT(*), SUM(v) FROM {t} WHERE v < {p}",
)

OPTION_TIMEOUT_MS = 120_000
# pressure = offered qps / (recorded qps * CAPACITY_HEADROOM): at 1x the
# steady offered rate reads ~0.4 — comfortably under every watermark —
# while --multiple 4 plateaus at ~1.6, deep in rung 3, with the window
# ramp passing rungs 1-2 at the spike edges
CAPACITY_HEADROOM = 2.5
PRESSURE_WINDOW_S = 0.25
# recovery bar: post-spike p50 within factor x pre-spike p50 + floor
# (floor absorbs scheduler jitter on tiny absolute latencies; the
# metastable failure mode this guards against is 10-100x, not 2x)
RECOVER_FACTOR = 3.0
RECOVER_FLOOR_MS = 80.0
# protected p99 bar during the spike, relative to its own pre-spike p99
# (the floor absorbs the armed chaos plan's own injected straggler
# delays + queueing on loaded CI boxes; the failure mode this guards —
# protected queries starving behind an unshed backlog — is seconds)
PROTECTED_BAR_FACTOR = 5.0
PROTECTED_BAR_FLOOR_MS = 750.0
# SLO burn windows for the spike (ISSUE 17): the fast window is wider
# than the whole compressed spike (so both paired windows see the full
# shed fraction — the fire decision reduces to the cumulative bad
# fraction, order-independent), and narrow enough that ~1 s into the
# good-traffic recovery phase it drains to zero and CLEARS the latch
SLO_FAST_S = 1.0
SLO_SLOW_S = 6.0
SLO_BURN_THRESHOLD = 1.0


def _pctl(sorted_vals: List[float], frac: float) -> float:
    from pinot_tpu.utils.stats import pctl
    return pctl(sorted_vals, frac)


# -- clients (cluster HTTP vs in-process broker) ----------------------------

class _Outcome:
    __slots__ = ("kind", "ms", "payload")

    def __init__(self, kind: str, ms: float = 0.0,
                 payload: Optional[dict] = None):
        self.kind = kind          # ok | shed | error
        self.ms = ms
        self.payload = payload or {}


class _ClusterClient:
    """POST /query/sql against a BrokerNode; a shed is HTTP 429 with
    the structured payload (anything else shed-shaped fails the
    structured-429 contract)."""

    extra_opt = ""  # appended inside every OPTION(...) clause

    def __init__(self, broker_url: str):
        self.url = broker_url

    def query(self, sql: str) -> _Outcome:
        from pinot_tpu.cluster.http_util import http_json
        t0 = time.perf_counter()
        try:
            http_json("POST", f"{self.url}/query/sql", {"sql": sql},
                      timeout=120.0)
            return _Outcome("ok", (time.perf_counter() - t0) * 1e3)
        except urllib.error.HTTPError as e:
            try:
                body = json.loads(e.read().decode())
            except Exception:
                body = {}
            if e.code == 429:
                return _Outcome("shed", payload=body)
            return _Outcome("error", payload={
                "status": e.code, **(body if isinstance(body, dict)
                                     else {})})
        except Exception as e:  # noqa: BLE001 — summarized, not raised
            return _Outcome("error",
                            payload={"error": f"{type(e).__name__}: {e}"})


class _LocalClient:
    """In-process Broker path: a shed raises OverloadShedError, whose
    payload() is the same structured shape the HTTP plane ships."""

    extra_opt = ""  # appended inside every OPTION(...) clause

    def __init__(self, broker):
        self.broker = broker

    def query(self, sql: str) -> _Outcome:
        from pinot_tpu.broker.workload import OverloadShedError
        from pinot_tpu.query.sql import SqlError
        t0 = time.perf_counter()
        try:
            self.broker.query(sql)
            return _Outcome("ok", (time.perf_counter() - t0) * 1e3)
        except OverloadShedError as e:
            return _Outcome("shed", payload=e.payload())
        except SqlError as e:
            return _Outcome("error", payload={"error": str(e)})


# -- cluster / table builders ----------------------------------------------

def _gen_columns(rows: int, seed: int = 7) -> Dict[str, Any]:
    import numpy as np
    rng = np.random.default_rng(seed)
    return {"k": rng.integers(0, 16, rows).astype(np.int32),
            "v": rng.integers(0, 1000, rows).astype(np.int32)}


def _schema(table: str):
    from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
    return Schema(table, [
        FieldSpec("k", DataType.INT, FieldType.DIMENSION),
        FieldSpec("v", DataType.INT, FieldType.METRIC),
    ])


def configure_tenants() -> None:
    """Register the gate's tenant tiers on the process-global workload
    manager. Budgets stay unlimited here on purpose: the replay's shed
    stream must be a pure function of the pinned rung schedule
    (budget sheds are wall-clock-fed and unit-tested separately)."""
    from pinot_tpu.broker.workload import global_workload
    for _table, tenant, tier, _w in TENANT_TABLES:
        global_workload.set_tenant(tenant, tier=tier)


def build_cluster(tmp: str, rows: int = 4096, poll: float = 0.1):
    """Controller + 2 servers + broker hosting the three tenant tables
    (TableConfig ``tenant`` field shipped through the routing
    snapshot)."""
    from pinot_tpu.cluster import BrokerNode, Controller, ServerNode
    from pinot_tpu.segment import SegmentBuilder
    from pinot_tpu.spi import TableConfig

    ctrl = Controller(os.path.join(tmp, "ctrl"), heartbeat_timeout=5.0,
                      reconcile_interval=0.2)
    servers = [ServerNode(f"server_{i}", ctrl.url, poll_interval=poll)
               for i in range(2)]
    broker = BrokerNode(ctrl.url, routing_refresh=poll,
                        query_stats_path=os.path.join(
                            tmp, "query_stats.jsonl"))
    cols = _gen_columns(rows)
    for table, tenant, _tier, _w in TENANT_TABLES:
        schema = _schema(table)
        builder = SegmentBuilder(schema, TableConfig(table))
        ctrl.add_table(table, schema.to_dict(),
                       config={"tenant": tenant}, replication=2)
        half = rows // 2
        for i, (lo, hi) in enumerate(((0, half), (half, rows))):
            d = builder.build({n: v[lo:hi] for n, v in cols.items()},
                              os.path.join(tmp, table), f"seg_{i}")
            ctrl.add_segment(table, f"seg_{i}", d)
    v = ctrl.routing_snapshot()["version"]
    for s in servers:
        assert s.wait_for_version(v, timeout=30.0), "server never synced"
    assert broker.wait_for_version(v, timeout=30.0), "broker never synced"

    def stop():
        broker.stop()
        for s in servers:
            try:
                s.stop()
            except Exception:
                pass
        ctrl.stop()

    return ctrl, servers, broker, stop


def build_local(tmp: str, rows: int = 4096):
    """In-process Broker hosting the same tenant tables (the
    bench_common overload gate's fast mode)."""
    from pinot_tpu.broker import Broker
    from pinot_tpu.segment import SegmentBuilder
    from pinot_tpu.server import TableDataManager
    from pinot_tpu.spi import TableConfig

    broker = Broker()
    cols = _gen_columns(rows)
    for table, tenant, _tier, _w in TENANT_TABLES:
        schema = _schema(table)
        cfg = TableConfig(table, tenant=tenant)
        dm = TableDataManager(table)
        dm.table_config = cfg
        dm.add_segment_dir(SegmentBuilder(schema, cfg).build(
            cols, os.path.join(tmp, table), "seg_0"))
        broker.register_table(dm)
    return broker


# -- the seeded mix ---------------------------------------------------------

def build_mix(seed: int, n_queries: int) -> List[Dict[str, Any]]:
    """The seeded (table, tenant, tier, sql) sequence — pure in
    (seed, n)."""
    import numpy as np
    rng = np.random.default_rng([seed, 1209])
    weighted = [t for t in TENANT_TABLES for _ in range(t[3])]
    out = []
    for i in range(n_queries):
        table, tenant, tier, _w = \
            weighted[int(rng.integers(len(weighted)))]
        shape = QUERY_SHAPES[int(rng.integers(len(QUERY_SHAPES)))]
        sql = shape.format(t=table, p=int(rng.integers(100, 1000)))
        out.append({"qid": f"rp{seed}_{i}", "table": table,
                    "tenant": tenant, "tier": tier, "sql": sql})
    return out


# -- recording --------------------------------------------------------------

def record_phase(client, mix: List[Dict[str, Any]], qps: float,
                 stats_path: Optional[str],
                 prefix: str = "") -> Dict[str, Any]:
    """Run the mix at 1x, paced at ``qps``; returns per-tier latency
    baselines and (local mode) writes the query_stats records the
    cluster broker would have written itself."""
    from pinot_tpu.utils import ledger as uledger
    lat: Dict[str, List[float]] = {}
    errors = 0
    t0 = time.perf_counter()
    for i, q in enumerate(mix):
        due = t0 + i / qps
        now = time.perf_counter()
        if due > now:
            time.sleep(due - now)
        sql = (f"{q['sql']} OPTION(timeoutMs={OPTION_TIMEOUT_MS},"
               f"queryId={prefix}{q['qid']}{client.extra_opt})")
        out = client.query(sql)
        if out.kind == "ok":
            lat.setdefault(q["tier"], []).append(out.ms)
            if stats_path is not None:
                # local mode writes the replay input itself — the SAME
                # validated query_stats contract the cluster broker's
                # forensics plane appends (arrival_ms per record)
                uledger.append_record(uledger.make_record(
                    "query_stats", qid=q["qid"], table=q["table"],
                    wall_ms=round(out.ms, 3), partial=False,
                    servers_queried=0, servers_responded=0,
                    exception_codes=[], sql=q["sql"],
                    tenant=q["tenant"],
                    arrival_ms=round((time.perf_counter() - t0) * 1e3,
                                     3)), stats_path)
        else:
            errors += 1
    return {"latencies": {t: sorted(v) for t, v in lat.items()},
            "errors": errors,
            "duration_s": time.perf_counter() - t0}


# -- the pure replay plan ---------------------------------------------------

def load_records(stats_path: str) -> List[Dict[str, Any]]:
    """query_stats records with the replay fields, arrival order."""
    records = []
    with open(stats_path) as fh:
        for line in fh:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and rec.get("kind") == "query_stats" \
                    and rec.get("sql") and not rec.get("shed") \
                    and rec.get("arrival_ms") is not None:
                # the cluster broker records the FULL SQL including its
                # original OPTION clause; the replay appends its own
                # (fresh qid/timeout/retryAttempt), so strip the old one
                rec = dict(rec)
                rec["sql"] = rec["sql"].split(" OPTION(")[0].rstrip()
                records.append(rec)
    records.sort(key=lambda r: (float(r["arrival_ms"]), r.get("qid")))
    return records


def plan_replay(records: List[Dict[str, Any]], multiple: float,
                seed: int, capacity_qps: Optional[float] = None,
                tier_of: Optional[Dict[str, str]] = None
                ) -> Dict[str, Any]:
    """The PURE replay plan: schedule + rung pins + predicted shed
    stream, a function of (records, multiple, seed, capacity) only —
    no clocks, no randomness beyond the seeded deterministic draws.

    The offered-rate curve over the compressed schedule maps through
    ``OverloadGovernor.rung_for_pressure`` (the same watermark ladder
    live signals drive) into a rung per scheduled query; the pure shed
    ladder then decides each (qid, tenant, tier) — and each shed
    query's single retry is scheduled ``retryAfterMs`` later and
    decided the same way. Computing this twice MUST yield identical
    streams (the gate asserts it), and the live run's observed stream
    must match it exactly."""
    from pinot_tpu.broker.workload import (OverloadGovernor,
                                           retry_after_ms,
                                           shed_decision)
    if not records:
        return {"entries": [], "pins": {}, "shed_stream": [],
                "capacity_qps": 0.0}
    t_base = float(records[0]["arrival_ms"])
    span_ms = max(float(records[-1]["arrival_ms"]) - t_base, 1.0)
    if capacity_qps is None:
        recorded_qps = len(records) / (span_ms / 1e3)
        capacity_qps = recorded_qps * CAPACITY_HEADROOM
    offsets = [(float(r["arrival_ms"]) - t_base) / 1e3 / multiple
               for r in records]

    def pressure_at(t: float, sched: List[float]) -> float:
        lo = t - PRESSURE_WINDOW_S
        n = sum(1 for s in sched if lo < s <= t)
        return (n / PRESSURE_WINDOW_S) / capacity_qps

    entries: List[Dict[str, Any]] = []
    pins: Dict[str, int] = {}
    shed_stream: List[Tuple[str, str, int, str, int]] = []
    for r, off in zip(records, offsets):
        qid = f"{r['qid']}_x{seed}"
        tenant = r.get("tenant") or "default"
        tier = (tier_of or {}).get(tenant) or r.get("tier") \
            or "standard"
        rung = OverloadGovernor.rung_for_pressure(
            pressure_at(off, offsets))
        pins[qid] = rung
        entry = {"offset_s": off, "qid": qid, "sql": r["sql"],
                 "tenant": tenant, "tier": tier, "rung": rung,
                 "retry_attempt": 0}
        entries.append(entry)
        reason = shed_decision(qid, tenant, tier, rung)
        if reason is None:
            continue
        after = retry_after_ms(qid, tenant, rung)
        shed_stream.append((qid, tenant, rung, reason, after))
        # the client-side retry contract: one retry, retryAfterMs
        # later, marked retryAttempt=1 — decided by the same ladder
        r_qid = f"{qid}_r1"
        r_off = off + after / 1e3
        r_rung = OverloadGovernor.rung_for_pressure(
            pressure_at(r_off, offsets))
        pins[r_qid] = r_rung
        entries.append({"offset_s": r_off, "qid": r_qid, "sql": r["sql"],
                        "tenant": tenant, "tier": tier, "rung": r_rung,
                        "retry_attempt": 1, "retry_of": qid})
        r_reason = shed_decision(r_qid, tenant, tier, r_rung)
        if r_reason is not None:
            shed_stream.append((r_qid, tenant, r_rung, r_reason,
                                retry_after_ms(r_qid, tenant, r_rung)))
    entries.sort(key=lambda e: (e["offset_s"], e["qid"]))
    return {"entries": entries, "pins": pins,
            "shed_stream": sorted(shed_stream),
            "capacity_qps": capacity_qps}


# -- the pure SLO alert plan (ISSUE 17) -------------------------------------

def plan_slo(records: List[Dict[str, Any]], plan: Dict[str, Any],
             multiple: float) -> Tuple[List[Dict[str, Any]],
                                       Dict[str, Any], float]:
    """The precomputed SLO burn-alert stream for the spike, pure in
    (records, plan, multiple): synthetic spike ``query_stats`` modeled
    from the replay plan — scheduled arrivals, recorded 1x walls x
    ``multiple``, the planned shed stream — fed through
    ``utils/slo.plan_alert_stream``. Same inputs => byte-identical
    output (the gate computes it twice and compares).

    Objectives are derived FROM the plan so the verdict has margin:
    availability budget = half the planned besteffort shed fraction
    (final burn 2.0x by construction — fires decisively — while the
    protected tenant burns 0.0x), and the latency bar sits at 1.5x the
    recorded p50, which the ``multiple``x-modeled walls overrun.

    -> (objectives, plan_alert_stream output, besteffort shed frac)."""
    walls = {str(r["qid"]): float(r.get("wall_ms", 1.0))
             for r in records}
    shed_qids = {s[0] for s in plan["shed_stream"]}
    srecs: List[Dict[str, Any]] = []
    for e in plan["entries"]:
        base = e["qid"].split("_x")[0]   # rpSEED_i[_xSEED[_r1]]
        srecs.append({
            "tenant": e["tenant"],
            "arrival_ms": round(e["offset_s"] * 1e3, 3),
            "wall_ms": round(walls.get(base, 1.0) * multiple, 3),
            "shed": e["qid"] in shed_qids})
    be = [r for r in srecs if r["tenant"] == "ten_besteffort"]
    frac = (sum(1 for r in be if r["shed"]) / len(be)) if be else 0.0
    objectives: List[Dict[str, Any]] = []
    if 0.0 < frac < 1.0:
        avail_obj = 1.0 - frac / 2.0
        for tenant in ("ten_besteffort", "ten_protected"):
            objectives.append({
                "scope": f"tenant:{tenant}", "kind": "availability",
                "objective": round(avail_obj, 6),
                "fast_s": SLO_FAST_S, "slow_s": SLO_SLOW_S,
                "burn_threshold": SLO_BURN_THRESHOLD})
    sorted_walls = sorted(walls.values())
    bar = _pctl(sorted_walls, 0.5) * 1.5 if sorted_walls else 100.0
    for tenant in ("ten_besteffort", "ten_standard"):
        objectives.append({
            "scope": f"tenant:{tenant}", "kind": "latency",
            "bar_ms": round(bar, 3),
            "fast_s": SLO_FAST_S, "slow_s": SLO_SLOW_S,
            "burn_threshold": SLO_BURN_THRESHOLD})
    from pinot_tpu.utils.slo import plan_alert_stream
    return objectives, plan_alert_stream(srecs, objectives), frac


# -- the spike --------------------------------------------------------------

def run_spike(client, plan: Dict[str, Any], workers: int = 8
              ) -> Dict[str, Any]:
    """Dispatch the plan on schedule (pins already installed by the
    caller). Retries are REACTIVE: a worker that receives a shed
    honors the RESPONSE's retryAfterMs — the plan's precomputed retry
    entries are only the prediction it is checked against."""
    lat: Dict[str, List[float]] = {}
    sheds: List[Tuple[str, str, int, str, int]] = []
    errors: Dict[str, int] = {}
    structured = [0, 0]   # well-formed 429 payloads, malformed sheds
    submitted = [0]
    lock = threading.Lock()
    sem = threading.Semaphore(workers)
    threads: List[threading.Thread] = []
    t0 = time.perf_counter()

    def fire(entry: Dict[str, Any]) -> None:
        sql = (f"{entry['sql']} OPTION("
               f"timeoutMs={OPTION_TIMEOUT_MS},"
               f"queryId={entry['qid']},"
               f"retryAttempt={entry['retry_attempt']}"
               f"{client.extra_opt})")
        with lock:
            submitted[0] += 1
        out = client.query(sql)
        if out.kind == "ok":
            with lock:
                lat.setdefault(entry["tier"], []).append(out.ms)
            return
        if out.kind == "error":
            with lock:
                errors[entry["tier"]] = \
                    errors.get(entry["tier"], 0) + 1
            return
        p = out.payload
        well_formed = (p.get("errorCode") == 429
                       and isinstance(p.get("retryAfterMs"), int)
                       and p.get("retryAfterMs") > 0)
        with lock:
            structured[0 if well_formed else 1] += 1
            sheds.append((entry["qid"], p.get("tenant") or "?",
                          int(p.get("rung") or 0),
                          p.get("reason") or "?",
                          int(p.get("retryAfterMs") or 0)))
        if entry["retry_attempt"] == 0 and well_formed:
            # honor the response: wait retryAfterMs, retry once
            time.sleep(p["retryAfterMs"] / 1e3)
            fire({**entry, "qid": f"{entry['qid']}_r1",
                  "retry_attempt": 1})

    def dispatch(entry: Dict[str, Any]) -> None:
        try:
            fire(entry)
        finally:
            sem.release()

    for entry in plan["entries"]:
        if entry["retry_attempt"]:
            continue  # reactive retries only — predictions not replayed
        due = t0 + entry["offset_s"]
        now = time.perf_counter()
        if due > now:
            time.sleep(due - now)
        sem.acquire()
        th = threading.Thread(target=dispatch, args=(entry,),
                              daemon=True)
        threads.append(th)
        th.start()
    for th in threads:
        th.join(timeout=130.0)
    wall = time.perf_counter() - t0
    return {"latencies": {t: sorted(v) for t, v in lat.items()},
            "sheds": sorted(sheds), "errors": errors,
            "submitted": submitted[0],
            "structured_429": structured[0],
            "malformed_sheds": structured[1],
            "duration_s": wall}


# -- the gate ---------------------------------------------------------------

def run_gate(multiple: float = 4.0, seed: int = 20260805,
             n_queries: int = 48, rows: int = 4096,
             mode: str = "cluster", chaos: bool = True,
             record_qps: float = 24.0,
             ledger_out: Optional[str] = None,
             keep_dir: Optional[str] = None) -> Dict[str, Any]:
    """The full closed loop (module docstring). Returns the summary
    dict; ``ok`` is the gate verdict. Resets the process-global
    workload/governor state around the run."""
    from pinot_tpu.broker.workload import (global_governor,
                                           global_workload)
    from pinot_tpu.utils import faults
    from pinot_tpu.utils import ledger as uledger
    from pinot_tpu.utils.slo import (global_incidents, global_slo,
                                     normalize_alerts)

    tmp = keep_dir or tempfile.mkdtemp(prefix="ptpu_replay_")
    failures: List[str] = []

    def check(name: str, ok: bool, detail: str = "") -> None:
        if not ok:
            failures.append(f"{name}: {detail}")

    global_workload.reset()
    faults.clear()
    stop = None
    summary: Dict[str, Any] = {
        "mode": mode, "scenario": "overload_replay", "seed": seed,
        "multiple": multiple, "queries_recorded": n_queries}
    try:
        configure_tenants()
        stats_path = os.path.join(tmp, "replay_stats.jsonl")
        if mode == "cluster":
            _ctrl, _servers, broker, stop = build_cluster(tmp, rows)
            stats_path = broker.forensics.ledger_path
            client = _ClusterClient(broker.url)
            p0 = _servers[0].port
            chaos_plan_text = (
                f"seed={seed}; "
                f"segment.slow: match=server_0, delay_ms=40, times=8; "
                f"rpc.drop: match=:{p0}/query/bin, times=1")
        elif mode == "local":
            broker = build_local(tmp, rows)
            client = _LocalClient(broker)
            # local-mode chaos is armed AFTER the plan is computed: an
            # accountant OOM kill targeted at one ADMITTED besteffort
            # query (the watcher-kill story under pressure; protected
            # must still see zero kills)
            chaos_plan_text = None
        else:
            raise ValueError(f"unknown mode {mode!r}")

        mix = build_mix(seed, n_queries)
        # warmup: every (table, shape) pays its XLA compile outside the
        # measured phases
        seen = set()
        for q in mix:
            key = (q["table"], q["sql"].split("FROM")[0])
            if key in seen:
                continue
            seen.add(key)
            client.query(f"{q['sql']} OPTION("
                         f"timeoutMs={OPTION_TIMEOUT_MS},"
                         f"queryId=warm_{len(seen)}"
                         f"{client.extra_opt})")

        # 1) record at 1x — the replay input + the pre-spike baseline
        pre = record_phase(
            client, mix, record_qps,
            stats_path if mode == "local" else None)
        check("record.errors", pre["errors"] == 0,
              f"{pre['errors']} errors during the 1x recording")
        records = [r for r in load_records(stats_path)
                   if str(r.get("qid", "")).startswith(f"rp{seed}_")]
        check("record.count", len(records) >= n_queries * 0.9,
              f"only {len(records)} of {n_queries} recorded")

        # 2) the pure plan, computed twice — must match itself
        tier_of = {t[1]: t[2] for t in TENANT_TABLES}
        plan = plan_replay(records, multiple, seed, tier_of=tier_of)
        plan2 = plan_replay(records, multiple, seed, tier_of=tier_of)
        deterministic = (plan["shed_stream"] == plan2["shed_stream"]
                         and plan["pins"] == plan2["pins"])
        check("plan.deterministic", deterministic,
              "two same-seed plans diverged")
        check("plan.sheds_besteffort",
              any(s[1] == "ten_besteffort"
                  for s in plan["shed_stream"]),
              "the 4x plan shed no besteffort query — raise multiple")
        check("plan.protected_never_shed",
              all(s[1] != "ten_protected" for s in plan["shed_stream"]),
              "plan shed a protected query")

        # 2b) the pure SLO alert plan, computed twice — byte-identical
        # (utils/slo.plan_alert_stream: same corpus => same alert
        # stream, the ISSUE 17 determinism contract)
        slo_objs, slo_plan, be_frac = plan_slo(records, plan, multiple)
        slo_plan2 = plan_slo(records, plan, multiple)[1]
        slo_deterministic = (
            json.dumps(slo_plan, sort_keys=True)
            == json.dumps(slo_plan2, sort_keys=True))
        check("slo.plan_deterministic", slo_deterministic,
              "two same-input SLO alert plans diverged")
        check("slo.plan_alerts", len(slo_plan["alerts"]) >= 1,
              "the 4x SLO plan fired no burn alert — raise multiple")
        planned_avail = sorted({
            x for x in normalize_alerts(slo_plan["alerts"])
            if x[2] == "availability"})
        check("slo.plan_besteffort_burns",
              any(x[1] == "tenant:ten_besteffort"
                  for x in planned_avail),
              "planned availability burn missed the shed tenant")
        check("slo.plan_protected_never_burns",
              all(x[1] != "tenant:ten_protected"
                  for x in normalize_alerts(slo_plan["alerts"])),
              "the plan burned the protected tenant's budget")
        # live SLO plane: armed with the plan's availability objectives
        # only (live wall clocks are nondeterministic — the latency
        # objectives stay plan-side); fed by the cluster broker's
        # forensics plane per completed/shed query
        slo_live = mode == "cluster"
        if slo_live:
            global_slo.clear()
            global_incidents.reset()
            for spec in slo_objs:
                if spec["kind"] == "availability":
                    global_slo.set_objective(**spec)

        if mode == "local" and chaos:
            shed_qids = {s0[0] for s0 in plan["shed_stream"]}
            victim = next(
                (e["qid"] for e in plan["entries"]
                 if e["tier"] == "besteffort"
                 and not e["retry_attempt"]
                 and e["qid"] not in shed_qids), None)
            check("plan.oom_victim", victim is not None,
                  "no admitted besteffort query to target with "
                  "accounting.oom_kill")
            chaos_plan_text = (
                f"seed={seed}; accounting.oom_kill: match={victim}, "
                f"times=1") if victim else None

        # 3) the spike: pins + chaos armed, replay on schedule
        global_workload.clear_shed_log()
        global_governor.pin_rungs(plan["pins"])
        fault_plan = faults.install(chaos_plan_text) \
            if chaos and chaos_plan_text else None
        try:
            spike = run_spike(client, plan)
        finally:
            fired = len(fault_plan.fired) if fault_plan else 0
            faults.clear()
            global_governor.unpin()
        observed = [s for s in global_workload.shed_stream()
                    if s[0] in plan["pins"]]

        # 4) verify
        check("spike.stream_matches_plan",
              observed == plan["shed_stream"],
              f"observed {len(observed)} shed(s) != planned "
              f"{len(plan['shed_stream'])}")
        client_seen = sorted(s[0] for s in spike["sheds"])
        planned_qids = sorted(s[0] for s in plan["shed_stream"])
        check("spike.client_saw_every_shed",
              client_seen == planned_qids,
              f"client saw {len(client_seen)} shed responses, "
              f"planned {len(planned_qids)}")
        check("spike.structured_429",
              spike["malformed_sheds"] == 0
              and spike["structured_429"] == len(spike["sheds"]),
              f"{spike['malformed_sheds']} shed responses were not "
              "structured 429s")
        check("spike.protected_zero_sheds",
              not any(s[1] == "ten_protected" for s in observed),
              "a protected-tenant query was shed")
        check("spike.protected_zero_errors",
              spike["errors"].get("protected", 0) == 0,
              f"{spike['errors'].get('protected', 0)} protected "
              "errors (OOM-kill/5xx) during the spike")
        pre_prot = pre["latencies"].get("protected") or [0.0]
        prot = spike["latencies"].get("protected") or []
        prot_bar = (_pctl(pre_prot, 0.99) * PROTECTED_BAR_FACTOR
                    + PROTECTED_BAR_FLOOR_MS)
        prot_p99 = _pctl(prot, 0.99) if prot else 0.0
        check("spike.protected_completed", len(prot) >= 1,
              "no protected query completed during the spike")
        check("spike.protected_p99_bar", prot_p99 <= prot_bar,
              f"protected p99 {prot_p99:.1f}ms > bar {prot_bar:.1f}ms")
        if chaos:
            check("spike.chaos_fired", fired >= 1,
                  "the armed chaos plan never fired")

        # 4b) live SLO verdicts (cluster mode): the live availability
        # alert set must match the precomputed plan's — compared on the
        # normalized (alert, scope, kind, severity) projection, the
        # shed-stream discipline (ts/proc/burn magnitudes are process
        # identity and jitter, not decisions)
        live_avail: List[Any] = []
        incidents_count = 0
        if slo_live:
            global_incidents.drain(5.0)
            live_avail = sorted({
                x for x in normalize_alerts(global_slo.alerts.alerts())
                if x[0] == "slo_burn" and x[2] == "availability"})
            check("slo.live_matches_plan", live_avail == planned_avail,
                  f"live availability alerts {live_avail} != "
                  f"planned {planned_avail}")
            blk = global_slo.status_block()
            prot = next(
                (r for r in blk["objectives"]
                 if r["scope"] == "tenant:ten_protected"
                 and r["kind"] == "availability"), None)
            check("slo.protected_budget_intact",
                  prot is not None and prot["burn_slow"] == 0.0
                  and prot["budget_remaining"] == 1.0,
                  f"protected error budget dented: {prot}")
            inc = global_incidents.snapshot()
            incidents_count = inc["count"]
            check("slo.incident_captured", incidents_count >= 1,
                  "no incident bundle captured on the burn alert")
            if inc["incidents"]:
                first = inc["incidents"][0]
                verr = uledger.validate_record(first)
                check("slo.incident_valid", not verr,
                      f"incident bundle violates the ledger "
                      f"contract: {verr}")
                check("slo.incident_surfaces",
                      {"slow_queries", "overload", "tier", "devmem",
                       "compile", "slo"}
                      <= set(first.get("surfaces") or {}),
                      f"incident bundle missing surfaces: "
                      f"{sorted(first.get('surfaces') or {})}")

        # 5) recovery: fresh 1x pass must land inside the noise floor
        post_mix = [{**q, "qid": q["qid"] + "_post"} for q in mix]
        post = record_phase(client, post_mix, record_qps, None)
        pre_all = sorted(x for v in pre["latencies"].values()
                         for x in v)
        post_all = sorted(x for v in post["latencies"].values()
                          for x in v)
        pre_p50 = _pctl(pre_all, 0.5)
        post_p50 = _pctl(post_all, 0.5)
        recover_bar = pre_p50 * RECOVER_FACTOR + RECOVER_FLOOR_MS
        recovered = bool(post_all) and post_p50 <= recover_bar
        check("recovery", recovered,
              f"post-spike p50 {post_p50:.1f}ms > bar "
              f"{recover_bar:.1f}ms (pre {pre_p50:.1f}ms) — "
              "metastable state?")

        # 5b) the post-spike good traffic drained the 1s fast window,
        # so the paired-window level dropped below threshold and the
        # latched burn alert CLEARED — no stale page after recovery
        if slo_live:
            blk = global_slo.status_block()
            be = next(
                (r for r in blk["objectives"]
                 if r["scope"] == "tenant:ten_besteffort"
                 and r["kind"] == "availability"), None)
            check("slo.recovery_burn_cleared",
                  be is not None and not be["alerting"]
                  and be["burn_fast"] == 0.0,
                  f"burn alert latched past recovery: {be}")

        completed = sum(len(v) for v in spike["latencies"].values())
        shed_by_tenant: Dict[str, int] = {}
        shed_by_rung: Dict[str, int] = {}
        shed_by_reason: Dict[str, int] = {}
        for _qid, tn, rung, reason, _after in observed:
            shed_by_tenant[tn] = shed_by_tenant.get(tn, 0) + 1
            shed_by_rung[str(rung)] = shed_by_rung.get(str(rung), 0) + 1
            shed_by_reason[reason] = shed_by_reason.get(reason, 0) + 1
        tiers = {}
        for tier in ("protected", "standard", "besteffort"):
            lat = spike["latencies"].get(tier) or []
            tiers[tier] = {
                "completed": len(lat),
                "p50_ms": round(_pctl(lat, 0.5), 3),
                "p99_ms": round(_pctl(lat, 0.99), 3),
                "errors": spike["errors"].get(tier, 0),
            }
        summary.update({
            "backend": _backend(),
            "offered": spike["submitted"],
            "completed": completed,
            "shed": len(observed),
            "shed_by_tenant": shed_by_tenant,
            "shed_by_rung": shed_by_rung,
            "shed_by_reason": shed_by_reason,
            "tiers": tiers,
            "structured_429": spike["structured_429"],
            "retries": len([s for s in spike["sheds"]
                            if s[0].endswith("_r1")]),
            "deterministic": bool(deterministic
                                  and observed == plan["shed_stream"]),
            "protected_sheds": shed_by_tenant.get("ten_protected", 0),
            "protected_p99_ms": round(prot_p99, 3),
            "protected_bar_ms": round(prot_bar, 3),
            "goodput_qps": round(
                completed / max(spike["duration_s"], 1e-3), 3),
            "duration_s": round(spike["duration_s"], 3),
            "spike_errors": sum(spike["errors"].values()),
            "chaos": chaos,
            "faults_fired": fired,
            "recovered": recovered,
            "recovery": {"pre_p50_ms": round(pre_p50, 3),
                         "post_p50_ms": round(post_p50, 3),
                         "bar_ms": round(recover_bar, 3)},
            "extra": {"slo": {
                "plan_deterministic": slo_deterministic,
                "alerts_planned": len(slo_plan["alerts"]),
                "planned_availability": [list(x) for x in planned_avail],
                "live_availability": [list(x) for x in live_avail],
                "live": slo_live,
                "incidents": incidents_count,
                "besteffort_shed_frac": round(be_frac, 4),
            }},
            "ok": not failures,
        })
        if failures:
            summary["error"] = "; ".join(failures[:4])
        if ledger_out:
            contract = uledger.KINDS["replay_bench"]
            allowed = contract["required"] | contract["optional"]
            rec = uledger.make_record("replay_bench", **{
                k: v for k, v in summary.items() if k in allowed})
            uledger.append_record(rec, ledger_out)
        summary["failures"] = failures
        return summary
    finally:
        faults.clear()
        global_workload.reset()
        global_slo.clear()
        global_slo.path = None     # the tmp ledger dir is about to go
        global_incidents.reset()
        global_incidents.path = None
        if stop is not None:
            stop()
        if keep_dir is None:
            shutil.rmtree(tmp, ignore_errors=True)


# -- rebalance gate (ISSUE 19) ----------------------------------------------
# The closed-loop rebalance replay: record a skewed mix, burn the hot
# table's latency SLO with donor-only chaos, precompute the pure move
# plan, let the rebalancer execute it, and verify the observed move
# stream equals the plan byte-for-byte, digests never drift across the
# cutover, the protected table's p99 stays inside its bar, and the burn
# is measurably lower after convergence WITHOUT shifting to the
# receiver (the donor-matched chaos stays armed the whole time — the
# burn drops because placement moved, not because the fault cleared).

REBALANCE_TABLES = (("rb_hot", 3), ("rb_prot", 2))
REBALANCE_DELAY_MS = 40.0
# hot-table SLO bar, self-calibrated between the pre-chaos p99 and the
# injected +40ms: above noise, below the slowed donor
REBALANCE_BAR_FACTOR = 1.25
REBALANCE_BAR_FLOOR_MS = 15.0
# burn windows sized like the overload gate's: the slow window outlives
# the burn phase but drains within seconds of post-cutover good traffic
REBALANCE_FAST_S = 1.0
REBALANCE_SLOW_S = 6.0
REBALANCE_DRAIN_TIMEOUT_S = 20.0


def build_rebalance_cluster(tmp: str, rows: int = 2048,
                            poll: float = 0.1):
    """Controller + 2 servers + broker with engineered skew: ``rb_hot``
    lands wholly on server_0 (added while it is the only live server),
    ``rb_prot`` lands on server_1 (least-loaded placement after it
    joins) — the donor/receiver geometry the closed loop must fix."""
    from pinot_tpu.cluster import BrokerNode, Controller, ServerNode
    from pinot_tpu.segment import SegmentBuilder
    from pinot_tpu.spi import TableConfig

    ctrl = Controller(os.path.join(tmp, "ctrl"), heartbeat_timeout=5.0,
                      reconcile_interval=0.2)
    servers = [ServerNode("server_0", ctrl.url, poll_interval=poll)]
    cols = _gen_columns(rows)

    def add(table: str, n_segments: int) -> None:
        schema = _schema(table)
        builder = SegmentBuilder(schema, TableConfig(table))
        ctrl.add_table(table, schema.to_dict(), replication=1)
        step = rows // n_segments
        for i in range(n_segments):
            lo = i * step
            hi = rows if i == n_segments - 1 else (i + 1) * step
            d = builder.build({n: v[lo:hi] for n, v in cols.items()},
                              os.path.join(tmp, table), f"seg_{i}")
            ctrl.add_segment(table, f"seg_{i}", d)

    add(*REBALANCE_TABLES[0])   # all on server_0 (the future donor)
    v = ctrl.routing_snapshot()["version"]
    assert servers[0].wait_for_version(v, timeout=30.0), \
        "server_0 never synced"
    servers.append(ServerNode("server_1", ctrl.url, poll_interval=poll))
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline and len(ctrl.live_servers()) < 2:
        time.sleep(0.05)
    assert len(ctrl.live_servers()) >= 2, "server_1 never registered"
    add(*REBALANCE_TABLES[1])   # least-loaded -> server_1
    broker = BrokerNode(ctrl.url, routing_refresh=poll)
    v = ctrl.routing_snapshot()["version"]
    for s in servers:
        assert s.wait_for_version(v, timeout=30.0), "server never synced"
    assert broker.wait_for_version(v, timeout=30.0), "broker never synced"
    # park the scheduled pass: every rebalance pass in this gate is a
    # deliberate, manually-triggered phase
    ctrl.scheduler._next_run[ctrl.rebalancer.NAME] = \
        time.monotonic() + 1e9

    def stop():
        broker.stop()
        for s in servers:
            try:
                s.stop()
            except Exception:
                pass
        ctrl.stop()

    return ctrl, servers, broker, stop


def build_rebalance_mix(seed: int, n_queries: int
                        ) -> List[Dict[str, Any]]:
    """The seeded (qid, table, sql) sequence — pure in (seed, n), hot
    table weighted 2:1 so the burn signal dominates the mix."""
    import numpy as np
    rng = np.random.default_rng([seed, 1906])
    weighted = ["rb_hot", "rb_hot", "rb_prot"]
    out = []
    for i in range(n_queries):
        table = weighted[int(rng.integers(len(weighted)))]
        shape = QUERY_SHAPES[int(rng.integers(len(QUERY_SHAPES)))]
        sql = shape.format(t=table, p=int(rng.integers(100, 1000)))
        out.append({"qid": f"rbm{seed}_{i}", "table": table,
                    "sql": sql})
    return out


def _rb_phase(broker_url: str, mix: List[Dict[str, Any]], tag: str,
              qps: float) -> Dict[str, Any]:
    """Run the mix once, paced at ``qps``: per-table latencies + the
    per-qid result digest (the drift detector across cutovers)."""
    from pinot_tpu.cluster.http_util import http_json
    lat: Dict[str, List[float]] = {}
    digests: Dict[str, str] = {}
    errors = 0
    t_start = time.perf_counter()
    for i, q in enumerate(mix):
        target = t_start + i / qps
        delay = target - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        sql = (f"{q['sql']} OPTION(timeoutMs={OPTION_TIMEOUT_MS},"
               f"queryId={tag}_{q['qid']})")
        t0 = time.perf_counter()
        try:
            resp = http_json("POST", f"{broker_url}/query/sql",
                             {"sql": sql}, timeout=120.0)
        except Exception:  # noqa: BLE001 — counted, not raised
            errors += 1
            continue
        lat.setdefault(q["table"], []).append(
            (time.perf_counter() - t0) * 1e3)
        digests[q["qid"]] = json.dumps(
            (resp or {}).get("resultTable"), sort_keys=True)
    return {"lat": {t: sorted(v) for t, v in lat.items()},
            "digests": digests, "errors": errors,
            "duration_s": time.perf_counter() - t_start}


def run_rebalance_gate(seed: int = 20260807, n_queries: int = 24,
                       rows: int = 2048, qps: float = 12.0,
                       ledger_out: Optional[str] = None
                       ) -> Dict[str, Any]:
    """The closed-loop rebalance gate (section comment above). Returns
    the summary dict; ``ok`` is the verdict."""
    from pinot_tpu.cluster.rebalancer import plan_moves
    from pinot_tpu.engine.tier import global_tier
    from pinot_tpu.utils import faults
    from pinot_tpu.utils import ledger as uledger
    from pinot_tpu.utils.metrics import global_metrics
    from pinot_tpu.utils.slo import global_incidents, global_slo

    tmp = tempfile.mkdtemp(prefix="ptpu_rebalance_")
    failures: List[str] = []
    summary: Dict[str, Any] = {
        "scenario": "rebalance_replay", "seed": seed, "multiple": 1.0,
        "queries_recorded": n_queries, "mode": "cluster"}

    def check(name: str, ok: bool, detail: str = "") -> None:
        if not ok:
            failures.append(f"{name}: {detail}")

    faults.clear()
    global_slo.clear()
    global_incidents.reset()
    global_tier.configure(budget_bytes=None)
    stop = None
    try:
        ctrl, servers, broker, stop = build_rebalance_cluster(tmp, rows)
        rb = ctrl.rebalancer
        rb.budget_moves = 8          # one pass moves every hot segment
        rb.budget_bytes = 1 << 30
        rb.prewarm_timeout = 15.0
        mix = build_rebalance_mix(seed, n_queries)

        def holders() -> Dict[str, List[str]]:
            with ctrl._lock:
                return {s: list(h) for s, h in
                        ctrl._state["assignment"]["rb_hot"].items()}

        check("skew.initial",
              all(h == ["server_0"] for h in holders().values()),
              f"hot table not pinned to the donor: {holders()}")

        # warmup: every (table, shape) pays its XLA compile off-phase
        seen = set()
        for q in mix:
            key = (q["table"], q["sql"].split("FROM")[0])
            if key in seen:
                continue
            seen.add(key)
            _rb_phase(broker.url, [q], f"warm{len(seen)}", qps=1e9)

        # 1) record at 1x: the latency baseline + the digest corpus
        base = _rb_phase(broker.url, mix, "base", qps)
        check("record.errors", base["errors"] == 0,
              f"{base['errors']} errors during the 1x recording")
        hot_bar = (_pctl(base["lat"].get("rb_hot") or [0.0], 0.99)
                   * REBALANCE_BAR_FACTOR + REBALANCE_BAR_FLOOR_MS)
        prot_bar = (_pctl(base["lat"].get("rb_prot") or [0.0], 0.99)
                    * PROTECTED_BAR_FACTOR + PROTECTED_BAR_FLOOR_MS)
        check("record.bar_below_delay",
              hot_bar < _pctl(base["lat"].get("rb_hot") or [0.0], 0.5)
              + REBALANCE_DELAY_MS,
              f"bar {hot_bar:.1f}ms cannot separate the slowed donor")

        # 2) burn: donor-only chaos stays armed from here to the END —
        # the later burn drop must come from the cutover, not disarming
        global_slo.set_objective("rb_hot", "latency", bar_ms=hot_bar,
                                 objective=0.9,
                                 fast_s=REBALANCE_FAST_S,
                                 slow_s=REBALANCE_SLOW_S)
        global_slo.set_objective("rb_prot", "latency", bar_ms=prot_bar,
                                 objective=0.9,
                                 fast_s=REBALANCE_FAST_S,
                                 slow_s=REBALANCE_SLOW_S)
        fault_plan = faults.install(
            f"seed={seed}; segment.slow: match=server_0, "
            f"delay_ms={REBALANCE_DELAY_MS:.0f}, times=-1")
        burn = _rb_phase(broker.url, mix, "burn", qps)
        for qid, d in burn["digests"].items():
            check(f"digest.burn.{qid}", d == base["digests"].get(qid),
                  "digest drift under donor chaos")
        prot_burn = burn["lat"].get("rb_prot") or []
        check("burn.protected_p99",
              prot_burn and _pctl(prot_burn, 0.99) <= prot_bar,
              f"protected p99 {_pctl(prot_burn, 0.99):.1f}ms > bar "
              f"{prot_bar:.1f}ms during the burn")

        def _burn(scope: str) -> Dict[str, Any]:
            return next(
                (r for r in global_slo.status_block()["objectives"]
                 if r["scope"] == scope and r["kind"] == "latency"),
                {"burn_slow": 0.0, "burn_fast": 0.0, "alerting": False})

        burn_before = _burn("rb_hot")["burn_slow"]
        check("burn.ignited",
              burn_before >= rb.burn_threshold,
              f"hot-table burn {burn_before:.2f} never crossed "
              f"{rb.burn_threshold}")
        # the burn alert captured an incident; acknowledge it (the
        # freeze lever belongs to chaos_smoke --rebalance) and roll up
        global_incidents.reset()
        ctrl.rollup.run()
        rollup = (ctrl.rollup.snapshot() or {}).get("rollup")

        # 3) the pure plan, computed twice — must match itself, and the
        # executed move stream must match it byte-for-byte
        inputs = rb._plan_inputs()
        kw = dict(budget=rb._budget(), instances=inputs["instances"],
                  sizes=inputs["sizes"], recent=frozenset(),
                  threshold=rb.burn_threshold)
        expected = plan_moves(rollup, inputs["assignment"], **kw)
        expected2 = plan_moves(rollup, inputs["assignment"], **kw)
        proj = ("table", "segment", "donor", "receiver", "bytes",
                "reason")
        as_bytes = lambda moves: json.dumps(  # noqa: E731
            [{k: m[k] for k in proj} for m in moves], sort_keys=True)
        check("plan.deterministic",
              as_bytes(expected) == as_bytes(expected2),
              "two same-input plans diverged")
        check("plan.moves", len(expected) == REBALANCE_TABLES[0][1],
              f"planned {len(expected)} of {REBALANCE_TABLES[0][1]} "
              f"hot segments: {expected}")
        check("plan.geometry",
              all(m["donor"] == "server_0"
                  and m["receiver"] == "server_1" for m in expected),
              f"plan left the donor/receiver geometry: {expected}")

        ring_before = len(rb.snapshot()["moves"])
        res = rb.run()
        check("cutover.executed",
              not res["frozen"] and res["planned"] == len(expected)
              and res["executed"] == len(expected),
              f"pass did not execute the plan: {res}")
        events = rb.snapshot()["moves"][ring_before:]
        observed = [{k: e[k] for k in proj} for e in events
                    if e["phase"] == "plan"]
        check("cutover.stream_matches_plan",
              json.dumps(observed, sort_keys=True) == as_bytes(expected),
              f"observed move stream != plan "
              f"({len(observed)} vs {len(expected)} moves)")
        flipped = sorted(e["segment"] for e in events
                         if e["phase"] == "flip")
        check("cutover.flips",
              flipped == sorted(m["segment"] for m in expected),
              f"flips {flipped} != plan")
        v = ctrl.routing_snapshot()["version"]
        check("cutover.converged",
              broker.wait_for_version(v, timeout=15.0)
              and all(s.wait_for_version(v, timeout=15.0)
                      for s in servers),
              "cluster never converged on the flipped assignment")
        check("cutover.placement",
              all(h == ["server_1"] for h in holders().values()),
              f"hot table not on the receiver: {holders()}")

        # 4) after: chaos STILL armed on the donor; queries now route
        # to the receiver, so latency recovers and the burn drains
        c0 = global_metrics.snapshot()["counters"]
        after = _rb_phase(broker.url, mix, "after", qps)
        for qid, d in after["digests"].items():
            check(f"digest.after.{qid}", d == base["digests"].get(qid),
                  "digest drift across the cutover")
        hot_after = after["lat"].get("rb_hot") or []
        check("after.hot_inside_bar",
              hot_after and _pctl(hot_after, 0.99) <= hot_bar,
              f"hot p99 {_pctl(hot_after, 0.99):.1f}ms still over the "
              f"bar {hot_bar:.1f}ms after the cutover")
        prot_after = after["lat"].get("rb_prot") or []
        check("after.protected_p99",
              prot_after and _pctl(prot_after, 0.99) <= prot_bar,
              f"protected p99 {_pctl(prot_after, 0.99):.1f}ms > bar "
              f"{prot_bar:.1f}ms after the cutover")
        # the receiver's first touch per drained segment re-promotes
        # from WARM arrays (no cold re-pad): bounded, then zero
        c1 = global_metrics.snapshot()["counters"]
        promo_after = (c1.get("tier_promotions", 0)
                       - c0.get("tier_promotions", 0))
        check("after.promotions_bounded",
              promo_after <= len(expected),
              f"{promo_after} promotions for {len(expected)} drained "
              "segments — cold re-pads?")
        settle = _rb_phase(broker.url, mix, "settle", qps)
        for qid, d in settle["digests"].items():
            check(f"digest.settle.{qid}", d == base["digests"].get(qid),
                  "digest drift at steady state")
        c2 = global_metrics.snapshot()["counters"]
        promo_settle = (c2.get("tier_promotions", 0)
                        - c1.get("tier_promotions", 0))
        check("settle.no_rewarm", promo_settle == 0,
              f"{promo_settle} promotions at steady state — the "
              "pre-warm did not pay the receiver's warmup debt")

        # 5) burn convergence: measurably lower on the hot table, NOT
        # shifted to the receiver's protected table
        deadline = time.monotonic() + REBALANCE_DRAIN_TIMEOUT_S
        hot = _burn("rb_hot")
        while time.monotonic() < deadline and \
                (hot["burn_fast"] > 0.0
                 or hot["burn_slow"] >= burn_before * 0.5):
            time.sleep(0.25)
            hot = _burn("rb_hot")
        check("converge.burn_lower",
              hot["burn_slow"] < burn_before * 0.5
              and hot["burn_fast"] == 0.0,
              f"burn {hot['burn_slow']:.2f} (was {burn_before:.2f}) "
              "never drained after the cutover")
        prot = _burn("rb_prot")
        check("converge.not_shifted",
              prot["burn_slow"] < rb.burn_threshold
              and not prot["alerting"],
              f"burn shifted to the receiver: {prot}")

        summary.update({
            "backend": _backend(),
            "offered": 4 * n_queries,
            "completed": 4 * n_queries
            - sum(p["errors"] for p in (base, burn, after, settle)),
            "shed": 0,
            "goodput_qps": round(
                len(after["digests"])
                / max(after["duration_s"], 1e-3), 3),
            "duration_s": round(base["duration_s"] + burn["duration_s"]
                                + after["duration_s"]
                                + settle["duration_s"], 3),
            "faults_fired": len(fault_plan.fired),
            "chaos": True,
            "deterministic": as_bytes(expected) == as_bytes(expected2),
            "extra": {"rebalance": {
                "moves_planned": len(expected),
                "moves_executed": res["executed"],
                "burn_before": round(burn_before, 3),
                "burn_after": round(hot["burn_slow"], 3),
                "receiver_burn": round(prot["burn_slow"], 3),
                "hot_bar_ms": round(hot_bar, 3),
                "promotions_after": promo_after,
                "promotions_settle": promo_settle,
            }},
            "ok": not failures,
        })
        if failures:
            summary["error"] = "; ".join(failures[:4])
        if ledger_out:
            contract = uledger.KINDS["replay_bench"]
            allowed = contract["required"] | contract["optional"]
            rec = uledger.make_record("replay_bench", **{
                k: v for k, v in summary.items() if k in allowed})
            uledger.append_record(rec, ledger_out)
        summary["failures"] = failures
        return summary
    finally:
        faults.clear()
        global_slo.clear()
        global_slo.path = None
        global_incidents.reset()
        global_incidents.path = None
        if stop is not None:
            stop()
        shutil.rmtree(tmp, ignore_errors=True)


# -- the incident-autopsy gate (round 25) -----------------------------------
#
# Four passes over ONE warmed cluster, each sliced out of the broker's
# ledger by sequence: a clean pass must yield an EXPLICIT inconclusive
# verdict, then three injected causes — donor-only ``segment.slow``
# chaos, a cleared-cache compile storm, a starved HBM-budget tier
# thrash — must each be named top-1 with every competing cause scored
# strictly lower, and each verdict computed twice must be
# byte-identical (cluster/autopsy.py plan_autopsy is a detlint ROOTS
# member, so the same corpus can never rank differently).

AUTOPSY_TABLE = "ap_events"
AUTOPSY_DELAY_MS = 60.0
# far below one segment column: every admission demotes everything else
AUTOPSY_TIER_BUDGET_BYTES = 4096


def build_autopsy_cluster(tmp: str, rows: int = 1024,
                          poll: float = 0.1):
    """Controller + 2 servers + broker WITH a stats/trace ledger and
    full trace sampling (the straggler scorer reads per-server scatter
    spans out of ``query_trace`` records), one table replicated on both
    servers so every query scatters to both — the geometry a one-sided
    ``segment.slow`` plan must show up in."""
    from pinot_tpu.cluster import BrokerNode, Controller, ServerNode
    from pinot_tpu.segment import SegmentBuilder
    from pinot_tpu.spi import TableConfig

    ctrl = Controller(os.path.join(tmp, "ctrl"), heartbeat_timeout=5.0,
                      reconcile_interval=0.2)
    servers = [ServerNode(f"server_{i}", ctrl.url, poll_interval=poll)
               for i in range(2)]
    broker = BrokerNode(ctrl.url, routing_refresh=poll,
                        query_stats_path=os.path.join(
                            tmp, "query_stats.jsonl"),
                        trace_ratio=1.0)
    cols = _gen_columns(rows)
    schema = _schema(AUTOPSY_TABLE)
    builder = SegmentBuilder(schema, TableConfig(AUTOPSY_TABLE))
    ctrl.add_table(AUTOPSY_TABLE, schema.to_dict(), replication=2)
    half = rows // 2
    for i, (lo, hi) in enumerate(((0, half), (half, rows))):
        d = builder.build({n: v[lo:hi] for n, v in cols.items()},
                          os.path.join(tmp, AUTOPSY_TABLE), f"seg_{i}")
        ctrl.add_segment(AUTOPSY_TABLE, f"seg_{i}", d)
    v = ctrl.routing_snapshot()["version"]
    for s in servers:
        assert s.wait_for_version(v, timeout=30.0), "server never synced"
    assert broker.wait_for_version(v, timeout=30.0), "broker never synced"
    # park the closed loop: nothing may move segments mid-gate (the
    # rebalance-churn scorer must see an empty move stream)
    ctrl.scheduler._next_run[ctrl.rebalancer.NAME] = \
        time.monotonic() + 1e9

    def stop():
        broker.stop()
        for s in servers:
            try:
                s.stop()
            except Exception:
                pass
        ctrl.stop()

    return ctrl, servers, broker, stop


def build_autopsy_mix(seed: int, n_queries: int) -> List[Dict[str, Any]]:
    """The seeded single-table (qid, sql) sequence — pure in (seed, n)."""
    import numpy as np
    rng = np.random.default_rng([seed, 2025])
    out = []
    for i in range(n_queries):
        shape = QUERY_SHAPES[int(rng.integers(len(QUERY_SHAPES)))]
        out.append({"qid": f"ap{seed}_{i}", "table": AUTOPSY_TABLE,
                    "sql": shape.format(
                        t=AUTOPSY_TABLE,
                        p=int(rng.integers(100, 1000)))})
    return out


def run_autopsy_gate(seed: int = 20260807, n_queries: int = 12,
                     rows: int = 1024, qps: float = 25.0,
                     ledger_out: Optional[str] = None
                     ) -> Dict[str, Any]:
    """The incident-autopsy gate (section comment above). Returns the
    summary dict; ``ok`` is the verdict."""
    from pinot_tpu.cluster.autopsy import (global_autopsy, load_corpus,
                                           plan_autopsy, whydown)
    from pinot_tpu.engine.tier import global_tier
    from pinot_tpu.utils import faults
    from pinot_tpu.utils import ledger as uledger
    from pinot_tpu.utils.compileplane import (clear_staged_caches,
                                              global_compile_log)
    from pinot_tpu.utils.slo import (event_time, global_incidents,
                                     global_slo)

    tmp = tempfile.mkdtemp(prefix="ptpu_autopsy_")
    failures: List[str] = []
    summary: Dict[str, Any] = {
        "scenario": "autopsy_replay", "seed": seed, "multiple": 1.0,
        "queries_recorded": n_queries, "mode": "cluster"}

    def check(name: str, ok: bool, detail: str = "") -> None:
        if not ok:
            failures.append(f"{name}: {detail}")

    faults.clear()
    global_slo.clear()
    global_incidents.reset()
    global_incidents.post_hook = None   # the broker re-wires below
    global_autopsy.reset()
    global_autopsy.path = None
    global_tier.configure(budget_bytes=None)
    had_compile_path = bool(global_compile_log.path)
    stop = None
    t_start = time.perf_counter()
    try:
        ctrl, servers, broker, stop = build_autopsy_cluster(tmp, rows)
        path = broker.forensics.ledger_path
        mix = build_autopsy_mix(seed, n_queries)

        # wiring sanity: the broker adopted its ledger for the autopsy
        # plane and hooked attribution onto incident capture
        check("wire.autopsy_path", global_autopsy.path == path,
              f"autopsy ledger {global_autopsy.path} != {path}")
        check("wire.post_hook",
              getattr(global_incidents.post_hook, "__self__", None)
              is global_autopsy,
              "incident post hook not wired to the autopsy plane")

        # warmup: each query shape pays its XLA compile off-corpus, so
        # the clean pass sees zero in-window compile events
        seen = set()
        for q in mix:
            key = q["sql"].split("FROM")[0]
            if key in seen:
                continue
            seen.add(key)
            _rb_phase(broker.url, [q], f"apwarm{len(seen)}", qps=1e9)

        def probe(tag: str) -> None:
            # a synthetic info-severity alert captures a REAL incident
            # bundle (tier/devmem/overload/compile/slo surfaces) — the
            # pre/post tier blocks the thrash scorer deltas, and each
            # capture also exercises the post-hook auto-run
            alert = uledger.make_record(
                "alert", alert=f"autopsy_probe_{tag}", severity="info",
                rate_per_min=0.0, watermark=0.0, window_s=0.0,
                proc=global_incidents.proc)
            global_incidents.request(alert, sync=True)

        def run_pass(tag: str, expected: Optional[str],
                     inject=None, revert=None) -> Dict[str, Any]:
            prior = load_corpus(path)
            seq0 = prior[-1]["_seq"] if prior else 0
            probe(f"{tag}_pre")   # pre-window bundle (baseline tier)
            base = _rb_phase(broker.url, mix, f"{tag}b", qps)
            check(f"{tag}.baseline_errors", base["errors"] == 0,
                  f"{base['errors']} errors during the baseline")
            times = [t for t in (
                event_time(r) for r in load_corpus(path)
                if r["_seq"] > seq0 and r.get("kind") == "query_stats")
                if t is not None]
            check(f"{tag}.baseline_stats", bool(times),
                  "no baseline query_stats landed in the ledger")
            t_cut = max(times or [0.0]) + 1e-6
            if inject is not None:
                inject()
            try:
                win = _rb_phase(broker.url, mix, f"{tag}w", qps)
                probe(f"{tag}_post")   # bundle while still injected
            finally:
                if revert is not None:
                    revert()
            check(f"{tag}.window_errors", win["errors"] == 0,
                  f"{win['errors']} errors during the window")
            corpus = [r for r in load_corpus(path) if r["_seq"] > seq0]
            v1 = plan_autopsy(corpus, window=(t_cut, None))
            v2 = plan_autopsy(corpus, window=(t_cut, None))
            check(f"{tag}.byte_identical",
                  json.dumps(v1, sort_keys=True)
                  == json.dumps(v2, sort_keys=True),
                  "two same-corpus verdicts diverged")
            ranked = v1["causes"]
            if expected is None:
                check(f"{tag}.inconclusive",
                      v1["inconclusive"] and v1["top_cause"] == "",
                      "clean pass confabulated "
                      f"{ranked[0]['cause']}={ranked[0]['score']}")
            else:
                check(f"{tag}.top_cause", v1["top_cause"] == expected,
                      f"top {v1['top_cause'] or '<inconclusive>'} != "
                      f"{expected}: " + ", ".join(
                          f"{c['cause']}={c['score']}"
                          for c in ranked[:3]))
                check(f"{tag}.margin",
                      ranked[0]["score"] > ranked[1]["score"],
                      f"competing cause not strictly lower: "
                      f"{ranked[0]['cause']}={ranked[0]['score']} vs "
                      f"{ranked[1]['cause']}={ranked[1]['score']}")
            return v1

        verdicts: Dict[str, Dict[str, Any]] = {}
        verdicts["clean"] = run_pass("apc", None)
        verdicts["straggler"] = run_pass(
            "aps", "straggler",
            inject=lambda: faults.install(
                f"seed={seed}; segment.slow: match=server_0, "
                f"delay_ms={AUTOPSY_DELAY_MS:.0f}, times=-1"),
            revert=faults.clear)
        verdicts["compile_storm"] = run_pass(
            "apk", "compile_storm", inject=clear_staged_caches)
        verdicts["tier_thrash"] = run_pass(
            "apt", "tier_thrash",
            inject=lambda: global_tier.configure(
                budget_bytes=AUTOPSY_TIER_BUDGET_BYTES),
            revert=lambda: global_tier.configure(budget_bytes=None))

        # the per-query lane: whydown over a straggler-window query
        # must find it and surface the overlapping cross-plane events
        wd = whydown(load_corpus(path), qid=f"apsw_{mix[0]['qid']}")
        check("whydown.found",
              bool(wd["found"]) and wd["queries"] >= 1, str(wd))

        summary.update({
            "backend": _backend(),
            "offered": 8 * n_queries,
            "completed": 8 * n_queries,
            "shed": 0,
            "goodput_qps": round(
                n_queries
                / max(time.perf_counter() - t_start, 1e-3), 3),
            "duration_s": round(time.perf_counter() - t_start, 3),
            "faults_fired": 0,
            "chaos": True,
            "deterministic": not any("byte_identical" in f
                                     for f in failures),
            "extra": {"autopsy": {
                tag: {"top_cause": v["top_cause"],
                      "inconclusive": v["inconclusive"],
                      "top_score": v["causes"][0]["score"],
                      "excess_ms": v["window"]["excess_ms"],
                      "evidence_total": v["evidence_total"]}
                for tag, v in verdicts.items()}},
            "ok": not failures,
        })
        if failures:
            summary["error"] = "; ".join(failures[:4])
        if ledger_out:
            contract = uledger.KINDS["replay_bench"]
            allowed = contract["required"] | contract["optional"]
            rec = uledger.make_record("replay_bench", **{
                k: v for k, v in summary.items() if k in allowed})
            uledger.append_record(rec, ledger_out)
        summary["failures"] = failures
        return summary
    finally:
        faults.clear()
        global_tier.configure(budget_bytes=None)
        global_slo.clear()
        global_slo.path = None
        global_incidents.reset()
        global_incidents.path = None
        global_incidents.post_hook = None
        global_autopsy.reset()
        global_autopsy.path = None
        if not had_compile_path:
            # the broker adopted the tmp ledger (first-wins); release
            # it so a later in-process broker can adopt its own
            global_compile_log.configure(path="")
        if stop is not None:
            stop()
        shutil.rmtree(tmp, ignore_errors=True)


def _backend() -> str:
    try:
        import jax
        return jax.default_backend()
    except Exception:
        return "unknown"


# -- CLI --------------------------------------------------------------------

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    sub = ap.add_subparsers(dest="cmd")
    g = sub.add_parser("gate", help="full closed-loop overload gate")
    g.add_argument("--multiple", type=float, default=4.0)
    g.add_argument("--seed", type=int, default=20260805)
    g.add_argument("--queries", type=int, default=48)
    g.add_argument("--rows", type=int, default=4096)
    g.add_argument("--mode", choices=("cluster", "local"),
                   default="cluster")
    g.add_argument("--no-chaos", action="store_true")
    g.add_argument("--ledger", default=None,
                   help="append the replay_bench record here")
    p = sub.add_parser("plan", help="print the pure shed-decision "
                                    "stream for a query_stats ledger")
    p.add_argument("stats", help="query_stats JSONL path")
    p.add_argument("--multiple", type=float, default=4.0)
    p.add_argument("--seed", type=int, default=20260805)
    r = sub.add_parser("rebalance",
                       help="closed-loop rebalance gate (ISSUE 19)")
    r.add_argument("--seed", type=int, default=20260807)
    r.add_argument("--queries", type=int, default=24)
    r.add_argument("--rows", type=int, default=2048)
    r.add_argument("--qps", type=float, default=12.0)
    r.add_argument("--ledger", default=None,
                   help="append the replay_bench record here")
    a = sub.add_parser("autopsy",
                       help="incident-autopsy replay gate (ISSUE 20)")
    a.add_argument("--seed", type=int, default=20260807)
    a.add_argument("--queries", type=int, default=12)
    a.add_argument("--rows", type=int, default=1024)
    a.add_argument("--qps", type=float, default=25.0)
    a.add_argument("--ledger", default=None,
                   help="append the replay_bench record here")
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv[:1] == ["--rebalance"]:  # flag spelling of the subcommand
        argv[0] = "rebalance"
    if argv[:1] == ["--autopsy"]:   # flag spelling of the subcommand
        argv[0] = "autopsy"
    args = ap.parse_args(argv)
    if args.cmd == "autopsy":
        summary = run_autopsy_gate(seed=args.seed,
                                   n_queries=args.queries,
                                   rows=args.rows, qps=args.qps,
                                   ledger_out=args.ledger)
        print(json.dumps(summary))
        return 0 if summary.get("ok") else 1
    if args.cmd == "rebalance":
        summary = run_rebalance_gate(seed=args.seed,
                                     n_queries=args.queries,
                                     rows=args.rows, qps=args.qps,
                                     ledger_out=args.ledger)
        print(json.dumps(summary))
        return 0 if summary.get("ok") else 1
    if args.cmd == "plan":
        records = load_records(args.stats)
        plan = plan_replay(records, args.multiple, args.seed)
        print(json.dumps({
            "records": len(records),
            "capacity_qps": round(plan["capacity_qps"], 3),
            "entries": len(plan["entries"]),
            "shed_stream": [list(s) for s in plan["shed_stream"]]}))
        return 0
    if args.cmd != "gate":
        ap.print_help()
        return 2
    summary = run_gate(multiple=args.multiple, seed=args.seed,
                       n_queries=args.queries, rows=args.rows,
                       mode=args.mode, chaos=not args.no_chaos,
                       ledger_out=args.ledger)
    print(json.dumps(summary))
    return 0 if summary.get("ok") else 1


if __name__ == "__main__":
    sys.exit(main())
