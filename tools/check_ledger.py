"""Validate PERF_LEDGER.jsonl against the unified v2 schema.

Every line must parse as JSON; lines carrying ``"v": 2`` must satisfy
the per-kind field contract in pinot_tpu/utils/ledger.py — unknown or
missing fields fail, so a typo'd field name can never silently fork the
schema. Lines WITHOUT a ``v`` field are grandfathered pre-v2 history
(``--strict`` rejects them too, for freshly-started ledgers).

    python tools/check_ledger.py [path ...] [--strict]

Exit 0 when every line validates, 1 otherwise (tier-1 runs this over
the repo ledger — tests/test_observability.py).
"""
from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pinot_tpu.utils import ledger as uledger  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def check(path: str, strict: bool = False) -> int:
    res = uledger.validate_file(path)
    for lineno, msg in res["errors"]:
        print(f"{path}:{lineno}: {msg}")
    rc = 1 if res["errors"] else 0
    if strict and res["legacy"]:
        print(f"{path}: {res['legacy']} legacy (pre-v2) line(s) "
              "rejected by --strict")
        rc = 1
    print(json.dumps({"path": path, "lines": res["lines"],
                      "v2": res["v2"], "legacy": res["legacy"],
                      "kinds": res["kinds"],
                      "errors": len(res["errors"]),
                      "ok": rc == 0}))
    return rc


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    strict = "--strict" in args
    paths = [a for a in args if a != "--strict"] \
        or [os.path.join(REPO, "PERF_LEDGER.jsonl")]
    rc = 0
    for p in paths:
        if not os.path.exists(p):
            print(f"{p}: not found")
            rc = 1
            continue
        rc = max(rc, check(p, strict))
    return rc


if __name__ == "__main__":
    sys.exit(main())
