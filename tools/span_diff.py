"""Span-diff regression gate: per-phase timings from ``query_trace``
ledger records, diffed against a checked-in per-query-shape baseline.

The round-7/10/12 observability stack lands span trees in the ledger
(EXPLAIN ANALYZE, OPTION(ledgerTrace=true), and traceRatio production
sampling); until now a perf regression sat in those records until a
human ran a bench round. This tool closes that loop, jaxlint-ratchet
style:

- ``capture``  runs a small deterministic query corpus (in-process
  broker, seeded 2-segment table, traceRatio=1.0) and appends one
  validated ``query_trace`` record per query iteration to a ledger;
- ``update``   aggregates records into ``tools/span_baseline.json``:
  per query shape (normalized-SQL hash), the median wall ms and per
  root-phase median ms;
- ``check``    re-aggregates a candidate ledger and FAILS (exit 1) when
  a phase's speed-calibrated ms exceeds ``--bar`` x its baseline ms.

Speed calibration: raw ms would flag a uniformly loaded/slower machine
as a regression, so check first computes one per-run calibration factor
— the median of cand_wall/base_wall over the common shapes, clamped to
[0.2, 5] — and divides every candidate phase by it. A global speed
shift (machine load, different host) moves every wall equally and
cancels; a single phase regressing 2x in one shape barely moves the
cross-shape median, so it trips the bar. (A regression hitting the
dominant phase of EVERY shape at once would be absorbed into the
calibration — that class is what bench.py's vs_baseline wall gate is
for.) Candidate phases below ``--min-ms`` are skipped and sub-ms
baselines are floored at ``--min-ms`` (sub-ms-vs-sub-ms jitter cannot
trip the bar, but a tiny phase regressing to something large still
does), and medians over the capture iterations absorb per-run jitter. The baseline is a ratchet like jaxlint_baseline.json:
edit the corpus or materially change an engine phase's cost profile and
re-capture with ``update`` — in the same environment tier-1 runs in.

    python tools/span_diff.py capture --out /tmp/trace.jsonl [--iters 5]
    python tools/span_diff.py update  /tmp/trace.jsonl
    python tools/span_diff.py check   /tmp/trace.jsonl [--bar 1.7]
    python tools/span_diff.py check --fleet fleet_ledger.jsonl

Environment pinning (round 14): ``update`` stamps the capture
environment (JAX_PLATFORMS, jax_enable_x64, backend) into the baseline
header, and ``check`` FAILS LOUDLY (exit 3) when the current
environment differs — baselines captured outside the tier-1 env
(JAX_PLATFORMS=cpu, x64 on) silently miscalibrated every phase before.
bench_common.span_regression_gate surfaces exit 3 as an explicit
"environment mismatch" skip rather than a phase regression.

Fleet mode (round 14): ``check --fleet`` groups a fleet ledger's
``query_trace`` records by their ``node`` provenance stamp
(cluster/rollup.py) and runs the diff PER NODE, each with its own speed
calibration — a heterogeneous fleet (one node 3x slower across the
board) must not false-trip the ratchet, while a single node's single
phase regressing still does.

Exit 0 when no phase regresses; one summary JSON line last,
check_ledger-style. tier-1 runs capture+check through
tests/test_perf_forensics.py; bench_common.finish() runs check over the
repo ledger so a bench capture fails loudly on a span regression.
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
from typing import Any, Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_BASELINE = os.path.join(REPO, "tools", "span_baseline.json")
DEFAULT_BAR = 1.7          # < 2.0 so a 2x single-phase slowdown fails
DEFAULT_MIN_MS = 1.0       # sub-ms phases are timing noise, not signal
# the explicit self-time filler (query/explain.finalize_analyze) and the
# sampled-root gap are residuals, not phases a kernel change regresses
EXCLUDE_PHASES = {"broker_overhead"}
EXIT_ENV_MISMATCH = 3      # distinct from a phase regression (exit 1)


def capture_env(include_backend: bool = True) -> Dict[str, Any]:
    """The calibration-relevant capture environment, recorded into the
    baseline header by ``update`` and enforced by ``check``. Imports
    pinot_tpu first so the flags reflect the ENGINE's configuration
    (it enables x64 at import), not a bare interpreter's defaults.
    ``include_backend=False`` skips backend init — jax.default_backend()
    against a wedged device tunnel hangs indefinitely, so the mismatch
    check only initializes a backend once the cheap fields agree."""
    env: Dict[str, Any] = {
        "jax_platforms": os.environ.get("JAX_PLATFORMS", ""),
        "x64": None, "backend": "unknown"}
    try:
        import pinot_tpu  # noqa: F401 — configures jax as the engine runs

        import jax

        env["x64"] = bool(jax.config.jax_enable_x64)
        if include_backend:
            env["backend"] = jax.default_backend()
    except Exception:
        pass
    return env


def env_mismatch(baseline_env: Optional[Dict[str, Any]]
                 ) -> Optional[Dict[str, Any]]:
    """None when the current environment matches the baseline header
    (or the header predates env pinning — legacy baselines stay
    checkable); otherwise {field: [baseline, current]}. Checked
    cheapest-first: JAX_PLATFORMS / x64 need no backend init, so a
    baseline pinned to cpu fails fast on a device machine instead of
    hanging in device-tunnel init just to report the mismatch."""
    if not baseline_env:
        return None
    cur = capture_env(include_backend=False)
    diffs = {k: [baseline_env.get(k), cur.get(k)]
             for k in ("jax_platforms", "x64")
             if baseline_env.get(k) != cur.get(k)}
    # an UNSET JAX_PLATFORMS is not a platform statement — plenty of
    # valid cpu environments never export it (sitecustomize may force
    # the platform config regardless). Only a conflict between two
    # explicit values fails fast; otherwise the backend comparison
    # below is the authority.
    jp = diffs.get("jax_platforms")
    if jp is not None and not (jp[0] and jp[1]):
        del diffs["jax_platforms"]
    if diffs:
        return diffs
    cur = capture_env()
    if baseline_env.get("backend") != cur.get("backend"):
        return {"backend": [baseline_env.get("backend"),
                            cur.get("backend")]}
    return None


# ---------------------------------------------------------------------------
# aggregation
# ---------------------------------------------------------------------------

# one key per query *shape* across capture runs (qids are per-instance
# uuids, so they cannot key the baseline). Hoisted into the shared
# pinot_tpu/utils/shapehash.py (ISSUE 15) so compile_event records join
# query_trace records on the SAME hash — identity pinned by test.
from pinot_tpu.utils.shapehash import shape_key  # noqa: E402


def load_trace_records(paths: List[str]) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    for path in paths:
        if not os.path.exists(path):
            continue
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and \
                        rec.get("kind") == "query_trace" and \
                        rec.get("root") and rec.get("sql"):
                    out.append(rec)
    return out


def phase_times(root: Dict[str, Any]) -> Tuple[float, Dict[str, float]]:
    """-> (wall_ms, {phase: ms}) over the root's DIRECT children,
    summed by name (the utils/phases.py vocabulary level — coarse and
    rename-stable; kernel-internal spans stay out of the gate)."""
    wall = float(root.get("ms", 0.0))
    phases: Dict[str, float] = {}
    for c in root.get("children") or []:
        name = c.get("name", "?")
        if name in EXCLUDE_PHASES:
            continue
        phases[name] = phases.get(name, 0.0) + float(c.get("ms", 0.0))
    return wall, phases


DEFAULT_LAST = 5           # = capture --iters: one capture run's worth


def aggregate(records: List[Dict[str, Any]],
              last: Optional[int] = DEFAULT_LAST) -> Dict[str, Any]:
    """records -> {shape: {sql, n, wall_ms, phases: {name: {ms}}}}
    with per-shape medians over the NEWEST ``last`` records of that
    shape (ledgers are append-only, so file order is chronological —
    without the cutoff a fresh regression's handful of slow records
    would be out-voted by the shape's accumulated history and the
    median would stay green)."""
    by_shape: Dict[str, List[Dict[str, Any]]] = {}
    sqls: Dict[str, str] = {}
    for rec in records:
        k = shape_key(rec["sql"])
        by_shape.setdefault(k, []).append(rec)
        sqls.setdefault(k, rec["sql"][:160])
    if last is not None and last > 0:
        by_shape = {k: recs[-last:] for k, recs in by_shape.items()}
    out: Dict[str, Any] = {}
    for k, recs in sorted(by_shape.items()):
        walls: List[float] = []
        per_phase: Dict[str, List[float]] = {}
        for rec in recs:
            wall, phases = phase_times(rec["root"])
            if wall <= 0:
                continue
            walls.append(wall)
            for name, ms in phases.items():
                per_phase.setdefault(name, []).append(ms)
        if not walls:
            continue
        out[k] = {
            "sql": sqls[k],
            "n": len(walls),
            "wall_ms": round(statistics.median(walls), 3),
            "phases": {
                name: {"ms": round(statistics.median(vals), 3)}
                for name, vals in sorted(per_phase.items())},
        }
    return out


# ---------------------------------------------------------------------------
# diff
# ---------------------------------------------------------------------------

def speed_calibration(baseline: Dict[str, Any],
                      candidate: Dict[str, Any]) -> float:
    """Per-run machine-speed factor: median cand_wall/base_wall over
    the common shapes, clamped — a uniformly slower/faster environment
    scales every wall and cancels out of the per-phase comparison,
    while a one-shape one-phase regression barely moves the median."""
    ratios = [candidate[k]["wall_ms"] / baseline[k]["wall_ms"]
              for k in set(baseline) & set(candidate)
              if baseline[k]["wall_ms"] > 0]
    if not ratios:
        return 1.0
    return min(max(statistics.median(ratios), 0.2), 5.0)


def diff_shapes(baseline: Dict[str, Any], candidate: Dict[str, Any],
                bar: float, min_ms: float) -> Dict[str, Any]:
    cal = speed_calibration(baseline, candidate)
    regressions: List[Dict[str, Any]] = []
    checked = 0
    for k, cand in candidate.items():
        base = baseline.get(k)
        if base is None:
            continue
        for name, c in cand["phases"].items():
            b = base["phases"].get(name)
            if b is None:
                continue
            adj = c["ms"] / cal
            if adj < min_ms:
                continue  # noise floor: the candidate itself is sub-ms
            # a sub-ms BASELINE must not exempt the phase forever (a
            # 0.4ms planning phase regressing to 8ms is real): floor the
            # baseline at min_ms instead, so large regressions of tiny
            # phases trip while sub-ms-vs-sub-ms jitter cannot
            eff_base = max(b["ms"], min_ms)
            checked += 1
            if adj > bar * eff_base:
                regressions.append({
                    "shape": k, "sql": cand.get("sql", "")[:80],
                    "phase": name,
                    "base_ms": b["ms"], "cand_ms": c["ms"],
                    "calibrated_ms": round(adj, 3),
                    "ratio": round(adj / eff_base, 3),
                })
    return {
        "calibration": round(cal, 4),
        "checked_phases": checked,
        "regressions": regressions,
        "new_shapes": sorted(set(candidate) - set(baseline)),
        "missing_shapes": sorted(set(baseline) - set(candidate)),
    }


# ---------------------------------------------------------------------------
# capture: deterministic corpus -> query_trace ledger
# ---------------------------------------------------------------------------

# the capture corpus: small, deterministic, and shaped to hit the
# distinct engine paths (compact group-by, dense group-by, scalar agg,
# device selection). The SQL text IS the shape key — edit a query and
# the baseline must be re-captured (`update`), exactly like adding a
# jaxlint suppression.
CORPUS_SQL = [
    ("groupby_highcard",
     "SELECT hk, SUM(v), COUNT(*) FROM span_corpus WHERE f <= 60 "
     "GROUP BY hk ORDER BY hk LIMIT 500"),
    ("groupby_topn",
     "SELECT hk, SUM(v) FROM span_corpus GROUP BY hk "
     "ORDER BY SUM(v) DESC LIMIT 20"),
    ("groupby_multi_agg",
     "SELECT lk, SUM(v), MIN(v), MAX(v) FROM span_corpus "
     "GROUP BY lk ORDER BY lk LIMIT 50"),
    ("scalar_agg",
     "SELECT COUNT(*), SUM(v), AVG(v) FROM span_corpus WHERE f > 20"),
    ("selection",
     "SELECT lk, f, v FROM span_corpus ORDER BY v DESC LIMIT 25"),
]


def build_corpus_broker(tmpdir: str, rows: int = 8192,
                        trace_path: Optional[str] = None):
    """Seeded 2-segment table behind an in-process broker with
    traceRatio=1.0 — shared by `capture` and the tier-1 test so the
    checked-in baseline and the gate measure the same corpus."""
    import numpy as np

    from pinot_tpu.broker import Broker
    from pinot_tpu.segment import SegmentBuilder
    from pinot_tpu.server import TableDataManager
    from pinot_tpu.spi import (DataType, FieldSpec, FieldType, Schema,
                               TableConfig)

    rng = np.random.default_rng(20260804)
    schema = Schema("span_corpus", [
        FieldSpec("hk", DataType.INT, FieldType.DIMENSION),
        FieldSpec("lk", DataType.STRING, FieldType.DIMENSION),
        FieldSpec("f", DataType.INT, FieldType.DIMENSION),
        FieldSpec("v", DataType.INT, FieldType.METRIC),
    ])
    builder = SegmentBuilder(schema, TableConfig("span_corpus"))
    dm = TableDataManager("span_corpus")
    half = rows // 2
    for i in range(2):
        cols = {
            "hk": rng.integers(0, 400, half).astype(np.int32),
            "lk": rng.choice(["a", "b", "c", "d", "e"], half),
            "f": rng.integers(0, 100, half).astype(np.int32),
            "v": rng.integers(0, 1000, half).astype(np.int32),
        }
        dm.add_segment_dir(builder.build(
            cols, os.path.join(tmpdir, "span_corpus"), f"sc_{i}"))
    broker = Broker(trace_ratio=1.0, trace_ledger_path=trace_path)
    broker.register_table(dm)
    return broker


def capture(out_path: str, iters: int = 5, rows: int = 8192,
            tmpdir: Optional[str] = None) -> int:
    """Run the corpus ``iters`` times (after one untraced warmup pass
    that pays the XLA compiles) appending one query_trace record per
    query x iteration to ``out_path``. Returns the record count."""
    import shutil
    import tempfile

    own_tmp = tmpdir is None
    tmpdir = tmpdir or tempfile.mkdtemp(prefix="ptpu_span_corpus_")
    try:
        broker = build_corpus_broker(tmpdir, rows, trace_path=out_path)
        n = 0
        for _qid, sql in CORPUS_SQL:   # warmup: compile untraced
            broker.query(sql + " OPTION(traceRatio=0)")
        for _ in range(iters):
            for _qid, sql in CORPUS_SQL:
                broker.query(sql)
                n += 1
        return n
    finally:
        if own_tmp:
            shutil.rmtree(tmpdir, ignore_errors=True)


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def load_baseline(path: str) -> Dict[str, Any]:
    with open(path) as fh:
        data = json.load(fh)
    return data.get("shapes", {})


def load_baseline_env(path: str) -> Optional[Dict[str, Any]]:
    with open(path) as fh:
        data = json.load(fh)
    return data.get("env")


def write_baseline(path: str, shapes: Dict[str, Any],
                   env: Optional[Dict[str, Any]] = None) -> None:
    with open(path, "w") as fh:
        json.dump({"v": 1, "bar": DEFAULT_BAR, "min_ms": DEFAULT_MIN_MS,
                   "env": env if env is not None else capture_env(),
                   "shapes": shapes}, fh, indent=1, sort_keys=True)
        fh.write("\n")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("mode", choices=["check", "update", "capture"])
    ap.add_argument("ledgers", nargs="*",
                    help="trace ledger path(s); default: the repo "
                         "PERF_LEDGER.jsonl")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument("--bar", type=float, default=DEFAULT_BAR,
                    help="fail when a phase's self-vs-rest ratio "
                         "exceeds bar x baseline (default %(default)s)")
    ap.add_argument("--min-ms", type=float, default=DEFAULT_MIN_MS)
    ap.add_argument("--last", type=int, default=DEFAULT_LAST,
                    help="aggregate only the newest N records per shape"
                         " (0 = all; default %(default)s)")
    ap.add_argument("--out", default=None,
                    help="capture mode: the trace ledger to append to")
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--rows", type=int, default=8192)
    ap.add_argument("--fleet", action="store_true",
                    help="check mode: group records by their `node` "
                         "provenance stamp (fleet ledger) and diff "
                         "each node with its own speed calibration")
    # intermixed: `check --fleet <ledger>` must parse (plain parse_args
    # cannot interleave an nargs="*" positional with flags)
    args = ap.parse_intermixed_args(argv)

    if args.mode == "capture":
        if not args.out:
            print("capture requires --out", file=sys.stderr)
            return 2
        n = capture(args.out, iters=args.iters, rows=args.rows)
        print(json.dumps({"mode": "capture", "out": args.out,
                          "records": n, "ok": True}))
        return 0

    ledgers = args.ledgers or [os.path.join(REPO, "PERF_LEDGER.jsonl")]
    records = load_trace_records(ledgers)

    if args.mode == "update":
        shapes = aggregate(records, last=args.last or None)
        env = capture_env()
        rec_backends = {r.get("backend") for r in records} - {None}
        if rec_backends and rec_backends != {env["backend"]}:
            # the header must describe the RECORDS' environment; mixed
            # or foreign-backend records would stamp a lie into the
            # ratchet and re-introduce exactly the silent drift noise
            # the pin exists to stop
            print(f"refusing to update: records captured on backend(s) "
                  f"{sorted(rec_backends)} but the current environment "
                  f"is {env['backend']!r} — re-run capture+update in "
                  f"one environment", file=sys.stderr)
            return 2
        write_baseline(args.baseline, shapes, env)
        print(json.dumps({"mode": "update", "baseline": args.baseline,
                          "records": len(records), "env": env,
                          "shapes": len(shapes), "ok": True}))
        return 0

    if not os.path.exists(args.baseline):
        print(json.dumps({"mode": "check", "ok": True,
                          "skipped": f"no baseline at {args.baseline}"}))
        return 0
    baseline = load_baseline(args.baseline)
    mismatch = env_mismatch(load_baseline_env(args.baseline))
    if mismatch:
        # fail LOUDLY instead of silently miscalibrating: a cpu-captured
        # baseline checked on a tpu backend (or x64 flipped) makes every
        # per-phase ratio meaningless. Distinct exit code so callers
        # (bench_common.span_regression_gate) can surface the skip
        # without reading it as a phase regression.
        print("ENVIRONMENT MISMATCH vs baseline "
              f"{os.path.basename(args.baseline)}: "
              + "; ".join(f"{k}: baseline={b!r} current={c!r}"
                          for k, (b, c) in sorted(mismatch.items()))
              + " — re-capture the baseline in this environment "
                "(capture + update), or run check in the baseline's",
              file=sys.stderr)
        print(json.dumps({"mode": "check", "ok": False,
                          "env_mismatch": mismatch}))
        return EXIT_ENV_MISMATCH

    if args.fleet:
        return _check_fleet(records, baseline, args)

    shapes = aggregate(records, last=args.last or None)
    res = diff_shapes(baseline, shapes, args.bar, args.min_ms)
    for r in res["regressions"]:
        print(f"REGRESSION {r['shape']} phase={r['phase']}: "
              f"ms {r['base_ms']} -> {r['cand_ms']} "
              f"(calibrated {r['calibrated_ms']}, "
              f"{r['ratio']}x > bar {args.bar})  [{r['sql']}]")
    ok = not res["regressions"]
    print(json.dumps({"mode": "check", "bar": args.bar,
                      "records": len(records),
                      "shapes_checked": len(
                          set(shapes) & set(baseline)),
                      **res, "ok": ok}))
    return 0 if ok else 1


def _check_fleet(records: List[Dict[str, Any]],
                 baseline: Dict[str, Any], args) -> int:
    """check --fleet: per-node aggregation + per-node speed calibration
    (cluster/rollup.py stamps `node` onto every pulled record), so a
    heterogeneous fleet never false-trips the ratchet while one node's
    one-phase regression still does."""
    by_node: Dict[str, List[Dict[str, Any]]] = {}
    for rec in records:
        by_node.setdefault(str(rec.get("node") or "<local>"),
                           []).append(rec)
    nodes: Dict[str, Any] = {}
    regressions: List[Dict[str, Any]] = []
    for node, recs in sorted(by_node.items()):
        shapes = aggregate(recs, last=args.last or None)
        res = diff_shapes(baseline, shapes, args.bar, args.min_ms)
        for r in res["regressions"]:
            r = dict(r, node=node)
            regressions.append(r)
            print(f"REGRESSION node={node} {r['shape']} "
                  f"phase={r['phase']}: ms {r['base_ms']} -> "
                  f"{r['cand_ms']} (calibrated {r['calibrated_ms']}, "
                  f"{r['ratio']}x > bar {args.bar})  [{r['sql']}]")
        nodes[node] = {"records": len(recs),
                       "calibration": res["calibration"],
                       "checked_phases": res["checked_phases"],
                       "regressions": len(res["regressions"])}
    ok = not regressions
    print(json.dumps({"mode": "check", "fleet": True, "bar": args.bar,
                      "records": len(records), "nodes": nodes,
                      "regressions": regressions, "ok": ok}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
