"""Chaos smoke: seeded fault plans over a live 2-server cluster, plus a
seeded ingest chaos mode (``--ingest``).

The fault-tolerance acceptance gate (tier-1 runs this through
tests/test_faults.py, alongside check_ledger/check_static): build a
2-server in-process cluster hosting an SSB-lite ``lineorder`` table
(4 segments, replication 2) plus a replication-1 twin, capture
fault-free digests for a small SSB query set, then re-run under seeded
``PINOT_FAULTS``-grammar plans (utils/faults.py) and assert:

1. ``rpc.drop`` of server_0's first /query/bin dispatch: the broker
   fails over and every digest is byte-identical to the fault-free run.
2. ``wire.corrupt`` of server_0's first response frame: decode fails
   loudly, failover, digests byte-identical.
3. Sustained ``rpc.drop`` of server_0 against the replication-1 twin:
   ``allowPartialResults=true`` answers with ``partialResult=true``,
   populated ``exceptions[]`` and ``numServersResponded <
   numServersQueried``; the default mode fails whole-query.
4. Every cluster query appended a validated ``query_stats`` record to
   the broker's stats ledger (per-query wall/partial/exception-code/
   hedge/failover trend lines — ROADMAP round-9 item d), including at
   least one ``partial=true`` record from the replication-1 plan.

``--ingest`` runs the realtime-plane gate instead
(pinot_tpu/tools/ingest_fuzz.py harness, tier-1 via
tests/test_ingest_chaos.py): for each seed, drive seeded row sequences
through an append table (standalone seal) AND an upsert table (full
completion protocol + deep store) with every ingest fault point armed
— stream.error / stream.rebalance / commit.crash / commit.http_error /
handoff.stall / upsert.compact_crash — restarting from the checkpoint
on each injected crash, and assert (a) the final queryable state is
digest-exact vs the fault-free oracle (exactly-once across
crash/restart, upsert latest-wins preserved) and (b) every run
appended a validated ``ingest_stats`` freshness-ledger record.

``--tier`` runs the HBM-tier chaos gate (ISSUE 13, tier-1 via
tests/test_tier.py): an in-process broker over two 4-segment SSB-lite
tables captures fault-free digests, then (a) a seeded ``tier.evict``
plan force-demotes a segment MID-QUERY (between planning and the
group dispatch — its device columns and stacked copies drop) — every
query must rebuild/re-promote through the normal device_col path and
answer byte-exact, with two same-seed runs firing identical (point,
site, hit) streams (the round-16 per-(qid, site-key) discipline); and
(b) the mix re-runs under a constrained HBM budget (half the live
two-table working set), alternating tables so coldest-first demotion
has victims outside the pinned working set: demotions must fire,
digests stay byte-exact, and every devmem pool must reconcile
tracked-vs-actual to the byte across the churn.

``--rate`` runs the round-16 sustained-rate gate
(pinot_tpu/engine/loadgen.py, tier-1 via tests/test_faults.py): 2
tables (append standalone + upsert protocol) x 2 partitions of
sustained multi-partition ingest WITH a concurrent query mix and ALL
ingest fault points armed, micro-batching at its process default (ON
since round 16), asserting (a) byte-exact final queryable state vs the
ingest_fuzz oracle, (b) >=1 validated ``ingest_bench`` ledger record
plus per-table ``ingest_stats`` rows, and (c) the freshness gate green
— a fresh tools/freshness_gate.py capture checked against the
checked-in tools/freshness_baseline.json.

Prints one summary JSON line last, check_ledger-style; exit 0 when all
assertions hold.

    python tools/chaos_smoke.py [--rows N] [--seed N]
    python tools/chaos_smoke.py --ingest [--rows N] [--seeds 40,50,57]
    python tools/chaos_smoke.py --rate [--rows N] [--seed N]
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

SMOKE_QUERY_IDS = ("q1.1", "q2.1", "q3.2", "q4.1")
OPTION = " OPTION(timeoutMs=300000)"


def smoke_queries(qids=SMOKE_QUERY_IDS):
    """(qid, sql) for the smoke subset of the SSB suite."""
    import bench
    by_id = {q[0]: q for q in bench.QUERIES}
    out = []
    for qid in qids:
        _, preds, vexpr, gcols = by_id[qid]
        out.append((qid, bench.spec_to_sql(preds, vexpr, gcols)))
    return out


def build_ssb_cluster(tmp: str, rows: int = 4096, n_segments: int = 4,
                      poll: float = 0.1):
    """Controller + 2 servers + broker over an SSB-lite ``lineorder``
    (replication 2) and a ``lineorder_r1`` twin (replication 1) built
    from the same segment directories. Returns (ctrl, servers, broker,
    stop)."""
    import bench
    from pinot_tpu.cluster import BrokerNode, Controller, ServerNode
    from pinot_tpu.segment import SegmentBuilder
    from pinot_tpu.segment.builder import Categorical
    from pinot_tpu.spi import Schema, TableConfig

    cols = bench.gen_columns(rows)
    fields = bench._ssb_fields(cols)

    ctrl = Controller(os.path.join(tmp, "ctrl"), heartbeat_timeout=5.0,
                      reconcile_interval=0.2)
    servers = [ServerNode(f"server_{i}", ctrl.url, poll_interval=poll)
               for i in range(2)]
    # per-query query_stats ledger: the soak's trend-line output (and
    # the assertion target — every cluster query must append a
    # check_ledger-valid record). trace_ratio=1.0: every soak query is
    # production-sampled, so the chaos plans also exercise the sampled
    # span plane (failover/hedge spans under injected faults) and every
    # run must land validated query_trace records beside the stats.
    broker = BrokerNode(ctrl.url, routing_refresh=poll,
                        query_stats_path=os.path.join(
                            tmp, "query_stats.jsonl"),
                        trace_ratio=1.0)

    for table, replication in (("lineorder", 2), ("lineorder_r1", 1)):
        schema = Schema(table, fields)
        builder = SegmentBuilder(schema, TableConfig(table))
        ctrl.add_table(table, schema.to_dict(), replication=replication)
        step = rows // n_segments
        for i in range(n_segments):
            lo, hi = i * step, rows if i == n_segments - 1 \
                else (i + 1) * step
            part = {n: (Categorical(v.codes[lo:hi], v.values)
                        if isinstance(v, Categorical) else v[lo:hi])
                    for n, v in cols.items()}
            d = builder.build(part, os.path.join(tmp, table), f"seg_{i}")
            ctrl.add_segment(table, f"seg_{i}", d)

    v = ctrl.routing_snapshot()["version"]
    for s in servers:
        assert s.wait_for_version(v, timeout=30.0), "server never synced"
    assert broker.wait_for_version(v, timeout=30.0), "broker never synced"

    def stop():
        broker.stop()
        for s in servers:
            try:
                s.stop()
            except Exception:
                pass
        ctrl.stop()

    return ctrl, servers, broker, stop


def digest(resp: dict):
    import bench
    return bench._digest([tuple(r) for r in resp["resultTable"]["rows"]])


def _iter_kind(path: str, kind: str):
    """v2 records of one kind from a ledger file."""
    with open(path) as fh:
        for line in fh:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and rec.get("kind") == kind:
                yield rec


def _iter_stats(path: str, partial=None):
    """query_stats records from a stats ledger, optionally filtered by
    the partialResult flag."""
    for rec in _iter_kind(path, "query_stats"):
        if partial is not None and rec.get("partial") != partial:
            continue
        yield rec


SPAN_BASELINE = os.path.join(REPO, "tools", "span_baseline.json")


def _file_hash(path: str):
    import hashlib
    with open(path, "rb") as fh:
        return hashlib.sha256(fh.read()).hexdigest()


# seeds/rows whose decision streams fire EVERY ingest fault point
# (verified by test_ingest_chaos's all-points gate; re-scan if the plan
# changes). The all-points check is calibrated for exactly these values
# — other --seeds/--rows still gate digest-exact recovery + the ledger,
# but fire whatever subset of points their decision streams produce
INGEST_SEEDS = (40, 50, 57)
INGEST_ROWS = 300


def main_ingest(args) -> int:
    """--ingest: seeded chaos over the realtime plane, digest-exact
    recovery + a validated ingest_stats freshness-ledger record per
    run."""
    from pinot_tpu.tools import ingest_fuzz as IF
    from pinot_tpu.utils import faults
    from pinot_tpu.utils import ledger as uledger

    seeds = tuple(int(s) for s in args.seeds.split(","))
    tmp = tempfile.mkdtemp(prefix="ptpu_ingest_chaos_")
    ledger_path = os.path.join(tmp, "ingest_stats.jsonl")
    failures = []
    summary = {"mode": "ingest", "rows": args.rows, "seeds": list(seeds),
               "runs": 0, "faults_fired": 0, "restarts": 0,
               "points": []}

    def check(name, ok, detail=""):
        if not ok:
            failures.append(f"{name}: {detail}")
            print(f"FAIL {name}: {detail}")

    faults.clear()
    points = set()
    try:
        for seed in seeds:
            for upsert, protocol in ((False, False), (True, True)):
                tag = (f"seed{seed}."
                       + ("upsert" if upsert else "append")
                       + (".protocol" if protocol else ""))
                run_dir = os.path.join(tmp, tag)
                try:
                    m, plan, restarts = IF.run_one(
                        run_dir, seed, args.rows, upsert=upsert,
                        protocol=protocol)
                except Exception as e:  # noqa: BLE001 — into the summary
                    check(tag, False, f"EXC {type(e).__name__}: {e}")
                    continue
                summary["runs"] += 1
                summary["faults_fired"] += len(plan.fired)
                summary["restarts"] += restarts
                points |= {f["point"] for f in plan.fired}
                got = IF.digest(IF.queryable_rows(m))
                exp = IF.digest(IF.oracle_rows(
                    IF.gen_rows(seed, args.rows), upsert))
                check(f"{tag}.digest", got == exp,
                      f"{len(got)} rows vs oracle {len(exp)} after "
                      f"{restarts} restarts")
                m.write_ingest_stats(ledger_path, seed=seed,
                                     restarts=restarts,
                                     faults_fired=len(plan.fired))
        summary["points"] = sorted(points)
        if seeds == INGEST_SEEDS and args.rows == INGEST_ROWS:
            check("points.all_fired",
                  points >= {"stream.error", "stream.rebalance",
                             "commit.crash", "commit.http_error",
                             "handoff.stall", "upsert.compact_crash"},
                  f"only {sorted(points)} fired across seeds {seeds}")
        else:
            summary["points_gate"] = \
                "skipped: all-points check is calibrated for the " \
                "default --seeds/--rows only"
        # the freshness ledger: one VALIDATED ingest_stats record per run
        res = uledger.validate_file(ledger_path)
        n_stats = res["kinds"].get("ingest_stats", 0)
        summary["ingest_stats"] = n_stats
        check("ingest_stats.valid", not res["errors"],
              f"invalid records: {res['errors'][:3]}")
        check("ingest_stats.count", n_stats >= summary["runs"]
              and n_stats >= 1,
              f"{n_stats} records for {summary['runs']} runs")
    finally:
        faults.clear()
        shutil.rmtree(tmp, ignore_errors=True)

    summary["ok"] = not failures
    summary["failures"] = failures
    print(json.dumps(summary))
    return 0 if not failures else 1


RATE_ROWS = 600
OVERLOAD_ROWS = 2048
TIER_ROWS = 2048


def build_ssb_table(tmp: str, rows: int, n_segments: int = 4,
                    table: str = "lineorder", seg_prefix: str = "seg_"):
    """In-process SSB-lite table: ``n_segments`` segments split from
    one seeded bench.gen_columns draw. Returns (TableDataManager,
    segment dirs)."""
    import bench
    from pinot_tpu.segment import SegmentBuilder
    from pinot_tpu.segment.builder import Categorical
    from pinot_tpu.server import TableDataManager
    from pinot_tpu.spi import Schema, TableConfig

    cols = bench.gen_columns(rows)
    schema = Schema(table, bench._ssb_fields(cols))
    builder = SegmentBuilder(schema, TableConfig(table))
    dm = TableDataManager(table)
    step = rows // n_segments
    dirs = []
    for i in range(n_segments):
        lo, hi = i * step, rows if i == n_segments - 1 else (i + 1) * step
        part = {n: (Categorical(v.codes[lo:hi], v.values)
                    if isinstance(v, Categorical) else v[lo:hi])
                for n, v in cols.items()}
        d = builder.build(part, os.path.join(tmp, table),
                          f"{seg_prefix}{i}")
        dirs.append(d)
        dm.add_segment_dir(d)
    return dm, dirs


def main_tier(args) -> int:
    """--tier: the HBM-tier chaos gate (module docstring): mid-query
    ``tier.evict`` demotion recovers byte-exact with same-seed
    determinism, and a constrained budget demotes coldest-first with
    every devmem pool reconciling to the byte."""
    import bench
    from pinot_tpu.broker import Broker
    from pinot_tpu.engine.tier import global_tier, reconcile_devmem
    from pinot_tpu.utils import faults
    from pinot_tpu.utils.devmem import global_device_memory
    from pinot_tpu.utils.metrics import global_metrics

    tmp = tempfile.mkdtemp(prefix="ptpu_tier_chaos_")
    failures = []
    summary = {"mode": "tier", "rows": args.rows, "seed": args.seed,
               "queries": 0, "faults_fired": 0, "promotions": 0,
               "demotions": 0}

    def check(name, ok, detail=""):
        if not ok:
            failures.append(f"{name}: {detail}")
            print(f"FAIL {name}: {detail}")

    faults.clear()
    global_tier.configure(budget_bytes=None)
    # start from devmem-synced caches: when this gate runs inside a
    # warm pytest process, earlier tests' cube/stack entries survive
    # the per-test accounting reset and would fail the byte-exact
    # reconcile below through no fault of the tier's
    from pinot_tpu.engine.batch import clear_stack_cache
    from pinot_tpu.ops.plan_cache import global_cube_cache
    clear_stack_cache()
    global_cube_cache.clear()
    try:
        # TWO tables over the same seeded data: the twin gives the
        # budget enforcement demotion victims OUTSIDE the querying
        # table's pinned working set (and its digests must equal the
        # original's — same rows, different placement history)
        dm, _dirs = build_ssb_table(tmp, args.rows)
        dm2, _dirs2 = build_ssb_table(tmp, args.rows,
                                      table="lineorder2",
                                      seg_prefix="t2seg_")
        broker = Broker()
        broker.register_table(dm)
        broker.register_table(dm2)
        queries = smoke_queries(tuple(args.queries.split(",")))
        summary["queries"] = len(queries)

        def run_all(tag, twin=False):
            # deterministic query ids: the per-(qid, site-key) fault
            # streams must be identical across same-seed runs
            out = {}
            for qid, sql in queries:
                if twin:
                    sql = sql.replace("FROM lineorder ",
                                      "FROM lineorder2 ")
                res = broker.query(
                    sql + f" OPTION(timeoutMs=300000,"
                          f"queryId=tier.{tag}.{qid})")
                out[qid] = bench._digest([tuple(r) for r in res.rows])
            return out

        baseline = run_all("base")
        check("twin.digests", run_all("base2", twin=True) == baseline,
              "twin table digests differ from the original's")

        # (a) mid-query demotion: the group access hook force-demotes
        # seg_1 (device columns AND stacked copies) after planning,
        # before dispatch — the SAME query must rebuild/re-promote
        # through device_col and answer byte-exact. times=1 per
        # (query id, site) stream: once per query, every query.
        plan_text = (f"seed={args.seed}; "
                     "tier.evict: match=seg_1, times=1")

        def run_plan(tag):
            plan = faults.install(plan_text)
            try:
                got = run_all(tag)
            finally:
                faults.clear()
            return plan, got

        d0 = global_tier.demotions
        plan1, got1 = run_plan("evict")
        summary["faults_fired"] += len(plan1.fired)
        check("tier_evict.fired", len(plan1.fired) >= 1,
              "tier.evict never fired")
        check("tier_evict.demoted", global_tier.demotions > d0,
              "no demotion recorded")
        for qid in baseline:
            check(f"tier_evict.{qid}", got1[qid] == baseline[qid],
                  "digest mismatch after mid-query demotion")
        # same-seed determinism: identical (point, site, hit) streams
        plan2, got2 = run_plan("evict")
        summary["faults_fired"] += len(plan2.fired)
        check("tier_evict.deterministic",
              plan1.fired_summary() == plan2.fired_summary(),
              f"{plan1.fired_summary()} != {plan2.fired_summary()}")
        for qid in baseline:
            check(f"tier_evict.rerun.{qid}", got2[qid] == baseline[qid],
                  "digest mismatch on same-seed rerun")

        # (b) constrained budget: half the live two-table working set —
        # alternating tables forces coldest-first demotion of the idle
        # table's segments; digests stay exact, pools reconcile
        total = global_device_memory.snapshot()["total"]["bytes"]
        budget = max(total // 2, 1)
        summary["budget_bytes"] = budget
        global_tier.configure(budget_bytes=budget)
        d1 = global_tier.demotions
        got3 = run_all("budget")
        got4 = run_all("budget2", twin=True)
        got5 = run_all("budget3")
        for qid in baseline:
            check(f"tier_budget.{qid}",
                  got3[qid] == baseline[qid]
                  and got4[qid] == baseline[qid]
                  and got5[qid] == baseline[qid],
                  "digest mismatch under constrained budget")
        check("tier_budget.demoted", global_tier.demotions > d1,
              "constrained budget never demoted")
        # the four pools this gate resets at start; plan_cache_acc is
        # suite-wide compile warmth (donated buffers, TPU only) whose
        # accounting a warm pytest process has already zeroed — the
        # fresh-process bench covers all five
        rec = reconcile_devmem(
            dm.acquire_segments() + dm2.acquire_segments(),
            pools=("segment_cols", "stack_cache", "cube_cache",
                   "cube_stacked"))
        summary["reconcile"] = rec
        for pool, r in rec.items():
            check(f"reconcile.{pool}", r["tracked"] == r["actual"],
                  f"tracked {r['tracked']} != actual {r['actual']}")
        snap = global_tier.snapshot()
        summary["promotions"] = snap["promotions"]
        summary["demotions"] = snap["demotions"]
        # churn bound: demotions are per-query work (at most the idle
        # table's segments per alternation), not a runaway loop
        check("tier_budget.churn_bounded",
              global_tier.demotions - d1 <= 8 * 3 * len(queries) + 8,
              f"{global_tier.demotions - d1} demotions for "
              f"{3 * len(queries)} queries")
        c = global_metrics.snapshot()["counters"]
        check("tier.promotions_counted",
              c.get("tier_promotions", 0) >= snap["promotions"] - 1,
              "tier_promotions counter missing")
    finally:
        faults.clear()
        global_tier.configure(budget_bytes=None)
        shutil.rmtree(tmp, ignore_errors=True)

    summary["ok"] = not failures
    summary["failures"] = failures
    print(json.dumps(summary))
    return 0 if not failures else 1


REBALANCE_ROWS = 2048


def build_rebalance_cluster(tmp: str, rows: int, poll: float = 0.1):
    """A deliberately skewed cluster for the closed-loop rebalance
    gate: ``lineorder`` (3 segments, replication 1) is added while
    server_0 is the ONLY live server so every segment lands there;
    then server_1 joins and the protected ``lineorder_s`` twin (2
    segments) lands on it least-loaded. Returns (ctrl, servers,
    broker, stop)."""
    import bench
    from pinot_tpu.cluster import BrokerNode, Controller, ServerNode
    from pinot_tpu.segment import SegmentBuilder
    from pinot_tpu.segment.builder import Categorical
    from pinot_tpu.spi import Schema, TableConfig

    cols = bench.gen_columns(rows)
    fields = bench._ssb_fields(cols)
    ctrl = Controller(os.path.join(tmp, "ctrl"), heartbeat_timeout=5.0,
                      reconcile_interval=0.2)
    servers = [ServerNode("server_0", ctrl.url, poll_interval=poll)]

    def add_table(table, n_segments):
        schema = Schema(table, fields)
        builder = SegmentBuilder(schema, TableConfig(table))
        ctrl.add_table(table, schema.to_dict(), replication=1)
        step = rows // n_segments
        for i in range(n_segments):
            lo, hi = i * step, rows if i == n_segments - 1 \
                else (i + 1) * step
            part = {n: (Categorical(v.codes[lo:hi], v.values)
                        if isinstance(v, Categorical) else v[lo:hi])
                    for n, v in cols.items()}
            d = builder.build(part, os.path.join(tmp, table), f"seg_{i}")
            ctrl.add_segment(table, f"seg_{i}", d)

    add_table("lineorder", 3)       # all on server_0 (the future donor)
    v = ctrl.routing_snapshot()["version"]
    assert servers[0].wait_for_version(v, timeout=30.0), \
        "server_0 never synced"
    servers.append(ServerNode("server_1", ctrl.url, poll_interval=poll))
    add_table("lineorder_s", 2)     # least-loaded -> server_1
    broker = BrokerNode(ctrl.url, routing_refresh=poll)
    v = ctrl.routing_snapshot()["version"]
    for s in servers:
        assert s.wait_for_version(v, timeout=30.0), "server never synced"
    assert broker.wait_for_version(v, timeout=30.0), "broker never synced"

    def stop():
        broker.stop()
        for s in servers:
            try:
                s.stop()
            except Exception:
                pass
        ctrl.stop()

    return ctrl, servers, broker, stop


def main_rebalance(args) -> int:
    """--rebalance: the closed-loop rebalance chaos gate (ISSUE 19):
    a burn-triggered move under seeded ``rebalance.crash`` +
    ``cutover.stall`` recovers byte-exact from the journal, same-seed
    stall runs fire identical (point, site, hit) streams, an
    incident-open pass plans ZERO moves, and the devmem/tier pools
    reconcile to the byte after the donor drain."""
    import time as _time

    from pinot_tpu.cluster.http_util import http_json
    from pinot_tpu.engine.tier import global_tier, reconcile_devmem
    from pinot_tpu.utils import faults
    from pinot_tpu.utils.slo import global_incidents, global_slo

    tmp = tempfile.mkdtemp(prefix="ptpu_rebalance_chaos_")
    failures = []
    summary = {"mode": "rebalance", "rows": args.rows,
               "seed": args.seed, "queries": 0, "faults_fired": 0}

    def check(name, ok, detail=""):
        if not ok:
            failures.append(f"{name}: {detail}")
            print(f"FAIL {name}: {detail}")

    faults.clear()
    global_slo.clear()
    global_incidents.reset()
    global_tier.configure(budget_bytes=None)
    from pinot_tpu.engine.batch import clear_stack_cache
    from pinot_tpu.ops.plan_cache import global_cube_cache
    clear_stack_cache()
    global_cube_cache.clear()
    ctrl, servers, broker, stop = build_rebalance_cluster(tmp, args.rows)
    rb = ctrl.rebalancer
    rb.budget_moves = 1     # one move per pass: each chaos phase is
    rb.prewarm_timeout = 10.0  # exactly one cutover
    # park the scheduled pass: every pass in this gate is a deliberate,
    # manually-triggered chaos phase
    ctrl.scheduler._next_run[rb.NAME] = _time.monotonic() + 1e9
    try:
        queries = smoke_queries(tuple(args.queries.split(",")))
        summary["queries"] = len(queries)

        def run_all(tag):
            out = {}
            for qid, sql in queries:
                for table in ("lineorder", "lineorder_s"):
                    q = sql.replace("FROM lineorder ", f"FROM {table} ")
                    resp = http_json(
                        "POST", f"{broker.url}/query/sql",
                        {"sql": q + f" OPTION(timeoutMs=300000,"
                                    f"queryId=rb.{tag}.{table}.{qid})"},
                        timeout=120.0)
                    out[(table, qid)] = digest(resp)
            return out

        def holders(table="lineorder"):
            with ctrl._lock:
                return {s: list(h) for s, h in
                        ctrl._state["assignment"][table].items()}

        baseline = run_all("base")
        check("skew.initial",
              all(h == ["server_0"] for h in holders().values()),
              f"burn table not pinned to server_0: {holders()}")

        # arm a latency objective the baseline traffic cannot meet:
        # every query is a bad event, slow-window burn saturates, the
        # burn-rate alert fires and the flight recorder captures an
        # incident (round-22 plane, all through the real feed path)
        global_slo.set_objective("lineorder", "latency", bar_ms=0.01,
                                 objective=0.9)
        run_all("burn")
        deadline = _time.monotonic() + 10.0
        while _time.monotonic() < deadline and \
                global_incidents.snapshot(limit=0)["count"] < 1:
            _time.sleep(0.05)
        check("incident.captured",
              global_incidents.snapshot(limit=0)["count"] >= 1,
              "burn alert never captured an incident")

        # (a) incident-open pass: plans ZERO moves, placement untouched
        ctrl.rollup.run()
        before = holders()
        res = rb.run()
        check("freeze.zero_moves",
              res["frozen"] and res["planned"] == 0,
              f"incident-open pass was not frozen: {res}")
        check("freeze.placement", holders() == before,
              "placement changed under an open incident")

        # (b) burn-triggered move under rebalance.crash: the pass dies
        # in the cutover window AFTER the receiver pre-warmed, BEFORE
        # the flip journal commit; the journal must carry the move
        global_incidents.reset()
        ctrl.rollup.run()
        plan = faults.install(f"seed={args.seed}; rebalance.crash: "
                              f"match=rebalance/lineorder/, times=1")
        crashed = False
        try:
            rb.run()
        except faults.FaultInjected:
            crashed = True
        summary["faults_fired"] += len(plan.fired)
        faults.clear()
        check("crash.raised", crashed, "rebalance.crash never fired")
        journal = rb._load_journal()
        check("crash.journal",
              journal is not None and journal.get("phase") == "prewarm",
              f"no prewarm journal after crash: {journal}")
        moved = (journal or {}).get("move") or {}
        seg = moved.get("segment")
        check("crash.overreplicated",
              sorted(holders().get(seg) or []) ==
              ["server_0", "server_1"],
              f"receiver not pre-warmed: {holders()}")

        # (c) recovery: the next pass (same controller, or the new
        # leader over the shared data dir) resumes the journaled move
        # idempotently — exactly one final assignment, donor drained
        res = rb.run()
        check("recover.resumed", res["resumed"] == 1,
              f"journaled move not resumed: {res}")
        check("recover.journal_cleared", rb._load_journal() is None,
              "journal left behind after recovery")
        check("recover.flip", holders().get(seg) == ["server_1"],
              f"resumed move did not converge: {holders()}")
        v = ctrl.routing_snapshot()["version"]
        check("recover.converged",
              broker.wait_for_version(v, timeout=10.0)
              and all(s.wait_for_version(v, timeout=10.0)
                      for s in servers),
              "cluster never converged on the flipped assignment")
        # no orphaned receiver load: exactly one resident copy of the
        # moved segment on the receiver, zero on the drained donor
        have1 = {s.name for s in
                 servers[1]._tables["lineorder"].acquire_segments()}
        have0 = {s.name for s in
                 servers[0]._tables["lineorder"].acquire_segments()}
        check("recover.receiver_loaded", seg in have1,
              f"receiver lost the segment: {sorted(have1)}")
        check("recover.donor_unloaded", seg not in have0,
              f"donor still holds the segment: {sorted(have0)}")
        got = run_all("after")
        for k in baseline:
            check(f"digest.{k[0]}.{k[1]}", got[k] == baseline[k],
                  "digest drift across the crash-recovered cutover")

        # (d) cutover.stall: the pre-warm hangs past its deadline; the
        # move aborts, the donor keeps serving, placement is unchanged
        # — and the abort path is state-neutral, so two same-seed
        # passes must fire IDENTICAL (point, site, hit) streams
        stall_text = (f"seed={args.seed}; cutover.stall: "
                      f"match=rebalance/lineorder/, delay_ms=30, "
                      f"times=-1")
        before = holders()

        def stall_pass(tag):
            plan = faults.install(stall_text)
            try:
                r = rb.run()
            finally:
                faults.clear()
            return plan, r

        plan_a, res_a = stall_pass("a")
        summary["faults_fired"] += len(plan_a.fired)
        check("stall.aborted",
              res_a["planned"] >= 1
              and res_a["aborted"] == res_a["planned"],
              f"stalled pass did not abort every move: {res_a}")
        check("stall.placement", holders() == before,
              "aborted move changed placement")
        plan_b, res_b = stall_pass("b")
        summary["faults_fired"] += len(plan_b.fired)
        check("stall.deterministic",
              plan_a.fired_summary() == plan_b.fired_summary()
              and len(plan_a.fired) >= 1,
              f"{plan_a.fired_summary()} != {plan_b.fired_summary()}")
        check("stall.placement2", holders() == before,
              "second stalled pass changed placement")

        # (e) pools reconcile to the byte after the drain (the gate's
        # devmem subset — plan_cache_acc is suite-wide compile warmth)
        segs = []
        for s in servers:
            for dm in s._tables.values():
                segs.extend(dm.acquire_segments())
        rec = reconcile_devmem(
            segs, pools=("segment_cols", "stack_cache", "cube_cache",
                         "cube_stacked"))
        summary["reconcile"] = rec
        for pool, r in rec.items():
            check(f"reconcile.{pool}", r["tracked"] == r["actual"],
                  f"tracked {r['tracked']} != actual {r['actual']}")
        got = run_all("final")
        for k in baseline:
            check(f"digest.final.{k[0]}.{k[1]}",
                  got[k] == baseline[k],
                  "digest drift after the chaos sequence")
        snap = rb.snapshot()
        summary["rebalance"] = {k: snap[k] for k in
                                ("passes", "executed", "aborted",
                                 "resumed", "frozen_passes")}
    finally:
        faults.clear()
        global_slo.clear()
        global_incidents.reset()
        stop()
        shutil.rmtree(tmp, ignore_errors=True)

    summary["ok"] = not failures
    summary["failures"] = failures
    print(json.dumps(summary))
    return 0 if not failures else 1


AUTOPSY_ROWS = 1024


def main_autopsy(args) -> int:
    """--autopsy: the incident-autopsy chaos gate (ISSUE 20): a REAL
    SLO burn fires an alert, the flight recorder captures the incident
    and its post hook runs attribution on the capture thread — the
    ring entry must carry the ``rca`` verdict ref and the ledger a
    contract-valid ``rca_verdict``; a fleet-level verdict over the
    rollup's pulled corpus must name an injected compile storm with
    EVERY evidence pointer resolvable back to its ledger line by
    (node, proc, seq); and a clean follow-up window must say
    ``inconclusive`` explicitly rather than confabulate a cause."""
    import time as _time

    import traffic_replay as TR
    from pinot_tpu.cluster.autopsy import (global_autopsy, load_corpus,
                                           plan_autopsy)
    from pinot_tpu.cluster.forensics import read_ledger_since
    from pinot_tpu.engine.tier import global_tier
    from pinot_tpu.utils import faults
    from pinot_tpu.utils import ledger as uledger
    from pinot_tpu.utils.compileplane import (clear_staged_caches,
                                              global_compile_log)
    from pinot_tpu.utils.slo import (event_time, global_incidents,
                                     global_slo)

    tmp = tempfile.mkdtemp(prefix="ptpu_autopsy_chaos_")
    failures = []
    summary = {"mode": "autopsy", "rows": args.rows, "seed": args.seed,
               "queries": 0, "faults_fired": 0}

    def check(name, ok, detail=""):
        if not ok:
            failures.append(f"{name}: {detail}")
            print(f"FAIL {name}: {detail}")

    faults.clear()
    global_slo.clear()
    global_incidents.reset()
    global_incidents.post_hook = None   # the broker re-wires below
    global_autopsy.reset()
    global_autopsy.path = None
    global_tier.configure(budget_bytes=None)
    had_compile_path = bool(global_compile_log.path)
    stop = None
    try:
        ctrl, servers, broker, stop = TR.build_autopsy_cluster(
            tmp, args.rows)
        path = broker.forensics.ledger_path
        mix = TR.build_autopsy_mix(args.seed, 8)
        summary["queries"] = len(mix)
        seen = set()
        for q in mix:           # warmup: compiles land off-window
            key = q["sql"].split("FROM")[0]
            if key not in seen:
                seen.add(key)
                TR._rb_phase(broker.url, [q], f"cwarm{len(seen)}",
                             qps=1e9)

        def t_cut_after(seq0):
            times = [t for t in (
                event_time(r) for r in load_corpus(path)
                if r["_seq"] > seq0 and r.get("kind") == "query_stats")
                if t is not None]
            return (max(times) + 1e-6) if times else 0.0

        # (a) baseline window, then a real burn THROUGH a compile
        # storm: an unmeetable latency objective makes every query a
        # bad event, the burn-rate alert fires on the live feed path,
        # the recorder captures the incident and the post hook lands
        # the verdict — nothing in this gate calls the autopsy plane
        # directly
        TR._rb_phase(broker.url, mix, "cbase", qps=50.0)
        t_cut = t_cut_after(0)
        check("baseline.stats", t_cut > 0.0,
              "no baseline query_stats landed in the ledger")
        global_slo.set_objective(TR.AUTOPSY_TABLE, "latency",
                                 bar_ms=0.01, objective=0.9)
        clear_staged_caches()   # the cause the fleet verdict must name
        TR._rb_phase(broker.url, mix, "cburn", qps=50.0)
        deadline = _time.monotonic() + 10.0
        while _time.monotonic() < deadline and \
                global_incidents.snapshot(limit=0)["count"] < 1:
            _time.sleep(0.05)
        global_slo.clear()      # disarm before the clean window
        check("incident.captured",
              global_incidents.snapshot(limit=0)["count"] >= 1,
              "burn alert never captured an incident")
        check("incident.drained", global_incidents.drain(timeout=10.0),
              "capture queue never drained")

        # (b) the ring answers "what burned AND why" in one lookup,
        # and the landed verdict honors the ledger contract
        entry = (global_incidents.snapshot(limit=1)["incidents"]
                 or [{}])[0]
        check("incident.rca_ref", bool(entry.get("rca")),
              f"no rca ref on {entry.get('incident_id')}")
        ap = global_autopsy.snapshot(limit=1)
        summary["autopsies"] = ap["computed"]
        check("autopsy.computed",
              ap["computed"] >= 1 and ap["errors"] == 0,
              f"computed={ap['computed']} errors={ap['errors']}")
        lres = uledger.validate_file(path)
        summary["ledger_kinds"] = lres["kinds"]
        check("ledger.valid", not lres["errors"],
              f"invalid records: {lres['errors'][:3]}")
        check("ledger.rca_verdict",
              lres["kinds"].get("rca_verdict", 0) >= 1,
              f"kinds={lres['kinds']}")

        # (c) fleet-level attribution: pull the node ledger into the
        # rollup's fleet ledger, plan over THAT corpus, and walk every
        # evidence pointer back to its ledger line
        ctrl.rollup.run()
        fleet_path = ctrl.rollup.ledger_path
        fleet = plan_autopsy(load_corpus(fleet_path),
                             window=(t_cut, None))
        summary["fleet_top"] = fleet["top_cause"]
        check("fleet.top_cause", fleet["top_cause"] == "compile_storm",
              f"top {fleet['top_cause'] or '<inconclusive>'}: " +
              ", ".join(f"{c['cause']}={c['score']}"
                        for c in fleet["causes"][:3]))
        ptrs = [p for c in fleet["causes"] for p in c["evidence"]]
        summary["evidence_pointers"] = len(ptrs)
        check("fleet.evidence", len(ptrs) >= 1, "verdict has no "
              "evidence to resolve")
        for node, proc, seq in ptrs:
            recs, _ = read_ledger_since(fleet_path, seq - 1)
            hit = recs[0] if recs else {}
            if not (str(hit.get("node") or "") == node
                    and str(hit.get("proc") or "") == proc):
                check(f"fleet.pointer.{seq}", False,
                      f"[{node},{proc},{seq}] resolved to "
                      f"{hit.get('kind')}/{hit.get('node')}/"
                      f"{hit.get('proc')}")

        # (d) no anomaly -> an EXPLICIT inconclusive, not a
        # confabulated cause
        seq0 = load_corpus(path)[-1]["_seq"]
        TR._rb_phase(broker.url, mix, "ccb", qps=50.0)
        t_clean = t_cut_after(seq0)
        TR._rb_phase(broker.url, mix, "ccw", qps=50.0)
        clean = plan_autopsy(
            [r for r in load_corpus(path) if r["_seq"] > seq0],
            window=(t_clean, None))
        check("clean.inconclusive",
              clean["inconclusive"] and clean["top_cause"] == "",
              f"clean window confabulated {clean['top_cause']}="
              f"{clean['causes'][0]['score']}")
    except Exception as e:  # noqa: BLE001 — into the summary
        check("autopsy.run", False, f"EXC {type(e).__name__}: {e}")
    finally:
        faults.clear()
        global_tier.configure(budget_bytes=None)
        global_slo.clear()
        global_slo.path = None
        global_incidents.reset()
        global_incidents.path = None
        global_incidents.post_hook = None
        global_autopsy.reset()
        global_autopsy.path = None
        if not had_compile_path:
            global_compile_log.configure(path="")
        if stop is not None:
            stop()
        shutil.rmtree(tmp, ignore_errors=True)

    summary["ok"] = not failures
    summary["failures"] = failures
    print(json.dumps(summary))
    return 0 if not failures else 1


VECTOR_ROWS = 4096
VECTOR_DIM = 16
VECTOR_LISTS = 16
VECTOR_K = 8


def build_vector_cluster(tmp: str, rows: int, seed: int,
                         n_segments: int = 4, poll: float = 0.1):
    """Controller + 2 servers + broker over a ``vectors`` table
    (replication 2) with an IVF vector index on ``emb``. Returns
    (ctrl, servers, broker, stop, query_vectors)."""
    import numpy as np
    from pinot_tpu.cluster import BrokerNode, Controller, ServerNode
    from pinot_tpu.segment import SegmentBuilder
    from pinot_tpu.spi import Schema, TableConfig
    from pinot_tpu.spi.config import IndexingConfig
    from pinot_tpu.spi.schema import DataType, FieldSpec, FieldType

    rng = np.random.default_rng(seed)
    centers = rng.standard_normal((8, VECTOR_DIM)).astype(np.float32)
    a = rng.integers(0, 8, rows)
    vecs = (centers[a] + 0.15 * rng.standard_normal(
        (rows, VECTOR_DIM))).astype(np.float32)
    data = {"id": np.arange(rows, dtype=np.int64), "emb": vecs,
            "views": rng.integers(0, 1000, rows).astype(np.int32)}
    qvecs = vecs[rng.integers(0, rows, 4)] + 0.01 * rng.standard_normal(
        (4, VECTOR_DIM)).astype(np.float32)

    schema = Schema("vectors", [
        FieldSpec("id", DataType.LONG, FieldType.DIMENSION),
        FieldSpec("emb", DataType.FLOAT, FieldType.DIMENSION),
        FieldSpec("views", DataType.INT, FieldType.METRIC)])
    cfg = TableConfig("vectors", indexing=IndexingConfig(
        vector_index_columns={"emb": {
            "metric": "cosine", "nLists": VECTOR_LISTS, "seed": 7}}))
    ctrl = Controller(os.path.join(tmp, "ctrl"), heartbeat_timeout=5.0,
                      reconcile_interval=0.2)
    servers = [ServerNode(f"server_{i}", ctrl.url, poll_interval=poll)
               for i in range(2)]
    broker = BrokerNode(ctrl.url, routing_refresh=poll,
                        query_stats_path=os.path.join(
                            tmp, "query_stats.jsonl"))
    builder = SegmentBuilder(schema, cfg)
    ctrl.add_table("vectors", schema.to_dict(), replication=2)
    step = rows // n_segments
    for i in range(n_segments):
        lo, hi = i * step, rows if i == n_segments - 1 \
            else (i + 1) * step
        d = builder.build({k: v[lo:hi] for k, v in data.items()},
                          os.path.join(tmp, "vectors"), f"seg_{i}")
        ctrl.add_segment("vectors", f"seg_{i}", d)
    v = ctrl.routing_snapshot()["version"]
    for s in servers:
        assert s.wait_for_version(v, timeout=30.0), "server never synced"
    assert broker.wait_for_version(v, timeout=30.0), \
        "broker never synced"

    def stop():
        broker.stop()
        for s in servers:
            try:
                s.stop()
            except Exception:
                pass
        ctrl.stop()

    return ctrl, servers, broker, stop, qvecs


def vector_sql(qvec, k: int = VECTOR_K) -> str:
    arr = ", ".join(f"{float(x):.6f}" for x in qvec)
    vs = f"VECTOR_SIMILARITY(emb, ARRAY[{arr}], {k})"
    return (f"SELECT id, {vs} AS score FROM vectors WHERE {vs} "
            f"ORDER BY {vs} DESC LIMIT {k}")


def main_vector(args) -> int:
    """--vector: the vector-search chaos gate (ISSUE 14): seeded
    VECTOR_SIMILARITY top-k queries over a 2-server cluster must
    (a) fail over byte-identically under ``rpc.drop`` with same-seed
    runs firing identical decision streams, (b) recover byte-identical
    top-k from a mid-query ``tier.evict`` demotion of the vector pool,
    (c) reject malformed calls as structured errors even under chaos,
    and (d) leave the ``vector`` devmem pool reconciled to the byte."""
    from pinot_tpu.cluster.http_util import http_json
    from pinot_tpu.index import vector as vix
    from pinot_tpu.utils import faults
    from pinot_tpu.utils.devmem import global_device_memory

    tmp = tempfile.mkdtemp(prefix="ptpu_vector_chaos_")
    failures = []
    summary = {"mode": "vector", "rows": args.rows, "seed": args.seed,
               "queries": 0, "faults_fired": 0}

    def check(name, ok, detail=""):
        if not ok:
            failures.append(f"{name}: {detail}")
            print(f"FAIL {name}: {detail}")

    faults.clear()
    # start from devmem-synced vector residents: inside a warm pytest
    # process, earlier tests' readers can still hold device arrays
    # whose pool accounting the per-test reset already cleared (the
    # --tier gate's cache-clear discipline, applied to this pool)
    for r in vix.live_readers():
        r.evict_device()
    ctrl, servers, broker, stop, qvecs = build_vector_cluster(
        tmp, args.rows, args.seed)
    try:
        sqls = [vector_sql(q) for q in qvecs]

        def run_all(tag):
            out = {}
            for i, sql in enumerate(sqls):
                resp = http_json(
                    "POST", f"{broker.url}/query/sql",
                    {"sql": sql + f" OPTION(timeoutMs=300000,"
                                  f"queryId=vec.{tag}.{i})"},
                    timeout=120.0)
                out[i] = digest(resp)
            return out

        baseline = run_all("base")
        summary["queries"] = len(sqls)
        check("baseline.rows", all(baseline.values()),
              "a fault-free vector query returned no rows")

        # (a) rpc.drop failover: server_0's first /query/bin dispatch
        # dies; the broker must fail over to the replica and answer
        # byte-identically, two same-seed runs firing identical streams
        # (port-scoped match: heartbeat traffic must not join the
        # stream comparison — background timing isn't deterministic)
        p0 = servers[0].port
        plan_text = (f"seed={args.seed}; "
                     f"rpc.drop: match=:{p0}/query/bin, times=1")

        def run_plan(tag):
            # clear the previous plan's failure backoff so the
            # selector dials server_0 again and the fault re-fires —
            # same-seed determinism is a property of the decision
            # STREAMS, so both runs must present the same dial pattern
            for s in servers:
                broker._failures.record_success(s.instance_id)
            plan = faults.install(plan_text)
            try:
                got = run_all(tag)
            finally:
                faults.clear()
            return plan, got

        plan1, got1 = run_plan("drop")
        summary["faults_fired"] += len(plan1.fired)
        check("rpc_drop.fired", len(plan1.fired) >= 1,
              "rpc.drop never fired")
        for i in baseline:
            check(f"rpc_drop.q{i}", got1[i] == baseline[i],
                  "top-k digest mismatch after failover")
        plan2, got2 = run_plan("drop")
        check("rpc_drop.deterministic",
              plan1.fired_summary() == plan2.fired_summary(),
              f"{plan1.fired_summary()} != {plan2.fired_summary()}")
        for i in baseline:
            check(f"rpc_drop.rerun.q{i}", got2[i] == baseline[i],
                  "digest mismatch on same-seed rerun")

        # (b) tier.evict mid-query: the vector pool's device residents
        # drop between accesses; the search must re-upload and answer
        # byte-identically (once per query stream, every query)
        plan3 = faults.install(
            f"seed={args.seed}; tier.evict: match=seg_1, times=1")
        got3 = run_all("evict")
        faults.clear()
        summary["faults_fired"] += len(plan3.fired)
        check("tier_evict.fired", len(plan3.fired) >= 1,
              "tier.evict never fired")
        for i in baseline:
            check(f"tier_evict.q{i}", got3[i] == baseline[i],
                  "top-k digest mismatch after mid-query demotion")

        # (c) structured errors survive chaos: a bad-dim call is a
        # user error (HTTP 400 / SqlError), never a partial result
        from urllib.error import HTTPError
        try:
            http_json("POST", f"{broker.url}/query/sql",
                      {"sql": "SELECT id FROM vectors WHERE "
                              "VECTOR_SIMILARITY(emb, ARRAY[1.0], 3) "
                              "LIMIT 3"}, timeout=60.0)
            check("bad_dim.structured", False, "no error raised")
        except HTTPError as e:
            body = e.read().decode("utf-8", "replace")
            check("bad_dim.structured",
                  e.code == 400 and "dim mismatch" in body,
                  f"HTTP {e.code}: {body[:200]}")
        except Exception as e:  # noqa: BLE001 — into the summary
            check("bad_dim.structured", False,
                  f"unexpected error: {e}")

        # (d) vector pool reconciles to the byte across the churn
        tracked = global_device_memory.pool_bytes("vector")
        actual = sum(r.device_bytes() for r in vix.live_readers())
        summary["vector_pool"] = {"tracked": tracked, "actual": actual}
        check("reconcile.vector", tracked == actual,
              f"tracked {tracked} != actual {actual}")

        # forensics ride along for free: every vector query landed a
        # validated query_stats record
        from pinot_tpu.utils import ledger as uledger
        res = uledger.validate_file(
            os.path.join(tmp, "query_stats.jsonl"))
        check("query_stats.valid", not res["errors"],
              f"invalid records: {res['errors'][:3]}")
        check("query_stats.count",
              res["kinds"].get("query_stats", 0) >= 4 * len(sqls),
              f"{res['kinds'].get('query_stats', 0)} records for "
              f"{4 * len(sqls)} queries")
    finally:
        faults.clear()
        stop()
        shutil.rmtree(tmp, ignore_errors=True)

    summary["ok"] = not failures
    summary["failures"] = failures
    print(json.dumps(summary))
    return 0 if not failures else 1


FUSED_ROWS = 65536


def build_fused_broker(tmp: str, rows: int, seed: int):
    """In-process broker over a 3-table join star (the whole-plan mesh
    compilation surface: fact ``orders`` in 4 segments + two dims)."""
    import numpy as np

    from pinot_tpu.broker import Broker
    from pinot_tpu.segment import SegmentBuilder
    from pinot_tpu.server import TableDataManager
    from pinot_tpu.spi import (DataType, FieldSpec, FieldType, Schema,
                               TableConfig)

    rng = np.random.default_rng(seed)
    n_cust = max(rows // 4, 64)
    n_part = max(rows // 64, 16)
    tables = {
        "customers": ({
            "c_id": np.arange(n_cust).astype(np.int32),
            "c_nation": rng.choice(["us", "de", "jp", "br", "cn"],
                                   n_cust),
        }, [FieldSpec("c_id", DataType.INT),
            FieldSpec("c_nation", DataType.STRING)], 1),
        "parts": ({
            "p_id": np.arange(n_part).astype(np.int32),
            "p_brand": rng.choice(["acme", "blitz", "corex"], n_part),
        }, [FieldSpec("p_id", DataType.INT),
            FieldSpec("p_brand", DataType.STRING)], 1),
        "orders": ({
            "o_key": np.arange(rows).astype(np.int64),
            "o_cust": rng.choice(n_cust, rows).astype(np.int32),
            "o_part": rng.choice(n_part, rows).astype(np.int32),
            "o_price": rng.integers(10, 5000, rows).astype(np.int64),
        }, [FieldSpec("o_key", DataType.LONG),
            FieldSpec("o_cust", DataType.INT),
            FieldSpec("o_part", DataType.INT),
            FieldSpec("o_price", DataType.LONG, FieldType.METRIC)], 4),
    }
    broker = Broker()
    for name, (cols, fields, n_segments) in tables.items():
        schema = Schema(name, fields)
        b = SegmentBuilder(schema, TableConfig(name))
        dm = TableDataManager(name)
        n = len(next(iter(cols.values())))
        step = -(-n // n_segments)
        for i in range(n_segments):
            chunk = {k: v[i * step:(i + 1) * step]
                     for k, v in cols.items()}
            dm.add_segment_dir(b.build(chunk, os.path.join(tmp, name),
                                       f"s{i}"))
        broker.register_table(dm)
    return broker, tables


FUSED_MIX = [
    "SELECT c.c_nation, SUM(o.o_price), COUNT(*) FROM orders o "
    "JOIN customers c ON o.o_cust = c.c_id "
    "GROUP BY c.c_nation ORDER BY c.c_nation LIMIT 10",
    "SELECT c.c_nation, p.p_brand, SUM(o.o_price) FROM orders o "
    "JOIN customers c ON o.o_cust = c.c_id "
    "JOIN parts p ON o.o_part = p.p_id "
    "GROUP BY c.c_nation, p.p_brand "
    "ORDER BY c.c_nation, p.p_brand LIMIT 20",
    "SELECT c.c_nation, o.o_key, "
    "ROW_NUMBER() OVER (PARTITION BY c.c_nation ORDER BY o.o_key) "
    "FROM orders o JOIN customers c ON o.o_cust = c.c_id "
    "WHERE o.o_price > 4900 ORDER BY c.c_nation, o.o_key LIMIT 50",
    "SELECT c.c_nation, SUM(o.o_price) FROM orders o "
    "JOIN customers c ON o.o_cust = c.c_id "
    "WHERE o.o_price > 2500 GROUP BY c.c_nation "
    "UNION ALL "
    "SELECT p.p_brand, SUM(o.o_price) FROM orders o "
    "JOIN parts p ON o.o_part = p.p_id "
    "WHERE o.o_price <= 2500 GROUP BY p.p_brand",
]


def main_fused(args) -> int:
    """--fused: the whole-plan mesh compilation chaos gate (ISSUE 16):
    (a) fused == mailbox byte-identical digests over a join + window +
    set-op mix, (b) a p=1.0 ``device.overflow`` plan forces the real
    fallback edge — the mailbox plane serves every query byte-
    identically, two same-seed runs firing identical streams — and
    (c) a cross-host distributed_join under a seeded ``rpc.drop``
    pins that cross-process plans ride the mailbox data plane (the
    fused counter never moves), fail LOUDLY when a frame drops, and
    answer byte-identical to the numpy oracle once the fault clears."""
    import numpy as np

    from pinot_tpu.multistage import fused
    from pinot_tpu.utils import faults

    tmp = tempfile.mkdtemp(prefix="ptpu_fused_chaos_")
    failures = []
    summary = {"mode": "fused", "rows": args.rows, "seed": args.seed,
               "queries": len(FUSED_MIX), "faults_fired": 0}

    def check(name, ok, detail=""):
        if not ok:
            failures.append(f"{name}: {detail}")
            print(f"FAIL {name}: {detail}")

    def dig(res):
        return sorted(tuple(r) for r in res.rows)

    faults.clear()
    broker, _tables = build_fused_broker(tmp, args.rows, args.seed)
    try:
        # (a) parity: every mix query byte-identical across planes,
        # and the fused plane genuinely engaged
        plans0 = fused.STATS["fused_plans"]
        for i, q in enumerate(FUSED_MIX):
            d_m = dig(broker.query(q + " OPTION(multistageFused=false)"))
            d_f = dig(broker.query(q + " OPTION(multistageFused=true)"))
            check(f"parity.q{i}", d_f == d_m,
                  "fused and mailbox digests differ")
        check("parity.engaged",
              fused.STATS["fused_plans"] - plans0 >= len(FUSED_MIX),
              "the fused plane never engaged on the mix")

        # (b) device.overflow chaos: forced overflow takes the real
        # fallback edge; the mailbox plane must serve every query
        # byte-identically and same-seed runs fire identical streams
        def overflow_run():
            plan = faults.install(
                f"seed={args.seed}; device.overflow: "
                f"match=multistage.fused, p=1.0")
            try:
                out = [dig(broker.query(
                    q + " OPTION(multistageFused=true)"))
                    for q in FUSED_MIX]
            finally:
                faults.clear()
            return plan, out

        fb0 = fused.STATS["fused_fallbacks"]
        plan1, got1 = overflow_run()
        summary["faults_fired"] += len(plan1.fired)
        check("overflow.fired", len(plan1.fired) >= len(FUSED_MIX),
              f"{len(plan1.fired)} fires for {len(FUSED_MIX)} queries")
        check("overflow.fallbacks",
              fused.STATS["fused_fallbacks"] - fb0 >= len(FUSED_MIX),
              "forced overflow did not route the mailbox fallback")
        for i, q in enumerate(FUSED_MIX):
            check(f"overflow.q{i}",
                  got1[i] == dig(broker.query(
                      q + " OPTION(multistageFused=false)")),
                  "digest mismatch on the chaos fallback path")
        plan2, got2 = overflow_run()
        check("overflow.deterministic",
              plan1.fired_summary() == plan2.fired_summary(),
              f"{plan1.fired_summary()} != {plan2.fired_summary()}")
        check("overflow.rerun", got1 == got2,
              "same-seed rerun digests differ")

        # (c) cross-host plans ride the mailbox data plane: a 2-process
        # distributed_join never touches the fused counter; a seeded
        # rpc.drop of one mailbox frame fails the stage loudly (no
        # partial relation), same-seed reruns fire identical streams,
        # and the join is byte-exact once the fault clears
        from pinot_tpu.cluster import Controller, ServerNode
        from pinot_tpu.multistage.dispatch import distributed_join
        from pinot_tpu.segment import SegmentBuilder
        from pinot_tpu.spi import (DataType, FieldSpec, FieldType,
                                   Schema, TableConfig)

        rng = np.random.default_rng(args.seed + 1)
        n_o, n_c = 400, 50
        xo = {"cust_id": rng.integers(0, n_c + 5, n_o)
              .astype(np.int32),
              "amount": rng.integers(1, 1000, n_o).astype(np.int32)}
        xc = {"id": np.arange(n_c, dtype=np.int32),
              "tier": rng.choice(["gold", "silver"], n_c)}
        ctrl = Controller(os.path.join(tmp, "ctrl"),
                          heartbeat_timeout=5.0,
                          reconcile_interval=0.2)
        servers = [ServerNode(f"server_{i}", ctrl.url,
                              poll_interval=0.1) for i in range(2)]
        try:
            so = Schema("xorders", [
                FieldSpec("cust_id", DataType.INT),
                FieldSpec("amount", DataType.INT, FieldType.METRIC)])
            sc = Schema("xcust", [
                FieldSpec("id", DataType.INT),
                FieldSpec("tier", DataType.STRING)])
            ctrl.add_table("xorders", so.to_dict(), replication=1)
            ctrl.add_table("xcust", sc.to_dict(), replication=1)
            ctrl.add_segment("xorders", "xorders_0", SegmentBuilder(
                so, TableConfig("xorders")).build(
                xo, os.path.join(tmp, "xseg"), "xorders_0"))
            ctrl.add_segment("xcust", "xcust_0", SegmentBuilder(
                sc, TableConfig("xcust")).build(
                xc, os.path.join(tmp, "xseg"), "xcust_0"))
            v = ctrl.routing_snapshot()["version"]
            for s in servers:
                assert s.wait_for_version(v, timeout=30.0)

            def owner_url(table):
                for s in servers:
                    dm = s._tables.get(table)
                    if dm is not None and dm.acquire_segments():
                        return s.url
                raise AssertionError(table)

            def run_join():
                return distributed_join(
                    [{"url": owner_url("xorders"),
                      "sql": "SELECT cust_id, amount FROM xorders "
                             "LIMIT 100000", "alias": "o"}],
                    [{"url": owner_url("xcust"),
                      "sql": "SELECT id, tier FROM xcust "
                             "LIMIT 100000", "alias": "c"}],
                    [s.url for s in servers],
                    ["o.cust_id"], ["c.id"])

            plans_x = fused.STATS["fused_plans"]
            drop_text = (f"seed={args.seed}; rpc.drop: "
                         f"match=/mailbox, times=1")

            def drop_run():
                plan = faults.install(drop_text)
                loud = False
                try:
                    run_join()
                except Exception:
                    loud = True
                finally:
                    faults.clear()
                return plan, loud

            pland1, loud1 = drop_run()
            summary["faults_fired"] += len(pland1.fired)
            check("rpc_drop.fired", len(pland1.fired) >= 1,
                  "rpc.drop never fired on the mailbox plane")
            check("rpc_drop.loud", loud1,
                  "a dropped mailbox frame did not fail the stage")
            pland2, loud2 = drop_run()
            check("rpc_drop.deterministic",
                  pland1.fired_summary() == pland2.fired_summary(),
                  f"{pland1.fired_summary()} != "
                  f"{pland2.fired_summary()}")
            check("rpc_drop.rerun_loud", loud2,
                  "same-seed rerun did not fail the stage")

            rel = run_join()
            tier = {int(i): t for i, t in zip(xc["id"], xc["tier"])}
            exp = sorted((int(c), int(a), tier[int(c)]) for c, a in
                         zip(xo["cust_id"], xo["amount"])
                         if int(c) in tier)
            got = sorted(zip(rel.data["o.cust_id"].tolist(),
                             rel.data["o.amount"].tolist(),
                             rel.data["c.tier"].tolist()))
            check("crosshost.digest", got == exp,
                  "distributed join differs from the numpy oracle")
            check("crosshost.mailbox_pinned",
                  fused.STATS["fused_plans"] == plans_x,
                  "a cross-host plan engaged the fused plane")
        finally:
            for s in servers:
                try:
                    s.stop()
                except Exception:
                    pass
            ctrl.stop()
    finally:
        faults.clear()
        shutil.rmtree(tmp, ignore_errors=True)

    summary["ok"] = not failures
    summary["failures"] = failures
    print(json.dumps(summary))
    return 0 if not failures else 1


def main_overload(args) -> int:
    """--overload: the ISSUE-12 overload-resilience gate. One closed-
    loop traffic replay (tools/traffic_replay.py, cluster mode): record
    a three-tenant mix at 1x, replay it at --multiple N with chaos
    armed, and assert the acceptance contract — protected-tenant p99
    inside its bar with ZERO sheds/kills while besteffort sheds absorb
    the excess, every shed a structured 429 with retryAfterMs, the
    shed stream byte-identical to the pure same-seed plan, post-spike
    latency back inside the pre-spike noise floor, and >=1 validated
    ``replay_bench`` ledger record."""
    import traffic_replay as TR
    from pinot_tpu.utils import ledger as uledger

    tmp = tempfile.mkdtemp(prefix="ptpu_overload_")
    ledger_path = os.path.join(tmp, "replay_bench.jsonl")
    failures = []
    summary = {"mode": "overload", "seed": args.seed,
               "multiple": args.multiple, "rows": args.rows}

    def check(name, ok, detail=""):
        if not ok:
            failures.append(f"{name}: {detail}")
            print(f"FAIL {name}: {detail}")

    try:
        res = TR.run_gate(multiple=args.multiple, seed=args.seed,
                          n_queries=args.replay_queries, rows=args.rows,
                          mode="cluster", chaos=True,
                          ledger_out=ledger_path)
        summary.update({k: res.get(k) for k in (
            "offered", "completed", "shed", "shed_by_tenant",
            "shed_by_rung", "tiers", "structured_429", "retries",
            "deterministic", "protected_sheds", "protected_p99_ms",
            "protected_bar_ms", "goodput_qps", "faults_fired",
            "recovered", "recovery")})
        check("overload.ok", res.get("ok") is True,
              res.get("error", "gate failed"))
        check("overload.deterministic", res.get("deterministic") is True,
              "same-seed shed streams diverged")
        check("overload.protected_untouched",
              res.get("protected_sheds") == 0
              and (res.get("tiers") or {}).get(
                  "protected", {}).get("errors", 1) == 0,
              f"protected sheds={res.get('protected_sheds')} "
              f"errors={(res.get('tiers') or {}).get('protected')}")
        check("overload.besteffort_absorbs",
              (res.get("shed_by_tenant") or {}).get(
                  "ten_besteffort", 0) >= 1,
              f"shed_by_tenant={res.get('shed_by_tenant')}")
        check("overload.structured_429",
              res.get("structured_429") == res.get("shed")
              and res.get("shed", 0) >= 1,
              f"{res.get('structured_429')} structured of "
              f"{res.get('shed')} sheds")
        check("overload.chaos_fired", res.get("faults_fired", 0) >= 1,
              "the armed chaos plan never fired")
        check("overload.recovered", res.get("recovered") is True,
              f"recovery={res.get('recovery')}")
        lres = uledger.validate_file(ledger_path)
        summary["ledger_kinds"] = lres["kinds"]
        check("overload.ledger_valid", not lres["errors"],
              f"invalid records: {lres['errors'][:3]}")
        check("overload.replay_bench_record",
              lres["kinds"].get("replay_bench", 0) >= 1,
              f"kinds={lres['kinds']}")
    except Exception as e:  # noqa: BLE001 — into the summary
        check("overload.run", False, f"EXC {type(e).__name__}: {e}")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    summary["ok"] = not failures
    summary["failures"] = failures
    print(json.dumps(summary))
    return 0 if not failures else 1


def main_rate(args) -> int:
    """--rate: the sustained ingest-while-query chaos gate (module
    docstring). Chaos-armed loadgen run -> oracle exactness + validated
    ingest_bench/ingest_stats records -> fault-free freshness-gate
    capture+check vs the checked-in baseline."""
    import freshness_gate as FG
    from pinot_tpu.engine.loadgen import (LoadgenConfig, TableLoadSpec,
                                          run_load)
    from pinot_tpu.tools.ingest_fuzz import ingest_plan
    from pinot_tpu.utils import faults
    from pinot_tpu.utils import ledger as uledger

    tmp = tempfile.mkdtemp(prefix="ptpu_rate_chaos_")
    ledger_path = os.path.join(tmp, "ingest_bench.jsonl")
    failures = []
    summary = {"mode": "rate", "rows": args.rows, "seed": args.seed}

    def check(name, ok, detail=""):
        if not ok:
            failures.append(f"{name}: {detail}")
            print(f"FAIL {name}: {detail}")

    faults.clear()
    try:
        cfg = LoadgenConfig(
            tables=[
                TableLoadSpec("rate_append", partitions=2),
                TableLoadSpec("rate_upsert", partitions=2, upsert=True,
                              protocol=True),
            ],
            seed=args.seed,
            rows_per_partition=args.rows,
            query_concurrency=2,
            scenario="chaos_rate",
            fault_plan=ingest_plan(args.seed, protocol=True),
            ledger_path=ledger_path,
            max_wall_s=90.0)
        res = run_load(os.path.join(tmp, "run"), cfg)
        summary.update(
            {k: res.get(k) for k in
             ("rows", "rows_per_s", "duration_s", "freshness_p50_ms",
              "freshness_p99_ms", "commit_p50_ms", "queries",
              "query_p50_ms", "query_errors", "restarts",
              "faults_fired", "batched", "oracle_ok")})
        # (a) chaos actually happened AND the final state is byte-exact
        # vs the fault-free oracle (run_load diffs per table/partition)
        check("rate.ok", res.get("ok") is True,
              res.get("error", "oracle mismatch"))
        check("rate.fired", res.get("faults_fired", 0) >= 1,
              "the armed plan never fired")
        check("rate.queries_ran", res.get("queries", 0) >= 1,
              "no concurrent queries completed")
        # (b) validated ledger: one ingest_bench + per-table stats rows
        lres = uledger.validate_file(ledger_path)
        summary["ledger_kinds"] = lres["kinds"]
        check("rate.ledger_valid", not lres["errors"],
              f"invalid records: {lres['errors'][:3]}")
        check("rate.ingest_bench_record",
              lres["kinds"].get("ingest_bench", 0) >= 1
              and lres["kinds"].get("ingest_stats", 0) >= 2,
              f"kinds={lres['kinds']}")
        # (c) the freshness ratchet: fresh fault-free gate-corpus
        # capture checked against the checked-in baseline (the same
        # check bench_common.finish() runs on every bench capture)
        gate_ledger = os.path.join(tmp, "gate_corpus.jsonl")
        try:
            FG.capture(gate_ledger, iters=args.gate_iters)
            rc = FG.main(["check", gate_ledger])
            summary["freshness_gate_exit"] = rc
            check("rate.freshness_gate", rc == 0, f"exit {rc}")
        except Exception as e:  # noqa: BLE001 — into the summary
            check("rate.freshness_gate", False,
                  f"EXC {type(e).__name__}: {e}")
    finally:
        faults.clear()
        shutil.rmtree(tmp, ignore_errors=True)

    summary["ok"] = not failures
    summary["failures"] = failures
    print(json.dumps(summary))
    return 0 if not failures else 1


def _rollup_gate(ctrl, broker, tmp, queries, seed, check) -> dict:
    """The round-14 fleet-rollup chaos gate (satellite): fault-kill one
    broker's ledger pull mid-rollup, then assert skip-count + exact
    per-table totals + a valid fleet ledger + the --fleet span check."""
    import span_diff
    from pinot_tpu.cluster import BrokerNode
    from pinot_tpu.cluster.http_util import http_json
    from pinot_tpu.utils import faults
    from pinot_tpu.utils import ledger as uledger

    out: dict = {}
    b2 = BrokerNode(ctrl.url, routing_refresh=0.1,
                    query_stats_path=os.path.join(tmp, "qs_broker2.jsonl"))
    # the fault arms BEFORE broker2 serves any ledger pull: every pull
    # of it — including an auto-fired periodic pass — dies, so its rows
    # can never leak into the fleet ledger and the exactness assert
    # below is airtight
    plan = faults.install(
        f"seed={seed}; rpc.drop: match=:{b2.port}/debug/ledger")
    try:
        assert b2.wait_for_version(
            ctrl.routing_snapshot()["version"], timeout=30.0)
        qid, sql = queries[0]
        http_json("POST", f"{b2.url}/query/sql", {"sql": sql + OPTION},
                  timeout=120.0)
        rollup = None
        try:
            rollup = ctrl.rollup.run()
        except Exception as e:  # noqa: BLE001 — into the summary
            check("rollup.run", False, f"EXC {type(e).__name__}: {e}")
        out["rollup_faults_fired"] = len(plan.fired)
        check("rollup.pull_fault_fired", len(plan.fired) >= 1,
              "the /debug/ledger rpc.drop never fired")
        if rollup is not None:
            check("rollup.valid",
                  not uledger.validate_record(rollup),
                  f"{uledger.validate_record(rollup)}")
            check("rollup.dead_broker_counted",
                  rollup["nodes_skipped"] >= 1
                  and b2.instance_id in rollup.get("skipped_nodes", []),
                  f"skipped={rollup.get('skipped_nodes')}")
            # exactness: fleet per-table query counts == sum over the
            # brokers whose pulls SURVIVED of their own ledger rows
            expected: dict = {}
            for rec in _iter_stats(broker.forensics.ledger_path):
                t = rec.get("table")
                expected[t] = expected.get(t, 0) + 1
            got = {t: s.get("queries", 0)
                   for t, s in rollup["tables"].items()}
            check("rollup.table_totals_exact", got == expected,
                  f"rollup {got} != surviving brokers {expected}")
            out["rollup_tables"] = got
        # the whole fleet ledger must be contract-valid, rollup
        # records included (check_ledger reports the new kind)
        res = uledger.validate_file(ctrl.rollup.ledger_path)
        check("fleet_ledger.valid", not res["errors"],
              f"invalid records: {res['errors'][:3]}")
        check("fleet_ledger.kinds",
              res["kinds"].get("fleet_rollup", 0) >= 1
              and res["kinds"].get("query_stats", 0) >= 1,
              f"kinds={res['kinds']}")
        out["fleet_ledger_kinds"] = res["kinds"]
        # fleet span-diff over the aggregated (node-stamped) trace
        # corpus: per-node calibration, same env as the baseline
        rc = span_diff.main(["check", "--fleet",
                             ctrl.rollup.ledger_path])
        check("fleet_span_diff", rc == 0, f"exit {rc}")
    finally:
        faults.clear()
        b2.stop()
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=None,
                    help="table rows (default: 4096 cluster mode, "
                         "300/seeded-run ingest mode)")
    ap.add_argument("--seed", type=int, default=20260804)
    ap.add_argument("--queries", default=",".join(SMOKE_QUERY_IDS),
                    help="comma-separated SSB qids (tier-1 runs a "
                         "2-query subset to protect the suite budget)")
    ap.add_argument("--ingest", action="store_true",
                    help="run the realtime ingest chaos gate instead "
                         "of the cluster query gate")
    ap.add_argument("--rate", action="store_true",
                    help="run the sustained ingest-while-query rate "
                         "gate (loadgen + ingest_bench + freshness "
                         "ratchet)")
    ap.add_argument("--overload", action="store_true",
                    help="run the closed-loop traffic-replay overload "
                         "gate (tools/traffic_replay.py cluster mode)")
    ap.add_argument("--tier", action="store_true",
                    help="run the HBM-tier gate: mid-query tier.evict "
                         "recovery + constrained-budget demotion with "
                         "devmem reconciliation")
    ap.add_argument("--vector", action="store_true",
                    help="run the vector-search gate: seeded "
                         "VECTOR_SIMILARITY queries under rpc.drop + "
                         "tier.evict with identical top-k and a "
                         "reconciled vector devmem pool")
    ap.add_argument("--rebalance", action="store_true",
                    help="run the closed-loop rebalance gate: "
                         "burn-triggered move under rebalance.crash + "
                         "cutover.stall recovers byte-exact, incident "
                         "freeze honored, pools reconciled")
    ap.add_argument("--autopsy", action="store_true",
                    help="run the incident-autopsy gate: a real SLO "
                         "burn -> incident -> post-hook rca_verdict "
                         "with resolvable fleet evidence pointers, "
                         "and a clean window says inconclusive")
    ap.add_argument("--fused", action="store_true",
                    help="run the whole-plan mesh compilation gate: "
                         "fused == mailbox parity, device.overflow "
                         "fallback and cross-host mailbox pinning "
                         "under seeded rpc.drop")
    ap.add_argument("--multiple", type=float, default=4.0,
                    help="--overload mode: replay load multiple")
    ap.add_argument("--replay-queries", type=int, default=40,
                    help="--overload mode: recorded-mix size")
    ap.add_argument("--seeds", default=",".join(map(str, INGEST_SEEDS)),
                    help="--ingest mode seeds (comma-separated)")
    ap.add_argument("--gate-iters", type=int, default=2,
                    help="--rate mode: freshness-gate capture "
                         "iterations (default %(default)s)")
    args = ap.parse_args(argv)
    if args.rows is None:
        args.rows = INGEST_ROWS if args.ingest \
            else RATE_ROWS if args.rate \
            else OVERLOAD_ROWS if args.overload \
            else TIER_ROWS if args.tier \
            else VECTOR_ROWS if args.vector \
            else REBALANCE_ROWS if args.rebalance \
            else AUTOPSY_ROWS if args.autopsy \
            else FUSED_ROWS if args.fused else 4096
    if args.ingest:
        return main_ingest(args)
    if args.rate:
        return main_rate(args)
    if args.overload:
        return main_overload(args)
    if args.tier:
        return main_tier(args)
    if args.vector:
        return main_vector(args)
    if args.rebalance:
        return main_rebalance(args)
    if args.autopsy:
        return main_autopsy(args)
    if args.fused:
        return main_fused(args)

    from pinot_tpu.cluster.http_util import http_json
    from pinot_tpu.utils import faults
    from pinot_tpu.utils.metrics import global_metrics

    tmp = tempfile.mkdtemp(prefix="ptpu_chaos_")
    failures = []
    summary = {"rows": args.rows, "seed": args.seed, "plans": 0,
               "queries": 0, "faults_fired": 0}

    def check(name, ok, detail=""):
        if not ok:
            failures.append(f"{name}: {detail}")
            print(f"FAIL {name}: {detail}")

    faults.clear()
    baseline_hash = _file_hash(SPAN_BASELINE) \
        if os.path.exists(SPAN_BASELINE) else None
    ctrl, servers, broker, stop = build_ssb_cluster(tmp, args.rows)
    try:
        queries = smoke_queries(tuple(args.queries.split(",")))

        def run_all():
            out = {}
            for qid, sql in queries:
                # generous CLIENT timeout: the first query pays the XLA
                # compile (the broker-side budget is OPTION(timeoutMs))
                resp = http_json("POST", f"{broker.url}/query/sql",
                                 {"sql": sql + OPTION}, timeout=120.0)
                out[qid] = digest(resp)
            return out

        baseline = run_all()
        summary["queries"] = len(baseline)
        p0 = servers[0].port

        # compile-plane forensics (ISSUE 15): the baseline pass paid
        # the XLA compiles — every warmed plan must have landed >=1
        # validated compile_event (they ride the broker's stats
        # ledger, schema-checked with it below), keyed by the shared
        # normalized-SQL shape hash. Then a SAME-SEED chaos pass over
        # cleared compile caches must produce the IDENTICAL
        # (site, trigger, plan_shape) attribution set — faults perturb
        # routing, never compile attribution.
        from pinot_tpu.utils.compileplane import (clear_staged_caches,
                                                  global_compile_log)

        def _qstream(events):
            # query-attributed events only: setup-time compiles (none
            # today, but e.g. a future index build) carry no qid and
            # must not poison the parity comparison. cold/warmup
            # collapse to one first-compile class: both are warmup by
            # the detector's own rule, and which of two CONCURRENT
            # scatter threads classifies first is scheduler noise —
            # the attribution the gate pins is that chaos never turns
            # a first compile into a retrace/rebuild (or vice versa).
            def cls(t):
                return t if t not in ("cold", "warmup") else "first"
            return sorted({(e["site"], cls(e["trigger"]),
                            e.get("plan_shape"))
                           for e in events if e.get("qid")})

        stream_base = _qstream(global_compile_log.events())
        base_shapes = {s for _site, _trig, s in stream_base if s}
        summary["compile_events"] = len(global_compile_log.events())
        summary["compile_shapes"] = len(base_shapes)
        check("compile.per_warmed_plan",
              len(base_shapes) >= len(queries),
              f"{len(base_shapes)} compile plan shapes for "
              f"{len(queries)} warmed plans")
        # seq watermark, not a ring index: the event ring is bounded,
        # and a large corpus could wrap it between the passes
        seq0 = max((e["seq"] for e in global_compile_log.events()),
                   default=0)
        for s in servers:
            broker._failures.record_success(s.instance_id)
        clear_staged_caches()
        plan = faults.install(
            f"seed={args.seed}; "
            f"rpc.drop: match=:{p0}/query/bin, times=1")
        try:
            got = run_all()
        finally:
            faults.clear()
        summary["plans"] += 1
        stream_chaos = _qstream(
            [e for e in global_compile_log.events()
             if e["seq"] > seq0])
        check("compile.chaos_fired", len(plan.fired) >= 1,
              "parity plan never fired")
        check("compile.stream_nonempty", len(stream_chaos) >= 1,
              "no compile events in the chaos parity pass")
        check("compile.chaos_parity", stream_base == stream_chaos,
              f"attribution diverged under chaos: "
              f"{stream_base} != {stream_chaos}")
        for qid in baseline:
            check(f"compile.parity.{qid}", got[qid] == baseline[qid],
                  "digest mismatch on the recompile-under-chaos pass")

        # plan 1: drop server_0's first data-plane dispatch per key
        for plan_name, plan_text in (
                ("rpc.drop",
                 f"seed={args.seed}; "
                 f"rpc.drop: match=:{p0}/query/bin, times=1"),
                ("wire.corrupt",
                 f"seed={args.seed}; wire.corrupt: match=server_0, "
                 "times=1")):
            # clear the previous plan's failure backoff so the selector
            # dials server_0 again and this plan's fault actually fires
            for s in servers:
                broker._failures.record_success(s.instance_id)
            c0 = global_metrics.snapshot()["counters"]
            plan = faults.install(plan_text)
            try:
                got = run_all()
            finally:
                faults.clear()
            summary["plans"] += 1
            summary["faults_fired"] += len(plan.fired)
            check(f"{plan_name}.fired", len(plan.fired) >= 1,
                  "fault never fired")
            c1 = global_metrics.snapshot()["counters"]
            check(f"{plan_name}.failover",
                  c1.get("scatter_failovers", 0)
                  > c0.get("scatter_failovers", 0),
                  "no failover recorded")
            for qid in baseline:
                check(f"{plan_name}.{qid}", got[qid] == baseline[qid],
                      "digest mismatch after failover")

        # plan 3: replication-1 twin, server_0 permanently dropped —
        # the partial-result metadata contract
        plan = faults.install(
            f"seed={args.seed}; rpc.drop: match=:{p0}/query/bin")
        try:
            sql = ("SELECT d_year, SUM(lo_revenue) FROM lineorder_r1 "
                   "GROUP BY d_year ORDER BY d_year LIMIT 100 "
                   "OPTION(timeoutMs=300000,allowPartialResults=true)")
            resp = http_json("POST", f"{broker.url}/query/sql",
                             {"sql": sql}, timeout=120.0)
            summary["plans"] += 1
            summary["faults_fired"] += len(plan.fired)
            check("partial.flag", resp.get("partialResult") is True,
                  f"partialResult={resp.get('partialResult')}")
            check("partial.exceptions",
                  len(resp.get("exceptions", [])) >= 1, "no exceptions[]")
            check("partial.servers",
                  resp.get("numServersResponded", 0)
                  < resp.get("numServersQueried", 0),
                  f"{resp.get('numServersResponded')} !< "
                  f"{resp.get('numServersQueried')}")
            # default mode: whole-query failure
            import urllib.error
            try:
                http_json("POST", f"{broker.url}/query/sql", {
                    "sql": "SELECT SUM(lo_revenue) FROM lineorder_r1 "
                           "OPTION(timeoutMs=300000)"}, timeout=120.0)
                check("partial.default_fails", False,
                      "default mode returned despite dead replica")
            except urllib.error.HTTPError:
                pass
        finally:
            faults.clear()

        # recovery: fault-free digests once more (detector backoffs heal).
        # Any failure mode must land in the summary JSON, never a raw
        # traceback past the last print
        import time
        recovered = False
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and not recovered:
            try:
                recovered = run_all() == baseline
            except urllib.error.HTTPError:
                pass
            if not recovered:
                time.sleep(0.5)
        check("recovery", recovered,
              "cluster did not recover fault-free digests within 30s")

        # forensics plane: the soak must have emitted one validated
        # query_stats record per cluster query (ROADMAP round-9 item d)
        from pinot_tpu.utils import ledger as uledger
        stats = uledger.validate_file(broker.forensics.ledger_path)
        n_stats = stats["kinds"].get("query_stats", 0)
        summary["query_stats"] = n_stats
        check("query_stats.valid", not stats["errors"],
              f"invalid records: {stats['errors'][:3]}")
        # baseline + two failover plans + the partial-contract plan +
        # recovery all route through BrokerNode.query: at minimum the
        # three full run_all passes must be on record
        check("query_stats.count", n_stats >= 3 * len(queries) + 1,
              f"only {n_stats} query_stats records for "
              f"{len(queries)} queries")
        check("query_stats.partial_flagged",
              any(True for _ in _iter_stats(
                  broker.forensics.ledger_path, partial=True)),
              "no partialResult=true query_stats record from the "
              "replication-1 plan")
        # traceRatio=1.0 sampling: every soak query must also have
        # landed a VALIDATED query_trace record (validate_file above
        # already schema-checked them), qid-joinable to its stats row
        n_traces = stats["kinds"].get("query_trace", 0)
        summary["query_trace"] = n_traces
        check("query_trace.count", n_traces >= 3 * len(queries),
              f"only {n_traces} query_trace records for "
              f"{len(queries)} queries x 3 full passes")
        trace_qids = {r.get("qid") for r in _iter_kind(
            broker.forensics.ledger_path, "query_trace")}
        stats_qids = {r.get("qid") for r in _iter_stats(
            broker.forensics.ledger_path) if r.get("traced")}
        check("trace_stats_join", bool(trace_qids)
              and trace_qids <= stats_qids,
              f"{len(trace_qids - stats_qids)} trace qids without a "
              "traced query_stats row")
        # the chaos run must not have corrupted the checked-in span
        # baseline (nothing may write it outside `span_diff.py update`)
        if baseline_hash is not None:
            check("span_baseline.intact",
                  _file_hash(SPAN_BASELINE) == baseline_hash,
                  "tools/span_baseline.json changed during the soak")

        # fleet forensics rollup under chaos (round 14): a second
        # broker joins the fleet, then its ledger pull is fault-killed
        # MID-ROLLUP (rpc.drop on its /debug/ledger endpoint) — the
        # controller rollup must stay contract-valid, skip + count the
        # dead node, and per-table query totals must equal the sum of
        # the SURVIVING brokers' query_stats rows exactly
        summary.update(_rollup_gate(ctrl, broker, tmp, queries,
                                    args.seed, check))
        summary["plans"] += 1
    finally:
        faults.clear()
        stop()
        shutil.rmtree(tmp, ignore_errors=True)

    summary["ok"] = not failures
    summary["failures"] = failures
    print(json.dumps(summary))
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
