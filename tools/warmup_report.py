"""Warmup-debt report + post-warmup compile gate over ``compile_event``
ledger records (ISSUE 15).

The compile plane (pinot_tpu/utils/compileplane.py) lands one validated
``compile_event`` per XLA compile: site, trigger taxonomy {cold, warmup,
overflow_retry, drift_requantize, lru_evict_rebuild, retrace}, explicit
``lower_ms``/``compile_ms`` split, normalized plan-shape hash (shared
with span_diff via utils/shapehash) and executable memory/FLOPs. This
tool renders the cold-start debt report from any ledger and gates it:

    python tools/warmup_report.py report [ledger ...]
    python tools/warmup_report.py gate   [ledger ...] \
        [--max-post-warmup N] [--min-events N]

``report`` prints per-plan-shape rows (compiles, median/total compile
ms, trigger breakdown, warmup cost = compiles x median — the same
ranking cluster/rollup.py ships as ``fleet_rollup.plan_shapes``) plus
the per-trigger and per-site totals, one summary JSON line last.

``gate`` is the ratchet bench_common.finish() runs beside the span /
freshness / overload gates: post-warmup compiles (trigger retrace or
lru_evict_rebuild) above ``--max-post-warmup`` (default 0) fail with
exit 1 — a warmed engine paying unexplained compiles is the compile
storm's leading indicator, caught at bench time instead of as a silent
QPS cliff. ``--min-events`` (default 1) guards against a structurally
vacuous green: a gate corpus that emitted NO compile events means the
instrumentation is broken, not that warmup debt is zero.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from pinot_tpu.utils.compileplane import (  # noqa: E402
    POST_WARMUP_TRIGGERS, TRIGGERS)

POST_WARMUP = set(POST_WARMUP_TRIGGERS)


def load_compile_events(paths: List[str]) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    for path in paths:
        if not os.path.exists(path):
            continue
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and \
                        rec.get("kind") == "compile_event":
                    out.append(rec)
    return out


def summarize(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Pure events -> report dict (the oracle tests pin this).

    Events dedupe by (proc, seq) first — a FLEET ledger ships the same
    event once per node that served it (cluster/rollup puller), and a
    duplicate-counted retrace would spuriously trip the gate. The
    per-shape aggregation IS cluster/rollup.rank_plan_shapes, so this
    report and the webapp plan_shapes panel can never disagree over
    one corpus."""
    from pinot_tpu.cluster.rollup import rank_plan_shapes

    seen: set = set()
    deduped: List[Dict[str, Any]] = []
    for e in events:
        uid = (e.get("proc"), e.get("seq"))
        if uid in seen:
            continue
        seen.add(uid)
        deduped.append(e)
    by_trigger: Dict[str, int] = {}
    by_site: Dict[str, int] = {}
    total_ms = 0.0
    for e in deduped:
        total_ms += float(e.get("lower_ms", 0.0)) \
            + float(e.get("compile_ms", 0.0))
        t = e.get("trigger") or "?"
        by_trigger[t] = by_trigger.get(t, 0) + 1
        site = e.get("site") or "?"
        by_site[site] = by_site.get(site, 0) + 1
    return {
        "events": len(deduped),
        "compile_ms_total": round(total_ms, 3),
        "by_trigger": {t: by_trigger[t] for t in sorted(by_trigger)},
        "by_site": {s: by_site[s] for s in sorted(by_site)},
        "post_warmup": sum(n for t, n in by_trigger.items()
                           if t in POST_WARMUP),
        "shapes": rank_plan_shapes(deduped, top=len(deduped) or 1),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("mode", choices=["report", "gate"])
    ap.add_argument("ledgers", nargs="*",
                    help="ledger path(s); default: the repo "
                         "PERF_LEDGER.jsonl")
    ap.add_argument("--max-post-warmup", type=int, default=0,
                    help="gate: allowed retrace + lru_evict_rebuild "
                         "compiles (default %(default)s)")
    ap.add_argument("--min-events", type=int, default=1,
                    help="gate: minimum compile events for a "
                         "non-vacuous pass (default %(default)s)")
    ap.add_argument("--top", type=int, default=20)
    args = ap.parse_intermixed_args(argv)

    ledgers = args.ledgers or [os.path.join(REPO, "PERF_LEDGER.jsonl")]
    events = load_compile_events(ledgers)
    rep = summarize(events)

    if args.mode == "report":
        print(f"warmup debt: {rep['events']} compiles, "
              f"{rep['compile_ms_total']} ms total")
        for t in TRIGGERS:
            if rep["by_trigger"].get(t):
                print(f"  {t:>20}: {rep['by_trigger'][t]}")
        for s in rep["shapes"][: args.top]:
            print(f"  shape {s['plan_shape']}: x{s['compiles']} "
                  f"median {s['median_compile_ms']}ms "
                  f"cost {s['warmup_cost']} {s['triggers']} "
                  f"[{(s['sql'] or '')[:60]}]")
        print(json.dumps({"mode": "report", "ok": True,
                          **{k: rep[k] for k in
                             ("events", "compile_ms_total",
                              "by_trigger", "by_site",
                              "post_warmup")},
                          "shapes": len(rep["shapes"])}))
        return 0

    failures: List[str] = []
    if rep["events"] < args.min_events:
        failures.append(
            f"vacuous: only {rep['events']} compile_event record(s) "
            f"(< {args.min_events}) — instrumentation or corpus broken")
    if rep["post_warmup"] > args.max_post_warmup:
        offenders = [s for s in rep["shapes"]
                     if any(t in POST_WARMUP for t in s["triggers"])]
        failures.append(
            f"{rep['post_warmup']} post-warmup compile(s) > allowed "
            f"{args.max_post_warmup}: "
            + "; ".join(f"{s['plan_shape']} {s['triggers']}"
                        for s in offenders[:5]))
    for f in failures:
        print(f"GATE FAIL: {f}", file=sys.stderr)
    print(json.dumps({"mode": "gate", "ok": not failures,
                      "events": rep["events"],
                      "post_warmup": rep["post_warmup"],
                      "max_post_warmup": args.max_post_warmup,
                      "by_trigger": rep["by_trigger"],
                      "failures": failures}))
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
