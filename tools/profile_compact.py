"""Phase-level profile of the fused compact-strategy SSB kernels.

Decomposes kernel time into the round-6 pipeline's phases —
mask / fuse (key + payload materialization) / compact / sort /
aggregate / transfer — for the slow compact-path queries, so
strategy-ladder regressions are visible between captures (VERDICT r4
next-step #1b, round-6 satellite). The decomposition itself lives in
pinot_tpu/ops/phase_profile.py (EXPLAIN ANALYZE's
OPTION(profilePhases=true) shares it); this CLI appends one validated
v2 ``phase_profile`` record per query to PERF_LEDGER.jsonl
(pinot_tpu/utils/ledger.py), so the ledger keeps a phase-attribution
history alongside the headline captures.

Run standalone (CPU or chip; bounded by the caller):

    python tools/profile_compact.py q2.1 q3.2 q4.3

Prints one JSON line per query with phase times, compaction stats, and
the planner's cost-model trace (estimated vs measured selectivity).
"""
from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax

    qids = set(sys.argv[1:]) or {"q2.1", "q3.2", "q4.3"}
    from bench import QUERIES, build_or_load_segment, spec_to_sql
    from bench_common import LEDGER
    from pinot_tpu.ops.phase_profile import profile_plan
    from pinot_tpu.query.context import build_query_context
    from pinot_tpu.query.planner import SegmentPlanner
    from pinot_tpu.query.sql import parse_sql
    from pinot_tpu.utils import ledger as uledger

    seg = build_or_load_segment()
    backend = jax.default_backend()

    for qid, preds, vexpr, gcols in QUERIES:
        if qid not in qids:
            continue
        sql = spec_to_sql(preds, vexpr, gcols)
        ctx = build_query_context(parse_sql(sql))
        plan = SegmentPlanner(ctx, seg).plan()
        rec = uledger.make_record(
            "phase_profile",
            metric="compact_phase_profile", backend=backend, qid=qid,
            n_rows=int(seg.n_docs), **profile_plan(plan))
        print(json.dumps(rec), flush=True)
        uledger.append_record(rec, LEDGER)


if __name__ == "__main__":
    main()
