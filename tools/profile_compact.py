"""Phase-level TPU profile of the compact-strategy SSB kernels.

Decomposes kernel time into mask-eval / compaction / post-aggregation /
transfer-compaction for the slow compact-path queries so optimization
targets the real bottleneck (VERDICT r4 next-step #1b). Run standalone on
the real chip (bounded by the caller):

    python tools/profile_compact.py q2.1 q3.2 q4.3

Prints one JSON line per query with phase times and compaction stats.
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def timeit(fn, *args, iters=5):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    out = fn(*args)
    jax.block_until_ready(out)
    t_one = time.perf_counter() - t0
    t0 = time.perf_counter()
    outs = [fn(*args) for _ in range(iters + 1)]
    jax.block_until_ready(outs)
    t_k = time.perf_counter() - t0
    # pipelined launches amortize the tunneled-dispatch floor: per-call
    # device time ~= (t_{k+1} - t_1) / k (bench.kernel_time convention)
    return max((t_k - t_one) / iters, 1e-9)


def main():
    qids = set(sys.argv[1:]) or {"q2.1", "q3.2", "q4.3"}
    from bench import QUERIES, build_or_load_segment, spec_to_sql
    from pinot_tpu.engine.executor import resolve_params
    from pinot_tpu.ops import kernels
    from pinot_tpu.ops.compact import (default_slots_cap, full_slots_cap,
                                       sorted_default_slots_cap)
    from pinot_tpu.ops.kernels import _needs_sort, jitted_kernel
    from pinot_tpu.query.context import build_query_context
    from pinot_tpu.query.planner import SegmentPlanner
    from pinot_tpu.query.sql import parse_sql

    seg = build_or_load_segment()
    bucket = seg.bucket
    n = np.int32(seg.n_docs)

    for qid, preds, vexpr, gcols in QUERIES:
        if qid not in qids:
            continue
        sql = spec_to_sql(preds, vexpr, gcols)
        ctx = build_query_context(parse_sql(sql))
        plan = SegmentPlanner(ctx, seg).plan()
        kp = plan.kernel_plan
        cols = seg.device_cols(plan.col_names)
        params = resolve_params(plan)

        res = {"qid": qid, "strategy": kp.strategy,
               "space": kp.group_space if kp.is_group_by else 0,
               "n_cols": len(cols),
               "col_dtypes": [str(c.dtype) for c in cols],
               "needs_sort": _needs_sort(kp) if kp.is_group_by else None}

        # phase 1: mask eval only
        def mask_fn(cols, n, params):
            valid = jnp.arange(bucket, dtype=jnp.int32) < n
            return valid & kernels._eval_pred(kp.pred, cols, params, bucket)

        jmask = jax.jit(mask_fn)
        res["t_mask_ms"] = round(timeit(jmask, cols, n, params) * 1e3, 2)

        if kp.strategy == "compact":
            from pinot_tpu.ops.compact import compact
            needed = sorted({ci for ci, _ in kp.group_keys}
                            | set().union(
                                *[kernels._value_col_indices(s.value)
                                  for s in kp.aggs if s.value is not None]
                                or [set()]))
            cap = (sorted_default_slots_cap(bucket) if _needs_sort(kp)
                   else default_slots_cap(bucket))
            res["slots_cap"] = cap
            res["cap_rows"] = cap * 128

            def comp_fn(cols, n, params):
                m = mask_fn(cols, n, params)
                return compact(m, tuple(cols[ci] for ci in needed), cap)

            jcomp = jax.jit(comp_fn)
            res["t_mask_compact_ms"] = round(
                timeit(jcomp, cols, n, params) * 1e3, 2)
            valid, ccols, n_valid, matched, overflow = jcomp(cols, n, params)
            res["matched"] = int(matched)
            res["n_valid_rows"] = int(n_valid)
            res["overflow"] = int(overflow)
            res["inflation"] = round(int(n_valid) / max(int(matched), 1), 2)

            # full kernel without transfer compaction
            f_noxfer = jitted_kernel(kp, bucket, xfer_compact=False)
            res["t_kernel_noxfer_ms"] = round(
                timeit(f_noxfer, cols, n, params) * 1e3, 2)

        # full kernel (as shipped)
        ffull = jitted_kernel(kp, bucket)
        res["t_kernel_ms"] = round(timeit(ffull, cols, n, params) * 1e3, 2)
        print(json.dumps(res), flush=True)


if __name__ == "__main__":
    import jax
    import jax.numpy as jnp
    main()
