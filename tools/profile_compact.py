"""Phase-level profile of the fused compact-strategy SSB kernels.

Decomposes kernel time into the round-6 pipeline's phases —
mask / fuse (key + payload materialization) / compact / aggregate /
transfer — for the slow compact-path queries, so strategy-ladder
regressions are visible between captures (VERDICT r4 next-step #1b,
round-6 satellite). Every run APPENDS one record per query to
PERF_LEDGER.jsonl (metric "compact_phase_profile"), so the ledger keeps
a phase-attribution history alongside the headline captures.

Run standalone (CPU or chip; bounded by the caller):

    python tools/profile_compact.py q2.1 q3.2 q4.3

Prints one JSON line per query with phase times, compaction stats, and
the planner's cost-model trace (estimated vs measured selectivity).
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def timeit(fn, *args, iters=5):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    out = fn(*args)
    jax.block_until_ready(out)
    t_one = time.perf_counter() - t0
    t0 = time.perf_counter()
    outs = [fn(*args) for _ in range(iters + 1)]
    jax.block_until_ready(outs)
    t_k = time.perf_counter() - t0
    # pipelined launches amortize the tunneled-dispatch floor: per-call
    # device time ~= (t_{k+1} - t_1) / k (bench.kernel_time convention)
    return max((t_k - t_one) / iters, 1e-9)


def main():
    qids = set(sys.argv[1:]) or {"q2.1", "q3.2", "q4.3"}
    from bench import QUERIES, build_or_load_segment, spec_to_sql
    from bench_common import ledger_append_raw
    from pinot_tpu.engine.executor import resolve_params
    from pinot_tpu.ops import kernels
    from pinot_tpu.ops.compact import compact, full_slots_cap
    from pinot_tpu.ops.kernels import (_needs_sort, _payload_columns,
                                       cpu_scatter_default, jitted_kernel)
    from pinot_tpu.query.context import build_query_context
    from pinot_tpu.query.planner import SegmentPlanner
    from pinot_tpu.query.sql import parse_sql

    seg = build_or_load_segment()
    bucket = seg.bucket
    n = np.int32(seg.n_docs)
    backend = jax.default_backend()

    for qid, preds, vexpr, gcols in QUERIES:
        if qid not in qids:
            continue
        sql = spec_to_sql(preds, vexpr, gcols)
        ctx = build_query_context(parse_sql(sql))
        plan = SegmentPlanner(ctx, seg).plan()
        kp = plan.kernel_plan
        cols = seg.device_cols(plan.col_names)
        params = resolve_params(plan)

        res = {"metric": "compact_phase_profile", "backend": backend,
               "qid": qid, "n_rows": int(seg.n_docs),
               "strategy": kp.strategy,
               "space": kp.group_space if kp.is_group_by else 0,
               "n_cols": len(cols),
               "est_selectivity": plan.est_selectivity,
               "cost_trace": plan.strategy_trace,
               "needs_sort": _needs_sort(kp) if kp.is_group_by else None,
               "scatter_core": cpu_scatter_default()}

        # phase 1: predicate mask only
        def mask_fn(cols, n, params):
            valid = jnp.arange(bucket, dtype=jnp.int32) < n
            return valid & kernels._eval_pred(kp.pred, cols, params, bucket)

        res["t_mask_ms"] = round(
            timeit(jax.jit(mask_fn), cols, n, params) * 1e3, 2)

        if kp.strategy == "compact":
            cap = plan.slots_cap or full_slots_cap(bucket)
            res["slots_cap"] = cap
            res["cap_rows"] = cap * 128

            # phase 2: + fused key/payload materialization
            def fuse_fn(cols, n, params):
                m = mask_fn(cols, n, params)
                m, keys = kernels._group_keys_sentinel(kp, m, cols, params)
                payloads, *_meta = _payload_columns(kp, m, cols, params)
                return (m, keys) + payloads

            res["t_fuse_ms"] = round(
                timeit(jax.jit(fuse_fn), cols, n, params) * 1e3, 2)

            # phase 3: + one compaction of [key] + payloads
            def comp_fn(cols, n, params):
                m = mask_fn(cols, n, params)
                m, keys = kernels._group_keys_sentinel(kp, m, cols, params)
                payloads, *_meta = _payload_columns(kp, m, cols, params)
                return compact(m, (keys,) + payloads, cap)

            jcomp = jax.jit(comp_fn)
            res["t_compact_ms"] = round(
                timeit(jcomp, cols, n, params) * 1e3, 2)
            _v, _c, n_valid, matched, overflow = jcomp(cols, n, params)
            res["matched"] = int(matched)
            res["measured_selectivity"] = round(
                int(matched) / max(int(seg.n_docs), 1), 8)
            res["n_valid_rows"] = int(n_valid)
            res["overflow"] = int(overflow)
            res["inflation"] = round(int(n_valid) / max(int(matched), 1), 2)

            # phase 4: + post-aggregation (full kernel minus transfer
            # compaction)
            f_noxfer = jitted_kernel(kp, bucket, plan.slots_cap,
                                     xfer_compact=False)
            res["t_aggregate_ms"] = round(
                timeit(f_noxfer, cols, n, params) * 1e3, 2)

        # phase 5: full kernel (as shipped, with transfer compaction)
        ffull = jitted_kernel(kp, bucket, plan.slots_cap)
        res["t_kernel_ms"] = round(timeit(ffull, cols, n, params) * 1e3, 2)
        if "t_aggregate_ms" in res:
            res["t_transfer_ms"] = round(
                max(res["t_kernel_ms"] - res["t_aggregate_ms"], 0.0), 2)
        print(json.dumps(res), flush=True)
        ledger_append_raw(res)


if __name__ == "__main__":
    import jax
    import jax.numpy as jnp
    main()
