"""Freshness regression gate: ``ingest_bench`` ledger records diffed
against a checked-in baseline — the ingest plane's ratchet, built the
way tools/span_diff.py ratchets query phases.

Round 11 gave freshness a ledger (``ingest_stats``) and round 16 gives
it a benchmark (bench_ingest.py / pinot_tpu/engine/loadgen.py); this
tool gives it the regression BAR the ROADMAP demands ("a regression bar
on freshness like the >=5x SSB bar"):

- ``capture``  runs the deterministic gate corpus — a drain-mode
  loadgen run (2 tables x 2 partitions, mem transport, seeded rows,
  concurrent query mix, no chaos) — ``--iters`` times, appending one
  validated ``ingest_bench`` record per iteration;
- ``update``   aggregates records into ``tools/freshness_baseline.json``:
  per scenario, the median run wall and the median of each gated
  metric (freshness p50/p99, commit p50/p99);
- ``check``    re-aggregates a candidate ledger and FAILS (exit 1) when
  a gated metric's speed-calibrated value exceeds ``--bar`` x baseline.

Speed calibration: freshness scales with machine speed, so raw ms would
flag a loaded CI box. ``check`` computes one calibration factor — the
median of cand_wall/base_wall over common scenarios (the corpus is
drain-mode, so its wall IS a machine-speed probe), clamped to [0.2, 5]
— and divides every candidate metric by it. A uniformly slower machine
moves wall and freshness together and cancels; a freshness-only
regression (a stall on the fetch->queryable or seal->checkpoint path)
moves the metric without the wall and trips. A calibration pinned at
the clamp bounds means the environments are not comparable: the check
reports an explicit skip (ok, ``calibration_saturated``), never a
phantom regression. Per-metric noise floors (MIN_MS) keep
sub-floor-vs-sub-floor jitter from tripping while still catching a
tiny metric regressing to something large (the span_diff floor rule).

Environment pinning reuses span_diff's header verbatim: ``update``
stamps JAX_PLATFORMS/x64/backend, ``check`` exits 3 on a mismatch, and
bench_common.freshness_regression_gate surfaces that as an explicit
skip. Re-capture the baseline in the FULL tier-1 environment
(JAX_PLATFORMS=cpu PINOT_CPU_FAST_GROUPBY=0
XLA_FLAGS=--xla_force_host_platform_device_count=8), same as the span
baseline.

    python tools/freshness_gate.py capture --out /tmp/fg.jsonl [--iters 3]
    python tools/freshness_gate.py update  /tmp/fg.jsonl
    python tools/freshness_gate.py check   /tmp/fg.jsonl [--bar 1.8]

Exit 0 green / 1 regression / 2 usage / 3 environment mismatch; one
summary JSON line last, check_ledger-style. tier-1 runs capture+check
through tools/chaos_smoke.py --rate (tests/test_faults.py) and the
synthetic trip/calibration tests in tests/test_ingest_bench.py;
bench_common.finish() runs check on every bench capture.
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import statistics
import sys
import tempfile
from typing import Any, Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

import span_diff  # noqa: E402 — shared env pin (capture_env/env_mismatch)

DEFAULT_BASELINE = os.path.join(REPO, "tools", "freshness_baseline.json")
DEFAULT_BAR = 1.8          # < 2.0 so a 2x single-metric regression fails
DEFAULT_LAST = 5           # newest records per scenario (append-only
#                            ledgers must not out-vote a fresh regression)
EXIT_ENV_MISMATCH = 3

# gated metrics with per-metric noise floors (ms): freshness on the mem
# transport is sub-ms, so its floor sits well below it; commit latency
# includes a segment build and lives in the tens of ms
MIN_MS = {
    "freshness_p50_ms": 0.05,
    "freshness_p99_ms": 0.10,
    "commit_p50_ms": 1.0,
    "commit_p99_ms": 2.0,
}

GATE_SCENARIO = "gate_corpus"
GATE_SEED = 20260805
GATE_ROWS = 1200           # per partition; drain mode — wall is the
#                            machine-speed probe the calibration uses


def corpus_config(ledger_path: str, rows: int = GATE_ROWS,
                  seed: int = GATE_SEED):
    """The deterministic gate corpus (shared by capture and the smoke
    tests so the checked-in baseline and the gate measure the same
    run shape). Mem transport: the gate ratchets ENGINE freshness, not
    protocol-fake socket throughput."""
    from pinot_tpu.engine.loadgen import LoadgenConfig, TableLoadSpec
    return LoadgenConfig(
        tables=[
            TableLoadSpec("fg_append", partitions=2, threshold=96),
            TableLoadSpec("fg_upsert", partitions=2, upsert=True,
                          protocol=True, threshold=96),
        ],
        seed=seed, rows_per_partition=rows, query_concurrency=2,
        scenario=GATE_SCENARIO, ledger_path=ledger_path)


def capture(out_path: str, iters: int = 3, rows: int = GATE_ROWS) -> int:
    """Run the corpus ``iters`` times (fresh data dir each — a reused
    checkpoint would make later iterations consume nothing), appending
    one ingest_bench record per run. Returns records appended."""
    from pinot_tpu.engine.loadgen import run_load
    n = 0
    for i in range(iters):
        tmp = tempfile.mkdtemp(prefix="ptpu_fgate_")
        try:
            summary = run_load(tmp, corpus_config(out_path, rows=rows))
            if not summary.get("ok"):
                raise RuntimeError(
                    f"gate corpus run {i} failed: "
                    f"{summary.get('error', 'oracle mismatch')}")
            n += 1
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    return n


# ---------------------------------------------------------------------------
# aggregation + diff
# ---------------------------------------------------------------------------

def load_bench_records(paths: List[str]) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    for path in paths:
        if not os.path.exists(path):
            continue
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) \
                        and rec.get("kind") == "ingest_bench" \
                        and rec.get("ok") and rec.get("scenario"):
                    out.append(rec)
    return out


def aggregate(records: List[Dict[str, Any]],
              last: Optional[int] = DEFAULT_LAST) -> Dict[str, Any]:
    """records -> {scenario: {n, wall_s, metrics: {name: ms}}} with
    per-scenario medians over the NEWEST ``last`` records."""
    by_s: Dict[str, List[Dict[str, Any]]] = {}
    for rec in records:
        by_s.setdefault(str(rec["scenario"]), []).append(rec)
    if last is not None and last > 0:
        by_s = {k: v[-last:] for k, v in by_s.items()}
    out: Dict[str, Any] = {}
    for s, recs in sorted(by_s.items()):
        walls = [float(r.get("duration_s", 0.0)) for r in recs
                 if float(r.get("duration_s", 0.0)) > 0]
        if not walls:
            continue
        metrics: Dict[str, float] = {}
        for m in MIN_MS:
            vals = [float(r[m]) for r in recs
                    if isinstance(r.get(m), (int, float))]
            if vals:
                metrics[m] = round(statistics.median(vals), 3)
        out[s] = {"n": len(recs),
                  "wall_s": round(statistics.median(walls), 4),
                  "metrics": metrics}
    return out


def speed_calibration(baseline: Dict[str, Any],
                      candidate: Dict[str, Any]) -> float:
    ratios = [candidate[k]["wall_s"] / baseline[k]["wall_s"]
              for k in set(baseline) & set(candidate)
              if baseline[k]["wall_s"] > 0]
    if not ratios:
        return 1.0
    return min(max(statistics.median(ratios), 0.2), 5.0)


def diff_scenarios(baseline: Dict[str, Any], candidate: Dict[str, Any],
                   bar: float) -> Dict[str, Any]:
    cal = speed_calibration(baseline, candidate)
    regressions: List[Dict[str, Any]] = []
    checked = 0
    for s, cand in candidate.items():
        base = baseline.get(s)
        if base is None:
            continue
        for m, c_ms in cand["metrics"].items():
            b_ms = base["metrics"].get(m)
            if b_ms is None:
                continue
            floor = MIN_MS[m]
            adj = c_ms / cal
            if adj < floor:
                continue               # noise floor: candidate tiny
            eff_base = max(b_ms, floor)  # tiny baselines floored, not
            checked += 1                 # exempted (span_diff rule)
            if adj > bar * eff_base:
                regressions.append({
                    "scenario": s, "metric": m,
                    "base_ms": b_ms, "cand_ms": c_ms,
                    "calibrated_ms": round(adj, 3),
                    "ratio": round(adj / eff_base, 3),
                })
    return {
        "calibration": round(cal, 4),
        "calibration_saturated": cal in (0.2, 5.0),
        "checked_metrics": checked,
        "regressions": regressions,
        "new_scenarios": sorted(set(candidate) - set(baseline)),
        "missing_scenarios": sorted(set(baseline) - set(candidate)),
    }


# ---------------------------------------------------------------------------
# baseline io + CLI
# ---------------------------------------------------------------------------

def load_baseline(path: str) -> Dict[str, Any]:
    with open(path) as fh:
        return json.load(fh)


def write_baseline(path: str, scenarios: Dict[str, Any],
                   env: Optional[Dict[str, Any]] = None) -> None:
    with open(path, "w") as fh:
        json.dump({"v": 1, "bar": DEFAULT_BAR, "min_ms": MIN_MS,
                   "env": env if env is not None
                   else span_diff.capture_env(),
                   "scenarios": scenarios}, fh, indent=1, sort_keys=True)
        fh.write("\n")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("mode", choices=["check", "update", "capture"])
    ap.add_argument("ledgers", nargs="*",
                    help="ingest_bench ledger path(s); default: the "
                         "repo PERF_LEDGER.jsonl")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument("--bar", type=float, default=DEFAULT_BAR)
    ap.add_argument("--last", type=int, default=DEFAULT_LAST)
    ap.add_argument("--out", default=None,
                    help="capture mode: the ledger to append to")
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--rows", type=int, default=GATE_ROWS)
    args = ap.parse_intermixed_args(argv)

    if args.mode == "capture":
        if not args.out:
            print("capture requires --out", file=sys.stderr)
            return 2
        n = capture(args.out, iters=args.iters, rows=args.rows)
        print(json.dumps({"mode": "capture", "out": args.out,
                          "records": n, "ok": True}))
        return 0

    ledgers = args.ledgers or [os.path.join(REPO, "PERF_LEDGER.jsonl")]
    records = load_bench_records(ledgers)

    if args.mode == "update":
        scenarios = aggregate(records, last=args.last or None)
        env = span_diff.capture_env()
        rec_backends = {r.get("backend") for r in records} - {None}
        if rec_backends and rec_backends != {env["backend"]}:
            print(f"refusing to update: records captured on backend(s) "
                  f"{sorted(rec_backends)} but the current environment "
                  f"is {env['backend']!r} — re-run capture+update in "
                  f"one environment", file=sys.stderr)
            return 2
        write_baseline(args.baseline, scenarios, env)
        print(json.dumps({"mode": "update", "baseline": args.baseline,
                          "records": len(records), "env": env,
                          "scenarios": len(scenarios), "ok": True}))
        return 0

    if not os.path.exists(args.baseline):
        print(json.dumps({"mode": "check", "ok": True,
                          "skipped": f"no baseline at {args.baseline}"}))
        return 0
    data = load_baseline(args.baseline)
    mismatch = span_diff.env_mismatch(data.get("env"))
    if mismatch:
        print("ENVIRONMENT MISMATCH vs baseline "
              f"{os.path.basename(args.baseline)}: "
              + "; ".join(f"{k}: baseline={b!r} current={c!r}"
                          for k, (b, c) in sorted(mismatch.items()))
              + " — re-capture in this environment (capture + update)",
              file=sys.stderr)
        print(json.dumps({"mode": "check", "ok": False,
                          "env_mismatch": mismatch}))
        return EXIT_ENV_MISMATCH

    scenarios = aggregate(records, last=args.last or None)
    res = diff_scenarios(data.get("scenarios", {}), scenarios, args.bar)
    if res["calibration_saturated"]:
        # >5x-off wall: this machine/config is not comparable to the
        # baseline capture — an explicit skip, never a phantom red
        print(json.dumps({"mode": "check", "ok": True,
                          "skipped": "speed calibration saturated "
                                     f"({res['calibration']}) — "
                                     "re-capture the baseline here",
                          **res}))
        return 0
    for r in res["regressions"]:
        print(f"FRESHNESS REGRESSION {r['scenario']} {r['metric']}: "
              f"ms {r['base_ms']} -> {r['cand_ms']} "
              f"(calibrated {r['calibrated_ms']}, "
              f"{r['ratio']}x > bar {args.bar})")
    ok = not res["regressions"]
    print(json.dumps({"mode": "check", "bar": args.bar,
                      "records": len(records),
                      "scenarios_checked": len(
                          set(scenarios) & set(data.get("scenarios", {}))),
                      **res, "ok": ok}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
