"""SLO report + error-budget gate over ``query_stats`` /
``slo_status`` / ``alert`` / ``incident`` ledger records (ISSUE 17).

The SLO plane (pinot_tpu/utils/slo.py) burns per-table/tenant error
budgets over Google-SRE paired fast/slow windows and fires latched
burn-rate alerts through the generic alerting plane, snapshotting an
incident bundle on each fire. This tool replays any ledger corpus
through the SAME pure evaluator (``plan_alert_stream`` — deterministic:
the same corpus yields the same verdict byte-for-byte) and gates it:

    python tools/slo_report.py report [ledger ...] \
        [--latency-bar-ms MS] [--availability-objective F]
    python tools/slo_report.py gate   [ledger ...] \
        [--latency-bar-ms MS] [--availability-objective F] \
        [--objective F] [--burn-threshold X] [--min-events N]

``report`` prints the per-objective burn table (fast/slow burn, budget
remaining, event/bad counts) for every table in the corpus plus the
recorded slo_status/alert/incident counts, one summary JSON line last.

``gate`` is the ratchet bench_common.finish() runs as the FIFTH gate
beside span / freshness / overload / warmup: any objective whose slow-
window burn reaches the threshold — i.e. the bench corpus itself would
have paged — fails with exit 1 and ``GATE FAIL:`` lines. ``--min-events``
(default 1) guards the structurally vacuous green: a corpus with no
``query_stats`` records means the forensics plane is broken, not that
the SLOs are healthy.

``report --autopsy`` (round 25) joins each captured incident to its
``rca_verdict`` record (cluster/autopsy.py) — one command answers
"what burned and why": the verdict's top cause, an explicit
``inconclusive``, or ``pending`` when attribution hasn't landed yet.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from pinot_tpu.utils.slo import (  # noqa: E402
    DEFAULT_BURN_THRESHOLD, DEFAULT_FAST_WINDOW_S,
    DEFAULT_OBJECTIVE, DEFAULT_SLOW_WINDOW_S, plan_alert_stream)

GATE_KINDS = ("query_stats", "slo_status", "alert", "incident")


def load_records(paths: List[str],
                 kinds: tuple = GATE_KINDS) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    for path in paths:
        if not os.path.exists(path):
            continue
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and rec.get("kind") in kinds:
                    out.append(rec)
    return out


def autopsy_join(records: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """The --autopsy section rows (pure, ledger order): each captured
    incident joined to its ``rca_verdict`` by ``incident_ref`` —
    verdicts keyed last-wins, the incident discipline's (proc, seq)
    identity making re-runs supersede. ``verdict`` is the top cause, an
    explicit ``inconclusive``, or ``pending`` when attribution hasn't
    landed (recorder hook unwired / still in flight)."""
    verdicts: Dict[str, Dict[str, Any]] = {}
    for r in records:
        if r.get("kind") == "rca_verdict" and r.get("incident_ref"):
            verdicts[str(r["incident_ref"])] = r
    rows: List[Dict[str, Any]] = []
    for r in records:
        if r.get("kind") != "incident":
            continue
        iid = str(r.get("incident_id") or "")
        v = verdicts.get(iid)
        if v is None:
            status = "pending"
        elif v.get("inconclusive"):
            status = "inconclusive"
        else:
            status = str(v.get("top_cause") or "")
        top = (v.get("causes") or [{}])[0] if v else {}
        rows.append({"incident_id": iid,
                     "alert": str(r.get("alert") or ""),
                     "severity": r.get("severity"),
                     "verdict": status,
                     "score": top.get("score"),
                     "detail": top.get("detail")})
    return rows


def build_objectives(records: List[Dict[str, Any]],
                     latency_bar_ms: Optional[float],
                     availability_objective: Optional[float],
                     objective: float,
                     fast_s: float, slow_s: float,
                     burn_threshold: float) -> List[Dict[str, Any]]:
    """One declared objective per table discovered in the corpus (pure,
    sorted — the determinism contract): a latency objective when a bar
    is configured, an availability objective when a target is. Tenant
    scopes come free — plan_alert_stream scopes on both."""
    tables = sorted({str(r["table"]) for r in records
                     if r.get("kind") == "query_stats"
                     and r.get("table")})
    objs: List[Dict[str, Any]] = []
    for t in tables:
        if latency_bar_ms is not None:
            objs.append({"scope": t, "kind": "latency",
                         "bar_ms": latency_bar_ms,
                         "objective": objective,
                         "fast_s": fast_s, "slow_s": slow_s,
                         "burn_threshold": burn_threshold})
        if availability_objective is not None:
            objs.append({"scope": t, "kind": "availability",
                         "objective": availability_objective,
                         "fast_s": fast_s, "slow_s": slow_s,
                         "burn_threshold": burn_threshold})
    return objs


def summarize(records: List[Dict[str, Any]],
              objectives: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Pure records -> report dict (the oracle tests pin this): the
    replayed burn table over the query_stats corpus + the counts of
    what the live plane actually recorded. Dedupes query_stats by
    (proc-less) identity is NOT needed — the stats corpus is per-query
    and a fleet ledger stamps ``node`` without duplicating lines."""
    stats = [r for r in records if r.get("kind") == "query_stats"]
    plan = (plan_alert_stream(stats, objectives) if objectives
            else {"alerts": [], "status": []})
    recorded = {k: sum(1 for r in records if r.get("kind") == k)
                for k in ("slo_status", "alert", "incident")}
    worst = max((row["burn_slow"] for row in plan["status"]),
                default=0.0)
    return {"queries": len(stats),
            "objectives": len(objectives),
            "alerts_planned": len(plan["alerts"]),
            "status": plan["status"],
            "worst_burn_slow": worst,
            "recorded": recorded}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("mode", choices=["report", "gate"])
    ap.add_argument("ledgers", nargs="*",
                    help="ledger path(s); default: the repo "
                         "PERF_LEDGER.jsonl")
    ap.add_argument("--latency-bar-ms", type=float, default=None,
                    help="latency SLO bar in ms (omit: no latency "
                         "objective)")
    ap.add_argument("--availability-objective", type=float, default=None,
                    help="availability good-fraction target, e.g. 0.999 "
                         "(omit: no availability objective)")
    ap.add_argument("--objective", type=float, default=DEFAULT_OBJECTIVE,
                    help="latency good-fraction target "
                         "(default %(default)s — p99 <= bar)")
    ap.add_argument("--burn-threshold", type=float,
                    default=DEFAULT_BURN_THRESHOLD,
                    help="burn-rate alert threshold "
                         "(default %(default)sx)")
    ap.add_argument("--fast-s", type=float, default=DEFAULT_FAST_WINDOW_S)
    ap.add_argument("--slow-s", type=float, default=DEFAULT_SLOW_WINDOW_S)
    ap.add_argument("--min-events", type=int, default=1,
                    help="gate: minimum query_stats records for a "
                         "non-vacuous pass (default %(default)s)")
    ap.add_argument("--autopsy", action="store_true",
                    help="report: join each captured incident to its "
                         "rca_verdict (top cause / inconclusive / "
                         "pending)")
    args = ap.parse_intermixed_args(argv)

    ledgers = args.ledgers or [os.path.join(REPO, "PERF_LEDGER.jsonl")]
    kinds = GATE_KINDS + ("rca_verdict",) if args.autopsy else GATE_KINDS
    records = load_records(ledgers, kinds=kinds)
    objectives = build_objectives(
        records, args.latency_bar_ms, args.availability_objective,
        args.objective, args.fast_s, args.slow_s, args.burn_threshold)
    rep = summarize(records, objectives)

    if args.mode == "report":
        print(f"slo: {rep['queries']} queries, "
              f"{rep['objectives']} objective(s), "
              f"{rep['alerts_planned']} alert(s) would fire, "
              f"recorded {rep['recorded']}")
        for row in rep["status"]:
            print(f"  {row['scope']}/{row['kind']}: "
                  f"burn {row['burn_fast']}x/{row['burn_slow']}x "
                  f"budget {row['budget_remaining'] * 100:.1f}% "
                  f"({row['bad']}/{row['events']} bad)")
        extra: Dict[str, Any] = {}
        if args.autopsy:
            rows = autopsy_join(records)
            print(f"autopsy: {len(rows)} incident(s)")
            for row in rows:
                score = "" if row["score"] is None \
                    else f" ({row['score']})"
                print(f"  {row['incident_id']} [{row['alert']}/"
                      f"{row['severity']}]: {row['verdict']}{score}")
                if row["detail"]:
                    print(f"    {row['detail']}")
            extra["autopsy"] = {
                "incidents": len(rows),
                "attributed": sum(
                    1 for r in rows
                    if r["verdict"] not in ("pending", "inconclusive")),
                "inconclusive": sum(1 for r in rows
                                    if r["verdict"] == "inconclusive"),
                "pending": sum(1 for r in rows
                               if r["verdict"] == "pending")}
        print(json.dumps({"mode": "report", "ok": True,
                          **{k: rep[k] for k in
                             ("queries", "objectives", "alerts_planned",
                              "worst_burn_slow", "recorded")},
                          **extra}))
        return 0

    failures: List[str] = []
    if rep["queries"] < args.min_events:
        failures.append(
            f"vacuous: only {rep['queries']} query_stats record(s) "
            f"(< {args.min_events}) — forensics plane or corpus broken")
    for row in rep["status"]:
        if row["events"] and row["burn_slow"] >= args.burn_threshold:
            failures.append(
                f"{row['scope']}/{row['kind']} burned "
                f"{row['burn_slow']}x >= {args.burn_threshold}x "
                f"({row['bad']}/{row['events']} bad, budget "
                f"{row['budget_remaining'] * 100:.1f}% left)")
    for f in failures:
        print(f"GATE FAIL: {f}", file=sys.stderr)
    print(json.dumps({"mode": "gate", "ok": not failures,
                      "queries": rep["queries"],
                      "objectives": rep["objectives"],
                      "alerts_planned": rep["alerts_planned"],
                      "worst_burn_slow": rep["worst_burn_slow"],
                      "burn_threshold": args.burn_threshold,
                      "recorded": rep["recorded"],
                      "failures": failures}))
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
