#!/bin/bash
# One-shot TPU-recovery capture: phase profile of the reworked compact
# path, then the full SSB suite. Run the moment the axon tunnel answers
# (see PINOT memory: it wedges for hours; captures must be immediate).
set -u -o pipefail
cd "$(dirname "$0")/.."
echo "== backend probe =="
probe=$(timeout 120 python -c \
    "import jax; print(jax.default_backend())") || probe=""
echo "backend: ${probe:-<none>}"
if [ "$probe" != "tpu" ]; then
    echo "no TPU backend (tunnel wedged or CPU fallback); aborting" >&2
    exit 1
fi
echo "== phase profile (q2.1 q3.2 q4.3) =="
if ! timeout 2400 python tools/profile_compact.py q2.1 q3.2 q4.3 \
        | tee /tmp/profile_compact_tpu.json; then
    echo "profile failed/timed out; continuing to the capture" >&2
fi
echo "== full SSB capture =="
# budget > 13 queries x 900s worker timeout + retry headroom, and
# refuse the CPU fallback: this window exists to get CHIP numbers
if ! PINOT_BENCH_ALLOW_CPU=0 timeout 14400 python bench.py \
        | tee /tmp/bench_tpu_full.json; then
    echo "capture FAILED (see /tmp/bench_tpu_full.json)" >&2
    exit 1
fi
echo "capture complete; ledger updated"
