#!/bin/bash
# One-shot TPU-recovery capture: phase profile of the reworked compact
# path, then the full SSB suite. Run the moment the axon tunnel answers
# (see PINOT memory: it wedges for hours; captures must be immediate).
set -u
cd "$(dirname "$0")/.."
echo "== backend probe =="
if ! timeout 120 python -c "import jax; print(jax.default_backend(), len(jax.devices()))"; then
    echo "tunnel still wedged; aborting" >&2
    exit 1
fi
echo "== phase profile (q2.1 q3.2 q4.3) =="
timeout 2400 python tools/profile_compact.py q2.1 q3.2 q4.3 \
    | tee /tmp/profile_compact_tpu.json
echo "== full SSB capture =="
timeout 10800 python bench.py | tee /tmp/bench_tpu_full.json
