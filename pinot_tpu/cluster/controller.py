"""Controller: cluster metadata + declarative reconciliation.

Reference parity: pinot-controller/.../BaseControllerStarter.java:351 +
PinotHelixResourceManager (table/segment/instance CRUD) + segment
assignment strategies (helix/core/assignment/segment/) + periodic tasks
(RetentionManager, SegmentStatusChecker — BaseControllerStarter.java:
174-191). TPU-native stance (SURVEY.md section 5, distributed backend):
Helix/ZK is replaceable infrastructure, not product surface — a single
controller process owns a file-backed property store (atomic tmp+rename
JSON, the ZK property-store analog), instances announce themselves with
heartbeats (ephemeral-node analog), and a reconciliation loop converges
ideal state: every segment assigned to `replication` live servers with
minimal movement (keep surviving replicas, top up from least-loaded).
Brokers/servers poll a monotonically versioned ideal state instead of
watching ZK events.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .http_util import JsonHandler, start_http

HEARTBEAT_TIMEOUT_S = 10.0
RECONCILE_INTERVAL_S = 1.0


class Controller:
    def __init__(self, data_dir: str, port: int = 0,
                 heartbeat_timeout: float = HEARTBEAT_TIMEOUT_S,
                 reconcile_interval: float = RECONCILE_INTERVAL_S):
        self.data_dir = data_dir
        os.makedirs(data_dir, exist_ok=True)
        self._lock = threading.RLock()
        self._routing_cache: Optional[Dict[str, Any]] = None
        self.heartbeat_timeout = heartbeat_timeout
        self.reconcile_interval = reconcile_interval
        self._state: Dict[str, Any] = self._load() or {
            "version": 0,
            "tables": {},      # name -> {schema, config, replication}
            "segments": {},    # table -> {segment -> {location}}
            "assignment": {},  # table -> {segment -> [instance ids]}
        }
        self._instances: Dict[str, Dict[str, Any]] = {}  # ephemeral
        self._stop = threading.Event()
        self._httpd, self.port, _ = start_http(self._make_handler(), port)
        self._recon = threading.Thread(target=self._reconcile_loop,
                                       daemon=True)
        self._recon.start()

    # -- property store ----------------------------------------------------
    def _path(self) -> str:
        return os.path.join(self.data_dir, "cluster_state.json")

    def _load(self) -> Optional[Dict[str, Any]]:
        if os.path.exists(self._path()):
            with open(self._path()) as fh:
                return json.load(fh)
        return None

    def _persist(self) -> None:
        tmp = self._path() + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(self._state, fh, indent=1)
        os.replace(tmp, self._path())

    def _bump(self) -> None:
        self._state["version"] += 1
        self._persist()

    # -- instance registry (Helix liveness analog) -------------------------
    def register_instance(self, inst: Dict[str, Any]) -> None:
        with self._lock:
            inst = dict(inst)
            inst["lastHeartbeat"] = time.monotonic()
            self._instances[inst["id"]] = inst
            self._reconcile_locked()

    def heartbeat(self, instance_id: str) -> bool:
        with self._lock:
            inst = self._instances.get(instance_id)
            if inst is None:
                return False
            inst["lastHeartbeat"] = time.monotonic()
            return True

    def live_servers(self) -> List[str]:
        now = time.monotonic()
        return sorted(
            i["id"] for i in self._instances.values()
            if i.get("role") == "server"
            and now - i["lastHeartbeat"] <= self.heartbeat_timeout)

    # -- tables / segments -------------------------------------------------
    def add_table(self, name: str, schema: Dict[str, Any],
                  config: Optional[Dict[str, Any]] = None,
                  replication: int = 1) -> None:
        with self._lock:
            self._state["tables"][name] = {
                "schema": schema, "config": config or {},
                "replication": replication}
            self._state["segments"].setdefault(name, {})
            self._state["assignment"].setdefault(name, {})
            self._bump()

    def drop_table(self, name: str) -> None:
        with self._lock:
            for key in ("tables", "segments", "assignment"):
                self._state[key].pop(name, None)
            self._bump()

    @staticmethod
    def _read_segment_meta(location: str) -> Optional[Dict[str, Any]]:
        """Pruning metadata from the segment dir (per-column min/max +
        partitions, ZK segment-metadata analog); None when unreadable."""
        try:
            with open(os.path.join(location, "metadata.json")) as fh:
                m = json.load(fh)
        except (OSError, ValueError):
            return None
        cols = {}
        for name, cm in (m.get("columns") or {}).items():
            entry = {k: cm[k] for k in ("min", "max", "partitions")
                     if k in cm}
            if entry:
                cols[name] = entry
        return {"columns": cols, "totalDocs": m.get("totalDocs"),
                "numPartitions": m.get("numPartitions")}

    def add_segment(self, table: str, segment: str, location: str,
                    metadata: Optional[Dict[str, Any]] = None) -> None:
        with self._lock:
            if table not in self._state["tables"]:
                raise KeyError(f"table {table!r} not registered")
            prev = self._state["segments"][table].get(segment)
            if metadata is None:
                metadata = self._read_segment_meta(location)
            self._state["segments"][table][segment] = {
                "location": location, "meta": metadata}
            if prev is not None and prev.get("location") != location:
                # segment refresh/replace: assignment may be unchanged but
                # servers must re-download — force a version bump so their
                # assignment sync sees it (segment refresh message analog)
                self._bump()
            self._reconcile_locked()

    # -- assignment / reconciliation ---------------------------------------
    def _reconcile_loop(self) -> None:
        while not self._stop.wait(self.reconcile_interval):
            with self._lock:
                self._reconcile_locked()

    def _reconcile_locked(self) -> None:
        """Converge assignment: each segment on `replication` live servers,
        minimal movement (TableRebalancer analog at small scale)."""
        live = self.live_servers()
        changed = False
        load: Dict[str, int] = {s: 0 for s in live}
        for table, segs in self._state["assignment"].items():
            for seg, holders in segs.items():
                for h in holders:
                    if h in load:
                        load[h] += 1
        for table, tmeta in self._state["tables"].items():
            repl = min(tmeta.get("replication", 1), max(len(live), 1))
            assign = self._state["assignment"].setdefault(table, {})
            for seg in self._state["segments"].get(table, {}):
                holders = [h for h in assign.get(seg, []) if h in live]
                while len(holders) < repl and live:
                    candidates = [s for s in live if s not in holders]
                    if not candidates:
                        break
                    pick = min(candidates, key=lambda s: load[s])
                    holders.append(pick)
                    load[pick] += 1
                if assign.get(seg) != holders:
                    assign[seg] = holders
                    changed = True
        if changed:
            self._bump()

    # -- views -------------------------------------------------------------
    def routing_snapshot(self) -> Dict[str, Any]:
        with self._lock:
            # cache the expensive deep copy per version: brokers poll this
            # endpoint continuously and the state only changes on _bump()
            cached = self._routing_cache
            if cached is not None and cached["version"] == \
                    self._state["version"]:
                snap = dict(cached)
            else:
                snap = {
                    "version": self._state["version"],
                    "tables": {
                        t: {"schema": m["schema"], "config": m["config"]}
                        for t, m in self._state["tables"].items()},
                    "assignment": json.loads(json.dumps(
                        self._state["assignment"])),
                    "segments": json.loads(json.dumps(
                        self._state["segments"])),
                }
                self._routing_cache = snap
                snap = dict(snap)
            # liveness is heartbeat-driven, not version-driven: always fresh
            snap["instances"] = {
                i["id"]: {"host": i["host"], "port": i["port"],
                          "role": i.get("role")}
                for i in self._instances.values()}
            snap["liveServers"] = self.live_servers()
            return snap

    def server_assignment(self, instance_id: str) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Dict[str, str]] = {}
            for table, segs in self._state["assignment"].items():
                for seg, holders in segs.items():
                    if instance_id in holders:
                        loc = self._state["segments"][table][seg]["location"]
                        out.setdefault(table, {})[seg] = loc
            return {"version": self._state["version"], "tables": out,
                    "schemas": {t: m["schema"] for t, m in
                                self._state["tables"].items()}}

    # -- REST --------------------------------------------------------------
    def _make_handler(self):
        ctrl = self

        class Handler(JsonHandler):
            routes = {
                ("GET", "/health"): lambda h, b: (200, {"status": "OK"}),
                ("POST", "/instances"): lambda h, b: (
                    ctrl.register_instance(b) or (200, {"status": "OK"})),
                ("POST", "/heartbeat/"): lambda h, b: (
                    (200, {"status": "OK"})
                    if ctrl.heartbeat(h.path.rsplit("/", 1)[1])
                    else (404, {"error": "unknown instance"})),
                ("POST", "/tables"): lambda h, b: (
                    ctrl.add_table(b["name"], b["schema"],
                                   b.get("config"),
                                   b.get("replication", 1))
                    or (200, {"status": "OK"})),
                ("DELETE", "/tables/"): lambda h, b: (
                    ctrl.drop_table(h.path.rsplit("/", 1)[1])
                    or (200, {"status": "OK"})),
                ("POST", "/segments"): lambda h, b: (
                    ctrl.add_segment(b["table"], b["segment"],
                                     b["location"], b.get("metadata"))
                    or (200, {"status": "OK"})),
                ("GET", "/routing"): lambda h, b: (
                    200, ctrl.routing_snapshot()),
                ("GET", "/assignments/"): lambda h, b: (
                    200, ctrl.server_assignment(h.path.rsplit("/", 1)[1])),
            }
        return Handler

    def stop(self) -> None:
        self._stop.set()
        self._httpd.shutdown()
        self._httpd.server_close()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"
