"""Controller: cluster metadata + declarative reconciliation.

Reference parity: pinot-controller/.../BaseControllerStarter.java:351 +
PinotHelixResourceManager (table/segment/instance CRUD) + segment
assignment strategies (helix/core/assignment/segment/) + periodic tasks
(RetentionManager, SegmentStatusChecker — BaseControllerStarter.java:
174-191). TPU-native stance (SURVEY.md section 5, distributed backend):
Helix/ZK is replaceable infrastructure, not product surface — a single
controller process owns a file-backed property store (atomic tmp+rename
JSON, the ZK property-store analog), instances announce themselves with
heartbeats (ephemeral-node analog), and a reconciliation loop converges
ideal state: every segment assigned to `replication` live servers with
minimal movement (keep surviving replicas, top up from least-loaded).
Brokers/servers poll a monotonically versioned ideal state instead of
watching ZK events.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .http_util import JsonHandler, start_http


def _compile_health_snapshot() -> Dict[str, Any]:
    """Compile-plane block for /ui/data (utils/compileplane)."""
    from ..utils.compileplane import compile_health
    from ..utils.metrics import global_metrics
    return compile_health(global_metrics.snapshot())

HEARTBEAT_TIMEOUT_S = 10.0
RECONCILE_INTERVAL_S = 1.0


class Controller:
    def __init__(self, data_dir: str, port: int = 0,
                 heartbeat_timeout: float = HEARTBEAT_TIMEOUT_S,
                 reconcile_interval: float = RECONCILE_INTERVAL_S,
                 lease_ttl: Optional[float] = None,
                 instance_id: Optional[str] = None):
        """lease_ttl enables HA mode (round-5, VERDICT r4 next-step
        #10; LeadControllerManager analog): controllers sharing a
        data_dir contend for a file lease; exactly one leads (runs
        reconcile/periodic tasks, accepts writes) while the others tail
        the versioned property store and serve stale-ok reads, taking
        over within ~lease_ttl of the leader dying. lease_ttl=None is
        the classic single-node controller."""
        import uuid as _uuid

        self.data_dir = data_dir
        os.makedirs(data_dir, exist_ok=True)
        self._lock = threading.RLock()
        self._routing_cache: Optional[Dict[str, Any]] = None
        self.heartbeat_timeout = heartbeat_timeout
        self.reconcile_interval = reconcile_interval
        self.lease_ttl = lease_ttl
        self.instance_id = instance_id or f"controller_{_uuid.uuid4().hex[:8]}"
        # single-writer atomic bool BY DESIGN: the lease loop abdicates
        # without _lock (taking it would deadlock through
        # _bump->_persist) and GIL-atomic bool stores need no guard
        self.is_leader = False  # guarded-by: none
        self._recon: Optional[threading.Thread] = None
        self._state: Dict[str, Any] = self._load() or {
            "version": 0,
            "tables": {},      # name -> {schema, config, replication}
            "segments": {},    # table -> {segment -> {location, meta}}
            "assignment": {},  # table -> {segment -> [instance ids]}
            "lineage": {},     # table -> [{id, from, to, state}]
        }
        self._state.setdefault("lineage", {})
        self._instances: Dict[str, Dict[str, Any]] = {}  # ephemeral
        self._status: Dict[str, Any] = {}
        self._stop = threading.Event()
        # periodic controller tasks (BaseControllerStarter.java:174-191);
        # built before the HTTP server binds so /periodictask/* never sees
        # a half-constructed controller
        from .periodic import BasePeriodicTask, PeriodicTaskScheduler
        self.scheduler = PeriodicTaskScheduler()
        # periodic tasks are leader-gated in HA mode: an abdicated
        # controller's scheduler keeps ticking (restartability) but its
        # tasks no-op — a fenced-out epoch must never mutate the shared
        # property store or delete deep-store artifacts
        self.scheduler.register(BasePeriodicTask(
            "RetentionManager", interval_s=60.0,
            fn=self._leader_gated(self.run_retention)))
        self.scheduler.register(BasePeriodicTask(
            "SegmentStatusChecker", interval_s=30.0,
            fn=self._leader_gated(self.run_status_check)))
        # fleet forensics rollup (round 14): pull per-node ledgers,
        # aggregate cluster-wide, serve at GET /debug/fleet + the
        # webapp Fleet view. Leader-gated like every periodic task and
        # REST-triggerable (POST /periodictask/run/ForensicsRollup);
        # the initial delay keeps short-lived test controllers from
        # auto-pulling mid-setup
        from .rollup import ForensicsRollupTask
        self.rollup = ForensicsRollupTask(self)
        self.scheduler.register(BasePeriodicTask(
            ForensicsRollupTask.NAME, interval_s=30.0,
            initial_delay_s=30.0,
            fn=self._leader_gated(self.rollup.run)))
        # closed-loop rebalance (round 24): consumes the rollup's
        # slo/heat/plan_shapes blocks, moves segments when a budget
        # burns, freezes while an incident is open. Leader-gated +
        # REST-triggerable like the rollup; the initial delay sits
        # after the first rollup pass so a pass has a fleet view
        from .rebalancer import ClosedLoopRebalanceTask
        self.rebalancer = ClosedLoopRebalanceTask(self)
        self.scheduler.register(BasePeriodicTask(
            ClosedLoopRebalanceTask.NAME, interval_s=60.0,
            initial_delay_s=45.0,
            fn=self._leader_gated(self.rebalancer.run)))
        # realtime commit arbitration (SegmentCompletionManager FSM); the
        # registry fallback keeps restarts/purges from re-electing a
        # committer for an already-registered segment
        from .completion import SegmentCompletionManager

        def _registered(table: str, segment: str):
            with self._lock:
                entry = self._state["segments"].get(table, {}).get(segment)
                if entry is None:
                    return None
                meta = entry.get("meta") or {}
                return {"downloadURI": entry.get("location"),
                        "offset": meta.get("endOffset")}

        self.completion = SegmentCompletionManager(
            expected_replicas=lambda t: self._state["tables"]
            .get(t, {}).get("replication", 1),
            registered_segment=_registered)
        self._httpd, self.port, _ = start_http(self._make_handler(), port)
        if self.lease_ttl is None:
            self._become_leader()
        else:
            # one synchronous acquire attempt so constructing against a
            # free lease returns an already-leading controller; then the
            # lease loop renews / tails / takes over
            if self._try_acquire_lease():
                self._become_leader()
            self._lease_thread = threading.Thread(
                target=self._lease_loop, daemon=True)
            self._lease_thread.start()

    # -- leadership (LeadControllerManager analog) -------------------------
    def _lease_path(self) -> str:
        return os.path.join(self.data_dir, "leader.lease")

    def _read_lease(self) -> Optional[Dict[str, Any]]:
        try:
            with open(self._lease_path()) as fh:
                return json.load(fh)
        except (OSError, ValueError):
            return None

    def _write_lease(self, epoch: int) -> None:
        tmp = self._lease_path() + f".w{self.instance_id}"
        with open(tmp, "w") as fh:
            json.dump({"holder": self.instance_id, "epoch": epoch,
                       "expires": time.time() + self.lease_ttl}, fh)
        os.replace(tmp, self._lease_path())

    def _try_acquire_lease(self) -> bool:
        """Claim the lease if free/expired. A short-lived O_EXCL lock
        file serializes contenders (stale locks from a crash mid-claim
        are broken after 2x ttl)."""
        now = time.time()
        cur = self._read_lease()
        if cur and cur.get("holder") != self.instance_id \
                and cur.get("expires", 0) > now:
            return False
        lock = self._lease_path() + ".lock"
        try:
            fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            try:
                if now - os.path.getmtime(lock) > \
                        max(self.lease_ttl or 1.0, 1.0) * 2:
                    os.unlink(lock)
            except OSError:
                pass
            return False
        try:
            os.close(fd)
            cur = self._read_lease()   # re-check under the claim lock
            if cur and cur.get("holder") != self.instance_id \
                    and cur.get("expires", 0) > now:
                return False
            epoch = (cur or {}).get("epoch", 0)
            if not cur or cur.get("holder") != self.instance_id:
                epoch += 1             # fencing token: bumps on takeover
            self._write_lease(epoch)
            return True
        finally:
            try:
                os.unlink(lock)
            except OSError:
                pass

    def _leader_gated(self, fn):
        def run():
            if self.lease_ttl is not None and not self.is_leader:
                return
            fn()
        return run

    def _renew_lease(self) -> bool:
        """Renew under the same claim lock acquisition takes, re-checking
        the holder — a stalled leader must never clobber a standby's
        fresh claim or regress the fencing epoch. False -> abdicate."""
        lock = self._lease_path() + ".lock"
        try:
            fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            # a contender holds the claim lock this tick; keep leading
            # until the holder check resolves next tick
            cur = self._read_lease()
            return not cur or cur.get("holder") == self.instance_id
        try:
            os.close(fd)
            cur = self._read_lease()
            if cur and cur.get("holder") != self.instance_id:
                return False           # stolen while we stalled
            self._write_lease((cur or {}).get("epoch", 1))
            return True
        finally:
            try:
                os.unlink(lock)
            except OSError:
                pass

    def _become_leader(self) -> None:
        with self._lock:
            # the previous leader may have written newer state: reload
            fresh = self._load()
            if fresh is not None and fresh.get("version", 0) >= \
                    self._state.get("version", 0):
                self._state = fresh
                self._state.setdefault("lineage", {})
                self._routing_cache = None
            self.is_leader = True
        if self._recon is None:
            self._recon = threading.Thread(target=self._reconcile_loop,
                                           daemon=True)
            self._recon.start()
            self.scheduler.start()

    def _tail_state(self) -> None:
        """Standby read path: follow the leader's property-store writes
        so reads (routing, status, UI) serve fresh-enough snapshots."""
        fresh = self._load()
        if fresh is None:
            return
        with self._lock:
            if fresh.get("version", 0) > self._state.get("version", 0):
                self._state = fresh
                self._state.setdefault("lineage", {})
                self._routing_cache = None

    def _lease_loop(self) -> None:
        interval = max(self.lease_ttl / 3.0, 0.05)
        while not self._stop.wait(interval):
            if self.is_leader:
                if not self._renew_lease():
                    # lease stolen (e.g. long GC pause past expiry):
                    # abdicate — never act on a fenced-out epoch.
                    # is_leader is a single-writer atomic bool; taking
                    # _lock here would deadlock through _bump->_persist
                    self.is_leader = False  # jaxlint: ok unlocked-mutation
            else:
                self._tail_state()
                if self._try_acquire_lease():
                    self._become_leader()

    # -- property store ----------------------------------------------------
    def _path(self) -> str:
        return os.path.join(self.data_dir, "cluster_state.json")

    def _load(self) -> Optional[Dict[str, Any]]:
        if os.path.exists(self._path()):
            with open(self._path()) as fh:
                return json.load(fh)
        return None

    def _persist(self) -> None:
        if self.lease_ttl is not None:
            # epoch fence on the STORE, not just the lease: a stalled
            # ex-leader can keep is_leader for up to one renewal tick
            # after a takeover — re-check the lease holder immediately
            # before every write so its stale in-memory state can never
            # clobber the new leader's property store. (Review r5: the
            # lease file alone protected only itself.)
            cur = self._read_lease()
            if not self.is_leader or (
                    cur and cur.get("holder") != self.instance_id):
                # callers (_bump) hold _lock; atomic bool abdication
                self.is_leader = False  # jaxlint: ok unlocked-mutation
                return   # abdicate silently; _tail_state re-syncs reads
        tmp = self._path() + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(self._state, fh, indent=1)
        os.replace(tmp, self._path())

    def _bump(self) -> None:
        # every caller mutates _state under self._lock and bumps inside
        # the same critical section
        self._state["version"] += 1  # jaxlint: ok unlocked-mutation
        self._persist()

    # -- instance registry (Helix liveness analog) -------------------------
    def register_instance(self, inst: Dict[str, Any]) -> None:
        with self._lock:
            inst = dict(inst)
            inst["lastHeartbeat"] = time.monotonic()
            self._instances[inst["id"]] = inst
            self._reconcile_locked()

    def heartbeat(self, instance_id: str,
                  residency: Optional[Dict[str, Any]] = None) -> bool:
        """Liveness refresh; servers also piggyback their per-segment
        tier residency ({table: {segment: hot|warm|cold|cube}}, the
        HBM-tier placement signal the routing snapshot ships to
        brokers for affinity routing)."""
        with self._lock:
            inst = self._instances.get(instance_id)
            if inst is None:
                return False
            inst["lastHeartbeat"] = time.monotonic()
            if residency is not None:
                inst["residency"] = residency
            return True

    def assignment_version(self) -> int:
        """The current property-store version — piggybacked on
        heartbeat responses as an assignment epoch so brokers/servers
        converge on a flip without waiting out a poll interval (or a
        restart)."""
        with self._lock:
            return self._state["version"]

    def live_servers(self, tenant: Optional[str] = None) -> List[str]:
        """Live server instances; with tenant, only instances carrying
        that tag (tag-based tenant isolation, controller tenant mgmt)."""
        now = time.monotonic()
        out = []
        for i in self._instances.values():
            if i.get("role") != "server":
                continue
            if now - i["lastHeartbeat"] > self.heartbeat_timeout:
                continue
            if tenant is not None and tenant not in (i.get("tags") or []):
                continue
            out.append(i["id"])
        return sorted(out)

    def live_brokers(self) -> List[str]:
        """Live (heartbeat-fresh) broker instances — the reference's
        HelixExternalViewBasedQueryQuotaManager divides each table's
        QPS quota by this count, and round 14 made brokers
        register+heartbeat exactly like servers, so the routing
        snapshot can now ship it (broker/quota.py consumes it)."""
        now = time.monotonic()
        return sorted(
            i["id"] for i in self._instances.values()
            if i.get("role") == "broker"
            and now - i["lastHeartbeat"] <= self.heartbeat_timeout)

    # -- tables / segments -------------------------------------------------
    def add_table(self, name: str, schema: Dict[str, Any],
                  config: Optional[Dict[str, Any]] = None,
                  replication: int = 1) -> None:
        with self._lock:
            self._state["tables"][name] = {
                "schema": schema, "config": config or {},
                "replication": replication}
            self._state["segments"].setdefault(name, {})
            self._state["assignment"].setdefault(name, {})
            self._bump()

    def update_table_config(self, name: str,
                            config: Dict[str, Any]) -> None:
        """Replace a table's config without touching schema/replication/
        assignment (the updateTableConfig REST operation; reload then
        reconciles segments against it)."""
        with self._lock:
            if name not in self._state["tables"]:
                raise KeyError(f"table {name!r} not registered")
            self._state["tables"][name]["config"] = config or {}
            self._bump()

    @staticmethod
    def _delete_artifact(location: Optional[str]) -> None:
        """Best-effort deletion of a retired segment's bytes (local dir or
        deep-store archive via PinotFS) — dropping only the metadata would
        grow deep-store/disk unboundedly (RetentionManager deletes the
        artifacts too)."""
        if not location:
            return
        try:
            from ..spi.filesystem import fs_for_uri
            fs, path = fs_for_uri(location)
            fs.delete(path, force=True)
        except Exception:
            pass  # unreachable store: metadata removal still wins

    def drop_table(self, name: str) -> None:
        with self._lock:
            for key in ("tables", "segments", "assignment", "lineage"):
                self._state[key].pop(name, None)
            self._bump()
        # outside self._lock: segmentCommitEnd nests completion._lock ->
        # self._lock (register), so nesting the other way here would be
        # an ABBA deadlock
        self.completion.drop_table(name)

    @staticmethod
    def _read_segment_meta(location: str) -> Optional[Dict[str, Any]]:
        """Pruning metadata from the segment dir (per-column min/max +
        partitions, ZK segment-metadata analog); None when unreadable."""
        from .deepstore import pruning_metadata
        return pruning_metadata(location)

    def add_segment(self, table: str, segment: str, location: str,
                    metadata: Optional[Dict[str, Any]] = None) -> None:
        with self._lock:
            if table not in self._state["tables"]:
                raise KeyError(f"table {table!r} not registered")
            prev = self._state["segments"][table].get(segment)
            if metadata is None:
                metadata = self._read_segment_meta(location)
            self._state["segments"][table][segment] = {
                "location": location, "meta": metadata}
            if prev is not None and prev.get("location") != location:
                # segment refresh/replace: assignment may be unchanged but
                # servers must re-download — force a version bump so their
                # assignment sync sees it (segment refresh message analog)
                self._bump()
            self._reconcile_locked()

    # -- assignment / reconciliation ---------------------------------------
    def _reconcile_loop(self) -> None:
        while not self._stop.wait(self.reconcile_interval):
            if self.lease_ttl is not None and not self.is_leader:
                continue   # abdicated: a fenced-out epoch must not act
            with self._lock:
                self._reconcile_locked()

    def _table_tenant(self, table: str) -> Optional[str]:
        cfg = self._state["tables"].get(table, {}).get("config") or {}
        return cfg.get("serverTenant")

    def _segment_tier_tag(self, table: str, segment: str) -> Optional[str]:
        """First age-matching tier's server tag, else None (stay on the
        tenant). TierFactory TIME segmentSelector analog: age is measured
        from the segment's creationTimeMs metadata."""
        cfg = self._state["tables"].get(table, {}).get("config") or {}
        tiers = cfg.get("tiers") or []
        if not tiers:
            return None
        meta = (self._state["segments"].get(table, {}).get(segment)
                or {}).get("meta") or {}
        created_ms = meta.get("creationTimeMs")
        if created_ms is None:
            return None
        age = time.time() - created_ms / 1e3
        for t in tiers:
            if age >= float(t.get("segmentAgeSeconds", float("inf"))):
                return t.get("serverTag")
        return None

    def _segment_live(self, table: str, segment: str,
                      tenant_live: List[str],
                      tag_cache: Optional[Dict[str, List[str]]] = None
                      ) -> List[str]:
        tag = self._segment_tier_tag(table, segment)
        if tag is None:
            return tenant_live
        if tag_cache is not None and tag in tag_cache:
            tier_live = tag_cache[tag]
        else:
            tier_live = self.live_servers(tag)
            if tag_cache is not None:
                tag_cache[tag] = tier_live
        # a tier with zero live servers must not unassign the segment:
        # availability beats placement policy (the reference likewise
        # keeps serving from the current tier until the target has hosts)
        return tier_live if tier_live else tenant_live

    def _reconcile_locked(self) -> None:
        """Converge assignment: each segment on `replication` live servers
        of the table's tenant, minimal movement (TableRebalancer analog at
        small scale)."""
        changed = False
        all_live = self.live_servers()
        load: Dict[str, int] = {s: 0 for s in all_live}
        for table, segs in self._state["assignment"].items():
            for seg, holders in segs.items():
                for h in holders:
                    if h in load:
                        load[h] += 1
        tag_cache: Dict[str, List[str]] = {}
        for table, tmeta in self._state["tables"].items():
            tenant_live = self.live_servers(self._table_tenant(table))
            assign = self._state["assignment"].setdefault(table, {})
            for seg in self._state["segments"].get(table, {}):
                # tier selection may narrow the candidates to the tier
                # tag's servers (age-based tiered storage)
                live = self._segment_live(table, seg, tenant_live,
                                          tag_cache)
                repl = min(tmeta.get("replication", 1), max(len(live), 1))
                cur = assign.get(seg, [])
                holders = [h for h in cur if h in live]
                while len(holders) < repl and live:
                    candidates = [s for s in live if s not in holders]
                    if not candidates:
                        break
                    pick = min(candidates, key=lambda s: load.get(s, 0))
                    holders.append(pick)
                    load[pick] = load.get(pick, 0) + 1
                if any(h not in cur for h in holders):
                    # migration in flight (tier move / replacement): keep
                    # prior live holders serving until the next tick, when
                    # the new targets have had a poll+download cycle —
                    # approximation of the reference's external-view
                    # gating (routing only advertises ONLINE replicas)
                    for h in cur:
                        if h in all_live and h not in holders:
                            holders.append(h)
                if assign.get(seg) != holders:
                    assign[seg] = holders
                    changed = True
        if changed:
            self._bump()

    # -- rebalance (TableRebalancer analog) --------------------------------
    def rebalance(self, table: str, dry_run: bool = False,
                  replication: Optional[int] = None) -> Dict[str, Any]:
        """Recompute a balanced assignment with minimal movement: keep
        surviving replicas, move only what load-balance requires. Returns
        the before/after diff (rebalance observer analog); applies unless
        dry_run."""
        with self._lock:
            if table not in self._state["tables"]:
                raise KeyError(f"table {table!r} not registered")
            live = self.live_servers(self._table_tenant(table))
            # tiered segments may be placeable even when the tenant has no
            # live servers (and vice versa): gate and cap on the union
            cfg = self._state["tables"][table].get("config") or {}
            tag_cache: Dict[str, List[str]] = {}
            for t in cfg.get("tiers") or []:
                tag = t.get("serverTag")
                if tag is not None and tag not in tag_cache:
                    tag_cache[tag] = self.live_servers(tag)
            union = list(dict.fromkeys(
                live + [s for ls in tag_cache.values() for s in ls]))
            if not union:
                return {"status": "NO_SERVERS", "table": table}
            if replication is None:
                replication = self._state["tables"][table].get(
                    "replication", 1)
            elif not dry_run:
                # a dry run must not change cluster state
                self._state["tables"][table]["replication"] = replication
            repl = min(replication, len(union))
            segs = sorted(self._state["segments"].get(table, {}))
            current = {s: list(self._state["assignment"]
                               .get(table, {}).get(s, []))
                       for s in segs}
            # target load per server for THIS table
            total = len(segs) * repl
            cap = -(-total // len(union))  # ceil
            load = {s: 0 for s in union}
            target: Dict[str, List[str]] = {}
            moved = 0
            # per-segment candidates honor tier placement, exactly like
            # the reconcile loop (a rebalance must not undo tiering)
            seg_live = {s: self._segment_live(table, s, live, tag_cache)
                        for s in segs}
            # pass 1: keep current holders that are candidates, under cap
            for seg in segs:
                kept = []
                for h in current[seg]:
                    if h in seg_live[seg] and load.get(h, 0) < cap \
                            and len(kept) < repl:
                        kept.append(h)
                        load[h] = load.get(h, 0) + 1
                target[seg] = kept
            # pass 2: top up from least-loaded candidates
            for seg in segs:
                while len(target[seg]) < min(repl, len(seg_live[seg])):
                    cands = [s for s in seg_live[seg]
                             if s not in target[seg]]
                    if not cands:
                        break
                    pick = min(cands, key=lambda s: load.get(s, 0))
                    target[seg].append(pick)
                    load[pick] = load.get(pick, 0) + 1
                    if pick not in current[seg]:
                        moved += 1
            result = {
                "status": "DRY_RUN" if dry_run else "DONE",
                "table": table,
                "segmentsMoved": moved,
                "numSegments": len(segs),
                "replication": repl,
                "serverLoad": load,
            }
            if not dry_run:
                if self._state["assignment"].get(table) != target:
                    self._state["assignment"][table] = target
                    self._bump()
            return result

    # -- retention (RetentionManager analog) -------------------------------
    _UNIT_MS = {"MILLISECONDS": 1, "SECONDS": 1_000, "MINUTES": 60_000,
                "HOURS": 3_600_000, "DAYS": 86_400_000}

    def run_retention(self) -> None:
        """Drop segments older than the table's retention, judged by the
        time column's max value in segment metadata. Artifact deletion
        (deep-store I/O) happens after the lock is released — a hung
        store must not stall the control plane."""
        now_ms = time.time() * 1e3
        retired: List[Optional[str]] = []
        with self._lock:
            changed = False
            for table, tmeta in list(self._state["tables"].items()):
                cfg = tmeta.get("config") or {}
                value = cfg.get("retentionValue")
                tcol = cfg.get("timeColumn")
                if not value or not tcol:
                    continue
                unit_ms = self._UNIT_MS.get(
                    str(cfg.get("retentionUnit", "DAYS")).upper(), 86_400_000)
                tcol_ms = self._UNIT_MS.get(
                    str(cfg.get("timeUnit", "MILLISECONDS")).upper(), 1)
                cutoff_ms = now_ms - float(value) * unit_ms
                for seg, entry in list(
                        self._state["segments"].get(table, {}).items()):
                    cm = ((entry.get("meta") or {}).get("columns")
                          or {}).get(tcol)
                    if cm is None or cm.get("max") is None:
                        continue
                    if float(cm["max"]) * tcol_ms < cutoff_ms:
                        entry = self._state["segments"][table].pop(
                            seg, None)
                        self._state["assignment"].get(table, {}).pop(
                            seg, None)
                        retired.append((entry or {}).get("location"))
                        changed = True
            if changed:
                self._bump()
        for loc in retired:
            self._delete_artifact(loc)

    # -- status checker (SegmentStatusChecker analog) ----------------------
    def run_status_check(self) -> None:
        with self._lock:
            live = set(self.live_servers())
            out: Dict[str, Any] = {}
            for table, tmeta in self._state["tables"].items():
                repl = tmeta.get("replication", 1)
                segs = self._state["segments"].get(table, {})
                assign = self._state["assignment"].get(table, {})
                unassigned = sum(
                    1 for s in segs
                    if not [h for h in assign.get(s, []) if h in live])
                under = sum(
                    1 for s in segs
                    if 0 < len([h for h in assign.get(s, []) if h in live])
                    < repl)
                out[table] = {
                    "numSegments": len(segs),
                    "numUnassigned": unassigned,
                    "numUnderReplicated": under,
                    "healthy": unassigned == 0,
                }
            self._status = out

    # -- segment lineage (replace/merge atomicity) -------------------------
    def start_replace_segments(self, table: str, from_segs: List[str],
                               to_segs: List[str]) -> str:
        """Begin an atomic segment swap (SegmentLineage IN_PROGRESS):
        the new segments stay invisible to routing until the end call."""
        import uuid as _uuid
        with self._lock:
            if table not in self._state["tables"]:
                raise KeyError(f"table {table!r} not registered")
            entry_id = _uuid.uuid4().hex[:12]
            self._state["lineage"].setdefault(table, []).append({
                "id": entry_id, "from": list(from_segs),
                "to": list(to_segs), "state": "IN_PROGRESS",
            })
            self._bump()
            return entry_id

    def _retire_lineage_segments(self, table: str, entry_id: str,
                                 from_state: str, to_state: str,
                                 seg_key: str, reconcile: bool) -> None:
        retired: List[Optional[str]] = []
        with self._lock:
            for e in self._state["lineage"].get(table, []):
                if e["id"] == entry_id and e["state"] == from_state:
                    e["state"] = to_state
                    for seg in e[seg_key]:
                        entry = self._state["segments"].get(
                            table, {}).pop(seg, None)
                        self._state["assignment"].get(table, {}).pop(
                            seg, None)
                        retired.append((entry or {}).get("location"))
                    if reconcile:
                        self._reconcile_locked()
                    self._bump()
                    break
            else:
                raise KeyError(
                    f"no {from_state} lineage entry {entry_id!r}")
        for loc in retired:  # deep-store I/O outside the lock
            self._delete_artifact(loc)

    def end_replace_segments(self, table: str, entry_id: str) -> None:
        """Flip the lineage entry to COMPLETED: new segments become
        routable, replaced ones are removed, atomically (one version
        bump). Removal (not permanent name exclusion) keeps replaced
        segment names reusable by later uploads."""
        self._retire_lineage_segments(table, entry_id, "IN_PROGRESS",
                                      "COMPLETED", "from", reconcile=True)

    def revert_replace_segments(self, table: str, entry_id: str) -> None:
        self._retire_lineage_segments(table, entry_id, "IN_PROGRESS",
                                      "REVERTED", "to", reconcile=False)

    def _excluded_segments(self, table: str) -> set:
        """Segments hidden from routing by lineage state. Only IN_PROGRESS
        "to" segments are hidden (resident on servers but not routable
        until the flip); COMPLETED/REVERTED entries already removed their
        dead segments, so finished entries never blacklist a name."""
        out: set = set()
        for e in self._state["lineage"].get(table, []):
            if e["state"] == "IN_PROGRESS":
                out.update(e["to"])
        return out

    # -- views -------------------------------------------------------------
    # -- admin REST reads (pinot-controller/.../api/resources analog) -----
    def admin_tables(self) -> Dict[str, Any]:
        with self._lock:
            return {"tables": [
                {"name": t, "replication": m.get("replication", 1),
                 "segments": len(self._state["segments"].get(t, {})),
                 "serverTenant": (m.get("config") or {})
                 .get("serverTenant")}
                for t, m in self._state["tables"].items()]}

    def admin_table(self, name: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            m = self._state["tables"].get(name)
            if m is None:
                return None
            return {"name": name, "schema": m["schema"],
                    "config": m.get("config"),
                    "replication": m.get("replication", 1),
                    "segments": sorted(
                        self._state["segments"].get(name, {})),
                    "assignment": dict(
                        self._state["assignment"].get(name, {})),
                    "lineage": list(self._state["lineage"].get(name, []))}

    def admin_segments(self, table: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            segs = self._state["segments"].get(table)
            if segs is None:
                return None
            asn = self._state["assignment"].get(table, {})
            return {"table": table, "segments": {
                s: {"location": e.get("location"),
                    "metadata": e.get("meta"),
                    "servers": list(asn.get(s, []))}
                for s, e in segs.items()}}

    def admin_instances(self) -> Dict[str, Any]:
        now = time.monotonic()
        with self._lock:
            return {"instances": sorted(({
                "id": i["id"], "role": i.get("role"),
                "host": i.get("host"), "port": i.get("port"),
                "tags": i.get("tags") or [],
                "lastHeartbeatSecondsAgo":
                    round(now - i["lastHeartbeat"], 1),
                "live": now - i["lastHeartbeat"]
                    <= self.heartbeat_timeout}
                for i in self._instances.values()),
                key=lambda x: x["id"])}

    def admin_leadership(self) -> Dict[str, Any]:
        return {"haEnabled": self.lease_ttl is not None,
                "isLeader": self.is_leader,
                "instanceId": self.instance_id,
                "lease": self._read_lease()}

    def _delete_segment_route(self, path: str):
        """Route adapter for DELETE /segments/{table}/{segment}:
        malformed paths and unknown names are routine 404s, never 500s
        (consistent with the GET admin endpoints)."""
        parts = [p for p in path.split("?")[0].split("/") if p]
        if len(parts) != 3 or parts[0] != "segments":
            return 404, {"error": "expected /segments/{table}/{segment}"}
        try:
            self.delete_segment(parts[1], parts[2])
        except KeyError as e:
            return 404, {"error": str(e).strip("'")}
        return 200, {"status": "OK"}

    def delete_segment(self, table: str, segment: str) -> None:
        """Admin segment drop: metadata + assignment + artifact
        (PinotSegmentRestletResource delete analog)."""
        with self._lock:
            entry = self._state["segments"].get(table, {}).pop(segment,
                                                               None)
            if entry is None:
                raise KeyError(f"unknown segment {table}/{segment}")
            self._state["assignment"].get(table, {}).pop(segment, None)
            self._bump()
        self._delete_artifact(entry.get("location"))

    def ui_data(self) -> Dict[str, Any]:
        """The web app's cluster snapshot (GET /ui/data, and the
        server-side hydration seed inlined into GET /ui)."""
        now = time.monotonic()
        with self._lock:
            instances = {
                i["id"]: {"live": now - i["lastHeartbeat"]
                          <= self.heartbeat_timeout,
                          "tags": i.get("tags") or [],
                          "role": i.get("role"),
                          "host": (f"{i.get('host')}:{i.get('port')}"
                                   if i.get("host") else "")}
                for i in self._instances.values()}
            tables = {
                t: {"replication": m.get("replication", 1),
                    "tenant": (m.get("config") or {}).get("serverTenant"),
                    "segments": sorted(
                        self._state["segments"].get(t, {})),
                    "assignment": {
                        s: list(h) for s, h in
                        self._state["assignment"].get(t, {}).items()}}
                for t, m in self._state["tables"].items()}
            version = self._state["version"]
        lease = self._read_lease() or {}
        tasks = {t["name"]: {k: v for k, v in t.items() if k != "name"}
                 for t in self.scheduler.status()}
        from ..utils.metrics import global_metrics, ingest_health
        return {"version": version, "instances": instances,
                "tables": tables, "tasks": tasks,
                "instance_id": self.instance_id,
                "leader": (self.instance_id if self.is_leader
                           else lease.get("holder")),
                "lease_holder": lease.get("holder"),
                # realtime-plane health next to the cluster view (shared
                # global_metrics for in-process roles)
                "ingest": ingest_health(global_metrics.snapshot()),
                # compile-plane warmup debt + storm alerts (ISSUE 15;
                # in-process roles share global_metrics — a standalone
                # controller reports zeros)
                "compile": _compile_health_snapshot(),
                # fleet forensics rollup (webapp Fleet view): the latest
                # ForensicsRollup pass, None until one has run
                "fleet": self.rollup.snapshot(),
                # closed-loop rebalance moves ring (Fleet view panel
                # beside the SLO budgets table)
                "rebalance": self.rebalancer.snapshot(limit=20)}

    def ui_page(self) -> str:
        """The controller web application (GET /ui): the reference's
        React cluster manager (pinot-controller/src/main/resources/app)
        as one server-bootstrapped single-page app — cluster/tables/
        tasks/query-console views hydrated from the inlined snapshot,
        live-refreshing from /ui/data (cluster/webapp.py)."""
        from .webapp import render_app
        return render_app(self.ui_data())

    def routing_snapshot(self) -> Dict[str, Any]:
        with self._lock:
            # cache the expensive deep copy per version: brokers poll this
            # endpoint continuously and the state only changes on _bump()
            cached = self._routing_cache
            if cached is not None and cached["version"] == \
                    self._state["version"]:
                snap = dict(cached)
            else:
                assignment = json.loads(json.dumps(
                    self._state["assignment"]))
                segments = json.loads(json.dumps(self._state["segments"]))
                for table in list(assignment):
                    hidden = self._excluded_segments(table)
                    if hidden:
                        assignment[table] = {
                            s: h for s, h in assignment[table].items()
                            if s not in hidden}
                        segments[table] = {
                            s: e for s, e in segments.get(table,
                                                          {}).items()
                            if s not in hidden}
                snap = {
                    "version": self._state["version"],
                    "tables": {
                        t: {"schema": m["schema"], "config": m["config"]}
                        for t, m in self._state["tables"].items()},
                    "assignment": assignment,
                    "segments": segments,
                }
                self._routing_cache = snap
                snap = dict(snap)
            # liveness is heartbeat-driven, not version-driven: always
            # fresh — residency (the HBM-tier placement signal) rides
            # the same path because it changes with every query, not
            # with the assignment version
            snap["instances"] = {
                i["id"]: {"host": i["host"], "port": i["port"],
                          "role": i.get("role"),
                          **({"residency": i["residency"]}
                             if i.get("residency") else {})}
                for i in self._instances.values()}
            snap["liveServers"] = self.live_servers()
            snap["liveBrokers"] = self.live_brokers()
            return snap

    def server_assignment(self, instance_id: str) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Dict[str, str]] = {}
            for table, segs in self._state["assignment"].items():
                # servers DO load IN_PROGRESS lineage "to" segments (they
                # must be resident before the atomic flip makes them
                # routable); replaced/reverted segments are already gone
                # from the assignment itself
                for seg, holders in segs.items():
                    if instance_id not in holders:
                        continue
                    entry = self._state["segments"][table].get(seg)
                    if entry is not None:
                        out.setdefault(table, {})[seg] = entry["location"]
            return {"version": self._state["version"], "tables": out,
                    "schemas": {t: m["schema"] for t, m in
                                self._state["tables"].items()}}

    # -- REST --------------------------------------------------------------
    def _make_handler(self):
        ctrl = self

        def guard(fn):
            """HA mode: writes only land on the lease holder — a
            standby answers 503 so clients retry/repoint instead of
            split-braining the property store."""
            def wrapped(h, b):
                if ctrl.lease_ttl is not None and not ctrl.is_leader:
                    return 503, {"error": "not leader",
                                 "leader": (ctrl._read_lease() or {})
                                 .get("holder")}
                return fn(h, b)
            return wrapped

        def _debug_index(c):
            from .forensics import debug_index
            return debug_index(
                getattr(c, "instance_id", "controller"), "controller",
                surfaces=("/debug/fleet", "/debug/incidents",
                          "/debug/rebalance"))

        def _incidents():
            from ..utils.slo import global_incidents
            return global_incidents.snapshot()

        def _autopsy(h):
            # on-demand fleet autopsy (round 25): the controller keeps
            # no verdict ring of its own — it plans over the rollup's
            # fleet ledger, where the brokers' rca_verdict records and
            # all cross-plane evidence already land. ?qid= runs the
            # per-query whydown lane instead.
            from urllib.parse import parse_qs, urlparse
            from .autopsy import load_corpus, plan_autopsy, whydown
            params = parse_qs(urlparse(h.path).query)
            corpus = load_corpus(ctrl.rollup.ledger_path)
            qid = (params.get("qid") or [None])[0]
            if qid:
                return whydown(corpus, qid=qid)
            return plan_autopsy(corpus)

        class Handler(JsonHandler):
            routes = {
                ("GET", "/ui"): lambda h, b: (
                    200, ("text/html", ctrl.ui_page())),
                ("GET", "/ui/data"): lambda h, b: (200, ctrl.ui_data()),
                ("GET", "/health"): lambda h, b: (200, {"status": "OK"}),
                ("POST", "/instances"): lambda h, b: (
                    ctrl.register_instance(b) or (200, {"status": "OK"})),
                # heartbeat responses carry the assignment-version
                # epoch (round 24): a broker/server whose routing is
                # behind re-syncs immediately instead of waiting out
                # its poll — rebalance cutovers converge in one
                # heartbeat interval without restarts
                ("POST", "/heartbeat/"): lambda h, b: (
                    (200, {"status": "OK",
                           "version": ctrl.assignment_version()})
                    if ctrl.heartbeat(h.path.rsplit("/", 1)[1],
                                      (b or {}).get("residency"))
                    else (404, {"error": "unknown instance"})),
                ("POST", "/tables"): lambda h, b: (
                    ctrl.add_table(b["name"], b["schema"],
                                   b.get("config"),
                                   b.get("replication", 1))
                    or (200, {"status": "OK"})),
                ("DELETE", "/tables/"): lambda h, b: (
                    ctrl.drop_table(h.path.rsplit("/", 1)[1])
                    or (200, {"status": "OK"})),
                ("POST", "/tableconfig/"): lambda h, b: (
                    ctrl.update_table_config(
                        h.path.rsplit("/", 1)[1], b)
                    or (200, {"status": "OK"})),
                ("POST", "/segments"): lambda h, b: (
                    ctrl.add_segment(b["table"], b["segment"],
                                     b["location"], b.get("metadata"))
                    or (200, {"status": "OK"})),
                ("GET", "/routing"): lambda h, b: (
                    200, ctrl.routing_snapshot()),
                ("GET", "/assignments/"): lambda h, b: (
                    200, ctrl.server_assignment(h.path.rsplit("/", 1)[1])),
                ("POST", "/rebalance/"): lambda h, b: (
                    200, ctrl.rebalance(
                        h.path.rsplit("/", 1)[1],
                        dry_run=bool((b or {}).get("dryRun")),
                        replication=(b or {}).get("replication"))),
                ("POST", "/lineage/start"): lambda h, b: (
                    200, {"entryId": ctrl.start_replace_segments(
                        b["table"], b["from"], b["to"])}),
                ("POST", "/lineage/end"): lambda h, b: (
                    ctrl.end_replace_segments(b["table"], b["entryId"])
                    or (200, {"status": "OK"})),
                ("POST", "/lineage/revert"): lambda h, b: (
                    ctrl.revert_replace_segments(b["table"], b["entryId"])
                    or (200, {"status": "OK"})),
                ("POST", "/periodictask/run/"): lambda h, b: (
                    (200, {"status": "OK"})
                    if ctrl.scheduler.trigger(h.path.rsplit("/", 1)[1])
                    else (404, {"error": "unknown task"})),
                ("GET", "/periodictask/status"): lambda h, b: (
                    200, {"tasks": ctrl.scheduler.status()}),
                # fleet forensics rollup plane (round 14)
                ("GET", "/debug/fleet"): lambda h, b: (
                    200, ctrl.rollup.snapshot()),
                # debug-surface index + incident ring (ISSUE 17): the
                # controller serves the fleet view, not node ledgers —
                # its index says so instead of advertising 404s
                ("GET", "/debug"): lambda h, b: (
                    200, _debug_index(ctrl)),
                ("GET", "/debug/incidents"): lambda h, b: (
                    200, _incidents()),
                # incident autopsy plane (round 25): fleet-wide
                # root-cause verdict on demand (cluster/autopsy.py)
                ("GET", "/debug/autopsy"): lambda h, b: (
                    200, _autopsy(h)),
                # closed-loop rebalance audit ring (round 24)
                ("GET", "/debug/rebalance"): lambda h, b: (
                    200, ctrl.rebalancer.snapshot()),
                ("POST", "/segmentConsumed"): lambda h, b: (
                    200, ctrl.completion.segment_consumed(
                        b["table"], b["segment"], b["server"],
                        int(b["offset"]))),
                ("POST", "/segmentCommitStart"): lambda h, b: (
                    200, ctrl.completion.segment_commit_start(
                        b["table"], b["segment"], b["server"])),
                ("POST", "/segmentCommitEnd"): lambda h, b: (
                    200, ctrl.completion.segment_commit_end(
                        b["table"], b["segment"], b["server"],
                        b["downloadURI"],
                        register=lambda: ctrl.add_segment(
                            b["table"], b["segment"], b["downloadURI"],
                            b.get("metadata")))),
                ("GET", "/status"): lambda h, b: (
                    ctrl.run_status_check() or (200, ctrl._status)),
                # admin REST reads (controller/api/resources analog)
                ("GET", "/tables"): lambda h, b: (
                    200, ctrl.admin_tables()),
                ("GET", "/tables/"): lambda h, b: (
                    (lambda t: (200, t) if t is not None else
                     (404, {"error": "unknown table"}))(
                        ctrl.admin_table(h.path.rsplit("/", 1)[1]))),
                ("GET", "/segments/"): lambda h, b: (
                    (lambda t: (200, t) if t is not None else
                     (404, {"error": "unknown table"}))(
                        ctrl.admin_segments(h.path.rsplit("/", 1)[1]))),
                ("GET", "/instances"): lambda h, b: (
                    200, ctrl.admin_instances()),
                ("GET", "/leadership"): lambda h, b: (
                    200, ctrl.admin_leadership()),
                # readiness for HA deployments: 200 only on the lease
                # holder, so a k8s Service readiness probe routes
                # clients to the leader (deploy/k8s.yaml)
                ("GET", "/health/leader"): lambda h, b: (
                    (200, {"status": "LEADER"})
                    if ctrl.lease_ttl is None or ctrl.is_leader
                    else (503, {"status": "STANDBY"})),
                ("DELETE", "/segments/"): lambda h, b: (
                    ctrl._delete_segment_route(h.path)),
            }

        Handler.routes = {k: (v if k[0] == "GET" else guard(v))
                          for k, v in Handler.routes.items()}
        return Handler

    def stop(self, release_lease: bool = True) -> None:
        """release_lease=False simulates a crash: the lease expires
        naturally and the standby takes over after ~lease_ttl (tests);
        the default deletes the lease for an immediate handoff."""
        self._stop.set()
        self.scheduler.stop()
        if self.lease_ttl is not None and release_lease and self.is_leader:
            cur = self._read_lease()
            if cur and cur.get("holder") == self.instance_id:
                try:
                    os.unlink(self._lease_path())
                except OSError:
                    pass
        # shutdown path: lease thread already stopped, atomic bool store
        self.is_leader = False  # jaxlint: ok unlocked-mutation
        self._httpd.shutdown()
        self._httpd.server_close()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"
