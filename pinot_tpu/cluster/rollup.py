"""Fleet forensics rollup plane: controller-pulled ledger aggregation.

ROADMAP direction 5(c): the forensics plane (rounds 7/10/12) lands
sampled ``query_trace`` / ``query_stats`` / ``ingest_stats`` records in
PER-NODE JSONL files, so nothing could trend a whole fleet. This module
closes that loop on the controller, the cluster's single pane of glass:

- ``ForensicsRollupTask`` (a ``cluster/periodic.py`` task, leader-gated
  in HA mode, REST-triggerable via ``POST /periodictask/run/
  ForensicsRollup``) pulls ``GET /debug/ledger?since=<seq>`` deltas
  from every live broker/server, re-validates each record through the
  ``utils/ledger.py`` contracts, stamps it with its source ``node`` and
  appends it to the controller-side FLEET ledger. A dead or partitioned
  node is skipped and counted — a bounded per-node timeout means one
  wedged node can never wedge the pull. Per-node cursors persist next
  to the fleet ledger (atomic tmp+rename, the property-store idiom) so
  a controller restart never re-ships already-pulled records.
- Each pass aggregates the fleet ledger into a validated
  ``fleet_rollup`` record: per-table fleet stats (query counts, QPS,
  p50/p99 wall ms, partial/failover/hedge/batched ratios, worst-table
  ingest freshness), a hot-segment heat ranking, the slowest fleet
  queries, and per-node drift/batching/device-memory blocks with
  unique-process fleet totals (in-process clusters share one metrics
  registry per process — node blocks dedupe by the ``proc`` token
  before summing, or totals would multiply-count).
- Served at controller ``GET /debug/fleet`` and rendered as the
  webapp's Fleet view; ``tools/span_diff.py check --fleet`` trends the
  aggregated ``query_trace`` corpus with per-node speed calibration.

The aggregation functions are pure record->dict math, exported for the
oracle tests (tests/test_fleet_forensics.py).
"""
from __future__ import annotations

import calendar
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ..utils import ledger as uledger
from ..utils.metrics import global_metrics
from .http_util import http_json

PULL_TIMEOUT_S = 3.0
HEAT_TOP = 20
SLOW_TOP = 10
# aggregation window: the per-pass stats re-aggregate over an in-memory
# deque of the newest N fleet records (fed incrementally by each pull;
# loaded from the fleet ledger once at startup), so a long-lived
# controller's pass cost stays bounded instead of re-reading an
# ever-growing file every 30 s. Exactness holds up to the window; a
# clipped pass says so in the record (``window_clipped``).
AGG_WINDOW = 20_000

# the per-node counter subset the rollup carries (drift/requantize,
# retraces, scatter health, batching) — full snapshots stay on the nodes
NODE_COUNTER_KEYS = (
    "selectivity_drift_detected", "selectivity_drift_requantized",
    "selectivity_drift_recompiles", "plan_cache_retraces",
    "plan_cache_expected_recompiles", "scatter_failovers",
    "scatter_hedges", "scatter_partial_responses",
    "scatter_server_errors", "batched_dispatches", "batched_queries",
    "fused_dispatch_errors", "cube_cache_hits", "cube_cache_misses",
    "sampled_traces", "faults_fired",
    # HBM tier (engine/tier.py): paid uploads / budget demotions /
    # affinity-routed avoided uploads
    "tier_promotions", "tier_demotions", "tier_affinity_hits",
    # compile-plane warmup debt (utils/compileplane, ISSUE 15)
    "compiles_total", "compiles_retrace", "compiles_lru_evict_rebuild",
    "compile_ms_total", "compile_storm_alerts",
)
PLAN_SHAPE_TOP = 20


from ..utils.stats import pctl as _pctl  # noqa: E402 — the ONE fleet
# percentile definition (utils/metrics snapshots + engine/loadgen
# ingest-bench percentiles share it so trend lines stay comparable)


def _ts_epoch(ts: Any) -> Optional[float]:
    """Ledger envelope ts ("%Y-%m-%dT%H:%M:%SZ", UTC) -> epoch seconds
    (None when unparseable — legacy/hand-edited lines must not kill a
    rollup pass)."""
    try:
        return calendar.timegm(time.strptime(str(ts),
                                             "%Y-%m-%dT%H:%M:%SZ"))
    except (ValueError, TypeError):
        return None


def aggregate_tables(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fleet per-table stats over ``query_stats`` + ``ingest_stats``
    records (the pulled, node-stamped fleet-ledger corpus).

    ``queries`` is the exact record count per table — the chaos gate
    asserts it equals the sum of the surviving brokers' own ledgers.
    QPS is queries over the observed ts window (1 s envelope
    resolution, floored at 1 s — a burst inside one second reads as
    n/1). Percentiles use the registry definition (_pctl)."""
    acc: Dict[str, Dict[str, Any]] = {}
    for rec in records:
        if rec.get("kind") != "query_stats":
            continue
        t = rec.get("table") or "<unknown>"
        e = acc.setdefault(t, {
            "queries": 0, "errors": 0, "partial": 0, "slow": 0,
            "traced": 0, "failovers": 0, "hedges": 0, "batched": 0,
            "batched_queries": 0, "rows": 0, "shed": 0,
            "shed_by_tenant": {}, "walls": [],
            "t_min": None, "t_max": None})
        e["queries"] += 1
        if rec.get("shed"):
            # overload plane (ISSUE 12): fleet-wide shed-rate trend
            # lines per table and per tenant. Shed rows are counted in
            # ``queries`` (the chaos gate's exactness contract) but
            # EXCLUDED from the latency walls: a shed is rejected at
            # admission in sub-ms, and folding those into p50/p99
            # would mask the latency regression exactly during the
            # overload the shed counters are reporting.
            e["shed"] += 1
            tn = rec.get("tenant") or "default"
            e["shed_by_tenant"][tn] = e["shed_by_tenant"].get(tn, 0) + 1
        else:
            e["walls"].append(float(rec.get("wall_ms", 0.0)))
        if rec.get("error"):
            e["errors"] += 1
        if rec.get("partial"):
            e["partial"] += 1
        if rec.get("slow"):
            e["slow"] += 1
        if rec.get("traced"):
            e["traced"] += 1
        e["failovers"] += int(rec.get("failovers", 0))
        e["hedges"] += int(rec.get("hedges", 0))
        e["batched"] += int(rec.get("batched", 0))
        if rec.get("batched"):
            e["batched_queries"] += 1
        e["rows"] += int(rec.get("rows", 0))
        ts = _ts_epoch(rec.get("ts"))
        if ts is not None:
            e["t_min"] = ts if e["t_min"] is None else min(e["t_min"], ts)
            e["t_max"] = ts if e["t_max"] is None else max(e["t_max"], ts)
    # latest ingest freshness per table (the freshness ledger); round 16
    # writers (engine/loadgen, bench_ingest) also carry the sustained-run
    # percentiles — trended per table when present
    freshness: Dict[str, float] = {}
    fresh_pctl: Dict[str, Dict[str, float]] = {}
    for rec in records:
        if rec.get("kind") == "ingest_stats" and rec.get("table"):
            freshness[rec["table"]] = float(rec.get("freshness_ms", 0.0))
            pcts = {k: float(rec[k])
                    for k in ("freshness_p50_ms", "freshness_p99_ms")
                    if isinstance(rec.get(k), (int, float))}
            if pcts:
                fresh_pctl[rec["table"]] = pcts
    out: Dict[str, Any] = {}
    for t, e in sorted(acc.items()):
        walls = sorted(e.pop("walls"))
        t_min, t_max = e.pop("t_min"), e.pop("t_max")
        window = max((t_max - t_min), 1.0) if t_min is not None else 1.0
        n = e["queries"]
        out[t] = {
            **e,
            "qps": round(n / window, 3),
            "p50_ms": round(_pctl(walls, 0.5), 3),
            "p99_ms": round(_pctl(walls, 0.99), 3),
            "partial_ratio": round(e["partial"] / n, 4) if n else 0.0,
            "batched_ratio": round(e["batched_queries"] / n, 4)
            if n else 0.0,
        }
        if t in freshness:
            out[t]["freshness_ms"] = round(freshness[t], 3)
    for t, f in freshness.items():
        out.setdefault(t, {"queries": 0})["freshness_ms"] = round(f, 3)
    for t, pcts in fresh_pctl.items():
        out.setdefault(t, {"queries": 0}).update(
            {k: round(v, 3) for k, v in pcts.items()})
    return out


def rank_plan_shapes(records: List[Dict[str, Any]],
                     top: int = PLAN_SHAPE_TOP) -> List[Dict[str, Any]]:
    """The fleet's hottest plan shapes ranked by warmup cost —
    ``compiles x median compile_ms`` per normalized plan-shape hash
    over the pulled ``compile_event`` corpus. Events dedupe by their
    (proc, seq) identity first (the heat-table rule: two in-process
    node roles shipping one shared compile ledger must not
    double-count), then aggregate per shape with the trigger breakdown.
    This ranking is verbatim the prefetch list ROADMAP direction 3's
    AOT executable plane consumes: a fresh replica warming these
    shapes first amortizes the most cold-start debt per compile."""
    seen: set = set()
    by_shape: Dict[str, Dict[str, Any]] = {}
    for rec in records:
        if rec.get("kind") != "compile_event":
            continue
        uid = (rec.get("proc"), rec.get("seq"))
        if uid in seen:
            continue
        seen.add(uid)
        shape = rec.get("plan_shape") or "<none>"
        e = by_shape.setdefault(shape, {
            "plan_shape": shape, "sql": None, "compiles": 0,
            "triggers": {}, "_ms": []})
        e["compiles"] += 1
        e["_ms"].append(float(rec.get("lower_ms", 0.0))
                        + float(rec.get("compile_ms", 0.0)))
        t = rec.get("trigger") or "?"
        e["triggers"][t] = e["triggers"].get(t, 0) + 1
        if not e["sql"] and rec.get("sql"):
            e["sql"] = str(rec["sql"])[:120]
    out: List[Dict[str, Any]] = []
    for e in by_shape.values():
        ms = sorted(e.pop("_ms"))
        med = _pctl(ms, 0.5)
        e["median_compile_ms"] = round(med, 3)
        e["total_compile_ms"] = round(sum(ms), 3)
        e["warmup_cost"] = round(e["compiles"] * med, 3)
        out.append(e)
    out.sort(key=lambda e: (-e["warmup_cost"], e["plan_shape"]))
    return out[: max(top, 0)]


def slow_queries(records: List[Dict[str, Any]],
                 top: int = SLOW_TOP) -> List[Dict[str, Any]]:
    """The fleet's slowest queries (webapp "fleet slow queries" panel)."""
    rows = [{"qid": r.get("qid"), "node": r.get("node"),
             "table": r.get("table"),
             "wall_ms": float(r.get("wall_ms", 0.0)),
             "partial": bool(r.get("partial")),
             "sql": (r.get("sql") or "")[:120]}
            for r in records if r.get("kind") == "query_stats"]
    rows.sort(key=lambda r: -r["wall_ms"])
    return rows[: max(top, 0)]


def merge_heat(node_blocks: Dict[str, Dict[str, Any]],
               top: int = HEAT_TOP) -> List[Dict[str, Any]]:
    """Fleet hot-segment ranking from the per-node heat tables.

    Node blocks dedupe by ``proc`` first (in-process roles share ONE
    heat registry — summing per node would multiply-count), then merge
    by (table, segment): distinct processes hosting replicas of a
    segment contribute real, additive touches."""
    by_proc: Dict[str, List[Dict[str, Any]]] = {}
    for node_id in sorted(node_blocks):
        blk = node_blocks[node_id]
        by_proc[blk.get("proc") or node_id] = blk.get("heat") or []
    merged: Dict[Tuple[str, str], Dict[str, Any]] = {}
    for rows in by_proc.values():
        for r in rows:
            key = (r.get("table") or "?", r.get("segment") or "?")
            m = merged.setdefault(key, {
                "table": key[0], "segment": key[1], "touches": 0,
                "rows_scanned": 0, "device_hits": 0,
                "device_misses": 0})
            for f in ("touches", "rows_scanned", "device_hits",
                      "device_misses"):
                m[f] += int(r.get(f, 0))
    out = sorted(merged.values(),
                 key=lambda e: (-e["touches"], -e["rows_scanned"],
                                e["segment"]))[: max(top, 0)]
    for e in out:
        acc = e["device_hits"] + e["device_misses"]
        e["device_hit_ratio"] = round(e["device_hits"] / acc, 4) \
            if acc else None
    return out


def aggregate_slo(node_blocks: Dict[str, Dict[str, Any]]
                  ) -> Dict[str, Any]:
    """Fleet SLO table from the per-node ``slo``/``incidents`` blocks
    (ledger_debug_payload, ISSUE 17). Node blocks dedupe by ``proc``
    first (in-process roles share ONE SloPlane — summing per node would
    multiply-count), then merge per (scope, kind): worst (max) burn
    rates and lowest budget remaining across processes — the fleet view
    surfaces the most-burned replica, not an average that hides it —
    with additive event/bad/incident counts (distinct processes observe
    distinct queries). Pure record->dict math, exported for the oracle
    tests."""
    seen: Dict[str, Dict[str, Any]] = {}
    for node_id in sorted(node_blocks):
        blk = node_blocks[node_id]
        seen.setdefault(blk.get("proc") or node_id, blk)
    rows: Dict[Tuple[str, str], Dict[str, Any]] = {}
    incidents = 0
    armed = False
    for blk in seen.values():
        inc = blk.get("incidents") or {}
        incidents += int(inc.get("count", 0))
        slo = blk.get("slo") or {}
        armed = armed or bool(slo.get("armed"))
        for r in slo.get("objectives") or []:
            key = (str(r.get("scope") or "?"), str(r.get("kind") or "?"))
            m = rows.setdefault(key, {
                "scope": key[0], "kind": key[1],
                "objective": r.get("objective"),
                "burn_fast": 0.0, "burn_slow": 0.0,
                "budget_remaining": 1.0, "events": 0, "bad": 0,
                "alerting": False})
            m["burn_fast"] = max(m["burn_fast"],
                                 float(r.get("burn_fast", 0.0)))
            m["burn_slow"] = max(m["burn_slow"],
                                 float(r.get("burn_slow", 0.0)))
            m["budget_remaining"] = min(
                m["budget_remaining"],
                float(r.get("budget_remaining", 1.0)))
            m["events"] += int(r.get("events", 0))
            m["bad"] += int(r.get("bad", 0))
            m["alerting"] = m["alerting"] or bool(r.get("alerting"))
            if r.get("stale"):
                m["stale"] = True
    return {"armed": armed,
            "objectives": [rows[k] for k in sorted(rows)],
            "open_incidents": incidents}


def fleet_totals(node_blocks: Dict[str, Dict[str, Any]]
                 ) -> Dict[str, int]:
    """Unique-process sums of the carried counters + device bytes."""
    seen: Dict[str, Dict[str, Any]] = {}
    for node_id in sorted(node_blocks):
        blk = node_blocks[node_id]
        seen.setdefault(blk.get("proc") or node_id, blk)
    totals: Dict[str, int] = {k: 0 for k in NODE_COUNTER_KEYS}
    totals["device_bytes"] = 0
    for blk in seen.values():
        counters = blk.get("counters") or {}
        for k in NODE_COUNTER_KEYS:
            totals[k] += int(counters.get(k, 0))
        mem = blk.get("memory") or {}
        totals["device_bytes"] += int(
            (mem.get("total") or {}).get("bytes", 0))
    return totals


VERDICT_TOP = 5


def latest_verdicts(records: List[Dict[str, Any]],
                    top: int = VERDICT_TOP) -> List[Dict[str, Any]]:
    """The newest ``rca_verdict`` briefs in the pulled corpus (round
    25, webapp Autopsy panel): (proc, seq)-deduped like the plan-shape
    ranking (two in-process roles shipping one shared ledger must not
    double-count), newest last in ledger order so the panel's top row
    is the freshest verdict. Pure record->list math, exported for the
    oracle tests."""
    seen: set = set()
    rows: List[Dict[str, Any]] = []
    for rec in records:
        if rec.get("kind") != "rca_verdict":
            continue
        uid = (rec.get("proc"), rec.get("seq"))
        if uid in seen:
            continue
        seen.add(uid)
        causes = rec.get("causes") or []
        rows.append({
            "node": rec.get("node"), "proc": rec.get("proc"),
            "seq": rec.get("seq"), "ts": rec.get("ts"),
            "incident_ref": rec.get("incident_ref"),
            "top_cause": rec.get("top_cause"),
            "inconclusive": bool(rec.get("inconclusive")),
            "top_score": (causes[0].get("score")
                          if causes and isinstance(causes[0], dict)
                          else None),
            "detail": (causes[0].get("detail")
                       if causes and isinstance(causes[0], dict)
                       else None)})
    return rows[-max(top, 0):][::-1]


def _node_slo_brief(slo: Dict[str, Any]) -> Dict[str, Any]:
    """One node's SLO block compressed to the rebalancer's donor
    signal: worst slow-window burn across its objectives + whether any
    alert is latched."""
    objs = (slo or {}).get("objectives") or []
    return {
        "worst_burn_slow": max(
            [float(o.get("burn_slow", 0.0) or 0.0) for o in objs]
            or [0.0]),
        "alerting": any(bool(o.get("alerting")) for o in objs),
    }


class ForensicsRollupTask:
    """The controller-side pull + aggregate pass (module docstring).
    Registered as a BasePeriodicTask; ``run()`` is also the manual
    trigger body (idempotent — cursors make pulls incremental)."""

    NAME = "ForensicsRollup"

    def __init__(self, controller, ledger_path: Optional[str] = None,
                 pull_timeout: float = PULL_TIMEOUT_S):
        self.controller = controller
        self.ledger_path = ledger_path or os.path.join(
            controller.data_dir, "fleet_ledger.jsonl")
        self.pull_timeout = pull_timeout
        self._lock = threading.Lock()
        # serializes whole passes: the scheduler's periodic fire, a
        # manual REST trigger and a direct run() (chaos gate) may
        # overlap — without this, two passes would read the same
        # cursors and double-ship every node's delta
        self._run_lock = threading.Lock()
        self._cursors: Dict[str, int] = self._load_cursors()
        # the rolling aggregation window (module constant above):
        # pre-load the existing fleet ledger once, then feed deltas
        existing, _ = _read_fleet(self.ledger_path)
        self._window: deque = deque(existing, maxlen=AGG_WINDOW)
        self._total_records = len(existing)
        self.last_rollup: Optional[Dict[str, Any]] = None
        self.pulls = 0

    # -- cursor persistence (restart must not re-ship pulled records) ------
    def _cursor_path(self) -> str:
        return self.ledger_path + ".cursors"

    def _load_cursors(self) -> Dict[str, int]:
        try:
            with open(self._cursor_path()) as fh:
                data = json.load(fh)
            return {str(k): int(v) for k, v in data.items()}
        except (OSError, ValueError):
            return {}

    def _save_cursors(self) -> None:
        tmp = self._cursor_path() + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(self._cursors, fh)
        os.replace(tmp, self._cursor_path())

    # -- pull targets ------------------------------------------------------
    def _targets(self) -> List[Tuple[str, str]]:
        """Live (heartbeat-fresh) brokers and servers with a dialable
        host/port, from the controller's ephemeral instance registry."""
        c = self.controller
        now = time.monotonic()
        out: List[Tuple[str, str]] = []
        with c._lock:
            for inst in c._instances.values():
                if inst.get("role") not in ("broker", "server"):
                    continue
                if now - inst["lastHeartbeat"] > c.heartbeat_timeout:
                    continue
                if not inst.get("host") or not inst.get("port"):
                    continue
                out.append((inst["id"],
                            f"http://{inst['host']}:{inst['port']}"))
        return sorted(out)

    # -- the pass ----------------------------------------------------------
    def run(self) -> Dict[str, Any]:
        # whole-pass serialization: overlapped passes would read the
        # same cursors and double-ship deltas (the scheduler serializes
        # its own fires through run_once, but a direct run() — chaos
        # gate, tests — may overlap a periodic fire)
        with self._run_lock:
            return self._run_locked()

    def _run_locked(self) -> Dict[str, Any]:
        pulled = 0
        invalid = 0
        skipped: List[str] = []
        node_blocks: Dict[str, Dict[str, Any]] = {}
        targets = self._targets()
        for node_id, url in targets:
            since = self._cursors.get(node_id, 0)
            try:
                resp = http_json(
                    "GET", f"{url}/debug/ledger?since={since}",
                    timeout=self.pull_timeout)
            except Exception:
                # dead/partitioned node: skipped and counted, the pull
                # moves on — one wedged node never wedges the fleet
                skipped.append(node_id)
                continue
            for rec in resp.get("records") or []:
                if not isinstance(rec, dict) or "v" not in rec or \
                        uledger.validate_record(rec):
                    invalid += 1  # legacy or contract-violating: dropped
                    continue
                stamped = dict(rec)
                stamped["node"] = node_id
                uledger.append_record(stamped, self.ledger_path)
                self._window.append(stamped)
                self._total_records += 1
                pulled += 1
            # cursor updates publish under _lock: snapshot() copies
            # _cursors for GET /debug/fleet while a pass is mid-pull,
            # and a dict resize during that copy raises (CC201
            # mixed-guard — _run_lock serializes passes, _lock guards
            # the served state)
            with self._lock:
                self._cursors[node_id] = int(resp.get("nextSeq", since))
            node_blocks[node_id] = {
                "role": resp.get("role"),
                "proc": resp.get("proc"),
                "counters": {k: (resp.get("counters") or {}).get(k, 0)
                             for k in NODE_COUNTER_KEYS},
                "batching": resp.get("batching"),
                "memory": resp.get("memory"),
                "tier": resp.get("tier"),
                "heat": resp.get("heat"),
                # SLO burn table + incident counts (ISSUE 17)
                "slo": resp.get("slo"),
                "incidents": resp.get("incidents"),
            }
        self._save_cursors()

        # aggregate over the rolling window (not just this delta): the
        # rollup is the cumulative cluster view — fed incrementally, so
        # a pass never re-reads the whole file; restarts reload it once
        fleet_records = list(self._window)
        node_summaries = {
            n: {"role": b["role"], "proc": b["proc"],
                "counters": b["counters"],
                "memory": {p: v for p, v in
                           ((b.get("memory") or {}).items())
                           if p == "total" or (v or {}).get("entries")},
                # HBM tier occupancy beside the device-bytes block
                # (webapp Fleet view renders both)
                **({"tier": b["tier"]} if b.get("tier") else {}),
                # per-node SLO brief (worst slow-window burn + alerting
                # flag): the closed-loop rebalancer's donor-ranking
                # signal (cluster/rebalancer.plan_moves). In-process
                # roles share one SloPlane so these degenerate to the
                # same value per proc — the planner's load tiebreak
                # carries ranking then; distinct processes diverge.
                **({"slo": _node_slo_brief(b["slo"])}
                   if (b.get("slo") or {}).get("armed") else {})}
            for n, b in node_blocks.items()}
        fields: Dict[str, Any] = {
            "nodes_polled": len(targets),
            "nodes_skipped": len(skipped),
            "skipped_nodes": skipped,
            "records_pulled": pulled,
            "invalid_records": invalid,
            "fleet_records": self._total_records,
            "tables": aggregate_tables(fleet_records),
            "slow_queries": slow_queries(fleet_records),
            # the fleet's hottest plan shapes by warmup cost — the
            # direction-3 executable-plane prefetch list (ISSUE 15)
            "plan_shapes": rank_plan_shapes(fleet_records),
            "heat": merge_heat(node_blocks),
            "nodes": node_summaries,
            "fleet": fleet_totals(node_blocks),
            # worst-replica fleet SLO view + open incident count
            "slo": aggregate_slo(node_blocks),
            # newest root-cause verdicts (round 25, Autopsy panel)
            "autopsy": latest_verdicts(fleet_records),
        }
        if self._total_records > len(fleet_records):
            # older records aged out of the window: say so instead of
            # presenting a clipped aggregation as complete history
            fields["window_clipped"] = len(fleet_records)
        rec = uledger.make_record("fleet_rollup", **fields)
        uledger.append_record(rec, self.ledger_path)
        with self._lock:
            self.last_rollup = rec
            self.pulls += 1
        global_metrics.gauge("fleet_nodes_polled", len(targets))
        global_metrics.gauge("fleet_nodes_skipped", len(skipped))
        global_metrics.gauge("fleet_records_total", self._total_records)
        return rec

    # -- serving (GET /debug/fleet) ----------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"ledger": self.ledger_path,
                    "pulls": self.pulls,
                    "cursors": dict(self._cursors),
                    "rollup": self.last_rollup}


def _read_fleet(path: str) -> Tuple[List[Dict[str, Any]], int]:
    """Parse the fleet ledger (rollup records excluded from their own
    aggregation input)."""
    records: List[Dict[str, Any]] = []
    lines = 0
    if os.path.exists(path):
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                lines += 1
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and \
                        rec.get("kind") != "fleet_rollup":
                    records.append(rec)
    return records, lines
