from .controller import Controller  # noqa: F401
from .server_node import ServerNode  # noqa: F401
from .broker_node import BrokerNode  # noqa: F401
