"""Segment completion protocol: controller-arbitrated realtime commit.

Reference parity: pinot-common/.../protocols/SegmentCompletionProtocol.java
:77-122 (message types HOLD / CATCHUP / COMMIT / COMMIT_CONTINUE /
COMMIT_SUCCESS / FAILED, split-commit) + pinot-controller/.../realtime/
SegmentCompletionManager.java (the FSM electing exactly one committer per
consuming segment among its replicas).

Flow per (table, segment):
    replicas hit their row/time threshold -> POST segmentConsumed(offset)
    controller HOLDs until every expected replica reported or the
    decision window elapses, then elects the largest offset:
        winner   -> COMMIT  (commit at target offset)
        laggards -> CATCHUP (consume to target, report again, then HOLD)
    winner: segmentCommitStart -> build + upload to deep store ->
            segmentCommitEnd(downloadURI) -> controller registers the
            segment (atomic version bump) -> COMMIT_SUCCESS
    other replicas' next segmentConsumed -> COMMITTED + downloadURI
    (they discard their consuming state and download — peer/deep-store
    download path).
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

HOLD = "HOLD"
CATCHUP = "CATCHUP"
COMMIT = "COMMIT"
COMMIT_CONTINUE = "COMMIT_CONTINUE"
COMMIT_SUCCESS = "COMMIT_SUCCESS"
COMMITTED = "COMMITTED"
FAILED = "FAILED"


class SegmentCompletionManager:
    def __init__(self, expected_replicas: Callable[[str], int],
                 decision_window_s: float = 0.5,
                 commit_timeout_s: float = 30.0,
                 committed_ttl_s: float = 300.0,
                 registered_segment: Optional[
                     Callable[[str, str], Optional[Dict[str, Any]]]] = None):
        """expected_replicas: table -> how many replicas consume each
        segment (the controller's replication for the table).
        committed_ttl_s bounds FSM memory: COMMITTED entries are purged
        after laggards have had that long to fetch the downloadURI (they
        fall back to the controller's segment registry afterwards)."""
        self._expected = expected_replicas
        self.decision_window_s = decision_window_s
        self.commit_timeout_s = commit_timeout_s
        self.committed_ttl_s = committed_ttl_s
        # fallback registry lookup: (table, segment) -> {"downloadURI",
        # "offset"} | None. The FSM is memory-only; after a controller
        # restart or a TTL purge a laggard's report must NOT re-elect a
        # committer for a segment the cluster already registered — that
        # would overwrite the canonical artifact with a divergent one.
        self._registered = registered_segment
        self._lock = threading.Lock()
        self._fsm: Dict[Tuple[str, str], Dict[str, Any]] = {}

    def _purge_locked(self) -> None:
        # _locked suffix contract: every caller already holds self._lock
        now = time.monotonic()
        dead = [k for k, e in self._fsm.items()
                if e["state"] == "COMMITTED" and e.get("commit_ts")
                and now - e["commit_ts"] > self.committed_ttl_s]
        for k in dead:
            del self._fsm[k]  # jaxlint: ok unlocked-mutation

    def drop_table(self, table: str) -> None:
        with self._lock:
            for k in [k for k in self._fsm if k[0] == table]:
                del self._fsm[k]

    def _entry(self, table: str, segment: str) -> Dict[str, Any]:
        # called only from the FSM transitions, which hold self._lock
        key = (table, segment)
        if key not in self._fsm:
            self._fsm[key] = {  # jaxlint: ok unlocked-mutation
                "state": "HOLDING", "offsets": {},
                "first_ts": time.monotonic(),
                "winner": None, "target": None,
                "download_uri": None, "commit_ts": None}
        return self._fsm[key]

    def segment_consumed(self, table: str, segment: str, server: str,
                         offset: int) -> Dict[str, Any]:
        with self._lock:
            self._purge_locked()
            if (table, segment) not in self._fsm and \
                    self._registered is not None:
                reg = self._registered(table, segment)
                if reg is not None:
                    return {"status": COMMITTED,
                            "downloadURI": reg.get("downloadURI"),
                            "offset": reg.get("offset")}
            e = self._entry(table, segment)
            if e["state"] == "COMMITTED":
                return {"status": COMMITTED,
                        "downloadURI": e["download_uri"],
                        "offset": e["target"]}
            if e["state"] == "COMMITTING":
                if server == e["winner"]:
                    # winner re-reporting (e.g. after restart): carry on
                    return {"status": COMMIT, "offset": e["target"]}
                # a committer died? allow takeover after timeout
                if time.monotonic() - (e["commit_ts"] or 0) \
                        > self.commit_timeout_s:
                    e["offsets"][server] = offset
                    return self._elect(table, e, server, takeover=True)
                return {"status": HOLD}
            e["offsets"][server] = max(offset,
                                       e["offsets"].get(server, offset))
            expected = max(self._expected(table), 1)
            window_over = (time.monotonic() - e["first_ts"]
                           >= self.decision_window_s)
            if len(e["offsets"]) >= expected or window_over:
                return self._elect(table, e, server)
            return {"status": HOLD}

    def _elect(self, table: str, e: Dict[str, Any], server: str,
               takeover: bool = False) -> Dict[str, Any]:
        """Pick the committer: the largest reported offset (ties: first
        reporter). Laggards catch up to the target; the winner commits."""
        if e["target"] is None or takeover:
            cands = dict(e["offsets"])
            if takeover and len(cands) > 1:
                cands.pop(e["winner"], None)  # the stalled committer
            winner = max(cands, key=lambda s: (cands[s],))
            e["winner"] = winner
            e["target"] = max(e["target"] or 0, cands[winner])
            if takeover:
                e["state"] = "HOLDING"
        if server == e["winner"] and \
                e["offsets"].get(server, -1) >= e["target"]:
            # the winner may have consumed past the elected target while
            # holding; commit everything it has so the artifact's end
            # offset and the adopters' resume offset agree (no duplicate
            # re-consumption on the laggards)
            e["target"] = e["offsets"][server]
            e["state"] = "COMMITTING"
            e["commit_ts"] = time.monotonic()
            return {"status": COMMIT, "offset": e["target"]}
        if e["offsets"].get(server, -1) < e["target"]:
            return {"status": CATCHUP, "offset": e["target"]}
        return {"status": HOLD}

    def segment_commit_start(self, table: str, segment: str, server: str
                             ) -> Dict[str, Any]:
        with self._lock:
            e = self._fsm.get((table, segment))
            if e is None or e["state"] != "COMMITTING" or \
                    e["winner"] != server:
                return {"status": FAILED}
            e["commit_ts"] = time.monotonic()
            return {"status": COMMIT_CONTINUE}

    def segment_commit_end(self, table: str, segment: str, server: str,
                           download_uri: str,
                           register: Callable[[], None]) -> Dict[str, Any]:
        """register() runs under the FSM lock — the segment-metadata write
        and the COMMITTED flip are atomic with respect to replica polls."""
        with self._lock:
            e = self._fsm.get((table, segment))
            if e is None or e["state"] != "COMMITTING" or \
                    e["winner"] != server:
                return {"status": FAILED}
            register()
            e["state"] = "COMMITTED"
            e["download_uri"] = download_uri
            e["commit_ts"] = time.monotonic()  # TTL purge baseline
            return {"status": COMMIT_SUCCESS}

    def status(self, table: str, segment: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            e = self._fsm.get((table, segment))
            return dict(e) if e else None


def _commit_fault(server_id: str, op: str, segment: str) -> None:
    """Named ingest fault hook (``commit.http_error``): the commit RPC
    fails mid-protocol before reaching the controller. One hook per
    protocol boundary so a seeded plan can kill exactly the
    segmentConsumed / commitStart / commitEnd leg it targets (the
    generic rpc.* trio in http_util still applies underneath). Shared
    by BOTH clients — HTTP and protocol-local chaos plans must see
    identical boundaries and site keys."""
    from ..utils import faults
    if faults.active():
        faults.fault_point("commit.http_error",
                           f"{server_id}/{op}/{segment}")


class CompletionClient:
    """Server-side protocol client: reports thresholds and runs the
    split-commit against the controller REST API (the server half of
    SegmentCompletionProtocol — ServerSegmentCompletionProtocolHandler
    analog)."""

    def __init__(self, controller_url: str, server_id: str,
                 deepstore_uri: str):
        self.controller_url = controller_url
        self.server_id = server_id
        self.deepstore_uri = deepstore_uri

    def _commit_fault(self, op: str, segment: str) -> None:
        _commit_fault(self.server_id, op, segment)

    def segment_consumed(self, table: str, segment: str, offset: int
                         ) -> Dict[str, Any]:
        from .http_util import http_json
        self._commit_fault("segmentConsumed", segment)
        return http_json("POST", f"{self.controller_url}/segmentConsumed",
                         {"table": table, "segment": segment,
                          "server": self.server_id, "offset": offset})

    def split_commit(self, table: str, segment: str, seg_dir: str,
                     metadata: Optional[Dict[str, Any]] = None) -> bool:
        """commitStart -> upload to deep store -> commitEnd. Returns True
        on COMMIT_SUCCESS."""
        from .deepstore import upload_segment
        from .http_util import http_json
        self._commit_fault("segmentCommitStart", segment)
        start = http_json("POST",
                          f"{self.controller_url}/segmentCommitStart",
                          {"table": table, "segment": segment,
                           "server": self.server_id})
        if start.get("status") != COMMIT_CONTINUE:
            return False
        uri = upload_segment(seg_dir,
                             self.deepstore_uri.rstrip("/") + "/" + table)
        self._commit_fault("segmentCommitEnd", segment)
        end = http_json("POST", f"{self.controller_url}/segmentCommitEnd",
                        {"table": table, "segment": segment,
                         "server": self.server_id, "downloadURI": uri,
                         "metadata": metadata})
        return end.get("status") == COMMIT_SUCCESS


class LocalCompletionClient:
    """In-process CompletionClient: the same two-call surface the
    realtime manager speaks (segment_consumed / split_commit), driving a
    SegmentCompletionManager directly instead of the controller REST
    API. Commits upload through the real deep-store pack/upload path and
    register into a shared ``registry`` dict (the controller's
    segment-metadata analog) that doubles as the FSM's
    ``registered_segment`` fallback — so peer replicas and restarted
    processes resolve COMMITTED downloads exactly like the HTTP flow.

    Exists for the ingest-vs-oracle fuzzer and standalone protocol
    soaks: every protocol boundary still passes the
    ``commit.http_error`` fault hook, and downloads still pass
    ``handoff.stall`` (deepstore), so chaos plans behave identically to
    the clustered path without HTTP servers in the loop."""

    def __init__(self, completion: SegmentCompletionManager,
                 server_id: str, deepstore_uri: str,
                 registry: Optional[Dict[Tuple[str, str],
                                         Dict[str, Any]]] = None):
        self.completion = completion
        self.server_id = server_id
        self.deepstore_uri = deepstore_uri
        self.registry = registry if registry is not None else {}

    def _commit_fault(self, op: str, segment: str) -> None:
        _commit_fault(self.server_id, op, segment)

    def segment_consumed(self, table: str, segment: str, offset: int
                         ) -> Dict[str, Any]:
        self._commit_fault("segmentConsumed", segment)
        return self.completion.segment_consumed(table, segment,
                                                self.server_id, offset)

    def split_commit(self, table: str, segment: str, seg_dir: str,
                     metadata: Optional[Dict[str, Any]] = None) -> bool:
        from .deepstore import upload_segment
        self._commit_fault("segmentCommitStart", segment)
        start = self.completion.segment_commit_start(table, segment,
                                                     self.server_id)
        if start.get("status") != COMMIT_CONTINUE:
            return False
        uri = upload_segment(seg_dir,
                             self.deepstore_uri.rstrip("/") + "/" + table)
        self._commit_fault("segmentCommitEnd", segment)

        def register() -> None:
            # runs under the FSM lock, like the controller's add_segment
            self.registry[(table, segment)] = {  # jaxlint: ok unlocked-mutation
                "downloadURI": uri,
                "offset": (metadata or {}).get("endOffset")}

        end = self.completion.segment_commit_end(table, segment,
                                                 self.server_id, uri,
                                                 register=register)
        return end.get("status") == COMMIT_SUCCESS
