"""Segment completion protocol: controller-arbitrated realtime commit.

Reference parity: pinot-common/.../protocols/SegmentCompletionProtocol.java
:77-122 (message types HOLD / CATCHUP / COMMIT / COMMIT_CONTINUE /
COMMIT_SUCCESS / FAILED, split-commit) + pinot-controller/.../realtime/
SegmentCompletionManager.java (the FSM electing exactly one committer per
consuming segment among its replicas).

Flow per (table, segment):
    replicas hit their row/time threshold -> POST segmentConsumed(offset)
    controller HOLDs until every expected replica reported or the
    decision window elapses, then elects the largest offset:
        winner   -> COMMIT  (commit at target offset)
        laggards -> CATCHUP (consume to target, report again, then HOLD)
    winner: segmentCommitStart -> build + upload to deep store ->
            segmentCommitEnd(downloadURI) -> controller registers the
            segment (atomic version bump) -> COMMIT_SUCCESS
    other replicas' next segmentConsumed -> COMMITTED + downloadURI
    (they discard their consuming state and download — peer/deep-store
    download path).
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

HOLD = "HOLD"
CATCHUP = "CATCHUP"
COMMIT = "COMMIT"
COMMIT_CONTINUE = "COMMIT_CONTINUE"
COMMIT_SUCCESS = "COMMIT_SUCCESS"
COMMITTED = "COMMITTED"
FAILED = "FAILED"


class SegmentCompletionManager:
    def __init__(self, expected_replicas: Callable[[str], int],
                 decision_window_s: float = 0.5,
                 commit_timeout_s: float = 30.0,
                 committed_ttl_s: float = 300.0,
                 registered_segment: Optional[
                     Callable[[str, str], Optional[Dict[str, Any]]]] = None):
        """expected_replicas: table -> how many replicas consume each
        segment (the controller's replication for the table).
        committed_ttl_s bounds FSM memory: COMMITTED entries are purged
        after laggards have had that long to fetch the downloadURI (they
        fall back to the controller's segment registry afterwards)."""
        self._expected = expected_replicas
        self.decision_window_s = decision_window_s
        self.commit_timeout_s = commit_timeout_s
        self.committed_ttl_s = committed_ttl_s
        # fallback registry lookup: (table, segment) -> {"downloadURI",
        # "offset"} | None. The FSM is memory-only; after a controller
        # restart or a TTL purge a laggard's report must NOT re-elect a
        # committer for a segment the cluster already registered — that
        # would overwrite the canonical artifact with a divergent one.
        self._registered = registered_segment
        self._lock = threading.Lock()
        self._fsm: Dict[Tuple[str, str], Dict[str, Any]] = {}

    def _purge_locked(self) -> None:
        # _locked suffix contract: every caller already holds self._lock
        now = time.monotonic()
        dead = [k for k, e in self._fsm.items()
                if e["state"] == "COMMITTED" and e.get("commit_ts")
                and now - e["commit_ts"] > self.committed_ttl_s]
        for k in dead:
            del self._fsm[k]  # jaxlint: ok unlocked-mutation

    def drop_table(self, table: str) -> None:
        with self._lock:
            for k in [k for k in self._fsm if k[0] == table]:
                del self._fsm[k]

    def _entry(self, table: str, segment: str) -> Dict[str, Any]:
        # called only from the FSM transitions, which hold self._lock
        key = (table, segment)
        if key not in self._fsm:
            self._fsm[key] = {  # jaxlint: ok unlocked-mutation
                "state": "HOLDING", "offsets": {},
                "first_ts": time.monotonic(),
                "winner": None, "target": None,
                "download_uri": None, "commit_ts": None}
        return self._fsm[key]

    def segment_consumed(self, table: str, segment: str, server: str,
                         offset: int) -> Dict[str, Any]:
        with self._lock:
            self._purge_locked()
            if (table, segment) not in self._fsm and \
                    self._registered is not None:
                reg = self._registered(table, segment)
                if reg is not None:
                    return {"status": COMMITTED,
                            "downloadURI": reg.get("downloadURI"),
                            "offset": reg.get("offset")}
            e = self._entry(table, segment)
            if e["state"] == "COMMITTED":
                return {"status": COMMITTED,
                        "downloadURI": e["download_uri"],
                        "offset": e["target"]}
            if e["state"] == "COMMITTING":
                if server == e["winner"]:
                    # winner re-reporting (e.g. after restart): carry on
                    return {"status": COMMIT, "offset": e["target"]}
                # a committer died? allow takeover after timeout
                if time.monotonic() - (e["commit_ts"] or 0) \
                        > self.commit_timeout_s:
                    e["offsets"][server] = offset
                    return self._elect(table, e, server, takeover=True)
                return {"status": HOLD}
            e["offsets"][server] = max(offset,
                                       e["offsets"].get(server, offset))
            expected = max(self._expected(table), 1)
            window_over = (time.monotonic() - e["first_ts"]
                           >= self.decision_window_s)
            if len(e["offsets"]) >= expected or window_over:
                return self._elect(table, e, server)
            return {"status": HOLD}

    def _elect(self, table: str, e: Dict[str, Any], server: str,
               takeover: bool = False) -> Dict[str, Any]:
        """Pick the committer: the largest reported offset (ties: first
        reporter). Laggards catch up to the target; the winner commits."""
        if e["target"] is None or takeover:
            cands = dict(e["offsets"])
            if takeover and len(cands) > 1:
                cands.pop(e["winner"], None)  # the stalled committer
            winner = max(cands, key=lambda s: (cands[s],))
            e["winner"] = winner
            e["target"] = max(e["target"] or 0, cands[winner])
            if takeover:
                e["state"] = "HOLDING"
        if server == e["winner"] and \
                e["offsets"].get(server, -1) >= e["target"]:
            # the winner may have consumed past the elected target while
            # holding; commit everything it has so the artifact's end
            # offset and the adopters' resume offset agree (no duplicate
            # re-consumption on the laggards)
            e["target"] = e["offsets"][server]
            e["state"] = "COMMITTING"
            e["commit_ts"] = time.monotonic()
            return {"status": COMMIT, "offset": e["target"]}
        if e["offsets"].get(server, -1) < e["target"]:
            return {"status": CATCHUP, "offset": e["target"]}
        return {"status": HOLD}

    def segment_commit_start(self, table: str, segment: str, server: str
                             ) -> Dict[str, Any]:
        with self._lock:
            e = self._fsm.get((table, segment))
            if e is None or e["state"] != "COMMITTING" or \
                    e["winner"] != server:
                return {"status": FAILED}
            e["commit_ts"] = time.monotonic()
            return {"status": COMMIT_CONTINUE}

    def segment_commit_end(self, table: str, segment: str, server: str,
                           download_uri: str,
                           register: Callable[[], None]) -> Dict[str, Any]:
        """register() runs under the FSM lock — the segment-metadata write
        and the COMMITTED flip are atomic with respect to replica polls."""
        with self._lock:
            e = self._fsm.get((table, segment))
            if e is None or e["state"] != "COMMITTING" or \
                    e["winner"] != server:
                return {"status": FAILED}
            register()
            e["state"] = "COMMITTED"
            e["download_uri"] = download_uri
            e["commit_ts"] = time.monotonic()  # TTL purge baseline
            return {"status": COMMIT_SUCCESS}

    def status(self, table: str, segment: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            e = self._fsm.get((table, segment))
            return dict(e) if e else None


class CompletionClient:
    """Server-side protocol client: reports thresholds and runs the
    split-commit against the controller REST API (the server half of
    SegmentCompletionProtocol — ServerSegmentCompletionProtocolHandler
    analog)."""

    def __init__(self, controller_url: str, server_id: str,
                 deepstore_uri: str):
        self.controller_url = controller_url
        self.server_id = server_id
        self.deepstore_uri = deepstore_uri

    def segment_consumed(self, table: str, segment: str, offset: int
                         ) -> Dict[str, Any]:
        from .http_util import http_json
        return http_json("POST", f"{self.controller_url}/segmentConsumed",
                         {"table": table, "segment": segment,
                          "server": self.server_id, "offset": offset})

    def split_commit(self, table: str, segment: str, seg_dir: str,
                     metadata: Optional[Dict[str, Any]] = None) -> bool:
        """commitStart -> upload to deep store -> commitEnd. Returns True
        on COMMIT_SUCCESS."""
        from .deepstore import upload_segment
        from .http_util import http_json
        start = http_json("POST",
                          f"{self.controller_url}/segmentCommitStart",
                          {"table": table, "segment": segment,
                           "server": self.server_id})
        if start.get("status") != COMMIT_CONTINUE:
            return False
        uri = upload_segment(seg_dir,
                             self.deepstore_uri.rstrip("/") + "/" + table)
        end = http_json("POST", f"{self.controller_url}/segmentCommitEnd",
                        {"table": table, "segment": segment,
                         "server": self.server_id, "downloadURI": uri,
                         "metadata": metadata})
        return end.get("status") == COMMIT_SUCCESS
