"""Periodic task framework for the controller.

Reference parity: pinot-core/.../periodictask/{BasePeriodicTask,
PeriodicTaskScheduler}.java — named tasks with an interval and an
initial delay, run serially by a scheduler thread, with manual
run-now triggering (the controller REST /periodictask/run analog).
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional


class BasePeriodicTask:
    """Subclass or wrap a callable; run() must be idempotent — the
    scheduler may invoke it concurrently with a manual trigger only if
    the subclass opts out of the serial lock."""

    def __init__(self, name: str, interval_s: float,
                 fn: Optional[Callable[[], None]] = None,
                 initial_delay_s: float = 0.0):
        self.name = name
        self.interval_s = interval_s
        self.initial_delay_s = initial_delay_s
        self._fn = fn
        self._lock = threading.Lock()
        self.run_count = 0
        self.last_error: Optional[str] = None
        self.last_run_ms: float = 0.0

    def run(self) -> None:
        if self._fn is None:
            raise NotImplementedError
        self._fn()

    def run_once(self) -> None:
        """Serialized entry used by the scheduler and manual triggers."""
        with self._lock:
            t0 = time.perf_counter()
            try:
                self.run()
                self.last_error = None
            except Exception as e:  # tasks must not kill the scheduler
                self.last_error = f"{type(e).__name__}: {e}"
            finally:
                self.run_count += 1
                self.last_run_ms = (time.perf_counter() - t0) * 1e3


class PeriodicTaskScheduler:
    def __init__(self):
        self._tasks: Dict[str, BasePeriodicTask] = {}
        self._next_run: Dict[str, float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def register(self, task: BasePeriodicTask) -> None:
        self._tasks[task.name] = task
        self._next_run[task.name] = time.monotonic() + task.initial_delay_s

    def start(self, tick_s: float = 0.1) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop, args=(tick_s,),
                                        daemon=True)
        self._thread.start()

    def _loop(self, tick_s: float) -> None:
        while not self._stop.wait(tick_s):
            now = time.monotonic()
            for name, task in list(self._tasks.items()):
                if now >= self._next_run.get(name, 0.0):
                    self._next_run[name] = now + task.interval_s
                    task.run_once()

    def trigger(self, name: str) -> bool:
        """Run a task now (controller REST /periodictask/run analog)."""
        task = self._tasks.get(name)
        if task is None:
            return False
        task.run_once()
        return True

    def status(self) -> List[Dict[str, object]]:
        return [{"name": t.name, "intervalSeconds": t.interval_s,
                 "runCount": t.run_count, "lastError": t.last_error,
                 "lastRunMs": round(t.last_run_ms, 3)}
                for t in self._tasks.values()]

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
