"""Incident autopsy plane: deterministic cross-plane root-cause
attribution with a replay-gated verdict (round 25).

Rounds 7-24 built every measurement plane — spans, compile forensics,
devmem/tier telemetry, the SLO burn plane, the incident flight
recorder, the closed-loop rebalancer — but nothing *explains* burn: an
operator staring at an open incident still has to eyeball eight debug
surfaces to learn whether the cause was a compile storm, tier thrash,
overload shedding, rebalance churn, an armed fault stream, or a
straggler node. This module turns the recorded evidence into an
attributed verdict:

- ``load_corpus`` reads a node (or fleet) ledger and stamps every
  record with its 1-based line number — the ``seq`` half of the
  ``(node, proc, seq)`` evidence pointers every verdict carries, the
  exact sequence discipline ``forensics.read_ledger_since`` resolves
  (torn tails excluded, so a pointer always lands on a complete line).
- ``assemble_window`` splits the corpus into a baseline and an
  incident window on the injectable event-time clock
  (``utils/slo.event_time`` — ``arrival_ms + wall_ms``, never wall
  clock), computes the excess latency over the baseline p50, and
  gathers the cross-plane events (compile/rebalance/alert/slo/
  incident/ingest/trace) that land after the baseline by ledger
  order — append order IS time order, so no timestamp parsing.
- eight pure scorers — one per cause family in the fixed ``CAUSES``
  taxonomy — each return matched-evidence refs plus an
  excess-attribution fraction ("post-warmup compile_ms accounts for
  0.62 of excess p99"). Tier/devmem/overload evidence comes from the
  incident bundles' surface blocks; compile-time attribution is split
  by the compile_event trigger taxonomy so an eviction-rebuild storm
  attributes to tier thrash, a drift retrace to drift, and only the
  rest to a plain compile storm; straggler skew is discounted by
  in-window compile time so a one-sided warmup never masquerades as a
  partitioned node.
- ``plan_autopsy`` ranks the taxonomy and emits the verdict dict — an
  explicit ``inconclusive`` verdict when no cause clears ``MIN_SCORE``
  (never a confabulated top cause). Every scorer and the assembler is
  a detlint ROOTS member (DT301-DT305 clean), so the same corpus
  yields byte-identical verdicts (``json.dumps(..., sort_keys=True)``)
  — the ``tools/traffic_replay.py --autopsy`` gate computes each
  verdict twice and compares bytes.
- ``whydown`` is the per-query lane (EXPLAIN ANALYZE
  ``OPTION(whydown=true)`` / ``GET /debug/autopsy?qid=``): the
  cross-plane events whose ledger positions overlap the query's own
  wall window, annotated onto its trace.
- ``AutopsyPlane`` is the live wrapper: it runs ``plan_autopsy`` over
  the node ledger, lands the verdict as a validated ``rca_verdict``
  record in the same ledger, keeps a bounded ring for
  ``GET /debug/autopsy``, and attaches the verdict ref back onto the
  originating incident's ring entry. Wired as the
  ``IncidentRecorder.post_hook`` it runs automatically on incident
  fire — on the recorder's background thread, fenced, never on the
  query path.
"""
from __future__ import annotations

import json
import os
import threading
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ..utils import ledger as uledger
from ..utils.metrics import global_metrics
from ..utils.slo import DEFAULT_BURN_THRESHOLD, event_time, \
    global_incidents
from .forensics import PROC_TOKEN

# the fixed cause taxonomy — scorer order IS this order, ranking is
# (-score, cause) so ties break alphabetically, never by code motion
CAUSES = ("compile_storm", "tier_thrash", "overload_shed",
          "rebalance_churn", "chaos_faults", "straggler",
          "drift_recompile", "ingest_stall")

DEFAULT_WINDOW_S = 60.0       # incident window when none is given
MIN_SCORE = 0.15              # below this the verdict is inconclusive
EVIDENCE_CAP = 12             # refs per cause (bounded records)
STRAGGLER_MIN_RATIO = 2.0     # slowest server vs median, per trace
STRAGGLER_MIN_SKEW_MS = 20.0  # absolute per-trace skew floor (noise)
REBALANCE_SATURATION = 6.0    # move-phase events for full confidence
AUTOPSY_RING_CAPACITY = 32

# compile_event trigger split: eviction rebuilds attribute to tier
# thrash, drift retraces to drift — only the rest is a compile storm
_TIER_TRIGGERS = ("lru_evict_rebuild",)
_DRIFT_TRIGGERS = ("drift_requantize", "retrace")

# the cross-plane event kinds the window assembler / whydown gather
_CROSS_KINDS = ("alert", "compile_event", "incident", "ingest_stats",
                "rebalance_event", "replay_bench", "slo_status")


# ---------------------------------------------------------------------------
# corpus loading + evidence pointers
# ---------------------------------------------------------------------------

def load_corpus(path: Optional[str]) -> List[Dict[str, Any]]:
    """Read a ledger file into seq-stamped records: each record gains
    ``_seq`` = its 1-based line number, the pointer
    ``forensics.read_ledger_since(path, seq - 1)`` resolves. The same
    torn-tail discipline as the rollup puller: a final line without a
    newline is an append in flight and is excluded, so an evidence
    pointer never names a half-written record. Unparseable lines
    advance the sequence but ship nothing."""
    records: List[Dict[str, Any]] = []
    if not path or not os.path.exists(path):
        return records
    with open(path) as fh:
        for i, line in enumerate(fh):
            if not line.endswith("\n"):
                break   # torn tail: not yet addressable
            text = line.strip()
            if not text:
                continue
            try:
                rec = json.loads(text)
            except ValueError:
                continue
            if isinstance(rec, dict):
                rec["_seq"] = i + 1
                records.append(rec)
    return records


def _stamped(records: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Hand-built corpora (tests) arrive without ``_seq``; stamp by
    list position so evidence pointers stay meaningful either way."""
    out: List[Dict[str, Any]] = []
    for i, rec in enumerate(records):
        if "_seq" not in rec:
            rec = dict(rec)
            rec["_seq"] = i + 1
        out.append(rec)
    return out


def _ref(rec: Dict[str, Any]) -> List[Any]:
    """One evidence pointer: [node, proc, seq] — node is the fleet
    provenance stamp (empty on a node-local ledger), proc the writer's
    process token (empty for kinds that don't carry one), seq the
    ledger line number from ``load_corpus``."""
    return [str(rec.get("node") or ""), str(rec.get("proc") or ""),
            int(rec.get("_seq") or 0)]


def _median(vals: List[float]) -> float:
    """Median of a SORTED list (0.0 when empty) — pure, no numpy."""
    if not vals:
        return 0.0
    n = len(vals)
    m = n // 2
    if n % 2:
        return float(vals[m])
    return (float(vals[m - 1]) + float(vals[m])) / 2.0


# ---------------------------------------------------------------------------
# window assembly
# ---------------------------------------------------------------------------

def assemble_window(records: List[Dict[str, Any]],
                    window: Optional[Tuple[float, Optional[float]]] = None
                    ) -> Dict[str, Any]:
    """Split a seq-stamped corpus into baseline + incident window.

    ``query_stats`` records are windowed on the injectable event-time
    clock (``arrival_ms + wall_ms``): baseline = completions before
    ``t0``, window = completions in ``[t0, t1]`` (``t1=None`` =
    unbounded). The cross-plane kinds carry no event time, so they
    window by LEDGER ORDER: everything after the last baseline stats
    line is in-window (append order is time order) — which also keeps
    a window query's compile events in-window even though they land in
    the ledger before the query's own stats record. Without an
    explicit window the last ``DEFAULT_WINDOW_S`` seconds of event
    time form the window (the incident auto-run default).

    Excess = sum of each non-shed window query's latency above the
    baseline p50 — the denominator every time-attribution fraction
    divides by."""
    stats = [r for r in records if r.get("kind") == "query_stats"]
    times = [event_time(r) for r in stats]
    known = [t for t in times if t is not None]
    if window is not None:
        t0, t1 = window
    else:
        t1 = max(known) if known else 0.0
        t0 = t1 - DEFAULT_WINDOW_S
    win_stats: List[Dict[str, Any]] = []
    base_stats: List[Dict[str, Any]] = []
    for rec, t in zip(stats, times):
        if t is None:
            continue
        if t < t0:
            base_stats.append(rec)
        elif t1 is None or t <= t1:
            win_stats.append(rec)
    cut_seq = 0
    for rec in base_stats:
        cut_seq = max(cut_seq, int(rec["_seq"]))
    events: Dict[str, List[Dict[str, Any]]] = {
        k: [] for k in _CROSS_KINDS + ("query_trace",)}
    pre: Dict[str, List[Dict[str, Any]]] = {"incident": [],
                                            "ingest_stats": []}
    for rec in records:
        kind = rec.get("kind")
        if kind in events and int(rec["_seq"]) > cut_seq:
            events[kind].append(rec)
        elif kind in pre and int(rec["_seq"]) <= cut_seq:
            pre[kind].append(rec)
    base_wall = sorted(float(r.get("wall_ms") or 0.0)
                       for r in base_stats if not r.get("shed"))
    p50 = _median(base_wall)
    excess = 0.0
    for rec in win_stats:
        if rec.get("shed"):
            continue
        excess += max(0.0, float(rec.get("wall_ms") or 0.0) - p50)
    return {"t0": t0, "t1": t1, "stats": win_stats,
            "baseline": base_stats, "cut_seq": cut_seq,
            "baseline_p50_ms": round(p50, 3),
            "excess_ms": round(excess, 3),
            "events": events, "pre": pre}


# ---------------------------------------------------------------------------
# shared scorer helpers
# ---------------------------------------------------------------------------

def _compile_split(win: Dict[str, Any]
                   ) -> Dict[str, List[Dict[str, Any]]]:
    """Window compile events partitioned by trigger family (module
    docstring): eviction rebuilds -> tier, drift retraces -> drift,
    everything else -> storm."""
    out: Dict[str, List[Dict[str, Any]]] = {"storm": [], "tier": [],
                                            "drift": []}
    for rec in win["events"]["compile_event"]:
        trig = str(rec.get("trigger") or "")
        if trig in _TIER_TRIGGERS:
            out["tier"].append(rec)
        elif trig in _DRIFT_TRIGGERS:
            out["drift"].append(rec)
        else:
            out["storm"].append(rec)
    return out


def _compile_ms(recs: List[Dict[str, Any]]) -> float:
    """Total staging time (lower + compile) over compile events."""
    total = 0.0
    for rec in recs:
        total += float(rec.get("lower_ms") or 0.0) \
            + float(rec.get("compile_ms") or 0.0)
    return total


def _excess_fraction(total_ms: float, excess_ms: float) -> float:
    """total_ms as a fraction of the window's excess, in [0, 1]."""
    if excess_ms <= 0.0 or total_ms <= 0.0:
        return 0.0
    return min(1.0, total_ms / excess_ms)


def _latest_tier_block(recs: List[Dict[str, Any]]
                       ) -> Optional[Tuple[Dict[str, Any],
                                           Dict[str, Any]]]:
    """Last incident bundle carrying a tier surface -> (record, tier
    block); the tier/devmem evidence source the bundle contributes."""
    found = None
    for rec in recs:
        surf = rec.get("surfaces")
        if isinstance(surf, dict) and isinstance(surf.get("tier"),
                                                 dict):
            found = (rec, surf["tier"])
    return found


def _cause(name: str, score: float, evidence: List[Dict[str, Any]],
           detail: str) -> Dict[str, Any]:
    """One ranked-cause row: score rounded for byte-stable verdicts,
    evidence capped and rendered as [node, proc, seq] pointers."""
    return {"cause": name, "score": round(score, 4),
            "evidence": [_ref(r) for r in evidence[:EVIDENCE_CAP]],
            "detail": detail}


# ---------------------------------------------------------------------------
# the cause scorers (one per taxonomy family, all pure)
# ---------------------------------------------------------------------------

def score_compile_storm(win: Dict[str, Any]) -> Dict[str, Any]:
    """Post-warmup compile time (non-eviction, non-drift triggers) as
    a fraction of the window's excess latency."""
    evs = _compile_split(win)["storm"]
    total = _compile_ms(evs)
    score = _excess_fraction(total, win["excess_ms"])
    pool = evs + [a for a in win["events"]["alert"]
                  if "compile" in str(a.get("alert") or "")]
    return _cause(
        "compile_storm", score, pool,
        f"post-warmup compile {total:.0f} ms over {len(evs)} event(s) "
        f"~ {score:.2f} of {win['excess_ms']:.0f} ms excess")


def score_tier_thrash(win: Dict[str, Any]) -> Dict[str, Any]:
    """Demote/re-promote churn under an armed HBM budget: the demotion
    delta between the last pre-window and last in-window incident
    bundles' tier surfaces, normalized per window query, combined with
    eviction-rebuild compile time as an excess fraction."""
    post = _latest_tier_block(win["events"]["incident"])
    pre = _latest_tier_block(win["pre"]["incident"])
    evict = _compile_split(win)["tier"]
    evict_frac = _excess_fraction(_compile_ms(evict),
                                  win["excess_ms"])
    churn = 0
    evidence = list(evict)
    if post is not None and post[1].get("armed"):
        base = int(pre[1].get("demotions") or 0) \
            if pre is not None else 0
        churn = max(0, int(post[1].get("demotions") or 0) - base)
        evidence = [post[0]] + evidence
    served = [r for r in win["stats"] if not r.get("shed")]
    churn_score = min(1.0, churn / max(1.0, float(len(served)))) \
        if churn else 0.0
    score = max(churn_score, evict_frac)
    return _cause(
        "tier_thrash", score, evidence,
        f"{churn} demotions over {len(served)} window queries; "
        f"evict-rebuild compile {_compile_ms(evict):.0f} ms")


def score_overload_shed(win: Dict[str, Any]) -> Dict[str, Any]:
    """Shed fraction of the window's queries (availability signal —
    a shed is a denied answer, not a latency sample)."""
    stats = win["stats"]
    shed = [r for r in stats if r.get("shed")]
    score = len(shed) / float(len(stats)) if stats else 0.0
    pool = shed + [a for a in win["events"]["alert"]
                   if "overload" in str(a.get("alert") or "")
                   or "shed" in str(a.get("alert") or "")]
    return _cause(
        "overload_shed", score, pool,
        f"{len(shed)}/{len(stats)} window queries shed")


def score_rebalance_churn(win: Dict[str, Any]) -> Dict[str, Any]:
    """Executed rebalance move phases inside the window (prewarm/flip/
    drain/abort) against the saturation constant."""
    moves = [r for r in win["events"]["rebalance_event"]
             if str(r.get("phase") or "") in ("prewarm", "flip",
                                              "drain", "abort")]
    score = min(1.0, len(moves) / REBALANCE_SATURATION)
    phases: Dict[str, int] = {}
    for rec in moves:
        p = str(rec.get("phase"))
        phases[p] = phases.get(p, 0) + 1
    desc = ", ".join(f"{k}={phases[k]}" for k in sorted(phases)) \
        or "none"
    return _cause(
        "rebalance_churn", score, moves,
        f"{len(moves)} move phase(s) in window ({desc})")


def _max_faults(recs: List[Dict[str, Any]]) -> int:
    m = 0
    for rec in recs:
        m = max(m, int(rec.get("faults_fired") or 0))
    return m


def score_chaos_faults(win: Dict[str, Any]) -> Dict[str, Any]:
    """Armed fault-plane activity: the faults_fired delta carried by
    ingest_stats (a process-wide cumulative counter — deltaed against
    the pre-window records) plus chaos-armed replay_bench records."""
    ing = [r for r in win["events"]["ingest_stats"]
           if int(r.get("faults_fired") or 0) > 0]
    delta = max(0, _max_faults(win["events"]["ingest_stats"])
                - _max_faults(win["pre"]["ingest_stats"]))
    rb = [r for r in win["events"]["replay_bench"]
          if int(r.get("faults_fired") or 0) > 0]
    total = delta
    for rec in rb:
        total += int(rec.get("faults_fired") or 0)
    n = max(1, len(win["stats"]))
    score = min(1.0, total / float(n)) if total else 0.0
    return _cause(
        "chaos_faults", score, ing + rb,
        f"{total} fault firing(s) across {n} window queries")


def _server_spans(node: Dict[str, Any],
                  out: Dict[str, float]) -> None:
    """Accumulate per-server scatter-call time over one span tree
    (the broker-side span includes network + server wait, so a
    delayed server shows up here)."""
    attrs = node.get("attrs") or {}
    srv = attrs.get("server")
    if srv and node.get("name") == "scatter_call":
        key = str(srv)
        out[key] = out.get(key, 0.0) + float(node.get("ms") or 0.0)
    for child in node.get("children") or ():
        _server_spans(child, out)


def score_straggler(win: Dict[str, Any]) -> Dict[str, Any]:
    """Per-server skew from the window's span trees: for each traced
    query the slowest server's scatter time above the median of the
    REMAINING servers, counted only when the skew is both relative
    (>= 2x that median) and absolute (>= 20 ms) — then discounted by the window's
    total compile time, so a one-sided warmup never reads as a
    partitioned node. The remaining skew is taken as a fraction of
    excess; hedges/failovers/partials ride along as supporting
    evidence."""
    excess = win["excess_ms"]
    qids = {str(r.get("qid")) for r in win["stats"]}
    total_skew = 0.0
    hits: Dict[str, int] = {}
    traces: List[Dict[str, Any]] = []
    for tr in win["events"]["query_trace"]:
        if qids and str(tr.get("qid")) not in qids:
            continue
        root = tr.get("root")
        if not isinstance(root, dict):
            continue
        per: Dict[str, float] = {}
        _server_spans(root, per)
        if len(per) < 2:
            continue
        top_ms, top_srv = max(
            (ms, srv) for srv, ms in sorted(per.items()))
        # skew vs the median of the OTHER servers: with the top server
        # included a 2-server cluster could never satisfy the 2x ratio
        # (median = mean of the pair)
        med = _median(sorted(ms for srv, ms in per.items()
                             if srv != top_srv))
        skew = top_ms - med
        if top_ms < STRAGGLER_MIN_RATIO * max(med, 1e-9) \
                or skew < STRAGGLER_MIN_SKEW_MS:
            continue
        total_skew += skew
        hits[top_srv] = hits.get(top_srv, 0) + 1
        traces.append(tr)
    adj = max(0.0, total_skew
              - _compile_ms(win["events"]["compile_event"]))
    score = _excess_fraction(adj, excess)
    worst = ""
    if hits:
        worst = max((c, s) for s, c in sorted(hits.items()))[1]
    support = [r for r in win["stats"]
               if r.get("hedges") or r.get("failovers")
               or r.get("partial")]
    return _cause(
        "straggler", score, traces + support,
        f"server {worst or '<none>'} slowest in "
        f"{hits.get(worst, 0)}/{len(win['events']['query_trace'])} "
        f"trace(s); unexplained skew {adj:.0f} ms "
        f"~ {score:.2f} of excess")


def score_drift_recompile(win: Dict[str, Any]) -> Dict[str, Any]:
    """Drift-triggered recompilation (retrace / drift_requantize) as a
    fraction of the window's excess latency."""
    evs = _compile_split(win)["drift"]
    total = _compile_ms(evs)
    score = _excess_fraction(total, win["excess_ms"])
    return _cause(
        "drift_recompile", score, evs,
        f"drift/retrace compile {total:.0f} ms over {len(evs)} "
        f"event(s) ~ {score:.2f} of excess")


def score_ingest_stall(win: Dict[str, Any]) -> Dict[str, Any]:
    """Freshness-objective burn inside the window: a stale gauge is
    full-confidence, otherwise burn_slow against the objective's own
    threshold; ingest_stats records over the freshness bar ride along
    as evidence."""
    rows = [r for r in win["events"]["slo_status"]
            if str(r.get("slo_kind") or "") == "freshness"]
    score = 0.0
    evidence: List[Dict[str, Any]] = []
    bars: List[float] = []
    for rec in rows:
        if rec.get("stale"):
            s = 1.0
        else:
            thr = float(rec.get("threshold")
                        or DEFAULT_BURN_THRESHOLD)
            s = min(1.0, float(rec.get("burn_slow") or 0.0)
                    / max(thr, 1e-9))
        if s > 0.0:
            evidence.append(rec)
        score = max(score, s)
        if rec.get("bar_ms") is not None:
            bars.append(float(rec["bar_ms"]))
    if bars:
        bar = min(bars)
        evidence += [r for r in win["events"]["ingest_stats"]
                     if float(r.get("freshness_ms") or 0.0) > bar]
    return _cause(
        "ingest_stall", score, evidence,
        f"{len(rows)} freshness status row(s) in window, "
        f"peak confidence {score:.2f}")


# scorer order mirrors CAUSES — the taxonomy is ranked, never pruned
SCORERS = (score_compile_storm, score_tier_thrash,
           score_overload_shed, score_rebalance_churn,
           score_chaos_faults, score_straggler,
           score_drift_recompile, score_ingest_stall)


# ---------------------------------------------------------------------------
# the verdict planner (pure — the byte-replayable surface)
# ---------------------------------------------------------------------------

def plan_autopsy(records: List[Dict[str, Any]],
                 window: Optional[Tuple[float, Optional[float]]] = None,
                 incident: Optional[Dict[str, Any]] = None,
                 proc: str = "plan") -> Dict[str, Any]:
    """Rank the full cause taxonomy over a recorded corpus -> the
    verdict dict (the ``rca_verdict`` payload minus envelope/seq).
    Pure in (records, window, incident, proc): the same corpus yields
    byte-identical verdicts under ``json.dumps(..., sort_keys=True)``
    — the traffic_replay gate's comparison object. ``inconclusive`` is
    an explicit non-answer: when no cause clears ``MIN_SCORE`` the top
    cause is left empty rather than confabulated."""
    recs = _stamped(records)
    win = assemble_window(recs, window=window)
    causes = [fn(win) for fn in SCORERS]
    causes.sort(key=lambda c: (-c["score"], c["cause"]))
    top = causes[0] if causes else None
    inconclusive = top is None or top["score"] < MIN_SCORE
    total_refs = 0
    for c in causes:
        total_refs += len(c["evidence"])
    return {
        "incident_ref": str((incident or {}).get("incident_id")
                            or ""),
        "window": {"t0": round(float(win["t0"]), 6),
                   "t1": (None if win["t1"] is None
                          else round(float(win["t1"]), 6)),
                   "stats": len(win["stats"]),
                   "baseline": len(win["baseline"]),
                   "baseline_p50_ms": win["baseline_p50_ms"],
                   "excess_ms": win["excess_ms"]},
        "causes": causes,
        "top_cause": "" if inconclusive else top["cause"],
        "inconclusive": inconclusive,
        "evidence_total": total_refs,
        "proc": proc,
    }


def _event_summary(rec: Dict[str, Any]) -> Dict[str, Any]:
    """One whydown row: the pointer plus the kind's headline fields."""
    out: Dict[str, Any] = {"kind": rec.get("kind"), "ref": _ref(rec)}
    for key in ("site", "trigger", "compile_ms", "phase", "segment",
                "donor", "receiver", "alert", "severity", "scope",
                "slo_kind", "burn_slow", "incident_id", "table",
                "freshness_ms", "faults_fired"):
        if key in rec:
            out[key] = rec[key]
    return out


def whydown(records: List[Dict[str, Any]],
            qid: Optional[str] = None,
            window: Optional[Tuple[float, float]] = None
            ) -> Dict[str, Any]:
    """The per-query autopsy lane: the cross-plane events overlapping
    one query's wall window. The target window comes from the query's
    own stats record (``arrival_ms``..``arrival_ms + wall_ms``) or an
    explicit ``window`` in event-time seconds; overlap for the
    timeless cross-plane kinds is by ledger position — every event
    between the first and last overlapping query's ledger lines.
    Pure, same determinism contract as ``plan_autopsy``."""
    recs = _stamped(records)
    stats = [r for r in recs if r.get("kind") == "query_stats"]
    target = None
    if qid is not None:
        for rec in stats:
            if str(rec.get("qid")) == str(qid):
                target = rec   # last record wins (retries share qids)
    if window is not None:
        a0, a1 = float(window[0]), float(window[1])
    elif target is not None and target.get("arrival_ms") is not None:
        a = float(target["arrival_ms"])
        a0 = a / 1e3
        a1 = (a + float(target.get("wall_ms") or 0.0)) / 1e3
    else:
        return {"qid": "" if qid is None else str(qid),
                "found": False, "window": None, "queries": 0,
                "events": []}
    touched: List[Dict[str, Any]] = []
    for rec in stats:
        t_a = rec.get("arrival_ms")
        if t_a is None:
            continue
        s0 = float(t_a) / 1e3
        s1 = (float(t_a) + float(rec.get("wall_ms") or 0.0)) / 1e3
        if s1 >= a0 and s0 <= a1:
            touched.append(rec)
    if not touched:
        return {"qid": "" if qid is None else str(qid),
                "found": target is not None,
                "window": [round(a0, 6), round(a1, 6)],
                "queries": 0, "events": []}
    lo = min(int(r["_seq"]) for r in touched)
    hi = max(int(r["_seq"]) for r in touched)
    events = [_event_summary(r) for r in recs
              if r.get("kind") in _CROSS_KINDS
              and lo <= int(r["_seq"]) <= hi]
    return {"qid": "" if qid is None else str(qid),
            "found": target is not None,
            "window": [round(a0, 6), round(a1, 6)],
            "queries": len(touched), "events": events}


# ---------------------------------------------------------------------------
# the live plane (ring + ledger sink + incident hook)
# ---------------------------------------------------------------------------

class AutopsyPlane:
    """Live wrapper over ``plan_autopsy``: loads the configured
    ledger, lands the verdict as a validated ``rca_verdict`` record in
    the SAME ledger, keeps a bounded ring for ``GET /debug/autopsy``
    and attaches the verdict ref onto the originating incident's ring
    entry. ``on_incident`` is the ``IncidentRecorder.post_hook``
    target — it runs on the recorder's background capture thread,
    fully fenced, so attribution never sits on the query path."""

    def __init__(self):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=AUTOPSY_RING_CAPACITY)
        self._seq = 0
        self.path: Optional[str] = None  # guarded-by: none — config
        self.computed = 0
        self.errors = 0

    def run(self, incident: Optional[Dict[str, Any]] = None,
            ledger_path: Optional[str] = None,
            window: Optional[Tuple[float, Optional[float]]] = None,
            ts: Optional[str] = None) -> Dict[str, Any]:
        """One attribution pass: corpus -> verdict -> ledger + ring.
        ``ledger_path`` overrides the evidence source (the controller
        runs over the fleet ledger); the verdict record always lands
        in ``self.path`` when configured. ``ts`` is the injectable
        ledger timestamp (deterministic emitters)."""
        path = ledger_path or self.path
        records = load_corpus(path)
        verdict = plan_autopsy(records, window=window,
                               incident=incident, proc=PROC_TOKEN)
        with self._lock:
            self._seq += 1
            seq = self._seq
        fields = dict(verdict)
        fields["seq"] = seq
        if path:
            fields["ledger"] = path
        if ts is not None:
            fields["ts"] = ts
        rec = uledger.make_record("rca_verdict", **fields)
        if self.path:
            try:
                uledger.append_record(rec, self.path)
            except OSError:
                # observability must never fail the data path (the
                # forensics write policy)
                global_metrics.count("rca_verdict_write_errors")
        with self._lock:
            self._ring.append(rec)
            self.computed += 1
        global_metrics.count("autopsies_computed")
        if incident is not None:
            global_incidents.attach_verdict(
                str(incident.get("incident_id") or ""),
                {"proc": rec["proc"], "seq": seq,
                 "top_cause": rec["top_cause"],
                 "inconclusive": rec["inconclusive"]})
        return rec

    def on_incident(self, incident_rec: Dict[str, Any]) -> None:
        """The post-snapshot hook (IncidentRecorder.post_hook): runs
        attribution for a freshly captured incident — background
        thread, fenced, never raises into the recorder."""
        try:
            self.run(incident=incident_rec)
        except Exception:
            with self._lock:
                self.errors += 1
            global_metrics.count("autopsy_errors")

    # -- serving (GET /debug/autopsy) --------------------------------------
    def snapshot(self, limit: Optional[int] = None) -> Dict[str, Any]:
        with self._lock:
            verdicts = list(self._ring)[::-1]
        count = len(verdicts)   # ring size, not the limited slice
        if limit is not None:
            verdicts = verdicts[:max(limit, 0)]
        return {"count": count, "computed": self.computed,
                "errors": self.errors, "ledger": self.path,
                "verdicts": verdicts}

    def reset(self) -> None:
        """Test isolation: clear the ring/counters; the seq counter
        survives — (proc, seq) is a verdict's identity (the incident
        discipline)."""
        with self._lock:
            self._ring.clear()
            self.computed = 0
            self.errors = 0


global_autopsy = AutopsyPlane()
