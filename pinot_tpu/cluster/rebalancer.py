"""Closed-loop rebalancer (round 24): SLO-burn-driven self-healing
placement with incident freeze, churn budgets and crash-safe cutover.

The reference's TableRebalancer (mirrored statically as
``Controller.rebalance()``) moves segments when an operator asks. This
task closes ROADMAP direction 5's loop instead: every pass it reads the
fleet rollup's ``slo``/``heat``/``plan_shapes`` blocks (cluster/
rollup.py), computes a **pure move plan** and executes it as crash-safe
three-phase cutovers:

1. **Plan** — ``plan_moves(rollup, assignment, ...)`` is a
   deterministic function of its inputs (detlint entry registry,
   DT301–DT305 clean): tables whose slow-window burn crosses the
   threshold donate their hottest segments from the worst-burn /
   most-loaded holder to the receiver with the best tier-residency
   affinity (round-18 heartbeats), capped under a bytes+moves churn
   budget per pass; the plan is EMPTY while any incident is open
   (round-22 flight recorder) — never churn placement mid-incident.
2. **Pre-warm** — the receiver is appended to the segment's holders
   (over-replication; ``_reconcile_locked`` keeps both replicas while
   both are live), its next assignment poll downloads + loads the
   segment, and the pass waits for the segment to show in the
   receiver's residency heartbeat. When the compile plane is staging,
   the prewarm event records the table's top ``plan_shapes`` so the
   receiver's warmup debt is prepaid by the executable plane. A stall
   past the deadline (``cutover.stall``) aborts: receiver removed,
   journal cleared, donor keeps serving.
3. **Flip + drain** — donor removed from holders under the
   controller's state machinery (brokers converge via the
   assignment-version epoch on heartbeat responses), then the donor's
   copy drains through the tier's WARM demotion path
   (``TierManager.drain`` — device residents drop, padded host arrays
   stay, no cold re-pad; in-flight queries finish on references they
   already hold).

Crash safety follows the rollup-cursor discipline: a single-move
journal (``rebalance_journal.json``, tmp+rename) records the move
before each irreversible phase. A controller crash / leader failover
mid-move (``rebalance.crash`` fires in the cutover window, before the
flip journal commit) leaves the journal behind; the next pass — same
controller or the new leader over the shared data dir — resumes the
journaled move idempotently (holder append and donor removal are both
idempotent; exactly one final assignment, never a double-assign) or
rolls it back if the receiver never warmed. Torn journal tmp files are
dropped on load (``_clean_orphans``).

Every phase appends a validated ``rebalance_event`` v2 ledger record
(utils/ledger.py — the writer-side contract lives here) to the fleet
ledger and mirrors it into a bounded ring served at controller
``GET /debug/rebalance`` and the webapp Fleet "moves" panel.

Gates: ``tools/traffic_replay.py --rebalance`` (observed move stream
byte-equal to the precomputed plan, zero digest drift, protected
tenant inside its bar, burn lower after convergence, fewer uploads/
affinity misses) and ``tools/chaos_smoke.py --rebalance`` (seeded
crash/stall recovery, incident freeze, pool reconciliation).
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..utils import ledger as uledger
from ..utils.faults import FaultInjected, fault_fires, fault_point
from ..utils.metrics import global_metrics

# churn budget defaults: at most this many moves / journalled bytes per
# pass — rebalance heals placement, it must never become the load
DEFAULT_BURN_THRESHOLD = 1.0   # slow-window burn >= 1.0: budget exhausting
DEFAULT_MOVES_PER_PASS = 2
DEFAULT_BYTES_PER_PASS = 256 << 20
PREWARM_TIMEOUT_S = 15.0
PREWARM_POLL_S = 0.05
RING_CAP = 256
# passes a completed move's segment sits out of planning: the slow burn
# window (minutes) outlives a cutover (seconds), so a fresh-enough-looking
# rollup would otherwise nominate the segment straight back (ping-pong)
RECENT_COOLDOWN_PASSES = 5

# receiver affinity from tier residency heartbeats: a copy already on
# device beats a warm copy beats nothing (round-18 placement signal)
_AFFINITY = {"hot": 3, "cube": 2, "warm": 1}


class RebalanceCrash(FaultInjected):
    """Injected controller death inside the cutover window (the
    ``rebalance.crash`` point): raised between receiver pre-warm and
    the flip journal commit — recovery must resume from the journal."""


# -- the pure planning plane (detlint ROOTS members) -----------------------

def incident_frozen(rollup: Optional[Dict[str, Any]]) -> bool:
    """Freeze predicate: any open incident in the fleet SLO block means
    the pass plans ZERO moves — placement churn during an incident
    destroys the evidence the flight recorder just captured."""
    slo = (rollup or {}).get("slo") or {}
    return int(slo.get("open_incidents", 0) or 0) > 0


def burning_tables(rollup: Optional[Dict[str, Any]],
                   threshold: float = DEFAULT_BURN_THRESHOLD
                   ) -> List[Tuple[str, float]]:
    """(table, worst slow-window burn) for table-scoped objectives at or
    over the threshold, worst first (scope is the deterministic
    tiebreak). Tenant-scoped objectives don't nominate tables — a
    tenant burn names no segments to move."""
    slo = (rollup or {}).get("slo") or {}
    worst: Dict[str, float] = {}
    for o in slo.get("objectives") or []:
        scope = str(o.get("scope") or "")
        if not scope or scope.startswith("tenant:"):
            continue
        burn = float(o.get("burn_slow", 0.0) or 0.0)
        if burn >= threshold and burn > worst.get(scope, 0.0):
            worst[scope] = burn
    return sorted(worst.items(), key=lambda e: (-e[1], e[0]))


def receiver_affinity(instances: Dict[str, Any], table: str,
                      segment: str, instance_id: str) -> int:
    """Residency-affinity score for placing (table, segment) on the
    instance, from its heartbeat residency block."""
    inst = instances.get(instance_id) or {}
    res = ((inst.get("residency") or {}).get(table)) or {}
    return _AFFINITY.get(res.get(segment), 0)


def churn_capped(moves: List[Dict[str, Any]],
                 budget: Optional[Dict[str, Any]] = None
                 ) -> List[Dict[str, Any]]:
    """Budget predicate: the longest rank-order prefix within the
    bytes+moves churn budget. The first move always fits — a segment
    larger than the byte budget must still be movable, just alone."""
    budget = budget or {}
    max_moves = int(budget.get("moves", DEFAULT_MOVES_PER_PASS))
    max_bytes = int(budget.get("bytes", DEFAULT_BYTES_PER_PASS))
    out: List[Dict[str, Any]] = []
    total = 0
    for m in moves:
        if len(out) >= max_moves:
            break
        b = int(m.get("bytes", 0))
        if out and total + b > max_bytes:
            break
        out.append(m)
        total += b
    return out


def plan_moves(rollup: Optional[Dict[str, Any]],
               assignment: Dict[str, Dict[str, List[str]]],
               now: Optional[float] = None,
               budget: Optional[Dict[str, Any]] = None,
               instances: Optional[Dict[str, Any]] = None,
               sizes: Optional[Dict[str, int]] = None,
               recent: Optional[frozenset] = None,
               threshold: float = DEFAULT_BURN_THRESHOLD
               ) -> List[Dict[str, Any]]:
    """The pure move plan: a deterministic function of the fleet rollup
    (slo burn + heat + per-node briefs), the assignment table, the
    instance registry snapshot (role + residency) and the segment size
    map. No wall clock (``now`` is an injected input, reserved for
    age-based policies), no ambient randomness, no IO — execution-side
    impurity (journal, HTTP, sleeps) lives in ClosedLoopRebalanceTask.

    Per burning table (worst burn first), hottest segments first (fleet
    heat rank, name tiebreak): donate from the worst-burn then
    most-loaded holder, receive on the non-holder with the best
    residency affinity, then least load, then least burn, then id.
    ``recent`` (``table/segment`` keys moved within the cooldown —
    execution state, fed in as data) is the anti-flap guard: a burn
    window outlives a cutover, so without it the next pass would read
    the same stale burn and plan the segment straight back. Returns
    ``[]`` while any incident is open; the ranked list is capped by
    ``churn_capped``.
    """
    del now  # deterministic planners take time as data; none needed yet
    if rollup is None or incident_frozen(rollup):
        return []
    assignment = assignment or {}
    instances = instances or {}
    sizes = sizes or {}
    recent = recent or frozenset()
    servers = sorted(i for i in instances
                     if (instances[i] or {}).get("role") == "server")
    if len(servers) < 2:
        return []
    # current per-server replica load: donor/receiver tiebreaks, updated
    # as the plan allocates so one pass spreads rather than piles on
    load: Dict[str, int] = {s: 0 for s in servers}
    for table in sorted(assignment):
        for seg in sorted(assignment[table]):
            for h in assignment[table][seg]:
                if h in load:
                    load[h] += 1
    node_burn: Dict[str, float] = {}
    for n in sorted((rollup.get("nodes") or {})):
        brief = ((rollup["nodes"][n] or {}).get("slo")) or {}
        node_burn[n] = float(brief.get("worst_burn_slow", 0.0) or 0.0)
    heat_rank = {(r.get("table"), r.get("segment")): i
                 for i, r in enumerate(rollup.get("heat") or [])}
    moves: List[Dict[str, Any]] = []
    for table, burn in burning_tables(rollup, threshold):
        segs = assignment.get(table) or {}
        hot_first = sorted(
            segs, key=lambda s: (heat_rank.get((table, s),
                                               len(heat_rank)), s))
        for seg in hot_first:
            if f"{table}/{seg}" in recent:
                continue
            holders = [h for h in segs.get(seg) or [] if h in load]
            if not holders:
                continue
            receivers = [s for s in servers if s not in holders]
            if not receivers:
                continue
            donor = sorted(
                holders,
                key=lambda h: (-node_burn.get(h, 0.0),
                               -load.get(h, 0), h))[0]
            receiver = sorted(
                receivers,
                key=lambda s: (-receiver_affinity(instances, table,
                                                  seg, s),
                               load.get(s, 0),
                               node_burn.get(s, 0.0), s))[0]
            load[donor] -= 1
            load[receiver] += 1
            moves.append({
                "table": table, "segment": seg,
                "donor": donor, "receiver": receiver,
                "bytes": int(sizes.get(f"{table}/{seg}", 0)),
                "reason": f"burn_slow={burn:.3f}",
            })
    return churn_capped(moves, budget)


# -- execution plane -------------------------------------------------------

def _dir_bytes(path: Optional[str]) -> int:
    """On-disk size of a local segment dir (0 for URIs/missing): the
    churn-budget charge. Deterministically ordered walk — sizes feed
    the pure plan as data."""
    if not path or "://" in path or not os.path.isdir(path):
        return 0
    total = 0
    for root, dirs, files in os.walk(path):
        dirs.sort()
        for f in sorted(files):
            try:
                total += os.path.getsize(os.path.join(root, f))
            except OSError:
                pass
    return total


class ClosedLoopRebalanceTask:
    """The leader-gated periodic pass (module docstring). ``run()`` is
    also the manual-trigger body (POST /periodictask/run/
    ClosedLoopRebalance) and the chaos gates' direct entry."""

    NAME = "ClosedLoopRebalance"

    def __init__(self, controller,
                 journal_path: Optional[str] = None,
                 burn_threshold: float = DEFAULT_BURN_THRESHOLD,
                 budget_moves: int = DEFAULT_MOVES_PER_PASS,
                 budget_bytes: int = DEFAULT_BYTES_PER_PASS,
                 prewarm_timeout: float = PREWARM_TIMEOUT_S):
        self.controller = controller
        self.journal_path = journal_path or os.path.join(
            controller.data_dir, "rebalance_journal.json")
        self.burn_threshold = burn_threshold
        self.budget_moves = budget_moves
        self.budget_bytes = budget_bytes
        self.prewarm_timeout = prewarm_timeout
        # _run_lock serializes whole passes (periodic fire vs manual
        # trigger vs direct run()); _lock guards the served ring/
        # counters so GET /debug/rebalance never reads mid-mutation.
        # Blocking under _run_lock is BY DESIGN (the rollup-task
        # pattern): a pass IS journal writes, controller flips and
        # pre-warm waits, and nothing latency-sensitive ever contends
        # on it — snapshot()/the REST surface take only _lock. The
        # CC202 suppressions below all carry this rationale.
        self._run_lock = threading.Lock()
        self._lock = threading.Lock()
        self._ring: List[Dict[str, Any]] = []  # guarded-by: _lock
        self.passes = 0             # guarded-by: _lock
        self.moves_executed = 0     # guarded-by: _lock
        self.moves_aborted = 0      # guarded-by: _lock
        self.moves_resumed = 0      # guarded-by: _lock
        self.frozen_passes = 0      # guarded-by: _lock
        self.last_plan: List[Dict[str, Any]] = []  # guarded-by: _lock
        # anti-flap cooldown: "table/segment" -> pass number the key
        # expires at; fed to plan_moves as a frozenset (pure input)
        self._recent: Dict[str, int] = {}  # guarded-by: _lock
        self._clean_orphans()

    # -- journal (rollup-cursor discipline: tmp+rename, torn tmp dropped) --
    def _journal(self, state: Dict[str, Any]) -> None:
        tmp = self.journal_path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(state, fh)
        os.replace(tmp, self.journal_path)  # concur: ok CC202

    def _unjournal(self) -> None:
        try:
            os.unlink(self.journal_path)
        except OSError:
            pass

    def _load_journal(self) -> Optional[Dict[str, Any]]:
        try:
            with open(self.journal_path) as fh:
                state = json.load(fh)
        except (OSError, ValueError):
            return None
        return state if isinstance(state, dict) and \
            isinstance(state.get("move"), dict) else None

    def _clean_orphans(self) -> None:
        """A crash mid-journal-write leaves ``.tmp`` behind; the rename
        never landed, so the committed journal (if any) is the truth —
        drop the orphan."""
        tmp = self.journal_path + ".tmp"
        try:
            os.unlink(tmp)
        except OSError:
            pass

    # -- audit stream ------------------------------------------------------
    def _event(self, phase: str, move: Dict[str, Any],
               reason: Optional[str] = None, planned: bool = True
               ) -> Dict[str, Any]:
        rec = uledger.make_record(
            "rebalance_event",
            table=str(move.get("table", "*")),
            segment=str(move.get("segment", "*")),
            donor=str(move.get("donor", "")),
            receiver=str(move.get("receiver", "")),
            phase=phase,
            reason=reason if reason is not None
            else str(move.get("reason", "")),
            bytes=int(move.get("bytes", 0)),
            planned=bool(planned))
        try:
            uledger.append_record(rec,
                                  self.controller.rollup.ledger_path)
        except OSError:
            pass  # ledger dir gone mid-teardown: the ring still serves
        with self._lock:
            self._ring.append(rec)
            if len(self._ring) > RING_CAP:
                del self._ring[: len(self._ring) - RING_CAP]
        global_metrics.count("rebalance_events")
        global_metrics.count(f"rebalance_{phase}")
        return rec

    # -- plan inputs (execution-side snapshot, fed to the pure plan) -------
    def _plan_inputs(self) -> Dict[str, Any]:
        c = self.controller
        now = time.monotonic()
        with c._lock:
            assignment = json.loads(json.dumps(c._state["assignment"]))
            locations = {
                t: {s: (e or {}).get("location")
                    for s, e in segs.items()}
                for t, segs in c._state["segments"].items()}
            instances = {
                i["id"]: {"role": i.get("role"),
                          "residency": i.get("residency")}
                for i in c._instances.values()
                if now - i["lastHeartbeat"] <= c.heartbeat_timeout}
        sizes: Dict[str, int] = {}
        for t in sorted(locations):
            for s in sorted(locations[t]):
                sizes[f"{t}/{s}"] = _dir_bytes(locations[t][s])
        return {"assignment": assignment, "instances": instances,
                "sizes": sizes}

    def _budget(self) -> Dict[str, int]:
        return {"moves": self.budget_moves, "bytes": self.budget_bytes}

    # -- the pass ----------------------------------------------------------
    def run(self) -> Dict[str, Any]:
        with self._run_lock:
            return self._run_locked()  # concur: ok CC202

    def _run_locked(self) -> Dict[str, Any]:
        self._clean_orphans()
        rollup = (self.controller.rollup.snapshot() or {}).get("rollup")
        # a journaled move from a crashed pass / failed-over leader
        # finishes FIRST, even under freeze: abandoning a half-flipped
        # move is worse than finishing it — crash safety beats policy
        resumed = self._recover()  # concur: ok CC202
        if rollup is not None and incident_frozen(rollup):
            with self._lock:
                self.frozen_passes += 1
                self.passes += 1
                self.last_plan = []
            self._event("freeze", {}, reason="incident_open",
                        planned=False)
            return {"planned": 0, "executed": 0, "aborted": 0,
                    "resumed": resumed, "frozen": True}
        if resumed:
            # a resumed move came from an OLDER pass's plan and just
            # changed placement; this pass's rollup predates it, so any
            # fresh plan would be stale-on-arrival (and can nominate the
            # just-moved segment back). Plan on the next pass instead.
            with self._lock:
                self.passes += 1
                self.last_plan = []
            return {"planned": 0, "executed": 0, "aborted": 0,
                    "resumed": resumed, "frozen": False}
        inputs = self._plan_inputs()
        with self._lock:
            recent = frozenset(k for k, exp in self._recent.items()
                               if exp > self.passes)
        moves = plan_moves(rollup, inputs["assignment"],
                           budget=self._budget(),
                           instances=inputs["instances"],
                           sizes=inputs["sizes"],
                           recent=recent,
                           threshold=self.burn_threshold)
        with self._lock:
            self.last_plan = [dict(m) for m in moves]
        executed = aborted = 0
        for m in moves:
            self._event("plan", m)
            if self._execute_move(m) == "done":  # concur: ok CC202
                executed += 1
            else:
                aborted += 1
        with self._lock:
            self.passes += 1
        return {"planned": len(moves), "executed": executed,
                "aborted": aborted, "resumed": resumed, "frozen": False}

    def _recover(self) -> int:
        st = self._load_journal()
        if st is None:
            return 0
        move = st["move"]
        phase = str(st.get("phase", "prewarm"))
        self._event("resume", move, reason=f"journal:{phase}")
        with self._lock:
            self.moves_resumed += 1
        self._execute_move(move, resume_phase=phase)  # concur: ok CC202
        return 1

    # -- the three-phase cutover -------------------------------------------
    def _execute_move(self, move: Dict[str, Any],
                      resume_phase: Optional[str] = None) -> str:
        site = f"rebalance/{move['table']}/{move['segment']}"
        if resume_phase is None:
            self._journal({"move": move,  # concur: ok CC202
                           "phase": "prewarm"})
            self._event("prewarm", move,
                        reason=self._prewarm_reason(move))
        if resume_phase != "flip":
            # phase 1: over-replicate onto the receiver (idempotent —
            # a resumed prewarm re-appends and re-waits)
            self._add_holder(move)  # concur: ok CC202
            stalled = False
            try:
                fault_point("cutover.stall", site)
            except OSError:
                stalled = True
            if stalled or not self._wait_prewarm(move):  # concur: ok CC202
                # abort: the donor never stopped serving; roll the
                # receiver back out and clear the journal
                self._remove_holder(move,  # concur: ok CC202
                                    move["receiver"])
                self._unjournal()
                self._event("abort", move, reason="prewarm_timeout")
                with self._lock:
                    self.moves_aborted += 1
                return "aborted"
            # the cutover window: a controller death here (before the
            # flip journal commit) must resume from the prewarm journal
            if fault_fires("rebalance.crash", site):
                raise RebalanceCrash(
                    f"injected fault rebalance.crash ({site})")
            self._journal({"move": move,  # concur: ok CC202
                           "phase": "flip"})
        # phase 2: flip — remove the donor under the controller's state
        # machinery; brokers converge on the heartbeat epoch
        self._event("flip", move)
        self._remove_holder(move, move["donor"])  # concur: ok CC202
        # phase 3: drain the donor's copy via WARM demotion (no cold
        # re-pad; in-flight queries finish on refs they already hold)
        self._event("drain", move)
        from ..engine.tier import global_tier
        global_tier.drain(move["segment"], reason="rebalance",
                          table=move["table"])
        self._unjournal()
        with self._lock:
            self.moves_executed += 1
            self._recent[f"{move['table']}/{move['segment']}"] = \
                self.passes + RECENT_COOLDOWN_PASSES
        return "done"

    def _prewarm_reason(self, move: Dict[str, Any]) -> str:
        """When the compile plane is staging, name the table's top
        plan_shapes in the prewarm record — the receiver's warmup debt
        the executable plane should prepay before traffic flips."""
        from ..utils.compileplane import staging_enabled
        if not staging_enabled():
            return str(move.get("reason", ""))
        rollup = (self.controller.rollup.snapshot() or {}).get(
            "rollup") or {}
        shapes = [s.get("plan_shape") for s in
                  (rollup.get("plan_shapes") or [])[:4]
                  if isinstance(s, dict)]
        return f"{move.get('reason', '')};stage_shapes={len(shapes)}"

    def _add_holder(self, move: Dict[str, Any]) -> None:
        c = self.controller
        with c._lock:
            holders = c._state["assignment"].setdefault(
                move["table"], {}).setdefault(move["segment"], [])
            if move["receiver"] not in holders:
                holders.append(move["receiver"])
                c._bump()  # concur: ok CC202

    def _remove_holder(self, move: Dict[str, Any],
                       instance_id: str) -> None:
        c = self.controller
        with c._lock:
            holders = c._state["assignment"].get(
                move["table"], {}).get(move["segment"])
            # never strand a segment at zero holders: the donor only
            # leaves once another replica is in the holder list
            if holders and instance_id in holders and len(holders) > 1:
                holders.remove(instance_id)
                c._bump()  # concur: ok CC202

    def _wait_prewarm(self, move: Dict[str, Any]) -> bool:
        """Block until the receiver's residency heartbeat shows the
        segment loaded (any tier — presence means the download+load
        completed), or the deadline passes."""
        c = self.controller
        deadline = time.monotonic() + self.prewarm_timeout
        while time.monotonic() < deadline:
            with c._lock:
                inst = c._instances.get(move["receiver"]) or {}
                res = ((inst.get("residency") or {})
                       .get(move["table"])) or {}
            if move["segment"] in res:
                return True
            time.sleep(PREWARM_POLL_S)  # concur: ok CC202
        return False

    # -- serving (GET /debug/rebalance, webapp Fleet moves panel) ----------
    def snapshot(self, limit: int = RING_CAP) -> Dict[str, Any]:
        pending = self._load_journal()  # file IO outside _lock
        with self._lock:
            ring = [dict(r) for r in self._ring[-max(limit, 0):]]
            return {"passes": self.passes,
                    "executed": self.moves_executed,
                    "aborted": self.moves_aborted,
                    "resumed": self.moves_resumed,
                    "frozen_passes": self.frozen_passes,
                    "burn_threshold": self.burn_threshold,
                    "budget": {"moves": self.budget_moves,
                               "bytes": self.budget_bytes},
                    "pending": pending,
                    "cooldown": sorted(
                        k for k, exp in self._recent.items()
                        if exp > self.passes),
                    "last_plan": [dict(m) for m in self.last_plan],
                    "count": len(ring), "moves": ring}
