"""Query forensics plane: slow-query ring + per-query stats ledger.

Reference parity: the reference's broker query log (BaseBrokerRequest
Handler logs table/timeMs/exceptions per request, rate-limited) and the
/debug/... admin endpoints, collapsed to one broker-side object:

- every completed cluster query builds a VALIDATED ``query_stats``
  ledger record (utils/ledger.py kind: wall ms, partialResult,
  exceptions[] codes, hedge/failover counts, servers queried vs
  responded) and appends it to the configured stats ledger, so chaos
  soaks (tools/chaos_smoke.py) produce per-query trend lines instead of
  only aggregate counters;
- queries that were slow (``OPTION(slowQueryMs=...)`` or the broker
  default), errored, or carried a stitched trace (EXPLAIN ANALYZE) also
  enter a bounded ring buffer served at ``GET /debug/queries`` and
  rendered by the /ui console + controller webapp.

The ring is the one deliberately host-synchronous piece (a deque under
a lock, mutated per query) — it lives on the broker's HTTP path, never
inside kernels.
"""
from __future__ import annotations

import json
import os
import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ..query.sql import SqlError
from ..utils import ledger as uledger
from ..utils.metrics import global_metrics
from ..utils.slo import global_incidents, global_slo

DEFAULT_SLOW_QUERY_MS = 500.0
DEFAULT_TRACE_RATIO = 0.0
RING_CAPACITY = 128

# process identity for fleet rollups: in-process clusters run several
# node roles in ONE interpreter sharing global_metrics / heat / devmem —
# the controller's rollup dedupes those per-node blocks by this token so
# fleet totals never multiply-count a shared registry
PROC_TOKEN = f"{os.getpid()}-{uuid.uuid4().hex[:6]}"


def parse_slow_query_ms(options: Dict[str, Any],
                        default_ms: float) -> float:
    """Validate OPTION(slowQueryMs=...) up front — a bad value must be a
    400-class SqlError BEFORE any work is dispatched, not a ValueError
    after the scatter already ran."""
    raw = options.get("slowQueryMs")
    if raw is None:
        return default_ms
    try:
        return max(float(raw), 0.0)
    except (TypeError, ValueError):
        raise SqlError(f"invalid slowQueryMs value {raw!r}; "
                       "expected a number of milliseconds") from None


def ratio_value(raw: Any, what: str = "traceRatio") -> float:
    """A sampling ratio in [0, 1] or a 400-class SqlError — shared by
    the per-query option and the broker-default / env configuration so
    a bad PINOT_TRACE_RATIO fails at startup, not per query."""
    try:
        v = float(raw)
    except (TypeError, ValueError):
        raise SqlError(f"invalid {what} value {raw!r}; "
                       "expected a fraction in [0, 1]") from None
    if not 0.0 <= v <= 1.0:
        raise SqlError(f"invalid {what} value {raw!r}; "
                       "expected a fraction in [0, 1]")
    return v


def parse_trace_ratio(options: Dict[str, Any], default: float) -> float:
    """Validate OPTION(traceRatio=...) pre-dispatch (400-class on a bad
    value); absent option -> the broker default."""
    raw = options.get("traceRatio")
    if raw is None:
        return default
    return ratio_value(raw)


def default_trace_ratio(override: Optional[float] = None) -> float:
    """The broker-default sampling ratio, shared by the in-process
    Broker and BrokerNode/QueryForensics so their precedence can't
    diverge: constructor override wins, then PINOT_TRACE_RATIO, then
    off — validated either way, so a bad env value fails at broker
    startup rather than per query."""
    if override is not None:
        return ratio_value(override)
    env_ratio = os.environ.get("PINOT_TRACE_RATIO")
    if env_ratio is not None:
        return ratio_value(env_ratio)
    return DEFAULT_TRACE_RATIO


class QueryForensics:
    """Per-broker forensics state: the slow-query ring and the optional
    query_stats ledger sink."""

    def __init__(self, slow_query_ms: Optional[float] = None,
                 ledger_path: Optional[str] = None,
                 capacity: int = RING_CAPACITY,
                 trace_ratio: Optional[float] = None):
        env_slow = os.environ.get("PINOT_SLOW_QUERY_MS")
        self.default_slow_ms = float(
            slow_query_ms if slow_query_ms is not None
            else env_slow if env_slow is not None
            else DEFAULT_SLOW_QUERY_MS)
        self.ledger_path = (ledger_path
                            or os.environ.get("PINOT_QUERY_STATS_LEDGER")
                            or None)
        # traceRatio production sampling default (OPTION(traceRatio=...)
        # overrides per query)
        self.trace_ratio = default_trace_ratio(trace_ratio)
        self.stats_written = 0
        self.traces_written = 0
        self._ring: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        # epoch for the query_stats ``arrival_ms`` offsets: the ledger's
        # envelope ts has 1 s resolution, far too coarse for the
        # traffic-replay harness's inter-arrival deltas
        # (tools/traffic_replay.py) — arrival offsets are recorded in
        # ms against this per-broker epoch instead
        self._epoch = time.perf_counter()

    # -- recording ---------------------------------------------------------
    def record(self, qid: str, table: Optional[str], sql: str, t0: float,
               result: Optional[Any], scatters: List[Any],
               slow_ms: Optional[float] = None,
               trace: Optional[Any] = None,
               error: Optional[BaseException] = None,
               traced: bool = False,
               workload: Optional[Dict[str, Any]] = None
               ) -> Dict[str, Any]:
        """Build + validate the query_stats record for one completed (or
        failed) cluster query; append it to the stats ledger when one is
        configured, and admit slow/errored/traced queries to the ring.
        Returns the validated record."""
        wall_ms = (time.perf_counter() - t0) * 1e3
        threshold = self.default_slow_ms if slow_ms is None else slow_ms
        slow = wall_ms >= threshold
        fields: Dict[str, Any] = {
            "qid": qid,
            "table": table or "<compound>",
            "wall_ms": round(wall_ms, 3),
            "partial": bool(getattr(result, "partial_result", False)),
            "servers_queried": int(
                getattr(result, "num_servers_queried", 0) or 0),
            "servers_responded": int(
                getattr(result, "num_servers_responded", 0) or 0),
            "exception_codes": sorted({
                int(e.get("errorCode", 0))
                for e in getattr(result, "exceptions", []) or []}),
            "sql": sql,
            "hedges": sum(getattr(s, "hedges", 0) for s in scatters),
            "failovers": sum(getattr(s, "failovers", 0)
                             for s in scatters),
            # ms since this broker's forensics epoch: the inter-arrival
            # signal tools/traffic_replay.py replays at multiples
            "arrival_ms": round((t0 - self._epoch) * 1e3, 3),
        }
        if workload:
            # overload plane attribution (broker/workload.py): tenant,
            # degraded rung, and — on a shed — shed/shed_rung/
            # retry_after_ms, the per-table/tenant shed-rate trend line
            # the fleet rollup aggregates
            fields.update(workload)
        if result is not None:
            fields["rows"] = len(result.rows)
            fields["segments_queried"] = result.num_segments
            fields["segments_pruned"] = result.num_segments_pruned
        if slow:
            fields["slow"] = True
        if error is not None:
            fields["error"] = str(error)[:300]
        if traced or trace is not None:
            # stats<->trace join key: the query_trace record in this
            # ledger carries the same qid
            fields["traced"] = True
        serde = sum(getattr(s, "serde_ms", 0.0) for s in scatters)
        net = sum(getattr(s, "net_ms", 0.0) for s in scatters)
        if serde:
            fields["serde_ms"] = round(serde, 3)
        if net:
            fields["net_ms"] = round(net, 3)
        # cross-query micro-batching (PR 8): fused dispatches this
        # query's server executions participated in + the largest
        # batch shared — the throughput plane's query_stats trend line
        batched = sum(getattr(s, "batched_dispatches", 0)
                      for s in scatters)
        if batched:
            fields["batched"] = batched
            fields["batch_size"] = max(
                getattr(s, "batch_size_max", 0) for s in scatters)
        # placement-affinity routing (HBM tier): segments dispatched to
        # a replica already holding them hot — the per-query
        # avoided-upload trend line the fleet rollup aggregates
        affinity = sum(getattr(s, "affinity_hits", 0) for s in scatters)
        if affinity:
            fields["tier_affinity_hits"] = affinity
        rec = uledger.make_record("query_stats", **fields)
        if self.ledger_path:
            try:
                uledger.append_record(rec, self.ledger_path)
                with self._lock:
                    self.stats_written += 1
            except OSError:
                # observability must never fail the data path: a full
                # disk / missing directory drops the record, counted so
                # the loss is visible (the record itself was VALIDATED
                # above — schema bugs still surface loudly)
                global_metrics.count("query_stats_write_errors")
        if slow or error is not None or trace is not None:
            entry = dict(rec)
            if trace is not None:
                entry["trace"] = (trace.to_dict()
                                  if hasattr(trace, "to_dict") else trace)
            with self._lock:
                self._ring.append(entry)
        # SLO plane feed (utils/slo.py): unarmed this is ONE attribute
        # read — the <1% hot-path overhead contract
        global_slo.observe_query(rec)
        return rec

    def record_trace(self, root: Any, sql: str, qid: str
                     ) -> Optional[Dict[str, Any]]:
        """A sampled production query's span tree -> validated
        ``query_trace`` record in the SAME ledger the query_stats
        records land in, cross-linked by qid (the stats record carries
        ``traced: true``). Returns the validated record (None only when
        no ledger is configured)."""
        rec = uledger.trace_record(root, sql, qid=qid, sampled=True)
        if not self.ledger_path:
            return rec
        try:
            uledger.append_record(rec, self.ledger_path)
            with self._lock:
                self.traces_written += 1
        except OSError:
            # observability must never fail the data path (same policy
            # as the stats record above)
            global_metrics.count("query_trace_write_errors")
        return rec

    # -- serving -----------------------------------------------------------
    def snapshot(self, limit: Optional[int] = None) -> Dict[str, Any]:
        """GET /debug/queries payload: newest first."""
        with self._lock:
            entries = list(self._ring)
        entries.reverse()
        if limit is not None:
            entries = entries[:max(limit, 0)]
        return {"slowQueryMs": self.default_slow_ms,
                "traceRatio": self.trace_ratio,
                "statsLedger": self.ledger_path,
                "statsWritten": self.stats_written,
                "tracesWritten": self.traces_written,
                "count": len(entries),
                "queries": entries}


# ---------------------------------------------------------------------------
# ledger shipping (round 14): incremental per-node /debug endpoints the
# controller's ForensicsRollupTask pulls (cluster/rollup.py)
# ---------------------------------------------------------------------------

def parse_since(path: str) -> int:
    """``?since=N`` off a /debug/ledger request path (0 when absent or
    malformed — the puller then re-reads from the start, which is safe:
    the controller advances its cursor from the response's nextSeq)."""
    from urllib.parse import parse_qs, urlparse
    try:
        return max(int(parse_qs(urlparse(path).query)["since"][0]), 0)
    except (KeyError, ValueError, IndexError):
        return 0


def read_ledger_since(path: Optional[str], since: int
                      ) -> Tuple[List[Dict[str, Any]], int]:
    """-> (records after line ``since``, nextSeq = total line count).

    The sequence is the ledger's LINE number (ledgers are append-only
    JSONL, so line order is stable); unparseable lines advance the
    sequence but ship nothing — the controller re-validates every
    record against the utils/ledger contracts anyway. A final line
    WITHOUT a newline terminator is an append still in flight: it must
    not advance the sequence, or the puller's cursor would step past
    the record and permanently drop it once the write completes."""
    records: List[Dict[str, Any]] = []
    seq = 0
    if path and os.path.exists(path):
        with open(path) as fh:
            for i, line in enumerate(fh):
                if not line.endswith("\n"):
                    break   # torn tail: ship it complete, next pull
                seq = i + 1
                if i < since:
                    continue
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                records.append(rec)
    return records, seq


def ledger_debug_payload(node_id: str, role: str, path: Optional[str],
                         since: int, heat_top: int = 64
                         ) -> Dict[str, Any]:
    """GET /debug/ledger payload (brokers AND servers): the incremental
    ledger delta plus the node-local telemetry blocks the rollup carries
    per node — metrics counters/gauges (drift, retraces, batching),
    device-memory pools and the segment-heat table — so one pull per
    node gathers everything the fleet view needs."""
    from ..engine.ragged import batching_health
    from ..engine.tier import global_tier
    from ..utils.compileplane import compile_health
    from ..utils.devmem import global_device_memory
    from ..utils.heat import global_segment_heat
    records, next_seq = read_ledger_since(path, since)
    snap = global_metrics.snapshot()
    return {"node": node_id, "role": role, "proc": PROC_TOKEN,
            "ledger": path, "since": since, "nextSeq": next_seq,
            "records": records,
            "counters": snap["counters"], "gauges": snap["gauges"],
            "batching": batching_health(snap),
            # compile-plane warmup debt + storm state (ISSUE 15)
            "compile": compile_health(snap),
            "memory": global_device_memory.snapshot(),
            "tier": global_tier.snapshot(),
            "heat": global_segment_heat.snapshot(top=heat_top),
            # SLO burn table + incident counts (ISSUE 17): the rollup
            # aggregates these per node into fleet_rollup.slo
            "slo": global_slo.status_block(),
            "incidents": {"count": global_incidents.snapshot(0)["count"],
                          "captured": global_incidents.captured}}


# the debug surfaces every data-plane role serves at minimum; roles
# extend with their extras (broker: queries/compile/slo; controller
# advertises its own set — it serves /debug/fleet, not node ledgers)
DEBUG_SURFACES = ("/debug/ledger", "/debug/memory", "/debug/incidents")

# roles that serve the incident autopsy plane (cluster/autopsy.py):
# the broker runs it over its node ledger, the controller over the
# fleet ledger — servers have no attribution surface, so advertising
# it there would be a lie the index exists to prevent
AUTOPSY_ROLES = ("broker", "controller")


def debug_index(node_id: str, role: str,
                extra: Tuple[str, ...] = (),
                surfaces: Optional[Tuple[str, ...]] = None
                ) -> Dict[str, Any]:
    """GET /debug payload — the index of every debug surface THIS node
    actually serves (truthful per role), so an operator landing on any
    role can enumerate the forensics endpoints instead of memorizing
    them. ``surfaces`` overrides the data-plane default set.
    ``/debug/autopsy`` is appended here, once, per AUTOPSY_ROLES — one
    source of truth instead of each role's extras drifting."""
    base = tuple(DEBUG_SURFACES if surfaces is None else surfaces)
    out = base + tuple(extra)
    if role in AUTOPSY_ROLES and "/debug/autopsy" not in out:
        out = out + ("/debug/autopsy",)
    return {"node": node_id, "role": role, "proc": PROC_TOKEN,
            "surfaces": sorted(out)}


def memory_debug_payload(node_id: str,
                         residency: Optional[Dict[str, Any]] = None
                         ) -> Dict[str, Any]:
    """GET /debug/memory payload: what lives in HBM on this node right
    now — per-pool live bytes / entries / evictions (utils/devmem), the
    tier occupancy (engine/tier.py hot/warm/cold + budget), this node's
    per-segment tier residency (servers pass it — the same block their
    heartbeats ship for affinity routing) and the hottest segments
    (utils/heat)."""
    from ..engine.tier import global_tier
    from ..utils.devmem import global_device_memory
    from ..utils.heat import global_segment_heat
    out = {"node": node_id, "proc": PROC_TOKEN,
           "pools": global_device_memory.snapshot(),
           "tier": global_tier.snapshot(),
           "heat": global_segment_heat.snapshot(top=50)}
    if residency is not None:
        out["residency"] = residency
    return out
