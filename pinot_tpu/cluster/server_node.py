"""Server node: segment hosting + query execution over HTTP.

Reference parity: pinot-server/.../BaseServerStarter.java:557 + the Helix
state model (SegmentOnlineOfflineStateModelFactory.java:78,128 — servers
receive ONLINE transitions and download/load segments) + the server half of
the single-stage data plane (InstanceRequestHandler.channelRead0). Here the
server polls its versioned assignment from the controller (ideal-state
pull, not ZK push), loads/unloads immutable segments to match, and serves
POST /query {sql, table, segments?} by running the per-segment planner +
batched kernel executor and returning wire-encoded partials — the
DataTable response analog.
"""
from __future__ import annotations

import os
import threading
import time
import urllib.error
import uuid
from typing import Any, Dict, List, Optional

from ..engine.accounting import global_accountant
from ..engine.scheduler import make_scheduler
from ..engine.serde import partial_to_wire
from ..query.context import build_query_context
from ..query.sql import parse_sql
from ..segment.immutable import ImmutableSegment
from ..server.data_manager import TableDataManager
from .http_util import (JsonHandler, http_json, start_http,
                        trace_context_from)


class ServerNode:
    def __init__(self, instance_id: str, controller_url: str, port: int = 0,
                 poll_interval: float = 0.3,
                 scheduler_config: Optional[Dict[str, Any]] = None,
                 tags: Optional[List[str]] = None,
                 advertise_host: Optional[str] = None,
                 ledger_path: Optional[str] = None):
        self.instance_id = instance_id
        self.controller_url = controller_url
        self.poll_interval = poll_interval
        # optional node-local perf ledger (ingest_stats writers etc.)
        # served incrementally at GET /debug/ledger for the controller's
        # fleet rollup; None still serves the telemetry blocks
        # (heat / device memory / counters) with zero records
        self.ledger_path = ledger_path
        # the host OTHER nodes dial (containers/k8s must advertise their
        # service-reachable name, not loopback); env override for
        # image-based deployments (deploy/)
        self.advertise_host = (advertise_host
                               or os.environ.get("PINOT_ADVERTISE_HOST")
                               or "127.0.0.1")
        self.tags = list(tags or [])  # tenant tags (Helix instance tags)
        import tempfile
        # local segment store for deep-store downloads (tar.gz locations)
        self.data_dir = tempfile.mkdtemp(prefix=f"ptpu_{instance_id}_")
        # admission + ordering for concurrent HTTP queries
        # (QuerySchedulerFactory analog; fcfs by default)
        self.scheduler = make_scheduler(scheduler_config)
        from ..multistage.exchange import MailboxService
        self.mailboxes = MailboxService()  # multi-stage receiving side
        # gRPC data plane (streaming Submit + mailbox; grpc_plane.py).
        # Optional: environments without grpcio still run the HTTP planes
        self.grpc_server = None
        self.grpc_port: Optional[int] = None
        try:
            from .grpc_plane import start_grpc
            self.grpc_server, self.grpc_port = start_grpc(self)
        except ImportError:
            pass
        # OOM protection: kill the most expensive query near the RSS limit
        # (PerQueryCPUMemAccountant WatcherTask analog); limit defaults to
        # 90% of system memory, override/disable via
        # scheduler_config["query.killer.rss_limit_bytes"] (0 disables)
        from ..engine.accounting import HeapWatcher, system_memory_bytes
        cfg = scheduler_config or {}
        # deterministic chaos: a node config can arm the process-global
        # fault plan (PINOT_FAULTS grammar — utils/faults.py); the env
        # var is the container path, this is the embedded-cluster path.
        # The plan is PROCESS-global: last installer wins, and stop()
        # disarms it again (only if still ours) so a stopped chaos node
        # doesn't keep injecting into the rest of the process
        self._fault_plan = None
        if cfg.get("fault.plan"):
            from ..utils import faults
            self._fault_plan = faults.install(cfg["fault.plan"])
        rss_limit = int(cfg.get("query.killer.rss_limit_bytes",
                                int(system_memory_bytes() * 0.9)))
        self.heap_watcher = (HeapWatcher(global_accountant, rss_limit).start()
                             if rss_limit > 0 else None)
        self._tables: Dict[str, TableDataManager] = {}
        self._assignment_version = -1
        self._stop = threading.Event()
        self._httpd, self.port, _ = start_http(self._make_handler(), port)
        self._register(retries=20)   # ~1min of startup tolerance
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    # -- control plane -----------------------------------------------------
    def _register(self, retries: int = 0) -> None:
        """retries > 0: tolerate startup transients — an HA standby's
        503, a not-yet-scheduled controller — with linear backoff (the
        crash-looping alternative is what k8s would otherwise do)."""
        for attempt in range(retries + 1):
            try:
                http_json("POST", f"{self.controller_url}/instances", {
                    "id": self.instance_id, "host": self.advertise_host,
                    "port": self.port, "role": "server",
                    "tags": self.tags})
                return
            except Exception:
                if attempt == retries:
                    raise
                time.sleep(min(0.5 * (attempt + 1), 5.0))

    def _residency(self, cap: int = 512) -> Dict[str, Dict[str, str]]:
        """Per-table {segment: tier} for THIS node's hosted segments —
        the placement signal every heartbeat carries (the broker's
        affinity routing prefers replicas already holding a segment
        hot). ``cube`` marks a non-hot segment whose ragged cube is
        resident (it answers plan-key-sharing queries without any
        column upload). Capped so a wide node can't bloat the
        control-plane heartbeat."""
        from ..engine.tier import TIER_HOT, segment_tier
        from ..ops.plan_cache import global_cube_cache
        cube_uids = global_cube_cache.resident_uids()
        out: Dict[str, Dict[str, str]] = {}
        n = 0
        for table, dm in list(self._tables.items()):
            segs: Dict[str, str] = {}
            for s in dm.acquire_segments():
                if n >= cap:
                    break
                t = segment_tier(s)
                if t != TIER_HOT and getattr(s, "uid", None) in cube_uids:
                    t = "cube"
                segs[s.name] = t
                n += 1
            if segs:
                out[table] = segs
        return out

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_interval):
            epoch = None
            try:
                try:
                    resp = http_json("POST",
                                     f"{self.controller_url}/heartbeat/"
                                     f"{self.instance_id}",
                                     {"residency": self._residency()})
                    # assignment-version epoch (round 24): when the
                    # heartbeat says our applied version is current,
                    # skip the assignment fetch this tick. A stale or
                    # absent epoch (older controller) always syncs; a
                    # partially-failed sync keeps _assignment_version
                    # behind the epoch, so retries still fire each poll
                    epoch = (resp or {}).get("version")
                except urllib.error.HTTPError as e:
                    if e.code != 404:
                        raise
                    # a RESTARTED controller has empty ephemeral state
                    # and answers 404 for unknown instances: re-announce
                    # (the ZK ephemeral-node re-registration Helix does
                    # on session re-establishment)
                    self._register()
                if epoch is None or epoch != self._assignment_version:
                    self._sync_assignment()
            except Exception:
                pass  # controller briefly unreachable; keep serving

    def _sync_assignment(self) -> None:
        a = http_json("GET", f"{self.controller_url}/assignments/"
                             f"{self.instance_id}")
        if a["version"] == self._assignment_version:
            return
        ok = True  # advance the version only after a fully-applied sync;
        # a failed segment load retries on every poll instead of being
        # silently skipped until an unrelated version bump
        for table, segs in a["tables"].items():
            dm = self._tables.setdefault(table, TableDataManager(table))
            have = {s.name for s in dm.acquire_segments()}
            for seg_name, location in segs.items():
                if seg_name not in have:
                    try:
                        # deep-store location: download + untar, then load
                        # (onBecomeOnlineFromOffline download path)
                        from .deepstore import (download_segment,
                                                is_deepstore_uri)
                        if is_deepstore_uri(location):
                            location = download_segment(
                                location,
                                os.path.join(self.data_dir, table))
                        dm.add_segment(ImmutableSegment.load(location))
                    except Exception:
                        ok = False
            for seg_name in have - set(segs):
                dm.remove_segment(seg_name)
                # reclaim the local deep-store download, if any (mmaps of
                # in-flight queries survive the unlink)
                local = os.path.join(self.data_dir, table, seg_name)
                if os.path.isdir(local):
                    import shutil
                    shutil.rmtree(local, ignore_errors=True)
        for table in list(self._tables):
            if table not in a["tables"]:
                del self._tables[table]
        if ok:
            self._assignment_version = a["version"]

    def wait_for_version(self, version: int, timeout: float = 10.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._assignment_version >= version:
                return True
            time.sleep(0.05)
        return False

    # -- data plane --------------------------------------------------------
    def execute(self, sql: str, segment_names: Optional[List[str]] = None,
                priority: int = 0,
                deadline_ms: Optional[float] = None,
                trace_ctx: Optional[Dict[str, Any]] = None,
                workload: Optional[Dict[str, Any]] = None
                ) -> Dict[str, Any]:
        """Admit through the scheduler (QueryScheduler.submit analog) and
        account the query so the watcher can kill it under pressure.
        ``deadline_ms`` is the dispatching broker's REMAINING budget; the
        accountant deadline becomes min(own timeoutMs, broker remaining)
        so a server never works past the point the broker stops
        listening. A sampled ``trace_ctx`` (http_util.
        inject_trace_context wire shape) activates a remote-rooted span
        tree around the executor and ships it back in the response
        envelope for the broker to stitch."""
        # accountant id stays server-local: in-process clusters share ONE
        # global accountant, and registering the broker's query id from
        # two server nodes (hybrid halves, hedged duplicates) would
        # collide; the broker id rides the span tree instead
        query_id = uuid.uuid4().hex[:12]
        # the deadline anchors at ARRIVAL, before scheduler admission:
        # queue time is inside the broker's budget, not in addition to it
        t_arrive = time.perf_counter()
        sampled = bool((trace_ctx or {}).get("sampled"))

        def run() -> Dict[str, Any]:
            # the scheduler runs this on a worker thread — the span
            # tracer is thread-local, so the tree must root HERE, not in
            # the HTTP handler thread that admitted the query
            if not sampled:
                return self._execute(sql, segment_names, query_id,
                                     deadline_ms, t_arrive)
            from ..utils import phases as ph
            from ..utils.spans import span_tracer
            root = span_tracer.start(
                ph.SERVER_QUERY, server=self.instance_id,
                query_id=trace_ctx.get("queryId") or query_id,
                parent_span_id=trace_ctx.get("parentSpanId"))
            try:
                resp = self._execute(sql, segment_names, query_id,
                                     deadline_ms, t_arrive)
            finally:
                root = span_tracer.stop() or root
            root.annotate(segments=resp.get("segmentsQueried", 0))
            resp["trace"] = root.to_dict()
            return resp

        # tenant/tier attribution forwarded by the dispatching broker
        # (broker_node._scatter): the tier-aware HeapWatcher kill
        # ordering and post-paid tenant budgets act HERE, where the
        # kernels actually execute
        wl = workload or {}
        global_accountant.register(query_id,
                                   tenant=wl.get("tenant"),
                                   tier=wl.get("tier"), sql=sql)
        try:
            resp = self.scheduler.execute(run, query_id,
                                          priority=priority)
        finally:
            usage = global_accountant.unregister(query_id)
        if usage is not None and usage.batched_dispatches:
            # cross-query micro-batching participation (engine/ragged):
            # rides the wire header so the broker's query_stats records
            # carry batched/batch_size per query
            resp["batched"] = usage.batched_dispatches
            resp["batchSize"] = usage.max_batch_size
        return resp

    def _execute(self, sql: str, segment_names: Optional[List[str]] = None,
                 query_id: Optional[str] = None,
                 deadline_ms: Optional[float] = None,
                 t_arrive: Optional[float] = None) -> Dict[str, Any]:
        t0 = time.perf_counter()
        stmt = parse_sql(sql)
        from ..query.sql import DdlStmt, SetOpStmt
        if isinstance(stmt, (SetOpStmt, DdlStmt)):
            raise ValueError("leaf servers execute single-table stages; "
                             "set operations and DDL belong to the broker")
        from ..multistage.window import has_window
        if has_window(stmt):
            raise ValueError("leaf servers execute single-table stages; "
                             "window functions run in the dispatch stage")
        if query_id is not None:
            # enforce the query's timeoutMs where the work actually runs
            # (the broker-side deadline lives in a different process in
            # cluster mode), clamped to the broker's forwarded remaining
            # budget so a re-dispatched straggler cannot outlive the
            # scatter that asked for it
            from ..broker.broker import DEFAULT_TIMEOUT_MS
            timeout_ms = int(stmt.options.get("timeoutMs",
                                              DEFAULT_TIMEOUT_MS))
            if deadline_ms is not None:
                timeout_ms = min(timeout_ms, int(deadline_ms))
            global_accountant.set_deadline(
                query_id, (t_arrive or t0) + timeout_ms / 1e3)
        if stmt.joins:
            raise ValueError("leaf servers execute single-table stages")
        from ..utils.faults import fault_point
        fault_point("segment.slow", key=self.instance_id)
        ctx = build_query_context(stmt)
        dm = self._tables.get(ctx.table)
        if dm is None:
            return {"partials_raw": [], "segmentsQueried": 0}
        segments = dm.acquire_segments()
        if segment_names is not None:
            wanted = set(segment_names)
            segments = [s for s in segments if s.name in wanted]
        # shared loop with the in-process broker (engine/serving.py)
        from ..engine.serving import execute_segments, plan_segments
        if stmt.explain:
            ex = plan_segments(ctx, segments, use_rollups=False)
            from ..query.explain import explain_rows
            cols, rows = explain_rows(ctx, ex.real_plans, 0)
            return {"explain": {"columns": cols,
                                "rows": [list(r) for r in rows]},
                    "segmentsQueried": len(segments)}
        ex = execute_segments(ctx, segments)
        return {"partials_raw": ex.partials,
                "segmentsQueried": len(segments)}

    def execute_json(self, sql: str,
                     segment_names: Optional[List[str]] = None,
                     deadline_ms: Optional[float] = None,
                     trace_ctx: Optional[Dict[str, Any]] = None,
                     workload: Optional[Dict[str, Any]] = None
                     ) -> Dict[str, Any]:
        """Legacy/debuggable JSON wire (also serves EXPLAIN)."""
        resp = self.execute(sql, segment_names, deadline_ms=deadline_ms,
                            trace_ctx=trace_ctx, workload=workload)
        raw = resp.pop("partials_raw", None)
        if raw is not None:
            resp["partials"] = [partial_to_wire(p) for p in raw]
        return resp

    def execute_bin(self, sql: str,
                    segment_names: Optional[List[str]] = None,
                    deadline_ms: Optional[float] = None,
                    trace_ctx: Optional[Dict[str, Any]] = None,
                    workload: Optional[Dict[str, Any]] = None) -> bytes:
        """Binary data plane: columnar DataBlock partials in one frame.
        The span tree (when sampled) rides the JSON frame header, along
        with ``serdeEncodeMs`` — the partial-encode time this side of
        the wire, so the broker can split its call-span gap into serde
        vs true network time (the encode is timed BEFORE the header is
        assembled; header serialization itself is negligible)."""
        from ..engine.datablock import (encode_partial,
                                        encode_wire_frame_blocks)
        resp = self.execute(sql, segment_names, deadline_ms=deadline_ms,
                            trace_ctx=trace_ctx, workload=workload)
        raw = resp.pop("partials_raw", [])
        t_enc = time.perf_counter()
        blocks = [encode_partial(p) for p in raw]
        resp["serdeEncodeMs"] = round(
            (time.perf_counter() - t_enc) * 1e3, 3)
        return encode_wire_frame_blocks(resp, blocks)

    def handle_reload(self, body: Dict[str, Any]) -> Dict[str, Any]:
        """Reload a hosted table's segments against a (new) table config
        (the reload segment/table REST operation + reload Helix message
        analog: servers rebuild secondary indexes in place)."""
        from ..spi.config import TableConfig
        table = body["table"]
        dm = self._tables.get(table)
        if dm is None:
            return {"reloaded": 0, "added": [], "removed": []}
        cfg_dict = body.get("tableConfig")
        if not cfg_dict:
            # reload against the CURRENT config: the controller's routing
            # snapshot is the config source of truth for cluster servers
            snap = http_json("GET", f"{self.controller_url}/routing")
            cfg_dict = (snap.get("tables", {}).get(table) or {}) \
                .get("config")
            if not cfg_dict:
                raise ValueError(f"no table config for {table!r} at the "
                                 "controller; pass tableConfig inline")
        changes = dm.reload(TableConfig.from_dict(cfg_dict))
        return {"reloaded": len(dm.acquire_segments()), **changes}

    def handle_mailbox(self, data: bytes) -> Dict[str, Any]:
        from ..multistage.dispatch import deliver_mailbox_frame
        deliver_mailbox_frame(self.mailboxes, data)
        return {"status": "OK"}

    def handle_stage(self, spec: Dict[str, Any],
                     trace_ctx: Optional[Dict[str, Any]] = None):
        from ..multistage.dispatch import execute_stage
        return execute_stage(self, spec, trace_ctx=trace_ctx)

    def _make_handler(self):
        from ..utils.slo import global_incidents
        from .forensics import (debug_index, ledger_debug_payload,
                                memory_debug_payload, parse_since)
        node = self

        class Handler(JsonHandler):
            routes = {
                ("GET", "/health"): lambda h, b: (200, {"status": "OK"}),
                # debug-surface index + incident flight-recorder ring
                # (ISSUE 17; in-process clusters share the recorder)
                ("GET", "/debug"): lambda h, b: (
                    200, debug_index(node.instance_id, "server")),
                ("GET", "/debug/incidents"): lambda h, b: (
                    200, global_incidents.snapshot()),
                # ledger shipping + device-memory telemetry (round 14):
                # the controller's ForensicsRollupTask pulls the ledger
                # delta + heat/devmem/counters blocks; /debug/memory is
                # the HBM residency view the future tiered segment
                # cache will admit/evict on
                ("GET", "/debug/ledger"): lambda h, b: (
                    200, ledger_debug_payload(
                        node.instance_id, "server", node.ledger_path,
                        parse_since(h.path))),
                ("GET", "/debug/memory"): lambda h, b: (
                    200, memory_debug_payload(node.instance_id,
                                              node._residency())),
                ("POST", "/query/bin"): lambda h, b: (
                    200, node.execute_bin(b["sql"], b.get("segments"),
                                          b.get("deadlineMs"),
                                          b.get("traceContext"),
                                          b.get("workload"))),
                ("POST", "/query"): lambda h, b: (
                    200, node.execute_json(b["sql"], b.get("segments"),
                                           b.get("deadlineMs"),
                                           b.get("traceContext"),
                                           b.get("workload"))),
                # multi-stage data plane (mailbox.proto analog) + stage
                # dispatch (worker.proto Submit analog; the trace
                # context rides an HTTP header because the StagePlan
                # proto body is opaque bytes)
                ("POST", "/mailbox"): lambda h, b: (
                    200, node.handle_mailbox(b)),
                ("POST", "/reload"): lambda h, b: (
                    200, node.handle_reload(b)),
                ("POST", "/stage"): lambda h, b: (
                    200, node.handle_stage(b, trace_context_from(
                        h.headers))),
            }
        return Handler

    def stop(self) -> None:
        self._stop.set()
        if self._fault_plan is not None:
            from ..utils import faults
            if faults.current_plan() is self._fault_plan:
                faults.clear()
            self._fault_plan = None
        self.scheduler.stop()
        if self.heap_watcher is not None:
            self.heap_watcher.stop()
        if self.grpc_server is not None:
            self.grpc_server.stop(grace=None)
        self._httpd.shutdown()
        self._httpd.server_close()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"
