"""Controller web application (single-file SPA, no build step).

Reference parity: pinot-controller/src/main/resources/app — the React/TS
cluster manager. The TPU-native stance replaces the 500-module React
build with one server-bootstrapped page: the controller renders the
current cluster snapshot INTO the page (so the first paint needs no
round trip and the page is meaningful to curl/tests), and the embedded
vanilla-JS app hydrates from it, then live-refreshes from GET /ui/data
and drives the admin REST (rebalance, periodic tasks, segment delete)
and any broker's /query/sql console.

Views (hash-routed): #/cluster (instances + leadership), #/tables
(list -> per-table detail: segments, assignment, rebalance), #/fleet
(the ForensicsRollup panels: per-table fleet stats, slowest queries,
drift/requantize + batching health per node, top-N hot segments with
device-memory bytes), #/tasks (periodic task status + run), #/query
(SQL console with EXPLAIN toggle against a configurable broker URL,
persisted in localStorage).
"""
from __future__ import annotations

import json
from typing import Any, Dict

_PAGE = """<!doctype html>
<html><head><meta charset="utf-8">
<title>pinot-tpu controller</title>
<style>
:root{--fg:#1d2733;--mut:#6b7a90;--line:#d7dee8;--acc:#2458e6;
--bad:#c0392b;--ok:#1e8e3e;--bg:#f6f8fb}
*{box-sizing:border-box}
body{font-family:system-ui,sans-serif;margin:0;color:var(--fg);
background:var(--bg)}
header{display:flex;align-items:center;gap:24px;padding:10px 20px;
background:#fff;border-bottom:1px solid var(--line)}
header h1{font-size:16px;margin:0}
nav a{margin-right:14px;text-decoration:none;color:var(--mut);
font-weight:600;font-size:14px}
nav a.on{color:var(--acc)}
main{padding:20px;max-width:1100px}
table{border-collapse:collapse;background:#fff;width:100%;
margin:10px 0 24px}
td,th{border:1px solid var(--line);padding:6px 10px;font-size:13px;
text-align:left}
th{background:#eef2f8}
.badge{padding:1px 8px;border-radius:9px;font-size:12px;color:#fff}
.live{background:var(--ok)}.dead{background:var(--bad)}
button{background:var(--acc);border:0;color:#fff;border-radius:4px;
padding:5px 12px;font-size:13px;cursor:pointer}
button.sec{background:#fff;color:var(--acc);
border:1px solid var(--acc)}
textarea{width:100%;height:90px;font-family:ui-monospace,monospace;
font-size:13px;padding:8px;border:1px solid var(--line);
border-radius:4px}
input[type=text]{padding:5px 8px;border:1px solid var(--line);
border-radius:4px;font-size:13px;width:320px}
.err{color:var(--bad);white-space:pre-wrap;font-family:monospace}
.mut{color:var(--mut);font-size:12px}
a.tbl{color:var(--acc);cursor:pointer;text-decoration:underline}
</style></head><body>
<header><h1>pinot-tpu controller</h1>
<nav id="nav"></nav>
<span class="mut" id="meta"></span>
<label class="mut" style="margin-left:auto">
<input type="checkbox" id="auto" checked> auto-refresh</label>
</header>
<main id="main"></main>
<script id="bootstrap" type="application/json">__BOOTSTRAP__</script>
<script>
"use strict";
let D = JSON.parse(document.getElementById("bootstrap").textContent);
const esc = (s) => String(s).replace(/[&<>"'\\\\]/g,
  c => ({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;",
         "'":"&#39;","\\\\":"&#92;"}[c]));
const VIEWS = [["#/cluster","Cluster"],["#/tables","Tables"],
  ["#/fleet","Fleet"],["#/tasks","Tasks"],["#/query","Query console"]];

function nav() {
  const cur = location.hash || "#/cluster";
  document.getElementById("nav").innerHTML = VIEWS.map(([h, t]) =>
    `<a href="${h}" class="${cur.startsWith(h) ? "on" : ""}">${t}</a>`
  ).join("");
  document.getElementById("meta").textContent =
    `routing v${D.version} · leader: ${D.leader || "?"}`;
}

async function refresh() {
  try {
    const r = await fetch("/ui/data");
    if (r.ok) { D = await r.json(); render(); }
  } catch (e) { /* controller restarting: keep the last snapshot */ }
}

function table(headers, rows) {
  return `<table><tr>${headers.map(h => `<th>${h}</th>`).join("")}</tr>`
    + rows.map(r => `<tr>${r.map(c => `<td>${c}</td>`).join("")}</tr>`)
      .join("") + "</table>";
}

function vCluster() {
  const inst = Object.entries(D.instances).map(([id, i]) =>
    [esc(id),
     `<span class="badge ${i.live ? "live" : "dead"}">` +
       `${i.live ? "LIVE" : "DEAD"}</span>`,
     esc((i.tags || []).join(", ")), esc(i.host || "")]);
  const ing = D.ingest || {};
  const ingest = `<p class="mut">realtime ingest: rows ` +
    `${ing.ingest_rows || 0} | freshness ` +
    `${ing.freshness_ms != null ?
        ing.freshness_ms.toFixed(1) + " ms" : "n/a"} | commits ` +
    `${ing.ingest_commits || 0} | commit retries ` +
    `${ing.ingest_commit_retries || 0} | rebalance resets ` +
    `${ing.ingest_rebalance_resets || 0} | upsert replays ` +
    `${ing.ingest_upsert_replays || 0} | orphans cleaned ` +
    `${ing.ingest_orphans_cleaned || 0}</p>`;
  return `<h2>Instances</h2>` +
    table(["id", "state", "tags", "host"], inst) + ingest +
    `<h2>Leadership</h2>` +
    table(["leader", "lease holder", "this instance"],
      [[esc(D.leader || "-"), esc(D.lease_holder || "-"),
        esc(D.instance_id || "-")]]) +
    `<p class="mut">debug surfaces: <a href="/debug">/debug</a> ·
     <a href="/debug/fleet">/debug/fleet</a> ·
     <a href="/debug/incidents">/debug/incidents</a> — per-node
     queries/compile/memory/ledger/slo indexes at each broker and
     server's own <code>/debug</code></p>`;
}

function vTables() {
  const rows = Object.entries(D.tables).map(([t, m]) =>
    [`<a class="tbl" href="#/tables/${encodeURIComponent(t)}">` +
       `${esc(t)}</a>`,
     m.replication, (m.segments || []).length,
     esc(m.tenant || "default")]);
  return "<h2>Tables</h2>" +
    table(["table", "replication", "segments", "tenant"], rows);
}

function vTable(t) {
  const m = D.tables[t];
  if (!m) return `<p class="err">unknown table ${esc(t)}</p>`;
  const segs = (m.segments || []).map(s =>
    [esc(s), esc(((m.assignment || {})[s] || []).join(", ")),
     `<button class="sec" data-act="del" data-t="${esc(t)}"` +
       ` data-s="${esc(s)}">delete</button>`]);
  return `<h2>${esc(t)}</h2>
    <p><button data-act="reb" data-t="${esc(t)}">rebalance</button>
    <span class="mut" id="actmsg">${esc(actMsg[t] || "")}</span></p>
    <h3>Segments</h3>` +
    table(["segment", "servers", ""], segs);
}

function vFleet() {
  // the ForensicsRollup panels (GET /debug/fleet via D.fleet)
  const f = D.fleet || {};
  const r = f.rollup;
  if (!r) return `<h2>Fleet forensics</h2>
    <p class="mut">no rollup yet — run the ForensicsRollup task
    (Tasks view) once brokers/servers have ledgers to pull.</p>`;
  const pull = `<p class="mut">pulls ${f.pulls || 0} ·
    nodes ${r.nodes_polled - r.nodes_skipped}/${r.nodes_polled} ok
    (${(r.skipped_nodes || []).map(esc).join(", ") || "none skipped"}) ·
    ${r.fleet_records || 0} fleet records · ledger ${esc(f.ledger
    || "")}</p>`;
  // fleet SLO view (ISSUE 17): worst-replica burn per objective —
  // fleet_rollup.slo from the proc-deduped node blocks
  const slo = r.slo || {};
  const sloTbl = (slo.objectives || []).length ? table(
    ["scope", "kind", "objective", "burn fast", "burn slow",
     "budget left", "events", "bad", "state"],
    slo.objectives.map(s => [esc(s.scope), esc(s.kind),
      s.objective != null ? s.objective : "-",
      (s.burn_fast != null ? s.burn_fast : 0) + "x",
      (s.burn_slow != null ? s.burn_slow : 0) + "x",
      ((s.budget_remaining != null ? s.budget_remaining : 1) * 100)
        .toFixed(1) + "%",
      s.events || 0, s.bad || 0,
      (s.alerting ? '<span class="badge dead">ALERTING</span>'
                  : '<span class="badge live">OK</span>') +
      (s.stale ? ' <span class="badge dead">STALE</span>' : "")]))
    : `<p class="mut">${slo.armed ? "no objectives reporting yet"
        : "SLO plane unarmed — no objectives declared on the nodes"
      }</p>`;
  const sloHead = `<h3>SLO error budgets <span class="mut">(worst
    replica · open incidents ${slo.open_incidents || 0} — see
    /debug/incidents on any node)</span></h3>`;
  const tbl = table(["table", "queries", "qps", "p50 ms", "p99 ms",
      "partial", "failovers", "hedges", "batched", "slow", "shed",
      "freshness ms"],
    Object.entries(r.tables || {}).map(([t, s]) =>
      [esc(t), s.queries || 0, s.qps || 0, s.p50_ms || 0,
       s.p99_ms || 0, s.partial || 0, s.failovers || 0, s.hedges || 0,
       s.batched_queries || 0, s.slow || 0,
       (s.shed || 0) + (s.shed_by_tenant &&
         Object.keys(s.shed_by_tenant).length
         ? " (" + Object.entries(s.shed_by_tenant).map(([tn, n]) =>
             esc(tn) + ":" + n).join(", ") + ")" : ""),
       s.freshness_ms != null ? s.freshness_ms : "-"]));
  const slow = table(["qid", "node", "table", "wall ms", "partial",
      "sql"],
    (r.slow_queries || []).map(q => [esc(q.qid || ""),
      esc(q.node || ""), esc(q.table || ""), q.wall_ms,
      q.partial ? "YES" : "no", esc(q.sql || "")]));
  // hottest plan shapes by warmup cost (compiles x median compile ms)
  // — the AOT executable plane's prefetch list (ISSUE 15)
  const shapes = table(["plan shape", "compiles", "median ms",
      "total ms", "warmup cost", "triggers", "sql"],
    (r.plan_shapes || []).map(p => [esc(p.plan_shape || ""),
      p.compiles || 0, p.median_compile_ms || 0,
      p.total_compile_ms || 0, p.warmup_cost || 0,
      esc(JSON.stringify(p.triggers || {})), esc(p.sql || "")]));
  const heat = table(["table", "segment", "touches", "rows scanned",
      "device hit ratio"],
    (r.heat || []).map(h => [esc(h.table), esc(h.segment), h.touches,
      h.rows_scanned,
      h.device_hit_ratio != null ? h.device_hit_ratio : "-"]));
  const nodes = table(["node", "role", "drift det/req/rec",
      "retraces", "batched", "cube hit/miss", "device bytes",
      "tier hot/warm/cold", "promote/demote", "affinity"],
    Object.entries(r.nodes || {}).map(([n, b]) => {
      const c = b.counters || {};
      const mem = ((b.memory || {}).total || {}).bytes || 0;
      const t = b.tier || {};
      const th = t.hot || {}, tw = t.warm || {}, tc = t.cold || {};
      return [esc(n), esc(b.role || ""),
        `${c.selectivity_drift_detected || 0}/` +
          `${c.selectivity_drift_requantized || 0}/` +
          `${c.selectivity_drift_recompiles || 0}`,
        c.plan_cache_retraces || 0, c.batched_dispatches || 0,
        `${c.cube_cache_hits || 0}/${c.cube_cache_misses || 0}`, mem,
        `${th.segments || 0} (${th.bytes || 0}B) / ` +
          `${tw.segments || 0} (${tw.bytes || 0}B) / ` +
          `${tc.segments || 0}` +
          (t.armed ? ` · budget ${t.budget_bytes}B` : ""),
        `${c.tier_promotions || 0}/${c.tier_demotions || 0}`,
        c.tier_affinity_hits || 0];
    }));
  // closed-loop rebalance moves ring (round 24, D.rebalance —
  // GET /debug/rebalance): the move audit stream beside the SLO
  // budgets that trigger it
  const rb = D.rebalance || {};
  const moveTbl = (rb.moves || []).length ? table(
    ["phase", "table", "segment", "donor", "receiver", "bytes",
     "reason"],
    rb.moves.map(m => [esc(m.phase || ""), esc(m.table || ""),
      esc(m.segment || ""), esc(m.donor || ""), esc(m.receiver || ""),
      m.bytes || 0, esc(m.reason || "")]))
    : `<p class="mut">no moves yet — the ClosedLoopRebalance task
      plans from the rollup's burn table (frozen while incidents are
      open)</p>`;
  const moveHead = `<h3>Rebalance moves <span class="mut">(passes
    ${rb.passes || 0} · executed ${rb.executed || 0} · aborted
    ${rb.aborted || 0} · resumed ${rb.resumed || 0} · frozen
    ${rb.frozen_passes || 0}${rb.pending ? " · MOVE PENDING" : ""}
    )</span></h3>`;
  // incident autopsy verdicts (round 25, fleet_rollup.autopsy —
  // newest rca_verdict briefs in the pulled corpus; on-demand
  // fleet-wide attribution at GET /debug/autopsy)
  const rcaTbl = (r.autopsy || []).length ? table(
    ["ts", "node", "incident", "verdict", "score", "detail"],
    r.autopsy.map(v => [esc(v.ts || ""), esc(v.node || ""),
      esc(v.incident_ref || "—"),
      v.inconclusive ? '<span class="mut">inconclusive</span>'
                     : esc(v.top_cause || ""),
      v.top_score != null ? v.top_score : "",
      esc(v.detail || "")]))
    : `<p class="mut">no verdicts yet — attribution runs
      automatically when an incident fires (cluster/autopsy.py), or
      on demand at <a href="/debug/autopsy">/debug/autopsy</a></p>`;
  const rcaHead = `<h3>Autopsy <span class="mut">(root-cause
    verdicts, newest first)</span></h3>`;
  return `<h2>Fleet forensics</h2>${pull}
    ${sloHead}${sloTbl}
    ${rcaHead}${rcaTbl}
    ${moveHead}${moveTbl}
    <h3>Per-table fleet stats</h3>${tbl}
    <h3>Slowest queries</h3>${slow}
    <h3>Hottest plan shapes (warmup debt)</h3>${shapes}
    <h3>Hot segments</h3>${heat}
    <h3>Drift / batching / device memory / HBM tier per node</h3>${nodes}`;
}

function vTasks() {
  const rows = Object.entries(D.tasks || {}).map(([n, s]) =>
    [esc(n), esc(JSON.stringify(s)),
     `<button class="sec" data-act="task" data-t="${esc(n)}">` +
       "run</button>"]);
  return "<h2>Periodic tasks</h2>" + table(["task", "status", ""], rows);
}

function vQuery() {
  const broker = localStorage.getItem("brokerUrl") || "";
  return `<h2>Query console</h2>
    <p>broker URL: <input type="text" id="broker"
      value="${esc(broker)}" placeholder="http://host:port">
      <label class="mut"><input type="checkbox" id="explain">
      EXPLAIN</label>
      <label class="mut"><input type="checkbox" id="analyze">
      ANALYZE</label></p>
    <textarea id="sql">SELECT 1</textarea>
    <p><button data-act="query">run</button>
    <button class="sec" data-act="forensics">slow queries</button>
    <span class="mut" id="qtime"></span></p>
    <div id="qout"></div><div id="forout"></div>`;
}

async function showForensics() {
  // the broker-side query-forensics ring (GET /debug/queries)
  const broker = document.getElementById("broker").value.trim();
  localStorage.setItem("brokerUrl", broker);
  const out = document.getElementById("forout");
  try {
    const d = await (await fetch(broker + "/debug/queries?n=20")).json();
    if (!d.count) {
      out.innerHTML = `<p class="mut">no slow queries recorded ` +
        `(threshold ${d.slowQueryMs} ms)</p>`;
      return;
    }
    out.innerHTML = `<h3>Slow queries ` +
      `<span class="mut">(threshold ${d.slowQueryMs} ms)</span></h3>` +
      table(["qid", "wall ms", "table", "partial", "failovers",
             "hedges", "sql"],
        d.queries.map(e => [esc(e.qid), e.wall_ms, esc(e.table),
          e.partial ? "YES" : "no", e.failovers || 0, e.hedges || 0,
          esc((e.sql || "").slice(0, 120))]));
  } catch (e) {
    out.innerHTML = `<p class="err">${esc(e)}</p>`;
  }
}

async function runQuery() {
  const broker = document.getElementById("broker").value.trim();
  localStorage.setItem("brokerUrl", broker);
  let sql = document.getElementById("sql").value;
  if (document.getElementById("analyze").checked)
    sql = "EXPLAIN ANALYZE " + sql;
  else if (document.getElementById("explain").checked)
    sql = "EXPLAIN PLAN FOR " + sql;
  const out = document.getElementById("qout");
  const t0 = performance.now();
  try {
    const r = await fetch(broker + "/query/sql", {method: "POST",
      headers: {"Content-Type": "application/json"},
      body: JSON.stringify({sql})});
    const res = await r.json();
    const ms = (performance.now() - t0).toFixed(1);
    // our broker reports errors as HTTP 4xx {"error": str}; keep the
    // reference's exceptions[] shape working too — but a PARTIAL
    // result (allowPartialResults=true) carries both exceptions and
    // surviving rows: render the rows under a warning, not an error
    if (res.error || (res.exceptions && res.exceptions.length
        && !res.partialResult)) {
      out.innerHTML = `<p class="err">${esc(
        res.error || JSON.stringify(res.exceptions))}</p>`;
      document.getElementById("qtime").textContent = "";
      return;
    }
    const rt = res.resultTable || res;
    const cols = (rt.dataSchema && rt.dataSchema.columnNames)
      || rt.columns || [];
    const rows = rt.rows || [];
    const warn = res.partialResult
      ? `<p class="err">PARTIAL RESULT: ${res.numServersResponded}` +
        `/${res.numServersQueried} servers responded — ` +
        `${esc((res.exceptions || []).map(e => e.message).join("; "))}` +
        `</p>`
      : "";
    out.innerHTML = warn + table(cols.map(esc),
      rows.map(row => row.map(c => esc(JSON.stringify(c)))));
    const srv = res.timeUsedMs !== undefined
      ? ` · ${Number(res.timeUsedMs).toFixed(1)} ms server` : "";
    document.getElementById("qtime").textContent =
      `${rows.length} rows · ${ms} ms round trip${srv}`;
  } catch (e) {
    out.innerHTML = `<p class="err">${esc(e)}</p>`;
  }
}

async function post(path) {
  const r = await fetch(path, {method: "POST"});
  return r.ok ? r.json().catch(() => ({})) : {error: r.status};
}
const actMsg = {};  // per-table: survives refresh(), never leaks into
async function rebalance(t) {       // another table's detail view
  const res = await post("/rebalance/" + encodeURIComponent(t));
  actMsg[t] = "rebalance: " + JSON.stringify(res);
  await refresh();
}
async function runTask(n) {
  await post("/periodictask/run/" + encodeURIComponent(n));
  refresh();
}
async function delSeg(t, s) {
  if (!confirm(`delete segment ${s} of ${t}?`)) return;
  await fetch(`/segments/${encodeURIComponent(t)}/` +
    encodeURIComponent(s), {method: "DELETE"});
  refresh();
}

function render() {
  nav();
  const h = location.hash || "#/cluster";
  const main = document.getElementById("main");
  const mt = h.match(/^#\\/tables\\/(.+)$/);
  if (mt) main.innerHTML = vTable(decodeURIComponent(mt[1]));
  else if (h.startsWith("#/tables")) main.innerHTML = vTables();
  else if (h.startsWith("#/fleet")) main.innerHTML = vFleet();
  else if (h.startsWith("#/tasks")) main.innerHTML = vTasks();
  else if (h.startsWith("#/query")) main.innerHTML = vQuery();
  else main.innerHTML = vCluster();
}
// event delegation via data attributes: dataset values arrive
// entity-DECODED as plain strings, so names with quotes/backslashes
// can never become executable script (no inline onclick handlers)
document.addEventListener("click", (ev) => {
  const b = ev.target.closest("button[data-act]");
  if (!b) return;
  const {act, t, s} = b.dataset;
  if (act === "del") delSeg(t, s);
  else if (act === "reb") rebalance(t);
  else if (act === "task") runTask(t);
  else if (act === "query") runQuery();
  else if (act === "forensics") showForensics();
});
window.addEventListener("hashchange", render);
setInterval(() => {
  if (document.getElementById("auto").checked
      && !(location.hash || "").startsWith("#/query")) refresh();
}, 3000);
render();
</script></body></html>"""


def render_app(bootstrap: Dict[str, Any]) -> str:
    """The SPA page with the cluster snapshot inlined (hydration seed —
    first paint and curl/tests see real data with zero extra fetches).
    `</` must not appear un-escaped inside a <script> block."""
    blob = json.dumps(bootstrap).replace("</", "<\\/")
    return _PAGE.replace("__BOOTSTRAP__", blob)
