"""Broker node: REST query entry, routing, scatter-gather, failure handling.

Reference parity: pinot-broker/ — PinotClientRequest.java:110 (/query/sql),
BrokerRoutingManager (routing table from the ideal state), instance
selectors (BalancedInstanceSelector round-robin across replicas),
ConnectionFailureDetector (unhealthy on failure, exponential-backoff
retry), and SingleConnectionBrokerRequestHandler.java:141-151
(scatter over servers, gather DataTables, reduce). Scatter here is
threaded HTTP to server nodes; partials come back in the serde wire
format and reduce through the same BrokerReduceService analog the
in-process broker uses.
"""
from __future__ import annotations

import threading
import time
import urllib.error
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

from ..engine.reduce import ResultTable, reduce_partials
from ..engine.serde import partial_from_wire
from ..query.context import build_query_context
from ..query.sql import SetOpStmt, SqlError, parse_sql, to_sql
from .http_util import JsonHandler, http_json, start_http


class FailureDetector:
    """Consecutive-failure marking with exponential backoff retry
    (BaseExponentialBackoffRetryFailureDetector analog)."""

    def __init__(self, base_backoff: float = 0.5, max_backoff: float = 30.0):
        self._fails: Dict[str, int] = {}
        self._until: Dict[str, float] = {}
        self._base = base_backoff
        self._max = max_backoff
        self._lock = threading.Lock()

    def healthy(self, server: str) -> bool:
        with self._lock:
            return time.monotonic() >= self._until.get(server, 0.0)

    def record_failure(self, server: str) -> None:
        with self._lock:
            n = self._fails.get(server, 0) + 1
            self._fails[server] = n
            backoff = min(self._base * (2 ** (n - 1)), self._max)
            self._until[server] = time.monotonic() + backoff

    def record_success(self, server: str) -> None:
        with self._lock:
            self._fails.pop(server, None)
            self._until.pop(server, None)


class BrokerNode:
    def __init__(self, controller_url: str, port: int = 0,
                 routing_refresh: float = 0.3):
        self.controller_url = controller_url
        self.routing_refresh = routing_refresh
        self._routing: Dict[str, Any] = {"version": -1}
        self._rr = 0  # round-robin cursor (BalancedInstanceSelector)
        self._failures = FailureDetector()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._pool = ThreadPoolExecutor(max_workers=16)
        self._httpd, self.port, _ = start_http(self._make_handler(), port)
        self._refresh_routing()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    # -- routing -----------------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.wait(self.routing_refresh):
            try:
                self._refresh_routing()
            except Exception:
                pass

    def _refresh_routing(self) -> None:
        snap = http_json("GET", f"{self.controller_url}/routing")
        with self._lock:
            if snap["version"] != self._routing.get("version"):
                self._routing = snap

    def wait_for_version(self, version: int, timeout: float = 10.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._routing.get("version", -1) >= version:
                return True
            try:
                self._refresh_routing()
            except Exception:
                pass
            time.sleep(0.05)
        return False

    def _server_url(self, server_id: str) -> Optional[str]:
        inst = self._routing.get("instances", {}).get(server_id)
        if inst is None:
            return None
        return f"http://{inst['host']}:{inst['port']}"

    def _route(self, table: str) -> Dict[str, List[str]]:
        """segment -> replica server ids, from the cached ideal state."""
        with self._lock:
            assignment = self._routing.get("assignment", {}).get(table)
        if assignment is None:
            raise SqlError(f"table {table!r} not found in routing")
        return assignment

    def _pick_replica(self, holders: List[str]) -> Optional[str]:
        candidates = [h for h in holders if self._failures.healthy(h)
                      and self._server_url(h)]
        if not candidates:
            # all backed off: try anyway rather than failing outright
            candidates = [h for h in holders if self._server_url(h)]
        if not candidates:
            return None
        self._rr += 1
        return candidates[self._rr % len(candidates)]

    # -- query path --------------------------------------------------------
    def query(self, sql: str) -> ResultTable:
        t0 = time.perf_counter()
        stmt = parse_sql(sql)
        if isinstance(stmt, SetOpStmt):
            return self._query_setop(stmt, t0)
        from ..multistage.window import has_window
        if stmt.joins or has_window(stmt):
            raise SqlError("multi-stage joins/windows over the remote data "
                           "plane arrive with the dispatch stage; use the "
                           "in-process broker for them")
        ctx = build_query_context(stmt)
        assignment = self._route(ctx.table)

        if stmt.explain:
            # plan shape is identical across servers: ask any holder, with
            # the same failover + failure-detector recording as the data path
            for seg, holders in assignment.items():
                tried = set()
                while True:
                    pick = self._pick_replica(
                        [h for h in holders if h not in tried])
                    if pick is None:
                        break
                    try:
                        resp = http_json(
                            "POST", f"{self._server_url(pick)}/query",
                            {"sql": sql})
                    except Exception:
                        tried.add(pick)
                        self._failures.record_failure(pick)
                        continue
                    exp = resp.get("explain", {})
                    return ResultTable(exp.get("columns", []),
                                       [tuple(r) for r in exp.get("rows", [])])
            raise SqlError("no live replica to explain against")

        # scatter: group segments by chosen replica
        by_server: Dict[str, List[str]] = {}
        unserved: List[str] = []
        for seg, holders in assignment.items():
            pick = self._pick_replica(holders)
            if pick is None:
                unserved.append(seg)
            else:
                by_server.setdefault(pick, []).append(seg)
        if unserved:
            raise SqlError(f"no live replica for segments {unserved[:3]}"
                           f"{'...' if len(unserved) > 3 else ''}")

        def call(server: str, segs: List[str], retry: bool = True):
            url = self._server_url(server)
            try:
                resp = http_json("POST", f"{url}/query",
                                 {"sql": sql, "segments": segs})
                self._failures.record_success(server)
                return resp
            except urllib.error.HTTPError as e:
                # the server answered: an application error, not a health
                # signal — surface it, don't poison the failure detector
                self._failures.record_success(server)
                try:
                    detail = e.read().decode()[:200]
                except Exception:
                    detail = str(e)
                raise SqlError(f"server {server} rejected query: "
                               f"{detail}") from None
            except Exception:
                self._failures.record_failure(server)
                if not retry:
                    raise
                # failover: re-pick replicas per segment, one retry
                regrouped: Dict[str, List[str]] = {}
                for seg in segs:
                    holders = [h for h in assignment.get(seg, [])
                               if h != server]
                    pick = self._pick_replica(holders)
                    if pick is None:
                        raise SqlError(f"no replica left for {seg!r}")
                    regrouped.setdefault(pick, []).append(seg)
                out = {"partials": [], "segmentsQueried": 0}
                for srv, ss in regrouped.items():
                    r = call(srv, ss, retry=False)
                    out["partials"].extend(r["partials"])
                    out["segmentsQueried"] += r["segmentsQueried"]
                return out

        futures = [self._pool.submit(call, srv, segs)
                   for srv, segs in by_server.items()]
        partials = []
        queried = 0
        for f in futures:
            resp = f.result()
            partials.extend(partial_from_wire(p) for p in resp["partials"])
            queried += resp["segmentsQueried"]

        result = reduce_partials(ctx, partials)
        result.num_segments = queried
        result.time_ms = (time.perf_counter() - t0) * 1e3
        return result

    def _query_setop(self, stmt: SetOpStmt, t0: float) -> ResultTable:
        """Set operations over the remote data plane: run each branch as
        its own scatter-gather (rendered back to SQL), combine at this
        broker — the same multiset merge the in-process broker uses."""
        from ..engine.reduce import DEFAULT_LIMIT
        from ..engine.setops import combine_setop, order_limit_rows

        def run(node) -> ResultTable:
            if isinstance(node, SetOpStmt):
                return combine_setop(node.op, node.all,
                                     run(node.left), run(node.right))
            if stmt.options:
                node.options = {**stmt.options, **node.options}
            if node.limit is None:
                node.limit = 1 << 31
            return self.query(to_sql(node))

        result = combine_setop(stmt.op, stmt.all,
                               run(stmt.left), run(stmt.right))
        limit = stmt.limit if stmt.limit is not None else DEFAULT_LIMIT
        result = order_limit_rows(result, stmt.order_by, limit, stmt.offset)
        result.time_ms = (time.perf_counter() - t0) * 1e3
        return result

    # -- REST --------------------------------------------------------------
    def _make_handler(self):
        node = self

        def q(h, b):
            sql = (b or {}).get("sql")
            if not sql:
                return 400, {"error": "missing sql"}
            try:
                return 200, node.query(sql).to_dict()
            except SqlError as e:
                return 400, {"error": str(e)}

        class Handler(JsonHandler):
            routes = {
                ("GET", "/health"): lambda h, b: (200, {"status": "OK"}),
                ("POST", "/query/sql"): q,
            }
        return Handler

    def stop(self) -> None:
        self._stop.set()
        self._pool.shutdown(wait=False)
        self._httpd.shutdown()
        self._httpd.server_close()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"
