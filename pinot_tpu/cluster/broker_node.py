"""Broker node: REST query entry, routing, scatter-gather, failure handling.

Reference parity: pinot-broker/ — PinotClientRequest.java:110 (/query/sql),
BrokerRoutingManager (routing table from the ideal state), instance
selectors (BalancedInstanceSelector round-robin across replicas),
ConnectionFailureDetector (unhealthy on failure, exponential-backoff
retry), and SingleConnectionBrokerRequestHandler.java:141-151
(scatter over servers, gather DataTables, reduce). Scatter here is
threaded HTTP to server nodes; partials come back in the serde wire
format and reduce through the same BrokerReduceService analog the
in-process broker uses.
"""
from __future__ import annotations

import itertools
import json
import threading
import time
import urllib.error
import uuid
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..engine.reduce import ResultTable, reduce_partials

from ..query.context import build_query_context
from ..query.sql import SetOpStmt, SqlError, parse_sql, to_sql
from ..utils import phases as ph
from ..utils.metrics import global_metrics, ingest_health
from ..utils.spans import Span, sample_decision, span, span_tracer
from ..utils.slo import SLOWQ_TAIL, global_incidents, global_slo
from .autopsy import global_autopsy, load_corpus, whydown
from .forensics import (QueryForensics, debug_index,
                        ledger_debug_payload, memory_debug_payload,
                        parse_since, parse_slow_query_ms,
                        parse_trace_ratio)
from .http_util import (JsonHandler, http_json, http_raw,
                        inject_trace_context, start_http)

# pinot-common QueryException error-code analogs (the exceptions[] wire
# contract the webapp/console already renders)
ERR_QUERY_EXECUTION = 200      # server answered with an application error
ERR_BROKER_TIMEOUT = 250       # query deadline exhausted mid-scatter
ERR_SERVER_NOT_RESPONDED = 427  # transport failure / no replica left


class ScatterTimeoutError(SqlError):
    """The query's timeoutMs budget ran out while scattering."""


def _parse_timeout_ms(options: Dict[str, Any]) -> int:
    """Validate OPTION(timeoutMs=...) up front: a bad value must be a
    400-class SqlError, never a ValueError escaping as a 500."""
    from ..broker.broker import DEFAULT_TIMEOUT_MS
    raw = options.get("timeoutMs", DEFAULT_TIMEOUT_MS)
    try:
        return int(raw)
    except (TypeError, ValueError):
        raise SqlError(f"invalid timeoutMs value {raw!r}; "
                       "expected an integer of milliseconds") from None


class ReplicaExhaustedError(SqlError):
    """No healthy replica left for a segment — an availability failure
    (exceptions[] code 427), not a query-execution error."""


class _SegmentShortfall(Exception):
    """A server answered 200 but ran fewer segments than asked — it is
    mid-(re)load after a heartbeat loss / reassignment and silently
    skips segments it doesn't hold yet. Classified with the transport
    failures so the caller fails over instead of reducing over a
    silent subset (found by the chaos soak: heartbeat churn under CPU
    starvation produced exact-looking partial answers)."""


@dataclass
class ScatterResult:
    """One scatter-gather's partials + the health metadata the response
    envelope carries (BrokerResponseNative analog). failovers/hedges are
    the PER-QUERY counts (global_metrics keeps the process-wide totals)
    so the forensics plane can write per-query trend lines."""
    partials: List[Any] = field(default_factory=list)
    segments_queried: int = 0
    pruned: int = 0
    servers_queried: int = 0
    servers_responded: int = 0
    exceptions: List[Dict[str, Any]] = field(default_factory=list)
    partial: bool = False
    failovers: int = 0
    hedges: int = 0
    # serde vs true-network split of the round-10 net gap, summed over
    # this scatter's calls: serde_ms = server-side frame encode +
    # broker-side decode; net_ms = call wall - remote tree - serde
    # (only measured on sampled/traced calls, where the remote tree
    # exists to subtract)
    serde_ms: float = 0.0
    net_ms: float = 0.0
    # cross-query micro-batching participation (engine/ragged.py via
    # the server wire header): fused dispatches this query's server
    # executions rode, and the largest batch any of them shared
    batched_dispatches: int = 0
    batch_size_max: int = 0
    # placement-affinity routing (HBM tier): segments this scatter sent
    # to a replica already holding them hot (or a warm cube) — the
    # per-query avoided-upload count (set on the scatter thread before
    # dispatch, never from pool threads)
    affinity_hits: int = 0
    # failovers/serde/net increment from call() on POOL threads —
    # float/int += is a non-atomic read-modify-write (the same race _rr
    # hit before its itertools.count fix), so they mutate under this lock
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False, compare=False)
    # set when the gather returns: an ABANDONED hedge straggler's late
    # response must not add its serde/net to a query_stats record that
    # is being (or has been) written — the span plane snapshots
    # `collect` for the same reason
    _closed: bool = field(default=False, repr=False, compare=False)

    def add_wire_times(self, serde: float, net: float = 0.0) -> None:
        with self._lock:
            if self._closed:
                return
            self.serde_ms += serde
            self.net_ms += net

    def add_batching(self, dispatches: int, batch_size: int) -> None:
        with self._lock:
            if self._closed:
                return
            self.batched_dispatches += int(dispatches)
            self.batch_size_max = max(self.batch_size_max,
                                      int(batch_size))

    def close_wire_times(self) -> None:
        with self._lock:
            self._closed = True


class FailureDetector:
    """Consecutive-failure marking with exponential backoff retry
    (BaseExponentialBackoffRetryFailureDetector analog)."""

    def __init__(self, base_backoff: float = 0.5, max_backoff: float = 30.0):
        self._fails: Dict[str, int] = {}
        self._until: Dict[str, float] = {}
        self._base = base_backoff
        self._max = max_backoff
        self._lock = threading.Lock()

    def healthy(self, server: str) -> bool:
        with self._lock:
            return time.monotonic() >= self._until.get(server, 0.0)

    def record_failure(self, server: str) -> None:
        with self._lock:
            n = self._fails.get(server, 0) + 1
            self._fails[server] = n
            backoff = min(self._base * (2 ** (n - 1)), self._max)
            self._until[server] = time.monotonic() + backoff

    def record_success(self, server: str) -> None:
        with self._lock:
            self._fails.pop(server, None)
            self._until.pop(server, None)

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Per-server consecutive-failure state for /metrics and the UI."""
        now = time.monotonic()
        with self._lock:
            servers = set(self._fails) | set(self._until)
            return {s: {
                "consecutiveFailures": self._fails.get(s, 0),
                "backoffRemainingS": round(
                    max(self._until.get(s, 0.0) - now, 0.0), 3),
            } for s in sorted(servers)}


class BrokerNode:
    def __init__(self, controller_url: str, port: int = 0,
                 routing_refresh: float = 0.3,
                 instance_selector: str = "balanced",
                 slow_query_ms: Optional[float] = None,
                 query_stats_path: Optional[str] = None,
                 trace_ratio: Optional[float] = None,
                 instance_id: Optional[str] = None):
        import os
        from ..broker.quota import QueryQuotaManager
        from ..broker.routing import make_selector
        from ..broker.workload import global_workload
        # overload protection (ISSUE 12): per-tenant budget admission +
        # the watermark degradation ladder, shared process-global with
        # the in-process broker (tenant isolation is per process)
        self.workload = global_workload
        self.controller_url = controller_url
        self.routing_refresh = routing_refresh
        # fleet identity (round 14): brokers register with the controller
        # like servers do (role "broker"), so the ForensicsRollupTask can
        # discover and pull their ledgers; live_servers() filters on role,
        # so broker registration never perturbs segment assignment
        self._instance_id = instance_id   # default derived after bind
        self.advertise_host = (os.environ.get("PINOT_ADVERTISE_HOST")
                               or "127.0.0.1")
        # forensics plane: slow-query ring (GET /debug/queries) + the
        # optional per-query query_stats ledger (chaos soak trend lines)
        # + the traceRatio production-sampling default (round 12)
        self.forensics = QueryForensics(slow_query_ms=slow_query_ms,
                                        ledger_path=query_stats_path,
                                        trace_ratio=trace_ratio)
        # compile-plane forensics (ISSUE 15): with a stats ledger
        # configured and no explicit PINOT_COMPILE_LEDGER, compile
        # events land in the SAME ledger so /debug/ledger ships them to
        # the fleet rollup's plan_shapes ranking with zero extra config
        # (first broker wins in in-process multi-broker tests)
        if self.forensics.ledger_path:
            from ..utils.compileplane import global_compile_log
            global_compile_log.configure_path_if_unset(
                self.forensics.ledger_path)
        # SLO plane (ISSUE 17): burn alerts / slo_status / incident
        # bundles default into the SAME stats ledger so /debug/ledger
        # ships them to the fleet rollup with zero extra config, and
        # the broker donates its slow-query ring tail to the incident
        # flight recorder's bundle (utils/ cannot import cluster state)
        if self.forensics.ledger_path:
            if global_slo.path is None:
                global_slo.path = self.forensics.ledger_path
            if global_incidents.path is None:
                global_incidents.path = self.forensics.ledger_path
            # incident autopsy plane (round 25): verdicts land in the
            # SAME ledger, and attribution runs automatically after
            # each incident capture — on the recorder's background
            # thread, fenced, never on the query path
            if global_autopsy.path is None:
                global_autopsy.path = self.forensics.ledger_path
            if global_incidents.post_hook is None:
                global_incidents.post_hook = global_autopsy.on_incident
        global_incidents.register_surface(
            "slow_queries",
            lambda: self.forensics.snapshot(SLOWQ_TAIL)["queries"])
        self._routing: Dict[str, Any] = {"version": -1}
        # round-robin cursor for explain/failover re-picks. An itertools
        # counter, not an int += 1: _pick_replica runs on pool threads
        # during failover, and the unlocked read-modify-write lost
        # increments (next() is a single atomic step under the GIL)
        self._rr = itertools.count(1)
        self._failures = FailureDetector()
        self._selector = make_selector(instance_selector)
        self._quota = QueryQuotaManager()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._pool = ThreadPoolExecutor(max_workers=16)
        self._httpd, self.port, _ = start_http(self._make_handler(), port)
        # the default identity is STABLE across restarts (host + bound
        # port, like operator-named servers), not a fresh random token:
        # the controller's rollup cursors key on this id, and a restart
        # under a new id would re-ship the broker's whole ledger into
        # the fleet ledger as duplicates
        self.instance_id = (self._instance_id
                            or f"broker_{self.advertise_host}_{self.port}")
        try:
            # best-effort: the controller may be an HA standby (503) or
            # briefly down — the loop below retries via the 404 path
            self._register()
        except Exception:
            pass
        self._refresh_routing()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _register(self) -> None:
        http_json("POST", f"{self.controller_url}/instances", {
            "id": self.instance_id, "host": self.advertise_host,
            "port": self.port, "role": "broker"})

    # -- routing -----------------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.wait(self.routing_refresh):
            epoch = None
            try:
                try:
                    resp = http_json(
                        "POST", f"{self.controller_url}/heartbeat/"
                                f"{self.instance_id}")
                    # assignment-version epoch (round 24): the
                    # heartbeat response names the controller's current
                    # version, so a rebalance flip that lands mid-poll
                    # converges on THIS tick instead of the next one
                    epoch = (resp or {}).get("version")
                except urllib.error.HTTPError as e:
                    if e.code != 404:
                        raise
                    # restarted controller with empty ephemeral state:
                    # re-announce (same rule as ServerNode._loop)
                    self._register()
            except Exception:
                pass
            try:
                self._refresh_routing()
                if epoch is not None and \
                        self._routing.get("version", -1) < epoch:
                    # the refresh raced a concurrent flip: the epoch
                    # proves a newer assignment exists — re-fetch now
                    self._refresh_routing()
            except Exception:
                pass

    def _refresh_routing(self) -> None:
        snap = http_json("GET", f"{self.controller_url}/routing")
        with self._lock:
            # always swap: instance host/port and liveServers are
            # heartbeat-driven, NOT version-driven — a rolled server
            # re-registers on a new port with the assignment version
            # unchanged, and a version-gated swap would keep routing
            # queries to the dead port forever (found by the rolling-
            # upgrade compat verifier, round-5). Consumers take one
            # snapshot reference, so the whole-dict swap stays
            # tear-free.
            self._routing = snap

    def wait_for_version(self, version: int, timeout: float = 10.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._routing.get("version", -1) >= version:
                return True
            try:
                self._refresh_routing()
            except Exception:
                pass
            time.sleep(0.05)
        return False

    def _server_url(self, server_id: str) -> Optional[str]:
        inst = self._routing.get("instances", {}).get(server_id)
        if inst is None:
            return None
        return f"http://{inst['host']}:{inst['port']}"

    def _route(self, table: str) -> Dict[str, List[str]]:
        """segment -> replica server ids, from the cached ideal state."""
        with self._lock:
            assignment = self._routing.get("assignment", {}).get(table)
        if assignment is None:
            raise SqlError(f"table {table!r} not found in routing")
        return assignment

    def _pick_replica(self, holders: List[str]) -> Optional[str]:
        candidates = [h for h in holders if self._failures.healthy(h)
                      and self._server_url(h)]
        if not candidates:
            # all backed off: try anyway rather than failing outright
            candidates = [h for h in holders if self._server_url(h)]
        if not candidates:
            return None
        return candidates[next(self._rr) % len(candidates)]

    # -- query path --------------------------------------------------------
    def _snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return self._routing

    def _table_config(self, table: str,
                      snap: Optional[Dict[str, Any]] = None
                      ) -> Dict[str, Any]:
        snap = snap if snap is not None else self._snapshot()
        return (snap.get("tables", {}).get(table) or {}).get("config") or {}

    def _placement(self, table: str,
                   snap: Dict[str, Any]) -> Dict[str, Dict[str, str]]:
        """{segment: {server: tier}} from the heartbeat-shipped
        residency blocks (HBM tier placement signal); empty when no
        server reports residency for this table."""
        out: Dict[str, Dict[str, str]] = {}
        for sid, inst in (snap.get("instances") or {}).items():
            res = (inst.get("residency") or {}).get(table) or {}
            for seg, tier in res.items():
                out.setdefault(seg, {})[sid] = tier
        return out

    def _segment_meta(self, table: str,
                      snap: Optional[Dict[str, Any]] = None
                      ) -> Dict[str, Any]:
        snap = snap if snap is not None else self._snapshot()
        return {s: (e or {}).get("meta")
                for s, e in (snap.get("segments", {}).get(table)
                             or {}).items()}

    def _check_quota(self, table: str,
                     snap: Optional[Dict[str, Any]] = None) -> None:
        snap = snap if snap is not None else self._snapshot()
        qps = self._table_config(table, snap).get("quotaQps")
        # the reference divides the table quota by the number of LIVE
        # brokers (external-view-change analog): the controller ships
        # the heartbeat-fresh broker list in every routing snapshot
        self._quota.set_num_brokers(len(snap.get("liveBrokers") or [])
                                    or 1)
        self._quota.set_quota(table, qps)
        self._quota.check(table)

    def _resolve_workload_tenant(self, table: Optional[str]) -> None:
        """Refresh the workload manager's table->tenant mapping from
        the routing snapshot's table config (the TableConfig ``tenant``
        field as shipped by the controller; hybrid logical names fall
        back to the _OFFLINE half's config)."""
        if not table:
            return
        cfg = self._table_config(table)
        if not cfg:
            cfg = self._table_config(f"{table}_OFFLINE")
        self.workload.set_table_tenant(table, cfg.get("tenant"))

    @staticmethod
    def _workload_fields(ticket) -> Optional[Dict[str, Any]]:
        """query_stats ledger fields for an ADMITTED query's workload
        attribution (the shed path builds its own)."""
        if ticket is None:
            return None
        out: Dict[str, Any] = {"tenant": ticket.tenant}
        if ticket.rung:
            out["rung"] = ticket.rung
        return out

    def query(self, sql: str) -> ResultTable:
        t0 = time.perf_counter()
        stmt = parse_sql(sql)
        from ..query.sql import DdlStmt
        if isinstance(stmt, DdlStmt):
            raise SqlError(
                "view DDL runs on the in-process broker (views are "
                "broker-local state; the networked broker carries no "
                "catalog yet)")
        # validate the forensics options up front (400-class, pre-dispatch)
        options = getattr(stmt, "options", {}) or {}
        slow_ms = parse_slow_query_ms(options,
                                      self.forensics.default_slow_ms)
        ratio = parse_trace_ratio(options, self.forensics.trace_ratio)
        # a client-supplied OPTION(queryId=...) is what makes the
        # deterministic sampling AND shed decisions hold ACROSS broker
        # replicas and client retries — without it each broker draws a
        # fresh uuid and only same-broker machinery (failover/hedge
        # attempts, which share this qid via traceContext) agrees
        qid = str(options.get("queryId") or uuid.uuid4().hex[:12])[:64]
        table = getattr(stmt, "table", None)
        # overload admission (ISSUE 12, broker/workload.py) once per
        # user query, before any planning/dispatch work. Plan-only
        # EXPLAIN skips (nothing to protect); a shed is recorded as a
        # query_stats row (tenant/rung/retryAfterMs) so the fleet
        # rollup trends shed rates, then surfaces as the structured
        # 429 (the /query/sql handler renders e.payload()).
        from ..broker.workload import (OverloadShedError, clamp_brownout,
                                       leaf_table, parse_retry_attempt)
        retry_attempt = parse_retry_attempt(options)
        ticket = None
        if not getattr(stmt, "explain", False) or \
                getattr(stmt, "analyze", False):
            wl_table = table or leaf_table(stmt)
            self._resolve_workload_tenant(wl_table)
            try:
                ticket = self.workload.admit(
                    qid, wl_table, retry_attempt=retry_attempt)
            except OverloadShedError as e:
                self.forensics.record(
                    qid, table, sql, t0, None, [], slow_ms, error=e,
                    workload={"tenant": e.tenant, "tier": e.tier,
                              "shed": True, "shed_rung": e.rung,
                              "retry_after_ms": e.retry_after_ms})
                raise
            if ticket.brownout:
                # rung-3 brownout: every admitted query clamps to the
                # floor deadline and runs with partial-result
                # semantics — a degraded answer beats a metastable
                # retry storm (one shared helper so the two brokers'
                # ladders can't drift)
                from ..broker.broker import DEFAULT_TIMEOUT_MS
                clamp_brownout(stmt.options, DEFAULT_TIMEOUT_MS)
        result: Optional[ResultTable] = None
        try:
            if getattr(stmt, "analyze", False):
                result = self._query_analyze(stmt, sql, t0, slow_ms)
                return result
            # traceRatio production sampling: deterministic in the qid
            # so replicas/retries agree when the client names the
            # query; a sampled query roots the SAME span tree EXPLAIN
            # ANALYZE uses (the scatter then propagates sampled=true
            # traceContext to every server), zero spans when unsampled.
            # EXPLAIN (plan-only) never samples, and rung >= 1 sheds
            # this speculative work entirely.
            sampled = (not getattr(stmt, "explain", False)
                       and not (ticket is not None and ticket.degraded)
                       and sample_decision(qid, ratio))
            scatters: List[ScatterResult] = []
            root: Optional[Span] = None
            if sampled:
                root = span_tracer.start(ph.QUERY, table=table,
                                         query_id=qid, sampled=True)
            try:
                try:
                    result = self._query_stmt(
                        stmt, sql, t0, qid, scatters,
                        workload=None if ticket is None else
                        {"tenant": ticket.tenant, "tier": ticket.tier})
                finally:
                    if sampled:
                        # stop on EVERY exit: a leaked thread-local
                        # stack would silently trace the next query on
                        # this HTTP worker thread
                        root = span_tracer.stop() or root
            except SqlError as e:
                if sampled and root is not None:
                    # the stats record below is flagged traced=true, so
                    # the trace record must exist for the qid join to
                    # hold — a failed query's spans are exactly the
                    # wanted ones
                    root.annotate(error=str(e)[:200])
                    self.forensics.record_trace(root, sql, qid)
                self.forensics.record(qid, table, sql, t0, None,
                                      scatters, slow_ms, trace=root,
                                      error=e, traced=sampled,
                                      workload=self._workload_fields(
                                          ticket))
                raise
            if sampled:
                root.annotate(
                    rows=len(result.rows),
                    servers_queried=result.num_servers_queried,
                    servers_responded=result.num_servers_responded)
                global_metrics.count("sampled_traces")
                self.forensics.record_trace(root, sql, qid)
            self.forensics.record(qid, table, sql, t0, result, scatters,
                                  slow_ms, trace=root, traced=sampled,
                                  workload=self._workload_fields(ticket))
            return result
        finally:
            # result-bytes estimate feeds the tenant's post-paid bucket
            # (the cluster broker never runs the engine's track_result
            # fence itself — the reduced rows are its usage signal)
            est = 0
            if result is not None:
                est = len(result.rows) * max(len(result.columns), 1) * 8
            self.workload.release(ticket, result_bytes=est or None)

    def _query_stmt(self, stmt, sql: str, t0: float, qid: str,
                    scatters: List["ScatterResult"],
                    workload: Optional[Dict[str, Any]] = None
                    ) -> ResultTable:
        """One statement through routing/scatter/reduce. ``scatters``
        collects every ScatterResult this statement dispatched so the
        caller (forensics, EXPLAIN ANALYZE) sees per-query hedge and
        failover counts. ``workload`` is the admitted query's
        tenant/tier attribution, forwarded on every server dispatch so
        the server-side accountant registers it too — the tier-aware
        HeapWatcher kill ordering and the post-paid cpu budgets run
        where the work actually executes, not just at the broker."""
        if isinstance(stmt, SetOpStmt):
            return self._query_setop(stmt, t0, qid, scatters, workload)
        from ..multistage.window import has_window
        if stmt.joins or has_window(stmt):
            raise SqlError("multi-stage joins/windows over the remote data "
                           "plane arrive with the dispatch stage; use the "
                           "in-process broker for them")

        # one snapshot for the whole query: hybrid detection, quota, time
        # boundary, pruning, and scatter must agree on routing state (the
        # refresh thread swaps self._routing underneath)
        snap = self._snapshot()
        # the query's timeoutMs is a BUDGET for the whole scatter: every
        # server call gets the remaining slice, and servers receive it as
        # deadlineMs so their accountant deadline is min(own, remaining)
        timeout_ms = _parse_timeout_ms(stmt.options)
        deadline = t0 + timeout_ms / 1e3
        snap_tables = snap.get("tables", {})
        if stmt.table not in snap_tables and \
                f"{stmt.table}_OFFLINE" in snap_tables and \
                f"{stmt.table}_REALTIME" in snap_tables:
            return self._query_hybrid(stmt, t0, snap, deadline, qid,
                                      scatters, workload)

        self._check_quota(stmt.table, snap)
        ctx = build_query_context(stmt)
        if stmt.explain:
            return self._explain_remote(sql, ctx.table, deadline)
        sc = self._scatter(sql, ctx, snap, deadline, qid, workload)
        scatters.append(sc)
        with span(ph.REDUCE, partials=len(sc.partials)):
            result = reduce_partials(ctx, sc.partials)
        result.num_segments = sc.segments_queried
        result.num_segments_pruned = sc.pruned
        self._attach_scatter_meta(result, [sc])
        result.time_ms = (time.perf_counter() - t0) * 1e3
        return result

    # -- EXPLAIN ANALYZE over the cluster plane (round-10 tentpole) --------
    def _query_analyze(self, stmt, sql: str, t0: float,
                       slow_ms: float) -> ResultTable:
        """Execute the statement for real under the span tracer, with
        cross-node propagation: every scatter call carries a sampled
        trace context, each server roots a remote span tree around its
        executor, and the broker stitches the trees — hedges, failovers
        and error branches included — under the scatter_call spans that
        dispatched them. Renders the same Node/Id/Parent/Time_Ms rows
        as the in-process broker (query/explain.py); the gap between a
        call span and its server_query child is the network +
        serialization cost (``net_ms``)."""
        from ..query.explain import finalize_analyze
        stmt.analyze = False  # the re-entrant path executes normally
        qid = uuid.uuid4().hex[:12]
        table = getattr(stmt, "table", None)
        scatters: List[ScatterResult] = []
        root = span_tracer.start(ph.QUERY, table=table, query_id=qid)
        err: Optional[SqlError] = None
        inner: Optional[ResultTable] = None
        try:
            inner = self._query_stmt(stmt, sql, t0, qid, scatters)
        except SqlError as e:
            err = e
        finally:
            root = span_tracer.stop() or root
        if err is not None:
            # the partial tree still reaches the forensics ring: a failed
            # analyze is exactly when the spans are wanted
            self.forensics.record(qid, table, sql, t0, None, scatters,
                                  slow_ms, trace=root, error=err,
                                  traced=True)
            raise err
        root.annotate(rows=len(inner.rows),
                      servers_queried=inner.num_servers_queried,
                      servers_responded=inner.num_servers_responded,
                      partial=inner.partial_result or None)
        cols, rows, trace = finalize_analyze(root)
        result = ResultTable(cols, rows, num_segments=inner.num_segments)
        result.trace = trace
        result.partial_result = inner.partial_result
        result.num_servers_queried = inner.num_servers_queried
        result.num_servers_responded = inner.num_servers_responded
        result.exceptions = list(inner.exceptions)
        result.time_ms = (time.perf_counter() - t0) * 1e3
        self.forensics.record(qid, table, sql, t0, result, scatters,
                              slow_ms, trace=root, traced=True)
        # whydown lane (round 25): OPTION(whydown=true) annotates the
        # analyze trace with the cross-plane events overlapping this
        # query's wall window. AFTER forensics.record, so the query's
        # own stats line anchors the ledger-position overlap
        from ..query.planner import _truthy
        options = getattr(stmt, "options", {}) or {}
        if _truthy(options.get("whydown", False)) and \
                self.forensics.ledger_path:
            trace["whydown"] = whydown(
                load_corpus(self.forensics.ledger_path), qid=qid)
        return result

    @staticmethod
    def _attach_scatter_meta(result: ResultTable,
                             scatters: List[ScatterResult]) -> None:
        result.num_servers_queried = sum(s.servers_queried
                                         for s in scatters)
        result.num_servers_responded = sum(s.servers_responded
                                           for s in scatters)
        for s in scatters:
            result.exceptions.extend(s.exceptions)
        result.partial_result = any(s.partial for s in scatters)
        if result.partial_result:
            global_metrics.count("scatter_partial_responses")

    def _query_hybrid(self, stmt, t0: float, snap: Dict[str, Any],
                      deadline: Optional[float] = None,
                      qid: Optional[str] = None,
                      scatters_out: Optional[List["ScatterResult"]] = None,
                      workload: Optional[Dict[str, Any]] = None
                      ) -> ResultTable:
        from ..broker.routing import (resolve_time_column, split_hybrid,
                                      time_boundary)
        logical = stmt.table
        off_table = f"{logical}_OFFLINE"
        self._check_quota(off_table, snap)  # charges EXPLAIN too
        time_col = resolve_time_column(
            self._table_config(off_table, snap),
            (snap.get("tables", {}).get(off_table) or {}).get("schema"))
        if not time_col:
            raise SqlError(
                f"hybrid table {logical!r} needs a timeColumn in its "
                f"config or a DATE_TIME schema field")
        boundary = time_boundary(
            self._segment_meta(off_table, snap), time_col)
        if boundary is None:
            raise SqlError(f"hybrid table {logical!r}: offline segments "
                           f"lack {time_col!r} metadata for the boundary")
        off, rt = split_hybrid(stmt, time_col, boundary)
        if stmt.explain:
            return self._explain_remote("EXPLAIN " + to_sql(off),
                                        off.table, deadline)
        scatters: List[ScatterResult] = []
        for part_stmt in (off, rt):
            ctx_p = build_query_context(part_stmt)
            scatters.append(
                self._scatter(to_sql(part_stmt), ctx_p, snap, deadline,
                              qid, workload))
        if scatters_out is not None:
            scatters_out.extend(scatters)
        with span(ph.REDUCE,
                  partials=sum(len(s.partials) for s in scatters)):
            result = reduce_partials(
                build_query_context(off),
                [p for s in scatters for p in s.partials])
        result.num_segments = sum(s.segments_queried for s in scatters)
        result.num_segments_pruned = sum(s.pruned for s in scatters)
        self._attach_scatter_meta(result, scatters)
        result.time_ms = (time.perf_counter() - t0) * 1e3
        return result

    def _explain_remote(self, sql: str, table: str,
                        deadline: Optional[float] = None) -> ResultTable:
        # plan shape is identical across servers: ask any holder, with the
        # same failover + failure-detector recording and the same
        # remaining-deadline budget as the data path
        assignment = self._route(table)
        for seg, holders in assignment.items():
            tried: set = set()
            while True:
                pick = self._pick_replica(
                    [h for h in holders if h not in tried])
                if pick is None:
                    break
                rem = None if deadline is None \
                    else deadline - time.perf_counter()
                if rem is not None and rem <= 0:
                    raise ScatterTimeoutError(
                        "query deadline exhausted while explaining")
                try:
                    resp = http_json(
                        "POST", f"{self._server_url(pick)}/query",
                        {"sql": sql},
                        timeout=10.0 if rem is None else max(rem, 0.05))
                except urllib.error.HTTPError as e:
                    # application error: surface it, keep health intact
                    self._failures.record_success(pick)
                    try:
                        detail = e.read().decode()[:200]
                    except Exception:
                        detail = str(e)
                    raise SqlError(f"server {pick} rejected explain: "
                                   f"{detail}") from None
                except Exception:
                    tried.add(pick)
                    self._failures.record_failure(pick)
                    continue
                self._failures.record_success(pick)
                exp = resp.get("explain", {})
                return ResultTable(exp.get("columns", []),
                                   [tuple(r) for r in exp.get("rows", [])])
        raise SqlError("no live replica to explain against")

    @staticmethod
    def _parse_hedge_option(ctx) -> Optional[float]:
        """Validate OPTION(hedgeMs=...) once, BEFORE dispatch: a bad
        value must be a 400-class SqlError, not a ValueError escaping
        mid-gather with futures in flight. None = option absent;
        0.0 = explicitly disabled."""
        raw = ctx.options.get("hedgeMs")
        if raw is None:
            return None
        try:
            v = float(raw)
        except (TypeError, ValueError):
            raise SqlError(f"invalid hedgeMs value {raw!r}; "
                           "expected a number of milliseconds") from None
        return max(v, 0.0)

    def _hedge_threshold_ms(self, hedge_opt: Optional[float],
                            server: str) -> Optional[float]:
        """When to re-dispatch a straggling server's segments elsewhere:
        a validated OPTION(hedgeMs=...) wins (0 disables); otherwise 3x
        the adaptive selector's latency EWMA for that server, floored at
        150 ms — the EWMA mixes query shapes, so a low floor would hedge
        every legitimately-heavy query after a stream of cheap ones
        (duplicated dispatch exactly when the cluster is loaded). A
        hedge fires at most once per group either way. Overload rung
        >= 1 disables hedging outright — speculative duplicate
        dispatch is the FIRST work the degradation ladder sheds."""
        if self.workload.governor.rung() >= 1:
            return None
        if hedge_opt is not None:
            return hedge_opt if hedge_opt > 0 else None
        est = getattr(self._selector, "estimate_ms", None)
        if est is not None:
            e = est(server)
            if e is not None:
                return max(3.0 * e, 150.0)
        return None

    def _scatter(self, sql: str, ctx,
                 snap: Optional[Dict[str, Any]] = None,
                 deadline: Optional[float] = None,
                 qid: Optional[str] = None,
                 workload: Optional[Dict[str, Any]] = None
                 ) -> ScatterResult:
        # one snapshot for assignment + segment metadata: the refresh
        # thread swaps self._routing, and mixing two snapshots could
        # silently drop segments assigned in one but absent in the other
        if snap is None:
            snap = self._snapshot()
        # tracing: when this query runs under the span tracer (EXPLAIN
        # ANALYZE rooted a tree on THIS thread), every dispatch attempt
        # gets a scatter_call span. call() runs on pool threads, so the
        # spans are built explicitly and collected here (list.append is
        # GIL-atomic), then stitched under the scatter span start-ordered
        collect: Optional[List[Span]] = \
            [] if span_tracer.active() else None
        sampled = collect is not None
        assignment = snap.get("assignment", {}).get(ctx.table)
        if assignment is None:
            raise SqlError(f"table {ctx.table!r} not found in routing")
        seg_entries = snap.get("segments", {}).get(ctx.table) or {}

        from ..query.planner import _truthy
        allow_partial = _truthy(ctx.options.get("allowPartialResults"))
        hedge_opt = self._parse_hedge_option(ctx)
        res = ScatterResult()

        # broker-side pruning over controller-held segment metadata; an
        # assigned segment with no metadata entry is never pruned
        from ..broker.routing import prune_segments
        meta = {s: (seg_entries.get(s) or {}).get("meta")
                for s in assignment}
        keep, res.pruned = prune_segments(
            meta, ctx.filter,
            (snap.get("tables", {}).get(ctx.table) or {}).get("config"))
        keep_set = set(keep)
        assignment = {s: h for s, h in assignment.items() if s in keep_set}

        # drop holders with no known URL up front so selector fallbacks
        # can only pick reachable servers
        assignment = {s: [h for h in holders if self._server_url(h)]
                      for s, holders in assignment.items()}

        # instance selection (pluggable: balanced / replicaGroup /
        # strictReplicaGroup / adaptive) — placement-aware: the
        # residency heartbeats tell the adaptive selector which
        # replicas already hold each segment hot (HBM tier)
        def healthy(h: str) -> bool:
            return self._failures.healthy(h)

        placement = self._placement(ctx.table, snap)
        picks = self._selector.select(assignment, healthy,
                                      placement=placement)
        if placement:
            # avoided-vs-paid uploads: a pick landing on a replica
            # that holds the segment hot (or a warm cube) skips the
            # column upload entirely. Segments NO server reported
            # residency for (heartbeat cap, table not yet surveyed)
            # count neither way — they would understate the hit ratio
            # through no fault of the routing
            for seg, pick in picks.items():
                tiers = placement.get(seg)
                if pick is None or not tiers:
                    continue
                if tiers.get(pick) in ("hot", "cube"):
                    res.affinity_hits += 1
                    global_metrics.count("tier_affinity_hits")
                else:
                    global_metrics.count("tier_affinity_misses")
        unserved = [s for s, p in picks.items() if p is None]
        if unserved:
            msg = (f"no live replica for segments {unserved[:3]}"
                   f"{'...' if len(unserved) > 3 else ''}")
            if not allow_partial:
                raise SqlError(msg)
            res.exceptions.append({"errorCode": ERR_SERVER_NOT_RESPONDED,
                                   "message": msg})
            res.partial = True
        by_server: Dict[str, List[str]] = {}
        for seg, pick in picks.items():
            if pick is not None:
                by_server.setdefault(pick, []).append(seg)

        adaptive = getattr(self._selector, "record_start", None)

        def remaining() -> Optional[float]:
            return None if deadline is None \
                else deadline - time.perf_counter()

        def attempt_span(server: str, segs: List[str],
                         attempt: str) -> Optional[Span]:
            if collect is None:
                return None
            # every later-written key is pre-seeded (None renders as
            # absent): an ABANDONED straggler may annotate from its pool
            # thread while the broker thread renders the tree, and value
            # overwrites of existing keys never resize the attrs dict
            # under that iteration (a fresh key insertion could)
            s = Span(ph.SCATTER_CALL, server=server, segments=len(segs),
                     attempt=attempt, span_id=uuid.uuid4().hex[:8],
                     status=None, error=None, net_ms=None, serde_ms=None)
            collect.append(s)
            return s

        def call(server: str, segs: List[str], retry: bool = True,
                 attempt: str = "primary"):
            url = self._server_url(server)
            sp = attempt_span(server, segs, attempt)
            rem = remaining()
            if rem is not None and rem <= 0:
                if sp is not None:
                    sp.finish()
                    sp.annotate(status="deadline")
                raise ScatterTimeoutError(
                    f"query deadline exhausted before dispatch to "
                    f"{server}")
            if adaptive:
                self._selector.record_start(server)
            tcall = time.perf_counter()
            try:
                from ..engine.datablock import decode_wire_frame
                from ..utils.faults import corrupt_bytes
                body = {"sql": sql, "segments": segs}
                if workload:
                    # tenant/tier attribution crosses the wire: the
                    # server registers its accountant entry with it
                    body["workload"] = workload
                if qid is not None or sampled:
                    # cross-node trace context: query id + sampled flag
                    # + the dispatching span, so the server's remote
                    # tree stitches back under THIS attempt
                    inject_trace_context(
                        body, query_id=qid, sampled=sampled,
                        parent_span_id=None if sp is None
                        else sp.attrs["span_id"],
                        remaining_ms=None if rem is None else rem * 1e3)
                if rem is not None:
                    # the server clamps its accountant deadline to
                    # min(its own timeoutMs, this remaining budget)
                    body["deadlineMs"] = int(rem * 1e3)
                raw = http_raw("POST", f"{url}/query/bin", body,
                               timeout=10.0 if rem is None
                               else max(rem, 0.05))
                raw = corrupt_bytes("wire.corrupt", server, raw)
                t_dec = time.perf_counter()
                header, decoded = decode_wire_frame(raw)
                dec_ms = (time.perf_counter() - t_dec) * 1e3
                n_run = int(header.get("segmentsQueried", 0))
                if n_run < len(segs):
                    raise _SegmentShortfall(
                        f"server {server} ran {n_run} of {len(segs)} "
                        f"requested segments (still loading after a "
                        f"reassignment?)")
                self._failures.record_success(server)
                # serde vs network split of the round-10 net gap: the
                # server timed its frame encode (serdeEncodeMs in the
                # header), the decode was timed above
                serde = dec_ms + float(header.get("serdeEncodeMs")
                                       or 0.0)
                net = 0.0
                if sp is not None:
                    sp.finish()
                    remote = header.get("trace")
                    if remote:
                        rt = Span.from_dict(remote)
                        sp.children.append(rt)
                        # call span - remote tree - serde = true
                        # network time
                        net = max(sp.duration_ms - rt.duration_ms
                                  - serde, 0.0)
                        sp.annotate(net_ms=round(net, 3))
                    sp.annotate(status="ok", serde_ms=round(serde, 3))
                res.add_wire_times(serde, net)
                if header.get("batched"):
                    res.add_batching(header.get("batched", 0),
                                     header.get("batchSize", 0))
                return {"partials": decoded, "segmentsQueried": n_run,
                        "dispatched": [server], "responders": [server]}
            except urllib.error.HTTPError as e:
                # the server answered: an application error, not a health
                # signal — surface it, don't poison the failure detector
                self._failures.record_success(server)
                try:
                    raw_body = e.read().decode()
                except Exception:
                    raw_body = str(e)
                detail = raw_body[:200]
                if sp is not None:
                    sp.finish()
                    sp.annotate(status="rejected", error=detail)
                if e.code == 429:
                    # a capacity rejection (SchedulerRejectedError via
                    # the server's JsonHandler): keep it STRUCTURED end
                    # to end so the broker's own /query/sql can render
                    # the retryable 429 instead of flattening to a 400
                    try:
                        body = json.loads(raw_body)
                    except ValueError:
                        body = {}
                    if isinstance(body, dict) and                             body.get("retryAfterMs") is not None:
                        err = SqlError(f"server {server} out of "
                                       f"capacity: "
                                       f"{body.get('error', detail)}")
                        err.error_code = int(body.get("errorCode", 429))
                        err.retry_after_ms = int(body["retryAfterMs"])
                        raise err from None
                raise SqlError(f"server {server} rejected query: "
                               f"{detail}") from None
            except (ScatterTimeoutError, SqlError):
                if sp is not None and sp.duration_ms == 0.0:
                    sp.finish()
                raise
            except Exception as e:
                self._failures.record_failure(server)
                # finish the attempt span NOW: the failover recursion
                # below gets its own spans, not this one's tail
                if sp is not None:
                    sp.finish()
                    sp.annotate(status="failed",
                                error=f"{type(e).__name__}: {e}"[:200])
                if not retry:
                    raise
                # failover: re-pick replicas per segment, one retry
                global_metrics.count("scatter_failovers")
                with res._lock:
                    res.failovers += 1
                regrouped: Dict[str, List[str]] = {}
                for seg in segs:
                    holders = [h for h in assignment.get(seg, [])
                               if h != server]
                    pick = self._pick_replica(holders)
                    if pick is None:
                        raise ReplicaExhaustedError(
                            f"no replica left for {seg!r}")
                    regrouped.setdefault(pick, []).append(seg)
                # dispatched/responders surface the failover in the
                # response health metadata: the dead primary stays in
                # "queried", the replica that actually answered joins
                # "responded" — a hidden failover is invisible otherwise
                out = {"partials": [], "segmentsQueried": 0,
                       "dispatched": [server], "responders": []}
                for srv, ss in regrouped.items():
                    r = call(srv, ss, retry=False, attempt="failover")
                    out["partials"].extend(r["partials"])
                    out["segmentsQueried"] += r["segmentsQueried"]
                    out["dispatched"].extend(r["dispatched"])
                    out["responders"].extend(r["responders"])
                return out
            finally:
                if adaptive:
                    self._selector.record_end(
                        server, (time.perf_counter() - tcall) * 1e3)

        with span(ph.SCATTER, table=ctx.table, servers=len(by_server),
                  segments=sum(len(s) for s in by_server.values())
                  ) as sc_span:
            try:
                self._gather(hedge_opt, assignment, by_server, call, res,
                             remaining, allow_partial)
            finally:
                # attach even when the gather raises: a failed analyze
                # still shows WHICH attempts failed (forensics ring).
                # Snapshot first — an abandoned straggler can still be
                # appending its failover attempt from a pool thread, and
                # list.sort() raises if the list mutates mid-sort
                res.close_wire_times()
                if sc_span is not None and collect:
                    done = list(collect)
                    done.sort(key=lambda s: s._t0)
                    sc_span.children.extend(done)
        global_metrics.gauge(
            "scatter_unhealthy_servers",
            sum(1 for s in snap.get("instances", {})
                if not self._failures.healthy(s)))
        return res

    def _gather(self, hedge_opt: Optional[float],
                assignment: Dict[str, List[str]],
                by_server: Dict[str, List[str]], call,
                res: ScatterResult, remaining, allow_partial: bool
                ) -> None:
        """Gather that collects per-server errors instead of letting the
        first f.result() abandon the rest, with deadline-aware waiting
        and hedged re-dispatch of stragglers.

        One 'group' per primary server dispatch. A group resolves when
        its primary attempt (internal failover included) succeeds, or
        when ALL parts of one hedge attempt succeed — whichever lands
        first; the loser is ignored (replica partials are byte-identical
        by construction, so either is correct, never both)."""
        groups: Dict[int, Dict[str, Any]] = {}
        fut_info: Dict[Any, Tuple[int, str, bool]] = {}
        for gid, (srv, segs) in enumerate(sorted(by_server.items())):
            groups[gid] = {"server": srv, "segs": segs, "done": False,
                           "errors": [], "t0": time.perf_counter(),
                           "hedged": False, "hedge_parts": 0,
                           "hedge_partials": [], "hedge_segments": 0,
                           "hedge_servers": [], "primary_failed": False}
            f = self._pool.submit(call, srv, segs)
            fut_info[f] = (gid, srv, False)

        responded: set = set()
        # every server an attempt was dispatched to: primaries up front,
        # hedge targets as they launch — so numServersResponded (a
        # subset of attempt targets) can never exceed numServersQueried
        queried: set = set(by_server)
        timed_out = False
        pending = set(fut_info)

        def abandon(futs) -> None:
            # consume late results/exceptions so the executor never logs
            # "exception was never retrieved" for attempts we no longer
            # care about (a hedged-out straggler, a post-deadline call)
            for f in futs:
                f.add_done_callback(lambda fut: fut.exception())

        while pending:
            if all(g["done"] for g in groups.values()):
                abandon(pending)  # every group resolved (hedges won):
                break             # don't wait out the stragglers
            rem = remaining()
            if rem is not None and rem <= 0:
                timed_out = True
                abandon(pending)
                for g in groups.values():
                    # only groups with NO recorded failure get the
                    # still-waiting entry — a server that already
                    # answered with an error must not also be reported
                    # as "did not respond"
                    if not g["done"] and not g["errors"]:
                        g["errors"].append({
                            "errorCode": ERR_BROKER_TIMEOUT,
                            "message": f"server {g['server']} did not "
                                       "respond within the query "
                                       "deadline"})
                break
            # poll fast only while some group could still hedge;
            # otherwise block the full remaining budget (or until a
            # completion) instead of 50 wakeups/s per scatter
            hedgeable = any(
                not g["done"] and not g["hedged"]
                and not g["primary_failed"]
                and self._hedge_threshold_ms(hedge_opt,
                                             g["server"]) is not None
                for g in groups.values())
            if hedgeable:
                tick = 0.02 if rem is None else min(0.02, rem)
            else:
                tick = rem  # None = block until a completion
            done, pending = wait(pending, timeout=tick,
                                 return_when=FIRST_COMPLETED)
            for f in done:
                gid, server, is_hedge = fut_info[f]
                g = groups[gid]
                try:
                    resp = f.result()
                except Exception as e:
                    if isinstance(e, ScatterTimeoutError):
                        code = ERR_BROKER_TIMEOUT
                    elif isinstance(e, ReplicaExhaustedError):
                        code = ERR_SERVER_NOT_RESPONDED
                    elif isinstance(e, SqlError):
                        # a capacity rejection keeps its own code (211/
                        # 429) so exceptions[] and the final raise stay
                        # structured-retryable end to end
                        code = getattr(e, "error_code", None) \
                            or ERR_QUERY_EXECUTION
                    else:
                        code = ERR_SERVER_NOT_RESPONDED
                    if not is_hedge:
                        g["primary_failed"] = True
                    entry = {"errorCode": code, "message": str(e),
                             "server": server}
                    if getattr(e, "retry_after_ms", None) is not None:
                        entry["retryAfterMs"] = e.retry_after_ms
                    g["errors"].append(entry)
                    continue
                if g["done"]:
                    continue  # the other attempt already resolved it
                if not is_hedge:
                    g["done"] = True
                    res.partials.extend(resp["partials"])
                    res.segments_queried += resp["segmentsQueried"]
                    queried.update(resp["dispatched"])
                    responded.update(resp["responders"])
                else:
                    g["hedge_partials"].extend(resp["partials"])
                    g["hedge_segments"] += resp["segmentsQueried"]
                    g["hedge_servers"].extend(resp["responders"])
                    g["hedge_parts"] -= 1
                    if g["hedge_parts"] == 0:
                        # every part of the hedge landed: commit it
                        g["done"] = True
                        res.partials.extend(g["hedge_partials"])
                        res.segments_queried += g["hedge_segments"]
                        responded.update(g["hedge_servers"])
            # hedge pass: a primary past its latency threshold gets its
            # segments re-dispatched to other healthy replicas, once
            now = time.perf_counter()
            for gid, g in groups.items():
                if g["done"] or g["hedged"] or g["primary_failed"]:
                    continue
                thr = self._hedge_threshold_ms(hedge_opt, g["server"])
                if thr is None or (now - g["t0"]) * 1e3 < thr:
                    continue
                g["hedged"] = True
                regrouped: Dict[str, List[str]] = {}
                ok = True
                for seg in g["segs"]:
                    holders = [h for h in assignment.get(seg, [])
                               if h != g["server"]
                               and self._failures.healthy(h)]
                    pick = self._pick_replica(holders)
                    if pick is None:
                        ok = False  # nowhere to hedge this segment
                        break
                    regrouped.setdefault(pick, []).append(seg)
                if not ok:
                    continue
                global_metrics.count("scatter_hedges", len(regrouped))
                res.hedges += len(regrouped)
                g["hedge_parts"] = len(regrouped)
                for srv2, ss in regrouped.items():
                    f2 = self._pool.submit(call, srv2, ss, False,
                                           "hedge")
                    fut_info[f2] = (gid, srv2, True)
                    queried.add(srv2)
                    pending.add(f2)

        failed = [g for g in groups.values() if not g["done"]]
        for g in failed:
            res.exceptions.extend(g["errors"])
        if res.exceptions:
            global_metrics.count("scatter_server_errors",
                                 len(res.exceptions))
        res.servers_queried = len(queried)
        res.servers_responded = len(responded)
        if failed:
            res.partial = True
            if not allow_partial:
                if timed_out:
                    raise ScatterTimeoutError(
                        f"query timed out: {len(failed)} of "
                        f"{len(groups)} servers unanswered when the "
                        f"timeoutMs budget ran out "
                        f"(set allowPartialResults=true for a partial "
                        f"answer); exceptions: "
                        f"{[e['message'] for e in res.exceptions][:3]}")
                first = (failed[0]["errors"] or
                         [{"message": "server failed"}])[0]
                err = SqlError(first["message"])
                if first.get("retryAfterMs") is not None:
                    # re-attach the capacity-rejection shape: the
                    # /query/sql handler renders these as HTTP 429
                    err.error_code = first.get("errorCode", 429)
                    err.retry_after_ms = first["retryAfterMs"]
                raise err

    def _query_setop(self, stmt: SetOpStmt, t0: float,
                     qid: Optional[str] = None,
                     scatters: Optional[List["ScatterResult"]] = None,
                     workload: Optional[Dict[str, Any]] = None
                     ) -> ResultTable:
        """Set operations over the remote data plane: run each branch as
        its own scatter-gather (rendered back to SQL), combine at this
        broker — the same multiset merge the in-process broker uses.
        The compound's timeoutMs is ONE budget: each branch gets the
        remaining slice, not a fresh full allowance. Branches run
        through _query_stmt, NOT self.query: the compound is ONE user
        query and writes ONE query_stats record — with the branch
        scatters' hedge/failover counts — not one per branch."""
        from ..engine.reduce import DEFAULT_LIMIT
        from ..engine.setops import combine_setop, order_limit_rows

        timeout_ms = _parse_timeout_ms(stmt.options)
        deadline = t0 + timeout_ms / 1e3
        qid = qid or uuid.uuid4().hex[:12]
        scatters = scatters if scatters is not None else []
        branches: List[ResultTable] = []  # leaf results carry the
        # scatter metadata combine_setop's fresh tables would drop

        def run(node) -> ResultTable:
            if isinstance(node, SetOpStmt):
                return combine_setop(node.op, node.all,
                                     run(node.left), run(node.right))
            if stmt.options:
                node.options = {**stmt.options, **node.options}
            remaining_ms = int((deadline - time.perf_counter()) * 1e3)
            node.options["timeoutMs"] = min(
                int(node.options.get("timeoutMs", timeout_ms)),
                max(remaining_ms, 1))
            if node.limit is None:
                node.limit = 1 << 31
            branch_sql = to_sql(node)
            out = self._query_stmt(parse_sql(branch_sql), branch_sql,
                                   time.perf_counter(), qid, scatters,
                                   workload)
            branches.append(out)
            return out

        result = combine_setop(stmt.op, stmt.all,
                               run(stmt.left), run(stmt.right))
        limit = stmt.limit if stmt.limit is not None else DEFAULT_LIMIT
        result = order_limit_rows(result, stmt.order_by, limit, stmt.offset)
        # a partial branch must not present the compound as complete
        result.num_servers_queried = sum(b.num_servers_queried
                                         for b in branches)
        result.num_servers_responded = sum(b.num_servers_responded
                                           for b in branches)
        for b in branches:
            result.exceptions.extend(b.exceptions)
        result.partial_result = any(b.partial_result for b in branches)
        result.time_ms = (time.perf_counter() - t0) * 1e3
        return result

    # -- scatter health (satellite: FailureDetector + counters exported) --
    def scatter_health(self) -> Dict[str, Any]:
        """Scatter-gather health: per-server consecutive-failure state
        from the FailureDetector plus the scatter counters — served at
        GET /metrics and rendered on the /ui console. ``ingest`` carries
        the realtime-plane recovery counters + freshness gauge next to
        the round-9 scatter counters (in-process roles share
        global_metrics; a standalone broker reports zeros)."""
        from ..engine.ragged import batching_health
        from ..engine.tier import tier_health
        from ..utils.compileplane import compile_health
        from ..utils.metrics import overload_health
        # armed freshness objectives sample their ingest gauges on the
        # health poll (dead/stale gauge = bad sample); unarmed this is
        # one attribute read
        if global_slo.armed:
            global_slo.observe_freshness()
        snap = global_metrics.snapshot()
        c = snap["counters"]
        fd = self._failures.snapshot()
        instances = self._snapshot().get("instances", {})
        overload = overload_health(snap)
        overload["tenants"] = self.workload.health()
        overload["governor"] = self.workload.governor.snapshot()
        return {
            "servers": fd,
            "unhealthyServers": sum(
                1 for s in instances if not self._failures.healthy(s)),
            "knownServers": len(instances),
            "counters": {k: c.get(k, 0) for k in (
                "scatter_failovers", "scatter_hedges",
                "scatter_partial_responses", "scatter_server_errors",
                "faults_fired")},
            "ingest": ingest_health(snap),
            # cross-query micro-batching counters (PR 8) — rendered on
            # the /ui console next to the scatter block
            "batching": batching_health(snap),
            # compile-plane warmup debt + storm alerting (ISSUE 15):
            # per-trigger compile counters, compile_ms_total, and the
            # storm watermark gauge beside the batching block
            "compile": compile_health(snap),
            # overload-protection plane (ISSUE 12): shed/degrade-rung
            # counters + per-tenant gauges (broker/workload.py)
            "overload": overload,
            # HBM tier occupancy + placement-affinity hit ratio
            # (engine/tier.py) — the memory-hierarchy health block
            "tier": tier_health(snap),
            # SLO burn table (ISSUE 17): per-objective fast/slow burn
            # + budget remaining + latch state (utils/slo.py)
            "slo": global_slo.status_block(),
        }

    # -- REST --------------------------------------------------------------
    def _make_handler(self):
        node = self

        def _compile_log_snapshot():
            from ..utils.compileplane import global_compile_log
            return global_compile_log.snapshot()

        def q(h, b):
            from ..broker.workload import OverloadShedError
            sql = (b or {}).get("sql")
            if not sql:
                return 400, {"error": "missing sql"}
            try:
                return 200, node.query(sql).to_dict()
            except OverloadShedError as e:
                # the structured 429: errorCode + retryAfterMs +
                # tenant/tier/rung — NEVER a 500/stack trace (the
                # acceptance contract chaos_smoke --overload pins)
                return 429, e.payload()
            except SqlError as e:
                code = getattr(e, "error_code", None)
                if code is not None and \
                        getattr(e, "retry_after_ms", None) is not None:
                    # e.g. a server's SchedulerRejectedError surfacing
                    # through the broker: keep it retryable-structured
                    return 429, (e.payload() if hasattr(e, "payload")
                                 else {"error": str(e),
                                       "errorCode": code,
                                       "retryAfterMs":
                                           e.retry_after_ms})
                return 400, {"error": str(e)}

        def _limit(path):
            from urllib.parse import parse_qs, urlparse
            try:
                return int(parse_qs(urlparse(path).query)["n"][0])
            except (KeyError, ValueError, IndexError):
                return None

        def debug_queries(h, b):
            # GET /debug/queries[?n=K]: the slow-query/forensics ring
            return 200, node.forensics.snapshot(_limit(h.path))

        def debug_incidents(h, b):
            # GET /debug/incidents[?n=K]: flight-recorder bundles,
            # newest first (utils/slo.py IncidentRecorder)
            return 200, global_incidents.snapshot(_limit(h.path))

        def debug_autopsy(h, b):
            # GET /debug/autopsy[?n=K]: verdict ring, newest first;
            # ?run=1 computes a fresh verdict synchronously over the
            # node ledger; ?qid=<id> runs the per-query whydown lane
            from urllib.parse import parse_qs, urlparse
            params = parse_qs(urlparse(h.path).query)
            qid = (params.get("qid") or [None])[0]
            if qid:
                return 200, whydown(
                    load_corpus(node.forensics.ledger_path), qid=qid)
            if (params.get("run") or [None])[0]:
                return 200, global_autopsy.run(
                    ledger_path=node.forensics.ledger_path)
            return 200, global_autopsy.snapshot(_limit(h.path))

        class Handler(JsonHandler):
            routes = {
                ("GET", "/health"): lambda h, b: (200, {"status": "OK"}),
                ("GET", "/metrics/prometheus"): lambda h, b: (
                    200, ("text/plain", global_metrics.prometheus())),
                ("GET", "/metrics"): lambda h, b: (
                    200, node.scatter_health()),
                ("GET", "/debug/queries"): debug_queries,
                # ledger shipping (round 14): the controller's
                # ForensicsRollupTask pulls validated stats/trace deltas
                # + node telemetry blocks from here
                ("GET", "/debug/ledger"): lambda h, b: (
                    200, ledger_debug_payload(
                        node.instance_id, "broker",
                        node.forensics.ledger_path,
                        parse_since(h.path))),
                ("GET", "/debug/memory"): lambda h, b: (
                    200, memory_debug_payload(node.instance_id)),
                # compile-plane forensics ring (ISSUE 15): recent
                # compile_events + compile-storm alerts, newest first
                ("GET", "/debug/compile"): lambda h, b: (
                    200, _compile_log_snapshot()),
                # debug-surface index + SLO plane (ISSUE 17)
                ("GET", "/debug"): lambda h, b: (
                    200, debug_index(node.instance_id, "broker",
                                     extra=("/debug/queries",
                                            "/debug/compile",
                                            "/debug/slo"))),
                ("GET", "/debug/incidents"): debug_incidents,
                ("GET", "/debug/autopsy"): debug_autopsy,
                ("GET", "/debug/slo"): lambda h, b: (
                    200, global_slo.status_block()),
                ("GET", "/ui"): lambda h, b: (
                    200, ("text/html", node.ui_page())),
                ("POST", "/query/sql"): q,
            }
        return Handler

    def ui_page(self) -> str:
        """Query console (GET /ui): the broker-side piece of the
        reference's controller web app (its Query Console tab posts to
        the broker exactly like this page). Server-rendered shell +
        vanilla JS against the existing /query/sql endpoint."""
        return """<!doctype html><html><head><title>pinot-tpu console</title>
<style>
 body{font-family:monospace;margin:2em;background:#111;color:#ddd}
 textarea{width:100%;height:6em;background:#1b1b1b;color:#ddd;
   border:1px solid #444;padding:.5em;font-family:monospace}
 button{margin:.5em 0;padding:.4em 1.2em;background:#2a6;border:0;
   color:#fff;cursor:pointer}
 table{border-collapse:collapse;margin-top:1em}
 td,th{border:1px solid #444;padding:.25em .6em;text-align:left}
 th{background:#222}
 #stats{color:#8a8;margin-top:.5em}
 #err{color:#e66;white-space:pre-wrap}
 #warn{color:#ea3;white-space:pre-wrap}
 #scatter{color:#789;margin-top:1.5em;font-size:.85em;
   border-top:1px solid #333;padding-top:.5em;white-space:pre-wrap}
 #slowq{color:#a96;margin-top:.5em;font-size:.85em;
   border-top:1px solid #333;padding-top:.5em}
 #slowq td{border:1px solid #333;font-size:1em}
 #links{font-size:.85em;color:#678}
 #links a{color:#7ac}
</style></head><body>
<h2>pinot-tpu query console</h2>
<div id=links>debug: <a href=/debug>index</a> &middot;
<a href=/debug/queries>queries</a> &middot;
<a href=/debug/compile>compile</a> &middot;
<a href=/debug/memory>memory</a> &middot;
<a href=/debug/ledger>ledger</a> &middot;
<a href=/debug/slo>slo</a> &middot;
<a href=/debug/incidents>incidents</a> &middot;
<a href=/debug/autopsy>autopsy</a></div>
<textarea id=sql>SELECT * FROM mytable LIMIT 10</textarea><br>
<button onclick=run()>Run (Ctrl-Enter)</button>
<div id=stats></div><div id=warn></div><div id=err></div><div id=out></div>
<div id=scatter></div>
<div id=slowq></div>
<script>
const esc=s=>String(s).replace(/[&<>"']/g,
  c=>({'&':'&amp;','<':'&lt;','>':'&gt;','"':'&quot;',"'":'&#39;'}[c]));
const sqlEl=document.getElementById('sql');
sqlEl.addEventListener('keydown',e=>{
  if(e.ctrlKey&&e.key==='Enter')run();});
async function run(){
  const t0=performance.now();
  document.getElementById('err').textContent='';
  document.getElementById('warn').textContent='';
  document.getElementById('out').innerHTML='';
  let j;
  try{
    const r=await fetch('/query/sql',{method:'POST',
      headers:{'Content-Type':'application/json'},
      body:JSON.stringify({sql:sqlEl.value})});
    j=await r.json();
  }catch(e){document.getElementById('err').textContent=e;return;}
  if(j.error){document.getElementById('err').textContent=j.error;return;}
  if(j.partialResult)
    document.getElementById('warn').textContent=
      'PARTIAL RESULT: '+j.numServersResponded+'/'+j.numServersQueried+
      ' servers responded — '+
      (j.exceptions||[]).map(e=>e.message).join('; ');
  const rt=j.resultTable||j;
  const cols=(rt.dataSchema&&rt.dataSchema.columnNames)||rt.columns||[];
  const rows=rt.rows||[];
  let h='<table><tr>'+cols.map(c=>'<th>'+esc(c)+'</th>').join('')+'</tr>';
  for(const row of rows)
    h+='<tr>'+row.map(v=>'<td>'+esc(v)+'</td>').join('')+'</tr>';
  h+='</table>';
  document.getElementById('out').innerHTML=h;
  const ms=(performance.now()-t0).toFixed(1);
  const srvMs=j.timeUsedMs!==undefined?j.timeUsedMs:j.timeMs;
  document.getElementById('stats').textContent=
    rows.length+' rows | server '+(srvMs!==undefined?
    srvMs.toFixed(1):'?')+' ms | wall '+ms+' ms | docs scanned '+
    (j.numDocsScanned!==undefined?j.numDocsScanned:'?');
}
async function health(){
  try{
    const m=await (await fetch('/metrics')).json();
    const c=m.counters||{};
    const srv=Object.entries(m.servers||{}).map(([id,s])=>
      esc(id)+': '+s.consecutiveFailures+' consecutive failures'+
      (s.backoffRemainingS>0?' (backoff '+s.backoffRemainingS+'s)':''))
      .join(' | ')||'all healthy';
    const i=m.ingest||{};
    const b=m.batching||{};const sf=b.solo_fallbacks||{};
    const o=m.overload||{};const ot=o.tenants||{};
    document.getElementById('scatter').textContent=
      'scatter health: '+m.unhealthyServers+'/'+m.knownServers+
      ' unhealthy | failovers '+(c.scatter_failovers||0)+
      ' | hedges '+(c.scatter_hedges||0)+
      ' | partial responses '+(c.scatter_partial_responses||0)+
      ' | server errors '+(c.scatter_server_errors||0)+
      ' — '+srv+
      '\\ningest: rows '+(i.ingest_rows||0)+
      ' | freshness '+(i.freshness_ms!=null?
        i.freshness_ms.toFixed(1)+' ms':'n/a')+
      ' | commit retries '+(i.ingest_commit_retries||0)+
      ' | rebalance resets '+(i.ingest_rebalance_resets||0)+
      ' | upsert replays '+(i.ingest_upsert_replays||0)+
      ' | orphans cleaned '+(i.ingest_orphans_cleaned||0)+
      '\\nbatching ('+(b.enabled?'on':'off')+'): fused dispatches '+
      (b.batched_dispatches||0)+
      ' | fused queries '+(b.batched_queries||0)+
      ' | queue depth '+(b.batch_queue_depth||0)+
      ' | cube cache '+(b.cube_cache_hits||0)+'/'+
      ((b.cube_cache_hits||0)+(b.cube_cache_misses||0))+
      ' | solo: deadline '+(sf.deadline||0)+
      ', incompatible '+(sf.incompatible||0)+
      ', window-expired '+(sf.window_expired||0)+
      ', no-peers '+(sf.no_peers||0)+
      ', timeout '+(sf.timeout||0)+
      ', leader-error '+(sf.leader_error||0)+
      ' | errors '+(b.fused_dispatch_errors||0)+
      ' | sizes '+JSON.stringify(b.batch_size_histogram||{})+
      '\\ncompile: '+(((m.compile||{}).compiles)||0)+
      ' compiles / '+(((m.compile||{}).compile_ms_total)||0).toFixed(0)+
      ' ms debt | triggers '+
      JSON.stringify((m.compile||{}).by_trigger||{})+
      ' | post-warmup '+(((m.compile||{}).post_warmup)||0)+
      ' | storm '+(((m.compile||{}).storm_per_min)||0)+'/min (watermark '+
      (((m.compile||{}).storm_watermark)||0)+') | alerts '+
      (((m.compile||{}).storm_alerts)||0)+
      '\\ntier ('+((m.tier||{}).armed?'budget '+
        ((m.tier||{}).budget_bytes||0)+'B':'unbounded')+'): hot '+
      (((m.tier||{}).hot||{}).segments||0)+' seg / '+
      (((m.tier||{}).hot||{}).bytes||0)+'B | warm '+
      (((m.tier||{}).warm||{}).segments||0)+' seg / '+
      (((m.tier||{}).warm||{}).bytes||0)+'B | cold '+
      (((m.tier||{}).cold||{}).segments||0)+
      ' | promotions '+((m.tier||{}).promotions||0)+
      ' | demotions '+((m.tier||{}).demotions||0)+
      ' | affinity '+((m.tier||{}).affinity_hits||0)+'/'+
      (((m.tier||{}).affinity_hits||0)+
       ((m.tier||{}).affinity_misses||0))+
      ((m.tier||{}).affinity_hit_ratio!=null?
        ' ('+((m.tier||{}).affinity_hit_ratio*100).toFixed(1)+'%)':'')+
      '\\noverload: rung '+(o.rung||0)+
      ' | shed '+(o.overload_shed||0)+
      ' (rung2 '+((o.shed_by_rung||{})['2']||0)+
      ', rung3 '+((o.shed_by_rung||{})['3']||0)+')'+
      ' | brownout clamps '+(o.overload_brownout_clamped||0)+
      ' | retries suppressed '+(o.overload_retries_suppressed||0)+
      ' | scheduler rejected '+(o.scheduler_rejected||0)+
      ' | tenants '+(Object.entries(ot).map(([t,s])=>
        esc(t)+'['+s.tier+'] inflight '+s.inflight+
        ' shed '+((o.shed_by_tenant||{})[t]||0)).join(', ')||'none')+
      '\\nslo: '+(((m.slo||{}).armed)?
        ((m.slo||{}).objectives||[]).map(s=>
          esc(s.scope)+'/'+s.kind+' burn '+s.burn_fast+'x/'+
          s.burn_slow+'x budget '+
          (s.budget_remaining*100).toFixed(0)+'%'+
          (s.alerting?' ALERTING':'')).join(' | ')||'no objectives'
        :'unarmed');
  }catch(e){}
}
async function slowq(){
  try{
    const d=await (await fetch('/debug/queries?n=5')).json();
    if(!d.count){
      document.getElementById('slowq').textContent=
        'forensics: no slow queries (threshold '+d.slowQueryMs+' ms)';
      return;
    }
    let h='forensics (slowest-recent, threshold '+d.slowQueryMs+
      ' ms):<table><tr><th>qid</th><th>wall ms</th><th>table</th>'+
      '<th>partial</th><th>sql</th></tr>';
    for(const e of d.queries)
      h+='<tr><td>'+esc(e.qid)+'</td><td>'+e.wall_ms+'</td><td>'+
        esc(e.table)+'</td><td>'+(e.partial?'YES':'no')+'</td><td>'+
        esc((e.sql||'').slice(0,120))+'</td></tr>';
    document.getElementById('slowq').innerHTML=h+'</table>';
  }catch(e){}
}
health();slowq();setInterval(health,3000);setInterval(slowq,3000);
</script></body></html>"""

    def stop(self) -> None:
        self._stop.set()
        self._pool.shutdown(wait=False)
        self._httpd.shutdown()
        self._httpd.server_close()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"
