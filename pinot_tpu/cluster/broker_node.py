"""Broker node: REST query entry, routing, scatter-gather, failure handling.

Reference parity: pinot-broker/ — PinotClientRequest.java:110 (/query/sql),
BrokerRoutingManager (routing table from the ideal state), instance
selectors (BalancedInstanceSelector round-robin across replicas),
ConnectionFailureDetector (unhealthy on failure, exponential-backoff
retry), and SingleConnectionBrokerRequestHandler.java:141-151
(scatter over servers, gather DataTables, reduce). Scatter here is
threaded HTTP to server nodes; partials come back in the serde wire
format and reduce through the same BrokerReduceService analog the
in-process broker uses.
"""
from __future__ import annotations

import threading
import time
import urllib.error
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

from ..engine.reduce import ResultTable, reduce_partials

from ..query.context import build_query_context
from ..query.sql import SetOpStmt, SqlError, parse_sql, to_sql
from .http_util import JsonHandler, http_json, http_raw, start_http


class FailureDetector:
    """Consecutive-failure marking with exponential backoff retry
    (BaseExponentialBackoffRetryFailureDetector analog)."""

    def __init__(self, base_backoff: float = 0.5, max_backoff: float = 30.0):
        self._fails: Dict[str, int] = {}
        self._until: Dict[str, float] = {}
        self._base = base_backoff
        self._max = max_backoff
        self._lock = threading.Lock()

    def healthy(self, server: str) -> bool:
        with self._lock:
            return time.monotonic() >= self._until.get(server, 0.0)

    def record_failure(self, server: str) -> None:
        with self._lock:
            n = self._fails.get(server, 0) + 1
            self._fails[server] = n
            backoff = min(self._base * (2 ** (n - 1)), self._max)
            self._until[server] = time.monotonic() + backoff

    def record_success(self, server: str) -> None:
        with self._lock:
            self._fails.pop(server, None)
            self._until.pop(server, None)


class BrokerNode:
    def __init__(self, controller_url: str, port: int = 0,
                 routing_refresh: float = 0.3,
                 instance_selector: str = "balanced"):
        from ..broker.quota import QueryQuotaManager
        from ..broker.routing import make_selector
        self.controller_url = controller_url
        self.routing_refresh = routing_refresh
        self._routing: Dict[str, Any] = {"version": -1}
        self._rr = 0  # round-robin cursor for explain/failover re-picks
        self._failures = FailureDetector()
        self._selector = make_selector(instance_selector)
        self._quota = QueryQuotaManager()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._pool = ThreadPoolExecutor(max_workers=16)
        self._httpd, self.port, _ = start_http(self._make_handler(), port)
        self._refresh_routing()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    # -- routing -----------------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.wait(self.routing_refresh):
            try:
                self._refresh_routing()
            except Exception:
                pass

    def _refresh_routing(self) -> None:
        snap = http_json("GET", f"{self.controller_url}/routing")
        with self._lock:
            # always swap: instance host/port and liveServers are
            # heartbeat-driven, NOT version-driven — a rolled server
            # re-registers on a new port with the assignment version
            # unchanged, and a version-gated swap would keep routing
            # queries to the dead port forever (found by the rolling-
            # upgrade compat verifier, round-5). Consumers take one
            # snapshot reference, so the whole-dict swap stays
            # tear-free.
            self._routing = snap

    def wait_for_version(self, version: int, timeout: float = 10.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._routing.get("version", -1) >= version:
                return True
            try:
                self._refresh_routing()
            except Exception:
                pass
            time.sleep(0.05)
        return False

    def _server_url(self, server_id: str) -> Optional[str]:
        inst = self._routing.get("instances", {}).get(server_id)
        if inst is None:
            return None
        return f"http://{inst['host']}:{inst['port']}"

    def _route(self, table: str) -> Dict[str, List[str]]:
        """segment -> replica server ids, from the cached ideal state."""
        with self._lock:
            assignment = self._routing.get("assignment", {}).get(table)
        if assignment is None:
            raise SqlError(f"table {table!r} not found in routing")
        return assignment

    def _pick_replica(self, holders: List[str]) -> Optional[str]:
        candidates = [h for h in holders if self._failures.healthy(h)
                      and self._server_url(h)]
        if not candidates:
            # all backed off: try anyway rather than failing outright
            candidates = [h for h in holders if self._server_url(h)]
        if not candidates:
            return None
        self._rr += 1
        return candidates[self._rr % len(candidates)]

    # -- query path --------------------------------------------------------
    def _snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return self._routing

    def _table_config(self, table: str,
                      snap: Optional[Dict[str, Any]] = None
                      ) -> Dict[str, Any]:
        snap = snap if snap is not None else self._snapshot()
        return (snap.get("tables", {}).get(table) or {}).get("config") or {}

    def _segment_meta(self, table: str,
                      snap: Optional[Dict[str, Any]] = None
                      ) -> Dict[str, Any]:
        snap = snap if snap is not None else self._snapshot()
        return {s: (e or {}).get("meta")
                for s, e in (snap.get("segments", {}).get(table)
                             or {}).items()}

    def _check_quota(self, table: str,
                     snap: Optional[Dict[str, Any]] = None) -> None:
        qps = self._table_config(table, snap).get("quotaQps")
        self._quota.set_quota(table, qps)
        self._quota.check(table)

    def query(self, sql: str) -> ResultTable:
        t0 = time.perf_counter()
        stmt = parse_sql(sql)
        from ..query.sql import DdlStmt
        if isinstance(stmt, DdlStmt):
            raise SqlError(
                "view DDL runs on the in-process broker (views are "
                "broker-local state; the networked broker carries no "
                "catalog yet)")
        if isinstance(stmt, SetOpStmt):
            return self._query_setop(stmt, t0)
        from ..multistage.window import has_window
        if stmt.joins or has_window(stmt):
            raise SqlError("multi-stage joins/windows over the remote data "
                           "plane arrive with the dispatch stage; use the "
                           "in-process broker for them")

        # one snapshot for the whole query: hybrid detection, quota, time
        # boundary, pruning, and scatter must agree on routing state (the
        # refresh thread swaps self._routing underneath)
        snap = self._snapshot()
        snap_tables = snap.get("tables", {})
        if stmt.table not in snap_tables and \
                f"{stmt.table}_OFFLINE" in snap_tables and \
                f"{stmt.table}_REALTIME" in snap_tables:
            return self._query_hybrid(stmt, t0, snap)

        self._check_quota(stmt.table, snap)
        ctx = build_query_context(stmt)
        if getattr(stmt, "analyze", False):
            # span scopes are per-process; the scatter-gather data plane
            # would lose the servers' trees — analyze locally instead
            raise SqlError("EXPLAIN ANALYZE is supported on the "
                           "in-process broker only (run the query "
                           "against a local Broker)")
        if stmt.explain:
            return self._explain_remote(sql, ctx.table)
        partials, queried, pruned = self._scatter(sql, ctx, snap)
        result = reduce_partials(ctx, partials)
        result.num_segments = queried
        result.num_segments_pruned = pruned
        result.time_ms = (time.perf_counter() - t0) * 1e3
        return result

    def _query_hybrid(self, stmt, t0: float,
                      snap: Dict[str, Any]) -> ResultTable:
        from ..broker.routing import (resolve_time_column, split_hybrid,
                                      time_boundary)
        logical = stmt.table
        off_table = f"{logical}_OFFLINE"
        self._check_quota(off_table, snap)  # charges EXPLAIN too
        time_col = resolve_time_column(
            self._table_config(off_table, snap),
            (snap.get("tables", {}).get(off_table) or {}).get("schema"))
        if not time_col:
            raise SqlError(
                f"hybrid table {logical!r} needs a timeColumn in its "
                f"config or a DATE_TIME schema field")
        boundary = time_boundary(
            self._segment_meta(off_table, snap), time_col)
        if boundary is None:
            raise SqlError(f"hybrid table {logical!r}: offline segments "
                           f"lack {time_col!r} metadata for the boundary")
        off, rt = split_hybrid(stmt, time_col, boundary)
        if stmt.explain:
            return self._explain_remote("EXPLAIN " + to_sql(off), off.table)
        partials: List[Any] = []
        queried = pruned = 0
        for part_stmt in (off, rt):
            ctx_p = build_query_context(part_stmt)
            p, q, pr = self._scatter(to_sql(part_stmt), ctx_p, snap)
            partials.extend(p)
            queried += q
            pruned += pr
        result = reduce_partials(build_query_context(off), partials)
        result.num_segments = queried
        result.num_segments_pruned = pruned
        result.time_ms = (time.perf_counter() - t0) * 1e3
        return result

    def _explain_remote(self, sql: str, table: str) -> ResultTable:
        # plan shape is identical across servers: ask any holder, with the
        # same failover + failure-detector recording as the data path
        assignment = self._route(table)
        for seg, holders in assignment.items():
            tried: set = set()
            while True:
                pick = self._pick_replica(
                    [h for h in holders if h not in tried])
                if pick is None:
                    break
                try:
                    resp = http_json(
                        "POST", f"{self._server_url(pick)}/query",
                        {"sql": sql})
                except Exception:
                    tried.add(pick)
                    self._failures.record_failure(pick)
                    continue
                exp = resp.get("explain", {})
                return ResultTable(exp.get("columns", []),
                                   [tuple(r) for r in exp.get("rows", [])])
        raise SqlError("no live replica to explain against")

    def _scatter(self, sql: str, ctx,
                 snap: Optional[Dict[str, Any]] = None
                 ) -> Tuple[List[Any], int, int]:
        # one snapshot for assignment + segment metadata: the refresh
        # thread swaps self._routing, and mixing two snapshots could
        # silently drop segments assigned in one but absent in the other
        if snap is None:
            snap = self._snapshot()
        assignment = snap.get("assignment", {}).get(ctx.table)
        if assignment is None:
            raise SqlError(f"table {ctx.table!r} not found in routing")
        seg_entries = snap.get("segments", {}).get(ctx.table) or {}

        # broker-side pruning over controller-held segment metadata; an
        # assigned segment with no metadata entry is never pruned
        from ..broker.routing import prune_segments
        meta = {s: (seg_entries.get(s) or {}).get("meta")
                for s in assignment}
        keep, pruned = prune_segments(
            meta, ctx.filter,
            (snap.get("tables", {}).get(ctx.table) or {}).get("config"))
        keep_set = set(keep)
        assignment = {s: h for s, h in assignment.items() if s in keep_set}

        # drop holders with no known URL up front so selector fallbacks
        # can only pick reachable servers
        assignment = {s: [h for h in holders if self._server_url(h)]
                      for s, holders in assignment.items()}

        # instance selection (pluggable: balanced / replicaGroup /
        # strictReplicaGroup / adaptive)
        def healthy(h: str) -> bool:
            return self._failures.healthy(h)

        picks = self._selector.select(assignment, healthy)
        unserved = [s for s, p in picks.items() if p is None]
        if unserved:
            raise SqlError(f"no live replica for segments {unserved[:3]}"
                           f"{'...' if len(unserved) > 3 else ''}")
        by_server: Dict[str, List[str]] = {}
        for seg, pick in picks.items():
            by_server.setdefault(pick, []).append(seg)

        adaptive = getattr(self._selector, "record_start", None)

        def call(server: str, segs: List[str], retry: bool = True):
            url = self._server_url(server)
            if adaptive:
                self._selector.record_start(server)
            tcall = time.perf_counter()
            try:
                from ..engine.datablock import decode_wire_frame
                raw = http_raw("POST", f"{url}/query/bin",
                               {"sql": sql, "segments": segs})
                header, decoded = decode_wire_frame(raw)
                self._failures.record_success(server)
                return {"partials": decoded,
                        "segmentsQueried": header.get("segmentsQueried", 0)}
            except urllib.error.HTTPError as e:
                # the server answered: an application error, not a health
                # signal — surface it, don't poison the failure detector
                self._failures.record_success(server)
                try:
                    detail = e.read().decode()[:200]
                except Exception:
                    detail = str(e)
                raise SqlError(f"server {server} rejected query: "
                               f"{detail}") from None
            except Exception:
                self._failures.record_failure(server)
                if not retry:
                    raise
                # failover: re-pick replicas per segment, one retry
                regrouped: Dict[str, List[str]] = {}
                for seg in segs:
                    holders = [h for h in assignment.get(seg, [])
                               if h != server]
                    pick = self._pick_replica(holders)
                    if pick is None:
                        raise SqlError(f"no replica left for {seg!r}")
                    regrouped.setdefault(pick, []).append(seg)
                out = {"partials": [], "segmentsQueried": 0}
                for srv, ss in regrouped.items():
                    r = call(srv, ss, retry=False)
                    out["partials"].extend(r["partials"])
                    out["segmentsQueried"] += r["segmentsQueried"]
                return out
            finally:
                if adaptive:
                    self._selector.record_end(
                        server, (time.perf_counter() - tcall) * 1e3)

        futures = [self._pool.submit(call, srv, segs)
                   for srv, segs in by_server.items()]
        partials: List[Any] = []
        queried = 0
        for f in futures:
            resp = f.result()
            partials.extend(resp["partials"])
            queried += resp["segmentsQueried"]
        return partials, queried, pruned

    def _query_setop(self, stmt: SetOpStmt, t0: float) -> ResultTable:
        """Set operations over the remote data plane: run each branch as
        its own scatter-gather (rendered back to SQL), combine at this
        broker — the same multiset merge the in-process broker uses."""
        from ..engine.reduce import DEFAULT_LIMIT
        from ..engine.setops import combine_setop, order_limit_rows

        def run(node) -> ResultTable:
            if isinstance(node, SetOpStmt):
                return combine_setop(node.op, node.all,
                                     run(node.left), run(node.right))
            if stmt.options:
                node.options = {**stmt.options, **node.options}
            if node.limit is None:
                node.limit = 1 << 31
            return self.query(to_sql(node))

        result = combine_setop(stmt.op, stmt.all,
                               run(stmt.left), run(stmt.right))
        limit = stmt.limit if stmt.limit is not None else DEFAULT_LIMIT
        result = order_limit_rows(result, stmt.order_by, limit, stmt.offset)
        result.time_ms = (time.perf_counter() - t0) * 1e3
        return result

    # -- REST --------------------------------------------------------------
    def _make_handler(self):
        node = self

        def q(h, b):
            sql = (b or {}).get("sql")
            if not sql:
                return 400, {"error": "missing sql"}
            try:
                return 200, node.query(sql).to_dict()
            except SqlError as e:
                return 400, {"error": str(e)}

        class Handler(JsonHandler):
            routes = {
                ("GET", "/health"): lambda h, b: (200, {"status": "OK"}),
                ("GET", "/ui"): lambda h, b: (
                    200, ("text/html", node.ui_page())),
                ("POST", "/query/sql"): q,
            }
        return Handler

    def ui_page(self) -> str:
        """Query console (GET /ui): the broker-side piece of the
        reference's controller web app (its Query Console tab posts to
        the broker exactly like this page). Server-rendered shell +
        vanilla JS against the existing /query/sql endpoint."""
        return """<!doctype html><html><head><title>pinot-tpu console</title>
<style>
 body{font-family:monospace;margin:2em;background:#111;color:#ddd}
 textarea{width:100%;height:6em;background:#1b1b1b;color:#ddd;
   border:1px solid #444;padding:.5em;font-family:monospace}
 button{margin:.5em 0;padding:.4em 1.2em;background:#2a6;border:0;
   color:#fff;cursor:pointer}
 table{border-collapse:collapse;margin-top:1em}
 td,th{border:1px solid #444;padding:.25em .6em;text-align:left}
 th{background:#222}
 #stats{color:#8a8;margin-top:.5em}
 #err{color:#e66;white-space:pre-wrap}
</style></head><body>
<h2>pinot-tpu query console</h2>
<textarea id=sql>SELECT * FROM mytable LIMIT 10</textarea><br>
<button onclick=run()>Run (Ctrl-Enter)</button>
<div id=stats></div><div id=err></div><div id=out></div>
<script>
const esc=s=>String(s).replace(/[&<>"']/g,
  c=>({'&':'&amp;','<':'&lt;','>':'&gt;','"':'&quot;',"'":'&#39;'}[c]));
const sqlEl=document.getElementById('sql');
sqlEl.addEventListener('keydown',e=>{
  if(e.ctrlKey&&e.key==='Enter')run();});
async function run(){
  const t0=performance.now();
  document.getElementById('err').textContent='';
  document.getElementById('out').innerHTML='';
  let j;
  try{
    const r=await fetch('/query/sql',{method:'POST',
      headers:{'Content-Type':'application/json'},
      body:JSON.stringify({sql:sqlEl.value})});
    j=await r.json();
  }catch(e){document.getElementById('err').textContent=e;return;}
  if(j.error){document.getElementById('err').textContent=j.error;return;}
  const rt=j.resultTable||j;
  const cols=(rt.dataSchema&&rt.dataSchema.columnNames)||rt.columns||[];
  const rows=rt.rows||[];
  let h='<table><tr>'+cols.map(c=>'<th>'+esc(c)+'</th>').join('')+'</tr>';
  for(const row of rows)
    h+='<tr>'+row.map(v=>'<td>'+esc(v)+'</td>').join('')+'</tr>';
  h+='</table>';
  document.getElementById('out').innerHTML=h;
  const ms=(performance.now()-t0).toFixed(1);
  const srvMs=j.timeUsedMs!==undefined?j.timeUsedMs:j.timeMs;
  document.getElementById('stats').textContent=
    rows.length+' rows | server '+(srvMs!==undefined?
    srvMs.toFixed(1):'?')+' ms | wall '+ms+' ms | docs scanned '+
    (j.numDocsScanned!==undefined?j.numDocsScanned:'?');
}
</script></body></html>"""

    def stop(self) -> None:
        self._stop.set()
        self._pool.shutdown(wait=False)
        self._httpd.shutdown()
        self._httpd.server_close()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"
