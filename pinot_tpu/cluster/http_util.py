"""Tiny stdlib HTTP plumbing shared by the cluster roles (the Netty/gRPC/
Jersey stack of the reference collapses to ThreadingHTTPServer + urllib for
the host-side control/data planes; intra-query device combines ride ICI via
parallel/distributed.py, which is where the bandwidth actually matters)."""
from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional, Tuple


class JsonHandler(BaseHTTPRequestHandler):
    """Dispatches (method, path-prefix) to registered handlers returning
    (status, json-able)."""

    routes: Dict[Tuple[str, str], Callable] = {}
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # quiet
        pass

    def _dispatch(self, method: str) -> None:
        body = None
        length = int(self.headers.get("Content-Length") or 0)
        raw = "octet-stream" in (self.headers.get("Content-Type") or "")
        if length and raw:
            # binary data plane: the handler receives the raw bytes
            body = self.rfile.read(length)
        elif length:
            try:
                body = json.loads(self.rfile.read(length))
            except ValueError as e:
                data = json.dumps(
                    {"error": f"malformed JSON body: {e}"}).encode()
                self.send_response(400)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
                return
        for (m, prefix), fn in sorted(self.routes.items(),
                                      key=lambda kv: -len(kv[0][1])):
            if m == method and self.path.split("?")[0].startswith(prefix):
                try:
                    status, payload = fn(self, body)
                except Exception as e:
                    # capacity/shed rejections (broker/workload.
                    # OverloadShedError, engine/scheduler.
                    # SchedulerRejectedError) must surface as
                    # STRUCTURED retryable JSON — HTTP 429 with
                    # errorCode + retryAfterMs — never a 500/stack
                    # trace a client can't act on
                    if getattr(e, "retry_after_ms", None) is not None \
                            and hasattr(e, "error_code"):
                        payload = (e.payload() if hasattr(e, "payload")
                                   else {"error": str(e),
                                         "errorCode": e.error_code,
                                         "retryAfterMs":
                                             e.retry_after_ms})
                        status = 429
                    else:  # surface handler errors as 500 JSON
                        status, payload = 500, {
                            "error": f"{type(e).__name__}: {e}"}
                if isinstance(payload, (bytes, bytearray)):
                    # binary data plane (DataTable-over-Netty analog)
                    data = bytes(payload)
                    ctype = "application/octet-stream"
                elif isinstance(payload, tuple) and len(payload) == 2 \
                        and isinstance(payload[0], str):
                    # (content_type, body) — e.g. the controller UI page
                    ctype, body = payload
                    data = body if isinstance(body, bytes) \
                        else str(body).encode()
                else:
                    data = json.dumps(payload).encode()
                    ctype = "application/json"
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
                return
        self.send_response(404)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_GET(self):
        self._dispatch("GET")

    def do_POST(self):
        self._dispatch("POST")

    def do_DELETE(self):
        self._dispatch("DELETE")


def start_http(handler_cls, port: int = 0) -> Tuple[ThreadingHTTPServer,
                                                    int, threading.Thread]:
    """Bind host: loopback by default (in-process clusters, tests);
    containerized deployments set PINOT_BIND_HOST=0.0.0.0 so the
    advertised service names are actually reachable across containers
    (deploy/)."""
    import os
    host = os.environ.get("PINOT_BIND_HOST", "127.0.0.1")
    srv = ThreadingHTTPServer((host, port), handler_cls)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv, srv.server_address[1], t


def inject_trace_context(body: Dict[str, Any],
                         query_id: Optional[str] = None,
                         sampled: bool = False,
                         parent_span_id: Optional[str] = None,
                         remaining_ms: Optional[float] = None
                         ) -> Dict[str, Any]:
    """Cross-node trace-context wire format: the broker stamps every
    scatter call (HTTP and gRPC) with ``traceContext`` so the server can
    root a remote span tree that stitches back under the dispatching
    call span. ``sampled`` gates the server-side tree (zero cost when
    false); ``parentSpanId`` is the dispatching scatter_call span;
    ``remainingMs`` mirrors the deadlineMs budget for span annotation
    (deadlineMs stays the accountant-authoritative field)."""
    ctx: Dict[str, Any] = {"queryId": query_id, "sampled": bool(sampled)}
    if parent_span_id is not None:
        ctx["parentSpanId"] = parent_span_id
    if remaining_ms is not None:
        ctx["remainingMs"] = int(remaining_ms)
    body["traceContext"] = ctx
    return body


def http_raw(method: str, url: str, body: Any = None,
             timeout: float = 10.0,
             headers: Optional[Dict[str, str]] = None) -> bytes:
    """Raw-bytes response; body may be JSON-able or raw bytes (the latter
    POSTs as octet-stream — the binary data plane both ways). ``headers``
    adds/overrides request headers (the trace-context side channel for
    binary-body planes, where the payload is opaque proto bytes)."""
    from ..utils.faults import rpc_faults
    rpc_faults(f"{method} {url}")
    if isinstance(body, (bytes, bytearray)):
        data = bytes(body)
        ctype = "application/octet-stream"
    else:
        data = json.dumps(body).encode() if body is not None else None
        ctype = "application/json"
    hdrs = {"Content-Type": ctype}
    if headers:
        hdrs.update(headers)
    req = urllib.request.Request(url, data=data, method=method,
                                 headers=hdrs)
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.read()


def http_json(method: str, url: str, body: Any = None,
              timeout: float = 10.0,
              headers: Optional[Dict[str, str]] = None) -> Any:
    payload = http_raw(method, url, body, timeout, headers)
    return json.loads(payload) if payload else None


# binary-body planes (POST /stage ships StagePlan proto bytes) cannot
# carry traceContext in the payload; it rides this header instead
TRACE_HEADER = "X-Pinot-Trace-Context"


def trace_context_header(ctx: Optional[Dict[str, Any]]
                         ) -> Optional[Dict[str, str]]:
    """traceContext dict -> request-headers dict (None when no ctx)."""
    if not ctx:
        return None
    return {TRACE_HEADER: json.dumps(ctx)}


def trace_context_from(headers: Any) -> Optional[Dict[str, Any]]:
    """Parse the trace-context header off an incoming request; a missing
    or malformed header is simply an unsampled request — tracing must
    never fail the data path."""
    raw = headers.get(TRACE_HEADER) if headers is not None else None
    if not raw:
        return None
    try:
        ctx = json.loads(raw)
    except ValueError:
        return None
    return ctx if isinstance(ctx, dict) else None
