"""gRPC data plane: streaming query responses + mailbox delivery.

Reference parity: pinot-core/.../transport/grpc/GrpcQueryServer.java:165
(server.proto:25 `rpc Submit(...) returns (stream ...)` — results stream
back block by block instead of one buffered DataTable) and the gRPC
mailbox of mailbox.proto:25. The wire contract IS protos/server.proto:
every message on the wire is a protobuf-encoded Frame (vendored protoc
gencode, protos/server_pb2.py) whose payload carries the framework's
binary frames (engine/datablock.py) — round-4 VERDICT item 9: the proto
went from documentation to the validated serializer, with
tests/test_grpc_contract.py asserting gencode/runtime/wire agreement.
HTTP (/query/bin, /mailbox) remains the default data plane; gRPC adds
streaming delivery (partials arrive as they are produced, the
reference's StreamingResponseUtils behavior) and a persistent-channel
alternative for mailbox fan-out.
"""
from __future__ import annotations

import json
from concurrent import futures
from typing import Any, Dict, Iterator, List, Optional, Tuple

import grpc

from ..protos import server_pb2

SERVICE = "pinot.tpu.Server"
_META = b"META"


def _wrap(payload: bytes) -> bytes:
    """bytes -> wire form of a pinot.tpu.Frame (the proto contract)."""
    return server_pb2.Frame(payload=payload).SerializeToString()


def _unwrap(wire: bytes) -> bytes:
    return server_pb2.Frame.FromString(wire).payload


class _Handlers(grpc.GenericRpcHandler):
    def __init__(self, node):
        self.node = node

    def service(self, handler_call_details):
        method = handler_call_details.method
        if method == f"/{SERVICE}/Submit":
            return grpc.unary_stream_rpc_method_handler(
                self._submit, request_deserializer=_unwrap,
                response_serializer=_wrap)
        if method == f"/{SERVICE}/Mailbox":
            return grpc.stream_unary_rpc_method_handler(
                self._mailbox, request_deserializer=_unwrap,
                response_serializer=_wrap)
        return None

    def _submit(self, request: bytes, context) -> Iterator[bytes]:
        """One partial block per chunk AS EACH SEGMENT FINISHES, then a
        META trailer — the streaming selection/response path the buffered
        HTTP plane lacks."""
        from ..engine.datablock import encode_partial
        req = json.loads(request)
        resp = self.node.execute(req["sql"], req.get("segments"),
                                 deadline_ms=req.get("deadlineMs"),
                                 trace_ctx=req.get("traceContext"))
        partials = resp.pop("partials_raw", [])
        for p in partials:
            yield encode_partial(p)
        yield _META + json.dumps(resp).encode()

    def _mailbox(self, request_iterator, context) -> bytes:
        from ..multistage.dispatch import deliver_mailbox_frame
        n = 0
        for frame in request_iterator:
            deliver_mailbox_frame(self.node.mailboxes, frame)
            n += 1
        return json.dumps({"delivered": n}).encode()


def start_grpc(node, port: int = 0) -> Tuple[grpc.Server, int]:
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
    server.add_generic_rpc_handlers((_Handlers(node),))
    bound = server.add_insecure_port(f"127.0.0.1:{port}")
    server.start()
    return server, bound


# ---------------------------------------------------------------------------
# client side
# ---------------------------------------------------------------------------

def submit_stream(target: str, sql: str,
                  segments: Optional[List[str]] = None,
                  timeout: float = 60.0,
                  deadline_ms: Optional[float] = None,
                  trace_ctx: Optional[Dict[str, Any]] = None):
    """-> (header dict, [decoded partials]); partials decode as chunks
    arrive (GrpcBrokerRequestHandler analog). A sampled ``trace_ctx``
    (http_util.inject_trace_context shape) makes the server root a span
    tree; it arrives on the META trailer header as ``trace``."""
    from ..engine.datablock import decode_partial
    from ..utils.faults import rpc_faults
    rpc_faults(f"GRPC {target}/Submit")
    partials: List[Any] = []
    header: Dict[str, Any] = {}
    with grpc.insecure_channel(target) as channel:
        call = channel.unary_stream(
            f"/{SERVICE}/Submit", request_serializer=_wrap,
            response_deserializer=_unwrap)
        req = json.dumps({"sql": sql, "segments": segments,
                          "deadlineMs": deadline_ms,
                          "traceContext": trace_ctx}).encode()
        for chunk in call(req, timeout=timeout):
            if chunk[:4] == _META:
                header = json.loads(chunk[4:])
            else:
                partials.append(decode_partial(chunk))
    return header, partials


def mailbox_send(target: str, frames: List[bytes],
                 timeout: float = 60.0) -> int:
    from ..utils.faults import rpc_faults
    rpc_faults(f"GRPC {target}/Mailbox")
    with grpc.insecure_channel(target) as channel:
        call = channel.stream_unary(
            f"/{SERVICE}/Mailbox", request_serializer=_wrap,
            response_deserializer=_unwrap)
        ack = call(iter(frames), timeout=timeout)
    return json.loads(ack)["delivered"]
