"""Deep-store segment packaging: tar.gz up/down through PinotFS.

Reference parity: segment tar.gz packaging (TarGzCompressionUtils) +
deep-store upload in the split-commit path (SplitSegmentCommitter /
SegmentUploader) and the server download-untar path
(SegmentOnlineOfflineStateModelFactory.java:128 onBecomeOnlineFromOffline
-> download from deep store via PinotFS -> untar -> load).
"""
from __future__ import annotations

import os
import tarfile
import tempfile

from ..spi.filesystem import fs_for_uri

SEGMENT_EXT = ".tar.gz"


def pack_segment(seg_dir: str, out_path: str = "") -> str:
    """tar.gz one segment directory; returns the archive path."""
    name = os.path.basename(seg_dir.rstrip("/"))
    if not out_path:
        out_path = os.path.join(tempfile.mkdtemp(prefix="ptpu_pack_"),
                                name + SEGMENT_EXT)
    with tarfile.open(out_path, "w:gz") as tar:
        tar.add(seg_dir, arcname=name)
    return out_path


def unpack_segment(archive: str, dest_root: str) -> str:
    """Untar into dest_root; returns the extracted segment dir."""
    os.makedirs(dest_root, exist_ok=True)
    with tarfile.open(archive, "r:gz") as tar:
        names = tar.getnames()
        top = {n.split("/", 1)[0] for n in names}
        if len(top) != 1:
            raise ValueError(f"segment archive must hold one directory, "
                             f"got {sorted(top)}")
        tar.extractall(dest_root, filter="data")
    return os.path.join(dest_root, top.pop())


def upload_segment(seg_dir: str, deepstore_uri: str) -> str:
    """Pack + copy a segment into the deep store; returns the download
    URI (metadata-push style: the caller hands this to the controller)."""
    name = os.path.basename(seg_dir.rstrip("/"))
    archive = pack_segment(seg_dir)
    dest_uri = deepstore_uri.rstrip("/") + "/" + name + SEGMENT_EXT
    fs, path = fs_for_uri(dest_uri)
    fs.copy_from_local(archive, path)
    os.remove(archive)
    return dest_uri


def download_segment(download_uri: str, dest_root: str) -> str:
    """Fetch + untar a deep-store segment; returns the local segment
    dir."""
    from ..utils import faults
    if faults.active():
        # handoff.stall: the COMMITTED-replica artifact fetch stalls
        # (delay_ms) then breaks — the adopter retries on its next poll.
        # Site key = archive basename, NOT the full URI: decision purity
        # in (seed, point, key) must survive run-scoped store roots
        # (tmp dirs would perturb the stream between identical runs)
        faults.fault_point("handoff.stall",
                           os.path.basename(download_uri.rstrip("/")))
    fs, path = fs_for_uri(download_uri)
    with tempfile.TemporaryDirectory(prefix="ptpu_dl_") as tmp:
        local = os.path.join(tmp, os.path.basename(path))
        fs.copy_to_local(path, local)
        return unpack_segment(local, dest_root)


def is_deepstore_uri(location: str) -> bool:
    return location.endswith(SEGMENT_EXT)


def pruning_metadata(seg_dir: str):
    """Broker-pruning metadata (per-column min/max/partitions + doc
    count) from a local segment dir; None when unreadable. The shape the
    controller stores per segment (ZK segment-metadata analog)."""
    import json
    try:
        with open(os.path.join(seg_dir, "metadata.json")) as fh:
            m = json.load(fh)
    except (OSError, ValueError):
        return None
    cols = {}
    for name, cm in (m.get("columns") or {}).items():
        entry = {k: cm[k] for k in ("min", "max", "partitions") if k in cm}
        if entry:
            cols[name] = entry
    out = {"columns": cols, "totalDocs": m.get("totalDocs"),
           "numPartitions": m.get("numPartitions")}
    # creationTimeMs drives age-based tier selection at the controller
    for k in ("startOffset", "endOffset", "partition", "creationTimeMs"):
        if k in m:
            out[k] = m[k]
    return out
