from .metadata import (DedupConfig, PartitionDedupMetadataManager,
                       PartitionUpsertMetadataManager,
                       UpsertConfig)  # noqa: F401
