"""Partial-upsert row merging (round-4, VERDICT r3 item 5).

Reference parity: pinot-segment-local/.../upsert/merger/
PartialUpsertMerger.java:30 + columnar/{Overwrite,Ignore,Increment,
Append,Union,Max,Min}Merger.java. Semantics reproduced:

- a NULL incoming value means "not provided" — the previous value is
  kept regardless of strategy (PartialUpsertColumnarMerger skips null
  new values);
- OVERWRITE (default): non-null new value wins;
- IGNORE: the first-seen value is immutable (new value discarded);
- INCREMENT: numeric add (previous null -> new value);
- MAX / MIN: numeric extremum;
- APPEND: multi-value list concatenation;
- UNION: multi-value set union (first-seen order preserved);
- primary-key and comparison columns always take the new row's values.

Row reads against either segment kind are targeted single-doc lookups
(fwd[doc] + dictionary gather), not whole-column decodes.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

STRATEGIES = ("overwrite", "ignore", "increment", "append", "union",
              "max", "min")


def _merge_value(strategy: str, prev: Any, new: Any) -> Any:
    if new is None:
        return prev            # partial semantics: null = not provided
    if strategy == "ignore":
        return prev if prev is not None else new
    if prev is None:
        return new
    if strategy == "overwrite":
        return new
    if strategy == "increment":
        return prev + new
    if strategy == "max":
        return max(prev, new)
    if strategy == "min":
        return min(prev, new)
    if strategy == "append":
        return list(prev) + list(new)
    if strategy == "union":
        out = list(prev)
        seen = set(out)
        for v in (list(new) if isinstance(new, (list, tuple)) else [new]):
            if v not in seen:
                seen.add(v)
                out.append(v)
        return out
    raise ValueError(f"unknown partial-upsert strategy {strategy!r}")


class PartialUpsertMerger:
    """Column-wise merge of the incoming row with the current live row."""

    def __init__(self, pk_columns: List[str],
                 comparison_column: Optional[str],
                 strategies: Dict[str, str],
                 default_strategy: str = "overwrite"):
        for col, s in strategies.items():
            if s.lower() not in STRATEGIES:
                raise ValueError(
                    f"unknown partial-upsert strategy {s!r} for {col!r}")
        if default_strategy.lower() not in STRATEGIES:
            raise ValueError(
                f"unknown default partial-upsert strategy "
                f"{default_strategy!r}")
        self._pk = set(pk_columns)
        self._cmp = comparison_column
        self._strategies = {c: s.lower() for c, s in strategies.items()}
        self._default = default_strategy.lower()

    def merge(self, prev_row: Dict[str, Any],
              new_row: Dict[str, Any]) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for col in new_row.keys() | prev_row.keys():
            newv = new_row.get(col)
            if col in self._pk or col == self._cmp:
                out[col] = newv
                continue
            out[col] = _merge_value(
                self._strategies.get(col, self._default),
                prev_row.get(col), newv)
        return out


def _py(v: Any) -> Any:
    return v.item() if isinstance(v, np.generic) else v


def read_row(segment, doc_id: int) -> Dict[str, Any]:
    """One row from either segment kind in value space (None for nulls).

    MutableSegment exposes get_row; ImmutableSegment is read through
    targeted fwd[doc] + dictionary gathers (never a whole-column
    decode — merging runs per ingested row)."""
    if hasattr(segment, "get_row"):
        return segment.get_row(doc_id)
    row: Dict[str, Any] = {}
    for name, m in segment.columns.items():
        nm = segment.null_mask(name)
        if nm is not None and nm[doc_id]:
            row[name] = None
            continue
        if getattr(m, "encoding", None) == "VECTOR":
            # vector columns have no fwd.bin — read the index matrix row
            mat = segment.index_reader(name, "vector").matrix
            row[name] = [float(x) for x in np.asarray(mat)[doc_id]]
            continue
        stored = segment.fwd(name)
        d = segment.dictionary(name)
        if not getattr(m, "single_value", True):
            ids = np.asarray(stored[doc_id])
            ids = ids[ids >= 0]
            row[name] = [_py(d.value(int(i))) for i in ids] \
                if d is not None else [_py(v) for v in ids]
            continue
        v = stored[doc_id]
        if d is not None:
            v = d.value(int(v))     # O(1), never the whole dictionary
        row[name] = _py(v)
    return row
