"""Upsert and dedup metadata managers.

Reference parity: pinot-segment-local/.../upsert/
ConcurrentMapPartitionUpsertMetadataManager.java (primary key -> latest
(segment, docId, comparisonValue); newer-or-equal comparison value wins;
the superseded location's validDocIds bit drops) and dedup/
ConcurrentMapPartitionDedupMetadataManager.java (PK seen -> row dropped at
ingestion). TPU-native difference: validDocIds are plain numpy bool masks
that fold into the kernel's filter mask as a MaskParam (masks replace
RoaringBitmap throughout this engine); restart rehydrates by replaying
committed segments' PK/comparison columns in commit order instead of
reading bitmap snapshots (which are still persisted for inspection).
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np


def _compact_crash(key: str) -> None:
    """Named ingest fault hook (``upsert.compact_crash``): simulated
    process death mid metadata replay / TTL eviction. Raises IngestCrash
    — the owning realtime manager must be abandoned and restarted; the
    restart replay rebuilds this manager from committed segments, which
    is what makes the crash recoverable."""
    from ..utils import faults
    if faults.active() and faults.fault_fires("upsert.compact_crash",
                                              key):
        raise faults.IngestCrash(
            f"injected upsert.compact_crash ({key})")


@dataclass
class UpsertConfig:
    pk_columns: List[str]
    comparison_column: Optional[str] = None  # None -> stream order wins
    # round-4: partial upsert (reference UpsertConfig.Mode.PARTIAL +
    # partialUpsertStrategies) and metadata TTL (metadataTTL, in
    # comparison-value units)
    mode: str = "full"                       # "full" | "partial"
    partial_strategies: Dict[str, str] = field(default_factory=dict)
    default_strategy: str = "overwrite"
    metadata_ttl: Optional[float] = None

    def __post_init__(self):
        if self.mode not in ("full", "partial"):
            raise ValueError(f"upsert mode must be full|partial, "
                             f"got {self.mode!r}")
        if self.metadata_ttl is not None and self.metadata_ttl <= 0:
            raise ValueError("metadata_ttl must be > 0")


@dataclass
class DedupConfig:
    pk_columns: List[str]


class PartitionUpsertMetadataManager:
    """Tracks PK -> (segment_object, doc_id, comparison_value).

    ``site_key`` carries table/partition identity into the
    upsert.compact_crash fault decisions (faults.py purity contract:
    per-key streams must not be shared across partitions, or
    same-seed fault assignment becomes thread-interleaving-dependent)."""

    def __init__(self, config: UpsertConfig, site_key: str = ""):
        self.config = config
        self.site_key = site_key
        self._map: Dict[Tuple, Tuple[Any, int, Any]] = {}
        self._lock = threading.Lock()
        self._largest_cmp: Any = None   # TTL watermark (reference:
        # BasePartitionUpsertMetadataManager._largestSeenComparisonValue)
        self._last_evict_watermark: Any = None
        self.merger = None
        if config.mode == "partial":
            from .merger import PartialUpsertMerger
            self.merger = PartialUpsertMerger(
                config.pk_columns, config.comparison_column,
                config.partial_strategies, config.default_strategy)

    def prepare_row(self, row) -> Any:
        """Partial upsert: merge the incoming row with the current live
        row for its PK BEFORE indexing (PartialUpsertColumnarMerger is
        applied on ingestion in the reference too). Full mode and
        first-seen PKs return the row unchanged."""
        if self.merger is None:
            return row
        pk = self._pk(row)
        with self._lock:
            cur = self._map.get(pk)
        if cur is None:
            return row
        from .merger import read_row
        seg, doc, _cmp = cur
        return self.merger.merge(read_row(seg, doc), dict(row))

    def _note_cmp(self, cmp_val: Any) -> None:
        if isinstance(cmp_val, (int, float)) and (
                self._largest_cmp is None or cmp_val > self._largest_cmp):
            self._largest_cmp = cmp_val

    def evict_expired(self) -> int:
        """Metadata TTL: drop tracking for PKs whose comparison value
        fell behind the watermark by more than metadata_ttl. Their rows
        stay queryable — only upsert management stops (reference
        removeExpiredPrimaryKeys semantics). Returns evicted count."""
        ttl = self.config.metadata_ttl
        if ttl is None or self._largest_cmp is None:
            return 0
        if self._largest_cmp == self._last_evict_watermark:
            return 0   # watermark unchanged: the O(keys) scan is skipped
        _compact_crash(f"evict/{self.site_key}")
        self._last_evict_watermark = self._largest_cmp
        horizon = self._largest_cmp - ttl
        with self._lock:
            stale = [pk for pk, (_s, _d, c) in self._map.items()
                     if isinstance(c, (int, float)) and c < horizon]
            for pk in stale:
                del self._map[pk]
        return len(stale)

    def _pk(self, row) -> Tuple:
        return tuple(row[c] for c in self.config.pk_columns)

    def _cmp(self, row, fallback: Any) -> Any:
        if self.config.comparison_column is None:
            return fallback
        return row[self.config.comparison_column]

    def add_row(self, segment, doc_id: int, row, order_token: Any
                ) -> bool:
        """Record a newly-indexed row. Returns True if it becomes the live
        one (invalidating any previous location), False if it loses to an
        existing newer record (its own bit should drop)."""
        pk = self._pk(row)
        cmp_val = self._cmp(row, order_token)
        self._note_cmp(cmp_val)
        with self._lock:
            cur = self._map.get(pk)
            if cur is not None:
                cur_seg, cur_doc, cur_cmp = cur
                if cmp_val >= cur_cmp:  # newer-or-equal wins (reference)
                    _invalidate(cur_seg, cur_doc)
                    self._map[pk] = (segment, doc_id, cmp_val)
                    return True
                _invalidate(segment, doc_id)
                return False
            self._map[pk] = (segment, doc_id, cmp_val)
            return True

    def replay_segment(self, segment, rows_pk: List[Tuple],
                       cmp_vals: List[Any]) -> None:
        """Restart rehydration: replay a committed segment's keys in doc
        order; builds this segment's valid mask and supersedes older ones."""
        _compact_crash(getattr(segment, "name", "replay"))
        valid = np.ones(len(rows_pk), dtype=bool)
        for c in cmp_vals:
            self._note_cmp(c)
        with self._lock:
            for doc_id, (pk, cmp_val) in enumerate(zip(rows_pk, cmp_vals)):
                cur = self._map.get(pk)
                if cur is not None:
                    cur_seg, cur_doc, cur_cmp = cur
                    if cmp_val >= cur_cmp:
                        if cur_seg is segment:
                            valid[cur_doc] = False
                        else:
                            _invalidate(cur_seg, cur_doc)
                        self._map[pk] = (segment, doc_id, cmp_val)
                    else:
                        valid[doc_id] = False
                else:
                    self._map[pk] = (segment, doc_id, cmp_val)
        # publish unconditionally AFTER the rebuild: the caller must never
        # pre-clear to None (that would expose superseded rows to queries
        # running concurrently with the replay)
        segment.set_valid_docs(valid if not valid.all() else None)

    def remap_segment(self, old, new, sealed_docs: int) -> None:
        """Seal: locations recorded against the consuming segment now live
        in the committed artifact. Docs >= sealed_docs were indexed after
        the seal snapshot and exist only in the dropped mutable — their
        entries are removed so the re-consumed copies re-register cleanly
        (repointing them would index past the artifact's mask)."""
        with self._lock:
            for pk, (seg, doc, cmp_val) in list(self._map.items()):
                if seg is old:
                    if doc < sealed_docs:
                        self._map[pk] = (new, doc, cmp_val)
                    else:
                        del self._map[pk]

    @property
    def num_keys(self) -> int:
        return len(self._map)


def _invalidate(segment, doc_id: int) -> None:
    if hasattr(segment, "invalidate_doc"):        # MutableSegment
        segment.invalidate_doc(doc_id)
        return
    # ImmutableSegment: copy-on-write mask update + version bump
    vd = segment.valid_docs
    if vd is None:
        vd = np.ones(segment.n_docs, dtype=bool)
    else:
        vd = vd.copy()
    vd[doc_id] = False
    segment.set_valid_docs(vd)


class PartitionDedupMetadataManager:
    """Exactly-once by PK: drop duplicate rows at ingestion."""

    def __init__(self, config: DedupConfig):
        self.config = config
        self._seen: set = set()
        self._lock = threading.Lock()

    def should_drop(self, row) -> bool:
        pk = tuple(row[c] for c in self.config.pk_columns)
        with self._lock:
            if pk in self._seen:
                return True
            self._seen.add(pk)
            return False

    def replay_segment(self, segment, rows_pk: List[Tuple]) -> None:
        with self._lock:
            self._seen.update(rows_pk)

    @property
    def num_keys(self) -> int:
        return len(self._seen)
