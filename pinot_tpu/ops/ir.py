"""Kernel plan IR: the hashable structure a per-segment query compiles to.

Reference parity: this is the TPU-native analog of pinot-core's physical
operator tree (FilterPlanNode.java:195 constructPhysicalOperator +
AggregationPlanNode / GroupByPlanNode). Key design difference from the
reference: literal values (dict ids, range bounds, IN sets) are NOT part of
the plan structure — they are runtime parameters fed to the jitted kernel,
so XLA compiles once per plan SHAPE and the same binary serves every query
with that shape (Pinot re-plans per query; we re-parameterize).

Columns are referenced by integer index into the kernel's `cols` tuple;
params by index into the `params` tuple. Both bindings are produced by the
planner (query/planner.py).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple


# ---------------------------------------------------------------------------
# Value expressions (projection / transform; operator/transform/ in reference)
# ---------------------------------------------------------------------------

class ValueExpr:
    pass


@dataclass(frozen=True)
class Col(ValueExpr):
    """A projected column. If dict_param is set, the stored array holds dict
    ids and params[dict_param] is the device-resident sorted dictionary
    values array: value = dict_values[ids] (one gather, mirrors Pinot's
    dictionary.get on the read path)."""
    col: int
    dict_param: Optional[int] = None


@dataclass(frozen=True)
class Lit(ValueExpr):
    param: int


@dataclass(frozen=True)
class Bin(ValueExpr):
    """Arithmetic transform: + - * / % (ArithmeticFunctions in reference)."""
    op: str
    lhs: ValueExpr
    rhs: ValueExpr


@dataclass(frozen=True)
class MvReduce(ValueExpr):
    """Per-row reduction over a multi-value column's padded (N, maxValues)
    dict-id matrix (pad id -1): mode in {sum, count, min, max}. MV
    aggregations pre-reduce per row and ride the scalar/group machinery:
    SUMMV = SUM(MvReduce sum), COUNTMV = SUM(MvReduce count), MINMV =
    MIN(MvReduce min), MAXMV = MAX(MvReduce max). Reference:
    pinot-core/.../query/aggregation/function/SumMVAggregationFunction.java
    (and Count/Min/Max MV variants)."""
    col: int
    mode: str
    dict_param: Optional[int] = None


@dataclass(frozen=True)
class Func(ValueExpr):
    """Device scalar transform: closed-form math (datetime extraction
    over epoch millis via civil-from-days integer arithmetic, casts,
    abs/floor/ceil/sqrt...). The device lowering of the reference's
    transform-function classes (DateTimeTransformFunction, CastTransform
    Function, ...); host peers live in query/functions.py and MUST agree
    exactly — oracle tests compare the two paths."""
    name: str
    args: Tuple["ValueExpr", ...]


@dataclass(frozen=True)
class Case(ValueExpr):
    """CASE WHEN <pred> THEN <value> ... ELSE <value> END as a where
    chain (CaseTransformFunction device lowering)."""
    whens: Tuple[Tuple["Pred", "ValueExpr"], ...]
    else_: "ValueExpr"


# ---------------------------------------------------------------------------
# Predicates (operator/filter/ + predicate evaluators in reference)
# ---------------------------------------------------------------------------

class Pred:
    pass


@dataclass(frozen=True)
class TrueP(Pred):
    pass


@dataclass(frozen=True)
class FalseP(Pred):
    pass


@dataclass(frozen=True)
class EqId(Pred):
    """stored[col] == params[param] — dict-id equality (the planner resolved
    the literal through the sorted dictionary; absent values fold to FalseP).

    negated: VALUE-level negation (!=). Distinct from wrapping in Not() for
    multi-value columns: `mv != x` matches when ANY value differs
    (reference NotEqualsPredicateEvaluator applyMV), while NOT(mv = x)
    matches when NO value equals. Identical for single-value columns."""
    col: int
    param: int
    negated: bool = False


@dataclass(frozen=True)
class IdRange(Pred):
    """lo <= stored[col] <= hi over dict ids or raw sorted-comparable values.
    Bounds are params (inclusive). The planner turns >,>=,<,<=,BETWEEN on
    dict columns into inclusive id ranges via Dictionary.id_range —
    the sorted-dictionary trick that replaces Pinot's RangeIndexBasedFilterOperator.
    negated: value-level NOT BETWEEN (see EqId.negated)."""
    col: int
    lo_param: Optional[int]
    hi_param: Optional[int]
    negated: bool = False


@dataclass(frozen=True)
class InSet(Pred):
    """stored[col] IN params[param] (padded to static length n with a
    sentinel that matches nothing). InPredicateEvaluator analog.
    negated: value-level NOT IN (see EqId.negated)."""
    col: int
    param: int
    n: int
    negated: bool = False


@dataclass(frozen=True)
class InBitmap(Pred):
    """stored[col] IN <set>, where params[param] is a (cardinality,) bool
    presence table over dict ids — one gather per value instead of the
    O(rows x set) broadcast compare InSet pays. The planner picks this for
    dict columns once the resolved id set exceeds INSET_BITMAP_MIN
    (reference: DictionaryBasedInPredicateEvaluator, which likewise
    precomputes the matching-id set once)."""
    col: int
    param: int
    negated: bool = False


@dataclass(frozen=True)
class Cmp(Pred):
    """Generic comparison on a value expression (raw-column / expression
    filters — ScanBasedFilterOperator + ExpressionFilterOperator analog).
    op in {'==','!=','<','<=','>','>='}; rhs is params[param]."""
    lhs: ValueExpr
    op: str
    param: int


@dataclass(frozen=True)
class MaskParam(Pred):
    """A precomputed per-doc bool mask passed as a kernel param. Serves
    null checks (NullPredicateEvaluator analog: params hold the unpacked
    null bitmap) and upsert validDocIds (queryableDocIds in the reference's
    upsert path — pinot-segment-local/.../upsert/)."""
    param: int


IsNull = MaskParam  # historical alias


@dataclass(frozen=True)
class And(Pred):
    children: Tuple[Pred, ...]


@dataclass(frozen=True)
class Or(Pred):
    children: Tuple[Pred, ...]


@dataclass(frozen=True)
class Not(Pred):
    child: Pred


# ---------------------------------------------------------------------------
# Aggregations (query/aggregation/function/ — 91 classes in reference; the
# core numeric family here, sketches later)
# ---------------------------------------------------------------------------

AGG_KINDS = ("count", "sum", "min", "max", "avg", "distinct_count")


@dataclass(frozen=True)
class AggSpec:
    kind: str                      # one of AGG_KINDS
    value: Optional[ValueExpr]     # None for COUNT(*)
    integral: bool = False         # exact int64 accumulation when True
    # distinct_count over a dict column: cardinality for the presence bitmap
    card: Optional[int] = None
    # magnitude bound (bits) of the integral value expression; sizes the
    # int8-limb decomposition of the MXU group-sum (kernels._limb_rows).
    # The planner tightens it via interval arithmetic over column min/max.
    bits: int = 63
    # False when the planner proved the value non-negative (halves the limbs)
    signed: bool = True
    # enableNullHandling: params[null_param] is the input column's null
    # mask — the aggregation skips those rows and reports the non-null
    # count so SUM/MIN/MAX over all-null inputs finalize to null
    # (NullableSingleInputAggregationFunction semantics)
    null_param: Optional[int] = None


# ---------------------------------------------------------------------------
# The kernel plan
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class SelectPlan:
    """Device selection/order-by: filter mask -> composite int64 order key
    -> jax.lax.top_k -> gather the selected columns at the winners.

    Reference parity: operator/query/LinearSelectionOrderByOperator.java
    (per-segment top offset+limit rows under the order, merged at broker
    reduce). order entries are (col, desc, card): dict columns compose by
    id (sorted dictionaries make id order == value order), card=0 marks a
    raw integral column; the planner guarantees the composite fits 63
    bits. k = offset + limit. Empty order = doc order (selection-only
    early-exit analog)."""
    pred: Pred
    select_cols: Tuple[int, ...]
    order: Tuple[Tuple[int, bool, int], ...]
    k: int


# ---------------------------------------------------------------------------
# Cross-stage fused IR (whole-plan mesh compilation, round 16)
#
# A multi-stage join pipeline compiles into ONE shard_map program when
# every stage worker shares a mesh: each stage boundary that the mailbox
# plane would serve with a host exchange becomes an explicit Exchange
# node, lowered to a collective inside the fused program ('hash' ->
# lax.all_to_all bucket exchange, 'broadcast' -> replication of the
# build side, the all_gather degenerate). The nodes carry exactly the
# static facts the verifier (analysis/plan_verify.py PV2xx) and the
# compile plane (utils/compileplane.staged token) need: partition spec,
# key slots, dtypes, and the per-shard shapes that must stay stable
# across collective boundaries. Like KernelPlan, everything here is
# frozen/hashable — one XLA binary per fused plan SHAPE, runtime arrays
# re-parameterize it.
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Exchange:
    """One stage boundary inside a fused plan. ``partitions`` is the
    mesh size the collective runs over (1 = single-device mesh, still a
    shard_map program); ``key_slots`` are (table_ordinal, slot) pairs
    naming which already-joined table each probe-key slot gathers from;
    ``cap`` is the pow2 per-device bucket capacity of a hash exchange
    (0 for broadcast — replication has no bucket)."""
    kind: str                           # 'hash' | 'broadcast'
    partitions: int
    key_slots: Tuple[int, ...]          # probe-side owner table ordinals
    key_dtype: str = "int32"
    cap: int = 0


@dataclass(frozen=True)
class FusedJoin:
    """One join stage of the fused program: the exchange that feeds it
    plus the dense-formulation statics (ops/join.device_equi_join).
    ``build_rows`` is the padded build-side length (static shape);
    ``max_dup`` the pow2 build-key multiplicity bound."""
    exchange: Exchange
    how: str                            # 'inner' | 'left'
    max_dup: int
    build_rows: int


@dataclass(frozen=True)
class FusedPlan:
    """The whole-plan IR: N join stages over ``n_tables`` relations,
    probe seed of ``base_rows`` (padded) rows sharded over
    ``partitions`` devices. ``pos_bound`` = base_rows * prod(max_dup)
    is the canonical-position domain — it must fit the accumulator
    dtype (``acc_dtype``) or the host cannot restore hash_join's
    canonical row order after the program returns."""
    stages: Tuple[FusedJoin, ...]
    n_tables: int
    base_rows: int
    partitions: int
    pos_bound: int
    acc_dtype: str = "int32"


@dataclass(frozen=True)
class KernelPlan:
    """Everything the kernel builder needs, hashable. group_keys is a tuple
    of (col_index, cardinality): group-by keys must be dict-encoded stored
    columns; the dense group key is cartesian dict-id arithmetic exactly
    like DictionaryBasedGroupKeyGenerator.java:63.

    strategy selects the group-by execution shape (ops/kernels.py):
    - 'dense':   one-hot dot_general over all rows — small group spaces;
    - 'compact': Pallas masked-row compaction (ops/compact.py), then
      factorized one-hot matmuls (small spaces) or sort + boundary diffs
      (large spaces) over the compacted rows only. The TPU answer to
      DocIdSetOperator + DefaultGroupByExecutor at SSB selectivities.
    """
    pred: Pred
    aggs: Tuple[AggSpec, ...]
    group_keys: Tuple[Tuple[int, int], ...] = ()
    strategy: str = "dense"
    # expression group keys (GROUP BY YEAR(ts), ...): parallel to
    # group_keys; entry k, when not None, is a ValueExpr already shifted
    # into [0, card_k) — evaluated instead of cols[col_idx]. Expression
    # keys force the dense strategy (compaction gathers key columns by
    # index). () means all-column keys.
    key_exprs: Tuple[Optional["ValueExpr"], ...] = ()

    @property
    def group_space(self) -> int:
        s = 1
        for _, card in self.group_keys:
            s *= max(card, 1)
        return s

    @property
    def is_group_by(self) -> bool:
        return len(self.group_keys) > 0
