"""Aggregation function registry: name resolution, mergeable states.

Reference parity: pinot-core/.../query/aggregation/function/ (91 classes,
AggregationFunctionFactory) — SUM/MIN/MAX/COUNT/AVG plus the long tail:
variance family (VarianceAggregationFunction), skew/kurtosis, COVAR,
MODE, MINMAXRANGE, PERCENTILE{,EST,TDIGEST,KLL} (+digit-suffixed forms),
DISTINCTCOUNT{,HLL,BITMAP}, SUMPRECISION, BOOL_AND/OR, FIRST/LASTWITHTIME.

TPU-native design: every aggregation is (vectorized per-segment state
extraction) + (commutative merge) + (finalize at broker reduce). States are
JSON-encodable (serde tags sets/tuples/dicts), and moment-family states are
*raw power sums* so merge is elementwise addition — the same contract the
device kernels use, which keeps partials interchangeable across the kernel,
host, and rollup execution paths.

The classic six (count/sum/min/max/avg/distinct_count) keep their original
state formats (ints, scalars, (sum,count), sets) because the XLA kernel
extract path (engine/executor.py) and star-tree rollups emit those directly.
"""
from __future__ import annotations

import math
import re
from decimal import Decimal
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np


def _sql_mod():
    # lazy: query.context imports this module for the name registry, and
    # query/__init__ imports context — a module-level import of query.sql
    # here would close that cycle during package init
    from ..query import sql
    return sql

# name (lowercased) -> kind; percentile forms handled by _PERC_RE
AGG_NAME_TO_KIND: Dict[str, str] = {
    "count": "count",
    "sum": "sum",
    "min": "min",
    "max": "max",
    "avg": "avg",
    "distinctcount": "distinct_count",
    "count_distinct": "distinct_count",
    "distinctcountbitmap": "distinct_count",
    "segmentpartitioneddistinctcount": "distinct_count",
    "distinctcounthll": "distinct_count_hll",
    "distinctcounthllplus": "distinct_count_hll",
    "variance": "var_samp",
    "var_samp": "var_samp",
    "varsamp": "var_samp",
    "var_pop": "var_pop",
    "varpop": "var_pop",
    "stddev": "stddev_samp",
    "stddev_samp": "stddev_samp",
    "stddevsamp": "stddev_samp",
    "stddev_pop": "stddev_pop",
    "stddevpop": "stddev_pop",
    "skewness": "skewness",
    "kurtosis": "kurtosis",
    "covar_pop": "covar_pop",
    "covar_samp": "covar_samp",
    "mode": "mode",
    "minmaxrange": "minmaxrange",
    "sumprecision": "sum_precision",
    "bool_and": "bool_and",
    "booland": "bool_and",
    "bool_or": "bool_or",
    "boolor": "bool_or",
    "firstwithtime": "first_with_time",
    "lastwithtime": "last_with_time",
    # sketch families (round-4; ops/sketches.py — reference:
    # DistinctCountThetaSketch/CPCSketch/ULL + Raw* variants)
    "distinctcountthetasketch": "distinct_count_theta",
    "distinctcountrawthetasketch": "raw_theta",
    "distinctcountcpcsketch": "distinct_count_cpc",
    "distinctcountrawcpcsketch": "raw_cpc",
    "distinctcountull": "distinct_count_ull",
    "distinctcountrawull": "raw_ull",
    "distinctcountrawhll": "raw_hll",
    "distinctcountrawhllplus": "raw_hll",
    "distinctcountsmarthll": "distinct_count_hll",
    "fasthll": "distinct_count_hll",
    # MV variants of registry kinds (MvWrapAgg; reference:
    # DistinctCountHLLMV / DistinctSumMV / MinMaxRangeMV / ...)
    "distinctcounthllmv": "distinct_count_hll_mv",
    "distinctcounthllplusmv": "distinct_count_hll_mv",
    "distinctcountrawhllmv": "raw_hll_mv",
    "distinctcountrawhllplusmv": "raw_hll_mv",
    "distinctcountbitmapmv": "distinct_count_mv",
    "distinctsummv": "distinct_sum_mv",
    "distinctavgmv": "distinct_avg_mv",
    "minmaxrangemv": "minmaxrange_mv",
    "distinctcountintegertuplesketch": "distinct_count_theta",
    "sumvaluesintegertuplesketch": "tuple_sketch_sum",
    "avgvalueintegertuplesketch": "tuple_sketch_avg",
    "exprmin": "expr_min",
    "exprmax": "expr_max",
    "stunion": "st_union",
    "st_union": "st_union",
    "fourthmoment": "fourthmoment",
    # funnel family (reference: funnel/ + funnel/window/)
    "funnelcount": "funnel_count",
    "funnelmaxstep": "funnel_max_step",
    "funnelmatchstep": "funnel_match_step",
    "funnelcompletecount": "funnel_complete_count",
    # distinct-input scalars + collections + misc sketches
    "distinctsum": "distinct_sum",
    "distinctavg": "distinct_avg",
    "arrayagg": "array_agg",
    "array_agg": "array_agg",
    "listagg": "listagg",
    "histogram": "histogram",
    "frequentlongssketch": "frequent_items",
    "frequentstringssketch": "frequent_items",
    "idset": "idset",
    "percentilesmarttdigest": "percentile_sketch",
    # multi-value variants (reference: SumMVAggregationFunction.java etc.)
    "summv": "sum_mv",
    "countmv": "count_mv",
    "minmv": "min_mv",
    "maxmv": "max_mv",
    "avgmv": "avg_mv",
    "distinctcountmv": "distinct_count_mv",
}

# MV aggregation states are value-space identical to a base kind's:
# COUNTMV merges by addition (a sum of per-row value counts), so its
# wire/merge base is "sum". Device lowering uses the same mapping
# (query/planner.resolve_agg builds AggSpec(base, MvReduce(...))).
MV_BASE_KIND: Dict[str, str] = {
    "sum_mv": "sum", "count_mv": "sum", "min_mv": "min", "max_mv": "max",
    "avg_mv": "avg", "distinct_count_mv": "distinct_count",
}


def base_kind(kind: str) -> str:
    return MV_BASE_KIND.get(kind, kind)

_PERC_RE = re.compile(
    r"^(percentile(?:raw)?(?:est|tdigest|kll)?)(\d{1,2}|100)?(mv)?$")

_SKETCH_KINDS = {"percentileest": "percentile_sketch",
                 "percentiletdigest": "percentile_sketch",
                 "percentilekll": "percentile_sketch",
                 "percentilerawest": "percentile_raw_sketch",
                 "percentilerawtdigest": "percentile_raw_sketch",
                 "percentilerawkll": "percentile_raw_sketch",
                 "percentile": "percentile"}


def is_agg_name(name: str) -> bool:
    if name in AGG_NAME_TO_KIND:
        return True
    m = _PERC_RE.match(name)
    return m is not None and m.group(1) in _SKETCH_KINDS


def resolve_call(name: str, args: Tuple[Any, ...], distinct: bool
                 ) -> Optional[Tuple[str, Any, Any, Tuple[Any, ...]]]:
    """-> (kind, arg, arg2, params) for an aggregation call, else None.

    `arg`/`arg2` are value-expression ASTs; `params` are plain literals
    (percentile p, mode reducer, ...) baked into the AggExpr identity.
    """
    if name == "count" and distinct:
        _need(name, args, 1)
        return ("distinct_count", args[0], None, ())
    if distinct and is_agg_name(name):
        # the reference's single-stage engine likewise rejects DISTINCT
        # qualifiers outside COUNT — silently dropping it would return
        # wrong answers
        raise _sql_mod().SqlError(
            f"{name}(DISTINCT ...) is not supported; only "
            "COUNT(DISTINCT ...)")
    m = _PERC_RE.match(name)
    if m is not None and m.group(1) in _SKETCH_KINDS:
        base, suffix = m.group(1), m.group(2)
        kind = _SKETCH_KINDS[base]
        if m.group(3):          # ...MV form: flattened per-row lists
            kind += "_mv"
        if suffix is not None:
            _need(name, args, 1)
            return (kind, args[0], None, (float(suffix),))
        if len(args) != 2:
            raise _sql_mod().SqlError(f"{name} needs (column, percentile)")
        p = args[1]
        if not isinstance(p, _sql_mod().Literal) or isinstance(p.value, str):
            raise _sql_mod().SqlError(f"{name}: percentile must be a numeric literal")
        pv = float(p.value)
        if not 0.0 <= pv <= 100.0:
            raise _sql_mod().SqlError(
                f"{name}: percentile must be in [0, 100], got {pv}")
        return (kind, args[0], None, (pv,))
    kind = AGG_NAME_TO_KIND.get(name)
    if kind is None:
        return None
    if kind == "count":
        # COUNT(col) keeps its argument: with null handling disabled it
        # counts every row anyway, but enableNullHandling skips the
        # column's null rows (NullableSingleInputAggregationFunction)
        _need(name, args, 1)
        return ("count", args[0], None, ())
    if kind in ("covar_pop", "covar_samp", "expr_min", "expr_max"):
        _need(name, args, 2)
        return (kind, args[0], args[1], ())
    if kind in ("tuple_sketch_sum", "tuple_sketch_avg"):
        # (keyExpr, valueExpr[, nominalEntries])
        if len(args) == 3:
            r = args[2]
            if not isinstance(r, _sql_mod().Literal):
                raise _sql_mod().SqlError(
                    f"{name}: nominalEntries must be a literal")
            size = int(r.value)
            if not 1 <= size <= (1 << 20):
                raise _sql_mod().SqlError(
                    f"{name}: nominalEntries must be in [1, 2^20]")
            return (kind, args[0], args[1], (size,))
        _need(name, args, 2)
        return (kind, args[0], args[1], ())
    if kind in ("first_with_time", "last_with_time"):
        if len(args) not in (2, 3):  # (data, time[, 'dataType'])
            raise _sql_mod().SqlError(f"{name} needs (dataColumn, timeColumn[, type])")
        return (kind, args[0], args[1], ())
    if kind == "mode":
        if len(args) == 2:
            r = args[1]
            if not isinstance(r, _sql_mod().Literal):
                raise _sql_mod().SqlError("mode: reducer must be a literal")
            reducer = str(r.value).lower()
            if reducer not in ("min", "max", "avg"):
                raise _sql_mod().SqlError(
                    f"mode: reducer must be MIN|MAX|AVG, got {r.value!r}")
            return (kind, args[0], None, (reducer,))
        _need(name, args, 1)
        return (kind, args[0], None, ("min",))
    if kind in ("distinct_count_hll", "raw_hll", "distinct_count_hll_mv",
                "raw_hll_mv", "distinct_count_cpc", "raw_cpc",
                "distinct_count_ull", "raw_ull"):
        # every register sketch allocates 2^param registers — the [4, 20]
        # bound is a memory-safety contract, not a style check
        if len(args) == 2:
            r = args[1]
            if not isinstance(r, _sql_mod().Literal):
                raise _sql_mod().SqlError(f"{name}: log2m must be a literal")
            try:
                log2m = int(r.value)
            except (TypeError, ValueError):
                raise _sql_mod().SqlError(
                    f"{name}: log2m must be an integer, "
                    f"got {r.value!r}") from None
            if not 4 <= log2m <= 20:
                raise _sql_mod().SqlError(
                    f"{name}: log2m must be in [4, 20], got {log2m}")
            return (kind, args[0], None, (log2m,))
        _need(name, args, 1)
        if kind.startswith(("distinct_count_hll", "raw_hll")):
            return (kind, args[0], None, (HLL_DEFAULT_LOG2M,))
        return (kind, args[0], None, ())
    if kind in ("percentile", "percentile_sketch", "percentile_raw_sketch"):
        # reached by plain-name aliases outside the percentile regex
        # (PERCENTILESMARTTDIGEST): same (column, percentile) contract
        if len(args) != 2:
            raise _sql_mod().SqlError(f"{name} needs (column, percentile)")
        p = args[1]
        if not isinstance(p, _sql_mod().Literal) or isinstance(p.value, str):
            raise _sql_mod().SqlError(
                f"{name}: percentile must be a numeric literal")
        pv = float(p.value)
        if not 0.0 <= pv <= 100.0:
            raise _sql_mod().SqlError(
                f"{name}: percentile must be in [0, 100], got {pv}")
        return (kind, args[0], None, (pv,))
    if kind in ("distinct_count_theta", "raw_theta", "frequent_items"):
        # (column[, sizing literal]): nominalEntries / maxMapSize — a
        # retained-item count, bounded to keep one query from pinning
        # gigabytes of sketch state
        if len(args) == 2:
            r = args[1]
            if not isinstance(r, _sql_mod().Literal):
                raise _sql_mod().SqlError(
                    f"{name}: size parameter must be a literal")
            try:
                size = int(r.value)
            except (TypeError, ValueError):
                raise _sql_mod().SqlError(
                    f"{name}: size parameter must be an integer, "
                    f"got {r.value!r}") from None
            if not 1 <= size <= (1 << 20):
                raise _sql_mod().SqlError(
                    f"{name}: size parameter must be in [1, 2^20], "
                    f"got {size}")
            return (kind, args[0], None, (size,))
        _need(name, args, 1)
        return (kind, args[0], None, ())
    if kind == "funnel_count":
        return _resolve_funnel_count(name, args)
    if kind in ("funnel_max_step", "funnel_match_step",
                "funnel_complete_count"):
        return _resolve_funnel_window(name, kind, args)
    if kind == "array_agg":
        # ARRAYAGG(col, 'dataType'[, distinct]) — the dataType literal is
        # accepted for reference-signature parity and ignored (numpy
        # carries the dtype); third literal true -> distinct
        if len(args) not in (1, 2, 3):
            raise _sql_mod().SqlError(
                f"{name} needs (column[, 'dataType'[, distinct]])")
        distinct_p: Tuple[Any, ...] = ()
        if len(args) == 3:
            d = args[2]
            if isinstance(d, _sql_mod().Literal) and \
                    str(d.value).lower() in ("true", "1"):
                distinct_p = ("distinct",)
        return (kind, args[0], None, distinct_p)
    if kind == "listagg":
        if len(args) != 2 or not isinstance(args[1], _sql_mod().Literal):
            raise _sql_mod().SqlError(
                f"{name} needs (column, 'separator')")
        return (kind, args[0], None, (str(args[1].value),))
    if kind == "histogram":
        if len(args) != 4:
            raise _sql_mod().SqlError(
                f"{name} needs (column, lower, upper, numBins)")
        vals = []
        for a in args[1:]:
            if not isinstance(a, _sql_mod().Literal) or \
                    isinstance(a.value, str):
                raise _sql_mod().SqlError(
                    f"{name}: lower/upper/numBins must be numeric literals")
            vals.append(a.value)
        lo, hi, bins = float(vals[0]), float(vals[1]), int(vals[2])
        if not (hi > lo and bins > 0):
            raise _sql_mod().SqlError(
                f"{name}: needs upper > lower and numBins > 0")
        return (kind, args[0], None, (lo, hi, bins))
    _need(name, args, 1)
    return (kind, args[0], None, ())


def _resolve_funnel_count(name: str, args: Tuple[Any, ...]):
    """FUNNELCOUNT(STEPS(p1, ...), CORRELATEBY(col)[, SETTINGS(...)]) —
    FunnelCountAggregationFunctionFactory argument shape; the SETTINGS
    strategy literals are accepted and ignored (one set-based strategy
    serves all of them here)."""
    sql = _sql_mod()
    steps = correlate = None
    for a in args:
        if isinstance(a, sql.FuncCall) and a.name == "steps":
            steps = a.args
        elif isinstance(a, sql.FuncCall) and a.name == "correlateby":
            if len(a.args) != 1:
                raise sql.SqlError(f"{name}: CORRELATEBY takes one column")
            correlate = a.args[0]
        elif isinstance(a, sql.FuncCall) and a.name == "settings":
            continue
        else:
            raise sql.SqlError(
                f"{name} args must be STEPS(...), CORRELATEBY(col)"
                "[, SETTINGS(...)]")
    if steps is None or not steps:
        raise sql.SqlError(f"{name} needs STEPS(...) with >= 1 predicate")
    if correlate is None:
        raise sql.SqlError(f"{name} needs CORRELATEBY(column)")
    return ("funnel_count", correlate, tuple(steps), ())


def _resolve_funnel_window(name: str, kind: str, args: Tuple[Any, ...]):
    """FUNNEL{MAXSTEP,MATCHSTEP,COMPLETECOUNT}(timestampExpression,
    windowSize, numberSteps, stepExpression..., [mode...]) —
    FunnelBaseAggregationFunction argument shape."""
    sql = _sql_mod()
    if len(args) < 4:
        raise sql.SqlError(
            f"{name} needs (timestampExpr, windowSize, numSteps, "
            "stepExpr, ...)")
    for i, what in ((1, "windowSize"), (2, "numberSteps")):
        if not isinstance(args[i], sql.Literal) or \
                isinstance(args[i].value, str):
            raise sql.SqlError(f"{name}: {what} must be a numeric literal")
    window = int(args[1].value)
    n_steps = int(args[2].value)
    if window <= 0 or n_steps <= 0:
        raise sql.SqlError(f"{name}: windowSize and numberSteps must be > 0")
    if len(args) < 3 + n_steps:
        raise sql.SqlError(
            f"{name}: expected {n_steps} step expressions, "
            f"got {len(args) - 3}")
    steps = tuple(args[3:3 + n_steps])
    modes = []
    for a in args[3 + n_steps:]:
        if not isinstance(a, sql.Literal) or not isinstance(a.value, str):
            raise sql.SqlError(f"{name}: modes must be string literals")
        mode = a.value.upper()
        if mode not in ("STRICT_DEDUPLICATION", "STRICT_ORDER",
                        "STRICT_INCREASE", "KEEP_ALL"):
            raise sql.SqlError(f"{name}: unknown mode {a.value!r}")
        modes.append(mode)
    return (kind, args[0], steps, (window, n_steps, *modes))


def _need(name: str, args: Tuple[Any, ...], n: int) -> None:
    if len(args) != n:
        raise _sql_mod().SqlError(f"{name} takes {n} argument(s), got {len(args)}")


# ---------------------------------------------------------------------------
# host-side evaluation context
# ---------------------------------------------------------------------------

class HostSel:
    """Selected-docs view handed to aggregation state extractors.

    ev(ast) -> numpy array over the selected docs; ev_bool(ast) -> bool
    mask over the selected docs (funnel step predicates); inv/n_groups
    present in group-by context (inv = group index per selected doc).
    """
    __slots__ = ("ev", "n", "inv", "n_groups", "ev_bool")

    def __init__(self, ev: Callable[[Any], np.ndarray], n: int,
                 inv: Optional[np.ndarray] = None, n_groups: int = 0,
                 ev_bool: Optional[Callable[[Any], np.ndarray]] = None):
        self.ev = ev
        self.n = n
        self.inv = inv
        self.n_groups = n_groups
        self.ev_bool = ev_bool


def _per_group_apply(vals: np.ndarray, inv: np.ndarray, n_groups: int,
                     fn: Callable[[np.ndarray], Any]) -> List[Any]:
    """Sort-split pattern: apply fn to each group's values (vectorized
    partition, python loop only over groups)."""
    order = np.argsort(inv, kind="stable")
    sv = vals[order]
    si = inv[order]
    bounds = np.searchsorted(si, np.arange(n_groups + 1))
    return [fn(sv[bounds[g]:bounds[g + 1]]) for g in range(n_groups)]


def _per_group_apply_multi(arrays: List[np.ndarray], inv: np.ndarray,
                           n_groups: int,
                           fn: Callable[..., Any]) -> List[Any]:
    """_per_group_apply over parallel arrays: fn receives one slice per
    input array (funnel states need correlate values + step masks from
    the same partition)."""
    order = np.argsort(inv, kind="stable")
    si = inv[order]
    bounds = np.searchsorted(si, np.arange(n_groups + 1))
    sliced = [a[order] for a in arrays]
    return [fn(*(a[bounds[g]:bounds[g + 1]] for a in sliced))
            for g in range(n_groups)]


def _f64(v: np.ndarray) -> np.ndarray:
    return np.asarray(v, dtype=np.float64)


def _py(v: Any) -> Any:
    return v.item() if isinstance(v, np.generic) else v


# ---------------------------------------------------------------------------
# aggregation implementations
# ---------------------------------------------------------------------------

class AggImpl:
    """One aggregation bound to its AggExpr (params in self.agg.params)."""

    # impls whose math needs numeric inputs keep True: the host path
    # raises a typed SqlError (not a numpy cast error) on string input.
    # Hash/selection-based impls (HLL, FIRST/LASTWITHTIME) flip it off.
    numeric_input = True

    def __init__(self, agg: Any):
        self.agg = agg

    # subclasses: empty / state / group_states / merge / finalize
    def empty(self) -> Any:
        raise NotImplementedError

    def state(self, h: HostSel) -> Any:
        raise NotImplementedError

    def group_states(self, h: HostSel) -> List[Any]:
        raise NotImplementedError

    def merge(self, a: Any, b: Any) -> Any:
        raise NotImplementedError

    def finalize(self, s: Any) -> Any:
        raise NotImplementedError


class _CentralMoments(AggImpl):
    """Shared base: state = (n, mean, M2[, M3[, M4]]) CENTRAL moments;
    merge = Chan's pairwise combine. Raw power sums (sum(x^k)) cancel
    catastrophically when |mean| >> stddev; central moments are the
    numerically-stable mergeable form — same design as the reference's
    PinotFourthMoment (pinot-segment-local/.../customobject/)."""
    K = 2  # highest central moment tracked

    def empty(self):
        return tuple([0, 0.0] + [0.0] * (self.K - 1))

    def _moments(self, v: np.ndarray) -> tuple:
        v = _f64(v)
        n = int(v.size)
        if n == 0:
            return self.empty()
        mean = float(v.mean())
        d = v - mean
        return tuple([n, mean] + [float(np.sum(d ** i))
                                  for i in range(2, self.K + 1)])

    def state(self, h: HostSel):
        return self._moments(h.ev(self.agg.arg))

    def group_states(self, h: HostSel):
        v = _f64(h.ev(self.agg.arg))
        n = np.bincount(h.inv, minlength=h.n_groups)
        safe = np.maximum(n, 1)
        mean = np.bincount(h.inv, weights=v, minlength=h.n_groups) / safe
        d = v - mean[h.inv]
        ms = [np.bincount(h.inv, weights=d ** i, minlength=h.n_groups)
              for i in range(2, self.K + 1)]
        return [tuple([int(n[g]), float(mean[g])]
                      + [float(m[g]) for m in ms])
                for g in range(h.n_groups)]

    def merge(self, a, b):
        na, nb = a[0], b[0]
        if na == 0:
            return b
        if nb == 0:
            return a
        n = na + nb
        d = b[1] - a[1]
        out = [n, a[1] + d * nb / n,
               a[2] + b[2] + d * d * na * nb / n]
        if self.K >= 3:
            out.append(a[3] + b[3]
                       + d ** 3 * na * nb * (na - nb) / n ** 2
                       + 3.0 * d * (na * b[2] - nb * a[2]) / n)
        if self.K >= 4:
            out.append(a[4] + b[4]
                       + d ** 4 * na * nb * (na * na - na * nb + nb * nb)
                       / n ** 3
                       + 6.0 * d * d * (na * na * b[2] + nb * nb * a[2])
                       / n ** 2
                       + 4.0 * d * (na * b[3] - nb * a[3]) / n)
        return tuple(out)


class VarianceAgg(_CentralMoments):
    K = 2

    def __init__(self, agg, sample: bool, stddev: bool):
        super().__init__(agg)
        self.sample = sample
        self.stddev = stddev

    def finalize(self, s):
        n, _mean, m2 = s
        if n == 0 or (self.sample and n < 2):
            return None
        var = max(m2, 0.0) / (n - 1 if self.sample else n)
        return math.sqrt(var) if self.stddev else var


class SkewnessAgg(_CentralMoments):
    K = 3

    def finalize(self, s):
        n, _mean, m2, m3 = s
        if n < 3:
            return None
        if m2 <= 0:
            return 0.0
        sd = math.sqrt(m2 / (n - 1))  # sample sd (commons-math Skewness)
        return (n / ((n - 1) * (n - 2))) * m3 / sd ** 3


class KurtosisAgg(_CentralMoments):
    K = 4

    def __init__(self, agg: Any, raw_m4: bool = False):
        super().__init__(agg)
        self.raw_m4 = raw_m4   # FOURTHMOMENT surfaces the m4 power sum

    def finalize(self, s):
        n, _mean, m2, m3, m4 = s
        if self.raw_m4:
            return float(m4) if n else None
        if n < 4:
            return None
        if m2 <= 0:
            return 0.0
        var = m2 / (n - 1)  # commons-math Kurtosis (sample, excess)
        term = (n * (n + 1.0)) / ((n - 1.0) * (n - 2.0) * (n - 3.0))
        return term * m4 / var ** 2 - 3.0 * (n - 1.0) ** 2 \
            / ((n - 2.0) * (n - 3.0))


class CovarAgg(AggImpl):
    """state = (n, Sx, Sy, Sxy); merge = elementwise add."""

    def __init__(self, agg, sample: bool):
        super().__init__(agg)
        self.sample = sample

    def empty(self):
        return (0, 0.0, 0.0, 0.0)

    def state(self, h: HostSel):
        x = _f64(h.ev(self.agg.arg))
        y = _f64(h.ev(self.agg.arg2))
        return (int(x.size), float(x.sum()), float(y.sum()),
                float((x * y).sum()))

    def group_states(self, h: HostSel):
        x = _f64(h.ev(self.agg.arg))
        y = _f64(h.ev(self.agg.arg2))
        n = np.bincount(h.inv, minlength=h.n_groups)
        sx = np.bincount(h.inv, weights=x, minlength=h.n_groups)
        sy = np.bincount(h.inv, weights=y, minlength=h.n_groups)
        sxy = np.bincount(h.inv, weights=x * y, minlength=h.n_groups)
        return [(int(n[g]), float(sx[g]), float(sy[g]), float(sxy[g]))
                for g in range(h.n_groups)]

    def merge(self, a, b):
        return tuple(x + y for x, y in zip(a, b))

    def finalize(self, s):
        n, sx, sy, sxy = s
        if n == 0 or (self.sample and n < 2):
            return None
        c = sxy - sx * sy / n
        return c / (n - 1 if self.sample else n)


class ModeAgg(AggImpl):
    """state = {value: count}; finalize picks per reducer (min|max|avg)."""

    numeric_input = False  # _counts handles US/object dtypes directly

    def empty(self):
        return {}

    def _counts(self, v: np.ndarray) -> dict:
        if v.dtype == object or v.dtype.kind in "US":
            v = v.astype(str)
        u, c = np.unique(v, return_counts=True)
        return {_py(k): int(n) for k, n in zip(u, c)}

    def state(self, h: HostSel):
        return self._counts(h.ev(self.agg.arg))

    def group_states(self, h: HostSel):
        v = h.ev(self.agg.arg)
        return _per_group_apply(v, h.inv, h.n_groups, self._counts)

    def merge(self, a, b):
        out = dict(a)
        for k, n in b.items():
            out[k] = out.get(k, 0) + n
        return out

    def finalize(self, s):
        if not s:
            return None
        best = max(s.values())
        winners = [k for k, n in s.items() if n == best]
        reducer = self.agg.params[0] if self.agg.params else "min"
        if reducer == "max":
            return max(winners)
        if reducer == "avg":
            try:
                return sum(float(w) for w in winners) / len(winners)
            except (TypeError, ValueError):
                raise _sql_mod().SqlError(
                    "mode: 'avg' reducer requires a numeric column") \
                    from None
        return min(winners)


class MinMaxRangeAgg(AggImpl):
    """state = (min, max) or None."""

    def empty(self):
        return None

    def _mm(self, v: np.ndarray):
        if v.size == 0:
            return None
        v = _f64(v)
        return (float(v.min()), float(v.max()))

    def state(self, h: HostSel):
        return self._mm(h.ev(self.agg.arg))

    def group_states(self, h: HostSel):
        v = _f64(h.ev(self.agg.arg))
        lo = np.full(h.n_groups, np.inf)
        hi = np.full(h.n_groups, -np.inf)
        np.minimum.at(lo, h.inv, v)
        np.maximum.at(hi, h.inv, v)
        return [(float(lo[g]), float(hi[g])) for g in range(h.n_groups)]

    def merge(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        return (min(a[0], b[0]), max(a[1], b[1]))

    def finalize(self, s):
        return None if s is None else s[1] - s[0]


class PercentileAgg(AggImpl):
    """Exact percentile: state = sorted list of all values (the reference's
    PercentileAggregationFunction keeps every value too); finalize indexes
    at floor((n-1) * p / 100), identical to its sorted-array lookup."""

    def empty(self):
        return []

    def state(self, h: HostSel):
        return _f64(h.ev(self.agg.arg)).tolist()

    def group_states(self, h: HostSel):
        v = _f64(h.ev(self.agg.arg))
        return _per_group_apply(v, h.inv, h.n_groups,
                                lambda g: g.tolist())

    def merge(self, a, b):
        return a + b

    def finalize(self, s):
        if not s:
            return None
        p = self.agg.params[0]
        arr = np.sort(np.asarray(s, dtype=np.float64))
        idx = int((len(arr) - 1) * p / 100.0)
        return float(arr[idx])


TDIGEST_MAX_CENTROIDS = 128


class PercentileSketchAgg(AggImpl):
    """Mergeable quantile sketch (t-digest-style size-capped centroids):
    state = [[mean, weight], ...] sorted by mean. Plays the role of the
    reference's PERCENTILEEST (QDigest), PERCENTILETDIGEST and
    PERCENTILEKLL sketches — approximate, bounded-size, mergeable."""

    def empty(self):
        return []

    def _compress(self, cents: List[List[float]]) -> List[List[float]]:
        if len(cents) <= TDIGEST_MAX_CENTROIDS:
            return cents
        cents.sort(key=lambda c: c[0])
        total = sum(c[1] for c in cents)
        out: List[List[float]] = []
        # scale function: uniform weight cap keeps tails reasonably sharp
        cap = max(total / TDIGEST_MAX_CENTROIDS, 1.0)
        cur_m, cur_w = cents[0]
        for m, w in cents[1:]:
            if cur_w + w <= cap * 2:
                cur_m = (cur_m * cur_w + m * w) / (cur_w + w)
                cur_w += w
            else:
                out.append([cur_m, cur_w])
                cur_m, cur_w = m, w
        out.append([cur_m, cur_w])
        return out

    def _from_values(self, v: np.ndarray) -> List[List[float]]:
        if v.size == 0:
            return []
        v = np.sort(_f64(v))
        if v.size <= TDIGEST_MAX_CENTROIDS:
            return [[float(x), 1.0] for x in v]
        # bucket into equal-count chunks
        chunks = np.array_split(v, TDIGEST_MAX_CENTROIDS)
        return [[float(c.mean()), float(c.size)] for c in chunks if c.size]

    def state(self, h: HostSel):
        return self._from_values(h.ev(self.agg.arg))

    def group_states(self, h: HostSel):
        v = _f64(h.ev(self.agg.arg))
        return _per_group_apply(v, h.inv, h.n_groups, self._from_values)

    def merge(self, a, b):
        return self._compress([list(c) for c in a] + [list(c) for c in b])

    def finalize(self, s):
        if not s:
            return None
        cents = sorted(s, key=lambda c: c[0])
        p = self.agg.params[0]
        total = sum(c[1] for c in cents)
        target = p / 100.0 * total
        acc = 0.0
        for m, w in cents:
            if acc + w >= target:
                return float(m)
            acc += w
        return float(cents[-1][0])


HLL_DEFAULT_LOG2M = 12


def _splitmix64(x: np.ndarray) -> np.ndarray:
    x = x.astype(np.uint64)
    x = (x + np.uint64(0x9E3779B97F4A7C15))
    x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return x ^ (x >> np.uint64(31))


def _hash64(v: np.ndarray) -> np.ndarray:
    if v.dtype == object or v.dtype.kind in "US":
        import hashlib
        sv = v.astype(str)
        u, inv = np.unique(sv, return_inverse=True)
        hu = np.asarray(
            [int.from_bytes(hashlib.md5(x.encode()).digest()[:8], "little")
             for x in u], dtype=np.uint64)
        return hu[inv]
    if v.dtype.kind == "f":
        v = v.astype(np.float64).view(np.int64)
    return _splitmix64(np.asarray(v).astype(np.int64))


class HllAgg(AggImpl):
    """HyperLogLog: state = list[int] of 2^log2m registers; merge = max."""

    numeric_input = False  # _hash64 hashes strings (md5) like Pinot HLL

    @property
    def log2m(self) -> int:
        return int(self.agg.params[0]) if self.agg.params \
            else HLL_DEFAULT_LOG2M

    def empty(self):
        return [0] * (1 << self.log2m)

    def _regs(self, v: np.ndarray) -> List[int]:
        p = self.log2m
        m = 1 << p
        if v.size == 0:
            return [0] * m
        h = _hash64(v)
        idx = (h >> np.uint64(64 - p)).astype(np.int64)
        rest = (h << np.uint64(p)) | np.uint64(1 << (p - 1))
        # rank = leading zeros in the remaining 64-p bits + 1
        lz = np.zeros(v.size, dtype=np.int64)
        mask = np.uint64(1) << np.uint64(63)
        cur = rest.copy()
        done = np.zeros(v.size, dtype=bool)
        for _ in range(64 - p + 1):
            top = (cur & mask) != 0
            done |= top
            lz += ~done
            cur = cur << np.uint64(1)
        rank = lz + 1
        regs = np.zeros(m, dtype=np.int64)
        np.maximum.at(regs, idx, rank)
        return regs.tolist()

    def state(self, h: HostSel):
        return self._regs(h.ev(self.agg.arg))

    def group_states(self, h: HostSel):
        v = h.ev(self.agg.arg)
        return _per_group_apply(v, h.inv, h.n_groups, self._regs)

    def merge(self, a, b):
        return np.maximum(np.asarray(a), np.asarray(b)).tolist()

    def finalize(self, s):
        regs = np.asarray(s, dtype=np.float64)
        m = regs.size
        alpha = 0.7213 / (1 + 1.079 / m)
        est = alpha * m * m / np.sum(2.0 ** -regs)
        zeros = int(np.sum(regs == 0))
        if est <= 2.5 * m and zeros > 0:
            est = m * math.log(m / zeros)  # linear counting range
        return int(round(est))


class SumPrecisionAgg(AggImpl):
    """Exact big-decimal sum: state = decimal string; merge = Decimal add."""

    def empty(self):
        return "0"

    def _sum(self, v: np.ndarray) -> str:
        if v.size == 0:
            return "0"
        if np.issubdtype(v.dtype, np.integer):
            return str(int(v.astype(object).sum()))  # python-int exact
        return str(sum((Decimal(repr(float(x))) for x in v), Decimal(0)))

    def state(self, h: HostSel):
        return self._sum(h.ev(self.agg.arg))

    def group_states(self, h: HostSel):
        v = h.ev(self.agg.arg)
        return _per_group_apply(v, h.inv, h.n_groups, self._sum)

    def merge(self, a, b):
        return str(Decimal(a) + Decimal(b))

    def finalize(self, s):
        d = Decimal(s)
        return int(d) if d == d.to_integral_value() else float(d)


class BoolAgg(AggImpl):
    """BOOL_AND / BOOL_OR: state = None | bool."""

    def __init__(self, agg, is_and: bool):
        super().__init__(agg)
        self.is_and = is_and

    def empty(self):
        return None

    def _red(self, v: np.ndarray):
        if v.size == 0:
            return None
        b = v.astype(bool)
        return bool(b.all()) if self.is_and else bool(b.any())

    def state(self, h: HostSel):
        return self._red(h.ev(self.agg.arg))

    def group_states(self, h: HostSel):
        v = h.ev(self.agg.arg)
        return _per_group_apply(v, h.inv, h.n_groups, self._red)

    def merge(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        return (a and b) if self.is_and else (a or b)

    def finalize(self, s):
        return s


class WithTimeAgg(AggImpl):
    """FIRSTWITHTIME / LASTWITHTIME: state = (time, value) | None."""

    numeric_input = False  # selection-based: string values are picked,
    # never cast

    def __init__(self, agg, last: bool):
        super().__init__(agg)
        self.last = last

    def empty(self):
        return None

    def _pick(self, vals: np.ndarray, times: np.ndarray):
        if vals.size == 0:
            return None
        i = int(np.argmax(times) if self.last else np.argmin(times))
        return (_py(times[i]), _py(vals[i]))

    def state(self, h: HostSel):
        return self._pick(h.ev(self.agg.arg), h.ev(self.agg.arg2))

    def group_states(self, h: HostSel):
        vals = h.ev(self.agg.arg)
        times = h.ev(self.agg.arg2)
        order = np.argsort(h.inv, kind="stable")
        sv, st, si = vals[order], times[order], h.inv[order]
        bounds = np.searchsorted(si, np.arange(h.n_groups + 1))
        return [self._pick(sv[bounds[g]:bounds[g + 1]],
                           st[bounds[g]:bounds[g + 1]])
                for g in range(h.n_groups)]

    def merge(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        if self.last:
            return a if a[0] >= b[0] else b
        return a if a[0] <= b[0] else b

    def finalize(self, s):
        return None if s is None else s[1]


# ---------------------------------------------------------------------------
# factory + dispatch used by host_eval / reduce / executor
# ---------------------------------------------------------------------------

def make(agg: Any) -> Optional[AggImpl]:
    """AggImpl for extended kinds; None for the classic six (inlined in
    host_eval/kernels with matched state formats)."""
    return _make_for_kind(agg, agg.kind)


def _make_for_kind(agg: Any, k: str) -> Optional[AggImpl]:
    if k == "var_pop":
        return VarianceAgg(agg, sample=False, stddev=False)
    if k == "var_samp":
        return VarianceAgg(agg, sample=True, stddev=False)
    if k == "stddev_pop":
        return VarianceAgg(agg, sample=False, stddev=True)
    if k == "stddev_samp":
        return VarianceAgg(agg, sample=True, stddev=True)
    if k == "skewness":
        return SkewnessAgg(agg)
    if k == "kurtosis":
        return KurtosisAgg(agg)
    if k == "covar_pop":
        return CovarAgg(agg, sample=False)
    if k == "covar_samp":
        return CovarAgg(agg, sample=True)
    if k == "mode":
        return ModeAgg(agg)
    if k == "minmaxrange":
        return MinMaxRangeAgg(agg)
    if k == "percentile":
        return PercentileAgg(agg)
    if k == "percentile_sketch":
        return PercentileSketchAgg(agg)
    if k == "distinct_count_hll":
        return HllAgg(agg)
    if k == "sum_precision":
        return SumPrecisionAgg(agg)
    if k == "bool_and":
        return BoolAgg(agg, is_and=True)
    if k == "bool_or":
        return BoolAgg(agg, is_and=False)
    if k == "first_with_time":
        return WithTimeAgg(agg, last=False)
    if k == "last_with_time":
        return WithTimeAgg(agg, last=True)
    if k == "expr_min":
        # EXPRMIN(proj, measure) == value-at-minimal-measure: exactly
        # the FIRSTWITHTIME state machine with measure as the time axis
        # (ChildExprMinMaxAggregationFunction analog)
        return WithTimeAgg(agg, last=False)
    if k == "expr_max":
        return WithTimeAgg(agg, last=True)
    if k == "fourthmoment":
        # raw power sums up to m4 (FourthMomentAggregationFunction);
        # kurtosis shares the state machine and finalizes the ratio —
        # FOURTHMOMENT surfaces the m4 sum itself
        return KurtosisAgg(agg, raw_m4=True)
    impl = _make_sketch(agg, k)
    if impl is not None:
        return impl
    if k.endswith("_mv"):
        # MV variant of any registry kind: wrap the base impl with the
        # flattening adapter (classic six _mv kinds return None here and
        # keep their hand-coded host/device paths)
        inner = _make_for_kind(agg, k[: -len("_mv")])
        if inner is not None:
            from . import sketches as S

            return S.MvWrapAgg(agg, inner)
    return None


def _make_sketch(agg: Any, k: str):
    """Round-4 families (ops/sketches.py); separate module, one routing
    point here."""
    from . import sketches as S

    if k == "tuple_sketch_sum":
        return S.TupleSketchAgg(agg, "sum")
    if k == "tuple_sketch_avg":
        return S.TupleSketchAgg(agg, "avg")
    if k == "st_union":
        return S.StUnionAgg(agg)
    if k == "distinct_count_theta":
        return S.ThetaSketchAgg(agg)
    if k == "distinct_count_cpc":
        return S.CpcSketchAgg(agg)
    if k == "distinct_count_ull":
        return S.UllSketchAgg(agg)
    if k == "raw_hll":
        return S.RawAgg(agg, HllAgg(agg))
    if k == "raw_theta":
        return S.RawAgg(agg, S.ThetaSketchAgg(agg))
    if k == "raw_cpc":
        return S.RawAgg(agg, S.CpcSketchAgg(agg))
    if k == "raw_ull":
        return S.RawAgg(agg, S.UllSketchAgg(agg))
    if k == "percentile_raw_sketch":
        return S.RawAgg(agg, PercentileSketchAgg(agg))
    if k == "funnel_count":
        return S.FunnelCountAgg(agg)
    if k == "funnel_max_step":
        return S.FunnelMaxStepAgg(agg)
    if k == "funnel_match_step":
        return S.FunnelMatchStepAgg(agg)
    if k == "funnel_complete_count":
        return S.FunnelCompleteCountAgg(agg)
    if k == "distinct_sum":
        return S.DistinctSumAgg(agg, avg=False)
    if k == "distinct_avg":
        return S.DistinctSumAgg(agg, avg=True)
    if k == "array_agg":
        return S.ArrayAggAgg(agg)
    if k == "listagg":
        return S.ArrayAggAgg(agg, listagg=True)
    if k == "histogram":
        return S.HistogramAgg(agg)
    if k == "frequent_items":
        return S.FrequentItemsAgg(agg)
    if k == "idset":
        return S.IdSetAgg(agg)
    return None


_CLASSIC_EMPTY = {"count": 0, "sum": 0, "min": None, "max": None,
                  "avg": (0, 0), "distinct_count": set}


def _impl(agg: Any) -> AggImpl:
    """Resolve (once per AggExpr) and cache the extended-agg impl on the
    expression itself — merge/finalize run per (group x partial) in the
    reduce hot loop and must not re-dispatch every call."""
    impl = getattr(agg, "_impl_cache", None)
    if impl is None:
        impl = make(agg)
        if impl is None:
            raise _sql_mod().SqlError(
                f"unknown aggregation kind {agg.kind!r}")
        object.__setattr__(agg, "_impl_cache", impl)  # frozen dataclass
    return impl


def empty_state(agg: Any) -> Any:
    k = base_kind(agg.kind)
    if k in _CLASSIC_EMPTY:
        e = _CLASSIC_EMPTY[k]
        return e() if callable(e) else e
    return _impl(agg).empty()


def merge_states(agg: Any, a: Any, b: Any) -> Any:
    k = base_kind(agg.kind)
    if k == "count":
        return a + b
    if k == "sum":
        # None = all inputs null (enableNullHandling); null-absorbing merge
        return b if a is None else a if b is None else a + b
    if k == "min":
        return b if a is None else a if b is None else min(a, b)
    if k == "max":
        return b if a is None else a if b is None else max(a, b)
    if k == "avg":
        if a is None:
            return b
        if b is None:
            return a
        return (a[0] + b[0], a[1] + b[1])
    if k == "distinct_count":
        return a | b
    return _impl(agg).merge(a, b)


def finalize_state(agg: Any, s: Any) -> Any:
    k = base_kind(agg.kind)
    if k == "avg":
        return None if s is None or s[1] == 0 else s[0] / s[1]
    if k == "distinct_count":
        return len(s)
    if k in ("count", "sum", "min", "max"):
        return s
    return _impl(agg).finalize(s)
