"""Sketch, funnel, and collection aggregations (round-4, VERDICT r3
item 4: close the gap toward the reference's 91 aggregation classes).

Reference parity (pinot-core .../query/aggregation/function/):
- DistinctCountThetaSketchAggregationFunction.java — here a KMV
  (k-minimum-values) sketch: the same theta-sketch estimator family
  (exact below nominalEntries, (k-1)/theta beyond), mergeable by
  keep-k-smallest union. The datasketches wire format is not
  reproduced; serialization is this module's canonical form.
- DistinctCountCPCSketchAggregationFunction.java /
  DistinctCountULLAggregationFunction.java — estimate-equivalent
  HLL-register sketches keyed by lgK/p. True CPC compression is a wire
  concern; the merge/estimate contract (mergeable registers, ~1/sqrt(2^lgK)
  error) is what query semantics observe.
- DistinctCountRaw*AggregationFunction.java / PercentileRaw*.java —
  RAW forms return the serialized sketch instead of the estimate
  (base64(zlib(json(state))), versioned; the reference returns
  datasketches base64 — format documented as this framework's own).
- funnel/FunnelCountAggregationFunction.java — per-step correlated
  distinct sets, finalized by progressive intersection.
- funnel/window/Funnel{MaxStep,MatchStep,CompleteCount}.java — sliding
  window over (timestamp, step) events with
  STRICT_DEDUPLICATION/STRICT_ORDER/STRICT_INCREASE/KEEP_ALL modes,
  reproduced step-for-step from the reference algorithm.
- Distinct{Sum,Avg}AggregationFunction.java, array/ArrayAgg*.java,
  array/ListAggFunction.java, HistogramAggregationFunction.java,
  FrequentLongsSketchAggregationFunction.java (Misra-Gries summary),
  IdSetAggregationFunction.java.
"""
from __future__ import annotations

import base64
import json
import zlib
from collections import deque
from typing import Any, List

import numpy as np

from .aggregations import (AggImpl, HllAgg, HostSel, PercentileSketchAgg,
                           _f64, _hash64, _per_group_apply,
                           _per_group_apply_multi, _py)

THETA_DEFAULT_NOMINAL = 4096
CPC_DEFAULT_LGK = 12
ULL_DEFAULT_P = 12
FREQUENT_DEFAULT_MAP_SIZE = 256
_RAW_VERSION = 1
_TWO64 = float(2 ** 64)


# ---------------------------------------------------------------------------
# distinct-count sketches
# ---------------------------------------------------------------------------

class ThetaSketchAgg(AggImpl):
    """KMV theta sketch: state = sorted list of the k smallest distinct
    64-bit hashes. Exact while |state| < k; beyond, the k-th smallest
    hash IS theta and the estimate is (k-1) / (theta / 2^64)."""

    numeric_input = False

    @property
    def k(self) -> int:
        return int(self.agg.params[0]) if self.agg.params \
            else THETA_DEFAULT_NOMINAL

    def empty(self):
        return []

    def _sketch(self, v: np.ndarray) -> List[int]:
        if v.size == 0:
            return []
        h = np.unique(_hash64(v))          # sorted ascending
        return h[: self.k].tolist()

    def state(self, h: HostSel):
        return self._sketch(h.ev(self.agg.arg))

    def group_states(self, h: HostSel):
        v = h.ev(self.agg.arg)
        return _per_group_apply(v, h.inv, h.n_groups, self._sketch)

    def merge(self, a, b):
        if not a:
            return b
        if not b:
            return a
        u = np.union1d(np.asarray(a, dtype=np.uint64),
                       np.asarray(b, dtype=np.uint64))
        return u[: self.k].tolist()

    def finalize(self, s):
        n = len(s)
        if n < self.k:
            return n
        theta = float(s[-1]) / _TWO64
        return int(round((self.k - 1) / theta))


class CpcSketchAgg(HllAgg):
    """CPC analog: HLL registers at lgK (params[0], default 12).
    Estimate-equivalent to the reference's CPC for query semantics."""

    @property
    def log2m(self) -> int:
        return int(self.agg.params[0]) if self.agg.params \
            else CPC_DEFAULT_LGK


class UllSketchAgg(HllAgg):
    """ULL analog: HLL registers at precision p (params[0], default 12)."""

    @property
    def log2m(self) -> int:
        return int(self.agg.params[0]) if self.agg.params \
            else ULL_DEFAULT_P


# ---------------------------------------------------------------------------
# RAW forms — serialized sketch instead of the estimate
# ---------------------------------------------------------------------------

def serialize_sketch(kind: str, state: Any) -> str:
    """Canonical raw-sketch wire form: base64(zlib(json)). Versioned so
    a future layout change stays decodable."""
    payload = json.dumps({"v": _RAW_VERSION, "kind": kind, "state": state},
                         separators=(",", ":"), default=_py)
    return base64.b64encode(zlib.compress(payload.encode())).decode()


def deserialize_sketch(raw: str) -> Any:
    doc = json.loads(zlib.decompress(base64.b64decode(raw)).decode())
    if doc.get("v") != _RAW_VERSION:
        raise ValueError(f"unknown raw sketch version {doc.get('v')!r}")
    return doc["state"]


class RawAgg(AggImpl):
    """Wraps a sketch impl; finalize returns the serialized sketch."""

    def __init__(self, agg: Any, inner: AggImpl):
        super().__init__(agg)
        self.inner = inner
        self.numeric_input = inner.numeric_input

    def empty(self):
        return self.inner.empty()

    def state(self, h: HostSel):
        return self.inner.state(h)

    def group_states(self, h: HostSel):
        return self.inner.group_states(h)

    def merge(self, a, b):
        return self.inner.merge(a, b)

    def finalize(self, s):
        return serialize_sketch(self.agg.kind, s)


# ---------------------------------------------------------------------------
# funnel family
# ---------------------------------------------------------------------------

class FunnelCountAgg(AggImpl):
    """FUNNELCOUNT(STEPS(c1, ..), CORRELATEBY(col)): agg.arg is the
    correlation expression, agg.arg2 the tuple of step predicates.
    State = per-step sets of correlated values; finalize intersects
    progressively (SetMergeStrategy.extractFinalResult)."""

    numeric_input = False

    @property
    def n_steps(self) -> int:
        return len(self.agg.arg2)

    def empty(self):
        return [set() for _ in range(self.n_steps)]

    def _build(self, corr: np.ndarray, masks: List[np.ndarray]):
        return [set(np.unique(corr[m]).tolist()) if m.any() else set()
                for m in masks]

    def state(self, h: HostSel):
        corr = h.ev(self.agg.arg)
        masks = [np.asarray(h.ev_bool(s), dtype=bool)
                 for s in self.agg.arg2]
        return self._build(corr, masks)

    def group_states(self, h: HostSel):
        corr = h.ev(self.agg.arg)
        masks = [np.asarray(h.ev_bool(s), dtype=bool)
                 for s in self.agg.arg2]
        return _per_group_apply_multi(
            [corr] + masks, h.inv, h.n_groups,
            lambda c, *ms: self._build(c, list(ms)))

    def merge(self, a, b):
        return [sa | sb for sa, sb in zip(a, b)]

    def finalize(self, s):
        out = [len(s[0])]
        cur = s[0]
        for i in range(1, self.n_steps):
            cur = s[i] & cur
            out.append(len(cur))
        return tuple(out)


class _ModeFlags:
    def __init__(self, modes):
        ms = {str(m).upper() for m in modes}
        self.dedup = "STRICT_DEDUPLICATION" in ms
        self.order = "STRICT_ORDER" in ms
        self.increase = "STRICT_INCREASE" in ms


class FunnelWindowAgg(AggImpl):
    """Base for FUNNELMAXSTEP / FUNNELMATCHSTEP / FUNNELCOMPLETECOUNT:
    (timestampExpression, windowSize, numSteps, stepExpr..., [modes]).
    agg.arg = timestamp AST, agg.arg2 = tuple of step predicates,
    params = (window_size, n_steps, *modes). State = list of
    [timestamp, step] events sorted by (timestamp, step) — the
    reference's PriorityQueue<FunnelStepEvent> ordering."""

    numeric_input = False

    @property
    def window(self) -> int:
        return int(self.agg.params[0])

    @property
    def n_steps(self) -> int:
        return int(self.agg.params[1])

    @property
    def modes(self) -> _ModeFlags:
        return _ModeFlags(self.agg.params[2:])

    def empty(self):
        return []

    def _events(self, ts: np.ndarray, masks: List[np.ndarray]):
        # first matching step per row (the reference breaks on first j)
        step = np.full(ts.shape, -1, dtype=np.int64)
        for j in range(len(masks) - 1, -1, -1):
            step = np.where(masks[j], j, step)
        sel = step >= 0
        ev = sorted(zip(ts[sel].tolist(), step[sel].tolist()))
        return [[int(t), int(s)] for t, s in ev]

    def state(self, h: HostSel):
        ts = np.asarray(h.ev(self.agg.arg), dtype=np.int64)
        masks = [np.asarray(h.ev_bool(s), dtype=bool)
                 for s in self.agg.arg2]
        return self._events(ts, masks)

    def group_states(self, h: HostSel):
        ts = np.asarray(h.ev(self.agg.arg), dtype=np.int64)
        masks = [np.asarray(h.ev_bool(s), dtype=bool)
                 for s in self.agg.arg2]
        return _per_group_apply_multi(
            [ts] + masks, h.inv, h.n_groups,
            lambda t, *ms: self._events(t, list(ms)))

    def merge(self, a, b):
        return sorted([list(e) for e in a] + [list(e) for e in b])

    # -- the reference's sliding-window machinery --------------------------
    def _fill_window(self, events: List, pos: int,
                     window: deque) -> int:
        """FunnelBaseAggregationFunction.fillWindow: ensure the window
        starts at a step-0 event, then pull every event inside
        [start, start+windowSize). Returns the new consume position."""
        while window and window[0][1] != 0:
            window.popleft()
        if not window:
            while pos < len(events) and events[pos][1] != 0:
                pos += 1
            if pos >= len(events):
                return pos
            window.append(events[pos])
            pos += 1
        end = window[0][0] + self.window
        while pos < len(events) and events[pos][0] < end:
            window.append(events[pos])
            pos += 1
        return pos

    def _process_window(self, window: deque) -> int:
        """FunnelMaxStepAggregationFunction.processWindow."""
        modes = self.modes
        max_step = 0
        prev_ts = -1
        for t, step in window:
            if modes.dedup and step == max_step - 1:
                return max_step
            if modes.order and step != max_step:
                return max_step
            if modes.increase and prev_ts == t:
                continue
            if max_step == step:
                max_step += 1
                prev_ts = t
            if max_step == self.n_steps:
                break
        return max_step

    def _max_step(self, events: List) -> int:
        final = 0
        window: deque = deque()
        pos = 0
        while pos < len(events) or window:
            pos = self._fill_window(events, pos, window)
            if not window:
                break
            final = max(final, self._process_window(window))
            if final == self.n_steps:
                break
            if window:
                window.popleft()
        return final


class FunnelMaxStepAgg(FunnelWindowAgg):
    def finalize(self, s):
        return self._max_step(s or [])


class FunnelMatchStepAgg(FunnelWindowAgg):
    def finalize(self, s):
        reached = self._max_step(s or [])
        return tuple(1 if i < reached else 0
                     for i in range(self.n_steps))


class FunnelCompleteCountAgg(FunnelWindowAgg):
    def finalize(self, s):
        """FunnelCompleteCountAggregationFunction.extractFinalResult:
        count completed funnel rounds; strict modes RESET the round."""
        events = s or []
        modes = self.modes
        total = 0
        window: deque = deque()
        pos = 0
        while pos < len(events) or window:
            pos = self._fill_window(events, pos, window)
            if not window:
                break
            window_start = window[0][0]
            max_step = 0
            prev_ts = -1
            for t, step in window:
                if modes.dedup and step == max_step - 1:
                    max_step = 0
                if modes.order and step != max_step:
                    max_step = 0
                if modes.increase and prev_ts == t:
                    continue
                prev_ts = t
                if max_step == step:
                    max_step += 1
                if max_step == self.n_steps:
                    total += 1
                    max_step = 0
                    window_start = t
            if window:
                window.popleft()
            while window and window[0][0] < window_start:
                window.popleft()
        return total


# ---------------------------------------------------------------------------
# distinct-input scalar aggregations + collections
# ---------------------------------------------------------------------------

class DistinctSumAgg(AggImpl):
    """DISTINCTSUM / DISTINCTAVG: state = set of distinct values."""

    def __init__(self, agg: Any, avg: bool):
        super().__init__(agg)
        self.avg = avg

    def empty(self):
        return set()

    def _vals(self, v: np.ndarray) -> set:
        return set(np.unique(v).tolist())

    def state(self, h: HostSel):
        return self._vals(h.ev(self.agg.arg))

    def group_states(self, h: HostSel):
        return _per_group_apply(h.ev(self.agg.arg), h.inv, h.n_groups,
                                self._vals)

    def merge(self, a, b):
        return a | b

    def finalize(self, s):
        if not s:
            return None if self.avg else 0
        t = sum(s)
        return t / len(s) if self.avg else _py(np.asarray(t)[()])


class ArrayAggAgg(AggImpl):
    """ARRAYAGG(col[, distinct]) / LISTAGG(col, sep): collected values.
    Cross-segment ordering is merge order (the reference makes the same
    non-guarantee)."""

    numeric_input = False

    def __init__(self, agg: Any, listagg: bool = False):
        super().__init__(agg)
        self.listagg = listagg

    @property
    def distinct(self) -> bool:
        # LISTAGG's params[0] is the separator, never a distinct flag
        return bool(not self.listagg and self.agg.params
                    and self.agg.params[-1] == "distinct")

    @property
    def sep(self) -> str:
        return str(self.agg.params[0]) if self.listagg else ","

    def empty(self):
        return []

    def _collect(self, v: np.ndarray) -> List:
        if self.distinct:
            return [_py(x) for x in np.unique(v)]
        return [_py(x) for x in v]

    def state(self, h: HostSel):
        return self._collect(h.ev(self.agg.arg))

    def group_states(self, h: HostSel):
        return _per_group_apply(h.ev(self.agg.arg), h.inv, h.n_groups,
                                self._collect)

    def merge(self, a, b):
        out = a + b
        if self.distinct:
            seen = set()
            out = [x for x in out if not (x in seen or seen.add(x))]
        return out

    def finalize(self, s):
        if self.listagg:
            return self.sep.join(str(x) for x in s)
        return tuple(s)


class HistogramAgg(AggImpl):
    """HISTOGRAM(col, lower, upper, numBins): equal-width bin counts
    (values outside [lower, upper) are dropped, like the reference)."""

    def empty(self):
        return [0] * int(self.agg.params[2])

    def _counts(self, v: np.ndarray) -> List[int]:
        lo, hi, bins = (float(self.agg.params[0]),
                        float(self.agg.params[1]),
                        int(self.agg.params[2]))
        v = _f64(v)
        v = v[(v >= lo) & (v < hi)]
        if v.size == 0:
            return [0] * bins
        idx = np.floor((v - lo) / (hi - lo) * bins).astype(np.int64)
        return np.bincount(np.clip(idx, 0, bins - 1),
                           minlength=bins).tolist()

    def state(self, h: HostSel):
        return self._counts(h.ev(self.agg.arg))

    def group_states(self, h: HostSel):
        return _per_group_apply(_f64(h.ev(self.agg.arg)), h.inv,
                                h.n_groups, self._counts)

    def merge(self, a, b):
        return [x + y for x, y in zip(a, b)]

    def finalize(self, s):
        return tuple(int(x) for x in s)


class FrequentItemsAgg(AggImpl):
    """FREQUENTLONGSSKETCH / FREQUENTSTRINGSSKETCH: Misra-Gries summary
    capped at maxMapSize (params[0]). Finalize returns the summary as a
    JSON object {value: estimated_count} sorted by count descending —
    the reference returns a datasketches base64 blob; this framework
    surfaces the decoded summary directly (documented deviation)."""

    numeric_input = False

    @property
    def cap(self) -> int:
        return int(self.agg.params[0]) if self.agg.params \
            else FREQUENT_DEFAULT_MAP_SIZE

    def empty(self):
        return {}

    def _prune(self, counts: dict) -> dict:
        if len(counts) <= self.cap:
            return counts
        # Misra-Gries decrement: subtract the (cap+1)-th largest count
        vals = sorted(counts.values(), reverse=True)
        dec = vals[self.cap]
        return {k: c - dec for k, c in counts.items() if c > dec}

    def state(self, h: HostSel):
        u, c = np.unique(h.ev(self.agg.arg), return_counts=True)
        return self._prune({_py(k): int(n) for k, n in zip(u, c)})

    def group_states(self, h: HostSel):
        def one(v):
            u, c = np.unique(v, return_counts=True)
            return self._prune({_py(k): int(n) for k, n in zip(u, c)})
        return _per_group_apply(h.ev(self.agg.arg), h.inv, h.n_groups, one)

    def merge(self, a, b):
        out = dict(a)
        for k, c in b.items():
            out[k] = out.get(k, 0) + c
        return self._prune(out)

    def finalize(self, s):
        items = sorted(s.items(), key=lambda kv: (-kv[1], str(kv[0])))
        return json.dumps({str(k): int(c) for k, c in items})


class TupleSketchAgg(AggImpl):
    """Integer tuple sketch (Sum/AvgValueIntegerTupleSketch analog):
    KMV over hashed keys, each retained entry carrying the SUM of its
    key's values. State = {"t": theta_hash | None, "e": [[hash, sum]]}
    with every retained hash STRICTLY below theta (exclusive sampling
    bound; None = 1.0, the exact regime below k entries). Merge takes
    theta = min of the sides and discards entries past it — an entry
    one side dropped can never survive with a partial sum — then
    re-caps at k. 'sum' finalizes sum_retained / theta (unbiased,
    exact below k); 'avg' is sum/count over retained entries (unbiased
    without scaling)."""

    numeric_input = False   # keys hash; values validated separately

    def __init__(self, agg: Any, mode: str):
        super().__init__(agg)
        self.mode = mode

    @property
    def k(self) -> int:
        return int(self.agg.params[0]) if self.agg.params \
            else THETA_DEFAULT_NOMINAL

    def empty(self):
        return {"t": None, "e": []}

    def _cap(self, entries, theta):
        """Keep the k smallest-hash entries; theta tightens to the
        (k+1)-th smallest so retained hashes stay strictly below it."""
        entries.sort(key=lambda e: e[0])
        if len(entries) > self.k:
            theta_h = entries[self.k][0]
            if theta is None or theta_h < theta:
                theta = theta_h
            entries = [e for e in entries if e[0] < theta][: self.k]
        return {"t": theta, "e": entries}

    def _from_pair(self, keys, vals):
        if len(keys) == 0:
            return {"t": None, "e": []}
        hs = _hash64(np.asarray(keys))
        uniq, inv = np.unique(hs, return_inverse=True)
        sums = np.bincount(inv, weights=np.asarray(vals, np.float64),
                           minlength=len(uniq))
        return self._cap([[int(u), float(s)]
                          for u, s in zip(uniq, sums)], None)

    def _numeric_values(self, h: HostSel) -> np.ndarray:
        """The value argument must be numeric (the key may be anything);
        numeric_input=False skips _typed_ev for the key, so enforce the
        value contract here with a typed SqlError instead of letting
        np.asarray raise a raw numpy ValueError on string columns."""
        vals = np.asarray(h.ev(self.agg.arg2))
        if vals.dtype.kind in "USO" and vals.size:
            from ..query.sql import SqlError
            raise SqlError(
                f"{self.agg.kind.upper()} requires a numeric value "
                f"expression; {self.agg.arg2!r} is a string expression")
        return vals.astype(np.float64)

    def state(self, h: HostSel):
        return self._from_pair(h.ev(self.agg.arg), self._numeric_values(h))

    def group_states(self, h: HostSel):
        keys = h.ev(self.agg.arg)
        vals = self._numeric_values(h)
        return _per_group_apply_multi([keys, vals], h.inv, h.n_groups,
                                      self._from_pair)

    def merge(self, a, b):
        thetas = [t for t in (a.get("t"), b.get("t")) if t is not None]
        theta = min(thetas) if thetas else None
        acc: dict = {}
        for h_, s in list(a["e"]) + list(b["e"]):
            if theta is not None and h_ >= theta:
                continue   # past the tighter side's sampling bound
            acc[h_] = acc.get(h_, 0.0) + s
        return self._cap([[h_, v] for h_, v in acc.items()], theta)

    def finalize(self, s):
        entries = s["e"]
        if not entries:
            return None if self.mode == "avg" else 0.0
        total = sum(v for _h, v in entries)
        if self.mode == "avg":
            return total / len(entries)
        frac = 1.0 if s["t"] is None else float(s["t"]) / _TWO64
        return total / frac


class StUnionAgg(AggImpl):
    """ST_UNION over POINT geometries: the distinct-point union as a
    MULTIPOINT (StUnionAggregationFunction's behavior for point data —
    the overwhelmingly common case; polygon union raises a clear
    not-supported error rather than a wrong answer)."""

    numeric_input = False

    def empty(self):
        return set()

    def _pts(self, v: np.ndarray) -> set:
        from ..geo.geometry import parse_wkb, parse_wkt
        out = set()
        for g in v:
            geom = parse_wkb(g) if isinstance(g, (bytes, bytearray)) \
                else parse_wkt(str(g))
            if geom.kind != "point":
                raise ValueError(
                    "ST_UNION supports POINT geometries only")
            out.add((geom.lng, geom.lat))
        return out

    def state(self, h: HostSel):
        return self._pts(h.ev(self.agg.arg))

    def group_states(self, h: HostSel):
        return _per_group_apply(h.ev(self.agg.arg), h.inv, h.n_groups,
                                self._pts)

    def merge(self, a, b):
        return a | b

    def finalize(self, s):
        from ..geo.geometry import _fmt
        if not s:
            return "MULTIPOINT EMPTY"
        pts = ", ".join(f"{_fmt(x)} {_fmt(y)}" for x, y in sorted(s))
        return f"MULTIPOINT ({pts})"


class MvWrapAgg(AggImpl):
    """MV variant of any single-input registry impl: per-row value
    lists flatten into one value stream (each value counts once, the
    reference's *MVAggregationFunction contract — e.g.
    DistinctCountHLLMVAggregationFunction, PercentileEstMV...). Group
    context repeats the row's group index per value."""

    def __init__(self, agg: Any, inner: AggImpl):
        super().__init__(agg)
        self.inner = inner
        self.numeric_input = False   # rows are object arrays of lists

    def _flatten(self, rows) -> np.ndarray:
        if len(rows) and not isinstance(rows[0], (list, tuple,
                                                  np.ndarray)):
            # a single-value column here would silently iterate
            # characters (strings) or crash (numerics)
            from ..query.sql import SqlError
            raise SqlError(
                f"{self.agg.kind.upper()} requires a multi-value "
                f"column; {self.agg.arg!r} is single-value")
        flat = [v for r in rows for v in r]
        if not flat:
            return np.array([], dtype=np.float64)
        if any(isinstance(v, str) for v in flat):
            arr = np.asarray(flat, dtype=object)
        else:
            arr = np.asarray(flat)
        if self.inner.numeric_input and arr.dtype.kind in "USO" \
                and arr.size:
            # re-apply the inner impl's input contract on the flattened
            # stream (the outer object-array eval bypassed _typed_ev)
            from ..query.sql import SqlError
            raise SqlError(
                f"{self.agg.kind.upper()} requires numeric input; "
                f"{self.agg.arg!r} is a string expression")
        return arr

    def empty(self):
        return self.inner.empty()

    def state(self, h: HostSel):
        flat = self._flatten(h.ev(self.agg.arg))
        h2 = HostSel(lambda _ast: flat, len(flat))
        return self.inner.state(h2)

    def group_states(self, h: HostSel):
        rows = h.ev(self.agg.arg)
        lens = np.asarray([len(r) for r in rows], dtype=np.int64)
        flat = self._flatten(rows)
        inv2 = np.repeat(h.inv, lens) if len(rows) else \
            np.array([], dtype=np.int64)
        h2 = HostSel(lambda _ast: flat, len(flat), inv2, h.n_groups)
        return self.inner.group_states(h2)

    def merge(self, a, b):
        return self.inner.merge(a, b)

    def finalize(self, s):
        return self.inner.finalize(s)


class IdSetAgg(AggImpl):
    """IDSET(col): serialized set of distinct ids
    (IdSetAggregationFunction; pairs with the IN_ID_SET filter)."""

    numeric_input = False

    def empty(self):
        return set()

    def state(self, h: HostSel):
        return set(np.unique(h.ev(self.agg.arg)).tolist())

    def group_states(self, h: HostSel):
        return _per_group_apply(h.ev(self.agg.arg), h.inv, h.n_groups,
                                lambda v: set(np.unique(v).tolist()))

    def merge(self, a, b):
        return a | b

    def finalize(self, s):
        return serialize_sketch("idset", sorted(_py(x) for x in s))
