"""On-device equi-join over dict-encoded keys.

Reference parity: pinot-query-runtime/.../runtime/operator/
HashJoinOperator.java (build table on the right, probe with the left).
A hash table is the wrong shape for a TPU, so the device formulation is
sort + bounded-run probe, all static shapes:

- sort the right side's key column once (argsort keeps row identity);
- each probe row binary-searches its run start (jnp.searchsorted — the
  vectorized 'hash lookup');
- the run is materialized as max_dup candidate slots per probe row
  (max_dup = the right side's maximum key multiplicity, a static bound
  the caller takes from dictionary/build stats — 1 for PK joins), with
  a match mask killing slots past the run.

Output is a dense (L, max_dup) pair matrix + mask — the shape-preserving
analog of the dynamic match list, ready for gathers of payload columns
and for the same masked aggregation kernels every other operator uses.

mesh_equi_join shards the PROBE side over the mesh and replicates the
build side (broadcast join): each device joins its left shard against
the full right relation with zero collectives in the probe loop — the
all-to-all hash-exchange alternative only pays when the build side is
too big to replicate, which dict-encoded dimension tables are not.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..compat import shard_map as _shard_map

SEG_AXIS = "seg"   # matches parallel.mesh.SEG_AXIS (ops cannot import
# parallel without a cycle; segment_mesh builds the same axis name)


def device_equi_join(lk: jax.Array, rk: jax.Array, max_dup: int
                     ) -> Tuple[jax.Array, jax.Array]:
    """-> (match (L, max_dup) bool, r_idx (L, max_dup) int32).

    Pair (i, r_idx[i, j]) is a join match iff match[i, j]. Rows of rk
    with a key multiplicity beyond max_dup are silently truncated —
    callers size max_dup from build-side stats so that cannot happen.
    """
    n_r = rk.shape[0]
    order = jnp.argsort(rk)
    rs = jnp.take(rk, order)
    start = jnp.searchsorted(rs, lk)                      # (L,)
    cand = start[:, None] + jnp.arange(max_dup,
                                       dtype=jnp.int32)[None, :]
    cand_c = jnp.clip(cand, 0, max(n_r - 1, 0))
    match = (jnp.take(rs, cand_c) == lk[:, None]) & (cand < n_r)
    r_idx = jnp.take(order, cand_c).astype(jnp.int32)
    return match, r_idx


@functools.partial(jax.jit, static_argnums=(2, 3))
def _mesh_join_jit(lk, rk, max_dup, mesh):
    def per_device(lk_shard, rk_full):
        return device_equi_join(lk_shard, rk_full, max_dup)

    return _shard_map(
        per_device, mesh=mesh,
        in_specs=(P("seg"), P()),
        out_specs=(P("seg"), P("seg")),
        check_vma=False)(lk, rk)


def _splitmix32(x):
    """Device-side mix so hash partitioning is uniform even for
    sequential dict codes (skew would overflow a bucket)."""
    x = x.astype(jnp.uint32)
    x = (x ^ (x >> 16)) * jnp.uint32(0x7FEB352D)
    x = (x ^ (x >> 15)) * jnp.uint32(0x846CA68B)
    return x ^ (x >> 16)


@functools.partial(jax.jit, static_argnums=(2, 3, 4))
def _shuffle_exchange_jit(codes, ids, n_dev, cap, mesh):
    """Hash-partition (code, id) pairs across the mesh with ONE
    lax.all_to_all over ICI (SURVEY 2.9: the HashExchange ->
    on-device all-to-all mapping — this is that collective, not a
    comment). Returns per-device received (n_dev*cap,) codes/ids with
    -1 padding, plus an overflow flag (bucket capacity exceeded ->
    caller falls back)."""
    def per_device(c, i):
        m = c.shape[0]
        part = (_splitmix32(c) % jnp.uint32(n_dev)).astype(jnp.int32)
        # invalid rows (-1 code, padding) route to pseudo-partition
        # n_dev: they sort LAST (no real partition's rank inflates) and
        # every write lands out of bounds -> dropped, never clobbering
        # a live slot
        valid = c >= 0
        part_eff = jnp.where(valid, part, n_dev).astype(jnp.int32)
        order = jnp.argsort(part_eff)
        sp = jnp.take(part_eff, order)
        sc = jnp.take(jnp.where(valid, c, -1), order)
        si = jnp.take(i, order)
        # rank within each partition run = position - run start
        run_start = jnp.searchsorted(sp, sp)
        within = jnp.arange(m, dtype=jnp.int32) \
            - run_start.astype(jnp.int32)
        live = sp < n_dev
        ok = (within < cap) & live
        overflow = jnp.any((within >= cap) & live)
        buckets_c = jnp.full((n_dev, cap), -1, dtype=c.dtype)
        buckets_i = jnp.full((n_dev, cap), -1, dtype=ids.dtype)
        tp = jnp.where(ok, sp, n_dev)     # non-ok writes drop (OOB)
        buckets_c = buckets_c.at[tp, within].set(sc, mode="drop")
        buckets_i = buckets_i.at[tp, within].set(si, mode="drop")
        # the collective: bucket d of every device lands on device d
        rc = jax.lax.all_to_all(buckets_c, SEG_AXIS, 0, 0, tiled=True)
        ri = jax.lax.all_to_all(buckets_i, SEG_AXIS, 0, 0, tiled=True)
        return rc.reshape(-1), ri.reshape(-1), overflow[None]

    return _shard_map(
        per_device, mesh=mesh,
        in_specs=(P(SEG_AXIS), P(SEG_AXIS)),
        out_specs=(P(SEG_AXIS), P(SEG_AXIS), P(SEG_AXIS)),
        check_vma=False)(codes, ids)


@functools.partial(jax.jit, static_argnums=(4, 5))
def _partition_join_jit(lk, lids, rk, rids, max_dup, mesh):
    """Per-device partition join after the exchange: every device joins
    its hash partition locally (zero collectives in the probe)."""
    def per_device(lc, li, rc, ri):
        match, r_pos = device_equi_join(lc, rc, max_dup)
        match = match & (lc >= 0)[:, None]       # dead probe entries
        r_glob = jnp.take(ri, r_pos)
        return match, jnp.broadcast_to(li[:, None], match.shape), r_glob

    return _shard_map(
        per_device, mesh=mesh,
        in_specs=(P(SEG_AXIS), P(SEG_AXIS), P(SEG_AXIS), P(SEG_AXIS)),
        out_specs=(P(SEG_AXIS), P(SEG_AXIS), P(SEG_AXIS)),
        check_vma=False)(lk, lids, rk, rids)


def mesh_shuffle_join(mesh: Mesh, lk: np.ndarray, rk: np.ndarray,
                      max_dup: int, slack: float = 2.0
                      ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Distributed hash-shuffle INNER join: both key arrays shard over
    the mesh, ONE all_to_all redistributes (code, row_id) pairs so equal
    codes land on the same device, then every device joins its
    partition locally. Returns global (l_idx, r_idx) matched pairs, or
    None when a hash bucket overflowed its capacity (caller retries
    with more slack or falls back to the host join).

    Reference mapping: HashExchange.java + HashJoinOperator — the
    repartitioning rides the ICI collective instead of mailboxes."""
    n_dev = mesh.devices.size

    def shard(arr, fill):
        pad = (-len(arr)) % n_dev
        if pad:
            arr = np.concatenate(
                [arr, np.full(pad, fill, dtype=arr.dtype)])
        return arr

    out = []
    for keys in (lk, rk):
        codes = shard(keys, -1)
        ids = shard(np.arange(len(keys), dtype=np.int64), -1)
        m = len(codes) // n_dev
        cap = max(int(m / n_dev * slack) + 16, 16)
        cap = 1 << (cap - 1).bit_length()   # pow2 bucket: bounded XLA
        # program count (cap is a jit static arg)
        c_d = jax.device_put(codes, NamedSharding(mesh, P(SEG_AXIS)))
        i_d = jax.device_put(ids, NamedSharding(mesh, P(SEG_AXIS)))
        rc, ri, ovf = _shuffle_exchange_jit(c_d, i_d, n_dev, cap, mesh)
        if bool(np.any(jax.device_get(ovf))):
            return None
        out.append((rc, ri))
    (lc, li), (rc, ri) = out
    match, l_glob, r_glob = _partition_join_jit(lc, li, rc, ri,
                                                max_dup, mesh)
    match = np.asarray(match)
    l_glob = np.asarray(l_glob)
    r_glob = np.asarray(r_glob)
    pairs = np.nonzero(match)
    l_idx = l_glob[pairs]
    r_idx = r_glob[pairs]
    keep = (l_idx >= 0) & (r_idx >= 0)
    l_idx = l_idx[keep]
    r_idx = r_idx[keep]
    # restore hash_join's exact output order (left-major; within a left
    # row matches share one code, and the stable build sort emits them
    # by ascending original right index) so every backend stays
    # byte-identical downstream
    o = np.lexsort((r_idx, l_idx))
    return l_idx[o], r_idx[o]


def mesh_equi_join(mesh: Mesh, lk: np.ndarray, rk: np.ndarray,
                   max_dup: int) -> Tuple[np.ndarray, np.ndarray]:
    """Broadcast join over a mesh: probe keys sharded on the 'seg' axis,
    build keys replicated. Returns host (L, max_dup) match/r_idx (the
    probe shard axis is padded to a device multiple and trimmed back)."""
    n = len(lk)
    n_dev = mesh.devices.size
    pad = (-n) % n_dev
    lk_p = np.concatenate([lk, np.full(pad, -1, dtype=lk.dtype)]) \
        if pad else lk
    lk_d = jax.device_put(lk_p, NamedSharding(mesh, P("seg")))
    rk_d = jax.device_put(rk, NamedSharding(mesh, P()))
    match, r_idx = _mesh_join_jit(lk_d, rk_d, max_dup, mesh)
    return np.asarray(match)[:n], np.asarray(r_idx)[:n]
