"""On-device equi-join over dict-encoded keys.

Reference parity: pinot-query-runtime/.../runtime/operator/
HashJoinOperator.java (build table on the right, probe with the left).
A hash table is the wrong shape for a TPU, so the device formulation is
sort + bounded-run probe, all static shapes:

- sort the right side's key column once (argsort keeps row identity);
- each probe row binary-searches its run start (jnp.searchsorted — the
  vectorized 'hash lookup');
- the run is materialized as max_dup candidate slots per probe row
  (max_dup = the right side's maximum key multiplicity, a static bound
  the caller takes from dictionary/build stats — 1 for PK joins), with
  a match mask killing slots past the run.

Output is a dense (L, max_dup) pair matrix + mask — the shape-preserving
analog of the dynamic match list, ready for gathers of payload columns
and for the same masked aggregation kernels every other operator uses.

mesh_equi_join shards the PROBE side over the mesh and replicates the
build side (broadcast join): each device joins its left shard against
the full right relation with zero collectives in the probe loop — the
all-to-all hash-exchange alternative only pays when the build side is
too big to replicate, which dict-encoded dimension tables are not.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def device_equi_join(lk: jax.Array, rk: jax.Array, max_dup: int
                     ) -> Tuple[jax.Array, jax.Array]:
    """-> (match (L, max_dup) bool, r_idx (L, max_dup) int32).

    Pair (i, r_idx[i, j]) is a join match iff match[i, j]. Rows of rk
    with a key multiplicity beyond max_dup are silently truncated —
    callers size max_dup from build-side stats so that cannot happen.
    """
    n_r = rk.shape[0]
    order = jnp.argsort(rk)
    rs = jnp.take(rk, order)
    start = jnp.searchsorted(rs, lk)                      # (L,)
    cand = start[:, None] + jnp.arange(max_dup,
                                       dtype=jnp.int32)[None, :]
    cand_c = jnp.clip(cand, 0, max(n_r - 1, 0))
    match = (jnp.take(rs, cand_c) == lk[:, None]) & (cand < n_r)
    r_idx = jnp.take(order, cand_c).astype(jnp.int32)
    return match, r_idx


@functools.partial(jax.jit, static_argnums=(2, 3))
def _mesh_join_jit(lk, rk, max_dup, mesh):
    def per_device(lk_shard, rk_full):
        return device_equi_join(lk_shard, rk_full, max_dup)

    return jax.shard_map(
        per_device, mesh=mesh,
        in_specs=(P("seg"), P()),
        out_specs=(P("seg"), P("seg")),
        check_vma=False)(lk, rk)


def mesh_equi_join(mesh: Mesh, lk: np.ndarray, rk: np.ndarray,
                   max_dup: int) -> Tuple[np.ndarray, np.ndarray]:
    """Broadcast join over a mesh: probe keys sharded on the 'seg' axis,
    build keys replicated. Returns host (L, max_dup) match/r_idx (the
    probe shard axis is padded to a device multiple and trimmed back)."""
    n = len(lk)
    n_dev = mesh.devices.size
    pad = (-n) % n_dev
    lk_p = np.concatenate([lk, np.full(pad, -1, dtype=lk.dtype)]) \
        if pad else lk
    lk_d = jax.device_put(lk_p, NamedSharding(mesh, P("seg")))
    rk_d = jax.device_put(rk, NamedSharding(mesh, P()))
    match, r_idx = _mesh_join_jit(lk_d, rk_d, max_dup, mesh)
    return np.asarray(match)[:n], np.asarray(r_idx)[:n]
