"""Phase-level decomposition of the fused group-by kernels.

One implementation of the mask / fuse / compact / sort / aggregate /
transfer timing ladder, shared by tools/profile_compact.py (the CLI that
appends ``phase_profile`` ledger records) and EXPLAIN ANALYZE with
OPTION(profilePhases=true) (engine/executor.py attaches the phases as
child spans of the segment kernel span).

Each phase time is the amortized per-launch device time of a jitted
prefix of the kernel pipeline (bench.kernel_time convention: pipelined
launches amortize the tunneled-dispatch floor), so successive phases are
CUMULATIVE — ``t_compact_ms`` includes mask+fuse — and deltas attribute
the increments. ``t_transfer_ms`` is the full kernel minus the
no-transfer-compaction variant.

Re-running prefixes compiles extra XLA programs; this is a profiling
surface, never part of the untraced query path.
"""
from __future__ import annotations

import time
from typing import Any, Dict, Optional

import numpy as np


def timeit(fn, *args, iters: int = 5) -> float:
    """Amortized per-launch seconds: warm once, then (t_{k+1}-t_1)/k so
    the fixed dispatch floor cancels (bench.kernel_time convention)."""
    import jax

    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    out = fn(*args)
    jax.block_until_ready(out)
    t_one = time.perf_counter() - t0
    t0 = time.perf_counter()
    outs = [fn(*args) for _ in range(iters + 1)]
    jax.block_until_ready(outs)
    t_k = time.perf_counter() - t0
    return max((t_k - t_one) / iters, 1e-9)


PHASE_KEYS = ("t_mask_ms", "t_fuse_ms", "t_compact_ms", "t_sort_ms",
              "t_aggregate_ms", "t_kernel_ms", "t_transfer_ms")


def profile_plan(plan, iters: int = 5) -> Dict[str, Any]:
    """Decompose a compiled 'kernel' plan's device time into phases.

    -> {strategy, space, est_selectivity, cost_trace, needs_sort,
        scatter_core, t_mask_ms, [compact-path: slots_cap, cap_rows,
        t_fuse_ms, t_compact_ms, [t_sort_ms], t_aggregate_ms, matched,
        measured_selectivity, n_valid_rows, overflow, inflation],
        t_kernel_ms, [t_transfer_ms]}
    """
    import jax
    import jax.numpy as jnp

    from ..engine.executor import resolve_params
    from . import kernels
    from .compact import compact, full_slots_cap
    from .kernels import (_needs_sort, _payload_columns,
                          cpu_scatter_default, jitted_kernel)

    seg = plan.segment
    kp = plan.kernel_plan
    bucket = seg.bucket
    n = np.int32(seg.n_docs)
    cols = seg.device_cols(plan.col_names)
    params = resolve_params(plan)

    res: Dict[str, Any] = {
        "strategy": kp.strategy,
        "space": kp.group_space if kp.is_group_by else 0,
        "n_cols": len(cols),
        "est_selectivity": plan.est_selectivity,
        "cost_trace": plan.strategy_trace,
        "needs_sort": _needs_sort(kp) if kp.is_group_by else None,
        "scatter_core": cpu_scatter_default(),
    }

    # phase 1: predicate mask only
    def mask_fn(cols, n, params):
        valid = jnp.arange(bucket, dtype=jnp.int32) < n
        return valid & kernels._eval_pred(kp.pred, cols, params, bucket)

    res["t_mask_ms"] = round(
        timeit(jax.jit(mask_fn), cols, n, params, iters=iters) * 1e3, 2)

    if kp.strategy == "compact":
        cap = plan.slots_cap or full_slots_cap(bucket)
        res["slots_cap"] = cap
        res["cap_rows"] = cap * 128

        # phase 2: + fused key/payload materialization
        def fuse_fn(cols, n, params):
            m = mask_fn(cols, n, params)
            m, keys = kernels._group_keys_sentinel(kp, m, cols, params)
            payloads, *_meta = _payload_columns(kp, m, cols, params)
            return (m, keys) + payloads

        res["t_fuse_ms"] = round(
            timeit(jax.jit(fuse_fn), cols, n, params, iters=iters) * 1e3,
            2)

        # phase 3: + one compaction of [key] + payloads
        def comp_fn(cols, n, params):
            m = mask_fn(cols, n, params)
            m, keys = kernels._group_keys_sentinel(kp, m, cols, params)
            payloads, *_meta = _payload_columns(kp, m, cols, params)
            return compact(m, (keys,) + payloads, cap)

        jcomp = jax.jit(comp_fn)
        res["t_compact_ms"] = round(
            timeit(jcomp, cols, n, params, iters=iters) * 1e3, 2)
        _v, ccols, n_valid, matched, overflow = jcomp(cols, n, params)
        res["matched"] = int(matched)
        res["measured_selectivity"] = round(
            int(matched) / max(int(seg.n_docs), 1), 8)
        res["n_valid_rows"] = int(n_valid)
        res["overflow"] = int(overflow)
        res["inflation"] = round(int(n_valid) / max(int(matched), 1), 2)

        if res["needs_sort"]:
            # phase 3b: + the sort-once pass over the compacted keys
            # (the sorted post's dominant O(n log n) step)
            def sort_fn(cols, n, params):
                _valid, ccols, *_rest = comp_fn(cols, n, params)
                return jnp.sort(ccols[0])

            res["t_sort_ms"] = round(
                timeit(jax.jit(sort_fn), cols, n, params,
                       iters=iters) * 1e3, 2)

        # phase 4: + post-aggregation (full kernel minus transfer
        # compaction)
        f_noxfer = jitted_kernel(kp, bucket, plan.slots_cap,
                                 xfer_compact=False)
        res["t_aggregate_ms"] = round(
            timeit(f_noxfer, cols, n, params, iters=iters) * 1e3, 2)

    # phase 5: full kernel (as shipped, with transfer compaction)
    ffull = jitted_kernel(kp, bucket, plan.slots_cap)
    res["t_kernel_ms"] = round(
        timeit(ffull, cols, n, params, iters=iters) * 1e3, 2)
    if "t_aggregate_ms" in res:
        res["t_transfer_ms"] = round(
            max(res["t_kernel_ms"] - res["t_aggregate_ms"], 0.0), 2)
    return res


def attach_phase_spans(prof: Dict[str, Any]) -> None:
    """Attach a profile's phase ladder to the current span as child
    event spans (EXPLAIN ANALYZE's OPTION(profilePhases=true) path).
    Cumulative ladder times are converted to per-phase increments."""
    from ..utils.spans import add_event

    if prof.get("t_aggregate_ms") is not None:   # compact decomposition
        ladder = [k for k in ("t_mask_ms", "t_fuse_ms", "t_compact_ms",
                              "t_sort_ms", "t_aggregate_ms")
                  if prof.get(k) is not None]
        prev = 0.0
        for k in ladder:
            cum = float(prof[k])
            add_event("phase_" + k[2:-3], max(cum - prev, 0.0),
                      cumulative_ms=cum)
            prev = cum
        add_event("phase_transfer", float(prof.get("t_transfer_ms", 0.0)))
        return
    # dense/one-hot kernels: mask, then the fused aggregate remainder
    mask_ms = float(prof.get("t_mask_ms", 0.0))
    kernel_ms = float(prof.get("t_kernel_ms", 0.0))
    add_event("phase_mask", mask_ms)
    add_event("phase_aggregate", max(kernel_ms - mask_ms, 0.0),
              cumulative_ms=kernel_ms)
