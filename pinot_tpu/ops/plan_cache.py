"""Keyed kernel-plan cache with donated accumulator buffers.

Round-6 tentpole: repeated SSB iterations must never re-trace or
re-allocate. jax.jit already caches traces, but nothing (a) surfaced a
hit/miss counter the bench can assert zero-retrace against, (b) kept the
per-plan output buffers alive so XLA can reuse them, or (c) recorded the
measured selectivity a plan actually saw (the observability input for
the cost model in multistage/costs.py).

The cache key is the full kernel identity — (plan structure, bucket,
slots_cap, platform, xfer_compact, scatter core, compact-path env knobs)
— exactly the signature the jitted-kernel lru caches use, so one entry
maps to one compiled XLA program.

Donation: each entry threads the previous call's device output dict back
in as a donated argument, so XLA aliases the new outputs onto the old
buffers instead of allocating fresh ones every query iteration. The
accumulator is only an aliasing source — the kernel never reads it. The
first call builds a zeroed accumulator from jax.eval_shape (trace-only,
no extra compile). run() device_gets inside the entry lock, so a buffer
is never donated while another thread's host copy is in flight.

Round-7 observability: every hit/miss also counts into
utils.metrics.global_metrics (one snapshot covers the whole engine), a
RetraceDetector flags any compile of an already-warm plan structure
after its first query (a retrace: shape change, evicted entry, flipped
env knob) as a span annotation + counter, and run() splits
compile-vs-execute-vs-transfer into utils/spans spans when a trace is
being taken.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..utils.devmem import global_device_memory, nbytes_of
from ..utils.metrics import global_metrics
from ..utils.spans import device_fence, span, span_tracer


def _donation_supported() -> bool:
    """Buffer donation is a TPU/GPU optimization; XLA:CPU ignores it (and
    older jax versions warn). Enable only where it buys anything."""
    try:
        return jax.default_backend() != "cpu"
    except Exception:
        return False


class RetraceDetector:
    """Flags kernel compiles that happen AFTER a plan structure's first
    (warmup) query — the silent perf killers: a bucket/shape change, an
    evicted entry, a flipped env knob in the cache key.

    Semantics: ``begin_query()`` (engine/serving.py, once per query)
    advances a generation. A cache miss whose plan structure was already
    compiled in an EARLIER generation is a retrace; misses within one
    generation (a table with mixed segment buckets compiles the same
    plan at several shapes on its first query) are warmup, not
    retraces. ``expected()`` brackets deliberate recompiles (the
    capacity-overflow retry ladder) so they count separately.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._gen = 0
        self._last_token: Any = object()       # never equals a real token
        self._first_gen: Dict[int, int] = {}   # hash(plan) -> generation
        self._expected = threading.local()
        self.retraces = 0
        self.expected_recompiles = 0
        # compile-plane forensics (utils/compileplane): first-ever vs
        # same-generation compiles, so the compile_event trigger
        # taxonomy reconciles EXACTLY against this snapshot
        self.cold_compiles = 0
        self.warmup_compiles = 0

    def begin_query(self, token: Any = None) -> None:
        """Advance the generation. ``token`` (the accountant's query id)
        dedupes multi-table executions of ONE query — a hybrid
        offline+realtime query plans two segment lists but must stay a
        single warmup generation, or its second half's cold compiles
        would read as retraces."""
        with self._lock:
            if token is not None and token == self._last_token:
                return
            self._last_token = token
            self._gen += 1

    @contextmanager
    def expected(self):
        """Bracket a deliberate recompile (overflow retry ladder)."""
        prev = getattr(self._expected, "on", False)
        self._expected.on = True
        try:
            yield
        finally:
            self._expected.on = prev

    def expected_active(self) -> bool:
        """Whether this thread is inside an expected() bracket (the
        plan cache pins the bracket into stage hints at miss time so
        the classification at the ACTUAL compile — which may run after
        the bracket closed — still counts as deliberate)."""
        return getattr(self._expected, "on", False)

    def classify_compile(self, token: Any) -> str:
        """Classify one compile of ``token`` (the forensics primitive):
        'cold' (first ever), 'warmup' (another compile inside the
        structure's first query generation), 'expected' (inside an
        expected() bracket — the overflow ladder / drift re-quantize),
        or 'retrace'. Counts the matching counter; called by
        utils/compileplane.StagedFn at the moment the XLA compile
        actually stages, so the compile_event stream and this
        detector's totals reconcile one-to-one."""
        h = hash(token)
        expected = getattr(self._expected, "on", False)
        with self._lock:
            last = self._first_gen.get(h)
            gen = self._gen
            self._first_gen[h] = gen
            # counters mutate under the lock: concurrent server threads
            # (cluster scatter pool) must not lose increments
            if last is None:
                self.cold_compiles += 1
                return "cold"
            if last >= gen:
                self.warmup_compiles += 1
                return "warmup"
            if expected:
                self.expected_recompiles += 1
            else:
                self.retraces += 1
        if expected:
            global_metrics.count("plan_cache_expected_recompiles")
            return "expected"
        global_metrics.count("plan_cache_retraces")
        span_tracer.annotate(retrace=True)
        return "retrace"

    def observe_compile(self, plan: Any) -> bool:
        """Count one compile; -> True when the retrace flag fired."""
        return self.classify_compile(plan) == "retrace"

    def snapshot(self) -> Dict[str, int]:
        return {"retraces": self.retraces,
                "expected_recompiles": self.expected_recompiles}

    def trigger_snapshot(self) -> Dict[str, int]:
        """The four raw classification counters (the compile-forensics
        reconciliation oracle; snapshot() keeps its historical shape)."""
        return {"cold": self.cold_compiles,
                "warmup": self.warmup_compiles,
                "retraces": self.retraces,
                "expected_recompiles": self.expected_recompiles}

    def clear(self) -> None:
        with self._lock:
            self._first_gen.clear()
            self._gen = 0
            self._last_token = object()
            self.retraces = 0
            self.expected_recompiles = 0
            self.cold_compiles = 0
            self.warmup_compiles = 0


class PlanCacheEntry:
    """One compiled kernel + its donated accumulator + run statistics."""

    def __init__(self, base_fn, donate: bool, plan: Any = None,
                 key: Any = None,
                 stage_hints: Optional[Dict[str, Any]] = None):
        from ..utils.compileplane import key_fingerprint, staged
        self._base = base_fn     # unjitted builder (eval_shape surface)
        self.donate = donate
        # compile-plane forensics: the jit is wrapped in explicit AOT
        # staging (utils/compileplane.StagedFn) so the first run's
        # lower/compile split, executable memory bytes and trigger
        # classification land a compile_event. The detector token stays
        # the PLAN STRUCTURE (the retrace detector's historical key);
        # stage_hints carry the miss context (drift re-quantize /
        # LRU-eviction rebuild) the trigger taxonomy refines through.
        if plan is None:
            # direct constructions (tests) get a never-reused token —
            # an id() here could alias a GC'd entry's address in the
            # detector's generation map (the round-19 memo rule)
            import uuid
            plan = ("plan_cache", uuid.uuid4().hex)
        if donate:
            def _wrapped(cols, n_docs, params, acc):
                del acc          # aliasing source only, never read
                return base_fn(cols, n_docs, params)
            self.fn = staged(jax.jit(_wrapped, donate_argnums=(3,)),
                             "plan_cache", plan, donated=True,
                             hints=stage_hints)
        else:
            self.fn = staged(jax.jit(base_fn), "plan_cache", plan,
                             hints=stage_hints)
        if key is not None:
            self.fn.key_fp = key_fingerprint(key)
        self._acc: Any = None
        self.lock = threading.Lock()
        self.runs = 0
        # set by the cache's LRU eviction: an entry evicted BEFORE its
        # first run completes must not leave phantom accumulator bytes
        # in the device-memory registry (run() re-checks after adding)
        self.devmem_evicted = False
        # measured selectivity feedback: what the kernel actually matched.
        # Mutated through record_measured/mark_overflowed ONLY — the
        # entry lock guards them, and analysis/jaxlint's
        # unlocked-mutation rule holds every other mutation site to that.
        self.last_matched: Optional[int] = None
        self.last_rows: Optional[int] = None
        # set once this entry's capacity has overflowed: the executor
        # then goes STRAIGHT to the full-capacity entry on later runs
        # instead of paying the overflowing tight kernel forever
        self.overflowed = False

    def make_acc(self, cols, n_docs, params):
        """Zeroed accumulator matching the kernel's output structure
        (trace-only via eval_shape — no extra compile)."""
        shapes = jax.eval_shape(self._base, cols, n_docs, params)
        return jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), shapes)

    def run(self, cols, n_docs, params) -> Dict[str, Any]:
        """Execute and return HOST numpy outputs.

        Non-donating entries (CPU) go straight through the thread-safe
        jitted function — concurrent same-plan queries keep executing in
        parallel exactly as the lru-jitted path always did. Only the
        donation path takes the entry lock: the accumulator swap and the
        device_get must serialize so a buffer is never donated while
        another thread's host copy is still in flight.

        Under an active span trace the first-run (compile) vs execute vs
        transfer split is fenced with block_until_ready; untraced runs
        keep async dispatch."""
        if not self.donate:
            with self.lock:
                self.runs += 1
                first = self.runs == 1
            with span("device_execute", compiled=first):
                out = self.fn(cols, n_docs, params)
                device_fence(out)
            with span("device_transfer"):
                # THE transfer fence for undonated entries
                return jax.device_get(out)  # jaxlint: ok host-sync
        with self.lock:
            self.runs += 1
            first = self.runs == 1
            if self._acc is None:
                self._acc = self.make_acc(cols, n_docs, params)
            with span("device_execute", compiled=first, donated=True):
                out = self.fn(cols, n_docs, params, self._acc)
                device_fence(out)
            with span("device_transfer"):
                # THE transfer fence for donated entries (must complete
                # inside the lock, before the buffers are re-donated)
                host = jax.device_get(out)  # jaxlint: ok host-sync
            self._acc = out      # next call donates these buffers
            if first:
                # device-memory telemetry: the donated accumulator is a
                # live HBM resident; shapes are fixed per entry so one
                # report per entry suffices (re-registered on eviction
                # rebuilds because the entry object is new). Re-check
                # the eviction flag AFTER adding: an entry LRU-evicted
                # between build and first run would otherwise register
                # bytes nothing ever removes.
                global_device_memory.add("plan_cache_acc", id(self),
                                         nbytes_of(out))
                if self.devmem_evicted:
                    global_device_memory.remove("plan_cache_acc",
                                                id(self), evicted=False)
        if first:
            # shared-budget admission (engine/tier.py) — OUTSIDE the
            # entry lock: the demotion path takes the stack/cube locks
            from ..engine.tier import global_tier
            global_tier.enforce()
        return host

    def record_measured(self, matched: int, rows: int) -> None:
        with self.lock:
            self.last_matched = int(matched)
            self.last_rows = int(rows)

    def mark_overflowed(self) -> None:
        """Capacity overflow observed (engine/executor.py retry ladder);
        taken under the entry lock so concurrent same-plan queries can't
        lose the flag."""
        with self.lock:
            self.overflowed = True

    @property
    def measured_selectivity(self) -> Optional[float]:
        if self.last_matched is None or not self.last_rows:
            return None
        return self.last_matched / self.last_rows


class KernelPlanCache:
    """(plan, bucket, slots_cap, platform, flags) -> PlanCacheEntry with
    hit/miss counters (the bench's zero-retrace assertion reads these)."""

    def __init__(self, maxsize: int = 512):
        self._entries: "OrderedDict[Tuple, PlanCacheEntry]" = OrderedDict()
        # (plan, bucket) -> last measured selectivity: the O(1) index
        # measured_for reads on the planning hot path (a lock-held scan
        # of every entry per planned segment would serialize planners)
        self._measured: "OrderedDict[Tuple, float]" = OrderedDict()
        # (plan, bucket, cap) combinations whose drift-requantize
        # expected-compile bracket has been consumed (_note_requantize)
        self._requantized: "OrderedDict[Tuple, bool]" = OrderedDict()
        # keys the LRU evicted (bounded memory of them): a re-miss of
        # one is an lru_evict_rebuild in the compile-event taxonomy
        self._evicted_keys: "OrderedDict[Tuple, bool]" = OrderedDict()
        self._maxsize = maxsize
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.detector = RetraceDetector()

    def entry(self, plan, bucket: int,
              slots_cap: Optional[int] = None,
              platform: Optional[str] = None,
              xfer_compact: bool = True,
              scatter: Optional[bool] = None,
              expected_compile: bool = False) -> PlanCacheEntry:
        from .kernels import (_ladder_min_elems, _two_pass_mode,
                              build_kernel, cpu_scatter_default)

        if scatter is None:
            scatter = cpu_scatter_default(platform)
        key = (plan, bucket, slots_cap, platform, xfer_compact, scatter,
               _two_pass_mode(), _ladder_min_elems())
        with self._lock:
            ent = self._entries.get(key)
            if ent is not None:
                self.hits += 1
                self._entries.move_to_end(key)
                hit = True
            else:
                self.misses += 1
                hit = False
        global_metrics.count("plan_cache_hits" if hit
                             else "plan_cache_misses")
        if hit:
            span_tracer.annotate(cache="hit")
            return ent
        span_tracer.annotate(cache="miss")
        # compile-plane forensics: the trigger CONTEXT is known here, at
        # the miss, but classification + the compile_event land at the
        # entry's first run — where the XLA compile actually stages
        # (utils/compileplane.StagedFn), so concurrent same-key misses
        # (only one entry survives the setdefault below) can never
        # double-count an event. The drift re-quantize hint is consumed
        # ONCE per (plan, bucket, cap): a LATER miss of the same
        # combination (LRU eviction churn, a mode flip) is a genuine
        # recompile and must stay visible to the retrace detector.
        stage_hints: Dict[str, Any] = {}
        if expected_compile and self._note_requantize(plan, bucket,
                                                      slots_cap):
            global_metrics.count("selectivity_drift_recompiles")
            stage_hints["expected_kind"] = "drift_requantize"
        elif self.detector.expected_active():
            # inside an executor expected() bracket (the overflow retry
            # ladder): pin the kind now — the bracket may have closed
            # by the time the entry first runs
            stage_hints["expected_kind"] = "overflow_retry"
        if __debug__:
            # debug assertion (analysis/plan_verify): every structure
            # entering the cache must honor the hashable-frozen key
            # contract and the strategy gates — a violation here means a
            # caller synthesized a plan behind the planner's back.
            # Stripped under python -O; PINOT_PLAN_VERIFY=0 disables.
            from ..analysis.plan_verify import debug_check_cache_plan
            debug_check_cache_plan(plan, bucket)
        with span("trace_kernel", bucket=bucket, slots_cap=slots_cap):
            base = build_kernel(plan, bucket, slots_cap, platform,
                                xfer_compact, scatter=scatter,
                                two_pass_mode=key[6], ladder_min=key[7])
            ent = PlanCacheEntry(base, _donation_supported(), plan=plan,
                                 key=key, stage_hints=stage_hints)
        with self._lock:
            # a concurrent miss may have built the same entry; keep the
            # first one registered so its run stats/accumulator survive
            ent = self._entries.setdefault(key, ent)
            if key in self._evicted_keys:
                # eviction-rebuild attribution attaches to the
                # SURVIVING entry at publish time (consumed exactly
                # once, by the first publisher): a loser of the
                # setdefault race above must not walk off with the
                # hint while the winner's compile reads as a plain
                # retrace. set_hints is a no-op once the first compile
                # consumed the hints — by then the marker was already
                # attached by whoever published first.
                del self._evicted_keys[key]
                ent.fn.set_hints(evicted=True)
            self._entries.move_to_end(key)
            while len(self._entries) > self._maxsize:
                old_key, old = self._entries.popitem(last=False)
                old.devmem_evicted = True  # before remove: run() rechecks
                global_device_memory.remove("plan_cache_acc", id(old))
                # remember the evicted key (bounded): its next miss is
                # an lru_evict_rebuild, not an unexplained retrace
                self._evicted_keys[old_key] = True
                while len(self._evicted_keys) > 4 * self._maxsize:
                    self._evicted_keys.popitem(last=False)
            global_metrics.gauge("plan_cache_entries", len(self._entries))
        return ent

    def stats(self) -> Dict[str, int]:
        return {"hits": self.hits, "misses": self.misses,
                "entries": len(self._entries),
                **self.detector.snapshot()}

    def snapshot_misses(self) -> int:
        return self.misses

    def _note_requantize(self, plan, bucket: int,
                         slots_cap: Optional[int]) -> bool:
        """True exactly once per (plan, bucket, cap): whether this miss
        is the drift re-quantize's own compile (bracket it) or a
        rebuild of a combination already compiled before (don't)."""
        key = (plan, bucket, slots_cap)
        with self._lock:
            if key in self._requantized:
                return False
            self._requantized[key] = True
            self._requantized.move_to_end(key)
            while len(self._requantized) > self._maxsize:
                self._requantized.popitem(last=False)
            return True

    @staticmethod
    def _measured_key(plan, bucket: int, segment, params) -> Tuple:
        """KernelPlan hoists literals into params, so two queries
        differing only in a literal value (WHERE f<=1 vs f<=99) — or
        structurally identical plans on different tables — share the
        plan object. The measurement key therefore carries segment
        identity and a params fingerprint: one query's measured
        selectivity must never set another query's capacity."""
        import numpy as np
        seg_id = getattr(segment, "uid", None) \
            or getattr(segment, "name", None)
        fp = []
        for p in params or ():
            if isinstance(p, np.ndarray):
                fp.append((str(p.dtype), p.shape, p.tobytes()))
            else:
                fp.append(repr(p))  # scalars + ("dictvals", col) markers
        return (plan, bucket, seg_id, tuple(fp))

    def record_measured(self, plan, bucket: int, entry: PlanCacheEntry,
                        matched: int, rows: int,
                        segment=None, params=None) -> None:
        """Record a run's measured selectivity on the entry AND the
        index measured_for reads — the engine executor's post-run
        feedback write."""
        entry.record_measured(matched, rows)
        sel = entry.measured_selectivity
        if sel is None:
            return
        key = self._measured_key(plan, bucket, segment, params)
        with self._lock:
            self._measured[key] = sel
            self._measured.move_to_end(key)
            while len(self._measured) > self._maxsize:
                self._measured.popitem(last=False)

    def measured_for(self, plan, bucket: int,
                     segment=None, params=None) -> Optional[float]:
        """Most recently measured selectivity for this exact
        (plan, bucket, segment, literal-params) combination — the
        feedback value query/planner.py's selectivity-drift re-quantize
        consumes (round 12): when it disagrees with the IR estimate past
        multistage/costs.SELECTIVITY_DRIFT_RATIO, the planner re-derives
        the compact capacity from this measurement and the resulting
        compile runs as an expected_compile (counted, never a retrace).
        Measurements only exist after a run of the same query on the
        same segment, so a hit here implies that shape has been warm."""
        key = self._measured_key(plan, bucket, segment, params)
        with self._lock:
            return self._measured.get(key)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._measured.clear()
            self._requantized.clear()
            self._evicted_keys.clear()
            self.hits = 0
            self.misses = 0
        global_device_memory.drop_pool("plan_cache_acc")
        self.detector.clear()


class CubeCache:
    """(cube spec, segment uid) -> device-resident literal-free cube
    (engine/ragged.py) — the piece that turns the plan cache from a
    compile-amortizer into a throughput engine (PR 8): queries sharing
    a plan STRUCTURE differ only in hoisted literal params, so one
    unmasked group-by over the union of predicate + group dimensions
    answers every one of them by contraction. The cube is keyed by the
    segment's process-unique load uid (the round-9 _STACK_CACHE rule:
    names recur across tables and reloads; uids never do) so a reload
    can never serve stale cells, and the name rides along only for
    evict_cubes_containing."""

    def __init__(self, maxsize: int = 16):
        self._entries: "OrderedDict[Tuple, Dict[str, Any]]" = OrderedDict()
        # (spec, uid tuple) -> {name: [S, ...]} stacked device arrays:
        # the warm fused path would otherwise re-copy every per-segment
        # cube through jnp.stack on every dispatch
        self._stacked: "OrderedDict[Tuple, Dict[str, Any]]" = OrderedDict()
        # key -> Event while a build is in flight: concurrent fused
        # leaders missing the same key must not each run the full
        # unmasked segment scan (cold-path dedup)
        self._building: Dict[Tuple, threading.Event] = {}
        self._maxsize = maxsize
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def entry(self, spec, segment, build_fn) -> Dict[str, Any]:
        key = (spec, segment.uid, segment.name)
        while True:
            with self._lock:
                hit = self._entries.get(key)
                if hit is not None:
                    self._entries.move_to_end(key)
                    self.hits += 1
                    waiting = None
                else:
                    waiting = self._building.get(key)
                    if waiting is None:
                        self._building[key] = threading.Event()
                        self.misses += 1
            if hit is not None:
                global_metrics.count("cube_cache_hits")
                return hit
            if waiting is None:
                break               # this thread builds
            # another leader is scanning this segment right now: wait
            # for its result instead of duplicating the scan (on its
            # failure the loop re-enters and this thread builds)
            waiting.wait(timeout=600)
        global_metrics.count("cube_cache_misses")
        try:
            built = build_fn()
        except BaseException:
            # failed build: release waiters (they re-enter and build)
            with self._lock:
                ev = self._building.pop(key, None)
            if ev is not None:
                ev.set()
            raise
        with self._lock:
            # publish BEFORE signaling: a waiter woken by the event
            # must find the entry, or it would re-run the very scan
            # the event deduplicates
            built = self._entries.setdefault(key, built)
            global_device_memory.add("cube_cache", key, nbytes_of(built))
            self._entries.move_to_end(key)
            while len(self._entries) > self._maxsize:
                old_key, _old = self._entries.popitem(last=False)
                global_device_memory.remove("cube_cache", old_key)
            global_metrics.gauge("cube_cache_entries", len(self._entries))
            ev = self._building.pop(key, None)
        if ev is not None:
            ev.set()
        # shared-budget admission (engine/tier.py), outside self._lock:
        # the cube is a new HBM resident charged to the one budget
        from ..engine.tier import global_tier
        global_tier.enforce(protect={segment.uid})
        return built

    def stacked(self, spec, segments, per_segment: List[Dict[str, Any]]
                ) -> Dict[str, Any]:
        """{name: [S, ...]} stack of the given segments' cubes, cached
        by (spec, uid tuple) so a warm fused dispatch pays zero device
        copies. ``per_segment`` must be the entry() results for the
        same segments, in order."""
        key = (spec, tuple(s.uid for s in segments),
               tuple(s.name for s in segments))
        with self._lock:
            hit = self._stacked.get(key)
            if hit is not None:
                self._stacked.move_to_end(key)
                return hit
        stacked = {name: jnp.stack([c[name] for c in per_segment])
                   for name in per_segment[0]}
        with self._lock:
            stacked = self._stacked.setdefault(key, stacked)
            global_device_memory.add("cube_stacked", key,
                                     nbytes_of(stacked))
            self._stacked.move_to_end(key)
            while len(self._stacked) > self._maxsize:
                old_key, _old = self._stacked.popitem(last=False)
                global_device_memory.remove("cube_stacked", old_key)
        # shared-budget admission (engine/tier.py), outside self._lock
        from ..engine.tier import global_tier
        global_tier.enforce(protect={s.uid for s in segments})
        return stacked

    def resident_uids(self) -> set:
        """Segment uids with a resident per-segment cube — the 'warm
        ragged cube' placement signal the residency heartbeats report
        (a replica holding the cube answers plan-key-sharing queries
        without re-scanning the columns)."""
        with self._lock:
            return {k[1] for k in self._entries}

    def evict_containing(self, segment_name: str) -> None:
        with self._lock:
            for key in [k for k in self._entries if k[2] == segment_name]:
                del self._entries[key]
                global_device_memory.remove("cube_cache", key)
            for key in [k for k in self._stacked
                        if segment_name in k[2]]:
                del self._stacked[key]
                global_device_memory.remove("cube_stacked", key)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "entries": len(self._entries),
                    "stacked": len(self._stacked)}

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._stacked.clear()
            self.hits = 0
            self.misses = 0
        global_device_memory.drop_pool("cube_cache")
        global_device_memory.drop_pool("cube_stacked")


global_plan_cache = KernelPlanCache()
global_cube_cache = CubeCache()
