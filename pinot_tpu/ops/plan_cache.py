"""Keyed kernel-plan cache with donated accumulator buffers.

Round-6 tentpole: repeated SSB iterations must never re-trace or
re-allocate. jax.jit already caches traces, but nothing (a) surfaced a
hit/miss counter the bench can assert zero-retrace against, (b) kept the
per-plan output buffers alive so XLA can reuse them, or (c) recorded the
measured selectivity a plan actually saw (the observability input for
the cost model in multistage/costs.py).

The cache key is the full kernel identity — (plan structure, bucket,
slots_cap, platform, xfer_compact, scatter core, compact-path env knobs)
— exactly the signature the jitted-kernel lru caches use, so one entry
maps to one compiled XLA program.

Donation: each entry threads the previous call's device output dict back
in as a donated argument, so XLA aliases the new outputs onto the old
buffers instead of allocating fresh ones every query iteration. The
accumulator is only an aliasing source — the kernel never reads it. The
first call builds a zeroed accumulator from jax.eval_shape (trace-only,
no extra compile). run() device_gets inside the entry lock, so a buffer
is never donated while another thread's host copy is in flight.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp


def _donation_supported() -> bool:
    """Buffer donation is a TPU/GPU optimization; XLA:CPU ignores it (and
    older jax versions warn). Enable only where it buys anything."""
    try:
        return jax.default_backend() != "cpu"
    except Exception:
        return False


class PlanCacheEntry:
    """One compiled kernel + its donated accumulator + run statistics."""

    def __init__(self, base_fn, donate: bool):
        self._base = base_fn     # unjitted builder (eval_shape surface)
        self.donate = donate
        if donate:
            def _wrapped(cols, n_docs, params, acc):
                del acc          # aliasing source only, never read
                return base_fn(cols, n_docs, params)
            self.fn = jax.jit(_wrapped, donate_argnums=(3,))
        else:
            self.fn = jax.jit(base_fn)
        self._acc: Any = None
        self.lock = threading.Lock()
        self.runs = 0
        # measured selectivity feedback: what the kernel actually matched
        self.last_matched: Optional[int] = None
        self.last_rows: Optional[int] = None
        # set once this entry's capacity has overflowed: the executor
        # then goes STRAIGHT to the full-capacity entry on later runs
        # instead of paying the overflowing tight kernel forever
        self.overflowed = False

    def make_acc(self, cols, n_docs, params):
        """Zeroed accumulator matching the kernel's output structure
        (trace-only via eval_shape — no extra compile)."""
        shapes = jax.eval_shape(self._base, cols, n_docs, params)
        return jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), shapes)

    def run(self, cols, n_docs, params) -> Dict[str, Any]:
        """Execute and return HOST numpy outputs.

        Non-donating entries (CPU) go straight through the thread-safe
        jitted function — concurrent same-plan queries keep executing in
        parallel exactly as the lru-jitted path always did. Only the
        donation path takes the entry lock: the accumulator swap and the
        device_get must serialize so a buffer is never donated while
        another thread's host copy is still in flight."""
        if not self.donate:
            with self.lock:
                self.runs += 1
            return jax.device_get(self.fn(cols, n_docs, params))
        with self.lock:
            self.runs += 1
            if self._acc is None:
                self._acc = self.make_acc(cols, n_docs, params)
            out = self.fn(cols, n_docs, params, self._acc)
            host = jax.device_get(out)
            self._acc = out      # next call donates these buffers
            return host

    def record_measured(self, matched: int, rows: int) -> None:
        self.last_matched = int(matched)
        self.last_rows = int(rows)

    @property
    def measured_selectivity(self) -> Optional[float]:
        if self.last_matched is None or not self.last_rows:
            return None
        return self.last_matched / self.last_rows


class KernelPlanCache:
    """(plan, bucket, slots_cap, platform, flags) -> PlanCacheEntry with
    hit/miss counters (the bench's zero-retrace assertion reads these)."""

    def __init__(self, maxsize: int = 512):
        self._entries: "OrderedDict[Tuple, PlanCacheEntry]" = OrderedDict()
        self._maxsize = maxsize
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def entry(self, plan, bucket: int,
              slots_cap: Optional[int] = None,
              platform: Optional[str] = None,
              xfer_compact: bool = True,
              scatter: Optional[bool] = None) -> PlanCacheEntry:
        from .kernels import (_ladder_min_elems, _two_pass_mode,
                              build_kernel, cpu_scatter_default)

        if scatter is None:
            scatter = cpu_scatter_default(platform)
        key = (plan, bucket, slots_cap, platform, xfer_compact, scatter,
               _two_pass_mode(), _ladder_min_elems())
        with self._lock:
            ent = self._entries.get(key)
            if ent is not None:
                self.hits += 1
                self._entries.move_to_end(key)
                return ent
            self.misses += 1
            base = build_kernel(plan, bucket, slots_cap, platform,
                                xfer_compact, scatter=scatter,
                                two_pass_mode=key[6], ladder_min=key[7])
            ent = PlanCacheEntry(base, _donation_supported())
            self._entries[key] = ent
            while len(self._entries) > self._maxsize:
                self._entries.popitem(last=False)
            return ent

    def stats(self) -> Dict[str, int]:
        return {"hits": self.hits, "misses": self.misses,
                "entries": len(self._entries)}

    def snapshot_misses(self) -> int:
        return self.misses

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self.hits = 0
            self.misses = 0


global_plan_cache = KernelPlanCache()
