"""Masked row compaction — the TPU-native DocIdSet/Projection primitive.

Reference parity: pinot-core/.../operator/DocIdSetOperator.java:59-86
materializes filtered docIds in blocks, then ProjectionOperator.java:67-78
batch-gathers projected columns for them. The TPU analog cannot scatter
(no efficient per-lane scatter on the VPU), so compaction works lane-wise:

- the (N,) column is viewed as (N/128, 128) — 128 independent lane streams;
- per (R,128) tile, each lane compacts its matched rows to the top via a
  broadcast-compare scatter (dest[r,c] = exclusive in-lane count, an
  R x R strict-lower-triangular matmul, then sum_r [dest==s] * x — all
  VPU/MXU ops, no scatter);
- every lane stream advances by the same amount: the tile's max per-lane
  count. Short lanes pad with invalid slots (valid flags are compacted
  alongside), so the output is "loosely compacted": size ~ matched rows
  times a small inflation factor, never more than the input;
- a running slot offset carried in SMEM across the (sequential) TPU grid
  places each tile's rows; each DMA writes a full fixed-size staging
  block and the next tile's DMA overwrites the garbage tail.

Order is NOT preserved — group-by / aggregation consumers don't need it.

Outputs are (slots_cap*128,) arrays + (n_slots, matched, overflow)
scalars. Rows at index >= n_slots*128 are uninitialized; consumers must
mask with `valid & (iota < n_slots*128)`. overflow != 0 means capacity
was exceeded and the result is incomplete — retry with full capacity
(`full_slots_cap(n)` can never overflow).

On CPU (tests, host fallback) an XLA nonzero-based implementation is used.
"""
from __future__ import annotations

import functools
import os
from typing import Tuple

import jax
import jax.numpy as jnp

LANES = 128
R = 32                 # sublane rows per subtile
K_MIN = 8              # minimum subtiles per grid step (gate + capacity math)
K_MAX = 32             # maximum (VMEM permitting — _choose_k)
STEP = K_MIN * R       # minimum rows per grid step (pallas gate, caps)
STAGE = K_MIN * R + R  # staging rows at K_MIN (capacity math only)


def _interpret() -> bool:
    """Test-only escape hatch: run the Pallas kernel in interpret mode on
    CPU (trace-time; dedicated tests call compact() directly, so the
    jitted-kernel caches never see a stale value)."""
    return os.environ.get("PINOT_PALLAS_INTERPRET", "0") == "1"


def _choose_k(n_cols: int, n: int) -> int:
    """Subtiles per grid step: as large as VMEM comfortably allows.

    Larger K cuts the sequential grid (fewer DMA waits / SMEM carry
    round-trips) and deepens the placement matmul contraction from R=32
    to K*R (the 128x128 MXU is depth-starved at 32). Rough VMEM budget
    per column stream: double-buffered input block (2*K*R*LANES*4B) +
    staging ((K+1)*R*LANES*4B) + the bf16 part tiles; cap the estimate
    at ~10MB of the ~16MB core VMEM."""
    k = K_MAX
    while k > K_MIN and k * R * LANES > n:
        k //= 2               # don't pad small inputs up to a giant step
    while k > K_MIN:
        in_blocks = 2 * k * R * LANES * 4 * (n_cols + 1)
        staging = (k + 1) * R * LANES * 4 * (n_cols + 1)
        parts = (4 * n_cols + 1) * k * R * LANES * 2
        stack = (k + 1) * R * k * R * 2
        if in_blocks + staging + parts + stack <= 10 << 20:
            break
        k //= 2
    # the grid consumes k*R*LANES rows per step; n is padded to that
    return k


# the full-capacity margin must cover the LARGEST staging block any
# chosen K can write ((K_MAX+1)*R rows) — the kernel's fits check is
# off+stage<=cap. The DEFAULT caps keep the small K_MIN-based floor:
# compact() shrinks K until the staging block fits the cap, so a small
# cap simply runs a smaller grid step — quadrupling the floors would
# quadruple every small-segment kernel's post-aggregation for nothing
# (measured ~2x CPU kernel time at 200k rows).
STAGE_MAX = (K_MAX + 1) * R

# smallest capacity the XLA fallback compaction accepts: it has no staging
# block, so the floor is only about keeping the ladder/post shapes sane.
# The cost model (multistage/costs.compact_slots_cap) clamps here when the
# selectivity estimate says almost nothing matches.
XLA_MIN_SLOTS = 8


def default_slots_cap(n: int) -> int:
    """Default output capacity (slot rows): 1/4 of the input, padded.

    The lane-wise compaction is loose — every subtile advances by its max
    per-lane count, so at selectivity p the slots consumed are ~E[max
    Binomial(R, p) over 128 lanes] / R, about 4-5x p for p around a few
    percent. 1/4 covers p <~ 8% without overflow; denser masks trigger the
    executor's full_slots_cap retry (engine/executor.py run_kernel)."""
    return max(n // (4 * LANES), 2 * STAGE) + STAGE


def sorted_default_slots_cap(n: int) -> int:
    """Default capacity for the sort-based group path: 1/16 of the input.

    Big-space group-bys are overwhelmingly low-selectivity (SSB Q3/Q4:
    0.01-0.5% matched), and the sort runs over the full static capacity,
    so a tighter cap is a direct kernel-time win. The loose-compaction
    advance floor is ~1 slot row per 32-row subtile with any match
    (~3.2%), so 1/16 (6.25%) keeps headroom; denser masks pay the
    full-capacity retry like everything else."""
    return max(n // (16 * LANES), 2 * STAGE) + STAGE


def full_slots_cap(n: int) -> int:
    """Capacity that can never overflow: total slot advance is bounded by
    one slot row per input row-of-128 plus one pad row per subtile, with
    margin for the largest staging block any K writes."""
    return n // LANES + n // (R * LANES) + STAGE_MAX


def f64_bitcast_ok(platform: str = None) -> bool:
    """XLA:TPU's x64 rewriter cannot lower f64 bitcast-convert (it legalizes
    s64/u64 as 32-bit pairs but has no rule for f64 bit views); emitting one
    crashes compilation on the real chip. CPU lowers it fine.

    platform: the platform the kernel will compile for — pass it whenever
    execution targets a mesh whose devices differ from the process default
    (e.g. a CPU dryrun mesh under a TPU default backend)."""
    return (platform or jax.default_backend()) == "cpu"


def compact(mask: jax.Array, cols: Tuple[jax.Array, ...], slots_cap: int,
            platform: str = None):
    """Compact masked elements of 1-D arrays toward the front (lane-wise).

    mask: (N,) bool; cols: tuple of (N,) arrays. 64-bit columns are
    bit-split into int32 pairs around the kernel. float64 columns on
    backends without f64 bitcast support (TPU) are carried as float32 —
    value-identical to the dense strategy there, which accumulates
    float_acc_dtype()=f32 anyway (kernels.py documented tolerance).
    Returns (valid, out_cols, n_valid_rows, matched, overflow) with
    valid/out_cols of length slots_cap*128.
    """
    n = mask.shape[0]
    # split 64-bit columns into int32 pairs (exact for int64 and float64)
    split_cols = []
    recipes = []  # (dtype, n_parts)
    for c in cols:
        if c.dtype == jnp.float64 and not f64_bitcast_ok(platform):
            c = c.astype(jnp.float32)
        if c.dtype.itemsize == 8:
            pair = jax.lax.bitcast_convert_type(c, jnp.int32)  # (N, 2)
            split_cols.extend([pair[:, 0], pair[:, 1]])
            recipes.append((c.dtype, 2))
        elif c.dtype.itemsize == 4:
            split_cols.append(jax.lax.bitcast_convert_type(c, jnp.int32))
            recipes.append((c.dtype, 1))
        else:
            split_cols.append(c.astype(jnp.int32))
            recipes.append((jnp.dtype(jnp.int32), 1))

    k_sub = _choose_k(len(split_cols), n)
    # the staging DMA writes (k_sub+1)*R rows; a cap smaller than one
    # staging block can't hold it (shape-invalid even when predicated
    # off) — shrink K, then fall back to XLA for pathological caps
    while (k_sub + 1) * R > slots_cap and k_sub > K_MIN:
        k_sub //= 2
    if _use_pallas(n, platform) and (k_sub + 1) * R <= slots_cap:
        # the kernel consumes k_sub*R*LANES rows per grid step; pad odd
        # sizes with unmatched rows (mask False) so every shape qualifies
        step_rows = k_sub * R * LANES
        rem = n % step_rows
        if rem:
            pad = step_rows - rem
            mask = jnp.pad(mask, (0, pad))
            split_cols = [jnp.pad(c, (0, pad)) for c in split_cols]
        valid, outs, n_slots, matched, overflow = _compact_pallas(
            mask, tuple(split_cols),
            n + (step_rows - rem if rem else 0), slots_cap, k_sub,
            _interpret())
    else:
        valid, outs, n_slots, matched, overflow = _compact_xla(
            mask, tuple(split_cols), n, slots_cap)

    # recombine split columns
    out_cols = []
    i = 0
    for dtype, parts in recipes:
        if parts == 2:
            pair = jnp.stack([outs[i], outs[i + 1]], axis=-1)
            out_cols.append(jax.lax.bitcast_convert_type(pair, dtype))
            i += 2
        else:
            out_cols.append(jax.lax.bitcast_convert_type(outs[i], dtype)
                            if dtype != jnp.int32 else outs[i])
            i += 1
    n_valid = n_slots * LANES
    return valid, tuple(out_cols), n_valid, matched, overflow


def _use_pallas(n: int, platform: str = None) -> bool:
    if n < STEP * LANES:
        return False
    if _interpret():
        return True            # test-only: interpret-mode kernel on CPU
    return (platform or jax.default_backend()) == "tpu"


def _compact_xla(mask, cols, n, slots_cap):
    """Fallback: exact dense compaction via cumsum + searchsorted + gather.

    Replaces the jnp.nonzero(size=...) formulation: XLA:CPU executed that
    lowering ~12x slower than one running-count cumsum plus a binary
    search for the k-th matched position (measured 14ms -> 1.2ms on a
    262k-row mask), and the cost now scales with the CAPACITY, not the
    input — the cost-model-tightened caps (multistage/costs.
    compact_slots_cap) make the search+gather nearly free at SSB
    selectivities."""
    cap = slots_cap * LANES
    size = min(cap, n)
    cs = jnp.cumsum(mask.astype(jnp.int32))
    # position of the (k+1)-th matched row = first index with cs == k+1;
    # k >= matched lands at n and is masked off below
    idx = jnp.searchsorted(cs, jnp.arange(1, size + 1, dtype=jnp.int32),
                           method="scan")
    valid_small = jnp.arange(size, dtype=jnp.int32) < cs[-1]
    outs = [jnp.where(valid_small, c.at[idx].get(mode="clip"), 0)
            for c in cols]
    if cap > size:
        pad = cap - size
        valid = jnp.concatenate(
            [valid_small, jnp.zeros(pad, dtype=jnp.bool_)])
        outs = [jnp.concatenate([o, jnp.zeros(pad, dtype=o.dtype)])
                for o in outs]
    else:
        valid = valid_small
    matched = cs[-1]
    overflow = (matched > cap).astype(jnp.int32)
    n_slots = jnp.minimum((matched + LANES - 1) // LANES,
                          jnp.int32(slots_cap))
    return valid, outs, n_slots, matched, overflow


# ---------------------------------------------------------------------------
# Pallas TPU kernel
# ---------------------------------------------------------------------------

def _kernel(mask_ref, *rest, n_cols: int, slots_cap: int, n_steps: int,
            k_sub: int):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    stage_rows = (k_sub + 1) * R
    col_refs = rest[:n_cols]
    valid_out = rest[n_cols]
    col_outs = rest[n_cols + 1: 2 * n_cols + 1]
    nslots_ref = rest[2 * n_cols + 1]
    matched_ref = rest[2 * n_cols + 2]
    overflow_ref = rest[2 * n_cols + 3]
    carry = rest[2 * n_cols + 4]            # SMEM (2,): [off, matched]
    oflow = rest[2 * n_cols + 5]            # SMEM (1,)
    stages = rest[2 * n_cols + 6: 3 * n_cols + 7]   # VMEM staging per col
    sems = rest[3 * n_cols + 7]             # DMA sems (n_cols + 1,)

    step = pl.program_id(0)

    @pl.when(step == 0)
    def _():
        # explicit int32 literals: weakly-typed Python ints re-canonicalize
        # to int64 when interpret mode's state discharge re-traces the
        # jaxpr under an x64-enabled process (dtype-mismatched ref swap)
        carry[0] = jnp.int32(0)
        carry[1] = jnp.int32(0)
        oflow[0] = jnp.int32(0)

    # strict lower triangular (R x R): exclusive in-lane running count
    row_i = jax.lax.broadcasted_iota(jnp.int32, (R, R), 0)
    col_i = jax.lax.broadcasted_iota(jnp.int32, (R, R), 1)
    stril = (row_i > col_i).astype(jnp.int32).astype(jnp.float32)
    out_iota = jax.lax.broadcasted_iota(jnp.int32, (R, R, LANES), 0)
    row_iota = jax.lax.broadcasted_iota(jnp.int32, (R, LANES), 0)
    stage_iota = jax.lax.broadcasted_iota(jnp.int32, (stage_rows, R), 0)
    sub_iota = jax.lax.broadcasted_iota(jnp.int32, (stage_rows, R), 1)

    # Per subtile: in-lane compaction (dest via the stril matmul, then a
    # one-hot gather-sum). Placement into the staging block happens in ONE
    # deep matmul per byte part across all k_sub subtiles:
    #     staging = stack_all @ vstack(subtile parts)
    # stack_all (stage_rows, k_sub*R) stacks each subtile's one-hot
    # placement at its running offset; invalid slots are exact zeros, so
    # overlapping garbage rows can't corrupt the sums. A k_sub*R-deep
    # contraction keeps the 128x128 MXU fed (per-subtile R=32-deep
    # matmuls ran it at ~25% depth utilization). Values stay bf16-exact:
    # columns are split into bytes (|v| <= 255) and recombined after f32
    # accumulation.
    valid_tiles = []
    part_tiles = [[[] for _ in range(4)] for _ in range(n_cols)]
    offs = []
    local_off = jnp.int32(0)
    total = jnp.int32(0)
    for k in range(k_sub):
        sl = slice(k * R, (k + 1) * R)
        m = mask_ref[sl, :] != 0                       # (R, 128)
        mf = m.astype(jnp.int32).astype(jnp.float32)
        # f32 reductions (exact: counts <= R=32): this jax's Mosaic cannot
        # lower integer sum/max reductions
        cntf = jnp.sum(mf, axis=0, dtype=jnp.float32)  # (128,)
        cnt = cntf.astype(jnp.int32)
        adv = jnp.max(cntf).astype(jnp.int32)
        dest = jax.lax.dot_general(
            stril, mf, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(jnp.int32)
        scat = (dest[None, :, :] == out_iota) & m[None, :, :]  # (R, R, 128)
        valid_tiles.append((row_iota < cnt[None, :]).astype(jnp.int32)
                           .astype(jnp.bfloat16))
        for ci in range(n_cols):
            x = col_refs[ci][sl, :]
            # byte-split BEFORE the one-hot gather-sum so the reduction
            # runs in f32 (exact: one-hot selects a single byte <= 255
            # per output slot) — this jax's Mosaic cannot lower integer
            # reductions at all
            for b in range(4):
                if b < 3:
                    part = jax.lax.bitwise_and(
                        jax.lax.shift_right_logical(x, jnp.int32(8 * b)),
                        jnp.int32(0xFF))
                else:
                    part = jax.lax.shift_right_arithmetic(x, jnp.int32(24))
                partf = part.astype(jnp.float32)
                compb = jnp.sum(
                    jnp.where(scat, partf[None, :, :], jnp.float32(0)),
                    axis=1, dtype=jnp.float32)         # (R, 128) f32
                part_tiles[ci][b].append(compb.astype(jnp.bfloat16))
        offs.append(local_off)
        local_off = local_off + adv
        # f32 scalar sum (exact: <= 4096 per step); jnp.sum-to-scalar on
        # int32 sneaks an int64 intermediate past the Mosaic lowering
        total = total + jnp.sum(cntf, dtype=jnp.float32).astype(jnp.int32)

    stack_all = jnp.concatenate(
        [(stage_iota == offs[k] + sub_iota).astype(jnp.int32)
         .astype(jnp.bfloat16) for k in range(k_sub)],
        axis=1)                                        # (stage_rows, k_sub*R)

    def place_all(tiles):
        t = jnp.concatenate(tiles, axis=0)             # (k_sub*R, 128)
        return jax.lax.dot_general(
            stack_all, t, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    off = carry[0]
    fits = off + stage_rows <= slots_cap

    for ci in range(n_cols + 1):
        if ci == 0:
            val = place_all(valid_tiles).astype(jnp.int32)
        else:
            acc = [place_all(part_tiles[ci - 1][b]) for b in range(4)]
            val = (((acc[3].astype(jnp.int32) * jnp.int32(256)
                     + acc[2].astype(jnp.int32)) * jnp.int32(256)
                    + acc[1].astype(jnp.int32)) * jnp.int32(256)
                   + acc[0].astype(jnp.int32))
        stages[ci][:] = val

    # DMA start + synchronous wait inside one conditional block: a skipped
    # step (overflow) skips both, so no semaphore imbalance across steps
    @pl.when(fits)
    def _():
        cps = []
        for ci in range(n_cols + 1):
            dst = valid_out if ci == 0 else col_outs[ci - 1]
            cp = pltpu.make_async_copy(
                stages[ci].at[:], dst.at[pl.ds(off, stage_rows)],
                sems.at[ci])
            cp.start()
            cps.append(cp)
        for cp in cps:
            cp.wait()
        carry[0] = off + local_off

    @pl.when(jnp.logical_not(fits))
    def _():
        oflow[0] = jnp.int32(1)

    carry[1] = carry[1] + total

    @pl.when(step == n_steps - 1)
    def _():
        nslots_ref[0, 0] = carry[0]
        matched_ref[0, 0] = carry[1]
        overflow_ref[0, 0] = oflow[0]


@functools.partial(jax.jit, static_argnums=(2, 3, 4, 5))
def _compact_pallas(mask, cols, n, slots_cap, k_sub, interp):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_cols = len(cols)
    step_rows = k_sub * R
    stage_rows = (k_sub + 1) * R
    n_steps = n // (step_rows * LANES)
    # int8, not uint8: Mosaic's ir_constant cannot emit uint8 literals in
    # this jax version, so `mask_ref != 0` failed TPU lowering
    mask2d = mask.reshape(n // LANES, LANES).astype(jnp.int8)
    cols2d = [c.reshape(n // LANES, LANES) for c in cols]

    in_specs = [pl.BlockSpec((step_rows, LANES), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)] * (n_cols + 1)
    out_shapes = ([jax.ShapeDtypeStruct((slots_cap, LANES), jnp.int32)]
                  * (n_cols + 1)
                  + [jax.ShapeDtypeStruct((1, 1), jnp.int32)] * 3)
    out_specs = ([pl.BlockSpec(memory_space=pl.ANY)] * (n_cols + 1)
                 + [pl.BlockSpec(memory_space=pltpu.SMEM)] * 3)

    kern = functools.partial(_kernel, n_cols=n_cols, slots_cap=slots_cap,
                             n_steps=n_steps, k_sub=k_sub)
    call = pl.pallas_call(
        kern,
        grid=(n_steps,),
        in_specs=in_specs,
        out_shape=out_shapes,
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.SMEM((2,), jnp.int32),
            pltpu.SMEM((1,), jnp.int32),
        ] + [pltpu.VMEM((stage_rows, LANES), jnp.int32)] * (n_cols + 1)
          + [pltpu.SemaphoreType.DMA((n_cols + 1,))],
        interpret=interp,
    )
    # the kernel is pure 32-bit; keep x64 promotion rules out of the trace
    from ..compat import disable_x64
    with disable_x64():
        outs = call(mask2d, *cols2d)

    valid2d = outs[0]
    col2d = outs[1: n_cols + 1]
    n_slots = outs[n_cols + 1][0, 0]
    matched = outs[n_cols + 2][0, 0]
    overflow = outs[n_cols + 3][0, 0]

    cap_rows = slots_cap * LANES
    row_ok = (jnp.arange(cap_rows, dtype=jnp.int32)
              < n_slots * LANES)
    valid = (valid2d.reshape(cap_rows) != 0) & row_ok
    out_cols = tuple(jnp.where(valid, c.reshape(cap_rows), 0)
                     for c in col2d)
    return valid, out_cols, n_slots, matched, overflow
