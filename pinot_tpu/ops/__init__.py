from .ir import (And, AggSpec, Bin, Cmp, Col, EqId, FalseP, InSet, KernelPlan,
                 Lit, Not, Or, Pred, IdRange, TrueP, ValueExpr)  # noqa: F401
from .kernels import build_kernel, float_acc_dtype, int_acc_dtype  # noqa: F401
