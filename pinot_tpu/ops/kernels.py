"""Kernel builder: KernelPlan -> jit-able whole-segment function.

Reference parity: replaces the per-block pull loop of pinot-core
(DocIdSetOperator.java:59-86 blocks of <=10k docIds -> ProjectionOperator
gathers -> DefaultAggregationExecutor / DefaultGroupByExecutor.process).
TPU-native: no docId materialization at all — predicates evaluate to a
whole-segment boolean mask (masks replace RoaringBitmap), projections are
gathers, aggregations are masked reductions. The whole query runs as one
fused XLA program per segment; block iteration disappears.

Group-by rides the MXU, not scatters: TPU scatter-add (segment_sum) is
orders of magnitude slower than matmul on this hardware (measured 1.4s vs
~70ms for a 16M-row, G=1024 group-by), so dense group aggregation is a
one-hot dot_general:

    sums[g] = L @ one_hot(keys)           # (rows, N) x (N, G) on the MXU

with masked-out rows routed to an out-of-range sentinel key (one_hot
yields an all-zero column — no pollution, no mask multiply). Integer sums
stay EXACT by decomposing |v| into int8 limbs (base 2^b with
(2^b-1)*bucket <= int32max so the MXU's int8xint8->int32 accumulation
can't overflow), one row per limb per sign, recombined in int64.
DISTINCTCOUNT presence is the same trick squared:
one_hot(keys)^T @ one_hot(ids) > 0. Float sums accumulate in
float_acc_dtype (f64 on CPU, f32 on TPU — documented tolerance).
The dense cartesian dict-id key is DictionaryBasedGroupKeyGenerator
.java:63 arithmetic.

Kernel signature (shape-stable, no data-dependent shapes):
    fn(cols: tuple[jax.Array], n_docs: int32, params: tuple[jax.Array])
        -> dict[str, jax.Array]
"""
from __future__ import annotations

import functools
import os
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from .ir import (AggSpec, And, Bin, Case, Cmp, Col, EqId, FalseP, Func,
                 IdRange, InBitmap, InSet, KernelPlan, Lit, MaskParam,
                 MvReduce, Not, Or, Pred, SelectPlan, TrueP, ValueExpr)

# IN lists longer than this use sorted-membership (raw values) or a
# presence-table gather (dict ids) instead of broadcast compare
INSET_SEARCH_MIN = 64
INSET_BITMAP_MIN = 64
# scalar DISTINCTCOUNT cardinality above which the one-hot presence
# matmul (rows x card MACs) yields to sort + run boundaries
DISTINCT_ONEHOT_CARD = 1 << 12

# unrolled masked-reduce limit for group MIN/MAX (no matmul form exists;
# above this the planner routes to segment ops on CPU or the host path)
MINMAX_UNROLL_GROUPS = 64


def cpu_scatter_default(platform: Optional[str] = None) -> bool:
    """Whether group-by kernels should take the scatter (segment-ops) path.

    The one-hot MXU formulation is the TPU design; XLA:CPU executes those
    int8 matmuls 50-100x slower than a plain scatter-add (PERF_LEDGER r04:
    compact kernels at 0.01-0.16x the numpy baseline on the CPU fallback).
    CPU scatter-add is fast, so when the execution platform is cpu the
    kernels swap the aggregation core for jax.ops.segment_* — same dense
    (space,) outputs, same extraction. PINOT_CPU_FAST_GROUPBY=0 pins the
    MXU formulation everywhere (the test suite does this so the TPU-shaped
    code stays covered on the virtual CPU mesh)."""
    plat = platform or jax.default_backend()
    return (plat == "cpu"
            and os.environ.get("PINOT_CPU_FAST_GROUPBY", "1") == "1")


def float_acc_dtype() -> jnp.dtype:
    """Float accumulator dtype. Pinot SUM/MIN/MAX/AVG return double; on CPU
    (tests — digest-exact vs numpy float64 oracle) we match that. On TPU
    f64 is emulated and slow, so accumulate f32 and accept documented
    tolerance (BASELINE.md: tolerance only where the reference itself is
    order-dependent — float summation order already differs)."""
    if jax.config.jax_enable_x64 and jax.default_backend() == "cpu":
        return jnp.float64
    return jnp.float32


def int_acc_dtype() -> jnp.dtype:
    """int64 when available: a 100M-row int32 segment sum needs ~2^57."""
    return jnp.int64 if jax.config.jax_enable_x64 else jnp.int32


def sum_carrier_dtype(bits: int):
    """Narrowest EXACT carrier for a compact-path integral sum payload
    whose magnitude the planner bounded at ``bits`` (_payload_columns
    narrows through this; analysis/plan_verify.py checks against it, so
    the narrowing rule cannot fork). Values under 2^31 ride int32 — half
    the compaction bytes, no 64-bit split. Returns None when no exact
    integer carrier of the claimed width exists (jax_enable_x64 off and
    bits >= 32): narrowing would silently truncate, so callers must fail
    loudly instead (PV104)."""
    if bits < 32:
        return jnp.int32
    return jnp.int64 if jax.config.jax_enable_x64 else None


def _limb_base_bits(bucket: int) -> int:
    """Largest b <= 7 with (2^b - 1) * bucket <= int32max: per-group int8
    dot products then can't overflow the MXU's int32 accumulator."""
    b = 7
    while b > 1 and ((1 << b) - 1) * bucket > (1 << 31) - 1:
        b -= 1
    return b


# ---------------------------------------------------------------------------
# value expressions
# ---------------------------------------------------------------------------

def _eval_value(ve: ValueExpr, cols, params, promote: bool = False
                ) -> jax.Array:
    """promote=True upcasts integral column leaves to int64 so products in
    aggregation expressions (SUM(price * discount)) can't wrap int32."""
    if isinstance(ve, Col):
        arr = cols[ve.col]
        if ve.dict_param is not None:
            arr = jnp.take(params[ve.dict_param], arr)
        if promote and jnp.issubdtype(arr.dtype, jnp.integer):
            arr = arr.astype(int_acc_dtype())
        return arr
    if isinstance(ve, Lit):
        return params[ve.param]
    if isinstance(ve, MvReduce):
        ids = cols[ve.col]                       # (N, M) int32, pad -1
        present = ids >= 0
        if ve.mode == "count":
            return present.sum(-1).astype(int_acc_dtype())
        vals = ids
        if ve.dict_param is not None:
            vals = jnp.take(params[ve.dict_param], jnp.maximum(ids, 0))
        if promote and jnp.issubdtype(vals.dtype, jnp.integer):
            vals = vals.astype(int_acc_dtype())
        if ve.mode == "sum":
            return jnp.where(present, vals,
                             jnp.zeros((), vals.dtype)).sum(-1)
        sign = 1 if ve.mode == "min" else -1
        filled = jnp.where(present, vals, _extreme(vals.dtype, sign))
        return filled.min(-1) if ve.mode == "min" else filled.max(-1)
    if isinstance(ve, Bin):
        l = _eval_value(ve.lhs, cols, params, promote)
        r = _eval_value(ve.rhs, cols, params, promote)
        if ve.op == "+":
            return l + r
        if ve.op == "-":
            return l - r
        if ve.op == "*":
            return l * r
        if ve.op == "/":
            # SQL division is double division (ArithmeticFunctions.divide)
            return l.astype(float_acc_dtype()) / r.astype(float_acc_dtype())
        if ve.op == "%":
            return l % r
        if ve.op == "//":
            return jnp.floor_divide(l, r)
        raise ValueError(f"unknown binary op {ve.op!r}")
    if isinstance(ve, Func):
        args = [_eval_value(a, cols, params, promote) for a in ve.args]
        return _eval_func(ve.name, args)
    if isinstance(ve, Case):
        out = _eval_value(ve.else_, cols, params, promote)
        # all-literal CASE (no columns, predicates const-folded) folds at
        # bucket 1 and returns a scalar for the consumer to broadcast
        scalar = not cols and not out.ndim
        bucket = (cols[0].shape[0] if cols
                  else (out.shape[0] if out.ndim else 1))
        out = jnp.broadcast_to(out, (bucket,) + out.shape[1:])
        # reverse order: the first matching WHEN must win
        for pred, val in reversed(ve.whens):
            m = jnp.reshape(_eval_pred(pred, cols, params, bucket),
                            (bucket,))
            v = _eval_value(val, cols, params, promote)
            ct = jnp.promote_types(v.dtype, out.dtype)
            out = jnp.where(m, v.astype(ct), out.astype(ct))
        return out[0] if scalar else out
    raise TypeError(f"unknown value expr {ve!r}")


# closed-form device datetime math over epoch millis. Civil-from-days is
# Howard Hinnant's branchless algorithm — pure integer ops that lower to
# XLA unchanged. Semantics MUST match query/functions.py's numpy
# datetime64 host path (floor division handles pre-1970 correctly).
_MS_DAY = 86_400_000


def _civil_ymd(days):
    z = days.astype(jnp.int64) + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097
    yoe = jnp.floor_divide(
        doe - jnp.floor_divide(doe, 1460) + jnp.floor_divide(doe, 36524)
        - jnp.floor_divide(doe, 146096), 365)
    y = yoe + era * 400
    doy = doe - (365 * yoe + jnp.floor_divide(yoe, 4)
                 - jnp.floor_divide(yoe, 100))
    mp = jnp.floor_divide(5 * doy + 2, 153)
    d = doy - jnp.floor_divide(153 * mp + 2, 5) + 1
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    y = jnp.where(m <= 2, y + 1, y)
    return y, m, d


def _days_from_civil(y, m, d):
    y = y - (m <= 2)
    era = jnp.floor_divide(y, 400)
    yoe = y - era * 400
    mp = jnp.where(m > 2, m - 3, m + 9)
    doy = jnp.floor_divide(153 * mp + 2, 5) + d - 1
    doe = yoe * 365 + jnp.floor_divide(yoe, 4)         - jnp.floor_divide(yoe, 100) + doy
    return era * 146097 + doe - 719468


def _eval_func(name: str, args) -> jax.Array:
    a = args[0]
    if name in ("cast_long", "cast_int"):
        if jnp.issubdtype(a.dtype, jnp.floating):
            a = jnp.trunc(a)  # C-style truncation (host cast_value)
        return a.astype(jnp.int64 if name == "cast_long" else jnp.int32)
    if name in ("cast_double", "cast_float"):
        return a.astype(jnp.float64 if name == "cast_double"
                        else jnp.float32)
    if name == "abs":
        return jnp.abs(a)
    if name == "floor":
        return jnp.floor(a.astype(float_acc_dtype()))
    if name == "ceil":
        return jnp.ceil(a.astype(float_acc_dtype()))
    if name == "sqrt":
        return jnp.sqrt(a.astype(float_acc_dtype()))
    if name == "exp":
        return jnp.exp(a.astype(float_acc_dtype()))
    if name == "ln":
        return jnp.log(a.astype(float_acc_dtype()))
    ms = a.astype(jnp.int64)
    days = jnp.floor_divide(ms, _MS_DAY)
    if name == "year":
        return _civil_ymd(days)[0]
    if name == "month":
        return _civil_ymd(days)[1]
    if name == "day":
        return _civil_ymd(days)[2]
    if name == "quarter":
        return jnp.floor_divide(_civil_ymd(days)[1] - 1, 3) + 1
    if name == "dayofweek":
        # 1=Monday..7=Sunday (host _field; epoch day 0 was a Thursday)
        return (days + 3) % 7 + 1
    if name == "hour":
        return jnp.floor_divide(ms, 3_600_000) % 24
    if name == "minute":
        return jnp.floor_divide(ms, 60_000) % 60
    if name == "second":
        return jnp.floor_divide(ms, 1000) % 60
    if name == "millisecond":
        return ms % 1000
    if name.startswith("trunc_"):
        unit = name[6:]
        if unit == "second":
            return jnp.floor_divide(ms, 1000) * 1000
        if unit == "minute":
            return jnp.floor_divide(ms, 60_000) * 60_000
        if unit == "hour":
            return jnp.floor_divide(ms, 3_600_000) * 3_600_000
        if unit == "day":
            return days * _MS_DAY
        if unit == "week":
            # ISO week start (Monday); day 0 = Thursday -> offset 3
            return (jnp.floor_divide(days + 3, 7) * 7 - 3) * _MS_DAY
        y, m, _d = _civil_ymd(days)
        if unit == "month":
            return _days_from_civil(y, m, jnp.ones_like(m)) * _MS_DAY
        if unit == "quarter":
            qm = jnp.floor_divide(m - 1, 3) * 3 + 1
            return _days_from_civil(y, qm, jnp.ones_like(m)) * _MS_DAY
        if unit == "year":
            return _days_from_civil(y, jnp.ones_like(m),
                                    jnp.ones_like(m)) * _MS_DAY
    raise ValueError(f"no device lowering for function {name!r}")


# ---------------------------------------------------------------------------
# predicates -> mask
# ---------------------------------------------------------------------------

def _val_negate(m: jax.Array, arr: jax.Array) -> jax.Array:
    """Value-level predicate negation (!=, NOT IN, NOT BETWEEN): flip the
    per-value mask, keeping MV pad slots (-1) unmatched so the any-
    reduction sees only real values."""
    m = ~m
    if arr.ndim == 2:
        m &= arr >= 0
    return m


def _mv_any(m: jax.Array) -> jax.Array:
    """MV predicate semantics: a row matches when ANY of its values does
    (reference predicate evaluators' applySV vs applyMV split). SV masks
    pass through; (N, M) masks reduce over the value axis. The -1 pad id
    can never equal a dictionary id or fall in an id range, so pad slots
    are inert."""
    return m.any(axis=-1) if m.ndim == 2 else m


def _eval_pred(p: Pred, cols, params, bucket: int) -> jax.Array:
    if isinstance(p, TrueP):
        return jnp.ones((bucket,), dtype=jnp.bool_)
    if isinstance(p, FalseP):
        return jnp.zeros((bucket,), dtype=jnp.bool_)
    if isinstance(p, EqId):
        arr = cols[p.col]
        m = arr == params[p.param]
        return _mv_any(_val_negate(m, arr) if p.negated else m)
    if isinstance(p, IdRange):
        arr = cols[p.col]
        m = jnp.ones(arr.shape, dtype=jnp.bool_)
        if p.lo_param is not None:
            m &= arr >= params[p.lo_param]
        if p.hi_param is not None:
            m &= arr <= params[p.hi_param]
        if p.lo_param is None and arr.ndim == 2:
            # hi-only range on MV: exclude the -1 pad slots (lo-bounded
            # ranges exclude them already: dict-id bounds are >= 0)
            m &= arr >= 0
        return _mv_any(_val_negate(m, arr) if p.negated else m)
    if isinstance(p, InSet):
        arr = cols[p.col]
        vals = params[p.param]  # (n,) sorted ascending
        if p.n > INSET_SEARCH_MIN:
            # sorted membership: binary search beats the O(rows x n)
            # broadcast compare for big IN lists (InPredicateEvaluator
            # analog for raw values; dict columns take InBitmap instead)
            idx = jnp.clip(jnp.searchsorted(vals, arr), 0, p.n - 1)
            m = jnp.take(vals, idx) == arr
        else:
            m = (arr[..., None] == vals[None, :]).any(axis=-1)
        return _mv_any(_val_negate(m, arr) if p.negated else m)
    if isinstance(p, InBitmap):
        arr = cols[p.col]
        tbl = params[p.param]   # (cardinality,) bool presence over ids
        m = jnp.take(tbl, jnp.maximum(arr, 0)) & (arr >= 0)
        return _mv_any(_val_negate(m, arr) if p.negated else m)
    if isinstance(p, Cmp):
        l = _eval_value(p.lhs, cols, params)
        r = params[p.param]
        if p.op == "==":
            return l == r
        if p.op == "!=":
            return l != r
        if p.op == "<":
            return l < r
        if p.op == "<=":
            return l <= r
        if p.op == ">":
            return l > r
        if p.op == ">=":
            return l >= r
        raise ValueError(f"unknown cmp op {p.op!r}")
    if isinstance(p, MaskParam):
        return params[p.param]
    if isinstance(p, And):
        m = _eval_pred(p.children[0], cols, params, bucket)
        for c in p.children[1:]:
            m &= _eval_pred(c, cols, params, bucket)
        return m
    if isinstance(p, Or):
        m = _eval_pred(p.children[0], cols, params, bucket)
        for c in p.children[1:]:
            m |= _eval_pred(c, cols, params, bucket)
        return m
    if isinstance(p, Not):
        return ~_eval_pred(p.child, cols, params, bucket)
    raise TypeError(f"unknown predicate {p!r}")


# ---------------------------------------------------------------------------
# shared helpers
# ---------------------------------------------------------------------------

def _extreme(dtype, sign: int):
    if jnp.issubdtype(dtype, jnp.integer):
        info = jnp.iinfo(dtype)
        return jnp.asarray(info.max if sign > 0 else info.min, dtype=dtype)
    return jnp.asarray(jnp.inf if sign > 0 else -jnp.inf, dtype=dtype)


def _acc_dtype(spec: AggSpec) -> jnp.dtype:
    return int_acc_dtype() if spec.integral else float_acc_dtype()


def _agg_name(i: int, spec: AggSpec) -> str:
    return f"agg{i}_{spec.kind}"


def _int8_dot(lhs: jax.Array, rhs: jax.Array) -> jax.Array:
    """(R, N) int8 x (N, G) int8 -> (R, G) int32 on the MXU."""
    return jax.lax.dot_general(lhs, rhs, (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.int32)


def _limb_rows(vals64: jax.Array, mask: jax.Array, bits: int, signed: bool,
               bucket: int) -> Tuple[List[jax.Array], List[int], int]:
    """Decompose a masked int64 vector into int8 limb rows per sign.

    Returns (rows, signs, base_bits): sum(v) over any subset equals
    sum_l sign_l * 2^(b*(l % nl)) * dot(row_l, subset_indicator), exactly.
    When the planner proved the value non-negative, the negative-sign rows
    are omitted entirely.
    """
    b = _limb_base_bits(bucket)
    nl = -(-min(bits, 63) // b)
    rows: List[jax.Array] = []
    signs: List[int] = []
    lim = jnp.uint64((1 << b) - 1)
    if signed:
        sources = ((1, jnp.where(mask & (vals64 >= 0), vals64, 0)),
                   (-1, jnp.where(mask & (vals64 < 0), -vals64, 0)))
    else:
        sources = ((1, jnp.where(mask, vals64, 0)),)
    for sign, src in sources:
        u = src.astype(jnp.uint64)
        for l in range(nl):
            rows.append(((u >> jnp.uint64(b * l)) & lim).astype(jnp.int8))
            signs.append(sign)
    return rows, signs, b


# ---------------------------------------------------------------------------
# device sketch lowerings (round-5, VERDICT r4 next-step #2): the
# flagship sketch aggregations stop demoting queries to host execution.
# Partial-state formats match the host AggImpl registry exactly, so
# kernel partials merge with host partials in the broker reduce.
# ---------------------------------------------------------------------------

def _device_splitmix64(v: jax.Array) -> jax.Array:
    """aggregations._splitmix64 on device (bit-identical): the shared
    64-bit hash for HLL/theta over raw numeric columns. Floats view
    their float64 bits as int64 first, exactly like the host _hash64."""
    if jnp.issubdtype(v.dtype, jnp.floating):
        v = jax.lax.bitcast_convert_type(v.astype(jnp.float64), jnp.int64)
    x = v.astype(jnp.uint64)
    x = x + jnp.uint64(0x9E3779B97F4A7C15)
    x = (x ^ (x >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    return x ^ (x >> jnp.uint64(31))


def _agg_hashes(spec: AggSpec, cols, params) -> jax.Array:
    """The 64-bit hash stream for a sketch aggregation: dict columns
    gather a precomputed per-id hash table (params[dict_param], host
    _hash64 over the dictionary values — md5 for strings); raw numeric
    columns hash on device."""
    ve = spec.value
    if isinstance(ve, Col) and ve.dict_param is not None:
        return jnp.take(params[ve.dict_param], cols[ve.col])
    return _device_splitmix64(_eval_value(ve, cols, params))


def _sorted_presence(comb: jax.Array, n_slots: int) -> jax.Array:
    """(n_slots,) bool: which slot ids appear in comb (sentinel rows
    carry id == n_slots). Sort + searchsorted boundary diffs — the same
    scatter-free shape as the big-cardinality DISTINCTCOUNT path."""
    s = jnp.sort(comb.astype(jnp.int32))
    edges = jnp.searchsorted(s, jnp.arange(n_slots + 1, dtype=jnp.int32))
    return (edges[1:] - edges[:-1]) > 0


def _hll_slots(spec: AggSpec, cols, params):
    """(slot, r_levels): register index = top log2m hash bits, rank =
    leading zeros of the remainder + 1 (sentinel bit bounds it), slot =
    idx * r_levels + (rank - 1). The single source of the device HLL
    scheme (scalar + grouped); must stay bit-identical to the host
    HllAgg._regs."""
    p = spec.card                    # log2m
    r_levels = 64 - p + 1
    h = _agg_hashes(spec, cols, params)
    idx = (h >> jnp.uint64(64 - p)).astype(jnp.int32)
    rest = (h << jnp.uint64(p)) | jnp.uint64(1 << (p - 1))
    rank = jax.lax.clz(rest).astype(jnp.int32) + 1   # 1 .. R
    return idx * r_levels + (rank - 1), r_levels


def _scalar_hll(name: str, spec: AggSpec, mask, cols, params,
                out: Dict[str, jax.Array]) -> None:
    """DISTINCTCOUNTHLL: (m * R) presence bitmap; extraction maxes over
    the rank axis into the host HllAgg register list."""
    slot, r_levels = _hll_slots(spec, cols, params)
    n_slots = (1 << spec.card) * r_levels
    comb = jnp.where(mask, slot, n_slots)
    out[name + "_present"] = _sorted_presence(comb, n_slots)


def _scalar_theta(name: str, spec: AggSpec, mask, cols, params,
                  out: Dict[str, jax.Array]) -> None:
    """KMV theta sketch: the k smallest DISTINCT hashes. Sort with an
    all-ones sentinel for unmatched rows, flag first occurrences, and
    gather the positions of unique-ranks 1..k (searchsorted over the
    cumulative unique count — no data-dependent shapes)."""
    k = spec.card
    h = _agg_hashes(spec, cols, params)
    sentinel = jnp.uint64(0xFFFFFFFFFFFFFFFF)
    s = jnp.sort(jnp.where(mask, h, sentinel))
    uniq = jnp.concatenate([jnp.ones(1, jnp.bool_), s[1:] != s[:-1]])
    ranks = chunked_cumsum(uniq.astype(jnp.int32)).astype(jnp.int32)
    pos = jnp.searchsorted(ranks, jnp.arange(1, k + 1, dtype=jnp.int32))
    picked = s.at[jnp.minimum(pos, s.shape[0] - 1)].get(mode="clip")
    n_uniq = ranks[-1]
    valid = jnp.arange(k, dtype=jnp.int32) < n_uniq
    # sentinel-valued picks are unmatched-row hashes, not data: mask them
    out[name + "_hashes"] = jnp.where(valid & (picked != sentinel),
                                      picked, sentinel)


def _scalar_percentile(name: str, spec: AggSpec, mask, cols, params,
                       out: Dict[str, jax.Array]) -> None:
    """Mergeable quantile summary: device sort of the matched values,
    equal-count chunk boundaries over the matched prefix, centroid
    means via prefix-sum differences. Output (C,) means + weights maps
    to the host PercentileSketchAgg centroid list."""
    c = spec.card                    # number of centroids
    vals = _eval_value(spec.value, cols, params).astype(float_acc_dtype())
    big = jnp.asarray(jnp.inf, vals.dtype)
    s = jnp.sort(jnp.where(mask, vals, big))    # matched prefix first
    mcount = jnp.sum(mask, dtype=jnp.int32)
    ps = chunked_cumsum(jnp.where(jnp.isfinite(s), s, 0))
    bounds = (jnp.arange(c + 1, dtype=jnp.int64) * mcount) // c
    totals = jnp.where(bounds > 0,
                       ps.at[jnp.maximum(bounds - 1, 0)].get(mode="clip"),
                       0)
    w = (bounds[1:] - bounds[:-1]).astype(jnp.int32)
    sums = totals[1:] - totals[:-1]
    out[name + "_pc_mean"] = jnp.where(
        w > 0, sums / jnp.maximum(w, 1).astype(sums.dtype), 0.0)
    out[name + "_pc_w"] = w


_SKETCH_SCALAR = {"distinct_count_hll": _scalar_hll,
                  "distinct_count_theta": _scalar_theta,
                  "percentile_sketch": _scalar_percentile,
                  # RAW forms share the kernels: RawAgg delegates state
                  # to the inner sketch impl, only finalize serializes
                  "raw_hll": _scalar_hll,
                  "raw_theta": _scalar_theta,
                  "percentile_raw_sketch": _scalar_percentile}

_HLL_KINDS = ("distinct_count_hll", "raw_hll")

# grouped HLL presence bitmap cap: space * 2^log2m * rank_levels slots
# (bool). 2^23 = 8MB per aggregation — plenty for dashboard-shaped
# group-bys; larger spaces keep the host registry.
GROUPED_HLL_LIMIT = 1 << 23


def _group_hll(name: str, spec: AggSpec, mask, keys_s, space: int, cols,
               params, out: Dict[str, jax.Array]) -> None:
    """Grouped DISTINCTCOUNTHLL on device (round-5): one combined key
    (group, register, rank) presence bitmap via the scatter-free
    sort+searchsorted shape. Output (space, m*R) bool rows merge across
    segments/shards by elementwise OR; extraction maxes ranks per group
    into host HllAgg register lists."""
    slot, r_levels = _hll_slots(spec, cols, params)
    m = 1 << spec.card
    comb = jnp.where(mask & (keys_s < space),
                     keys_s * (m * r_levels) + slot,
                     space * m * r_levels)
    pres = _sorted_presence(comb, space * m * r_levels)
    out[name + "_present"] = pres.reshape(space, m * r_levels)


# ---------------------------------------------------------------------------
# scalar (non-group-by) aggregation
# ---------------------------------------------------------------------------

def _scalar_agg(i: int, spec: AggSpec, mask, cols, params,
                out: Dict[str, jax.Array]) -> None:
    name = _agg_name(i, spec)
    cnt_dtype = int_acc_dtype()
    if spec.null_param is not None:
        # enableNullHandling: this aggregation skips null-input rows and
        # reports its own non-null count (extract finalizes all-null
        # SUM/MIN/MAX to null from it)
        mask = mask & ~params[spec.null_param]
        out[name + "_nnz"] = jnp.sum(mask, dtype=cnt_dtype)
    sketch_fn = _SKETCH_SCALAR.get(spec.kind)
    if sketch_fn is not None:
        sketch_fn(name, spec, mask, cols, params, out)
        return
    if spec.kind == "count":
        out[name] = jnp.sum(mask, dtype=cnt_dtype)
        return
    if spec.kind == "distinct_count":
        ids = _eval_value(spec.value, cols, params)
        ids_s = jnp.where(mask, ids, spec.card)  # sentinel past the card
        if spec.card > DISTINCT_ONEHOT_CARD:
            # sort + run boundaries: O(n log n) with no card-sized
            # matmul operand — scales DISTINCTCOUNT to 1M+ cardinality
            # (the partial stays the mergeable (card,) presence bitmap)
            s = jnp.sort(ids_s.astype(jnp.int32))
            edges = jnp.searchsorted(
                s, jnp.arange(spec.card + 1, dtype=jnp.int32))
            out[name + "_present"] = (edges[1:] - edges[:-1]) > 0
            return
        # presence via MXU: counts[c] = mask . one_hot(ids)[., c]; > 0
        oh = jax.nn.one_hot(ids_s, spec.card, dtype=jnp.int8)
        counts = _int8_dot(mask.astype(jnp.int8)[None, :], oh)[0]
        out[name + "_present"] = counts > 0
        return
    vals = _eval_value(spec.value, cols, params, promote=spec.integral)
    acc = _acc_dtype(spec)
    if spec.kind == "sum":
        out[name] = jnp.sum(jnp.where(mask, vals, 0).astype(acc))
    elif spec.kind == "min":
        big = _extreme(acc, +1)
        out[name] = jnp.min(jnp.where(mask, vals.astype(acc), big))
    elif spec.kind == "max":
        small = _extreme(acc, -1)
        out[name] = jnp.max(jnp.where(mask, vals.astype(acc), small))
    elif spec.kind == "avg":
        out[name + "_sum"] = jnp.sum(jnp.where(mask, vals, 0).astype(acc))
        out[name + "_cnt"] = jnp.sum(mask, dtype=cnt_dtype)
    else:
        raise ValueError(f"unknown agg kind {spec.kind!r}")


# ---------------------------------------------------------------------------
# group-by aggregation (one-hot dot_general; scatter on CPU)
# ---------------------------------------------------------------------------

def _group_keys_sentinel(plan: KernelPlan, mask, cols, params):
    """Shared cartesian dict-id key build (DictionaryBasedGroupKeyGenerator
    .java:63 arithmetic) + sentinel application: returns (mask, keys_s)
    with unmatched rows (and out-of-range expression keys) mapped to the
    sentinel key == plan.group_space. Single source of truth for the
    one-hot, scatter, and compact cores."""
    space = plan.group_space
    keys = jnp.zeros(mask.shape, dtype=jnp.int32)
    exprs = plan.key_exprs or (None,) * len(plan.group_keys)
    for (col_idx, card), kexpr in zip(plan.group_keys, exprs):
        ids = cols[col_idx] if kexpr is None \
            else _eval_value(kexpr, cols, params)
        keys = keys * jnp.int32(card) + ids.astype(jnp.int32)
    if plan.key_exprs:
        # expression keys have no dictionary guarantee: clamp strays
        # (pre-epoch garbage etc.) onto the sentinel instead of wrapping
        # into a wrong group
        mask = mask & (keys >= 0) & (keys < space)
    return mask, jnp.where(mask, keys, space)


def _scatter_group(plan: KernelPlan, mask, keys_s, cols, params, space: int,
                   out: Dict[str, jax.Array]) -> None:
    """CPU-fast group aggregation core: jax.ops.segment_* over sentinel
    keys (sentinel = space, sliced off). Output contract is identical to
    the one-hot formulation — dense (space,) arrays — so extraction and
    broker reduce are oblivious to which core ran."""
    nseg = space + 1
    cnt_dtype = int_acc_dtype()
    counts = jax.ops.segment_sum(mask.astype(cnt_dtype), keys_s,
                                 num_segments=nseg)[:space]
    out["group_count"] = counts
    for i, spec in enumerate(plan.aggs):
        name = _agg_name(i, spec)
        if spec.kind == "count":
            continue
        if spec.kind in _HLL_KINDS:
            # the grouped HLL presence shape is backend-agnostic
            _group_hll(name, spec, mask, keys_s, space, cols, params, out)
            continue
        if spec.kind == "distinct_count":
            ids = _eval_value(spec.value, cols, params)
            comb = jnp.where(
                mask, keys_s.astype(jnp.int64) * spec.card + ids,
                jnp.int64(space) * spec.card)
            pres = jax.ops.segment_sum(
                jnp.ones(comb.shape, dtype=jnp.int32), comb,
                num_segments=space * spec.card + 1)[:space * spec.card]
            out[name + "_present"] = pres.reshape(space, spec.card) > 0
            continue
        vals = _eval_value(spec.value, cols, params, promote=spec.integral)
        acc = _acc_dtype(spec)
        if spec.kind in ("sum", "avg"):
            s = jax.ops.segment_sum(
                jnp.where(mask, vals, 0).astype(acc), keys_s,
                num_segments=nseg)[:space]
            if spec.kind == "avg":
                out[name + "_sum"] = s
                out[name + "_cnt"] = counts
            else:
                out[name] = s
        elif spec.kind in ("min", "max"):
            sign = +1 if spec.kind == "min" else -1
            segf = (jax.ops.segment_min if spec.kind == "min"
                    else jax.ops.segment_max)
            filled = jnp.where(mask, vals.astype(acc), _extreme(acc, sign))
            out[name] = segf(filled, keys_s, num_segments=nseg)[:space]
        else:
            raise ValueError(f"unknown agg kind {spec.kind!r}")


def _group_aggs(plan: KernelPlan, mask, cols, params, bucket: int,
                out: Dict[str, jax.Array], scatter: bool = False) -> None:
    space = plan.group_space
    mask, keys_s = _group_keys_sentinel(plan, mask, cols, params)
    if scatter:
        _scatter_group(plan, mask, keys_s, cols, params, space, out)
        return
    oh8 = jax.nn.one_hot(keys_s, space, dtype=jnp.int8)

    # one int8 limb matrix serves counts + every exact integer sum
    int_rows: List[jax.Array] = [mask.astype(jnp.int8)]  # row 0: counts
    int_row_meta: List[Tuple[int, List[int], int]] = []  # (start, signs, b)

    acc_f = float_acc_dtype()
    float_rows: List[jax.Array] = []
    float_row_names: List[str] = []

    deferred: List[Tuple[int, AggSpec, str]] = []

    for i, spec in enumerate(plan.aggs):
        name = _agg_name(i, spec)
        kind = spec.kind
        if kind == "count":
            continue  # served by the shared count row
        if kind in _HLL_KINDS:
            deferred.append((i, spec, "hll"))
            continue
        if kind in ("sum", "avg") and spec.integral:
            vals = _eval_value(spec.value, cols, params, promote=True)
            rows, signs, b = _limb_rows(vals, mask, spec.bits, spec.signed,
                                        bucket)
            int_row_meta.append((len(int_rows), signs, b))
            int_rows.extend(rows)
            deferred.append((i, spec, "int_sum"))
        elif kind in ("sum", "avg"):
            vals = _eval_value(spec.value, cols, params)
            float_rows.append(jnp.where(mask, vals, 0).astype(acc_f))
            float_row_names.append(name)
            deferred.append((i, spec, "float_sum"))
        elif kind in ("min", "max"):
            deferred.append((i, spec, "minmax"))
        elif kind == "distinct_count":
            deferred.append((i, spec, "distinct"))
        else:
            raise ValueError(f"unknown agg kind {kind!r}")

    L = jnp.stack(int_rows)                      # (R, bucket) int8
    S = _int8_dot(L, oh8)                        # (R, space) int32
    counts = S[0].astype(int_acc_dtype())
    out["group_count"] = counts

    if float_rows:
        ohf = jax.nn.one_hot(keys_s, space, dtype=acc_f)
        F = jax.lax.dot_general(jnp.stack(float_rows), ohf,
                                (((1,), (0,)), ((), ())),
                                preferred_element_type=acc_f)

    meta_iter = iter(int_row_meta)
    float_idx = 0
    for i, spec, how in deferred:
        name = _agg_name(i, spec)
        if how == "int_sum":
            start, signs, b = next(meta_iter)
            total = jnp.zeros((space,), dtype=jnp.int64)
            nl = signs.count(1)  # limbs per sign group (positive run first)
            for j, sign in enumerate(signs):
                w = jnp.int64(1) << jnp.int64(b * (j % nl))
                total = total + jnp.int64(sign) * w * \
                    S[start + j].astype(jnp.int64)
            if spec.kind == "avg":
                out[name + "_sum"] = total
                out[name + "_cnt"] = counts
            else:
                out[name] = total
        elif how == "float_sum":
            row = F[float_idx]
            float_idx += 1
            if spec.kind == "avg":
                out[name + "_sum"] = row
                out[name + "_cnt"] = counts
            else:
                out[name] = row
        elif how == "hll":
            _group_hll(name, spec, mask, keys_s, space, cols, params, out)
        elif how == "minmax":
            _group_minmax(i, spec, mask, keys_s, space, cols, params, out)
        elif how == "distinct":
            ids = _eval_value(spec.value, cols, params)
            ids_s = jnp.where(mask, ids, spec.card)
            oh_ids = jax.nn.one_hot(ids_s, spec.card, dtype=jnp.int8)
            pair_counts = jax.lax.dot_general(
                jnp.swapaxes(oh8, 0, 1), oh_ids, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)  # (space, card)
            out[name + "_present"] = pair_counts > 0


def _group_minmax(i: int, spec: AggSpec, mask, keys, space: int, cols,
                  params, out: Dict[str, jax.Array]) -> None:
    """No matmul form exists for min/max. space <= MINMAX_UNROLL_GROUPS:
    unrolled masked reduces (still one fused pass per group on the VPU);
    larger spaces use segment ops (fast on CPU; the planner hosts them on
    backends with slow scatter)."""
    name = _agg_name(i, spec)
    vals = _eval_value(spec.value, cols, params, promote=spec.integral)
    acc = _acc_dtype(spec)
    sign = +1 if spec.kind == "min" else -1
    sentinel = _extreme(acc, sign)
    red = jnp.min if spec.kind == "min" else jnp.max
    if space <= MINMAX_UNROLL_GROUPS:
        outs = [red(jnp.where(mask & (keys == g), vals.astype(acc), sentinel))
                for g in range(space)]
        out[name] = jnp.stack(outs)
    else:
        seg = (jax.ops.segment_min if spec.kind == "min"
               else jax.ops.segment_max)
        out[name] = seg(jnp.where(mask, vals.astype(acc), sentinel),
                        keys, num_segments=space)


# ---------------------------------------------------------------------------
# compacted group-by (Pallas compaction -> aggregate matched rows only)
# ---------------------------------------------------------------------------

# factorized one-hot matmul above this space would still be cheap, but the
# (M, space/128) int8 operand materialization starts to dominate; the sort
# path takes over (cap: searchsorted probes scale with space)
FACTORIZED_GROUP_LIMIT = 1 << 14
# sort path ceiling: cost is one sort of the *matched* rows + (space+1)
# searchsorted probes + dense (space,) outputs — 2^22 keeps outputs and
# probes cheap while clearing MAX_DENSE_GROUPS (so spaces in (2^21, 2^22]
# that used to fall to host numpy now stay on device; SSB Q4.3's
# 7 x 250 x 1000 = 1.75M space lands here)
COMPACT_GROUP_LIMIT = 1 << 22


def _value_col_indices(ve) -> set:
    """EVERY stored-column index a value expression references —
    including through Func args and Case branches (whose WHEN
    predicates can reference columns too). Completeness matters: the
    segmented kernel picks its synthetic segment-index column past the
    max referenced index, and the ragged batcher's cube eligibility
    turns every predicate column into a cube dimension — a missed
    column would silently corrupt either."""
    if isinstance(ve, (Col, MvReduce)):
        return {ve.col}
    if isinstance(ve, Bin):
        return _value_col_indices(ve.lhs) | _value_col_indices(ve.rhs)
    if isinstance(ve, Func):
        return set().union(set(), *[_value_col_indices(a)
                                    for a in ve.args])
    if isinstance(ve, Case):
        out = _value_col_indices(ve.else_)
        for pred, val in ve.whens:
            out |= _pred_col_indices(pred) | _value_col_indices(val)
        return out
    return set()


def chunked_cumsum(x: jax.Array, chunk: int = 1 << 13) -> jax.Array:
    """Two-level cumsum: XLA's monolithic reduce-window lowering blows
    scoped VMEM beyond ~16M elements on TPU; chunking keeps windows small
    and is faster besides."""
    n = x.shape[0]
    if n <= chunk or n % chunk != 0:
        return jnp.cumsum(x)
    m = n // chunk
    x2 = x.reshape(m, chunk)
    within = jnp.cumsum(x2, axis=1)
    carry = jnp.concatenate(
        [jnp.zeros(1, x.dtype), jnp.cumsum(within[:, -1])[:-1]])
    return (within + carry[:, None]).reshape(n)


_IMIN64 = -(1 << 63)
_IMIN32 = -(1 << 31)


def _to_orderable64(v: jax.Array, integral: bool, platform: str = None):
    """Order-preserving map to int64. Integers pass through (exact); floats
    map via the classic sign-flip bijection on their bit patterns:
    non-negatives keep their bits, negatives reverse order and land below
    (imin + ~bits). f64 bit views only exist on backends whose x64 rewriter
    can lower them (CPU — compact.f64_bitcast_ok); everywhere else floats
    take the 32-bit bijection widened to int64, so no f64 op is ever
    emitted (TPU crashes on f64 bitcast-convert at compile time).
    Returns (orderable, mode) with mode consumed by _from_orderable64."""
    from .compact import f64_bitcast_ok

    if integral:
        return v.astype(jnp.int64), "int"
    if v.dtype == jnp.float64 and f64_bitcast_ok(platform):
        bits = jax.lax.bitcast_convert_type(v, jnp.int64)
        o = jnp.where(bits >= 0, bits,
                      jnp.int64(_IMIN64) + jnp.bitwise_not(bits))
        return o, "f64"
    bits = jax.lax.bitcast_convert_type(v.astype(jnp.float32), jnp.int32)
    o32 = jnp.where(bits >= 0, bits,
                    jnp.int32(_IMIN32) + jnp.bitwise_not(bits))
    return o32.astype(jnp.int64), "f32"


def _from_orderable64(o: jax.Array, mode: str, acc_f) -> jax.Array:
    if mode == "int":
        return o
    if mode == "f64":
        neg_bits = jnp.bitwise_not(o - jnp.int64(_IMIN64))
        bits = jnp.where(o >= 0, o, neg_bits)
        return jax.lax.bitcast_convert_type(bits, jnp.float64).astype(acc_f)
    o32 = o.astype(jnp.int32)
    neg_bits = jnp.bitwise_not(o32 - jnp.int32(_IMIN32))
    bits = jnp.where(o32 >= 0, o32, neg_bits)
    return jax.lax.bitcast_convert_type(bits, jnp.float32).astype(acc_f)


def _to_orderable(v: jax.Array, integral: bool, platform: str = None):
    """_to_orderable64 at the narrowest exact carrier width: 32-bit-or-
    smaller integers and f32-bijection orderables stay int32 so the
    compaction kernel moves half the bytes and the sort compares narrower
    keys. _from_orderable64 accepts either width per mode."""
    if integral and jnp.issubdtype(v.dtype, jnp.integer) \
            and v.dtype.itemsize <= 4:
        return v.astype(jnp.int32), "int"
    o, mode = _to_orderable64(v, integral, platform)
    if mode == "f32":
        return o.astype(jnp.int32), mode
    return o, mode


# post-aggregation size ladder: below this static capacity (elements) the
# sort/matmul cost is trivial and the extra lax.switch branches only cost
# compile time (the CPU test suite lives here). Env override for tests.
def _ladder_min_elems() -> int:
    # host env read resolved at jit-cache-key time, never under trace
    return int(os.environ.get("PINOT_COMPACT_LADDER_MIN",  # jaxlint: ok host-sync
                              1 << 22))


def _two_pass_mode() -> str:
    """'auto' (second compaction pass only after the loose Pallas pass),
    '1' force (tests exercise the wiring on the XLA fallback), '0' off."""
    return os.environ.get("PINOT_COMPACT_TWO_PASS", "auto")


def _post_sizes(cap_rows: int, step: int = 8,
                min_rows: int = 512) -> List[int]:
    """Geometric /step ladder of slot-row sizes up to the full capacity.
    The MXU post keeps the coarse /8 ladder (each branch traces a full
    sort/matmul program); the scatter post uses /4 down to 8 slot rows —
    its segment-op branches are cheap to trace and the finer ladder keeps
    the scatter's input within ~4x of the matched rows."""
    sizes = [cap_rows]
    while sizes[-1] // step >= min_rows:
        sizes.append(sizes[-1] // step)
    return sorted(set(sizes))


def _ladder_switch(sizes: List[int], n_valid, make_branch,
                   extra_branch=None, extra_when=None):
    """Dispatch the post-aggregation at the smallest ladder size whose
    element capacity covers n_valid. extra_branch (with its extra_when
    device predicate) appends an override branch — the two-pass path's
    pass-1 fallback on pass-2 overflow."""
    from .compact import LANES

    thresholds = jnp.asarray([s * LANES for s in sizes[:-1]],
                             dtype=jnp.int32)
    idx = jnp.sum((thresholds < n_valid).astype(jnp.int32)) \
        if sizes[:-1] else jnp.int32(0)
    branches = [make_branch(s) for s in sizes]
    if extra_branch is not None:
        idx = jnp.where(extra_when, jnp.int32(len(sizes)), idx)
        branches.append(extra_branch)
    if len(branches) == 1:
        return branches[0]()
    return jax.lax.switch(idx, branches)


def _payload_columns(plan: KernelPlan, mask, cols, params,
                     platform: str = None):
    """Fused aggregation-input materialization (round-6 tentpole).

    Every aggregation input is evaluated ONCE over the full segment,
    masked, and narrowed to its smallest exact carrier dtype BEFORE
    compaction, so the compaction kernel moves [key] + payloads instead
    of gathering every referenced source column, and the post-aggregation
    never re-evaluates value expressions over capacity-sized arrays.
    A 2-key GROUP BY with SUM(a - b) compacts 2 columns (key + int32
    payload) where the round-5 path compacted 4 and re-ran the key
    arithmetic and subtraction over the full static capacity.

    Returns (arrays, sum_jobs, mm_jobs, ord_modes):
      arrays    tuple of (bucket,) payload columns;
      sum_jobs  [(agg_idx, spec, slot)] for sum/avg — slots deduped by
                (value expression, integral), so SUM(x) + AVG(x) share
                one compacted column;
      mm_jobs   [(agg_idx, spec, slot)] for min/max (orderable slots
                deduped by value expression);
      ord_modes {slot: mode} consumed by _from_orderable64.
    """
    acc_f = float_acc_dtype()
    arrays: List[jax.Array] = []
    sum_slots: Dict[Tuple, int] = {}
    ord_slots: Dict[object, int] = {}
    ord_modes: Dict[int, str] = {}
    sum_jobs: List[Tuple[int, AggSpec, int]] = []
    mm_jobs: List[Tuple[int, AggSpec, int]] = []
    for i, spec in enumerate(plan.aggs):
        if spec.kind == "count":
            continue
        if spec.kind in ("sum", "avg"):
            key = (spec.value, spec.integral)
            slot = sum_slots.get(key)
            if slot is None:
                if spec.integral:
                    v = _eval_value(spec.value, cols, params, promote=True)
                    # the planner's interval arithmetic bounds |v| by
                    # spec.bits: values under 2^31 ride int32 through the
                    # compaction (half the bytes, no 64-bit split)
                    dt = sum_carrier_dtype(spec.bits)
                    if dt is None:
                        # pre-fix this truncated silently through
                        # int_acc_dtype(); exactness is unprovable here
                        raise ValueError(
                            f"no exact {spec.bits}-bit sum carrier with "
                            "jax_enable_x64 off; plan the host path or "
                            "demote the aggregation to float")
                    v = jnp.where(mask, v, 0).astype(dt)
                else:
                    v = _eval_value(spec.value, cols, params).astype(acc_f)
                    v = jnp.where(mask, v, jnp.zeros((), acc_f))
                slot = len(arrays)
                sum_slots[key] = slot
                arrays.append(v)
            sum_jobs.append((i, spec, slot))
        elif spec.kind in ("min", "max"):
            slot = ord_slots.get(spec.value)
            if slot is None:
                v = _eval_value(spec.value, cols, params)
                integral = spec.integral and \
                    jnp.issubdtype(v.dtype, jnp.integer)
                o, mode = _to_orderable(v, integral, platform)
                slot = len(arrays)
                ord_slots[spec.value] = slot
                ord_modes[slot] = mode
                arrays.append(o)
            mm_jobs.append((i, spec, slot))
        else:
            raise ValueError(
                f"compact group-by cannot lower {spec.kind!r}")
    return tuple(arrays), sum_jobs, mm_jobs, ord_modes


def _compact_group_aggs(plan: KernelPlan, mask, cols, params, bucket: int,
                        slots_cap: int, out: Dict[str, jax.Array],
                        platform: str = None,
                        scatter: bool = False,
                        two_pass_mode: Optional[str] = None,
                        ladder_min: Optional[int] = None,
                        xfer_sparse: bool = False) -> None:
    """Group aggregation over compacted matched rows — the fused
    compaction -> sort -> segment-sum ladder (round-6 tentpole rewrite).

    Reference parity: DocIdSetOperator (docId materialization) +
    DefaultGroupByExecutor, reshaped for the TPU. One fused prefix
    evaluates the predicate mask, the cartesian dict-id group key, and
    every aggregation payload (_payload_columns) in a single pass over
    the segment; ONE compaction call (ops/compact.py) then concentrates
    [key] + payloads. The post-aggregation core is picked per plan:

    - scatter (CPU execution, cpu_scatter_default): jax.ops.segment_*
      over the compacted prefix — the exact XLA compaction plus the
      cost-model-tightened capacity mean the scatter touches ~matched
      rows, not the static capacity;
    - sorted (_needs_sort: min/max present or space > the factorized
      limit): ONE lexicographic key sort carries every sum payload and
      the first min/max orderable; all aggregations read one
      searchsorted edges array (sort once, aggregate many);
    - factorized (small spaces, sums only): two-sided one-hot matmul on
      the MXU, fed by the precomputed payload limbs.

    Outputs are the same dense (space,) arrays as the dense strategy, so
    extraction and broker reduce are strategy-agnostic.

    Two refinements keep the post-aggregation cost proportional to the
    rows actually matched instead of the static capacity:

    - a SECOND compaction pass over the first pass's output (Pallas path
      only by default): lane-wise compaction is loose — every 32-row
      subtile with any match advances a full slot row, so a sparse mask
      inflates 10-45x; re-compacting the already-small output costs a
      fraction of pass 1. Pass-2 overflow falls back to the pass-1
      arrays in-kernel (a lax.switch branch), never to a host retry;
    - a lax.switch SIZE LADDER (now on every core, including scatter):
      the post-aggregation is traced at a few static sizes (slot rows,
      /8 apart) and the branch picked on device by the compacted row
      count, so the post sees ~the matched rows even on the
      full-capacity overflow retry.
    """
    from .compact import LANES, _use_pallas, compact

    space = plan.group_space
    needs_sort = _needs_sort(plan)
    mask, keys_s = _group_keys_sentinel(plan, mask, cols, params)
    payloads, sum_jobs, mm_jobs, ord_modes = _payload_columns(
        plan, mask, cols, params, platform)
    valid, comp, n_valid, matched, overflow = compact(
        mask, (keys_s,) + payloads, slots_cap, platform)
    out["overflow"] = overflow
    out["matched"] = matched.astype(int_acc_dtype())

    def post(valid_a, comp_t, rows: int) -> Dict[str, jax.Array]:
        v = valid_a[:rows]
        # compacted garbage slots were zeroed; re-sentinel their keys so
        # they can never pollute group 0 (payloads are already 0 there)
        k = jnp.where(v, comp_t[0][:rows], jnp.int32(space))
        pls = tuple(c[:rows] for c in comp_t[1:])
        o: Dict[str, jax.Array] = {}
        if scatter:
            _scatter_post(sum_jobs, mm_jobs, ord_modes, k, v, pls,
                          space, o)
        elif needs_sort and xfer_sparse:
            # q4.3 sparse-output contract: (group_idx, value) pairs
            # straight from the one sorted pass — no dense (space,)
            # arrays are ever materialized for big spaces
            _sorted_post_sparse(sum_jobs, mm_jobs, ord_modes, k, v, pls,
                                space, GROUP_XFER_CAP, o)
        elif needs_sort:
            _sorted_post(sum_jobs, mm_jobs, ord_modes, k, v, pls,
                         space, o)
        else:
            _factorized_post(sum_jobs, k, v, pls, space, rows, o)
        return o

    cap_rows = valid.shape[0]          # slots_cap * LANES elements
    mode = two_pass_mode if two_pass_mode is not None else _two_pass_mode()
    min_elems = ladder_min if ladder_min is not None else _ladder_min_elems()
    two_pass = (not scatter) and (
        mode == "1"
        or (mode == "auto" and _use_pallas(bucket, platform)
            and cap_rows >= min_elems))
    if two_pass:
        cap2 = max(slots_cap // 4, 512)
        valid2, comp2, n_valid2, _m2, of2 = compact(
            valid, comp, cap2, platform)
        out.update(_ladder_switch(
            _post_sizes(valid2.shape[0] // LANES), n_valid2,
            lambda s: functools.partial(post, valid2, comp2, s * LANES),
            # pass-2 overflow: aggregate the (complete) pass-1 arrays
            extra_branch=functools.partial(post, valid, comp, cap_rows),
            extra_when=of2 > 0))
        return

    if scatter:
        # the scatter ladder is always on: its branches trace in
        # milliseconds and the full-capacity overflow retry depends on it
        # to keep the segment ops near the matched count
        sizes = _post_sizes(cap_rows // LANES, step=4, min_rows=8)
    else:
        sizes = (_post_sizes(cap_rows // LANES) if cap_rows >= min_elems
                 else [cap_rows // LANES])
    out.update(_ladder_switch(
        sizes, n_valid,
        lambda s: functools.partial(post, valid, comp, s * LANES)))


def _scatter_post(sum_jobs, mm_jobs, ord_modes, keys, valid, payloads,
                  space: int, out: Dict[str, jax.Array]) -> None:
    """CPU scatter core over the compacted prefix: one jax.ops.segment_sum
    per unique payload slot (counts ride the valid column), segment
    min/max on the orderables. Garbage slots carry the sentinel key ==
    space; the sentinel segment is sliced off."""
    nseg = space + 1
    cnt_dtype = int_acc_dtype()
    acc_f = float_acc_dtype()
    counts = jax.ops.segment_sum(valid.astype(cnt_dtype), keys,
                                 num_segments=nseg)[:space]
    out["group_count"] = counts
    done: Dict[int, jax.Array] = {}
    for i, spec, slot in sum_jobs:
        name = _agg_name(i, spec)
        s = done.get(slot)
        if s is None:
            acc = int_acc_dtype() if spec.integral else acc_f
            s = jax.ops.segment_sum(payloads[slot].astype(acc), keys,
                                    num_segments=nseg)[:space]
            done[slot] = s
        if spec.kind == "avg":
            out[name + "_sum"] = s
            out[name + "_cnt"] = counts
        else:
            out[name] = s
    for i, spec, slot in mm_jobs:
        name = _agg_name(i, spec)
        o = payloads[slot]
        sign = +1 if spec.kind == "min" else -1
        filled = jnp.where(valid, o, _extreme(o.dtype, sign))
        segf = (jax.ops.segment_min if spec.kind == "min"
                else jax.ops.segment_max)
        picked = segf(filled, keys, num_segments=nseg)[:space]
        acc = _acc_dtype(spec)
        vals = _from_orderable64(picked, ord_modes[slot], acc_f)
        out[name] = jnp.where(counts > 0, vals.astype(acc),
                              _extreme(acc, sign))


def _factorized_post(sum_jobs, keys, valid, payloads, space, m, out):
    """sums[hi, lo] = (oh_hi . limb)^T @ oh_lo — two fused one-hot operands
    keep the contraction on the MXU without materializing (M, space).
    Inputs are the precompacted payload columns (_payload_columns), so no
    value expression is ever re-evaluated here.

    The contraction runs as a lax.scan over fixed-size row blocks: the
    (block, n_hi) x (block, 128) one-hot operands are rebuilt per block and
    accumulated into the (rows, n_hi, 128) result, so peak memory is
    independent of M. (Unblocked, XLA materialized the (rows, M, n_hi)
    stacked operand — 34 GB at full_slots_cap on a 134M-row segment.)"""
    g_pad = -(-(space + 1) // 128) * 128
    n_hi = g_pad // 128
    hi = keys >> jnp.int32(7)
    lo = keys & jnp.int32(127)

    cnt_dtype = int_acc_dtype()
    int_rows: List[jax.Array] = [valid.astype(jnp.int8)]
    int_slot_meta: Dict[int, Tuple[int, List[int], int]] = {}
    float_slot_idx: Dict[int, int] = {}
    frows: List[jax.Array] = []
    deferred: List[Tuple[int, AggSpec, str, int]] = []

    for i, spec, slot in sum_jobs:
        if spec.integral:
            if slot not in int_slot_meta:
                rows, signs, b = _limb_rows(payloads[slot], valid,
                                            spec.bits, spec.signed, m)
                int_slot_meta[slot] = (len(int_rows), signs, b)
                int_rows.extend(rows)
            deferred.append((i, spec, "int_sum", slot))
        else:
            if slot not in float_slot_idx:
                float_slot_idx[slot] = len(frows)
                frows.append(payloads[slot])   # already masked acc_f
            deferred.append((i, spec, "float_sum", slot))

    acc_f = float_acc_dtype()

    # block size: keep the per-block (R, MB, n_hi) int8 operand ~<=128MB
    n_int = len(int_rows)
    budget = max((128 << 20) // max(n_int * n_hi, 1), 1 << 15)
    mb = max(1 << 15, min(1 << 21, 1 << (budget.bit_length() - 1)))
    n_b = -(-m // mb)
    pad = n_b * mb - m

    def blocked(x, fill):
        if pad:
            x = jnp.concatenate(
                [x, jnp.full((pad,), fill, dtype=x.dtype)])
        return x.reshape(n_b, mb)

    hi_b = blocked(hi, space >> 7)     # sentinel key -> trimmed pad region
    lo_b = blocked(lo, space & 127)
    ir_b = jnp.stack([blocked(r, 0) for r in int_rows], axis=1)
    xs = (hi_b, lo_b, ir_b)
    fr_b = None
    if frows:
        fr_b = jnp.stack([blocked(r, 0) for r in frows], axis=1)
        xs = xs + (fr_b,)

    S0 = jnp.zeros((n_int, n_hi, 128), jnp.int32)
    F0 = jnp.zeros((len(frows), n_hi, 128), acc_f)

    def body(carry, xb):
        S, F = carry
        hb, lb, irb = xb[:3]
        oh_hi = jax.nn.one_hot(hb, n_hi, dtype=jnp.int8)   # (MB, n_hi)
        oh_lo = jax.nn.one_hot(lb, 128, dtype=jnp.int8)    # (MB, 128)
        lhs = oh_hi[None, :, :] * irb[:, :, None]          # (R, MB, n_hi)
        S = S + jax.lax.dot_general(
            lhs, oh_lo, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        if frows:
            flhs = oh_hi.astype(acc_f)[None, :, :] * xb[3][:, :, None]
            F = F + jax.lax.dot_general(
                flhs, oh_lo.astype(acc_f), (((1,), (0,)), ((), ())),
                precision=jax.lax.Precision.HIGHEST,
                preferred_element_type=acc_f)
        return (S, F), None

    if n_b == 1:  # small capacity: no scan, cheaper to compile (tests/CPU)
        (S, F), _ = body((S0, F0), tuple(x[0] for x in xs))
    else:
        (S, F), _ = jax.lax.scan(body, (S0, F0), xs)
    flat = S.reshape(n_int, g_pad)[:, :space]
    counts = flat[0].astype(cnt_dtype)
    out["group_count"] = counts
    if frows:
        Fflat = F.reshape(len(frows), g_pad)[:, :space]

    int_totals: Dict[int, jax.Array] = {}
    for i, spec, how, slot in deferred:
        name = _agg_name(i, spec)
        if how == "int_sum":
            total = int_totals.get(slot)
            if total is None:
                start, signs, b = int_slot_meta[slot]
                total = jnp.zeros((space,), dtype=jnp.int64)
                nl = signs.count(1)
                for j, sign in enumerate(signs):
                    w = jnp.int64(1) << jnp.int64(b * (j % nl))
                    total = total + jnp.int64(sign) * w * \
                        flat[start + j].astype(jnp.int64)
                int_totals[slot] = total
            if spec.kind == "avg":
                out[name + "_sum"] = total
                out[name + "_cnt"] = counts
            else:
                out[name] = total
        else:
            row = Fflat[float_slot_idx[slot]]
            if spec.kind == "avg":
                out[name + "_sum"] = row
                out[name + "_cnt"] = counts
            else:
                out[name] = row


def _needs_sort(plan: KernelPlan) -> bool:
    """Whether the compact strategy takes the sort path (vs factorized
    one-hot matmuls). Shared by _compact_group_aggs (path selection) and
    build_kernel (capacity selection) so the two can never disagree."""
    return (plan.group_space > FACTORIZED_GROUP_LIMIT
            or any(s.kind in ("min", "max") for s in plan.aggs))


def _sorted_post_common(sum_jobs, mm_jobs, keys, payloads, extra=()):
    """The slot dedup + ONE lexicographic sort both sorted posts share
    (dense and sparse must never diverge here — digest parity between
    them is pinned by test). Returns (sorted_ops, sum_slots, mm_slots,
    base): sum payload slots deduped in operand order, min/max
    orderable slots likewise (the first rides the sort as the
    secondary key), and ``base`` indexing the first ``extra`` operand
    (or the first sum payload when no extras ride along)."""
    sum_slots: List[int] = []        # unique payload slots, operand order
    for _i, _s, slot in sum_jobs:
        if slot not in sum_slots:
            sum_slots.append(slot)
    mm_slots: List[int] = []
    for _i, _s, slot in mm_jobs:
        if slot not in mm_slots:
            mm_slots.append(slot)
    first_o = [payloads[mm_slots[0]]] if mm_slots else []
    operands = [keys] + first_o + list(extra) \
        + [payloads[s] for s in sum_slots]
    sorted_ops = jax.lax.sort(operands, num_keys=1 + len(first_o))
    return sorted_ops, sum_slots, mm_slots, 1 + len(first_o)


def _sorted_orderables(keys, payloads, mm_slots, sorted_ops
                       ) -> Dict[int, jax.Array]:
    """Per-slot key-sorted orderables: the first slot already rode the
    main sort as the secondary key; each additional distinct min/max
    expression needs one more (key, orderable) sort of the prefix."""
    out: Dict[int, jax.Array] = {}
    for j, slot in enumerate(mm_slots):
        out[slot] = sorted_ops[1] if j == 0 else jax.lax.sort(
            [keys, payloads[slot]], num_keys=2)[1]
    return out


def _sorted_post(sum_jobs, mm_jobs, ord_modes, keys, valid, payloads,
                 space: int, out: Dict[str, jax.Array]) -> None:
    """Sort-once, aggregate-many: ONE lexicographic sort of the compacted
    prefix carries every sum payload AND the first min/max orderable as
    the secondary key (group min = first element of the run, max = last);
    every aggregation then reads the single searchsorted edges array.
    Additional *distinct* min/max value expressions each need one more
    (key, orderable) sort over the same prefix. Payloads arrive
    precomputed (_payload_columns) — no value expression evaluates here."""
    acc_f = float_acc_dtype()
    cnt_dtype = int_acc_dtype()

    sorted_ops, sum_slots, mm_slots, base = _sorted_post_common(
        sum_jobs, mm_jobs, keys, payloads,
        extra=(valid.astype(jnp.int32),))
    sk = sorted_ops[0]
    edges = jnp.searchsorted(sk, jnp.arange(space + 1, dtype=jnp.int32))

    def group_sums(sorted_vals, dtype):
        cs = chunked_cumsum(sorted_vals.astype(dtype))
        tot = jnp.concatenate([jnp.zeros(1, dtype), cs])
        return tot[edges[1:]] - tot[edges[:-1]]

    counts = group_sums(sorted_ops[base], cnt_dtype).astype(cnt_dtype)
    out["group_count"] = counts

    sums_done: Dict[Tuple[int, bool], jax.Array] = {}
    for i, spec, slot in sum_jobs:
        name = _agg_name(i, spec)
        s = sums_done.get((slot, spec.integral))
        if s is None:
            sv = sorted_ops[base + 1 + sum_slots.index(slot)]
            s = group_sums(sv, int_acc_dtype() if spec.integral else acc_f)
            sums_done[(slot, spec.integral)] = s
        if spec.kind == "avg":
            out[name + "_sum"] = s
            out[name + "_cnt"] = counts
        else:
            out[name] = s

    sorted_orderable = _sorted_orderables(keys, payloads, mm_slots,
                                          sorted_ops)
    n_rows = keys.shape[0]
    pos_min = jnp.minimum(edges[:-1], n_rows - 1)
    pos_max = jnp.clip(edges[1:] - 1, 0, n_rows - 1)
    for i, spec, slot in mm_jobs:
        name = _agg_name(i, spec)
        pos = pos_min if spec.kind == "min" else pos_max
        picked = sorted_orderable[slot].at[pos].get(mode="clip")
        acc = _acc_dtype(spec)
        vals = _from_orderable64(picked, ord_modes[slot], acc_f).astype(acc)
        # an empty group's edges collapse and pick a neighboring run's
        # row; neutralize to the extreme so cross-device pmin/pmax and
        # partial merges stay correct (dense _group_minmax convention)
        out[name] = jnp.where(
            counts > 0, vals,
            _extreme(acc, 1 if spec.kind == "min" else -1))


def _sorted_post_sparse(sum_jobs, mm_jobs, ord_modes, keys, valid, payloads,
                        space: int, cap: int,
                        out: Dict[str, jax.Array]) -> None:
    """Sparse sorted post (q4.3 contract): emit (group_idx, value) pairs
    straight from the ONE lexicographic sort instead of densifying to
    (space,) arrays and compacting them afterwards.

    At SSB q4.3's 1.75M group space the dense outputs dominated the
    kernel (space-sized searchsorted probes + several (space,) arrays
    for ~13 live groups). Here run boundaries come from the sorted
    keys themselves: first-occurrence flags -> unique ranks -> one
    searchsorted of cap probes over the rank vector, so every output
    is (cap,) and cost scales with the compacted rows, not the space.
    Output contract matches _compact_group_xfer exactly (group_idx
    holds dense space ids, sentinel rows carry count 0, group_overflow
    flags >cap live groups for the dense retry), so extraction and the
    batched dispatch are oblivious to which path produced it."""
    acc_f = float_acc_dtype()
    cnt_dtype = int_acc_dtype()

    sorted_ops, sum_slots, mm_slots, base = _sorted_post_common(
        sum_jobs, mm_jobs, keys, payloads)
    sk = sorted_ops[0]
    n_rows = sk.shape[0]

    # every live-key row is valid by construction (garbage slots were
    # re-sentineled to space before the sort), so run lengths ARE the
    # group counts and the valid column never needs to ride the sort
    live = sk < jnp.int32(space)
    uniq = live & jnp.concatenate(
        [jnp.ones(1, jnp.bool_), sk[1:] != sk[:-1]])
    ranks = chunked_cumsum(uniq.astype(jnp.int32)).astype(jnp.int32)
    n_live = ranks[-1]
    n_matched = jnp.searchsorted(sk, jnp.int32(space)).astype(jnp.int32)
    rids = jnp.arange(1, cap + 1, dtype=jnp.int32)
    starts = jnp.searchsorted(ranks, rids, side="left").astype(jnp.int32)
    ends = jnp.minimum(
        jnp.searchsorted(ranks, rids, side="right").astype(jnp.int32),
        n_matched)
    alive = rids <= n_live
    out["group_idx"] = jnp.where(
        alive, sk.at[jnp.minimum(starts, n_rows - 1)].get(mode="clip"),
        jnp.int32(space))
    counts = jnp.where(alive, (ends - starts).astype(cnt_dtype), 0)
    out["group_count"] = counts
    out["group_overflow"] = (n_live > cap).astype(jnp.int32)

    sums_done: Dict[Tuple[int, bool], jax.Array] = {}
    for i, spec, slot in sum_jobs:
        name = _agg_name(i, spec)
        s = sums_done.get((slot, spec.integral))
        if s is None:
            dtype = int_acc_dtype() if spec.integral else acc_f
            sv = sorted_ops[base + sum_slots.index(slot)]
            cs = jnp.concatenate(
                [jnp.zeros(1, dtype), chunked_cumsum(sv.astype(dtype))])
            s = cs[ends] - cs[starts]
            sums_done[(slot, spec.integral)] = s
        if spec.kind == "avg":
            out[name + "_sum"] = s
            out[name + "_cnt"] = counts
        else:
            out[name] = s

    sorted_orderable = _sorted_orderables(keys, payloads, mm_slots,
                                          sorted_ops)
    pos_min = jnp.minimum(starts, n_rows - 1)
    pos_max = jnp.clip(ends - 1, 0, n_rows - 1)
    for i, spec, slot in mm_jobs:
        name = _agg_name(i, spec)
        pos = pos_min if spec.kind == "min" else pos_max
        picked = sorted_orderable[slot].at[pos].get(mode="clip")
        acc = _acc_dtype(spec)
        vals = _from_orderable64(picked, ord_modes[slot], acc_f).astype(acc)
        out[name] = jnp.where(
            counts > 0, vals,
            _extreme(acc, 1 if spec.kind == "min" else -1))


# ---------------------------------------------------------------------------
# kernel assembly
# ---------------------------------------------------------------------------

def build_kernel(plan: KernelPlan, bucket: int,
                 slots_cap: Optional[int] = None,
                 platform: Optional[str] = None,
                 xfer_compact: bool = True,
                 local_segments: int = 1,
                 scatter: bool = False,
                 two_pass_mode: Optional[str] = None,
                 ladder_min: Optional[int] = None):
    """Return fn(cols, n_docs, params) -> dict of partial aggregation states.

    Shape contract: every cols[i] has the same (bucket,) length; n_docs is a
    traced scalar; outputs have static shapes derived only from the plan
    (scalars, or (group_space,) arrays) — never from the data. bucket is
    static (plans may bind zero columns, e.g. COUNT(*) with an IS NULL
    filter, so it can't be derived from cols).

    slots_cap sizes the compaction output for the 'compact' strategy
    (default: ops/compact.default_slots_cap(bucket)); the returned dict's
    "overflow" entry tells the executor to retry with full capacity.
    """

    total = bucket * local_segments

    def kernel(cols: Tuple[jax.Array, ...], n_docs: jax.Array,
               params: Tuple[jax.Array, ...]) -> Dict[str, jax.Array]:
        if local_segments == 1:
            valid = jnp.arange(total, dtype=jnp.int32) < n_docs
        else:
            # cols are local_segments same-bucket segments concatenated
            # along the row axis (the mesh path's per-device shard);
            # n_docs is (local_segments,)
            iota = jax.lax.broadcasted_iota(
                jnp.int32, (local_segments, bucket), 1)
            valid = (iota < n_docs[:, None]).reshape(total)
        mask = valid & _eval_pred(plan.pred, cols, params, total)
        out: Dict[str, jax.Array] = {}
        if plan.is_group_by and plan.strategy == "compact":
            from .compact import default_slots_cap, sorted_default_slots_cap
            # scatter mode compacts exactly (XLA nonzero), so the tight
            # sorted-path cap applies: smaller gathers + scatter inputs,
            # and the overflow retry covers dense matches
            cap = slots_cap or (sorted_default_slots_cap(total)
                                if _needs_sort(plan) or scatter
                                else default_slots_cap(total))
            # sparse sorted post (q4.3): the sorted core emits
            # (group_idx, value) pairs directly at big spaces, so the
            # densify-then-compact _compact_group_xfer never runs there
            sparse = (xfer_compact and not scatter and _needs_sort(plan)
                      and plan.group_space >= GROUP_XFER_SPACE)
            _compact_group_aggs(plan, mask, cols, params, total, cap, out,
                                platform, scatter, two_pass_mode,
                                ladder_min, xfer_sparse=sparse)
            # scatter implies CPU execution, where the "transfer" the
            # device-side live-group compaction optimizes is free — the
            # nonzero over a big space only adds kernel time there
            if xfer_compact and not scatter and not sparse:
                _compact_group_xfer(plan, out)
            return out
        out["matched"] = jnp.sum(mask, dtype=int_acc_dtype())
        if plan.is_group_by:
            _group_aggs(plan, mask, cols, params, total, out, scatter)
            if xfer_compact and not scatter:
                _compact_group_xfer(plan, out)
        else:
            for i, spec in enumerate(plan.aggs):
                _scalar_agg(i, spec, mask, cols, params, out)
        return out

    return kernel


# dense (space,) group outputs above this space are compacted on device to
# the non-empty groups before transfer — the tunneled host link makes a
# 437k-group dense row set (~10MB over several arrays) cost ~0.5s/query
GROUP_XFER_SPACE = 1 << 15
GROUP_XFER_CAP = 1 << 15


def _compact_group_xfer(plan: KernelPlan, out: Dict[str, jax.Array]) -> None:
    """Replace dense (space,) group outputs with gathered non-empty rows:
    group_idx holds the dense space ids (sentinel=space past the count),
    group_overflow flags >GROUP_XFER_CAP live groups (executor retries with
    xfer_compact=False). All-or-nothing: any 2-D output (grouped
    DISTINCTCOUNT presence) disables compaction for the whole result, since
    extract_partial indexes every output with one positions array."""
    space = plan.group_space
    if space < GROUP_XFER_SPACE:
        return
    dense = {k: v for k, v in out.items()
             if k not in ("matched", "overflow")}
    if not all(v.ndim == 1 and v.shape[0] == space for v in dense.values()):
        return
    counts = out["group_count"]
    live = counts > 0
    idx, = jnp.nonzero(live, size=GROUP_XFER_CAP, fill_value=space)
    out["group_idx"] = idx.astype(jnp.int32)
    out["group_overflow"] = (
        jnp.sum(live, dtype=jnp.int32) > GROUP_XFER_CAP).astype(jnp.int32)
    for k, v in dense.items():
        out[k] = jnp.where(idx < space, v.at[idx].get(mode="clip"),
                           jnp.zeros((), dtype=v.dtype))


def _pred_col_indices(p) -> set:
    """Stored-column indices a predicate references."""
    if isinstance(p, (EqId, IdRange, InSet, InBitmap)):
        return {p.col}
    if isinstance(p, Cmp):
        return _value_col_indices(p.lhs)
    if isinstance(p, (And, Or)):
        return set().union(*[_pred_col_indices(c) for c in p.children])
    if isinstance(p, Not):
        return _pred_col_indices(p.child)
    return set()


def build_select_kernel(plan: SelectPlan, bucket: int):
    """fn(cols, n_docs, params) -> {"sel_<i>": (k,) stored values,
    "ord_<j>": (k,) order-key ids/values, "matched": scalar}.

    The composite order key packs the (col, desc, card) entries most-
    significant-first into one int64; lax.top_k picks the winners in one
    fused pass (LinearSelectionOrderByOperator's heap, TPU-shaped).
    Rows beyond min(matched, k) are garbage — extract slices by matched.
    """
    def kernel(cols: Tuple[jax.Array, ...], n_docs: jax.Array,
               params: Tuple[jax.Array, ...]) -> Dict[str, jax.Array]:
        mask = (jnp.arange(bucket, dtype=jnp.int32) < n_docs) \
            & _eval_pred(plan.pred, cols, params, bucket)
        if plan.order:
            key = jnp.zeros(bucket, dtype=jnp.int64)
            for col, desc, card in plan.order:
                v = cols[col].astype(jnp.int64)
                if card:  # dict ids: sorted dictionary => id order
                    if desc:
                        v = jnp.int64(card - 1) - v
                    key = key * jnp.int64(card) + v
                else:     # raw integral key — the planner only emits it
                    # alone (card-free values can't pack into a radix)
                    key = -v if desc else v
            # ascending composite wins smallest; top_k wants max -> negate
            sort_key = jnp.where(mask, -key, jnp.iinfo(jnp.int64).min)
        else:
            # doc order: earliest rows win
            iota = jnp.arange(bucket, dtype=jnp.int64)
            sort_key = jnp.where(mask, -iota, jnp.iinfo(jnp.int64).min)
        _, idx = jax.lax.top_k(sort_key, plan.k)
        out: Dict[str, jax.Array] = {
            "matched": jnp.sum(mask, dtype=int_acc_dtype()),
        }
        for i, col in enumerate(plan.select_cols):
            out[f"sel_{i}"] = jnp.take(cols[col], idx, axis=0)
        for j, (col, _d, _c) in enumerate(plan.order):
            out[f"ord_{j}"] = jnp.take(cols[col], idx)
        return out

    return kernel


@functools.lru_cache(maxsize=512)
def jitted_select_kernel(plan: SelectPlan, bucket: int):
    from ..utils.compileplane import staged
    return staged(jax.jit(build_select_kernel(plan, bucket)),
                  "select_kernel", ("select", plan, bucket))


def _dict_value_cols(plan: KernelPlan) -> Dict[int, int]:
    """col index -> dict-values param index, for every Col(dict_param=..)
    referenced by an aggregation value expression."""
    found: Dict[int, int] = {}

    def walk(ve):
        if isinstance(ve, Col) and ve.dict_param is not None:
            found[ve.col] = ve.dict_param
        elif isinstance(ve, Bin):
            walk(ve.lhs)
            walk(ve.rhs)

    for spec in plan.aggs:
        if spec.value is not None:
            walk(spec.value)
    return found


def segmented_compact_ok(plan: KernelPlan) -> bool:
    """Whether a compact group-by plan can run the segmented batch kernel:
    no column may serve as both a group key and a dictionary-value source
    (the segment offsetting of dict ids would corrupt the group keys)."""
    if not (plan.is_group_by and plan.strategy == "compact"):
        return False
    key_cols = {ci for ci, _ in plan.group_keys}
    return not (key_cols & set(_dict_value_cols(plan)))


def build_segmented_compact_kernel(plan: KernelPlan, bucket: int,
                                   n_segments: int,
                                   slots_cap: Optional[int] = None,
                                   platform: Optional[str] = None,
                                   xfer_compact: bool = True,
                                   scatter: bool = False,
                                   two_pass_mode: Optional[str] = None,
                                   ladder_min: Optional[int] = None):
    """Multi-segment compact group-by as ONE device program.

    Reference parity: GroupByCombineOperator.java:125 runs the same
    group-by executor across segments on a thread pool; the TPU-native
    combine concatenates S same-bucket segments along the row axis and
    makes the segment index the leading group-key factor, so one Pallas
    compaction + one group pass serve the whole batch:

    - predicate masks evaluate vmapped (per-segment params: dict-id
      ranges differ across segment dictionaries);
    - per-segment dictionary-value params (S, card) flatten to (S*card,)
      and the referencing dict-id columns are offset by seg*card, so
      value gathers hit the right segment's dictionary after rows mix;
    - group space becomes S*space; the executor slices (S, space) rows
      apart host-side and decodes each against its own dictionaries.

    Inputs: cols tuple of (S, bucket); n_docs (S,); params tuple of
    (S, ...)-stacked arrays. Outputs: dense (S*space,) group arrays plus
    per-segment "matched" (S,).
    """
    from dataclasses import replace as dc_replace

    seg_col = 1 + max(
        [ci for ci, _ in plan.group_keys]
        + [c for s in plan.aggs if s.value is not None
           for c in _value_col_indices(s.value)]
        + list(_pred_col_indices(plan.pred)) + [-1])
    plan2 = dc_replace(plan, group_keys=((seg_col, n_segments),)
                       + plan.group_keys)
    dict_cols = _dict_value_cols(plan)
    total = n_segments * bucket

    def kernel(cols: Tuple[jax.Array, ...], n_docs: jax.Array,
               params: Tuple[jax.Array, ...]) -> Dict[str, jax.Array]:
        def pred_one(c, n, p):
            valid = jnp.arange(bucket, dtype=jnp.int32) < n
            return valid & _eval_pred(plan.pred, c, p, bucket)

        masks = jax.vmap(pred_one)(cols, n_docs, params)   # (S, bucket)
        seg2d = jax.lax.broadcasted_iota(jnp.int32, (n_segments, bucket), 0)

        flat_cols: List[jax.Array] = []
        for ci, c in enumerate(cols):
            pi = dict_cols.get(ci)
            if pi is not None:  # offset ids into the flattened dictionary
                card = params[pi].shape[1]
                c = c.astype(jnp.int32) + seg2d * jnp.int32(card)
            flat_cols.append(c.reshape(total))
        while len(flat_cols) <= seg_col:
            flat_cols.append(jnp.zeros(total, dtype=jnp.int32))
        flat_cols[seg_col] = seg2d.reshape(total)

        dict_pis = set(dict_cols.values())
        vparams = tuple(
            p.reshape((-1,) + p.shape[2:]) if i in dict_pis else p[0]
            for i, p in enumerate(params))

        from .compact import default_slots_cap, sorted_default_slots_cap
        cap = slots_cap or (sorted_default_slots_cap(total)
                            if _needs_sort(plan2)
                            else default_slots_cap(total))
        out: Dict[str, jax.Array] = {}
        sparse = (xfer_compact and not scatter and _needs_sort(plan2)
                  and plan2.group_space >= GROUP_XFER_SPACE)
        _compact_group_aggs(plan2, masks.reshape(total), tuple(flat_cols),
                            vparams, total, cap, out, platform, scatter,
                            two_pass_mode, ladder_min, xfer_sparse=sparse)
        out["matched"] = masks.sum(axis=1, dtype=int_acc_dtype())  # (S,)
        if xfer_compact and not scatter and not sparse:
            # live-group gather over the combined S*space — the executor
            # splits segments host-side via group_idx // space
            _compact_group_xfer(plan2, out)
        return out

    return kernel


@functools.lru_cache(maxsize=256)
def _jitted_segmented_cached(plan, bucket, n_segments, slots_cap, platform,
                             xfer_compact, scatter, two_pass_mode,
                             ladder_min):
    from ..utils.compileplane import staged
    key = ("segc", plan, bucket, n_segments, slots_cap, platform,
           xfer_compact, scatter, two_pass_mode, ladder_min)
    return staged(jax.jit(build_segmented_compact_kernel(
        plan, bucket, n_segments, slots_cap, platform, xfer_compact,
        scatter, two_pass_mode, ladder_min)), "segmented_kernel", key)


def jitted_segmented_compact(plan: KernelPlan, bucket: int,
                             n_segments: int,
                             slots_cap: Optional[int] = None,
                             platform: Optional[str] = None,
                             xfer_compact: bool = True,
                             scatter: Optional[bool] = None):
    if scatter is None:
        scatter = cpu_scatter_default(platform)
    return _jitted_segmented_cached(plan, bucket, n_segments, slots_cap,
                                    platform, xfer_compact, scatter,
                                    _two_pass_mode(), _ladder_min_elems())


# the env-flag wrapper keeps the lru_cache introspection surface
# (tests/tpu_hw_script assert cache hits across the retry ladder)
jitted_segmented_compact.cache_info = _jitted_segmented_cached.cache_info
jitted_segmented_compact.cache_clear = _jitted_segmented_cached.cache_clear


@functools.lru_cache(maxsize=1024)
def _jitted_kernel_cached(plan, bucket, slots_cap, platform, xfer_compact,
                          scatter, two_pass_mode, ladder_min):
    from ..utils.compileplane import staged
    key = ("kern", plan, bucket, slots_cap, platform, xfer_compact,
           scatter, two_pass_mode, ladder_min)
    return staged(jax.jit(build_kernel(plan, bucket, slots_cap, platform,
                                       xfer_compact, scatter=scatter,
                                       two_pass_mode=two_pass_mode,
                                       ladder_min=ladder_min)),
                  "kernel", key)


def jitted_kernel(plan: KernelPlan, bucket: int,
                  slots_cap: Optional[int] = None,
                  platform: Optional[str] = None,
                  xfer_compact: bool = True,
                  scatter: Optional[bool] = None):
    """jit once per (plan structure, bucket, capacity, target platform,
    aggregation core) — platform keys the cache because f64-bitcast
    support and the Pallas gate differ per backend (mesh execution may
    target a platform other than the process default); scatter=None
    resolves from the platform + PINOT_CPU_FAST_GROUPBY at call time
    (cpu_scatter_default) so the flag is part of the cache key, and the
    compact-path knobs (PINOT_COMPACT_TWO_PASS / _LADDER_MIN) resolve
    here for the same reason — flipping the env between calls must not
    hit a stale cached kernel."""
    if scatter is None:
        scatter = cpu_scatter_default(platform)
    return _jitted_kernel_cached(plan, bucket, slots_cap, platform,
                                 xfer_compact, scatter,
                                 _two_pass_mode(), _ladder_min_elems())


jitted_kernel.cache_info = _jitted_kernel_cached.cache_info
jitted_kernel.cache_clear = _jitted_kernel_cached.cache_clear
