"""Per-query resource accounting + query killing.

Reference parity: pinot-spi/.../accounting/ThreadResourceUsageAccountant
(SPI) + pinot-core/.../accounting/PerQueryCPUMemAccountantFactory.java:66 —
per-thread CPU/memory sampled into per-query aggregates (:125-126,263), a
WatcherTask that kills the most expensive query under heap pressure
(:471-494), and the hot-loop interrupt check
Tracing.ThreadAccountantOps.sample() (DocIdSetOperator.java:70).

TPU-native shape: queries are a handful of XLA launches, not thousands of
block iterations — sample() sits between per-segment launches (the
engine's natural preemption points), CPU comes from time.thread_time
deltas of the executing thread, memory is the tracked bytes of
materialized partials plus process RSS for the watcher's pressure signal.
"""
from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..query.sql import SqlError


class QueryKilledError(SqlError):
    """Raised inside the query's own execution path after a kill flag.
    is_deadline distinguishes a timeout (deadline exceeded) from an
    operator/watcher kill."""

    def __init__(self, msg: str, is_deadline: bool = False):
        super().__init__(msg)
        self.is_deadline = is_deadline


_PAGE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def process_rss_bytes() -> int:
    try:
        with open("/proc/self/statm") as fh:
            return int(fh.read().split()[1]) * _PAGE
    except (OSError, IndexError, ValueError):
        return 0


def system_memory_bytes() -> int:
    try:
        with open("/proc/meminfo") as fh:
            for line in fh:
                if line.startswith("MemTotal:"):
                    return int(line.split()[1]) * 1024
    except (OSError, IndexError, ValueError):
        pass
    return 0


@dataclass
class QueryUsage:
    query_id: str
    start: float = field(default_factory=time.perf_counter)
    deadline: Optional[float] = None
    cpu_s: float = 0.0
    mem_bytes: int = 0
    killed_reason: Optional[str] = None
    # the query's SQL text (when the registration point has it): the
    # compile-forensics plane (utils/compileplane) hashes it through
    # utils/shapehash so every compile_event carries the plan shape of
    # the query that paid the compile
    sql: Optional[str] = None
    # workload isolation (broker/workload.py): the owning tenant and
    # its priority tier. The watcher's kill ordering sheds besteffort
    # tenants before standard before protected, and unregister feeds
    # the tenant's post-paid cpu/result-bytes budgets from the usage
    # this fence already tracks
    tenant: Optional[str] = None
    tier: Optional[str] = None
    # cross-query micro-batching (engine/ragged.py): how many fused
    # dispatches this query rode and the largest batch it shared — the
    # server ships them in the wire header and the broker's forensics
    # plane lands them as query_stats batched/batch_size fields
    batched_dispatches: int = 0
    max_batch_size: int = 0
    _thread_cpu0: Dict[int, float] = field(default_factory=dict)

    @property
    def wall_s(self) -> float:
        return time.perf_counter() - self.start

    def cost(self) -> float:
        """Kill ordering: tracked memory dominates, wall time breaks ties
        (the reference ranks by allocated bytes)."""
        return self.mem_bytes + self.wall_s * 1e6


class ResourceAccountant:
    """Global registry: thread -> running query, with kill/timeout checks
    at sample points."""

    def __init__(self):
        self._lock = threading.Lock()
        self._by_query: Dict[str, QueryUsage] = {}
        self._by_thread: Dict[int, str] = {}

    # -- registration ------------------------------------------------------
    def register(self, query_id: str, deadline: Optional[float] = None,
                 tenant: Optional[str] = None,
                 tier: Optional[str] = None,
                 sql: Optional[str] = None) -> QueryUsage:
        u = QueryUsage(query_id, deadline=deadline, tenant=tenant,
                       tier=tier, sql=sql)
        tid = threading.get_ident()
        with self._lock:
            self._by_query[query_id] = u
            self._by_thread[tid] = query_id
        u._thread_cpu0[tid] = time.thread_time()
        return u

    def attach_thread(self, query_id: str) -> None:
        """Worker threads executing on behalf of a query (combine-pool
        TraceRunnable analog) call this so their samples account to it."""
        tid = threading.get_ident()
        with self._lock:
            if query_id in self._by_query:
                self._by_thread[tid] = query_id
                self._by_query[query_id]._thread_cpu0[tid] = \
                    time.thread_time()

    def unregister(self, query_id: str) -> Optional[QueryUsage]:
        with self._lock:
            u = self._by_query.pop(query_id, None)
            for tid in [t for t, q in self._by_thread.items()
                        if q == query_id]:
                del self._by_thread[tid]
        if u is not None and u.tenant:
            # post-paid tenant budgets (broker/workload.py): the usage
            # this accountant already tracked through the track_result
            # fence debits the tenant's cpu-ms/result-bytes buckets —
            # OUTSIDE our lock (the workload manager takes its own)
            try:
                from ..broker.workload import global_workload
                global_workload.observe(u)
            except Exception:
                pass  # stripped installs without the broker package
        return u

    def usage(self, query_id: str) -> Optional[QueryUsage]:
        with self._lock:
            return self._by_query.get(query_id)

    def current_query_id(self) -> Optional[str]:
        """The query this thread is executing on behalf of, if any (the
        retrace detector's generation token)."""
        with self._lock:
            return self._by_thread.get(threading.get_ident())

    def running(self) -> List[QueryUsage]:
        with self._lock:
            return list(self._by_query.values())

    # -- hot-loop hooks ----------------------------------------------------
    def sample(self) -> None:
        """Call between per-segment launches: accumulates this thread's CPU
        into the owning query and raises if the query was killed or timed
        out (ThreadAccountantOps.sample + interrupt-check analog)."""
        tid = threading.get_ident()
        t = time.thread_time()
        with self._lock:
            qid = self._by_thread.get(tid)
            u = self._by_query.get(qid) if qid else None
            if u is not None:
                # counters mutate under the lock: multiple worker threads
                # can be attached to one query (attach_thread) and unlocked
                # read-modify-write would lose updates
                t0 = u._thread_cpu0.get(tid, t)
                u.cpu_s += max(t - t0, 0.0)
                u._thread_cpu0[tid] = t
        if u is None:
            return
        if u.killed_reason is None:
            # deterministic chaos hook: behave exactly as the HeapWatcher
            # would under heap pressure — flag the query, count the kill,
            # raise at this (the query's own) sample point. The site key
            # stays "": decide() partitions the stream by the OWNING
            # query id (this thread is attached to u.query_id), so each
            # query draws its own hit/fire windows — `times=1` kills
            # every matching query once, and `match=<queryId>` pins the
            # kill to one named query
            from ..utils.faults import fault_fires
            if fault_fires("accounting.oom_kill", detail=u.query_id):
                u.killed_reason = ("injected heap pressure "
                                   "(fault accounting.oom_kill)")
                from ..utils.metrics import global_metrics
                global_metrics.count("queries_killed")
                global_metrics.count("queries_killed_oom")
        if u.killed_reason is not None:
            raise QueryKilledError(
                f"query {u.query_id} killed: {u.killed_reason}")
        if u.deadline is not None and time.perf_counter() > u.deadline:
            from ..utils.metrics import global_metrics
            global_metrics.count("query_deadline_kills")
            raise QueryKilledError(
                f"query {u.query_id} killed: deadline exceeded",
                is_deadline=True)

    def note_batched(self, query_id: str, batch_size: int) -> None:
        """A fused ragged dispatch included this query (engine/ragged.py
        leader thread) — counters mutate under the lock because the
        leader annotates every participant, not just its own query."""
        with self._lock:
            u = self._by_query.get(query_id)
            if u is not None:
                u.batched_dispatches += 1
                u.max_batch_size = max(u.max_batch_size, int(batch_size))

    def track_memory(self, nbytes: int) -> None:
        tid = threading.get_ident()
        with self._lock:
            qid = self._by_thread.get(tid)
            u = self._by_query.get(qid) if qid else None
            if u is not None:
                u.mem_bytes += max(int(nbytes), 0)

    def track_memory_for(self, query_id: str, nbytes: int) -> None:
        """Attribute bytes to a named query regardless of the calling
        thread — the fused ragged dispatch's leader apportions the
        batch's host outputs per participant so the heap watcher's
        kill ordering sees each query's real footprint, not the whole
        batch piled onto the leader."""
        with self._lock:
            u = self._by_query.get(query_id)
            if u is not None:
                u.mem_bytes += max(int(nbytes), 0)

    def track_result(self, host: Dict[str, Any]) -> None:
        """THE post-execute accounting fence: size a kernel's host output
        dict once, after the device_get. Every dispatch path (executor,
        batch, segmented, pipelined) accounts through here so the
        per-query loops stay free of ad-hoc host syncs — jaxlint's
        host-sync rule holds them to it."""
        import numpy as np
        self.track_memory(
            sum(np.asarray(v).nbytes  # jaxlint: ok host-sync
                for v in host.values()))

    def set_deadline(self, query_id: str, deadline: Optional[float]) -> None:
        with self._lock:
            u = self._by_query.get(query_id)
            if u is not None:
                u.deadline = deadline

    # -- killing -----------------------------------------------------------
    def kill(self, query_id: str, reason: str) -> bool:
        with self._lock:
            u = self._by_query.get(query_id)
        if u is None:
            return False
        u.killed_reason = reason
        from ..utils.metrics import global_metrics
        global_metrics.count("queries_killed")
        return True

    def kill_most_expensive(self, reason: str) -> Optional[str]:
        """PerQueryCPUMemResourceUsageAccountant.java:471-494 analog,
        tier-aware (broker/workload.py): victims come from the least-
        protected tier that has a running query — a ``protected``
        tenant's query is only ever killed when NOTHING less protected
        is running, the memory-pressure half of workload isolation."""
        try:
            from ..broker.workload import tier_shed_rank
        except Exception:
            # stripped install without the broker package (same stance
            # as unregister's observe hook): the watcher must still
            # kill SOMETHING, untiered, or the process OOMs
            def tier_shed_rank(_tier):
                return 0
        candidates = [u for u in self.running() if u.killed_reason is None]
        if not candidates:
            return None
        lowest = min(tier_shed_rank(u.tier) for u in candidates)
        candidates = [u for u in candidates
                      if tier_shed_rank(u.tier) == lowest]
        victim = max(candidates, key=QueryUsage.cost)
        victim.killed_reason = reason
        from ..utils.metrics import global_metrics
        global_metrics.count("queries_killed")
        return victim.query_id


class HeapWatcher:
    """Background memory-pressure watcher: when process RSS crosses the
    panic threshold, kill the most expensive running query (WatcherTask
    analog, PerQueryCPUMemAccountantFactory.java:263)."""

    def __init__(self, accountant: ResourceAccountant,
                 rss_limit_bytes: int, panic_fraction: float = 0.9,
                 interval_s: float = 0.2):
        self.accountant = accountant
        self.rss_limit = int(rss_limit_bytes)
        self.panic = panic_fraction
        self.interval = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.kills = 0

    def start(self) -> "HeapWatcher":
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            self.check_once()

    def check_once(self) -> Optional[str]:
        rss = process_rss_bytes()
        if self.rss_limit and rss > self.rss_limit * self.panic:
            victim = self.accountant.kill_most_expensive(
                f"heap pressure: rss {rss >> 20}MiB > "
                f"{int(self.rss_limit * self.panic) >> 20}MiB")
            if victim is not None:
                self.kills += 1
                from ..utils.metrics import global_metrics
                global_metrics.count("queries_killed_oom")
            return victim
        return None

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)


global_accountant = ResourceAccountant()
