"""Binary columnar partial codec — the DataTable/DataBlock analog.

Reference parity: pinot-common/.../datatable/ (versioned binary server->
broker result blocks, DataTableBuilderV4) and common/datablock/
ColumnarDataBlock.java. Pinot ships aggregation partials as length-
prefixed binary blocks over Netty; the JSON wire (engine/serde.py) kept
partials debuggable but costs ~10-70 bytes per group. This codec stores
partials columnar:

- group keys and numeric states as minimal-width little-endian arrays
  (int8/16/32/64 chosen by range, float64 for doubles);
- string key columns dictionary-encoded (unique values + narrow ids) —
  the ColumnarDataBlock trick, which also makes repeated group-key
  strings nearly free;
- AVG states as a (sum, count) column pair; object states (distinct
  sets, mode maps) fall back to the tagged-JSON cell encoding;
- frames > 4 KiB are zlib-compressed (the chunk-codec analog of
  pinot-segment-local io/compression; zlib is the always-available
  codec — see native/ for the zstd path used by segment storage).

`encode_partial`/`decode_partial` are the binary peers of serde.py's
`partial_to_wire`/`partial_from_wire`; cluster/server_node.py streams
them length-prefixed over the /query/bin data plane.
"""
from __future__ import annotations

import json
import struct
import zlib
from typing import Any, List, Tuple

import numpy as np

from .executor import AggPartial, GroupByPartial, SelectionPartial
from .serde import _dec_state, _enc_state

_MAGIC = b"PDB1"
_MAGIC_Z = b"PDBZ"
_COMPRESS_MIN = 4096

_INT_DTYPES = [np.int8, np.int16, np.int32, np.int64]

# column type tags
_C_INT, _C_FLOAT, _C_STRDICT, _C_OBJ, _C_AVG = range(5)
# partial type tags
_P_AGG, _P_GROUPBY, _P_SELECTION = range(3)


def _pack_json(buf: bytearray, obj: Any) -> None:
    b = json.dumps(obj).encode()
    buf += struct.pack("<I", len(b))
    buf += b


def _unpack_json(mv: memoryview, off: int) -> Tuple[Any, int]:
    (n,) = struct.unpack_from("<I", mv, off)
    off += 4
    return json.loads(bytes(mv[off:off + n])), off + n


def _shuffle(arr: np.ndarray) -> bytes:
    """Byte-transpose (blosc shuffle filter): group the k-th byte of every
    element together so zlib sees the near-constant high-byte planes as
    long runs. Self-inverting given (n, itemsize)."""
    n, isz = len(arr), arr.dtype.itemsize
    return arr.view(np.uint8).reshape(n, isz).T.tobytes()


def _unshuffle(raw: memoryview, dtype, n: int) -> np.ndarray:
    isz = np.dtype(dtype).itemsize
    planes = np.frombuffer(raw, dtype=np.uint8, count=n * isz)
    return np.ascontiguousarray(
        planes.reshape(isz, n).T).view(dtype).reshape(n)


def _int_col(buf: bytearray, vals: np.ndarray) -> None:
    if len(vals):
        lo, hi = int(vals.min()), int(vals.max())
    else:
        lo = hi = 0
    for code, dt in enumerate(_INT_DTYPES):
        info = np.iinfo(dt)
        if info.min <= lo and hi <= info.max:
            break
    raw = _shuffle(vals.astype(dt))
    buf += struct.pack("<BBI", _C_INT, code, len(raw))
    buf += raw


def _encode_column(buf: bytearray, vals: List[Any]) -> None:
    """Encode one column of python cell values, picking the layout."""
    probe = next((v for v in vals if v is not None), None)
    if probe is None and vals:
        buf += struct.pack("<B", _C_OBJ)
        _pack_json(buf, [_enc_state(v) for v in vals])
        return
    if isinstance(probe, bool):
        kind = "obj"
    elif isinstance(probe, (int, np.integer)):
        kind = "int"
    elif isinstance(probe, (float, np.floating)):
        kind = "float"
    elif isinstance(probe, str):
        kind = "str"
    elif (isinstance(probe, tuple) and len(probe) == 2
          and isinstance(probe[1], (int, np.integer))
          and isinstance(probe[0], (int, float, np.integer, np.floating))):
        kind = "avg"
    else:
        kind = "obj"
    # np.asarray probes the whole column at C speed: a None or mixed-type
    # cell lands on dtype object and demotes the column to OBJ
    if kind in ("int", "float"):
        arr = np.asarray(vals)
        if kind == "int" and arr.dtype.kind == "i":
            _int_col(buf, arr)
            return
        if arr.dtype.kind == "f" or (kind == "float"
                                     and arr.dtype.kind == "i"):
            raw = _shuffle(arr.astype(np.float64))
            buf += struct.pack("<BI", _C_FLOAT, len(raw))
            buf += raw
            return
    if kind == "str":
        arr = np.asarray(vals)
        if arr.dtype.kind == "U":
            uniq, inv = np.unique(arr, return_inverse=True)
            buf += struct.pack("<B", _C_STRDICT)
            _pack_json(buf, uniq.tolist())
            _int_col(buf, inv.astype(np.int64))
            return
    if kind == "avg" and all(isinstance(v, tuple) and len(v) == 2
                             for v in vals):
        buf += struct.pack("<B", _C_AVG)
        _encode_column(buf, [v[0] for v in vals])
        _encode_column(buf, [int(v[1]) for v in vals])
        return
    buf += struct.pack("<B", _C_OBJ)
    _pack_json(buf, [_enc_state(v) for v in vals])


def _decode_column(mv: memoryview, off: int) -> Tuple[List[Any], int]:
    (ctype,) = struct.unpack_from("<B", mv, off)
    off += 1
    if ctype == _C_INT:
        code, n = struct.unpack_from("<BI", mv, off)
        off += 5
        dt = _INT_DTYPES[code]
        arr = _unshuffle(mv[off:off + n], dt, n // np.dtype(dt).itemsize)
        return arr.tolist(), off + n
    if ctype == _C_FLOAT:
        (n,) = struct.unpack_from("<I", mv, off)
        off += 4
        arr = _unshuffle(mv[off:off + n], np.float64, n // 8)
        return arr.tolist(), off + n
    if ctype == _C_STRDICT:
        uniq, off = _unpack_json(mv, off)
        ids, off = _decode_column(mv, off)
        return [uniq[i] for i in ids], off
    if ctype == _C_AVG:
        sums, off = _decode_column(mv, off)
        cnts, off = _decode_column(mv, off)
        return list(zip(sums, cnts)), off
    assert ctype == _C_OBJ, ctype
    cells, off = _unpack_json(mv, off)
    return [_dec_state(c) for c in cells], off


def encode_partial(p: Any) -> bytes:
    buf = bytearray(_MAGIC)
    if isinstance(p, AggPartial):
        buf += struct.pack("<BH", _P_AGG, len(p.states))
        for s in p.states:
            _encode_column(buf, [s])
    elif isinstance(p, GroupByPartial):
        key_cols = list(zip(*p.groups.keys()))
        state_cols = list(zip(*p.groups.values()))
        buf += struct.pack("<BIHH", _P_GROUPBY, len(p.groups),
                           len(key_cols), len(state_cols))
        for col in key_cols:
            _encode_column(buf, col)
        for col in state_cols:
            _encode_column(buf, col)
    elif isinstance(p, SelectionPartial):
        buf += struct.pack("<B", _P_SELECTION)
        _pack_json(buf, p.labels)
        nc = len(p.rows[0]) if p.rows else 0
        no = len(p.order_keys[0]) if p.order_keys else 0
        buf += struct.pack("<IHH", len(p.rows), nc, no)
        for i in range(nc):
            _encode_column(buf, [r[i] for r in p.rows])
        for i in range(no):
            _encode_column(buf, [k[i] for k in p.order_keys])
    else:
        raise TypeError(f"unknown partial {type(p)}")
    if len(buf) >= _COMPRESS_MIN:
        z = zlib.compress(bytes(buf[4:]), 3)
        if len(z) + 8 < len(buf):
            return _MAGIC_Z + struct.pack("<I", len(buf) - 4) + z
    return bytes(buf)


_REL_MAGIC = b"PREL"
_REL_MAGIC_Z = b"PRLZ"


def encode_relation(rel) -> bytes:
    """Columnar binary for a multistage Relation block (the RowDataBlock/
    ColumnarDataBlock wire form of mailbox.proto MailboxContent). Same
    column layouts as partials; null masks ship bit-packed."""
    buf = bytearray(_REL_MAGIC)
    names = list(rel.data.keys())
    null_cols = [n for n in names if n in rel.nulls]
    _pack_json(buf, {"name": rel.name, "columns": names,
                     "nullColumns": null_cols, "rows": rel.n_rows})
    for n in names:
        _encode_column(buf, np.asarray(rel.data[n]).tolist())
    for n in null_cols:
        raw = np.packbits(np.asarray(rel.nulls[n], dtype=bool)).tobytes()
        buf += struct.pack("<I", len(raw))
        buf += raw
    if len(buf) >= _COMPRESS_MIN:
        z = zlib.compress(bytes(buf[4:]), 3)
        if len(z) + 8 < len(buf):
            return _REL_MAGIC_Z + struct.pack("<I", len(buf) - 4) + z
    return bytes(buf)


def decode_relation(data: bytes):
    from ..multistage.relation import Relation

    magic = bytes(data[:4])
    if magic == _REL_MAGIC_Z:
        (raw_len,) = struct.unpack_from("<I", data, 4)
        body = zlib.decompress(data[8:], bufsize=raw_len)
    elif magic == _REL_MAGIC:
        body = bytes(data[4:])
    else:
        raise ValueError(f"bad relation magic {magic!r}")
    mv = memoryview(body)
    header, off = _unpack_json(mv, 0)
    n = header["rows"]
    cols = {}
    for name in header["columns"]:
        cells, off = _decode_column(mv, off)
        arr = np.asarray(cells)
        if arr.dtype.kind in "USO":  # strings/mixed stay object cells
            a2 = np.empty(n, dtype=object)
            a2[:] = cells
            arr = a2
        cols[name] = arr
    nulls = {}
    for name in header["nullColumns"]:
        (ln,) = struct.unpack_from("<I", mv, off)
        off += 4
        bits = np.frombuffer(mv, dtype=np.uint8, count=ln, offset=off)
        off += ln
        nulls[name] = np.unpackbits(bits)[:n].astype(bool)
    return Relation(cols, nulls, header.get("name"))


_FRAME_MAGIC = b"PWR1"


def encode_wire_frame(header: Any, partials: List[Any]) -> bytes:
    """Length-prefixed response frame: JSON header + N partial blocks
    (the InstanceResponseBlock -> DataTable-bytes serialization at
    QueryScheduler.java:134, minus the thrift envelope)."""
    return encode_wire_frame_blocks(header,
                                    [encode_partial(p) for p in partials])


def encode_wire_frame_blocks(header: Any, blocks: List[bytes]) -> bytes:
    """Frame assembly over ALREADY-encoded partial blocks — the server
    times the block encode separately (serde vs network split) and the
    header must carry that measurement, so encode and assembly are two
    steps."""
    out = bytearray(_FRAME_MAGIC)
    h = json.dumps(header).encode()
    out += struct.pack("<I", len(h))
    out += h
    out += struct.pack("<I", len(blocks))
    for b in blocks:
        out += struct.pack("<I", len(b))
        out += b
    return bytes(out)


def decode_wire_frame(data: bytes) -> Tuple[Any, List[Any]]:
    if bytes(data[:4]) != _FRAME_MAGIC:
        raise ValueError("bad wire frame magic")
    mv = memoryview(data)
    (hn,) = struct.unpack_from("<I", mv, 4)
    header = json.loads(bytes(mv[8:8 + hn]))
    off = 8 + hn
    (n,) = struct.unpack_from("<I", mv, off)
    off += 4
    partials = []
    for _ in range(n):
        (ln,) = struct.unpack_from("<I", mv, off)
        off += 4
        partials.append(decode_partial(bytes(mv[off:off + ln])))
        off += ln
    return header, partials


def decode_partial(data: bytes) -> Any:
    magic = bytes(data[:4])
    if magic == _MAGIC_Z:
        (raw_len,) = struct.unpack_from("<I", data, 4)
        body = zlib.decompress(data[8:], bufsize=raw_len)
    elif magic == _MAGIC:
        body = bytes(data[4:])
    else:
        raise ValueError(f"bad partial magic {magic!r}")
    mv = memoryview(body)
    (ptype,) = struct.unpack_from("<B", mv, 0)
    off = 1
    if ptype == _P_AGG:
        (n,) = struct.unpack_from("<H", mv, off)
        off += 2
        states = []
        for _ in range(n):
            cells, off = _decode_column(mv, off)
            states.append(cells[0])
        return AggPartial(states)
    if ptype == _P_GROUPBY:
        ng, kw, ns = struct.unpack_from("<IHH", mv, off)
        off += 8
        key_cols = []
        for _ in range(kw):
            col, off = _decode_column(mv, off)
            key_cols.append(col)
        state_cols = []
        for _ in range(ns):
            col, off = _decode_column(mv, off)
            state_cols.append(col)
        keys = list(zip(*key_cols)) if kw else [()] * ng
        states = ([list(s) for s in zip(*state_cols)] if ns
                  else [[] for _ in range(ng)])
        return GroupByPartial(dict(zip(keys, states)))
    assert ptype == _P_SELECTION, ptype
    labels, off = _unpack_json(mv, off)
    nr, nc, no = struct.unpack_from("<IHH", mv, off)
    off += 8
    cols = []
    for _ in range(nc):
        col, off = _decode_column(mv, off)
        cols.append(col)
    ocols = []
    for _ in range(no):
        col, off = _decode_column(mv, off)
        ocols.append(col)
    rows = [tuple(cols[i][r] for i in range(nc)) for r in range(nr)]
    okeys = [tuple(ocols[i][r] for i in range(no)) for r in range(nr)]
    return SelectionPartial(labels, rows, okeys)
