"""Pipelined (double-buffered) segment scan: overlap host->device
transfer of the next segment with compute on the current one.

Reference parity: SURVEY 2.9 "pipelined streaming" — the reference keeps
servers saturated by streaming blocks through operator chains on thread
pools (BaseCombineOperator workers + Netty streaming responses). On a
TPU the analogous overlap is the DMA/compute pipeline: JAX dispatch is
asynchronous, so enqueueing segment i+1's ``jax.device_put`` before
blocking on segment i's kernel lets the H2D copy ride the transfer
engine while the MXU works. This path exists for COLD scans whose
working set exceeds the HBM budget: the resident-cache path
(engine/batch.py) stacks everything in HBM and launches once, which is
faster but needs the data to fit; this one holds at most TWO segments'
columns in device memory at a time and streams the rest.

The router (execute_plans_batched) sends a same-structure kernel group
here when its stacked footprint exceeds ``hbm_budget_bytes()``.
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..query.planner import CompiledPlan
from ..utils.stats import make_bump

# default budget: v5e has 16GB HBM; leave headroom for outputs/compile
_DEFAULT_BUDGET = 8 << 30

# observability: how many pipelined streams ran (tests + trace hooks);
# thread-safe — concurrent broker queries, tests assert exact counts
STATS = {"pipelined_groups": 0, "pipelined_segments": 0}
bump = make_bump(STATS)


def hbm_budget_bytes() -> int:
    """Resident-scan budget (PINOT_HBM_BUDGET_BYTES overrides; the
    reference sizes off-heap buffers from server config the same way)."""
    return int(os.environ.get("PINOT_HBM_BUDGET_BYTES", _DEFAULT_BUDGET))


def group_stack_bytes(plans: List[CompiledPlan], bucket: int) -> int:
    """Footprint of stacking this group's columns in HBM (what
    engine/batch.py would upload)."""
    total = 0
    for p in plans:
        for c in p.col_names:
            m = p.segment.columns[c]
            width = 1 if getattr(m, "single_value", True) else \
                (m.max_values or 1)
            # dict ids upload as int32; raw columns keep their dtype
            item = 4 if m.has_dict else np.dtype(m.fwd_dtype).itemsize
            total += bucket * width * item
    return total


def execute_kernel_plans_pipelined(plans: List[CompiledPlan],
                                   plan_struct, bucket: int,
                                   resolved_params: Dict[int, Tuple],
                                   idxs: List[int]) -> List[Any]:
    """Run same-structure kernel plans one segment at a time with the
    next segment's transfer in flight; returns partials in plans order.

    Double-buffer discipline: at any moment device memory holds the
    in-flight transfer (i+1) plus the executing segment (i); segment
    i-1's columns are dropped as soon as its kernel output is enqueued
    (jax frees the buffers when the last reference dies after the
    dependent computation completes).
    """
    from ..ops.kernels import jitted_kernel
    from .accounting import global_accountant
    from .executor import extract_partial

    fn = jitted_kernel(plan_struct, bucket)  # lru-cached jit: repeated
    # over-budget queries must not pay a fresh XLA compile per group
    group = [plans[i] for i in idxs]

    def stage(k: int):
        seg = group[k].segment
        return tuple(jax.device_put(seg.host_col_padded(c, bucket))
                     for c in group[k].col_names)

    bump("pipelined_groups")
    results: List[Any] = []
    staged = stage(0)
    outs: List[Any] = []
    for k, plan in enumerate(group):
        global_accountant.sample()
        cur = staged
        # enqueue the NEXT transfer before compute: async dispatch lets
        # the H2D copy overlap this kernel on the transfer engine
        staged = stage(k + 1) if k + 1 < len(group) else None
        out = fn(cur, jnp.int32(plan.segment.n_docs),
                 resolved_params[idxs[k]])
        outs.append(out)
        del cur  # last py-reference; freed once the kernel consumes it
        bump("pipelined_segments")
        if k >= 1:
            # bound in-flight work to the double buffer: resolve the
            # previous segment's output before enqueueing more
            # double-buffer resolution point — host-sync [jaxlint baseline]
            outs[k - 1] = jax.device_get(outs[k - 1])
    outs[-1] = jax.device_get(outs[-1])  # jaxlint: ok host-sync
    dense_fn = None
    for k, (plan, out) in enumerate(zip(group, outs)):
        out = {name: np.asarray(v)  # jaxlint: ok host-sync — host already
               for name, v in out.items()}
        global_accountant.track_result(out)
        if int(out.pop("group_overflow", 0)):
            # rerun this segment dense (no transfer compaction) WITHOUT
            # run_kernel: that path populates the persistent device cache,
            # which would make the over-budget working set resident —
            # exactly what this streaming path exists to avoid
            if dense_fn is None:
                dense_fn = jitted_kernel(plan_struct, bucket,
                                         xfer_compact=False)
            seg = plan.segment
            cols = tuple(jax.device_put(seg.host_col_padded(c, bucket))
                         for c in plan.col_names)
            from ..ops.plan_cache import global_plan_cache
            with global_plan_cache.detector.expected():
                # a deliberate dense rerun (compile-event taxonomy:
                # overflow_retry, never a retrace)
                dense = jax.device_get(dense_fn(  # jaxlint: ok host-sync
                    cols, jnp.int32(seg.n_docs),
                    resolved_params[idxs[k]]))
            del cols
            dense.pop("group_overflow", None)
            global_accountant.track_result(dense)
            results.append(extract_partial(plan, dense))
        else:
            results.append(extract_partial(plan, out))
    return results
