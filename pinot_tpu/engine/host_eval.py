"""Host (numpy) evaluation: the vectorized CPU fallback path.

Covers what the device kernels don't yet: selection queries, group-by on
raw/expression keys, very-high-cardinality group-by, DISTINCTCOUNT on raw
columns. Reference parity: this is the role ScanBasedFilterOperator +
SelectionOnlyOperator + NoDictionaryGroupKeyGenerator play in pinot-core —
the general path behind the optimized ones. Everything here is vectorized
numpy over the segment memmaps; no Python-per-row loops.
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..query.context import AggExpr, QueryContext
from ..query.sql import (Between, BinaryOp, BoolAnd, BoolNot, BoolOr,
                         CaseWhen, Cast, Comparison, FuncCall, Identifier,
                         InList, IsNull, Like, Literal, SqlError, Star)
from ..query import functions as F
from ..ops import aggregations
from ..segment.immutable import ImmutableSegment


def virtual_column(seg, name: str) -> Optional[np.ndarray]:
    """$docId / $segmentName / $hostName (segment/virtualcolumn/
    VirtualColumnProvider analog) — synthesized, never stored."""
    if name == "$docId":
        return np.arange(seg.n_docs, dtype=np.int64)
    if name == "$segmentName":
        return np.full(seg.n_docs, seg.name, dtype=object)
    if name == "$hostName":
        import socket
        return np.full(seg.n_docs, socket.gethostname(), dtype=object)
    return None


def eval_value(e: Any, seg: ImmutableSegment,
               sel: Optional[np.ndarray] = None) -> np.ndarray:
    """Evaluate a value expression to a numpy array over (selected) docs."""
    if isinstance(e, Identifier):
        if e.name.startswith("$"):
            vc = virtual_column(seg, e.name)
            if vc is None:
                raise SqlError(f"unknown virtual column {e.name!r}")
            return vc[sel] if sel is not None else vc
        vals = seg.raw_values(e.name)
        return vals[sel] if sel is not None else vals
    if isinstance(e, Literal):
        return np.asarray(e.value)
    if isinstance(e, BinaryOp):
        l = eval_value(e.lhs, seg, sel)
        r = eval_value(e.rhs, seg, sel)
        if e.op == "+":
            return l + r
        if e.op == "-":
            return l - r
        if e.op == "*":
            return l * r
        if e.op == "/":
            return l.astype(np.float64) / np.asarray(r, dtype=np.float64)
        if e.op == "%":
            return l % r
        raise SqlError(f"unknown op {e.op}")
    if isinstance(e, FuncCall):
        return _eval_func(e, seg, sel)
    if isinstance(e, CaseWhen):
        return _eval_case(e, seg, sel)
    if isinstance(e, Cast):
        return F.cast_value(eval_value(e.expr, seg, sel), e.type_name)
    raise SqlError(f"unsupported value expression {e!r}")


def _eval_func(e: FuncCall, seg: ImmutableSegment,
               sel: Optional[np.ndarray]) -> np.ndarray:
    if e.name == "vector_similarity":
        # VECTOR_SIMILARITY as a VALUE (ORDER BY score / select-list
        # column): exact per-doc similarity over the selected rows —
        # the candidate SELECTION already ran on device through the
        # filter's memoized search (engine/vector_exec.py)
        from .vector_exec import order_scores
        return order_scores(seg, e, sel)
    fd = F.lookup(e.name)
    if fd is None:
        raise SqlError(f"unknown function {e.name!r}")
    # dictionary fast path (TransformFunction-over-dictionary analog):
    # an elementwise function of one dict-encoded column evaluates once per
    # dictionary value, then gathers by dict id — O(cardinality) not O(rows)
    col_args = [a for a in e.args if not isinstance(a, Literal)]
    if (fd.elementwise and len(col_args) == 1
            and isinstance(col_args[0], Identifier)):
        name = col_args[0].name
        m = seg.columns.get(name)
        if m is not None and m.has_dict and \
                not getattr(m, "is_multi_value", False):
            d = seg.dictionary(name)
            dvals = np.asarray(d.values)
            args = [dvals if a is col_args[0] else a.value for a in e.args]
            per_value = np.asarray(F.call(e.name, *args))
            if per_value.ndim == 1 and len(per_value) == len(dvals):
                ids = np.asarray(seg.fwd(name)).astype(np.int64)
                if sel is not None:
                    ids = ids[sel]
                return per_value[ids]
    args = [a.value if isinstance(a, Literal) else eval_value(a, seg, sel)
            for a in e.args]
    return np.asarray(F.call(e.name, *args))


def _eval_case(e: CaseWhen, seg: ImmutableSegment,
               sel: Optional[np.ndarray]) -> np.ndarray:
    conds = []
    vals = []
    for cond, res in e.whens:
        m = eval_filter(cond, seg)
        conds.append(m[sel] if sel is not None else m)
        vals.append(np.asarray(eval_value(res, seg, sel)))
    if e.else_ is not None:
        default = np.asarray(eval_value(e.else_, seg, sel))
    else:
        stringy = any(v.dtype == object or v.dtype.kind in "US"
                      for v in vals)
        default = np.asarray(None if stringy else np.nan)
    n = len(conds[0])
    vals = [np.broadcast_to(v, (n,)) for v in vals]
    default = np.broadcast_to(default, (n,))
    return np.select(conds, vals, default=default)


def null_aware(ctx) -> bool:
    """The enableNullHandling query option (QueryOptionsUtils analog).
    Accepts anything with .options (QueryContext or SelectStmt); shares
    the option-truthiness parser with the planner."""
    from ..query.planner import _truthy
    return _truthy(ctx.options.get("enableNullHandling"))


def expr_null_mask(e: Any, seg) -> Optional[np.ndarray]:
    """Union of null masks of every column referenced by e (a row is null
    for the expression if any input is null — SQL null propagation)."""
    from ..query.sql import collect_identifiers
    m: Optional[np.ndarray] = None
    for name in collect_identifiers(e):
        if name.startswith("$"):
            continue  # virtual columns are never null
        nm = seg.null_mask(name)
        if nm is not None:
            m = nm.copy() if m is None else (m | nm)
    return m


def eval_filter_3vl(e: Any, seg) -> Tuple[np.ndarray, np.ndarray]:
    """Three-valued-logic filter evaluation for enableNullHandling.

    Returns (T, F): rows where the predicate is definitely TRUE and
    definitely FALSE; the rest are UNKNOWN (some input was null). Mirrors
    Pinot's null-handling predicate semantics: a row passes the filter
    only when the predicate is TRUE. NOT maps UNKNOWN to UNKNOWN
    (T/F swap), AND/OR follow Kleene logic.
    """
    n = seg.n_docs
    if e is None:
        return np.ones(n, dtype=bool), np.zeros(n, dtype=bool)
    if isinstance(e, BoolAnd):
        T = np.ones(n, dtype=bool)
        F = np.zeros(n, dtype=bool)
        for c in e.children:
            t, f = eval_filter_3vl(c, seg)
            T &= t
            F |= f
        return T, F
    if isinstance(e, BoolOr):
        T = np.zeros(n, dtype=bool)
        F = np.ones(n, dtype=bool)
        for c in e.children:
            t, f = eval_filter_3vl(c, seg)
            T |= t
            F &= f
        return T, F
    if isinstance(e, BoolNot):
        t, f = eval_filter_3vl(e.child, seg)
        return f, t
    if isinstance(e, IsNull):
        nm = expr_null_mask(e.expr, seg)
        if nm is None:
            nm = np.zeros(n, dtype=bool)
        t = ~nm if e.negated else nm
        return t, ~t  # IS [NOT] NULL never yields UNKNOWN
    # leaf predicate: evaluate two-valued, then mark null inputs UNKNOWN.
    # negated leaves (NOT BETWEEN / NOT IN / NOT LIKE) stay UNKNOWN on null
    # inputs because both T and F are masked by `valid`.
    m = eval_filter(e, seg)
    nm = expr_null_mask(e, seg)
    if nm is None:
        return m, ~m
    valid = ~nm
    return m & valid, ~m & valid


def _like_regex(pattern: str) -> "re.Pattern":
    out = []
    for ch in pattern:
        out.append(".*" if ch == "%" else "." if ch == "_" else re.escape(ch))
    return re.compile("^" + "".join(out) + "$", re.DOTALL)


def _mv_meta(seg: ImmutableSegment, e: Any):
    if isinstance(e, Identifier):
        m = seg.columns.get(e.name)
        if m is not None and not getattr(m, "single_value", True):
            return m
    return None


def _mv_pred_mask(seg: ImmutableSegment, name: str, op: str,
                  val: Any) -> np.ndarray:
    """Any-over-values predicate on an MV column, in dict-id space (the
    host peer of kernels._mv_any; pad id -1 is inert)."""
    ids = np.asarray(seg.fwd(name))          # (n, M)
    d = seg.dictionary(name)
    svals = np.asarray(d.values)
    m = seg.columns[name]

    def coerce(v):
        if m.data_type.is_numeric and isinstance(v, str):
            return float(v) if ("." in v or "e" in v.lower()) else int(v)
        if not m.data_type.is_numeric:
            return str(v)
        return v

    if op in ("range", "not_range"):  # val = (lo, hi) incl; elementwise
        lo_v, hi_v = coerce(val[0]), coerce(val[1])
        lo = int(np.searchsorted(svals, lo_v, side="left"))
        hi = int(np.searchsorted(svals, hi_v, side="right"))
        inside = (ids >= lo) & (ids < hi)
        if op == "range":
            return inside.any(axis=1)
        # NOT BETWEEN: any value outside (value-level negation, reference
        # NotBetween applyMV); pads stay excluded
        return (~inside & (ids >= 0)).any(axis=1)
    if op == "not_in":     # val = list; any value not in the set
        dids = [d.index_of(coerce(v)) for v in val]
        hit = np.isin(ids, [i for i in dids if i >= 0])
        return (~hit & (ids >= 0)).any(axis=1)
    val = coerce(val)
    if op == "==":
        i = d.index_of(val)
        return (ids == i).any(axis=1) if i >= 0 \
            else np.zeros(len(ids), dtype=bool)
    if op == "!=":         # any value differs (value-level negation)
        i = d.index_of(val)
        return ((ids != i) & (ids >= 0)).any(axis=1)
    if op == "<":
        hi = int(np.searchsorted(svals, val, side="left"))
        return ((ids >= 0) & (ids < hi)).any(axis=1)
    if op == "<=":
        hi = int(np.searchsorted(svals, val, side="right"))
        return ((ids >= 0) & (ids < hi)).any(axis=1)
    if op == ">":
        lo = int(np.searchsorted(svals, val, side="right"))
        return (ids >= lo).any(axis=1)
    assert op == ">=", op
    lo = int(np.searchsorted(svals, val, side="left"))
    return (ids >= lo).any(axis=1)


def eval_filter(e: Any, seg: ImmutableSegment) -> np.ndarray:
    n = seg.n_docs
    if e is None:
        return np.ones(n, dtype=bool)
    if isinstance(e, BoolAnd):
        m = eval_filter(e.children[0], seg)
        for c in e.children[1:]:
            m = m & eval_filter(c, seg)
        return m
    if isinstance(e, BoolOr):
        m = eval_filter(e.children[0], seg)
        for c in e.children[1:]:
            m = m | eval_filter(c, seg)
        return m
    if isinstance(e, BoolNot):
        return ~eval_filter(e.child, seg)
    if isinstance(e, Comparison):
        mvm = _mv_meta(seg, e.lhs)
        if mvm is not None and isinstance(e.rhs, Literal):
            return _mv_pred_mask(seg, e.lhs.name, e.op, e.rhs.value)
        # InvertedIndexFilterOperator analog: EQ/NEQ on a dict column with
        # an inverted index answers in O(selectivity) from posting lists
        if e.op in ("==", "!=") and isinstance(e.lhs, Identifier) \
                and isinstance(e.rhs, Literal):
            m = seg.columns.get(e.lhs.name)
            if m is not None and getattr(m, "has_dict", False) \
                    and "inverted" in getattr(m, "indexes", {}):
                # coerce the literal like the scan path (_align_str) does;
                # on a non-coercible literal fall through so the scan path
                # raises the same SqlError as without the index
                val = e.rhs.value
                if m.data_type.is_numeric and isinstance(val, str):
                    try:
                        val = float(val) if ("." in val or "e" in val.lower()
                                            ) else int(val)
                    except ValueError:
                        val = None
                elif not m.data_type.is_numeric:
                    val = str(val)
                if val is not None:
                    d = seg.dictionary(e.lhs.name)
                    did = d.index_of(val)
                    mask = seg.index_reader(e.lhs.name, "inverted") \
                        .mask_for_ids([did] if did >= 0 else [], n)
                    return ~mask if e.op == "!=" else mask
        # RangeIndexBasedFilterOperator analog: chunk zone maps on raw
        # numeric columns let the scan skip non-candidate chunks entirely
        if e.op in ("<", "<=", ">", ">=", "==") \
                and isinstance(e.lhs, Identifier) \
                and isinstance(e.rhs, Literal) \
                and isinstance(e.rhs.value, (int, float)) \
                and not isinstance(e.rhs.value, bool):
            m = seg.columns.get(e.lhs.name)
            if m is not None and not getattr(m, "has_dict", False) \
                    and "range" in getattr(m, "indexes", {}):
                rd = seg.index_reader(e.lhs.name, "range")
                v = e.rhs.value
                lo, hi = {"<": (None, v), "<=": (None, v), ">": (v, None),
                          ">=": (v, None), "==": (v, v)}[e.op]
                np_op = {"==": np.equal, "<": np.less, "<=": np.less_equal,
                         ">": np.greater, ">=": np.greater_equal}[e.op]
                vals = np.asarray(seg.fwd(e.lhs.name))
                cand = rd.candidate_mask(lo, hi, n)
                mask = np.zeros(n, dtype=bool)
                mask[cand] = np_op(vals[:n][cand], v)
                return mask
        l = eval_value(e.lhs, seg)
        r = eval_value(e.rhs, seg)
        l, r = _align_str(l, r)
        ops = {"==": np.equal, "!=": np.not_equal, "<": np.less,
               "<=": np.less_equal, ">": np.greater, ">=": np.greater_equal}
        return np.broadcast_to(ops[e.op](l, r), (n,)).copy()
    if isinstance(e, Between):
        if _mv_meta(seg, e.expr) is not None \
                and isinstance(e.lo, Literal) and isinstance(e.hi, Literal):
            return _mv_pred_mask(seg, e.expr.name,
                                 "not_range" if e.negated else "range",
                                 (e.lo.value, e.hi.value))
        v = eval_value(e.expr, seg)
        lo = eval_value(e.lo, seg)
        hi = eval_value(e.hi, seg)
        v, lo = _align_str(v, lo)
        v, hi = _align_str(v, hi)
        m = (v >= lo) & (v <= hi)
        return ~m if e.negated else m
    if isinstance(e, InList):
        if _mv_meta(seg, e.expr) is not None:
            if e.negated:
                return _mv_pred_mask(seg, e.expr.name, "not_in",
                                     [x.value for x in e.values])
            m = np.zeros(seg.n_docs, dtype=bool)
            for x in e.values:
                m |= _mv_pred_mask(seg, e.expr.name, "==", x.value)
            return m
        v = eval_value(e.expr, seg)
        vals = [x.value for x in e.values]
        if v.dtype == object:
            vset = {str(x) for x in vals}
            m = np.asarray([x in vset for x in v], dtype=bool)
        else:
            m = np.isin(v, np.asarray(vals))
        return ~m if e.negated else m
    if isinstance(e, Like):
        v = eval_value(e.expr, seg)
        rx = _like_regex(e.pattern)
        # evaluate once per dictionary value when possible
        if isinstance(e.expr, Identifier) and \
                seg.columns[e.expr.name].has_dict:
            d = seg.dictionary(e.expr.name)
            ok = np.asarray([bool(rx.match(str(x))) for x in d.values])
            m = ok[np.asarray(seg.fwd(e.expr.name)).astype(np.int64)]
        else:
            m = np.asarray([bool(rx.match(str(x))) for x in v], dtype=bool)
        return ~m if e.negated else m
    if isinstance(e, IsNull):
        if isinstance(e.expr, Identifier):
            nm = seg.null_mask(e.expr.name)
            m = nm if nm is not None else np.zeros(n, dtype=bool)
        else:
            m = np.zeros(n, dtype=bool)
        return ~m if e.negated else m
    if isinstance(e, Literal) and isinstance(e.value, bool):
        return np.full(n, e.value, dtype=bool)
    if isinstance(e, FuncCall):
        from ..index.predicates import try_index_filter_mask
        idx_mask = try_index_filter_mask(seg, e)
        if idx_mask is not None:
            return idx_mask
    if isinstance(e, (FuncCall, Identifier, Cast, CaseWhen)):
        # boolean-valued expression used as a predicate
        # (startsWith(col, 'x'), boolean column, ...)
        v = np.asarray(eval_value(e, seg))
        if v.dtype != bool:
            v = v.astype(bool)
        return np.broadcast_to(v, (n,)).copy()
    raise SqlError(f"unsupported filter {e!r}")


def _align_str(l: np.ndarray, r: np.ndarray):
    l, r = np.asarray(l), np.asarray(r)
    l_str = l.dtype == object or l.dtype.kind in "US"
    r_str = r.dtype == object or r.dtype.kind in "US"
    if l_str and r_str:
        return (np.asarray(l, dtype=object).astype(str),
                np.asarray(r, dtype=object).astype(str))
    if l_str != r_str:
        # numeric column vs string literal: coerce the string side
        # (BadQueryRequestException analog on failure)
        s, n = (l, r) if l_str else (r, l)
        try:
            s_num = s.astype(np.float64)
        except ValueError:
            raise SqlError(
                f"cannot compare numeric and non-numeric value "
                f"{s.reshape(-1)[:1]}") from None
        return (s_num, n) if l_str else (n, s_num)
    return l, r


# ---------------------------------------------------------------------------
# host aggregation / group-by over a selected doc set
# ---------------------------------------------------------------------------

def host_aggregate(ctx: QueryContext, seg: ImmutableSegment,
                   mask: np.ndarray) -> List[Any]:
    """Per-segment states for ctx.aggregations (mergeable, value-space)."""
    sel = np.nonzero(mask)[0]
    na = null_aware(ctx)
    states: List[Any] = []
    for agg in ctx.aggregations:
        sel2 = _agg_sel(agg, seg, sel, na)
        s = _agg_state(agg, seg, sel2, na)
        if na and agg.kind in ("sum", "sum_mv") and len(sel2) == 0:
            s = None  # SUM over all-null input is null, not 0
            # (COUNT_MV stays 0 — count semantics)
        states.append(s)
    return states


def _agg_keep(agg: AggExpr, seg, sel: np.ndarray) -> Optional[np.ndarray]:
    """Boolean keep-mask over sel dropping rows whose aggregation input is
    null (NullableSingleInputAggregationFunction semantics); None when the
    inputs have no nulls. COUNT(*) (arg None) keeps every filtered row."""
    nm = None
    for arg in (agg.arg, agg.arg2):
        if arg is None or isinstance(arg, tuple):
            # tuple = funnel step predicates; a null input makes the
            # predicate false (SQL three-valued logic), not a skipped row
            continue
        m = expr_null_mask(arg, seg)
        if m is not None:
            nm = m if nm is None else (nm | m)
    return None if nm is None else ~nm[sel]


def _agg_sel(agg: AggExpr, seg, sel: np.ndarray, na: bool) -> np.ndarray:
    if not na:
        return sel
    keep = _agg_keep(agg, seg, sel)
    return sel if keep is None else sel[keep]


def _require_numeric(agg: AggExpr, vals: np.ndarray,
                     kinds: tuple) -> None:
    """Typed SqlError (not a raw numpy ValueError) when a numeric-only
    aggregation is fed a string expression — reference behavior: Pinot
    rejects SUM/AVG over STRING at plan time."""
    if agg.kind in kinds and vals.dtype.kind in "USO":
        raise SqlError(f"{agg.kind.upper()} requires numeric input; "
                       f"{agg.arg!r} is a string expression")


def _typed_ev(impl, agg: AggExpr, seg, sel: np.ndarray):
    """HostSel evaluator that rejects string inputs to numeric-only
    registry impls with a typed SqlError BEFORE the impl's math sees
    them (no numpy-message sniffing; impls that legitimately take
    strings set numeric_input = False)."""
    def ev(ast):
        vals = eval_value(ast, seg, sel)
        if impl.numeric_input and vals.dtype.kind in "USO":
            raise SqlError(f"{agg.kind.upper()} requires numeric input; "
                           f"{ast!r} is a string expression")
        return vals
    return ev


def _bool_ev(seg, sel: np.ndarray, na: bool = False):
    """HostSel.ev_bool: a boolean predicate AST evaluated over the
    selected docs (funnel step expressions). Under enableNullHandling
    only definitely-TRUE rows match (3VL: a null input never satisfies
    a step predicate)."""
    def ev_bool(ast):
        if na:
            t, _f = eval_filter_3vl(ast, seg)
            return np.asarray(t, dtype=bool)[sel]
        return np.asarray(eval_filter(ast, seg), dtype=bool)[sel]
    return ev_bool


def _agg_state(agg: AggExpr, seg: ImmutableSegment, sel: np.ndarray,
               na: bool = False) -> Any:
    if agg.kind == "count":
        return int(len(sel))
    # registry first: MV variants of extended kinds (hll_mv, ...) carry
    # their own impls; the classic six _mv kinds fall through (make ->
    # None) to the hand-coded path below
    impl = aggregations.make(agg)
    if impl is not None:
        h = aggregations.HostSel(_typed_ev(impl, agg, seg, sel), len(sel),
                                 ev_bool=_bool_ev(seg, sel, na))
        return impl.state(h)
    if agg.kind.endswith("_mv"):
        return _mv_agg_state(agg, seg, sel)
    vals = eval_value(agg.arg, seg, sel)
    _require_numeric(agg, vals, ("sum", "avg"))
    if agg.kind == "sum":
        if len(sel) == 0:
            return 0
        if np.issubdtype(vals.dtype, np.integer):
            return int(vals.astype(np.int64).sum())
        return float(vals.astype(np.float64).sum())
    if agg.kind in ("min", "max") and vals.dtype.kind in "USO":
        # lexicographic string min/max (numpy 2.x has no unicode
        # minimum ufunc — use the builtin over the selected values)
        if len(sel) == 0:
            return None
        pick = min if agg.kind == "min" else max
        return _scalar(pick(vals))
    if agg.kind == "min":
        return None if len(sel) == 0 else _scalar(vals.min())
    if agg.kind == "max":
        return None if len(sel) == 0 else _scalar(vals.max())
    if agg.kind == "avg":
        if len(sel) == 0:
            return (0.0, 0)
        return (float(vals.astype(np.float64).sum()), int(len(sel)))
    if agg.kind == "distinct_count":
        return set(np.unique(vals).tolist())
    raise SqlError(f"unknown aggregation {agg.kind}")


def _mv_agg_state(agg: AggExpr, seg: ImmutableSegment,
                  sel: np.ndarray) -> Any:
    """States for the MV aggregation family over list-valued rows (the
    host peer of the MvReduce device lowering; states match the base
    kind's — ops/aggregations.MV_BASE_KIND)."""
    rows = eval_value(agg.arg, seg, sel)  # object array of per-row lists
    return _mv_state_from_rows(agg.kind, rows)


def _mv_state_from_rows(k: str, rows) -> Any:
    if len(rows) and not isinstance(rows[0], (list, tuple, np.ndarray)):
        # single-value input would iterate characters (strings) or crash
        raise SqlError(f"{k.upper()} requires a multi-value column")
    if k == "count_mv":
        return int(sum(len(r) for r in rows))
    if k == "distinct_count_mv":
        out: set = set()
        for r in rows:
            out.update(_scalar(v) for v in r)
        return out
    flat = [v for r in rows for v in r]
    if k == "sum_mv":
        if not flat:
            return 0
        s = sum(flat)
        return int(s) if isinstance(s, (int, np.integer)) else float(s)
    if k == "min_mv":
        return _scalar(min(flat)) if flat else None
    if k == "max_mv":
        return _scalar(max(flat)) if flat else None
    if k == "avg_mv":
        return (float(sum(flat)), len(flat)) if flat else (0.0, 0)
    raise SqlError(f"unknown MV aggregation {k}")


def _scalar(v: Any) -> Any:
    return v.item() if isinstance(v, np.generic) else v


def _unique_inverse(v: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """np.unique(return_inverse) with a bytes-view fast path for
    fixed-width unicode keys: sorting UCS4 strings is the costliest op
    of a string group-by, but the same factorization falls out of an
    integer view (memcmp order) at a fraction of the cost. The small
    distinct set is then re-sorted lexicographically and codes remapped,
    so callers observe exact np.unique semantics."""
    if v.dtype.kind == "U" and v.dtype.itemsize in (4, 8) \
            and len(v) > 4096:
        iv = np.ascontiguousarray(v).view(
            np.int32 if v.dtype.itemsize == 4 else np.int64)
        ub, inv0 = np.unique(iv, return_inverse=True)
        reps = ub.view(v.dtype)
        order = np.argsort(reps, kind="stable")
        rank = np.empty(len(order), dtype=inv0.dtype)
        rank[order] = np.arange(len(order), dtype=inv0.dtype)
        return reps[order], rank[inv0]
    return np.unique(v, return_inverse=True)


def host_group_by(ctx: QueryContext, seg: ImmutableSegment,
                  mask: np.ndarray) -> Dict[Tuple, List[Any]]:
    """Vectorized hash group-by: composite codes from per-key np.unique,
    np.bincount / ufunc.at per aggregation. IndexedTable-general analog."""
    sel = np.nonzero(mask)[0]
    nsel = len(sel)
    if nsel == 0:
        return {}
    na = null_aware(ctx)

    # MV group key: a row joins EVERY group of its values (reference MV
    # GroupKeyGenerator semantics) — expand matched rows to (row, value)
    # pairs; SV keys and aggregation inputs repeat per pair
    mv_flat: Dict[int, np.ndarray] = {}
    mv_keys = [ki for ki, g in enumerate(ctx.group_by)
               if isinstance(g, Identifier)
               and g.name in seg.columns
               and not getattr(seg.columns[g.name], "single_value", True)]
    if len(mv_keys) > 1:
        raise SqlError("GROUP BY supports at most one multi-value column")
    if mv_keys:
        ki = mv_keys[0]
        rows = eval_value(ctx.group_by[ki], seg, sel)
        lens = np.fromiter((len(r) for r in rows), dtype=np.int64,
                           count=len(rows))
        sel = np.repeat(sel, lens)
        nsel = len(sel)
        if nsel == 0:
            return {}
        flat = [v for r in rows for v in r]
        mv_flat[ki] = np.asarray(flat)

    codes = np.zeros(nsel, dtype=np.int64)
    uniques: List[Tuple[np.ndarray, bool]] = []
    for ki, g in enumerate(ctx.group_by):
        v = mv_flat[ki] if ki in mv_flat else eval_value(g, seg, sel)
        if v.dtype == object:
            v = v.astype(str)
        nm = expr_null_mask(g, seg) if na else None
        f = nm[sel] if nm is not None else None
        if f is not None and f.any():
            # null keys form their own group: encode the null flag as an
            # extra factor so the stored default value never collides
            vv = v.copy()
            vv[f] = vv[~f][0] if (~f).any() else vv[0]
            u, inv = _unique_inverse(vv)
            codes = (codes * len(u) + inv) * 2 + f
            uniques.append((u, True))
        else:
            u, inv = _unique_inverse(v)
            codes = codes * len(u) + inv
            uniques.append((u, False))
    ucodes, inv = np.unique(codes, return_inverse=True)
    n_groups = len(ucodes)

    # decode group keys: recover per-key value by walking codes backwards
    key_cols: List[List[Any]] = []
    rem = ucodes.copy()
    for u, has_null_flag in reversed(uniques):
        if has_null_flag:
            flag = rem % 2
            rem = rem // 2
            vals = u[rem % len(u)]
            key_cols.append([None if flag[i] else _scalar(vals[i])
                             for i in range(n_groups)])
        else:
            key_cols.append([_scalar(x) for x in (u[rem % len(u)])])
        rem = rem // len(u)
    key_cols.reverse()
    keys = list(zip(*key_cols))

    out: Dict[Tuple, List[Any]] = {tuple(k): [] for k in keys}
    na = null_aware(ctx)
    for agg in ctx.aggregations:
        keep = _agg_keep(agg, seg, sel) if na else None
        if keep is None or keep.all():
            per_group = _group_states(agg, seg, sel, inv, n_groups, na)
        else:
            per_group = _group_states(agg, seg, sel[keep], inv[keep],
                                      n_groups, na)
            if agg.kind in ("sum", "min", "max", "avg"):
                # groups whose inputs were all null -> null result, not a
                # sentinel from the empty reduction
                cnt = np.bincount(inv[keep], minlength=n_groups)
                per_group = [None if cnt[gi] == 0 else per_group[gi]
                             for gi in range(n_groups)]
        for gi, k in enumerate(keys):
            out[tuple(k)].append(per_group[gi])
    return out


def _group_states(agg: AggExpr, seg: ImmutableSegment, sel: np.ndarray,
                  inv: np.ndarray, n_groups: int,
                  na: bool = False) -> List[Any]:
    if agg.kind == "count":
        c = np.bincount(inv, minlength=n_groups)
        return [int(x) for x in c]
    impl = aggregations.make(agg)  # extended registry kinds (MV incl.)
    if impl is not None:
        h = aggregations.HostSel(_typed_ev(impl, agg, seg, sel),
                                 len(sel), inv, n_groups,
                                 ev_bool=_bool_ev(seg, sel, na))
        return impl.group_states(h)
    if agg.kind.endswith("_mv"):
        # evaluate the MV column ONCE, then sort-split — calling
        # _mv_agg_state per group would re-decode the whole MV forward
        # index per group (O(n_groups * n), seconds at a few hundred
        # groups; round-4 fuzzer finding)
        rows_all = eval_value(agg.arg, seg, sel)
        order = np.argsort(inv, kind="stable")
        bounds = np.searchsorted(inv[order], np.arange(n_groups + 1))
        sorted_rows = rows_all[order]
        return [_mv_state_from_rows(agg.kind,
                                    sorted_rows[bounds[gi]:bounds[gi + 1]])
                for gi in range(n_groups)]
    vals = eval_value(agg.arg, seg, sel)
    _require_numeric(agg, vals, ("sum", "avg"))
    if agg.kind in ("min", "max") and vals.dtype.kind in "USO":
        # lexicographic string min/max per group (matches the ungrouped
        # path's vals.min()/.max() semantics) via one stable sort-split
        order = np.argsort(inv, kind="stable")
        sv, si = vals[order], inv[order]
        bounds = np.searchsorted(si, np.arange(n_groups + 1))
        pick = min if agg.kind == "min" else max
        return [_scalar(pick(sv[bounds[g]:bounds[g + 1]]))
                if bounds[g + 1] > bounds[g] else None
                for g in range(n_groups)]
    if agg.kind == "sum":
        if np.issubdtype(vals.dtype, np.integer):
            s2 = np.zeros(n_groups, dtype=np.int64)  # exact int accumulation
            np.add.at(s2, inv, vals.astype(np.int64))
            return [int(x) for x in s2]
        s = np.bincount(inv, weights=vals.astype(np.float64),
                        minlength=n_groups)
        return [float(x) for x in s]
    if agg.kind == "min":
        m = np.full(n_groups, np.inf)
        np.minimum.at(m, inv, vals.astype(np.float64))
        if np.issubdtype(vals.dtype, np.integer):
            mi = np.full(n_groups, np.iinfo(np.int64).max, dtype=np.int64)
            np.minimum.at(mi, inv, vals.astype(np.int64))
            return [int(x) for x in mi]
        return [float(x) for x in m]
    if agg.kind == "max":
        if np.issubdtype(vals.dtype, np.integer):
            ma = np.full(n_groups, np.iinfo(np.int64).min, dtype=np.int64)
            np.maximum.at(ma, inv, vals.astype(np.int64))
            return [int(x) for x in ma]
        m = np.full(n_groups, -np.inf)
        np.maximum.at(m, inv, vals.astype(np.float64))
        return [float(x) for x in m]
    if agg.kind == "avg":
        s = np.zeros(n_groups, dtype=np.float64)
        np.add.at(s, inv, vals.astype(np.float64))
        c = np.bincount(inv, minlength=n_groups)
        return [(float(s[i]), int(c[i])) for i in range(n_groups)]
    if agg.kind == "distinct_count":
        sets: List[set] = [set() for _ in range(n_groups)]
        if vals.dtype == object:
            vals = vals.astype(str)
        order = np.argsort(inv, kind="stable")
        sorted_inv = inv[order]
        sorted_vals = vals[order]
        bounds = np.searchsorted(sorted_inv, np.arange(n_groups + 1))
        for gi in range(n_groups):
            sets[gi] = set(np.unique(
                sorted_vals[bounds[gi]:bounds[gi + 1]]).tolist())
        return sets
    raise SqlError(f"unknown aggregation {agg.kind}")


def host_selection(ctx: QueryContext, seg: ImmutableSegment,
                   mask: np.ndarray) -> Tuple[List[str], List[tuple],
                                              List[tuple]]:
    """Selection query over one segment -> (labels, rows, order_keys).

    Without ORDER BY, stops at offset+limit rows (SelectionOnlyOperator
    early-exit). With ORDER BY, returns the per-segment top
    offset+limit rows plus their sort keys for the merge at reduce.
    """
    sel = np.nonzero(mask)[0]
    need = None
    if ctx.limit is not None:
        need = ctx.offset + ctx.limit
    if not ctx.order_by and need is not None:
        sel = sel[:need]

    # expand *
    exprs: List[Any] = []
    labels: List[str] = []
    for item, label in zip(ctx.select_items, ctx.labels):
        if isinstance(item, Star):
            for cname in seg.schema.column_names:
                exprs.append(Identifier(cname))
                labels.append(cname)
        else:
            exprs.append(item)
            labels.append(label)

    order_vals: List[np.ndarray] = []
    if ctx.order_by:
        for o in ctx.order_by:
            v = eval_value(o.expr, seg, sel)
            if v.dtype == object:
                v = v.astype(str)
            order_vals.append(np.broadcast_to(v, (len(sel),)))
        # per-segment partial sort down to `need`
        idx = np.lexsort([
            (ov if o.ascending else _invert_order(ov))
            for o, ov in reversed(list(zip(ctx.order_by, order_vals)))])
        if need is not None:
            idx = idx[:need]
        sel = sel[idx]
        order_vals = [ov[idx] for ov in order_vals]

    cols = [np.broadcast_to(eval_value(e, seg, sel), (len(sel),))
            for e in exprs]
    if null_aware(ctx):
        # surface stored default values as real nulls in the result rows
        out_cols: List[np.ndarray] = []
        for e, c in zip(exprs, cols):
            nm = expr_null_mask(e, seg)
            if nm is not None and nm[sel].any():
                c = c.astype(object)
                c[nm[sel]] = None
            out_cols.append(c)
        cols = out_cols
    rows = [tuple(_scalar(c[i]) for c in cols) for i in range(len(sel))]
    okeys = [tuple(_scalar(ov[i]) for ov in order_vals)
             for i in range(len(sel))] if ctx.order_by else []
    return labels, rows, okeys


def _invert_order(v: np.ndarray) -> np.ndarray:
    if v.dtype.kind in "iuf":
        return -v.astype(np.float64)
    # strings: rank-invert
    u, inv = np.unique(v, return_inverse=True)
    return -inv.astype(np.int64)
