from .executor import SegmentExecutor, execute_segment  # noqa: F401
from .reduce import ResultTable, reduce_partials  # noqa: F401
