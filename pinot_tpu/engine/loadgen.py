"""Sustained multi-partition ingest-while-query load generation.

ISSUE 11 tentpole (ROADMAP direction 4): round 11 built the ingest
chaos substrate — six fault points, recovery muscle, the
``ingest_stats`` freshness ledger — and the ingest-vs-oracle fuzzer
drives it to a drained stream, but nothing exercised the RATE half:
freshness under sustained multi-partition pressure WHILE a concurrent
query mix runs, chaos armed. This module is that closed-loop harness,
the robustness analogue of what bench.py's query loop is for latency:

- **producers** push seeded row sequences into real wire-protocol
  stream backends (the kafka / kinesis / pulsar protocol fakes, the
  wirestream TCP broker, or the in-memory fake) at a target per-
  partition rate (or flat-out in drain mode);
- **consumers** drive ``RealtimeTableDataManager`` partitions exactly
  like its own ``_consume_loop`` — but under loadgen supervision, so an
  injected ``IngestCrash`` (commit.crash / upsert.compact_crash) kills
  the whole manager like a real process death and the supervisor
  restarts it from the durable checkpoint, counting restarts;
- **query workers** run a seeded mix through the real Broker path
  concurrently with ingest, each query NAMED (``OPTION(queryId=...)``)
  so the per-query fault streams (utils/faults.py round-16 rekeying)
  are reproducible and the run composes with micro-batching armed;
- a **sampler** trends each table's ``ingest_stats()`` (fetch->
  queryable freshness EWMA) into p50/p99 series, and per-commit
  latencies aggregate from ``manager.commit_latencies()``;
- the run ends **drained**: producers done, every partition's
  delivered-rows counter caught up, pending protocol commits settled —
  then the final queryable state (through the Broker) is diffed
  byte-exact against the fault-free oracle
  (pinot_tpu/tools/ingest_fuzz.oracle_rows per partition).

The summary dict is shaped for the validated ``ingest_bench`` ledger
kind (utils/ledger.py); ``write_ingest_bench`` appends it, and each
table also lands an ``ingest_stats`` record carrying its freshness
percentiles so the round-14 fleet rollup trends them per table.
Consumers: bench_ingest.py (the CLI bench), tools/freshness_gate.py
(the ratchet's capture corpus), tools/chaos_smoke.py --rate and
tests/test_ingest_bench.py.
"""
from __future__ import annotations

import hashlib
import json
import threading
import time
import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..realtime import InMemoryStream, RealtimeTableDataManager, \
    StreamConfig
from ..spi import DataType, FieldSpec, FieldType, Schema
from ..upsert import UpsertConfig
from ..utils import faults
# the fleet-shared percentile definition: ingest-bench freshness trend
# lines must stay comparable with the rollup's per-table aggregation
from ..utils.stats import pctl

BACKENDS = ("mem", "wire", "kafka", "kinesis", "pulsar")

# the query mix (formatted per table): integral-SUM group-bys that are
# micro-batch fusable, a scalar aggregation, and a MIN/MAX shape that
# always dispatches solo — so a concurrent run exercises fused AND solo
# paths against moving realtime snapshots
QUERY_MIX = (
    "SELECT COUNT(*), SUM(val) FROM {t}",
    "SELECT pk, COUNT(*), SUM(val) FROM {t} GROUP BY pk "
    "ORDER BY pk LIMIT 64",
    "SELECT pk, SUM(ts) FROM {t} WHERE val < 500 GROUP BY pk "
    "ORDER BY pk LIMIT 64",
    "SELECT MIN(val), MAX(ts) FROM {t}",
)

N_PKS = 13          # colliding PKs (the ingest_fuzz upsert regime)
MAX_RESTARTS = 200  # crash/restart budget before declaring non-recovery




def loadgen_schema(table: str) -> Schema:
    """The pk/ts/val shape shared with tools/ingest_fuzz so its oracle
    (append exactly-once / upsert latest-wins) applies verbatim."""
    return Schema(table, [
        FieldSpec("pk", DataType.INT),
        FieldSpec("ts", DataType.INT, FieldType.METRIC),
        FieldSpec("val", DataType.INT, FieldType.METRIC),
    ])


def gen_partition_rows(seed: int, table_idx: int, partition: int,
                       n: int) -> List[Dict[str, int]]:
    """Seeded per-partition row sequence: colliding PKs + tie-heavy
    out-of-order ts (upsert latest-wins genuinely exercised). Pure in
    (seed, table_idx, partition, n) — same-seed runs produce identical
    streams, which is what makes the final oracle diff byte-exact."""
    rng = np.random.default_rng([seed, table_idx, partition])
    pks = rng.integers(0, N_PKS, n)
    ts = rng.integers(0, max(2, n // 3), n)
    vals = rng.integers(0, 1000, n)
    # host-only numpy scalars (seeded producer data, never on device)
    return [{"pk": int(pks[i]), "ts": int(ts[i]),  # jaxlint: ok host-sync
             "val": int(vals[i])}  # jaxlint: ok host-sync
            for i in range(n)]


@dataclass
class TableLoadSpec:
    """One ingest table in the run."""
    name: str
    partitions: int = 2
    upsert: bool = False
    protocol: bool = False      # controller-arbitrated split commits
    threshold: int = 64         # flush_threshold_rows
    backend: str = "mem"        # mem | wire | kafka | kinesis | pulsar


# ---------------------------------------------------------------------------
# stream backends: one uniform (factory, produce, close) per protocol
# ---------------------------------------------------------------------------

class _Backend:
    """A live stream transport: SPI consumer factory + a producer
    callable ``produce(partition, rows)`` + teardown."""

    def __init__(self, factory, produce: Callable[[int, List[dict]], None],
                 close: Callable[[], None]):
        self.factory = factory
        self.produce = produce
        self.close = close


class _PerPartition:
    """Lazily one protocol client per partition (creation guarded; use
    is single-threaded per partition by construction)."""

    def __init__(self, make: Callable[[int], Any]):
        self._make = make
        self._lock = threading.Lock()
        self._by_p: Dict[int, Any] = {}

    def get(self, p: int) -> Any:
        with self._lock:
            c = self._by_p.get(p)
        if c is None:
            # construct OUTSIDE the lock (opens a connection); a lost
            # duplicate is just closed by the setdefault loser's GC
            c = self._make(p)
            with self._lock:
                c = self._by_p.setdefault(p, c)
        return c

    def close_all(self) -> None:
        with self._lock:
            clients = list(self._by_p.values())
            self._by_p.clear()
        for c in clients:
            try:
                c.close()
            except Exception:
                pass


def _kinesis_shard_keys(n_shards: int) -> List[str]:
    """One partition key per target shard (the fake routes by
    md5(key) % shards, like the real service's hash-key ranges)."""
    keys: List[Optional[str]] = [None] * n_shards
    i = 0
    while any(k is None for k in keys):
        k = f"pk{i}"
        shard = int(hashlib.md5(k.encode()).hexdigest(), 16) % n_shards
        if keys[shard] is None:
            keys[shard] = k
        i += 1
    return [k for k in keys if k is not None]


def make_backend(spec: TableLoadSpec, data_dir: str) -> _Backend:
    """Spin up the protocol fake for one table and return the uniform
    produce/consume endpoints. All fakes are in-process but speak their
    REAL wire protocol (TCP for kafka/pulsar/wirestream, SigV4 HTTP for
    kinesis), so the rate harness exercises the same consumer code
    paths production would."""
    if spec.backend == "mem":
        stream = InMemoryStream(spec.partitions, name=spec.name)

        def produce_mem(p: int, rows: List[dict]) -> None:
            for r in rows:
                stream.produce(r, p)
        return _Backend(stream, produce_mem, lambda: None)

    # the protocol clients below are single-connection and NOT
    # thread-safe; each partition has exactly one producer thread, so
    # every partition gets its own client (created lazily on the
    # producing thread)
    if spec.backend == "wire":
        from ..realtime.wirestream import (WireBroker, WireProducer,
                                           WireStream)
        broker = WireBroker(num_partitions=spec.partitions,
                            log_dir=os.path.join(data_dir, "wal"))
        prods = _PerPartition(
            lambda p: WireProducer("127.0.0.1", broker.port))

        def produce_wire(p: int, rows: List[dict]) -> None:
            prods.get(p).produce_many(rows, p)

        def close_wire() -> None:
            prods.close_all()
            broker.stop()
        return _Backend(WireStream("127.0.0.1", port=broker.port),
                        produce_wire, close_wire)

    if spec.backend == "kafka":
        from ..realtime.kafka import (FakeKafkaBroker, KafkaProducer,
                                      KafkaStream)
        broker = FakeKafkaBroker({spec.name: spec.partitions})
        prods = _PerPartition(
            lambda p: KafkaProducer("127.0.0.1", broker.port))

        def produce_kafka(p: int, rows: List[dict]) -> None:
            prods.get(p).produce_many(spec.name, p, rows)

        def close_kafka() -> None:
            prods.close_all()
            broker.stop()
        return _Backend(KafkaStream(spec.name, port=broker.port),
                        produce_kafka, close_kafka)

    if spec.backend == "kinesis":
        from ..realtime.kinesis import (FakeKinesisServer, KinesisClient,
                                        KinesisStream)
        srv = FakeKinesisServer({spec.name: spec.partitions},
                                access_key="AK", secret_key="SK")
        prods = _PerPartition(
            lambda p: KinesisClient(srv.endpoint_url, "AK", "SK"))
        shard_keys = _kinesis_shard_keys(spec.partitions)

        def produce_kinesis(p: int, rows: List[dict]) -> None:
            client = prods.get(p)
            for r in rows:
                client.put_record(spec.name, json.dumps(r).encode(),
                                  shard_keys[p])
        return _Backend(
            KinesisStream(spec.name, srv.endpoint_url,
                          access_key="AK", secret_key="SK"),
            produce_kinesis, srv.stop)

    if spec.backend == "pulsar":
        from ..realtime.pulsar import (FakePulsarBroker, PulsarProducer,
                                       PulsarStream)
        topics = [f"{spec.name}-partition-{p}"
                  for p in range(spec.partitions)]
        broker = FakePulsarBroker(topics)
        prods = _PerPartition(
            lambda p: PulsarProducer("127.0.0.1", broker.port))

        def produce_pulsar(p: int, rows: List[dict]) -> None:
            prods.get(p).send_many(f"{spec.name}-partition-{p}", rows)
        return _Backend(
            PulsarStream(spec.name, port=broker.port,
                         partitions=spec.partitions),
            produce_pulsar, broker.stop)

    raise ValueError(f"unknown backend {spec.backend!r}; "
                     f"have {list(BACKENDS)}")


# ---------------------------------------------------------------------------
# per-table runtime: manager generations + crash/restart supervision
# ---------------------------------------------------------------------------

class _TableRun:
    """One table's live state. The manager is the 'process': an
    injected IngestCrash abandons it wholesale and a fresh one restarts
    from the durable checkpoint (orphan cleanup + metadata replay), the
    supervision contract tools/ingest_fuzz.IngestRun pins for one
    partition — here generation-numbered so every partition's consumer
    thread migrates to the restarted manager."""

    def __init__(self, idx: int, spec: TableLoadSpec, data_dir: str,
                 register: Callable[[RealtimeTableDataManager], None],
                 fetch_backoff_s: float = 0.002):
        self.idx = idx
        self.spec = spec
        self.data_dir = data_dir
        self.backend = make_backend(spec, data_dir)
        self._register = register
        self.fetch_backoff_s = fetch_backoff_s
        self.lock = threading.Lock()
        self._quiesce = threading.Condition(self.lock)
        self.active = 0        # consumer threads inside manager work
        self.generation = 0
        self.restarting = False
        self.restarts = 0
        self.produced: List[int] = [0] * spec.partitions
        self.producers_done = 0
        self.commit_ms: List[float] = []      # drained from dead managers
        self.freshness_samples: List[float] = []
        self.completion = None
        self.registry: Dict[Tuple[str, str], Dict[str, Any]] = {}
        if spec.protocol:
            from ..cluster.completion import SegmentCompletionManager
            self.completion = SegmentCompletionManager(
                lambda t: 1, decision_window_s=0.0,
                registered_segment=lambda t, s: self.registry.get((t, s)))
        self.manager = self._make_manager()
        self._register(self.manager)

    def _make_manager(self) -> RealtimeTableDataManager:
        spec = self.spec
        cfg = StreamConfig(
            spec.name, num_partitions=spec.partitions,
            flush_threshold_rows=spec.threshold,
            consumer_factory=self.backend.factory,
            fetch_backoff_s=self.fetch_backoff_s)
        cc = None
        if spec.protocol:
            from ..cluster.completion import LocalCompletionClient
            cc = LocalCompletionClient(
                self.completion, f"lg_{spec.name}",
                f"file://{self.data_dir}/deepstore", self.registry)
        ucfg = UpsertConfig(["pk"], comparison_column="ts") \
            if spec.upsert else None
        m = RealtimeTableDataManager(
            spec.name, loadgen_schema(spec.name), cfg,
            os.path.join(self.data_dir, "server"),
            upsert_config=ucfg, completion_client=cc)
        m.report_interval_s = 0.0
        return m

    def current(self) -> Tuple[int, RealtimeTableDataManager]:
        """A consistent (generation, manager) pair. Waits out an
        in-flight restart: between the generation bump and the manager
        swap the pair would read (new generation, OLD manager) — a
        consumer holding that ticket would keep consuming into the
        abandoned manager forever (its rows invisible to queries, the
        real tail never drained)."""
        with self.lock:
            while self.restarting:
                self._quiesce.wait(0.25)
            return self.generation, self.manager

    def current_generation(self) -> int:
        with self.lock:
            return self.generation

    def enter(self, gen: int) -> bool:
        """Begin one unit of manager work on generation ``gen``.
        False = the generation moved (a crash/restart happened, or one
        is in flight): the caller must re-fetch the current manager."""
        with self.lock:
            if self.restarting or self.generation != gen:
                return False
            self.active += 1
            return True

    def exit(self) -> None:
        with self.lock:
            self.active -= 1
            self._quiesce.notify_all()

    def crash(self, gen: int) -> None:
        """IngestCrash observed on generation ``gen``: simulate the
        process death — abandon the manager, restart from the durable
        checkpoint. A real kill -9 stops every partition's consumer at
        once, so the restart QUIESCES first: the generation bump stops
        new enter()s, then the rebuild waits until every peer thread
        has left the old manager (seals in flight included) — without
        the barrier, the new manager's orphan cleanup races a zombie
        seal and deletes the segment it is writing. The rebuild
        (checkpoint read + metadata replay, disk-only) then serializes
        the whole table under the run lock — that IS the restart."""
        with self.lock:
            if self.generation != gen:
                return              # a peer thread already restarted
            self.generation += 1
            self.restarts += 1
            self.restarting = True
            try:
                # bounded quiesce: peers are in consume/seal work units
                # that finish in at most a few fetch-retry backoffs
                deadline = time.monotonic() + 30.0
                while self.active > 0 and time.monotonic() < deadline:
                    self._quiesce.wait(0.25)
                old = self.manager
                self.commit_ms.extend(old.commit_latencies())
                while True:
                    try:
                        self.manager = self._make_manager()
                        break
                    except faults.IngestCrash:
                        # crash inside the restart replay itself
                        self.restarts += 1
                        if self.restarts > MAX_RESTARTS:
                            raise RuntimeError(
                                f"{self.spec.name}: no recovery within "
                                f"{MAX_RESTARTS} restarts")
                self._register(self.manager)
            finally:
                # always released — current() waiters must not hang on
                # a blown restart budget
                self.restarting = False
                self._quiesce.notify_all()

    def note_produced(self, p: int, n: int) -> None:
        with self.lock:
            self.produced[p] += n

    def total_produced(self) -> int:
        with self.lock:
            return sum(self.produced)

    def producer_done(self) -> None:
        with self.lock:
            self.producers_done += 1

    def drained(self) -> bool:
        """All producers finished AND every produced row is queryable
        (committed segments + consuming snapshots — durable state, so
        the check survives crash/restart where the per-manager ``rows``
        counter resets) AND no partition still owes the completion
        protocol a commit. Exactly-once delivery means the doc total
        converges to the produced total from below."""
        with self.lock:
            if self.producers_done < self.spec.partitions:
                return False
            total = sum(self.produced)
            m = self.manager
        docs = sum(s.n_docs for s in m.acquire_segments())
        if docs < total:
            return False
        if self.spec.protocol:
            for mut in list(m._mutables.values()):
                if mut.n_docs >= self.spec.threshold:
                    return False    # commit owed: keep polling
        return True

    def sample_freshness(self) -> None:
        f = self.current()[1].ingest_stats()["freshness_ms"]
        if f is not None:
            with self.lock:
                self.freshness_samples.append(float(f))

    def raw_series(self) -> Tuple[List[float], List[float]]:
        """(freshness samples, per-commit latencies) — the manager's
        history is read before taking the run lock (commit_latencies
        takes the manager's own stats lock; no nesting)."""
        _gen, m = self.current()
        mlat = m.commit_latencies()
        with self.lock:
            return (list(self.freshness_samples),
                    self.commit_ms + mlat)

    def final_stats(self) -> Dict[str, Any]:
        _gen, m = self.current()
        fresh, commits = self.raw_series()
        fresh = sorted(fresh)
        commits = sorted(commits)
        with self.lock:
            restarts = self.restarts
        st = m.ingest_stats()
        st.update(
            restarts=restarts,
            freshness_p50_ms=round(pctl(fresh, 0.5), 3),
            freshness_p99_ms=round(pctl(fresh, 0.99), 3),
            commit_p50_ms=round(pctl(commits, 0.5), 3),
            commit_p99_ms=round(pctl(commits, 0.99), 3))
        return st

    def oracle_digest(self, seed: int,
                      rows_per_partition: int) -> List[Tuple[int, ...]]:
        from ..tools.ingest_fuzz import digest, oracle_rows
        expected: List[Tuple[int, int, int]] = []
        for p in range(self.spec.partitions):
            expected.extend(oracle_rows(
                gen_partition_rows(seed, self.idx, p, rows_per_partition),
                self.spec.upsert))
        return digest(expected)

    def close(self) -> None:
        try:
            self.current()[1].stop(timeout=1.0)
        finally:
            self.backend.close()


# ---------------------------------------------------------------------------
# the harness
# ---------------------------------------------------------------------------

@dataclass
class LoadgenConfig:
    tables: List[TableLoadSpec] = field(default_factory=lambda: [
        TableLoadSpec("lg_append", partitions=2),
        TableLoadSpec("lg_upsert", partitions=2, upsert=True,
                      protocol=True),
    ])
    seed: int = 0
    rows_per_partition: int = 400
    rate_rows_s: Optional[float] = None   # per partition; None = flat out
    query_concurrency: int = 2
    query_timeout_ms: int = 300_000
    # per-worker think time between queries: sustained pressure, not a
    # saturation attack — flat-out workers starve the consumer threads
    # of CPU and a chaos tail (rebalance resets re-consuming a starved
    # tail) can livelock against the wall cap. 0 = flat out.
    query_think_s: float = 0.01
    sample_interval_s: float = 0.02
    poll_interval_s: float = 0.005
    max_wall_s: float = 120.0             # hard cap (chaos stall guard)
    scenario: str = "loadgen"
    fault_plan: Optional[str] = None      # PINOT_FAULTS grammar; armed
    # around the whole run (producers+consumers+queries) when set
    ledger_path: Optional[str] = None     # when set, run_load appends
    # ONE validated ingest_bench record + one ingest_stats per table


class IngestLoadGen:
    """One closed-loop ingest-while-query run (module docstring)."""

    def __init__(self, data_dir: str, config: LoadgenConfig):
        from ..broker import Broker
        self.cfg = config
        self.data_dir = data_dir
        os.makedirs(data_dir, exist_ok=True)
        self.broker = Broker()
        self.tables = [
            _TableRun(i, spec, os.path.join(data_dir, spec.name),
                      self.broker.register_table)
            for i, spec in enumerate(config.tables)]
        self._stop = threading.Event()       # consumers + sampler
        self._qstop = threading.Event()      # query workers
        self._qlock = threading.Lock()
        self._q_lat: List[float] = []
        self._q_errors = 0
        self._fatal: List[str] = []

    # -- producer ----------------------------------------------------------
    def _produce_loop(self, table: _TableRun, p: int) -> None:
        cfg = self.cfg
        rows = gen_partition_rows(cfg.seed, table.idx, p,
                                  cfg.rows_per_partition)
        chunk = 64
        t0 = time.monotonic()
        sent = 0
        try:
            while sent < len(rows) and not self._stop.is_set():
                if cfg.rate_rows_s is not None:
                    # pace against the wall-clock schedule, never ahead
                    due = int((time.monotonic() - t0) * cfg.rate_rows_s)
                    if due <= sent:
                        time.sleep(min(chunk / cfg.rate_rows_s, 0.02))
                        continue
                    batch = rows[sent:min(sent + min(due - sent, chunk),
                                          len(rows))]
                else:
                    batch = rows[sent:sent + chunk]
                for attempt in range(3):
                    try:
                        table.backend.produce(p, batch)
                        break
                    except Exception:
                        # transport hiccup on a fake's TCP path: bounded
                        # retry — a re-produce would double rows, so give
                        # up loudly past the budget
                        if attempt == 2:
                            raise
                        time.sleep(0.05)
                sent += len(batch)
                table.note_produced(p, len(batch))
        except Exception as e:  # noqa: BLE001 — surfaced in the summary
            with self._qlock:
                self._fatal.append(
                    f"producer {table.spec.name}/{p}: "
                    f"{type(e).__name__}: {e}")
        finally:
            table.producer_done()

    # -- consumer (supervised _consume_loop analog) ------------------------
    def _consume_loop(self, table: _TableRun, p: int) -> None:
        poll = self.cfg.poll_interval_s
        while not self._stop.is_set():
            gen, m = table.current()
            try:
                consumer = \
                    m.stream_config.consumer_factory.create_consumer(p)
            except Exception:
                if self._stop.wait(poll):
                    return
                continue
            try:
                while not self._stop.is_set():
                    if not table.enter(gen):
                        break       # generation moved: re-fetch manager
                    crashed = False
                    try:
                        n = m.consume_once(p, consumer)
                        m._maybe_seal(p)
                    except faults.IngestCrash:
                        crashed = True
                    except Exception:
                        # transient trouble past the bounded retries:
                        # back off one poll, keep the consumer alive
                        n = 0
                    finally:
                        # leave the work unit BEFORE restarting: the
                        # quiesce barrier counts this thread out
                        table.exit()
                    if crashed:
                        try:
                            table.crash(gen)
                        except Exception as e:  # restart budget blown
                            with self._qlock:
                                self._fatal.append(
                                    f"{table.spec.name}: "
                                    f"{type(e).__name__}: {e}")
                            return
                        break  # re-enter on the new generation
                    if n == 0 and self._stop.wait(poll):
                        break
            finally:
                try:
                    consumer.close()
                except Exception:
                    pass

    # -- query mix ---------------------------------------------------------
    def _query_loop(self, w: int) -> None:
        cfg = self.cfg
        rng = np.random.default_rng([cfg.seed, 7700 + w])
        i = 0
        while not self._qstop.is_set():
            # host-only numpy draws (the seeded query mix picker)
            spec = cfg.tables[
                int(rng.integers(len(cfg.tables)))]  # jaxlint: ok host-sync
            tmpl = QUERY_MIX[
                int(rng.integers(len(QUERY_MIX)))]  # jaxlint: ok host-sync
            # deterministic names: the per-query fault streams
            # (utils/faults.py) reproduce across same-seed runs
            sql = (tmpl.format(t=spec.name)
                   + f" OPTION(timeoutMs={cfg.query_timeout_ms},"
                     f"queryId=lg_w{w}_q{i})")
            t0 = time.perf_counter()
            try:
                self.broker.query(sql)
                ms = (time.perf_counter() - t0) * 1e3
                with self._qlock:
                    self._q_lat.append(ms)
            except Exception:
                # chaos may legitimately kill queries (oom_kill,
                # deadline); counted, never fatal to the harness
                with self._qlock:
                    self._q_errors += 1
            i += 1
            if cfg.query_think_s > 0 \
                    and self._qstop.wait(cfg.query_think_s):
                return

    # -- sampler -----------------------------------------------------------
    def _sample_loop(self) -> None:
        while not self._stop.wait(self.cfg.sample_interval_s):
            for table in self.tables:
                table.sample_freshness()

    # -- the run -----------------------------------------------------------
    def run(self) -> Dict[str, Any]:
        cfg = self.cfg
        plan = faults.install(cfg.fault_plan) if cfg.fault_plan else None
        t0 = time.monotonic()
        threads: List[threading.Thread] = []
        try:
            for table in self.tables:
                for p in range(table.spec.partitions):
                    threads.append(threading.Thread(
                        target=self._produce_loop, args=(table, p),
                        name=f"lg-prod-{table.spec.name}-{p}",
                        daemon=True))
                    threads.append(threading.Thread(
                        target=self._consume_loop, args=(table, p),
                        name=f"lg-cons-{table.spec.name}-{p}",
                        daemon=True))
            sampler = threading.Thread(target=self._sample_loop,
                                       name="lg-sampler", daemon=True)
            workers = [threading.Thread(target=self._query_loop,
                                        args=(w,), name=f"lg-query-{w}",
                                        daemon=True)
                       for w in range(cfg.query_concurrency)]
            for t in threads + [sampler] + workers:
                t.start()
            deadline = t0 + cfg.max_wall_s
            while time.monotonic() < deadline:
                with self._qlock:
                    fatal = bool(self._fatal)
                if fatal:
                    break
                if all(t.drained() for t in self.tables):
                    break
                time.sleep(cfg.poll_interval_s)
            wall = time.monotonic() - t0
            # stop EVERYTHING at the drain mark — a consumer left
            # running while query workers drain can eat an injected
            # rebalance that discards the consuming tail after the
            # drained check, and nothing would re-consume it
            self._qstop.set()
            self._stop.set()
            for wkr in workers:
                # bounded by the run's own budget, NOT the query
                # timeout: a chaos-wedged query must not extend the
                # max_wall_s cap by minutes (the worker is a daemon —
                # a straggler past this is abandoned, its latency
                # sample lost, and the summary proceeds)
                wkr.join(timeout=30.0)
            for t in threads + [sampler]:
                t.join(timeout=30.0)
        finally:
            self._qstop.set()
            self._stop.set()
            fired = len(plan.fired) if plan is not None else 0
            if plan is not None:
                faults.clear()
        # fault-free settle: chaos ended with the run — re-consume any
        # tail a last-instant rebalance/crash discarded and finish
        # pending protocol commits, so the oracle diff always measures
        # a DRAINED state (consumer threads are joined: the
        # single-writer-per-partition rule holds for these calls)
        drained = self._settle(time.monotonic() + 30.0)
        return self._summary(wall, drained, fired,
                             chaos=plan is not None)

    def _settle(self, deadline: float) -> bool:
        # one consumer per (table, partition) for the whole settle loop
        # — consume_once's own-consumer path would pay a fresh
        # transport connection (TCP / SigV4 handshake) per iteration
        consumers: Dict[Tuple[int, int], Any] = {}
        try:
            while True:
                if all(t.drained() for t in self.tables):
                    return True
                if time.monotonic() >= deadline:
                    return False
                for table in self.tables:
                    _gen, m = table.current()
                    factory = m.stream_config.consumer_factory
                    for p in range(table.spec.partitions):
                        try:
                            c = consumers.get((table.idx, p))
                            if c is None:
                                c = factory.create_consumer(p)
                                consumers[(table.idx, p)] = c
                            m.consume_once(p, c)
                            m._maybe_seal(p)
                        except Exception:
                            # bounded by the deadline, not per-call; a
                            # broken consumer is rebuilt next pass
                            consumers.pop((table.idx, p), None)
                time.sleep(0.002)
        finally:
            for c in consumers.values():
                try:
                    c.close()
                except Exception:
                    pass

    def _summary(self, wall: float, drained: bool, fired: int,
                 chaos: bool) -> Dict[str, Any]:
        cfg = self.cfg
        per_table: Dict[str, Any] = {}
        oracle_ok = drained
        for table in self.tables:
            st = table.final_stats()
            if drained:
                from ..tools.ingest_fuzz import digest
                got = digest(self._queryable_rows(table.spec.name))
                exact = got == table.oracle_digest(
                    cfg.seed, cfg.rows_per_partition)
                st["oracle_ok"] = exact
                oracle_ok = oracle_ok and exact
            per_table[table.spec.name] = st
        with self._qlock:
            lat = sorted(self._q_lat)
            q_errors = self._q_errors
            fatal = list(self._fatal)
        # rows = PRODUCED rows (exact by construction; the per-manager
        # ingest_stats counter resets on a crash/restart, so per_table
        # "rows" means rows-since-last-restart on chaos runs)
        rows = sum(t.total_produced() for t in self.tables)
        partitions = sum(t.spec.partitions for t in self.tables)
        series = [t.raw_series() for t in self.tables]
        fresh_all = sorted(f for fr, _c in series for f in fr)
        commits_all = sorted(c for _f, cm in series for c in cm)
        from .ragged import global_batcher
        out: Dict[str, Any] = {
            "backend": _jax_backend(),
            "scenario": cfg.scenario,
            "seed": cfg.seed,
            "tables": len(self.tables),
            "partitions": partitions,
            "rows": rows,
            "duration_s": round(wall, 3),
            "rows_per_s": round(rows / wall, 3) if wall > 0 else 0.0,
            "rows_per_s_per_partition": round(
                rows / wall / max(partitions, 1), 3) if wall > 0 else 0.0,
            "freshness_p50_ms": round(pctl(fresh_all, 0.5), 3),
            "freshness_p99_ms": round(pctl(fresh_all, 0.99), 3),
            "commit_p50_ms": round(pctl(commits_all, 0.5), 3),
            "commit_p99_ms": round(pctl(commits_all, 0.99), 3),
            "commits": sum(st["commits"] for st in per_table.values()),
            "queries": len(lat),
            "queries_concurrent": cfg.query_concurrency,
            "query_p50_ms": round(pctl(lat, 0.5), 3),
            "query_p99_ms": round(pctl(lat, 0.99), 3),
            "query_errors": q_errors,
            "batched": bool(global_batcher.enabled),
            "restarts": sum(t.restarts for t in self.tables),
            "chaos": chaos,
            "faults_fired": fired,
            "oracle_ok": bool(oracle_ok),
            "per_table": per_table,
            "ok": bool(oracle_ok and drained and not fatal),
        }
        if not drained:
            out["error"] = (fatal[0] if fatal else
                            f"not drained within {cfg.max_wall_s}s")
        elif fatal:
            out["error"] = fatal[0]
        return out

    def _queryable_rows(self, table: str) -> List[Tuple[int, ...]]:
        res = self.broker.query(
            f"SELECT pk, ts, val FROM {table} LIMIT 10000000 "
            f"OPTION(timeoutMs={self.cfg.query_timeout_ms},"
            f"queryId=lg_oracle_{table})")
        return [tuple(int(v) for v in r) for r in res.rows]

    def close(self) -> None:
        self._qstop.set()
        self._stop.set()
        for table in self.tables:
            table.close()


def _jax_backend() -> str:
    try:
        import jax
        return jax.default_backend()
    except Exception:
        return "unknown"


def run_load(data_dir: str, config: LoadgenConfig) -> Dict[str, Any]:
    """Build, run, tear down. The one-call entry point the bench, the
    freshness gate's capture corpus and the smoke tests share. With
    ``config.ledger_path`` set, the summary lands as one validated
    ``ingest_bench`` record plus one per-table ``ingest_stats`` record
    (freshness percentiles included) before teardown."""
    lg = IngestLoadGen(data_dir, config)
    try:
        summary = lg.run()
        if config.ledger_path:
            write_ingest_bench(summary, config.ledger_path)
            summary["table_stats_written"] = write_table_stats(
                summary, lg.tables, config.ledger_path, config.seed)
        return summary
    finally:
        lg.close()


def write_ingest_bench(summary: Dict[str, Any], path: str,
                       **extra: Any) -> Dict[str, Any]:
    """Append the run summary as ONE validated ``ingest_bench`` record
    (writer-side contract enforcement, like every other kind)."""
    from ..utils import ledger as uledger
    contract = uledger.KINDS["ingest_bench"]
    allowed = contract["required"] | contract["optional"]
    fields = {k: v for k, v in summary.items() if k in allowed}
    fields.update(extra)
    rec = uledger.make_record("ingest_bench", **fields)
    uledger.append_record(rec, path)
    return rec


def write_table_stats(lg_summary: Dict[str, Any], tables: List[_TableRun],
                      path: str, seed: int) -> int:
    """One validated per-table ``ingest_stats`` record each, carrying
    the run's freshness percentiles — the rows the round-14 fleet
    rollup trends per table."""
    n = 0
    for table in tables:
        st = lg_summary["per_table"][table.spec.name]
        table.current()[1].write_ingest_stats(
            path, seed=seed, restarts=st.get("restarts", 0),
            freshness_p50_ms=st.get("freshness_p50_ms"),
            freshness_p99_ms=st.get("freshness_p99_ms"))
        n += 1
    return n
